"""Separately-jitted stage pipelines for per-stage timing — all plan kinds.

The reference prints a per-stage wall-time breakdown on every distributed
execute (t0 fftZY / t1 transpose / t2 all-to-all / t3 fftX,
``fft_mpi_3d_api.cpp:184-201``, ``README.md:44-58``) for every benchmarkable
config. Fusing the whole transform under one jit hides the ICI cost
(SURVEY.md §7), so benchmarking keeps a staged mode: each stage is its own
jit, synchronized and timed by :func:`..utils.timing.time_staged`.

:mod:`.slab` provides ``build_slab_stages`` for the slab c2c plan; this
module adds the pencil c2c pipeline (two exchanges -> t2a/t2b lines) and the
r2c/c2r pipelines for both decompositions. Stage boundaries carry
ceil-padded global arrays; shardings are established with
``with_sharding_constraint`` inside each stage (not pinned on the jits), so
uneven extents — e.g. the r2c half-spectrum n2//2+1, which almost never
divides the mesh — work in staged mode too.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from ..geometry import pad_to
from ..ops.executors import (
    get_c2r, get_executor, get_r2c, thunk_guard_substitute,
)
from ..utils.trace import trace_stages
from .exchange import exchange_chunked
from .pencil import PencilSpec
from .slab import SlabSpec, _crop_axis, _pad_axis, batch_pspec, check_batch

__all__ = [
    "build_pencil_stages",
    "build_slab_rfft_stages",
    "build_pencil_rfft_stages",
    "build_single_stages",
    "build_slab_op_stages",
]


def build_single_stages(
    shape: tuple[int, int, int],
    *,
    executor: str | Callable = "xla",
    forward: bool = True,
    batch: int | None = None,
) -> list:
    """Single-device staged pipeline: t0 (YZ planes) and t3 (X lines) as
    separate jits — the per-stage breakdown the reference prints even on
    one rank (``fft_mpi_3d_api.cpp:184-201``; t1/t2 are identically zero
    without a transpose/exchange). With the pallas executor, t0 is the
    fused 2D plane kernel and t3 the strided axis-0 kernel. ``batch=B``
    runs the stages over ``[B, ...]`` arrays."""
    check_batch(batch)
    bo = 0 if batch is None else 1
    ex = get_executor(executor) if isinstance(executor, str) else executor
    return trace_stages([
        ("t0_fft_yz", jax.jit(lambda x: ex(x, (1 + bo, 2 + bo), forward))),
        ("t3_fft_x", jax.jit(lambda y: ex(y, (bo,), forward))),
    ])

_AXIS_LETTER = "xyz"


def _pspec(mapping: dict[int, str]) -> P:
    return P(*[mapping.get(d) for d in range(3)])


# Tree-aware stage primitives: the pencil pipeline below is generic over
# the stage value — a single c64 array, or any pytree of same-shape
# arrays (the dd tier's (hi, lo) pair rides through unchanged; specs and
# shardings broadcast as pytree prefixes). The exchanges themselves go
# through the tree-generic :func:`.exchange.exchange_chunked`.
def _tpad(x, ax: int, to: int):
    return jax.tree_util.tree_map(lambda u: _pad_axis(u, ax, to), x)


def _tcrop(x, ax: int, to: int):
    return jax.tree_util.tree_map(lambda u: _crop_axis(u, ax, to), x)


def build_pencil_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str | Callable = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    perm: tuple[int, int, int] | None = None,
    order: str | None = None,
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], PencilSpec]:
    """Pencil c2c transform as five timed stages:
    t0 (first fft) | t2a (first exchange) | t1 (mid fft) | t2b (second
    exchange) | t3 (last fft) — the reference's taxonomy with the two
    pencil exchanges split out as t2a/t2b. ``overlap_chunks > 1`` keeps
    the overlapped chains' K-collective shape inside each exchange stage
    (:func:`.exchange.exchange_chunked`). ``batch=B`` runs the stages
    over ``[B, ...]`` arrays with one shared exchange per chunk.

    Generic over the stage value: ``executor`` may be a callable taking
    any pytree of same-shape arrays (the dd tier passes a (hi, lo) pair
    through ``ddslab.build_dd_pencil_stages``); pads/crops/exchanges map
    over leaves and specs broadcast as pytree prefixes."""
    if perm is None:
        perm = (0, 1, 2) if forward else (1, 2, 0)
    if order is None:
        order = "col_first" if forward else "row_first"
    check_batch(batch)
    bo = 0 if batch is None else 1  # leading-batch axis offset
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(tuple(int(s) for s in shape), rows, cols,
                      row_axis, col_axis, tuple(perm), order)
    n = spec.shape
    a, b, c = perm
    if order == "col_first":
        seq = [(col_axis, cols, c, b), (row_axis, rows, b, a)]
        mid_fft, last_fft = b, a
    else:
        seq = [(row_axis, rows, c, a), (col_axis, cols, a, b)]
        mid_fft, last_fft = a, b
    # fft-thunk guard (DFFT_THUNK_GUARD): the staged view of an uneven
    # inverse pencil chain is in the known XLA:CPU poisoned class exactly
    # like the fused chain — substitute before any stage traces (the
    # planner applies the same shared predicate).
    executor = thunk_guard_substitute(
        executor, decomposition="pencil", forward=forward,
        uneven=bool(n[a] % rows or n[b] % cols
                    or n[seq[0][2]] % seq[0][1]
                    or n[seq[1][2]] % seq[1][1]))
    ex = get_executor(executor) if isinstance(executor, str) else executor

    in_lay = {a: row_axis, b: col_axis}
    mid_lay = ({a: row_axis, c: col_axis} if order == "col_first"
               else {c: row_axis, b: col_axis})
    op = spec.out_placement
    out_lay = {op[0]: row_axis, op[1]: col_axis}

    bspec = lambda lay: batch_pspec(_pspec(lay), batch)
    sh = lambda lay: NamedSharding(mesh, bspec(lay))
    in_sh, mid_sh, out_sh = sh(in_lay), sh(mid_lay), sh(out_lay)
    pads = {a: pad_to(n[a], rows), b: pad_to(n[b], cols)}
    # each exchange's split axis is padded to its part count before it runs
    pads[seq[0][2]] = pad_to(n[seq[0][2]], seq[0][1])
    mid_pad = pad_to(n[seq[1][2]], seq[1][1])

    def smap(f, lay_in, lay_out):
        return _shard_map(f, mesh=mesh, in_specs=(bspec(lay_in),),
                          out_specs=bspec(lay_out))

    def t0(x):
        x = _tpad(_tpad(x, a + bo, pads[a]), b + bo, pads[b])
        x = lax.with_sharding_constraint(x, in_sh)
        y = smap(lambda v: ex(v, (c + bo,), forward), in_lay, in_lay)(x)
        y = _tpad(y, seq[0][2] + bo, pads[seq[0][2]])
        return lax.with_sharding_constraint(y, in_sh)

    def t2a(x):
        x = lax.with_sharding_constraint(x, in_sh)
        mesh_ax, parts, split, concat = seq[0]
        y = smap(lambda v: exchange_chunked(
            v, mesh_ax, split_axis=split + bo, concat_axis=concat + bo,
            axis_size=parts, algorithm=algorithm,
            wire_dtype=wire_dtype,
            overlap_chunks=overlap_chunks,
            chunk_axis=3 - split - concat + bo,
            exchange_name=f"t2a_exchange_{mesh_ax}"),
                 in_lay, mid_lay)(x)
        return lax.with_sharding_constraint(y, mid_sh)

    def t1(x):
        x = lax.with_sharding_constraint(x, mid_sh)
        concat0 = seq[0][3]
        y = smap(lambda v: _tpad(
            ex(_tcrop(v, concat0 + bo, n[concat0]), (mid_fft + bo,),
               forward),
            seq[1][2] + bo, mid_pad), mid_lay, mid_lay)(x)
        return lax.with_sharding_constraint(y, mid_sh)

    def t2b(x):
        x = lax.with_sharding_constraint(x, mid_sh)
        mesh_ax, parts, split, concat = seq[1]
        y = smap(lambda v: exchange_chunked(
            v, mesh_ax, split_axis=split + bo, concat_axis=concat + bo,
            axis_size=parts, algorithm=algorithm,
            wire_dtype=wire_dtype,
            overlap_chunks=overlap_chunks,
            chunk_axis=3 - split - concat + bo,
            exchange_name=f"t2b_exchange_{mesh_ax}"),
                 mid_lay, out_lay)(x)
        return lax.with_sharding_constraint(y, out_sh)

    def t3(x):
        x = lax.with_sharding_constraint(x, out_sh)
        concat1 = seq[1][3]
        y = smap(lambda v: ex(_tcrop(v, concat1 + bo, n[concat1]),
                              (last_fft + bo,), forward),
                 out_lay, out_lay)(x)
        for ax in op:
            y = _tcrop(y, ax + bo, n[ax])
        return y

    L = _AXIS_LETTER
    stages = [
        (f"t0_fft_{L[c]}", jax.jit(t0)),
        (f"t2a_exchange_{seq[0][0]}", jax.jit(t2a)),
        (f"t1_fft_{L[mid_fft]}", jax.jit(t1)),
        (f"t2b_exchange_{seq[1][0]}", jax.jit(t2b)),
        (f"t3_fft_{L[last_fft]}", jax.jit(t3)),
    ]
    return trace_stages(stages), spec


def build_slab_op_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    multiplier,
    *,
    axis_name: str = "slab",
    executor: str | Callable = "xla",
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], SlabSpec]:
    """The fused slab spectral-operator chain
    (:func:`..slab.build_slab_spectral_op`) as five separately-jitted,
    timed stages — the ``stop_at_transposed``/``start_from_transposed``
    mode at the staged tier, so the explain layer can measure the
    ``t_mid`` pointwise stage next to t0/t2/t3:

    t0 (forward YZ FFTs) | t2 (outbound exchange) | **t_mid** (final
    forward X FFT + wavenumber-diagonal multiply + first inverse X FFT,
    all in the transposed Y-slab layout) | t2 (return exchange) | t3
    (inverse YZ FFTs back to X-slabs).

    ``multiplier(i0, i1, i2)`` follows the fused builder's contract
    (int32 global index grids, per-shard offsets applied here).
    ``overlap_chunks > 1`` keeps the K-collective transport shape
    inside each exchange stage (:func:`.exchange.exchange_chunked`);
    flat transports and a plain 1D mesh axis only (the hierarchical
    two-leg chain measures fused)."""
    from .slab import apply_multiplier

    check_batch(batch)
    bo = 0 if batch is None else 1
    p = mesh.shape[axis_name]
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name, 0, 1)
    ex = get_executor(executor) if isinstance(executor, str) else executor
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    c1 = n1p // p  # transposed-midpoint local extent of the k1 axis
    xs = batch_pspec(P(axis_name, None, None), batch)
    ys = batch_pspec(P(None, axis_name, None), batch)
    x_sh, y_sh = NamedSharding(mesh, xs), NamedSharding(mesh, ys)

    def smap(f, i, o):
        return _shard_map(f, mesh=mesh, in_specs=(i,), out_specs=o)

    def t0(x):
        x = lax.with_sharding_constraint(_pad_axis(x, bo, n0p), x_sh)
        y = smap(lambda v: _pad_axis(
            ex(v, (1 + bo, 2 + bo), True), 1 + bo, n1p), xs, xs)(x)
        return lax.with_sharding_constraint(y, x_sh)

    def exch(y, split, concat, i, o, out_sh):
        y = smap(lambda v: exchange_chunked(
            v, axis_name, split_axis=split, concat_axis=concat,
            axis_size=p, algorithm=algorithm, wire_dtype=wire_dtype,
            overlap_chunks=overlap_chunks, chunk_axis=2 + bo), i, o)(y)
        return lax.with_sharding_constraint(y, out_sh)

    def t2_out(y):
        y = lax.with_sharding_constraint(y, x_sh)
        return exch(y, 1 + bo, bo, xs, ys, y_sh)

    def t_mid(y):
        y = lax.with_sharding_constraint(y, y_sh)

        def local(u):
            u = _crop_axis(u, bo, n0)
            u = ex(u, (bo,), True)                   # final forward X
            k1_lo = lax.axis_index(axis_name) * c1
            m = multiplier(
                jnp.arange(n0, dtype=jnp.int32)[:, None, None],
                (k1_lo + jnp.arange(c1, dtype=jnp.int32))[None, :, None],
                jnp.arange(n2, dtype=jnp.int32)[None, None, :])
            u = apply_multiplier(u, m)
            return _pad_axis(ex(u, (bo,), False), bo, n0p)  # inverse X

        y = smap(local, ys, ys)(y)
        return lax.with_sharding_constraint(y, y_sh)

    def t2_back(y):
        y = lax.with_sharding_constraint(y, y_sh)
        return exch(y, bo, 1 + bo, ys, xs, x_sh)

    def t3(y):
        y = lax.with_sharding_constraint(y, x_sh)
        y = smap(lambda v: ex(_crop_axis(v, 1 + bo, n1),
                              (1 + bo, 2 + bo), False), xs, xs)(y)
        return _crop_axis(y, bo, n0)

    stages = [
        # Both exchange stages normalize to the t2 key (stage_key), so
        # the explain join sums them per pass; the distinct names keep
        # the driver-tier breakdown showing each leg on its own row.
        ("t0_fft_yz", jax.jit(t0)),
        ("t2_exchange_out", jax.jit(t2_out)),
        ("t_mid", jax.jit(t_mid)),
        ("t2_exchange_back", jax.jit(t2_back)),
        ("t3_ifft_yz", jax.jit(t3)),
    ]
    return trace_stages(stages), spec


def build_slab_rfft_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    axis_name: str = "slab",
    executor: str = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], SlabSpec]:
    """Slab r2c (forward) / c2r (backward) as three timed stages — the
    per-stage breakdown for every benchmarkable r2c config
    (``fft_mpi_3d_api.cpp:184-201`` prints it for every run)."""
    check_batch(batch)
    bo = 0 if batch is None else 1
    p = mesh.shape[axis_name]
    spec = SlabSpec(tuple(int(s) for s in shape), p, axis_name,
                    in_axis=0 if forward else 1, out_axis=1 if forward else 0)
    ex = get_executor(executor)
    r2c, c2r = get_r2c(executor), get_c2r(executor)
    n0, n1, n2 = spec.shape
    n0p, n1p = spec.n0p, spec.n1p
    xs = batch_pspec(P(axis_name, None, None), batch)
    ys = batch_pspec(P(None, axis_name, None), batch)
    x_sh, y_sh = NamedSharding(mesh, xs), NamedSharding(mesh, ys)

    def smap(f, i, o):
        return _shard_map(f, mesh=mesh, in_specs=(i,), out_specs=o)

    if forward:

        def t0(x):  # real [n0, n1, n2] -> complex [n0p, n1p, n2h]
            x = lax.with_sharding_constraint(_pad_axis(x, bo, n0p), x_sh)
            y = smap(lambda v: _pad_axis(
                ex(r2c(v, 2 + bo), (1 + bo,), True), 1 + bo, n1p),
                xs, xs)(x)
            return lax.with_sharding_constraint(y, x_sh)

        def t2(y):
            y = lax.with_sharding_constraint(y, x_sh)
            z = smap(lambda v: exchange_chunked(
                v, axis_name, split_axis=1 + bo, concat_axis=bo,
                axis_size=p, algorithm=algorithm,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=2 + bo),
                xs, ys)(y)
            return lax.with_sharding_constraint(z, y_sh)

        def t3(z):
            z = lax.with_sharding_constraint(z, y_sh)
            w = smap(lambda v: ex(_crop_axis(v, bo, n0), (bo,), True),
                     ys, ys)(z)
            return _crop_axis(w, 1 + bo, n1)

        stages = [("t0_r2c_zy", jax.jit(t0)),
                  ("t2_exchange", jax.jit(t2)),
                  ("t3_fft_x", jax.jit(t3))]
    else:

        def t3i(z):  # complex [n0, n1, n2h] y-slabs
            z = lax.with_sharding_constraint(
                _pad_axis(z, 1 + bo, n1p), y_sh)
            w = smap(lambda v: _pad_axis(ex(v, (bo,), False), bo, n0p),
                     ys, ys)(z)
            return lax.with_sharding_constraint(w, y_sh)

        def t2(w):
            w = lax.with_sharding_constraint(w, y_sh)
            u = smap(lambda v: exchange_chunked(
                v, axis_name, split_axis=bo, concat_axis=1 + bo,
                axis_size=p, algorithm=algorithm,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=2 + bo),
                ys, xs)(w)
            return lax.with_sharding_constraint(u, x_sh)

        def t0i(u):
            u = lax.with_sharding_constraint(u, x_sh)
            w = smap(lambda v: c2r(
                ex(_crop_axis(v, 1 + bo, n1), (1 + bo,), False),
                n2, 2 + bo), xs, xs)(u)
            return _crop_axis(w, bo, n0)

        stages = [("t3_ifft_x", jax.jit(t3i)),
                  ("t2_exchange", jax.jit(t2)),
                  ("t0_ifft_y_c2r", jax.jit(t0i))]
    return trace_stages(stages), spec


def build_pencil_rfft_stages(
    mesh: Mesh,
    shape: tuple[int, int, int],
    *,
    row_axis: str = "row",
    col_axis: str = "col",
    executor: str = "xla",
    forward: bool = True,
    algorithm: str = "alltoall",
    overlap_chunks: int = 1,
    batch: int | None = None,
    wire_dtype: str | None = None,
) -> tuple[list[tuple[str, Callable]], PencilSpec]:
    """Pencil r2c/c2r as five timed stages with t2a/t2b exchange lines.
    Canonical chains only (the real axis must be device-local axis 2 on the
    real side), matching :func:`.pencil.build_pencil_rfft3d`."""
    check_batch(batch)
    bo = 0 if batch is None else 1
    rows, cols = mesh.shape[row_axis], mesh.shape[col_axis]
    spec = PencilSpec(
        tuple(int(s) for s in shape), rows, cols, row_axis, col_axis,
        perm=(0, 1, 2) if forward else (1, 2, 0),
        order="col_first" if forward else "row_first",
    )
    # fft-thunk guard: the staged uneven c2r pencil pipeline is in the
    # known XLA:CPU poisoned class (see build_pencil_stages).
    executor = thunk_guard_substitute(
        executor, decomposition="pencil", forward=forward,
        uneven=bool(spec.shape[0] % rows or spec.shape[1] % cols
                    or spec.shape[1] % rows
                    or (spec.shape[2] // 2 + 1) % cols))
    ex = get_executor(executor)
    r2c, c2r = get_r2c(executor), get_c2r(executor)
    n0, n1, n2 = spec.shape
    n0p, n1pc, n1pr = spec.n0p, spec.n1p_col, spec.n1p_row
    n2h = n2 // 2 + 1
    n2hp = pad_to(n2h, cols)
    zs, ysp, xs = (batch_pspec(P(row_axis, col_axis, None), batch),
                   batch_pspec(P(row_axis, None, col_axis), batch),
                   batch_pspec(P(None, row_axis, col_axis), batch))
    z_sh, y_sh, x_sh = (NamedSharding(mesh, s) for s in (zs, ysp, xs))

    def smap(f, i, o):
        return _shard_map(f, mesh=mesh, in_specs=(i,), out_specs=o)

    if forward:

        def t0(x):  # real z-pencils -> half-spectrum, padded for exch
            x = _pad_axis(_pad_axis(x, bo, n0p), 1 + bo, n1pc)
            x = lax.with_sharding_constraint(x, z_sh)
            y = smap(lambda v: _pad_axis(r2c(v, 2 + bo), 2 + bo, n2hp),
                     zs, zs)(x)
            return lax.with_sharding_constraint(y, z_sh)

        def t2a(y):
            y = lax.with_sharding_constraint(y, z_sh)
            z = smap(lambda v: exchange_chunked(
                v, col_axis, split_axis=2 + bo, concat_axis=1 + bo,
                axis_size=cols, algorithm=algorithm,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=bo),
                zs, ysp)(y)
            return lax.with_sharding_constraint(z, y_sh)

        def t1(z):
            z = lax.with_sharding_constraint(z, y_sh)
            w = smap(lambda v: _pad_axis(
                ex(_crop_axis(v, 1 + bo, n1), (1 + bo,), True),
                1 + bo, n1pr), ysp, ysp)(z)
            return lax.with_sharding_constraint(w, y_sh)

        def t2b(w):
            w = lax.with_sharding_constraint(w, y_sh)
            u = smap(lambda v: exchange_chunked(
                v, row_axis, split_axis=1 + bo, concat_axis=bo,
                axis_size=rows, algorithm=algorithm,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=2 + bo),
                ysp, xs)(w)
            return lax.with_sharding_constraint(u, x_sh)

        def t3(u):
            u = lax.with_sharding_constraint(u, x_sh)
            w = smap(lambda v: ex(_crop_axis(v, bo, n0), (bo,), True),
                     xs, xs)(u)
            return _crop_axis(_crop_axis(w, 1 + bo, n1), 2 + bo, n2h)

        stages = [("t0_r2c_z", jax.jit(t0)),
                  ("t2a_exchange_col", jax.jit(t2a)),
                  ("t1_fft_y", jax.jit(t1)),
                  ("t2b_exchange_row", jax.jit(t2b)),
                  ("t3_fft_x", jax.jit(t3))]
    else:

        def t3i(u):  # complex x-pencils [n0, n1, n2h]
            u = _pad_axis(_pad_axis(u, 1 + bo, n1pr), 2 + bo, n2hp)
            u = lax.with_sharding_constraint(u, x_sh)
            w = smap(lambda v: _pad_axis(ex(v, (bo,), False), bo, n0p),
                     xs, xs)(u)
            return lax.with_sharding_constraint(w, x_sh)

        def t2b(w):
            w = lax.with_sharding_constraint(w, x_sh)
            z = smap(lambda v: exchange_chunked(
                v, row_axis, split_axis=bo, concat_axis=1 + bo,
                axis_size=rows, algorithm=algorithm,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=2 + bo),
                xs, ysp)(w)
            return lax.with_sharding_constraint(z, y_sh)

        def t1i(z):
            z = lax.with_sharding_constraint(z, y_sh)
            w = smap(lambda v: _pad_axis(
                ex(_crop_axis(v, 1 + bo, n1), (1 + bo,), False),
                1 + bo, n1pc), ysp, ysp)(z)
            return lax.with_sharding_constraint(w, y_sh)

        def t2a(w):
            w = lax.with_sharding_constraint(w, y_sh)
            z = smap(lambda v: exchange_chunked(
                v, col_axis, split_axis=1 + bo, concat_axis=2 + bo,
                axis_size=cols, algorithm=algorithm,
                wire_dtype=wire_dtype,
                overlap_chunks=overlap_chunks, chunk_axis=bo),
                ysp, zs)(w)
            return lax.with_sharding_constraint(z, z_sh)

        def t0i(z):
            z = lax.with_sharding_constraint(z, z_sh)
            w = smap(lambda v: c2r(_crop_axis(v, 2 + bo, n2h), n2, 2 + bo),
                     zs, zs)(z)
            return _crop_axis(_crop_axis(w, bo, n0), 1 + bo, n1)

        stages = [("t3_ifft_x", jax.jit(t3i)),
                  ("t2b_exchange_row", jax.jit(t2b)),
                  ("t1_ifft_y", jax.jit(t1i)),
                  ("t2a_exchange_col", jax.jit(t2a)),
                  ("t0_c2r_z", jax.jit(t0i))]
    return trace_stages(stages), spec
