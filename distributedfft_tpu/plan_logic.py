"""Plan logic: option handling and decomposition selection.

The heFFTe analog layer (``heffte/heffteBenchmark/include/heffte_plan_logic.h``,
``src/heffte_plan_logic.cpp``): ``plan_options`` {algorithm, use_reorder,
use_pencils, use_gpu_aware} (``heffte_plan_logic.h:69-89``) and
``plan_operations`` (``heffte_plan_logic.cpp:410-432``), which inspects the
in/out geometry and picks the cheapest reshape pipeline.

On TPU the decision space is smaller and different: the transport is always
XLA collectives over the mesh (no gpu-aware/host-staged split — there is no
host staging to choose), layout reordering belongs to XLA's layout
assignment, and the real knobs are

- **decomposition**: slab (one exchange) vs pencil (two exchanges, but each
  on a smaller mesh axis and with more parallel lines per FFT stage);
- **exchange algorithm**: one fused ``all_to_all`` vs a pipelined
  ``ppermute`` ring (:mod:`.parallel.exchange`);
- **mesh geometry**: how to factor the device count into a 2D grid
  (``make_procgrid``, min-surface heuristics — :mod:`.geometry`).

:func:`logic_plan3d` resolves (shape, mesh/device-count, options) to a
concrete decomposition + mesh, the role of ``plan_operations``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Sequence

from jax.sharding import Mesh

from . import geometry as geo
from .parallel.exchange import ALGORITHMS
from .parallel.mesh import make_mesh


@dataclass(frozen=True)
class PlanOptions:
    """User-tunable plan knobs (``plan_options``,
    ``heffte_plan_logic.h:69-89``).

    ``decomposition``: "auto" | "single" | "slab" | "pencil".
    ``algorithm``: "alltoall" | "ppermute" (``reshape_algorithm``,
    ``heffte_plan_logic.h:47-56``).
    ``executor``: registered local-FFT backend name (``one_dim_backend``,
    ``heffte_common.h:275``).
    ``donate``: consume the input buffer (bufferDev ping-pong analog,
    ``fft_mpi_3d_api.cpp:66-81``).
    """

    decomposition: str = "auto"
    algorithm: str = "alltoall"
    executor: str = "xla"
    donate: bool = False

    def __post_init__(self) -> None:
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"unknown algorithm {self.algorithm!r}; use one of {ALGORITHMS}"
            )
        if self.decomposition not in ("auto", "single", "slab", "pencil"):
            raise ValueError(f"unknown decomposition {self.decomposition!r}")


DEFAULT_OPTIONS = PlanOptions()


def default_options(decomposition: str = "auto", **kw) -> PlanOptions:
    """cf. ``default_options<backend>()`` (``heffte_plan_logic.h:95``)."""
    return PlanOptions(decomposition=decomposition, **kw)


@dataclass(frozen=True)
class LogicPlan:
    """Resolved plan skeleton (the ``logic_plan3d`` analog,
    ``heffte_plan_logic.h:152-164``): the decomposition, the mesh to run on,
    and the intermediate layout chain as per-stage box lists."""

    shape: tuple[int, int, int]
    decomposition: str            # "single" | "slab" | "pencil"
    mesh: Mesh | None
    options: PlanOptions
    # Stage layouts: list of (fft_axes, boxes) pairs, input side first.
    stages: tuple = ()

    @property
    def num_exchanges(self) -> int:
        return {"single": 0, "slab": 1, "pencil": 2}[self.decomposition]


def choose_decomposition(shape: Sequence[int], ndev: int) -> str:
    """Pick slab vs pencil for ``ndev`` devices with no mesh constraint.

    A slab plan moves the whole world once; a pencil plan moves it twice but
    each FFT stage operates on full lines with ndev-way batching on both
    remaining axes. The slab plan stops scaling when devices outnumber the
    planes of the first axis (each device must own >= 1 X-plane and >= 1
    Y-plane) — the point where the reference would *shrink the device count*
    (``getProperDeviceNum``, ``fft_mpi_3d_api.cpp:232-272``) and heFFTe
    would switch to pencils (``use_pencils``, ``heffte_plan_logic.h:69-89``).
    """
    n0, n1, _ = shape
    if ndev <= 1:
        return "single"
    if ndev <= min(n0, n1):
        return "slab"
    return "pencil"


def negotiate_device_count(
    shape: Sequence[int], ndev: int, decomposition: str = "slab"
) -> int:
    """Largest device count <= ``ndev`` whose slabs/pencils divide the split
    axes evenly — the reference's device-count renegotiation
    (``getProperDeviceNum``, ``fft_mpi_3d_api.cpp:232-272``: when N0 %
    devices != 0 it *shrinks* the device count until slabs divide).

    On TPU the padded-exchange path makes uneven shapes correct anyway, so
    this is an *optimization* choice, not a correctness one: a caller that
    prefers zero padding waste over maximum parallelism can plan with the
    negotiated count (idle devices simply hold empty shards).
    """
    n0, n1, n2 = (int(s) for s in shape)
    start = min(ndev, n0, n1) if decomposition == "slab" else ndev
    for p in range(start, 0, -1):
        if decomposition == "slab":
            if n0 % p == 0 and n1 % p == 0:
                return p
        else:
            # pencil pads axis0/axis1 over mesh rows and axis1/axis2 over
            # mesh cols (PencilSpec n0p/n1p_row/n1p_col/n2p); an even plan
            # needs the planner's grid orientation (rows >= cols, as
            # logic_plan3d builds it) to divide all four.
            r, c = sorted(geo.make_procgrid(p), reverse=True)
            if n0 % r == 0 and n1 % r == 0 and n1 % c == 0 and n2 % c == 0:
                return p
    return 1


def logic_plan3d(
    shape: Sequence[int],
    mesh: Mesh | int | None,
    options: PlanOptions = DEFAULT_OPTIONS,
) -> LogicPlan:
    """Resolve (shape, mesh-or-device-count, options) to a concrete plan
    skeleton. The role of ``plan_operations``
    (``heffte_plan_logic.cpp:410-432``): all geometry decisions happen here,
    and the builders in :mod:`.parallel` only execute them.

    ``mesh`` may be ``None`` (single device), an int device count (the mesh
    is built here, shaped by the chosen decomposition), or an existing
    :class:`Mesh` (1D -> slab, 2D -> pencil; the mesh wins over
    ``options.decomposition == "auto"``).
    """
    shape = tuple(int(s) for s in shape)
    decomp = options.decomposition

    if isinstance(mesh, int):
        ndev = mesh
        if decomp == "auto":
            decomp = choose_decomposition(shape, ndev)
        if decomp == "single" or ndev == 1:
            mesh = None
            decomp = "single"
        elif decomp == "slab":
            mesh = make_mesh(ndev)
        else:  # pencil: most-square grid, larger factor on rows
            r, c = sorted(geo.make_procgrid(ndev), reverse=True)
            mesh = make_mesh((r, c))

    if decomp == "single":  # explicit request wins over any provided mesh
        mesh = None
    if mesh is None or math.prod(mesh.devices.shape) == 1:
        decomp = "single"
        mesh = None
    elif decomp == "auto":
        decomp = "pencil" if len(mesh.axis_names) == 2 else "slab"

    if decomp == "slab" and mesh is not None and len(mesh.axis_names) != 1:
        raise ValueError("slab decomposition requires a 1D mesh")
    if decomp == "pencil" and mesh is not None and len(mesh.axis_names) != 2:
        raise ValueError("pencil decomposition requires a 2D mesh")

    stages = stage_layouts(decomp, mesh, geo.world_box(shape))
    return LogicPlan(
        shape=shape, decomposition=decomp, mesh=mesh,
        options=replace(options, decomposition=decomp), stages=stages,
    )


def stage_layouts(decomposition: str, mesh: Mesh | None, world: geo.Box3) -> tuple:
    """The per-stage (fft_axes, boxes) layout chain of a decomposition over
    ``world`` — the single source of truth for box geometry (the 4-shape
    lists of ``logic_plan3d``, ``heffte_plan_logic.h:152-164``)."""
    if decomposition == "single" or mesh is None:
        return (((0, 1, 2), (world,)),)
    if decomposition == "slab":
        p = mesh.shape[mesh.axis_names[0]]
        return (
            ((1, 2), tuple(geo.make_slabs(world, p, axis=0, rule=geo.ceil_splits))),
            ((0,), tuple(geo.make_slabs(world, p, axis=1, rule=geo.ceil_splits))),
        )
    r, c = (mesh.shape[a] for a in mesh.axis_names[:2])
    return (
        ((2,), tuple(geo.make_pencils(world, (r, c), 2, rule=geo.ceil_splits))),
        ((1,), tuple(geo.make_pencils(world, (r, c), 1, rule=geo.ceil_splits))),
        ((0,), tuple(geo.make_pencils(world, (r, c), 0, rule=geo.ceil_splits))),
    )


def io_boxes(
    decomposition: str, mesh: Mesh | None, world_in: geo.Box3, world_out: geo.Box3
) -> tuple[list[geo.Box3], list[geo.Box3]]:
    """Per-device input/output boxes for the forward orientation; r2c plans
    pass a shrunk complex-side ``world_out``."""
    first = stage_layouts(decomposition, mesh, world_in)[0][1]
    last = stage_layouts(decomposition, mesh, world_out)[-1][1]
    return list(first), list(last)
