"""Plan logic: option handling, decomposition selection, reshape minimization.

The heFFTe analog layer (``heffte/heffteBenchmark/include/heffte_plan_logic.h``,
``src/heffte_plan_logic.cpp``): ``plan_options`` {algorithm, use_reorder,
use_pencils, use_gpu_aware} (``heffte_plan_logic.h:69-89``) and
``plan_operations`` (``heffte_plan_logic.cpp:410-432``), which inspects the
in/out geometry and picks the cheapest reshape pipeline — the pencil planner
(``:162-245``) and slab planner (``:265-408``) both detect when the caller's
layouts already *are* pencils/slabs on useful axes and emit fewer reshapes.

On TPU the decision space is smaller and different: the transport is always
XLA collectives over the mesh (no gpu-aware/host-staged split — there is no
host staging to choose), layout reordering belongs to XLA's layout
assignment, and the real knobs are

- **decomposition**: slab (one exchange) vs pencil (two exchanges, but each
  on a smaller mesh axis and with more parallel lines per FFT stage);
- **axis assignment** (the reshape-minimization lever): which array axis the
  input/output sharding lives on. The slab chain works for ANY ordered axis
  pair (in_axis != out_axis) and the pencil chain for any axis permutation
  in either exchange order, so a plan can *start from the caller's layout*
  instead of resharding to a fixed canonical one — the TPU translation of
  heFFTe's "already pencils on the right axes -> skip the reshape";
- **exchange algorithm**: one fused ``all_to_all`` vs a pipelined
  ``ppermute`` ring (:mod:`.parallel.exchange`);
- **mesh geometry**: how to factor the device count into a 2D grid
  (min-surface search, :func:`distributedfft_tpu.native.pencil_grid`);
- **device count**: shrink to an evenly-dividing count when that removes
  padding at no per-device compute cost (``getProperDeviceNum``,
  ``fft_mpi_3d_api.cpp:232-272``).

:func:`logic_plan3d` resolves (shape, mesh/device-count, options, layouts)
to a concrete decomposition + mesh + stage chain, the role of
``plan_operations``.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field, replace
from typing import Sequence

from jax.sharding import Mesh, PartitionSpec as P

from . import geometry as geo
from . import native
from .parallel.exchange import ALGORITHMS, WIRE_DTYPES, wire_itemsize
from .parallel.mesh import make_mesh
from .parallel.slab import check_batch


#: Valid ``PlanOptions.tune`` values (None defers to the DFFT_TUNE env var).
TUNE_MODES = (None, "off", "wisdom", "measure")


@dataclass(frozen=True)
class PlanOptions:
    """User-tunable plan knobs (``plan_options``,
    ``heffte_plan_logic.h:69-89``).

    ``decomposition``: "auto" | "single" | "slab" | "pencil".
    ``algorithm``: "alltoall" | "ppermute" (``reshape_algorithm``,
    ``heffte_plan_logic.h:47-56``).
    ``executor``: registered local-FFT backend name (``one_dim_backend``,
    ``heffte_common.h:275``).
    ``donate``: consume the input buffer (bufferDev ping-pong analog,
    ``fft_mpi_3d_api.cpp:66-81``).
    ``renegotiate``: device-count renegotiation when the mesh is built from
    an int device count (``getProperDeviceNum``, ``fft_mpi_3d_api.cpp:232-272``):
    "auto" shrinks only when the negotiated count removes padding at equal
    per-device compute (a strict win); "force" always shrinks to the largest
    evenly-dividing count (the reference's rule); "never" keeps the request.
    ``overlap_chunks``: pipelined t2/t3 exchange/compute overlap — the
    local block is split into K chunks along the bystander axis and each
    chunk's exchange issues before the previous chunk's downstream FFT
    (the ``MPI_Waitany`` overlap of the reference's pipelined p2p path,
    ``fft_mpi_3d_api.cpp:610-699``). ``None`` (the default) defers to the
    ``DFFT_OVERLAP`` env var at plan time (unset -> 1 = today's
    monolithic chain); an int >= 1 pins K; ``"auto"`` picks K from the
    per-device block bytes vs the VMEM/ICI crossover
    (:func:`auto_overlap_chunks`, model in ``docs/MFU_ANALYSIS.md``).
    ``tune``: measured plan selection (:mod:`.tuner`; the reference's
    plan-and-pick discipline generalized across decomposition,
    transport, executor, AND overlap K — heFFTe/AccFFT's finding that
    the best combination is configuration-dependent and must be
    searched). ``"off"`` keeps today's static heuristics byte-identical;
    ``"wisdom"`` consults the persistent wisdom store and falls back to
    the heuristics on a miss (never measures); ``"measure"`` runs the
    pruned tournament on a miss and records the winner. ``None`` (the
    default) defers to the ``DFFT_TUNE`` env var (unset -> ``"off"``).
    See ``docs/TUNING.md``.
    ``wire_dtype``: on-wire compression of the t2 exchange payload,
    one of the registered wire codecs
    (:data:`..parallel.exchange.WIRE_DTYPES`): ``"bf16"`` casts the
    complex payload to (real, imag) bfloat16 pairs immediately before
    each collective and back after (half the c64 wire bytes);
    ``"int8"`` quantizes the (real, imag) planes per exchange tile with
    power-of-two steps riding as a tiny f32 sidecar (~quarter the c64
    wire bytes). Both at a bounded, measured precision cost
    (:func:`..parallel.exchange.wire_roundtrip_error`). ``"none"`` pins
    the exact wire; ``None`` (the default) defers to the
    ``DFFT_WIRE_DTYPE`` env var at plan time (unset -> exact,
    byte-identical HLO to an uncompressed plan).
    ``max_roundtrip_err``: the plan's relative round-trip error budget.
    The tuner enumerates reduced-accuracy candidates — compressed wire
    (``wire_dtype``) and reduced matmul precision (``mm_precision``)
    tiers — only for plans that declare a budget, filters out candidates
    whose measured round-trip error (wire + precision errors compose;
    one budget governs the sum) exceeds it, and replays a stored
    reduced-accuracy winner only into plans whose budget admits its
    recorded error.
    ``mm_precision``: plan-scoped MXU contraction tier of the
    matmul-family executors — ``"bf16"`` (one bf16 pass), ``"f32"``
    (3-pass refinement), ``"highest"`` (f32-exact, the bare default).
    ``None`` (the default) leaves the trace on the ``DFFT_MM_PRECISION``
    env default — byte-identical HLO to today's plans. A non-None tier
    composes into the executor label (``matmul:bf16`` — a DISTINCT
    executor: plan-cache keyed, wisdom-recorded, two tiers coexisting in
    one process; :func:`..ops.executors.tiered_name`).
    ``mm_complex``: plan-scoped complex-product mode of the same family
    (``"gauss"`` = the 3-real-matmul split; ``None``/``"native"`` defers
    to ``DFFT_MM_COMPLEX``).
    ``fuse``: the Pallas stage-fusion tier — ``True`` composes the
    ``:fuse`` flag into the executor label (``pallas:fuse``, a DISTINCT
    plan-cache-keyed executor; :func:`..ops.executors.fused_name`),
    asking the stage-graph compiler's fusion pass to fold the wire
    codec's encode/decode into the adjacent stage computes (Pallas
    mega-kernels where eligible; see ``docs/TUNING.md`` "Pallas fusion
    tier"). ``False`` pins fusion off; ``None`` (the default) defers to
    the ``DFFT_FUSE`` env var at plan time (unset -> off,
    byte-identical HLO to today's plans). Only meaningful with a
    ``pallas``-family executor and a compressed ``wire_dtype``;
    ineligible graphs fall back to the unfused chain with a counted,
    explain-visible reason — never an error.
    """

    decomposition: str = "auto"
    algorithm: str = "alltoall"
    executor: str = "xla"
    donate: bool = False
    renegotiate: str = "auto"
    overlap_chunks: int | str | None = None
    tune: str | None = None
    wire_dtype: str | None = None
    max_roundtrip_err: float | None = None
    mm_precision: str | None = None
    mm_complex: str | None = None
    fuse: bool | None = None

    def __post_init__(self) -> None:
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"unknown algorithm {self.algorithm!r}; use one of {ALGORITHMS}"
            )
        wd = self.wire_dtype
        if isinstance(wd, str):
            wd = wd.strip().lower()
            object.__setattr__(self, "wire_dtype", wd or None)
            wd = self.wire_dtype
        if wd not in WIRE_DTYPES and wd != "none":
            raise ValueError(
                f"wire_dtype must be one of {WIRE_DTYPES} or 'none', "
                f"got {self.wire_dtype!r}")
        mre = self.max_roundtrip_err
        if mre is not None and (
                not isinstance(mre, (int, float)) or isinstance(mre, bool)
                or not mre > 0):
            raise ValueError(
                f"max_roundtrip_err must be a positive float or None, "
                f"got {mre!r}")
        if self.decomposition not in ("auto", "single", "slab", "pencil"):
            raise ValueError(f"unknown decomposition {self.decomposition!r}")
        if self.renegotiate not in ("auto", "force", "never"):
            raise ValueError(
                f"renegotiate must be auto|force|never, got {self.renegotiate!r}"
            )
        oc = self.overlap_chunks
        if isinstance(oc, str) and oc != "auto":
            # Numeric strings (the DFFT_OVERLAP env form) normalize to int.
            try:
                oc = int(oc)
            except ValueError:
                raise ValueError(
                    f"overlap_chunks must be an int >= 1, 'auto', or None, "
                    f"got {self.overlap_chunks!r}") from None
            object.__setattr__(self, "overlap_chunks", oc)
        if oc is not None and oc != "auto" and (
                not isinstance(oc, int) or isinstance(oc, bool) or oc < 1):
            raise ValueError(
                f"overlap_chunks must be an int >= 1, 'auto', or None, "
                f"got {self.overlap_chunks!r}")
        if self.tune not in TUNE_MODES:
            raise ValueError(
                f"tune must be one of {tuple(m for m in TUNE_MODES if m)} "
                f"or None, got {self.tune!r}")
        # Normalize + validate the plan-scoped matmul tiers (the executor
        # label is composed at plan time by api._apply_mm_tiers; this
        # keeps an invalid tier from surviving into the plan cache key).
        from .ops.executors import (
            MM_COMPLEX_MODES, MM_TIERS, TIER_ALIASES,
        )

        mp = self.mm_precision
        if isinstance(mp, str):
            mp = mp.strip().lower() or None
            mp = TIER_ALIASES.get(mp, mp)  # lax-name spellings normalize
            object.__setattr__(self, "mm_precision", mp)
        if mp is not None and mp not in MM_TIERS:
            raise ValueError(
                f"mm_precision must be one of {MM_TIERS} or None, "
                f"got {self.mm_precision!r}")
        mc = self.mm_complex
        if isinstance(mc, str):
            mc = mc.strip().lower() or None
            object.__setattr__(self, "mm_complex", mc)
        if mc is not None and mc not in MM_COMPLEX_MODES:
            raise ValueError(
                f"mm_complex must be one of {MM_COMPLEX_MODES} or None, "
                f"got {self.mm_complex!r}")
        fu = self.fuse
        if isinstance(fu, str):
            # Env-style spellings normalize to the tri-state bool.
            fu = fu.strip().lower()
            if fu in ("", "none"):
                fu = None
            elif fu in ("1", "true", "on", "fuse"):
                fu = True
            elif fu in ("0", "false", "off"):
                fu = False
            else:
                raise ValueError(
                    f"fuse must be a bool or None, got {self.fuse!r}")
            object.__setattr__(self, "fuse", fu)
        elif fu is not None and not isinstance(fu, bool):
            raise ValueError(
                f"fuse must be a bool or None, got {self.fuse!r}")


DEFAULT_OPTIONS = PlanOptions()


def default_options(decomposition: str = "auto", **kw) -> PlanOptions:
    """cf. ``default_options<backend>()`` (``heffte_plan_logic.h:95``)."""
    return PlanOptions(decomposition=decomposition, **kw)


# Exchange/compute overlap auto-heuristic constants (crossover model in
# docs/MFU_ANALYSIS.md "Exchange/compute overlap"): a chunk's exchange
# payload must stay above the ICI packet-efficiency floor or the
# per-collective latency exceeds the transfer it hides, and chunk count is
# capped — each extra chunk adds one collective's fixed cost while the
# hideable transfer per chunk shrinks as 1/K.
OVERLAP_AUTO_MIN_CHUNK_BYTES = 4 << 20   # ~4 MiB/device per chunk floor
OVERLAP_AUTO_MAX_CHUNKS = 8


def auto_overlap_chunks(
    shape: Sequence[int], ndev: int, itemsize: int = 8,
) -> int:
    """Pick the overlap chunk count K from the per-device block bytes.

    K = clamp(block_bytes / OVERLAP_AUTO_MIN_CHUNK_BYTES, 1,
    OVERLAP_AUTO_MAX_CHUNKS): small blocks stay monolithic (nothing worth
    hiding; per-collective latency dominates), large blocks split until
    the per-chunk payload reaches the ICI efficiency floor or the chunk
    cap. ``itemsize`` defaults to complex64 (the on-chip tier — TPUs have
    no c128). The bystander-axis extent clamps K again inside
    :func:`..parallel.exchange.overlap_chunk_bounds`, so a coarse K here
    is safe for any chain geometry."""
    if ndev <= 1:
        return 1
    block = itemsize * math.prod(int(s) for s in shape) // ndev
    return max(1, min(OVERLAP_AUTO_MAX_CHUNKS,
                      block // OVERLAP_AUTO_MIN_CHUNK_BYTES))


def resolve_overlap_chunks(
    value: int | str | None,
    shape: Sequence[int] | None = None,
    ndev: int = 1,
    itemsize: int = 8,
) -> int:
    """Resolve a ``PlanOptions.overlap_chunks`` value to a concrete K.

    ``None`` reads the ``DFFT_OVERLAP`` env var at call time (unset ->
    1, today's monolithic chain); ``"auto"`` (from either source) runs
    :func:`auto_overlap_chunks`; ints pass through validated."""
    if value is None:
        raw = os.environ.get("DFFT_OVERLAP", "").strip()
        value = raw if raw else 1
    if isinstance(value, str):
        if value == "auto":
            return auto_overlap_chunks(shape, ndev, itemsize) if shape else 1
        try:
            value = int(value)
        except ValueError:
            raise ValueError(
                f"overlap_chunks must be an int >= 1 or 'auto', got "
                f"{value!r} (check DFFT_OVERLAP)") from None
    if value < 1:
        raise ValueError(f"overlap_chunks must be >= 1, got {value}")
    return int(value)


def resolve_wire_dtype(value: str | None) -> str | None:
    """Resolve a ``PlanOptions.wire_dtype`` value to a concrete wire
    mode: ``None`` (exact) or a registered codec name
    (:data:`..parallel.exchange.WIRE_DTYPES` — ``"bf16"``, ``"int8"``).

    ``None`` reads the ``DFFT_WIRE_DTYPE`` env var at plan time (unset
    -> exact); ``"none"`` pins the exact wire regardless of the env.
    One resolution point so the planners, the tuner's candidate space,
    and the benchmark drivers agree on what a given environment ships."""
    if value is None:
        value = os.environ.get("DFFT_WIRE_DTYPE", "").strip() or "none"
    v = value.strip().lower() if isinstance(value, str) else value
    if v in (None, "", "none", "0"):
        return None
    if v in WIRE_DTYPES:
        return v
    raise ValueError(
        f"wire_dtype must be one of {tuple(w for w in WIRE_DTYPES if w)} "
        f"or 'none', got {value!r} (check DFFT_WIRE_DTYPE)")


def resolve_fuse(value: bool | None) -> bool:
    """Resolve a ``PlanOptions.fuse`` value to a concrete bool.

    ``None`` reads the ``DFFT_FUSE`` env var at plan time (unset ->
    ``False``, today's unfused chains — byte-identical HLO); explicit
    bools pass through. One resolution point so the planners, the
    tuner's candidate space, and the benchmark drivers agree on whether
    a given environment fuses."""
    if value is None:
        raw = os.environ.get("DFFT_FUSE", "").strip().lower()
        if raw in ("", "0", "false", "off", "none"):
            return False
        if raw in ("1", "true", "on", "fuse"):
            return True
        raise ValueError(
            f"DFFT_FUSE must be 0/1/on/off, got {raw!r}")
    return bool(value)


def fused_model_stages(lp, shape=None, itemsize: int = 8) -> tuple:
    """Stage keys the Pallas fusion tier fuses with the wire codec for
    the chain ``lp`` describes — the ``fused=`` argument of
    :func:`model_stage_seconds` (the explain layer and the tuner's
    pruning model both derive it here, so they price fused plans
    identically).

    Empty when the plan does not activate fusion (the
    :func:`..stagegraph.plan_fusion` gate: executor carries the
    ``:fuse`` flag, a wire codec is set, overlap K == 1), and for
    chains with no kernel-fused stage: the single tier has no exchange,
    and the slab chains' multi-axis t0 sender and trailing op-chain
    inverse pass run the pure-JAX codec path, whose HBM streams match
    the unfused chain's — only their t3 receiver (c2c) fuses. Pencil
    chains fuse sender and every receiver (``t0``/``t1``/``t3``)."""
    from .ops.executors import split_fuse

    ex = lp.options.executor
    if not isinstance(ex, str):
        return ()
    try:
        if not split_fuse(ex)[1]:
            return ()
    except ValueError:
        return ()
    if resolve_wire_dtype(lp.options.wire_dtype) is None:
        return ()
    k = lp.options.overlap_chunks
    if not isinstance(k, int):
        ndev = 1 if lp.mesh is None else math.prod(lp.mesh.devices.shape)
        k = resolve_overlap_chunks(k, shape, ndev, itemsize)
    if k != 1:
        return ()
    if lp.mesh is None or lp.decomposition == "single":
        return ()
    if lp.decomposition == "pencil":
        return ("t0", "t1", "t3")
    if getattr(lp, "op", None):
        return ()
    return ("t3",)


def resolve_tune_mode(value: str | None) -> str:
    """Resolve a ``PlanOptions.tune`` value to a concrete mode.

    ``None`` reads the ``DFFT_TUNE`` env var at plan time (unset ->
    ``"off"``, today's static-heuristic planning); explicit strings pass
    through validated. One resolution point so the planners and the
    benchmark drivers agree on what a given environment plans."""
    if value is None:
        value = os.environ.get("DFFT_TUNE", "").strip() or "off"
    if value not in TUNE_MODES or value is None:
        raise ValueError(
            f"tune mode must be one of {tuple(m for m in TUNE_MODES if m)}, "
            f"got {value!r} (check DFFT_TUNE)")
    return value


def eligible_decompositions(shape: Sequence[int], ndev: int) -> tuple[str, ...]:
    """Decompositions worth *measuring* for ``ndev`` devices — the search
    axis the static :func:`choose_decomposition` collapses to one point.

    Slab is eligible while every device owns at least one plane on both
    exchange axes (past that the reference shrinks the device count,
    ``getProperDeviceNum``); pencil is eligible on any multi-device count
    (a prime count degrades to a 1xN grid, still a valid measurement).
    Single-device has nothing to search."""
    shape = tuple(int(s) for s in shape)
    if ndev <= 1:
        return ("single",)
    out = []
    if ndev <= min(shape[0], shape[1]):
        out.append("slab")
    out.append("pencil")
    return tuple(out)


@dataclass(frozen=True)
class LogicPlan:
    """Resolved plan skeleton (the ``logic_plan3d`` analog,
    ``heffte_plan_logic.h:152-164``): the decomposition, the mesh to run on,
    the axis assignment of the stage chain, and the intermediate layout
    chain as per-stage box lists. Orientation follows the plan's own
    direction: ``stages[0]`` is this plan's input side."""

    shape: tuple[int, int, int]
    decomposition: str            # "single" | "slab" | "pencil"
    mesh: Mesh | None
    options: PlanOptions
    forward: bool = True
    # Slab chain: input sharded on slab_axes[0], output on slab_axes[1].
    slab_axes: tuple[int, int] | None = None
    # Pencil chain: input layout (row->perm[0], col->perm[1], perm[2] local)
    # and exchange order "col_first" | "row_first".
    pencil_perm: tuple[int, int, int] | None = None
    pencil_order: str | None = None
    # Whether the caller's in/out layouts are realized by the chain itself
    # (True) or still need an edge reshard (False).
    in_absorbed: bool = True
    out_absorbed: bool = True
    # Device-count renegotiation record: (requested, used, reason).
    negotiated: tuple | None = None
    # Stage layouts: list of (fft_axes, boxes) pairs, input side first.
    stages: tuple = ()
    # Leading batch axis of a coalesced multi-request plan: B independent
    # transforms ride the chain with ONE shared exchange per stage (the
    # batch is a bystander dim of every collective). None = unbatched.
    # Geometry (stages, boxes) stays per-transform; the payload/model
    # accounting below scales with it.
    batch: int | None = None
    # Fused spectral-operator chain marker (:mod:`.operators`): the op
    # kind ("poisson", ...) of a FFT -> pointwise -> iFFT plan whose
    # forward half stops at the transposed midpoint and whose inverse
    # half retraces the chain. The payload/model accounting below
    # doubles per-exchange entries (out + back legs) and inserts the
    # ``t_mid`` stage when this is set. None = a plain transform.
    op: str | None = None

    @property
    def num_exchanges(self) -> int:
        n = {"single": 0, "slab": 1, "pencil": 2}[self.decomposition]
        # An operator chain retraces every exchange on the way back.
        return 2 * n if self.op else n


def spec_entries(mesh: Mesh, spec: P, ndim: int) -> tuple:
    """Validate a user PartitionSpec (rank, axis names) and return it padded
    to ``ndim`` entries."""
    entries = tuple(spec)
    if len(entries) > ndim:
        raise ValueError(
            f"PartitionSpec {spec} has more entries than the {ndim} array dims"
        )
    for entry in entries:
        if entry is None:
            continue
        for nm in entry if isinstance(entry, tuple) else (entry,):
            if nm not in mesh.shape:
                raise ValueError(
                    f"spec {spec} names unknown mesh axis {nm!r}; mesh axes: "
                    f"{tuple(mesh.shape)}"
                )
    return entries + (None,) * (ndim - len(entries))


def classify_layout(mesh: Mesh, spec: P) -> tuple[str, tuple]:
    """Classify a mesh-expressible 3D layout against the chain shapes.

    Returns ``("slab", (axis,))`` when a 1D mesh's axis shards exactly one
    array dim, ``("pencil", (row_dim, col_dim))`` when a 2D mesh's axes each
    shard exactly one distinct dim, and ``("other", ())`` for everything
    else (replicated dims, tupled axes, partial placements) — the layout
    detection step of heFFTe's planners (``heffte_plan_logic.cpp:162-245``
    checks ``is_pencils``; ``:265-408`` checks slabs).
    """
    entries = spec_entries(mesh, spec, 3)
    placement: dict = {}
    for d, e in enumerate(entries):
        if e is None:
            continue
        names = e if isinstance(e, tuple) else (e,)
        if len(names) != 1:
            return ("other", ())
        placement[names[0]] = d
    names = list(mesh.axis_names)
    if len(names) == 1 and set(placement) == set(names):
        return ("slab", (placement[names[0]],))
    if len(names) == 2 and set(placement) == set(names):
        return ("pencil", (placement[names[0]], placement[names[1]]))
    return ("other", ())


def choose_decomposition(shape: Sequence[int], ndev: int) -> str:
    """Pick slab vs pencil for ``ndev`` devices with no mesh constraint.

    A slab plan moves the whole world once; a pencil plan moves it twice but
    each FFT stage operates on full lines with ndev-way batching on both
    remaining axes. The slab plan stops scaling when devices outnumber the
    planes of the first axis (each device must own >= 1 X-plane and >= 1
    Y-plane) — the point where the reference would *shrink the device count*
    (``getProperDeviceNum``, ``fft_mpi_3d_api.cpp:232-272``) and heFFTe
    would switch to pencils (``use_pencils``, ``heffte_plan_logic.h:69-89``).
    """
    n0, n1, _ = shape
    if ndev <= 1:
        return "single"
    if ndev <= min(n0, n1):
        return "slab"
    return "pencil"


def negotiate_device_count(
    shape: Sequence[int], ndev: int, decomposition: str = "slab", *,
    slab_axes: tuple[int, int] | None = None,
    perm: tuple[int, int, int] | None = None,
    order: str | None = None,
) -> int:
    """Largest device count <= ``ndev`` whose slabs/pencils divide the split
    axes evenly — the reference's device-count renegotiation
    (``getProperDeviceNum``, ``fft_mpi_3d_api.cpp:232-272``: when N0 %
    devices != 0 it *shrinks* the device count until slabs divide).

    On TPU the padded-exchange path makes uneven shapes correct anyway, so
    this is an *optimization* choice, not a correctness one; see
    ``PlanOptions.renegotiate`` for how :func:`logic_plan3d` applies it.
    """
    shape = tuple(int(s) for s in shape)
    if decomposition == "slab":
        a0, a1 = slab_axes if slab_axes is not None else (0, 1)
        start = min(ndev, shape[a0], shape[a1])
    else:
        start = ndev
    for p in range(start, 0, -1):
        if all(shape[a] % parts == 0
               for a, parts in _chain_pad_axes(shape, decomposition, p,
                                               slab_axes=slab_axes,
                                               perm=perm, order=order)):
            return p
    return 1


def _chain_pad_axes(
    shape, decomposition: str, p: int, *,
    slab_axes: tuple[int, int] | None = None,
    perm: tuple[int, int, int] | None = None,
    order: str | None = None,
) -> list[tuple[int, int]]:
    """(array_axis, parts) pairs the chain ceil-pads at device count ``p`` —
    the padding surface the renegotiation decision must judge. Uses the
    ACTUAL chain axes (post layout absorption), not the canonical ones."""
    if decomposition == "slab":
        in_axis, out_axis = slab_axes if slab_axes is not None else (0, 1)
        return [(in_axis, p), (out_axis, p)]
    rows, cols = native.pencil_grid(shape, p)
    a, b, c = perm if perm is not None else (0, 1, 2)
    pairs = [(a, rows), (b, cols)]  # input-side shard pads
    if (order or "col_first") == "col_first":
        pairs += [(c, cols), (b, rows)]  # exchange split-axis pads
    else:
        pairs += [(c, rows), (a, cols)]
    return pairs


def _apply_renegotiation(
    shape: tuple[int, int, int], ndev: int, decomp: str, mode: str, *,
    slab_axes: tuple[int, int] | None = None,
    perm: tuple[int, int, int] | None = None,
    order: str | None = None,
) -> tuple[int, tuple | None]:
    """Resolve the device count to actually use, judged on the actual chain
    axes (after layout absorption). Returns (count, record) where record =
    (requested, used, reason) for ``plan_info``."""
    if mode == "never" or ndev <= 1 or decomp == "single":
        return ndev, None
    neg = negotiate_device_count(shape, ndev, decomp,
                                 slab_axes=slab_axes, perm=perm, order=order)
    if neg == ndev:
        return ndev, None
    if mode == "force":
        return neg, (ndev, neg, "forced: largest evenly-dividing count")
    # "auto": shrink only when per-device padded compute does not grow —
    # i.e. the ceil-shard extents stay the same on every chain axis, so
    # dropping devices only removes padding (a strict win: same compute per
    # device, less padded exchange payload, fewer participants).
    old = _chain_pad_axes(shape, decomp, ndev,
                          slab_axes=slab_axes, perm=perm, order=order)
    new = _chain_pad_axes(shape, decomp, neg,
                          slab_axes=slab_axes, perm=perm, order=order)
    free = all(
        geo.ceil_shards(shape[a0], p1) == geo.ceil_shards(shape[a0], p0)
        for (a0, p0), (_, p1) in zip(old, new)
    )
    if free:
        return neg, (ndev, neg, "auto: even shards at equal per-device compute")
    return ndev, (
        ndev, ndev,
        f"kept: shrinking to {neg} evenly-dividing devices would raise "
        "per-device compute more than the padding it removes",
    )


def logic_plan3d(
    shape: Sequence[int],
    mesh: Mesh | int | None,
    options: PlanOptions = DEFAULT_OPTIONS,
    *,
    forward: bool = True,
    in_spec: P | None = None,
    out_spec: P | None = None,
    batch: int | None = None,
) -> LogicPlan:
    """Resolve (shape, mesh-or-device-count, options, layouts) to a concrete
    plan skeleton. The role of ``plan_operations``
    (``heffte_plan_logic.cpp:410-432``): all geometry decisions happen here,
    and the builders in :mod:`.parallel` only execute them.

    ``batch=B`` records a leading batch axis of B coalesced transforms
    (:class:`LogicPlan.batch`); decomposition/mesh decisions are
    per-transform, but the overlap-K auto heuristic sees the B-fold
    per-device block.

    ``mesh`` may be ``None`` (single device), an int device count (the mesh
    is built here, shaped by the chosen decomposition — pencil grids come
    from the min-surface search, and the device count may be renegotiated
    per ``options.renegotiate``), or an existing :class:`Mesh` (1D -> slab,
    2D -> pencil; the mesh wins over ``options.decomposition == "auto"``).

    ``in_spec`` / ``out_spec`` are the caller's layouts (this plan's own
    orientation). When one classifies as a slab/pencil layout of the mesh,
    the stage chain is re-axed to *start (or end) right there*, eliminating
    the edge reshard — heFFTe's reshape minimization
    (``heffte_plan_logic.cpp:162-245,265-408``). Unabsorbable layouts are
    reported via ``in_absorbed``/``out_absorbed`` and handled by the caller
    with an edge reshard.
    """
    shape = tuple(int(s) for s in shape)
    batch = check_batch(batch)
    decomp = options.decomposition
    negotiated = None
    requested = None  # device count requested as an int (renegotiable)

    hier = options.algorithm == "hierarchical"
    if hier:
        # The two-leg ICI/DCN transport runs the slab chain (ONE logical
        # exchange, decomposed into two axis-local legs) over a hybrid
        # 2D mesh whose axes are the two fabrics — a pencil chain's
        # exchanges are each axis-local already, so there is nothing for
        # the hierarchical transport to split there.
        if not isinstance(mesh, Mesh) or len(mesh.axis_names) != 2:
            raise ValueError(
                "algorithm='hierarchical' requires an explicit 2D hybrid "
                "(dcn x ici) Mesh (e.g. multihost.make_hybrid_mesh()); "
                f"got {mesh!r}")
        if decomp not in ("auto", "slab"):
            raise ValueError(
                "hierarchical transport runs the slab chain over the "
                f"combined hybrid axis; decomposition={decomp!r} is not "
                "compatible")
        decomp = "slab"

    if isinstance(mesh, int):
        requested = ndev = mesh
        if decomp == "auto":
            decomp = choose_decomposition(shape, ndev)
        if decomp == "single" or ndev == 1:
            mesh = None
            decomp = "single"
        elif decomp == "slab":
            mesh = make_mesh(ndev)
        else:  # pencil: min-surface grid (rows over axis 0, cols over axis 1)
            r, c = native.pencil_grid(shape, ndev)
            mesh = make_mesh((r, c))

    if decomp == "single":  # explicit request wins over any provided mesh
        mesh = None
    if mesh is None or math.prod(mesh.devices.shape) == 1:
        decomp = "single"
        mesh = None
    elif decomp == "auto":
        decomp = "pencil" if len(mesh.axis_names) == 2 else "slab"

    if (decomp == "slab" and mesh is not None
            and len(mesh.axis_names) != 1 and not hier):
        raise ValueError("slab decomposition requires a 1D mesh")
    if decomp == "pencil" and mesh is not None and len(mesh.axis_names) != 2:
        raise ValueError("pencil decomposition requires a 2D mesh")

    # ---- axis assignment (reshape minimization) ----
    # The hierarchical slab chain runs over the COMBINED hybrid axis, so
    # 2D-mesh layout classification (which would read the mesh as a
    # pencil grid) does not apply — unabsorbable layouts get the edge
    # reshard exactly like any other non-chain layout.
    kin = classify_layout(mesh, in_spec) if (
        mesh is not None and in_spec is not None and not hier) else None
    kout = classify_layout(mesh, out_spec) if (
        mesh is not None and out_spec is not None and not hier) else None
    slab_axes = None
    perm = order = None
    in_absorbed = in_spec is None or mesh is None
    out_absorbed = out_spec is None or mesh is None

    if decomp == "slab" and mesh is not None:
        default_in, default_out = (0, 1) if forward else (1, 0)
        if kin is not None and kin[0] == "slab":
            in_axis = kin[1][0]
            in_absorbed = True
        else:
            in_axis = default_in
        if kout is not None and kout[0] == "slab" and kout[1][0] != in_axis:
            out_axis = kout[1][0]
            out_absorbed = True
        else:
            out_axis = default_out if default_out != in_axis else default_in
        slab_axes = (in_axis, out_axis)
    elif decomp == "pencil" and mesh is not None:
        default_perm = (0, 1, 2) if forward else (1, 2, 0)
        default_order = "col_first" if forward else "row_first"
        if kin is not None and kin[0] == "pencil":
            a, b = kin[1]
            perm = (a, b, 3 - a - b)
            in_absorbed = True
        else:
            perm = default_perm
        # The two exchange orders reach two different output layouts; pick
        # the one matching the caller's out_spec when possible.
        col_first_out = (perm[1], perm[2])  # (row_dim, col_dim)
        row_first_out = (perm[2], perm[0])
        if kout is not None and kout[0] == "pencil":
            if kout[1] == col_first_out:
                order, out_absorbed = "col_first", True
            elif kout[1] == row_first_out:
                order, out_absorbed = "row_first", True
            else:
                order = default_order
        else:
            order = default_order

    # ---- device-count renegotiation (int-mesh requests only), judged on
    # the ACTUAL chain axes chosen above ----
    if requested is not None and mesh is not None:
        used, negotiated = _apply_renegotiation(
            shape, requested, decomp, options.renegotiate,
            slab_axes=slab_axes, perm=perm, order=order,
        )
        if used != requested:
            if used == 1 and (in_spec is not None or out_spec is not None):
                # Layout-carrying plans need a mesh; keep the request.
                negotiated = (requested, requested,
                              "kept: in_spec/out_spec require a mesh")
            elif used == 1:
                mesh = None
                decomp = "single"
                slab_axes = perm = order = None
            elif decomp == "slab":
                mesh = make_mesh(used)
            else:
                r, c = native.pencil_grid(shape, used)
                mesh = make_mesh((r, c))

    stages = stage_layouts(
        decomp, mesh, geo.world_box(shape),
        slab_axes=slab_axes, pencil_perm=perm, pencil_order=order,
    )
    # Resolve the overlap knob (None -> DFFT_OVERLAP env, "auto" ->
    # block-bytes heuristic) to a concrete K on the FINAL mesh, so the
    # builders and plan_info see one int. Single-device chains have no
    # exchange to overlap.
    overlap = 1 if (decomp == "single" or mesh is None) else (
        resolve_overlap_chunks(
            options.overlap_chunks, shape=shape,
            ndev=math.prod(mesh.devices.shape),
            # A batched chain's per-device block is B-fold, which is what
            # the "auto" block-bytes crossover must judge.
            itemsize=8 * (batch or 1)))
    # Resolve the wire-compression knob (None -> DFFT_WIRE_DTYPE env) to
    # a concrete mode; single-device chains have no wire to compress.
    wire = None if (decomp == "single" or mesh is None) else (
        resolve_wire_dtype(options.wire_dtype))
    return LogicPlan(
        shape=shape, decomposition=decomp, mesh=mesh,
        options=replace(options, decomposition=decomp,
                        overlap_chunks=overlap, wire_dtype=wire),
        forward=forward,
        slab_axes=slab_axes, pencil_perm=perm, pencil_order=order,
        in_absorbed=in_absorbed, out_absorbed=out_absorbed,
        negotiated=negotiated, stages=stages, batch=batch,
    )


def _grid_boxes(
    world: geo.Box3, placements: dict[int, int], *, rule=geo.ceil_splits,
    major_dim: int | None = None,
) -> tuple:
    """Boxes of a layout sharding ``placements`` = {array_dim: parts},
    ordered with ``major_dim``'s chunk index slowest (mesh row-major device
    order). With one entry this is a slab split; with two, a pencil grid."""
    dims = sorted(placements)
    if major_dim is not None and dims[0] != major_dim:
        dims = [major_dim] + [d for d in dims if d != major_dim]
    chunks = {
        d: [
            (world.low[d] + a, world.low[d] + b)
            for a, b in rule(world.shape[d], placements[d])
        ]
        for d in dims
    }
    import itertools

    boxes = []
    for combo in itertools.product(*(range(placements[d]) for d in dims)):
        low = list(world.low)
        high = list(world.high)
        for d, ci in zip(dims, combo):
            low[d], high[d] = chunks[d][ci]
        boxes.append(geo.Box3(tuple(low), tuple(high)))
    return tuple(boxes)


def stage_layouts(
    decomposition: str,
    mesh: Mesh | None,
    world: geo.Box3,
    *,
    slab_axes: tuple[int, int] | None = None,
    pencil_perm: tuple[int, int, int] | None = None,
    pencil_order: str | None = None,
) -> tuple:
    """The per-stage (fft_axes, boxes) layout chain of a decomposition over
    ``world`` — the single source of truth for box geometry (the 4-shape
    lists of ``logic_plan3d``, ``heffte_plan_logic.h:152-164``). Input side
    of the chain first, in the chain's own orientation."""
    if decomposition == "single" or mesh is None:
        return (((0, 1, 2), (world,)),)
    if decomposition == "slab":
        in_axis, out_axis = slab_axes if slab_axes is not None else (0, 1)
        # Product over every mesh axis: a 1D slab mesh has one, the
        # hierarchical slab chain's hybrid (dcn x ici) mesh has two
        # (their row-major linearization IS the combined slab axis).
        p = math.prod(mesh.shape[a] for a in mesh.axis_names)
        local_axes = tuple(a for a in range(3) if a != in_axis)
        return (
            (local_axes, _grid_boxes(world, {in_axis: p})),
            ((in_axis,), _grid_boxes(world, {out_axis: p})),
        )
    rows, cols = (mesh.shape[a] for a in mesh.axis_names[:2])
    a, b, c = pencil_perm if pencil_perm is not None else (0, 1, 2)
    order = pencil_order or "col_first"
    if order == "col_first":
        # fft c | exch col (c<->b) | fft b | exch row (b<->a) | fft a
        return (
            ((c,), _grid_boxes(world, {a: rows, b: cols}, major_dim=a)),
            ((b,), _grid_boxes(world, {a: rows, c: cols}, major_dim=a)),
            ((a,), _grid_boxes(world, {b: rows, c: cols}, major_dim=b)),
        )
    # row_first: fft c | exch row (c<->a) | fft a | exch col (a<->b) | fft b
    return (
        ((c,), _grid_boxes(world, {a: rows, b: cols}, major_dim=a)),
        ((a,), _grid_boxes(world, {c: rows, b: cols}, major_dim=c)),
        ((b,), _grid_boxes(world, {c: rows, a: cols}, major_dim=c)),
    )


def exchange_payloads(lp: LogicPlan, shape, itemsize: int) -> list[dict]:
    """Per-exchange payload accounting: the TRUE information moved versus
    the bytes each algorithm ships on the wire.

    A fused spectral-operator plan (``lp.op``) retraces every exchange on
    its inverse half, so its entry list is the forward chain's entries
    followed by their mirrors in reverse chain order (the return legs) —
    per-execute wire counters and the pruning model inherit the doubling
    from here. Mirror byte figures reuse the forward leg's (exact for the
    dense transports, whose padded volume is split/concat-symmetric; the
    ragged transport's uneven-world mirror differs only in which axis's
    ceil padding it strips).

    The reference sizes true payloads with exact per-peer count tables
    (``TransInfo``, ``fft_mpi_3d_api.cpp:84-133``; ``dfft_exchange_table``);
    on TPU the dense ``alltoall`` ships both split- and concat-axis ceil
    padding, ``alltoallv`` (ragged) strips the split-axis padding, and the
    concat-axis padding (the SPMD equal-shard layout itself) always
    travels. Entries: {stage, mesh_axis, parts, true_bytes,
    alltoall_bytes, alltoallv_bytes}.

    A batched plan (``lp.batch = B``) ships B transforms' payloads in ONE
    collective per stage — every byte entry scales by B (and the
    per-execute wire counters and the tuner's pruning model inherit that
    scaling from here), while ``parts``/launch counts do not.

    Every entry additionally carries ``link`` ("ici" | "dcn" — which
    fabric the entry's mesh axis rides, so the model prices each leg
    with the right bandwidth) and ``wire_factor`` (the on-wire byte
    scale of the plan's ``wire_dtype`` compression: 1.0 exact, 0.5 for
    c64 -> bf16 pairs — multiply any byte entry by it for the bytes
    actually on the wire). A hierarchical slab plan returns TWO entries
    (``t2a`` on the ICI axis, ``t2b`` on the DCN axis) — per-leg
    accounting of the one logical exchange.
    """
    if lp.mesh is None:
        return []

    def _done(entries: list[dict]) -> list[dict]:
        # Operator chains pay every exchange twice (out + back).
        if getattr(lp, "op", None):
            return entries + [dict(e) for e in reversed(entries)]
        return entries

    shape = tuple(int(s) for s in shape)
    bsz = getattr(lp, "batch", None) or 1
    pad = lambda n, k: k * (-(-n // k))
    wf = wire_itemsize(itemsize, lp.options.wire_dtype) / itemsize
    link = lambda ax: "dcn" if str(ax) == "dcn" else "ici"
    out = []
    if lp.decomposition == "slab":
        names = lp.mesh.axis_names
        p = math.prod(lp.mesh.shape[a] for a in names)
        a_in, a_out = lp.slab_axes if lp.slab_axes else (0, 1)
        oth = 3 - a_in - a_out
        n_in, n_out, n_oth = shape[a_in], shape[a_out], shape[oth]
        if lp.options.algorithm == "hierarchical" and len(names) == 2:
            # Two axis-local legs of the one logical exchange: each leg
            # is a dense tiled all-to-all over ITS axis of the padded
            # block, so each leg ships fraction (parts-1)/parts of the
            # padded world on its own fabric.
            dcn_name, ici_name = names
            padded = pad(n_in, p) * pad(n_out, p) * n_oth
            truev = n_in * n_out * n_oth
            for stage, ax_name, parts in (
                    ("t2a", ici_name, lp.mesh.shape[ici_name]),
                    ("t2b", dcn_name, lp.mesh.shape[dcn_name])):
                f = (parts - 1) / parts
                dense = int(padded * f * itemsize * bsz)
                out.append({
                    "stage": stage, "mesh_axis": ax_name, "parts": parts,
                    "link": link(ax_name), "wire_factor": wf,
                    "true_bytes": int(truev * f * itemsize * bsz),
                    "alltoall_bytes": dense,
                    "alltoallv_bytes": dense,  # each leg is dense
                })
            return _done(out)
        f = (p - 1) / p
        out.append({
            "stage": "t2", "mesh_axis": names[0], "parts": p,
            "link": link(names[0]), "wire_factor": wf,
            "true_bytes": int(n_in * n_out * n_oth * f * itemsize * bsz),
            "alltoall_bytes": int(pad(n_in, p) * pad(n_out, p) * n_oth * f
                                  * itemsize * bsz),
            "alltoallv_bytes": int(pad(n_in, p) * n_out * n_oth * f
                                   * itemsize * bsz),
        })
        return _done(out)
    rows, cols = (lp.mesh.shape[ax] for ax in lp.mesh.axis_names[:2])
    a, b, c = lp.pencil_perm if lp.pencil_perm else (0, 1, 2)
    order = lp.pencil_order or "col_first"
    # (stage, mesh_axis_idx, parts, split_axis, padded extents of the two
    # non-split axes at that stage)
    pa, pb = pad(shape[a], rows), pad(shape[b], cols)
    if order == "col_first":
        pc = pad(shape[c], cols)
        seq = [("t2a", 1, cols, c, pa * pb), ("t2b", 0, rows, b, pa * pc)]
    else:
        pc = pad(shape[c], rows)
        seq = [("t2a", 0, rows, c, pa * pb), ("t2b", 1, cols, a, pc * pb)]
    true_vol = shape[0] * shape[1] * shape[2]
    for stage, ax_i, parts, split, bystander_padded in seq:
        f = (parts - 1) / parts
        out.append({
            "stage": stage, "mesh_axis": lp.mesh.axis_names[ax_i],
            "parts": parts,
            "link": link(lp.mesh.axis_names[ax_i]), "wire_factor": wf,
            "true_bytes": int(true_vol * f * itemsize * bsz),
            "alltoall_bytes": int(bystander_padded * pad(shape[split], parts)
                                  * f * itemsize * bsz),
            "alltoallv_bytes": int(bystander_padded * shape[split] * f
                                   * itemsize * bsz),
        })
    return _done(out)


def mm_dft_flops(shape: Sequence[int], axes: Sequence[int] | None = None,
                 ) -> float:
    """Real flops of one dense-tier matmul-DFT transform over ``axes``
    (default: all three): each transformed axis is one complex
    contraction of the whole block against an n x n DFT matrix — N*n
    complex MACs = ``8*N*n`` real flops per axis. The four-step split
    spends fewer flops above the dense bound, so this is the
    conservative (dense) figure — a RANKING quantity for the
    precision-tier cost model (:func:`..tuner.mm_tier_tflops`), not a
    prediction."""
    shape = tuple(int(s) for s in shape)
    n_total = math.prod(shape)
    return sum(8.0 * n_total * shape[a] for a in (axes or range(3)))


def model_stage_seconds(
    lp: LogicPlan,
    shape: Sequence[int],
    itemsize: int,
    *,
    hbm_gbps: float,
    wire_gbps: float,
    launch_seconds: float,
    algorithm: str | None = None,
    overlap_chunks: int | None = None,
    exchange_correction: float = 1.0,
    dcn_gbps: float | None = None,
    mm_tflops: float | None = None,
    concurrent_hide_seconds: float = 0.0,
    hide_correction: float = 1.0,
    fused: Sequence[str] = (),
) -> dict:
    """Per-stage analytical prediction of one execution, keyed exactly
    ``t0..t3`` — the model side of the explain/attribution join. A fused
    spectral-operator plan (``lp.op``) additionally carries the
    ``t_mid`` midpoint stage (final forward FFT + pointwise multiply +
    first inverse FFT in the transposed layout) and prices BOTH legs of
    every exchange (``exchange_payloads`` doubles the entries).

    ``exchange_correction`` scales every exchange's modeled seconds (not
    its byte accounting): the persisted per-(device_kind, transport)
    measured/model ratio of the calibrated hardware profile
    (:func:`..calibrate.model_correction`), so a transport the ideal
    wire model consistently underprices on this fabric is predicted —
    and divergence-gated — at its observed cost.

    FFT stages are the HBM-stream roofline (each axis pass reads and
    writes the per-device block once — the 3-pass bound of
    ``docs/MFU_ANALYSIS.md``); exchanges are wire bytes under the plan's
    transport (:func:`exchange_payloads` +
    :func:`..parallel.exchange.exchange_model_seconds`) with the
    overlap-K exposure crossover, each exchange hiding under its own
    downstream FFT stage. Stage taxonomy: ``t0`` = input-side FFT pass
    (two local axes for slab, one for pencil), ``t1`` = the pencil
    chain's mid FFT (zero for slab/single — the pack is fused into the
    exchange by XLA), ``t2`` = every exchange's *exposed* time, ``t3`` =
    the output-side FFT pass. Every entry carries ``seconds`` plus the
    quantities it was derived from (``flops``, ``hbm_bytes``,
    ``wire_bytes``) so MFU/utilization ratios need no re-derivation.

    A batched plan (``lp.batch = B``) scales every per-stage quantity by
    B — B-fold FFT flops and HBM stream, B-fold exchange payload through
    :func:`exchange_payloads` — while collective launch counts stay at
    the unbatched plan's (the batched win the tuner's pruning and the
    explain attribution must both price honestly).

    ``mm_tflops`` prices the plan's FFT stages as matmul-DFT
    contractions at that MXU rate (the executor's precision tier —
    :func:`..tuner.mm_tier_tflops`): each stage's seconds become
    ``max(HBM stream, mm_flops / rate)`` and the entry carries
    ``mm_flops``, so the explain join and the pruning model both rank
    bf16 vs f32 vs exact tiers before any compile. ``None`` (the
    default, and every non-matmul executor) keeps the pure HBM
    roofline — byte-identical model output.

    ``concurrent_hide_seconds`` adds OTHER transforms' compute to every
    exchange's hide budget — the cross-transform hide of a
    :func:`..stagegraph.schedule_concurrent` program, priced exactly
    the way the leg pipeline prices the DCN leg under the ICI leg's
    hide: extra downstream work the wire transfer can overlap with.
    :func:`model_concurrent_seconds` derives it per transform from its
    co-scheduled peers; 0.0 (the default) is the single-transform
    model, numerically unchanged.

    ``hide_correction`` scales every exchange's hide budget — the
    measured/model *realized-overlap* ratio the monitor's dispatch
    attribution persists (:func:`..calibrate.model_correction` keys
    ``"leg_hide"``/``"concurrent_hide"``), so a schedule whose measured
    interleave achieves less hide than the ideal model assumes is
    priced — and auto-width/auto-K ranked — at its observed overlap.
    1.0 (the default) is the uncorrected model, numerically
    unchanged.

    ``fused`` names stages the Pallas fusion tier fuses with the wire
    codec (:func:`fused_model_stages`): the codec pack/unpack happens
    in-register inside the stage kernel, so the intermediate c64 block
    the unfused chain streams between stage and codec is replaced by
    the WIRE form — each read+write pass pair (2·block) becomes
    (1 + wire_factor)·block. Flops are unchanged (fusion moves bytes,
    not math); the mm_tflops compute floor still applies. ``()`` (the
    default) is the unfused model, numerically unchanged."""
    shape = tuple(int(s) for s in shape)
    ndev = 1 if lp.mesh is None else math.prod(lp.mesh.devices.shape)
    bsz = getattr(lp, "batch", None) or 1
    n_total = math.prod(shape) * bsz
    block_bytes = itemsize * n_total / ndev
    alg = algorithm or lp.options.algorithm
    k = overlap_chunks
    if k is None:
        oc = lp.options.overlap_chunks
        k = oc if isinstance(oc, int) else 1

    def fft_stage(axes) -> dict:
        hbm = 2.0 * block_bytes * len(axes)  # read + write per axis pass
        flops = sum(5.0 * n_total * math.log2(max(2, shape[a]))
                    for a in axes) / ndev
        out = {"seconds": hbm / (hbm_gbps * 1e9), "flops": flops,
               "hbm_bytes": hbm, "wire_bytes": 0.0}
        if mm_tflops:
            # Matmul-DFT pricing at the tier's rate; the HBM stream
            # stays the floor (a memory-bound stage cannot be bought
            # faster by a cheaper tier).
            mm = mm_dft_flops(shape, axes) * bsz / ndev
            out["mm_flops"] = mm
            out["seconds"] = max(out["seconds"], mm / (mm_tflops * 1e12))
        return out

    zero = {"seconds": 0.0, "flops": 0.0, "hbm_bytes": 0.0,
            "wire_bytes": 0.0}
    op_chain = bool(getattr(lp, "op", None))
    if op_chain:
        # Fused spectral-operator taxonomy (canonical chains only): t0 =
        # forward input-side pass(es), t1 = the pencil chain's forward
        # mid FFT, t2 = every exchange's exposed time (out AND back legs
        # — exchange_payloads doubles the entries), t_mid = the
        # transposed-midpoint stage (final forward FFT + the pointwise
        # multiply + first inverse FFT), t3 = the inverse passes back to
        # the input layout.
        mid = fft_stage((0, 0))  # forward + inverse pass of the mid axis
        pw = 2.0 * block_bytes   # pointwise multiply: read + write once
        mid["hbm_bytes"] += pw
        mid["seconds"] += pw / (hbm_gbps * 1e9)
        mid["flops"] += 6.0 * n_total / ndev  # one complex multiply/elem
        if lp.decomposition == "pencil" and lp.mesh is not None:
            out = {"t0": fft_stage((2,)), "t1": fft_stage((1,)),
                   "t2": dict(zero), "t_mid": mid,
                   "t3": fft_stage((1, 2))}
        else:  # slab and single-device fused chains share the shape
            out = {"t0": fft_stage((1, 2)), "t1": dict(zero),
                   "t2": dict(zero), "t_mid": mid,
                   "t3": fft_stage((1, 2))}
    elif lp.decomposition == "single" or lp.mesh is None:
        # The staged single pipeline splits the whole-cube transform into
        # t0 (YZ planes) and t3 (X lines); no pack, no exchange.
        out = {"t0": fft_stage((1, 2)), "t1": dict(zero),
               "t2": dict(zero), "t3": fft_stage((0,))}
    elif lp.decomposition == "slab":
        fft_stages = [s[0] for s in lp.stages]
        out = {"t0": fft_stage(fft_stages[0]), "t1": dict(zero),
               "t2": dict(zero), "t3": fft_stage(fft_stages[1])}
    else:
        fft_stages = [s[0] for s in lp.stages]
        out = {"t0": fft_stage(fft_stages[0]),
               "t1": fft_stage(fft_stages[1]),
               "t2": dict(zero), "t3": fft_stage(fft_stages[2])}

    if fused:
        from .parallel.exchange import wire_itemsize

        wf = wire_itemsize(itemsize, lp.options.wire_dtype) / float(itemsize)
        for st in fused:
            # A fused stage's exchange-facing stream is the WIRE form:
            # each of the stage's read+write pass pairs (2·block) keeps
            # one c64 stream and trades the other — the intermediate
            # block the unfused chain hands the codec — for wire bytes,
            # so 2·block -> (1 + wire_factor)·block per pass.
            e = out.get(st)
            if not e or e["hbm_bytes"] <= 0.0 or wf >= 1.0:
                continue
            e["hbm_bytes"] *= (1.0 + wf) / 2.0
            e["seconds"] = e["hbm_bytes"] / (hbm_gbps * 1e9)
            if mm_tflops and e.get("mm_flops"):
                e["seconds"] = max(e["seconds"],
                                   e["mm_flops"] / (mm_tflops * 1e12))
            e["fused"] = True

    from .parallel.exchange import (
        WIRE_BYTE_KEYS, exchange_model_seconds,
    )

    # Each exchange hides under the FFT stage that consumes its output:
    # slab t2 -> t3; pencil t2a -> t1, t2b -> t3.
    payloads = exchange_payloads(lp, shape, itemsize)
    hide = {"t2": out["t3"]["seconds"], "t2a": out["t1"]["seconds"],
            "t2b": out["t3"]["seconds"]}
    if lp.decomposition == "slab":
        # A hierarchical slab plan's two legs both hide under t3 (the
        # pencil-style t2a/t2b taxonomy without a mid FFT stage).
        hide["t2a"] = hide["t2b"] = out["t3"]["seconds"]
    if op_chain:
        # Operator chains: the outbound exchange hides under t_mid, the
        # return one under t3 — per-entry attribution collapses to one
        # figure because mirrored entries share their stage names, so
        # each exchange hides under half the downstream compute.
        half = 0.5 * (out["t_mid"]["seconds"] + out["t3"]["seconds"])
        hide = {"t2": half, "t2a": half, "t2b": half}
    if concurrent_hide_seconds:
        # Cross-transform hide: a co-scheduled transform's FFT compute
        # is available to run under this transform's wire time — the
        # same shape as the leg pipeline's dcn_raw hide bonus below.
        hide = {k: v + float(concurrent_hide_seconds)
                for k, v in hide.items()}
    t2 = out["t2"]
    # Leg-level pipelining of the hierarchical transport at K > 1:
    # chunk i's ICI leg issues while chunk i-1's DCN leg and downstream
    # FFT run (exchange._hierarchical_pipelined), so the ICI leg's hide
    # budget additionally includes the DCN leg's raw transfer — the
    # per-leg overlap exposure the tuner's auto-K and pruning must
    # price. Computed from the t2b entry's raw (K-independent) time.
    leg_pipelined = alg == "hierarchical" and k > 1
    dcn_raw = 0.0
    if leg_pipelined:
        for e in payloads:
            if e["stage"] == "t2b":
                gb = (dcn_gbps if e.get("link") == "dcn" and dcn_gbps
                      else wire_gbps)
                wb = (e[WIRE_BYTE_KEYS[alg]] * e.get("wire_factor", 1.0)
                      / ndev)
                dcn_raw = exchange_model_seconds(
                    wb, e["parts"], alg, wire_gbps=gb,
                    launch_seconds=launch_seconds)["seconds"]
                break
    for e in payloads:
        # Per-leg link bandwidth: the DCN leg of a hierarchical (or
        # hybrid-mesh pencil) exchange is priced at the calibrated DCN
        # figure, not the ICI one. wire_factor scales for the plan's
        # on-wire compression (bf16 pairs halve c64 wire bytes; int8
        # block-scaled pairs quarter them).
        gbps = (dcn_gbps if e.get("link") == "dcn" and dcn_gbps
                else wire_gbps)
        wire = e[WIRE_BYTE_KEYS[alg]] * e.get("wire_factor", 1.0) / ndev
        hide_s = hide.get(e["stage"], 0.0)
        pipelined = leg_pipelined and e["stage"] == "t2a"
        if pipelined:
            hide_s += dcn_raw
        hide_s *= hide_correction
        m = exchange_model_seconds(
            wire, e["parts"], alg, wire_gbps=gbps,
            launch_seconds=launch_seconds, overlap_chunks=k,
            hide_seconds=hide_s)
        t2["seconds"] += m["exposed_seconds"] * exchange_correction
        t2["wire_bytes"] += wire
        t2.setdefault("raw_seconds", 0.0)
        t2["raw_seconds"] += m["seconds"] * exchange_correction
        t2.setdefault("steps", 0)
        t2["steps"] += m["steps"]
        # Per-leg attribution rows (the t2a/t2b join axis of explain):
        # one entry per exchange/leg with its own modeled time, hide
        # budget, and whether the leg pipeline hides it.
        t2.setdefault("legs", []).append({
            "stage": e["stage"], "mesh_axis": str(e["mesh_axis"]),
            "link": e.get("link", "ici"), "parts": e["parts"],
            "wire_bytes": wire, "wire_gbps": gbps,
            "seconds": m["exposed_seconds"] * exchange_correction,
            "raw_seconds": m["seconds"] * exchange_correction,
            "hide_seconds": hide_s, "leg_pipelined": pipelined,
        })
    return out


def model_concurrent_seconds(
    transforms: Sequence[tuple],
    *,
    hbm_gbps: float,
    wire_gbps: float,
    launch_seconds: float,
    dcn_gbps: float | None = None,
    **model_kw,
) -> dict:
    """Analytical price of a :func:`..stagegraph.schedule_concurrent`
    program over N independent transforms — the cross-transform-hide
    model of the DaggerFFT scheduling framing, built from
    :func:`model_stage_seconds` the way the leg pipeline prices the
    ICI leg under the DCN leg.

    ``transforms`` is a sequence of ``(lp, shape, itemsize)`` triples
    (one per co-scheduled transform). Each transform's exchanges are
    re-priced with ``concurrent_hide_seconds`` = the OTHER transforms'
    total FFT compute: the staggered schedule places peer compute
    between a transform's collective issue and its consumption, so the
    wire transfer overlaps it (there is no cross-transform data
    dependency). Returns::

        {"sequential_seconds": sum of solo models,
         "concurrent_seconds": compute sum + re-priced exposed wire,
         "hidden_seconds":     what the schedule removed,
         "speedup":            sequential / concurrent,
         "per_transform":      the N re-priced stage dicts}

    ``concurrent_seconds`` never exceeds ``sequential_seconds`` (a
    schedule can be priced as no worse than running serially), and with
    one transform the two are equal — the degenerate case IS the solo
    model."""
    transforms = list(transforms)
    kw = dict(hbm_gbps=hbm_gbps, wire_gbps=wire_gbps,
              launch_seconds=launch_seconds, dcn_gbps=dcn_gbps,
              **model_kw)

    def compute_s(m: dict) -> float:
        return sum(m[k]["seconds"] for k in m if k != "t2")

    def exposed_s(m: dict) -> float:
        return m["t2"]["seconds"]

    solo = [model_stage_seconds(
                lp, shape, itemsize,
                fused=fused_model_stages(lp, shape, itemsize), **kw)
            for lp, shape, itemsize in transforms]
    comp = [compute_s(m) for m in solo]
    total_comp = sum(comp)
    priced = [
        model_stage_seconds(
            lp, shape, itemsize,
            concurrent_hide_seconds=total_comp - comp[i],
            fused=fused_model_stages(lp, shape, itemsize), **kw)
        for i, (lp, shape, itemsize) in enumerate(transforms)
    ]
    sequential = sum(comp[i] + exposed_s(solo[i])
                     for i in range(len(solo)))
    concurrent = min(
        sequential,
        total_comp + sum(exposed_s(m) for m in priced))
    return {
        "sequential_seconds": sequential,
        "concurrent_seconds": concurrent,
        "hidden_seconds": sequential - concurrent,
        "speedup": (sequential / concurrent) if concurrent > 0 else 1.0,
        "per_transform": priced,
    }


def io_boxes(lp: LogicPlan, world_in: geo.Box3, world_out: geo.Box3) -> tuple:
    """Per-device input/output boxes of the plan's own orientation; r2c
    plans pass a shrunk complex-side world."""
    first = stage_layouts(
        lp.decomposition, lp.mesh, world_in,
        slab_axes=lp.slab_axes, pencil_perm=lp.pencil_perm,
        pencil_order=lp.pencil_order,
    )[0][1]
    last = stage_layouts(
        lp.decomposition, lp.mesh, world_out,
        slab_axes=lp.slab_axes, pencil_perm=lp.pencil_perm,
        pencil_order=lp.pencil_order,
    )[-1][1]
    return list(first), list(last)
