"""Multi-tenant QoS: priority classes, weighted-fair drain, quotas, SLOs.

The serving tier (:mod:`.serving`) coalesces, batches, retries, and
deadline-bounds requests — but every request is anonymous and equal.
DaggerFFT (arXiv 2601.12209) frames distributed FFT as a task-scheduling
problem; this module extends that framing from *which stage runs next*
to *whose transform runs next*: the admission/priority/fairness shape
every production inference stack needs once heavy mixed traffic shares
one mesh. Four pieces:

1. :class:`Tenant` — one traffic source: a priority class
   (``realtime`` > ``interactive`` > ``batch``), a weight (its
   fair-share ratio against same-class peers), an optional token-bucket
   rate quota (transforms/s with burst), and an optional declared SLO
   wait target for the ledger.
2. :class:`QosPolicy` — the tenant registry plus the three decision
   points the :class:`..serving.CoalescingQueue` consults:

   - **admission** (:meth:`QosPolicy.admit`): an over-quota submit is
     shed with :class:`QuotaExceeded` (queue ``admission="raise"``) or
     parked until the bucket refills (``"block"``), bounded by the
     request's own deadline. Realtime tenants may overdraw their bucket
     by one extra burst before the same rules apply — so under equal
     configs a realtime tenant **never sheds before a batch tenant
     does**. Retries and degraded rebuilds are charged to the owning
     tenant's bucket too (:meth:`QosPolicy.charge` — recovery work is
     traffic, docs/ROBUSTNESS.md).
   - **drain order** (:meth:`QosPolicy.order_groups`): strict priority
     class first, then weighted-fair queueing across tenants within a
     class (per-tenant virtual time advancing by transforms/weight —
     the deficit-weighted round robin that lands a 3:1 weight as a 3:1
     drain share under saturation). A starvation clock promotes any
     group older than ``max_wait_s x starvation_factor`` to the front
     regardless of class, so batch traffic always eventually drains.
   - **concurrent-wave placement** (:meth:`QosPolicy.concurrent_chunks`):
     when the queue merges group DAGs via
     :func:`..stagegraph.schedule_concurrent`, higher classes keep the
     earlier (earliest-wave) schedule slots, and a realtime group never
     rides a cohort containing batch groups — it splits off alone (or
     with realtime/interactive peers) instead.

3. **Accounting** — per-tenant ``serving_tenant_*`` metrics
   (submits/transforms/quota_shed/wait histogram/deadline misses, wired
   in :mod:`.serving`) and the in-process **SLO ledger** kept here:
   per-tenant p50/p99 queue wait and deadline-miss counts against the
   declared target, surfaced by ``python -m distributedfft_tpu.report
   qos`` (reads :meth:`QosPolicy.ledger_json` via ``--ledger`` or the
   newest history record carrying a ``qos`` block).
4. **Spec string** — ``DFFT_QOS`` declares the whole policy without
   code (grammar below); ``CoalescingQueue(policy=)`` overrides.

Spec grammar (env ``DFFT_QOS``; tenants separated by ``;``)::

    spec   = tenant (";" tenant)*
    tenant = name ":" kv ("," kv)*
    kv     = "class=" ("realtime"|"interactive"|"batch")   default interactive
           | "weight=" W        fair-share weight within the class (default 1)
           | "rate=" R          token-bucket quota, transforms/s (default none)
           | "burst=" B         bucket capacity (default max(R, 1))
           | "slo=" T           declared wait-SLO target, seconds

Example: ``DFFT_QOS="acme:class=realtime,weight=3,rate=100,slo=0.05;
bulk:class=batch,rate=10"``. ``DFFT_QOS_STARVE_FACTOR`` scales the
starvation clock (default 4.0 x the queue's ``max_wait_s``).

Default-off discipline: with no policy configured (no ``DFFT_QOS``, no
``policy=``) the serving tier's behavior — HLO, flush order, span
names, metrics — is byte-identical to the policy-free tier (pinned in
``tests/test_a2n_qos.py``). Neither knob affects what a plan compiles
to, so neither is plan-cache-keyed. See ``docs/SERVING_QOS.md``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

__all__ = [
    "CLASSES",
    "Tenant",
    "QosPolicy",
    "QuotaExceeded",
    "class_rank",
    "parse_qos",
    "write_ledger",
]

#: Priority classes, strongest first — drain order is strict across
#: classes (weighted-fair only *within* one).
CLASSES = ("realtime", "interactive", "batch")

#: Default starvation-clock multiplier: a group older than
#: ``max_wait_s x factor`` is promoted to the front of the drain order
#: regardless of class (``DFFT_QOS_STARVE_FACTOR`` overrides).
DEFAULT_STARVE_FACTOR = 4.0

#: Starvation reference age when the queue has no ``max_wait_s`` of its
#: own (seconds).
DEFAULT_STARVE_WAIT_S = 1.0

#: Bound of the per-tenant wait reservoir the SLO ledger keeps (oldest
#: samples drop first; p50/p99 are computed over the tail).
_WAIT_RESERVOIR = 8192

#: Bound of the reservoir *export* (``slo_report(include_waits=True)``)
#: — the newest tail that rides inside monitor sample documents so the
#: fleet aggregator can quantile-merge waits across processes without
#: shipping the full 8192-sample ring on every sample.
_WAIT_EXPORT = 256


def class_rank(klass: str) -> int:
    """0 = realtime (drains first) .. 2 = batch (drains last)."""
    return CLASSES.index(klass)


class QuotaExceeded(RuntimeError):
    """Admission shed a submit: the tenant's token bucket is empty and
    the queue runs ``admission="raise"``. ``retry_after_s`` is the
    bucket's refill estimate — the backoff a well-behaved client
    applies before resubmitting."""

    def __init__(self, tenant: str, retry_after_s: float):
        super().__init__(
            f"tenant {tenant!r} is over its rate quota; retry after "
            f"~{retry_after_s:.3f}s")
        self.tenant = tenant
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class Tenant:
    """One registered traffic source of the serving tier.

    ``klass`` is the strict priority class, ``weight`` the fair-share
    ratio against same-class peers (a weight-3 tenant drains ~3x the
    transforms of a weight-1 peer under saturation), ``rate`` the
    token-bucket quota in transforms/s (None = unlimited), ``burst``
    the bucket capacity (default ``max(rate, 1)``), ``slo_wait_s`` the
    declared queue-wait target the SLO ledger judges p99 against."""

    name: str
    klass: str = "interactive"
    weight: float = 1.0
    rate: float | None = None
    burst: float | None = None
    slo_wait_s: float | None = None

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ValueError(f"tenant name must be a non-empty string, "
                             f"got {self.name!r}")
        if self.klass not in CLASSES:
            raise ValueError(f"tenant {self.name!r}: class must be one "
                             f"of {CLASSES}, got {self.klass!r}")
        if not isinstance(self.weight, (int, float)) or isinstance(
                self.weight, bool) or not self.weight > 0:
            raise ValueError(f"tenant {self.name!r}: weight must be a "
                             f"positive number, got {self.weight!r}")
        if self.rate is not None and (
                isinstance(self.rate, bool)
                or not isinstance(self.rate, (int, float))
                or not self.rate > 0):
            raise ValueError(f"tenant {self.name!r}: rate must be a "
                             f"positive number or None, got {self.rate!r}")
        if self.burst is not None and (
                isinstance(self.burst, bool)
                or not isinstance(self.burst, (int, float))
                or not self.burst > 0):
            raise ValueError(f"tenant {self.name!r}: burst must be a "
                             f"positive number or None, got {self.burst!r}")
        if self.burst is not None and self.rate is None:
            raise ValueError(f"tenant {self.name!r}: burst without rate "
                             f"is meaningless (no bucket to cap)")

    @property
    def rank(self) -> int:
        return class_rank(self.klass)

    @property
    def bucket_burst(self) -> float:
        return float(self.burst if self.burst is not None
                     else max(self.rate or 1.0, 1.0))


class _Bucket:
    """One tenant's token bucket (transforms as tokens). Refilled lazily
    on access from a monotonic clock; ``charge`` may drive the balance
    negative (retries/degraded rebuilds are paid for after the fact —
    the tenant then waits out its own recovery debt at admission)."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, *, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = now

    def _refill(self, now: float) -> None:
        if now > self.stamp:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.stamp) * self.rate)
        self.stamp = now

    def take(self, n: float, *, floor: float, now: float) -> float:
        """Deduct ``n`` tokens if the balance stays >= ``floor``
        afterwards; returns 0.0 on success, else the seconds until it
        would (the admission park/shed figure)."""
        self._refill(now)
        if self.tokens - n >= floor:
            self.tokens -= n
            return 0.0
        return (n + floor - self.tokens) / self.rate

    def charge(self, n: float, *, now: float) -> None:
        self._refill(now)
        self.tokens -= n


class QosPolicy:
    """Tenant registry + the serving queue's three QoS decision points
    (admission, drain order, concurrent-wave placement) + the SLO
    ledger. Thread-safe: every mutating entry point serializes on one
    internal lock (the serving queue calls in from submit threads, the
    flush path, and deadline timers concurrently)."""

    def __init__(self, tenants=(), *,
                 starvation_factor: float | None = None,
                 clock=time.monotonic):
        if starvation_factor is None:
            raw = os.environ.get("DFFT_QOS_STARVE_FACTOR", "").strip()
            starvation_factor = float(raw) if raw else DEFAULT_STARVE_FACTOR
        if not starvation_factor > 0:
            raise ValueError(f"starvation_factor must be positive, got "
                             f"{starvation_factor!r}")
        self.starvation_factor = float(starvation_factor)
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, Tenant] = {}
        self._buckets: dict[str, _Bucket] = {}
        # Weighted-fair state: per-tenant virtual time (advances by
        # transforms/weight as groups drain) and the tenants active in
        # the previous ordering round (a newly-active tenant's vtime is
        # floored at the active minimum so idle time never banks into
        # an unbounded burst credit).
        self._vtime: dict[str, float] = {}
        self._active: set[str] = set()
        # SLO ledger: per-tenant counters + bounded wait reservoir.
        self._ledger: dict[str, dict] = {}
        for t in tenants:
            self.register(t)

    # ------------------------------------------------------- registry

    def register(self, tenant: Tenant) -> Tenant:
        """Add (or replace) one tenant. Replacing resets its bucket and
        fair-share clock, keeps its ledger."""
        if not isinstance(tenant, Tenant):
            raise TypeError(f"register takes a Tenant, got {tenant!r}")
        with self._lock:
            self._tenants[tenant.name] = tenant
            self._buckets.pop(tenant.name, None)
            self._vtime.pop(tenant.name, None)
        return tenant

    def tenant(self, name: str) -> Tenant:
        with self._lock:
            t = self._tenants.get(name)
        if t is None:
            raise ValueError(
                f"unknown tenant {name!r}; registered: "
                f"{sorted(self._tenants)}")
        return t

    def resolve(self, name: str | None) -> Tenant:
        """The tenant of one submit: ``None`` maps to the implicit
        ``default`` tenant (interactive, weight 1, no quota — registered
        on first use), anything else must be registered."""
        if name is None:
            with self._lock:
                t = self._tenants.get("default")
                if t is None:
                    t = self._tenants["default"] = Tenant("default")
            return t
        return self.tenant(name)

    def tenants(self) -> tuple[Tenant, ...]:
        with self._lock:
            return tuple(self._tenants.values())

    def _entry(self, name: str) -> dict:
        # Caller holds the lock.
        e = self._ledger.get(name)
        if e is None:
            e = self._ledger[name] = {
                "submits": 0, "transforms": 0, "quota_shed": 0,
                "deadline_misses": 0, "waits": [],
            }
        return e

    # ------------------------------------------------------ admission

    def _bucket(self, t: Tenant, now: float) -> _Bucket | None:
        # Caller holds the lock.
        if t.rate is None:
            return None
        b = self._buckets.get(t.name)
        if b is None:
            b = self._buckets[t.name] = _Bucket(
                t.rate, t.bucket_burst, now=now)
        return b

    def admit(self, name: str | None, n: int = 1) -> float:
        """Admission decision for ``n`` transforms of tenant ``name``:
        0.0 = admitted (tokens taken), else the seconds until the bucket
        could cover them — the queue parks (``admission="block"``) or
        sheds with :class:`QuotaExceeded` (``"raise"``). Realtime
        tenants may overdraw down to ``-burst`` before a wait is ever
        demanded, so realtime never sheds before batch does. Pure bucket
        arithmetic — intake accounting is :meth:`note_submit` (the
        queue's park loop re-calls this without double-counting)."""
        t = self.resolve(name)
        now = self._clock()
        with self._lock:
            b = self._bucket(t, now)
            if b is None:
                return 0.0
            floor = -t.bucket_burst if t.klass == "realtime" else 0.0
            return b.take(float(n), floor=floor, now=now)

    def charge(self, name: str | None, n: int = 1) -> None:
        """Unconditionally deduct ``n`` transforms from the tenant's
        bucket — the recovery-work charge (retries, degraded rebuilds):
        the balance may go negative, and the tenant waits out its own
        debt at the next admission."""
        t = self.resolve(name)
        now = self._clock()
        with self._lock:
            b = self._bucket(t, now)
            if b is not None:
                b.charge(float(n), now=now)

    def note_submit(self, name: str | None, n: int = 1) -> None:
        t = self.resolve(name)
        with self._lock:
            self._entry(t.name)["submits"] += n

    def note_shed(self, name: str | None, n: int = 1) -> None:
        t = self.resolve(name)
        with self._lock:
            self._entry(t.name)["quota_shed"] += n

    # ---------------------------------------------------- drain order

    def starvation_s(self, max_wait_s: float | None) -> float:
        """The promotion age of the starvation clock: ``max_wait_s x
        starvation_factor`` (the queue's coalescing deadline scaled), or
        the default reference when the queue has none."""
        base = max_wait_s if max_wait_s else DEFAULT_STARVE_WAIT_S
        return float(base) * self.starvation_factor

    def order_groups(self, infos, *, max_wait_s: float | None = None):
        """Drain order of one flush: ``infos`` is a sequence of dicts
        ``{"key", "tenant", "n", "age_s"}`` (one pending group each, in
        formation order); returns them reordered:

        1. starved groups (``age_s`` past :meth:`starvation_s`) first,
           oldest first — regardless of class;
        2. then strict class rank (realtime, interactive, batch);
        3. within a class, weighted-fair queueing: repeatedly take the
           backlogged tenant with the smallest virtual time, advancing
           a *local* copy by ``n/weight`` per group taken — the
           deficit-weighted round robin whose long-run drain shares
           match the weights.

        The persistent virtual times advance only through
        :meth:`account_drain` (what actually drained — a flush with a
        ``limit`` may split a group and drain less than it ordered);
        ordering simulates charges on a local overlay so one tenant's
        many groups still interleave with its peers' within a call."""
        infos = list(infos)
        starve = self.starvation_s(max_wait_s)
        with self._lock:
            promoted = [i for i in infos if i["age_s"] >= starve]
            promoted.sort(key=lambda i: -i["age_s"])
            rest = [i for i in infos if i["age_s"] < starve]
            per_tenant: dict[str, list] = {}
            for i in rest:
                per_tenant.setdefault(i["tenant"], []).append(i)
            participating = set(per_tenant)
            returning = participating & self._active
            if returning:
                floor = min(self._vtime.get(t, 0.0) for t in returning)
                for t in participating - returning:
                    self._vtime[t] = max(self._vtime.get(t, 0.0), floor)
            self._active = participating
            vt = {t: self._vtime.get(t, 0.0) for t in participating}
            ordered = list(promoted)
            for rank in range(len(CLASSES)):
                backlog = {t: q for t, q in per_tenant.items()
                           if self._tenants.get(
                               t, Tenant(t)).rank == rank and q}
                while backlog:
                    t = min(backlog, key=lambda u: (vt.get(u, 0.0), u))
                    info = backlog[t].pop(0)
                    if not backlog[t]:
                        del backlog[t]
                    w = self._tenants.get(t, Tenant(t)).weight
                    vt[t] = vt.get(t, 0.0) + info["n"] / w
                    ordered.append(info)
            # Keep virtual times bounded: shift the whole axis toward
            # zero once it drifts far (ordering only reads differences).
            if self._vtime and min(self._vtime.values()) > 1e9:
                lo = min(self._vtime.values())
                for t in self._vtime:
                    self._vtime[t] -= lo
        return ordered

    def account_drain(self, name: str | None, n: int) -> None:
        """Record ``n`` transforms of tenant ``name`` actually drained:
        advances the persistent fair-share virtual time by ``n/weight``
        and the ledger's ``transforms`` counter. The queue calls this
        per executed group — a limited flush that splits a group
        charges only what it took, which is what makes the long-run
        drain shares track the weights."""
        t = self.resolve(name)
        with self._lock:
            self._vtime[t.name] = (self._vtime.get(t.name, 0.0)
                                   + n / t.weight)
            self._entry(t.name)["transforms"] += n

    # ------------------------------------------- concurrent placement

    def concurrent_chunks(self, infos, ncc: int):
        """Partition an ordered group list into the cohorts one
        concurrent dispatch merges (:func:`..stagegraph
        .schedule_concurrent`): consecutive runs of at most ``ncc``
        groups, never mixing a realtime group with a batch group — a
        realtime flush splits off alone (or with realtime/interactive
        peers) rather than riding a batch cohort. Earlier drain order =
        earlier schedule index = the earliest waves, so higher classes
        keep the front of each merged program."""
        chunks: list[list] = []
        cur: list = []
        cur_ranks: set[int] = set()
        for info in infos:
            t = info["tenant"]
            with self._lock:
                rank = self._tenants.get(t, Tenant(t)).rank
            splits = (rank == 0 and 2 in cur_ranks) or (
                rank == 2 and 0 in cur_ranks)
            if cur and (len(cur) >= ncc or splits):
                chunks.append(cur)
                cur, cur_ranks = [], set()
            cur.append(info)
            cur_ranks.add(rank)
        if cur:
            chunks.append(cur)
        return chunks

    def preempt_wave(self, infos, width: int):
        """Wave admission with realtime preemption — the streaming
        scheduler's admission point (docs/SERVING_QOS.md, "Streaming
        scheduler & wave preemption"). ``infos`` is the full pending
        sequence in drain order (:meth:`order_groups` output, dicts with
        at least ``tenant``/``n``), ``width`` the next wave's capacity.
        Returns ``(admit, bumped, charges)``:

        - ``admit`` — the groups the next wave dispatches (at most
          ``width``, relative order preserved), with EVERY realtime
          group guaranteed a slot ahead of lower classes: a realtime
          arrival never waits out a saturated wave.
        - ``bumped`` — the would-have-dispatched lower-class groups a
          realtime group displaced. They are re-queued, never dropped:
          the caller leaves them pending with formation stamps intact,
          so they sit at the front of the next drain order and their
          starvation clocks keep running.
        - ``charges`` — ``{tenant: transforms}`` already deducted (via
          :meth:`charge`) from the preempting realtime tenants: each
          bumped transform is recovery-shaped work paid by whoever
          demanded the slot, the same even-recovery-work-charges
          discipline retries follow.

        Without a realtime group past the cutoff this is plain
        truncation: ``(infos[:width], [], {})``.
        """
        infos = list(infos)
        width = max(1, int(width))
        with self._lock:
            ranks = {id(i): self._tenants.get(
                i["tenant"], Tenant(i["tenant"] or "default")).rank
                for i in infos}
        window = infos[:width]
        window_ids = {id(i) for i in window}
        rt = [i for i in infos if ranks[id(i)] == 0]
        jumpers = [i for i in rt if id(i) not in window_ids]
        if not jumpers:
            return window, [], {}
        others = [i for i in infos if ranks[id(i)] != 0]
        admit = (rt + others)[:width]
        # Preserve drain order within the admitted set: realtime first
        # is a guarantee of ADMISSION, not of schedule position —
        # concurrent_chunks/order already put higher classes first.
        admit_ids = {id(i) for i in admit}
        admit = [i for i in infos if id(i) in admit_ids]
        bumped = [i for i in window if id(i) not in admit_ids]
        charges: dict[str, int] = {}
        for k, b in enumerate(bumped):
            t = jumpers[k % len(jumpers)]["tenant"]
            charges[t] = charges.get(t, 0) + int(b.get("n", 1))
        for t, n in charges.items():
            self.charge(t, n)
        with self._lock:
            for t, n in charges.items():
                e = self._entry(t or "default")
                e["preemptions"] = e.get("preemptions", 0) + n
        return admit, bumped, charges

    # ------------------------------------------------------ SLO ledger

    def note_wait(self, name: str | None, seconds: float) -> None:
        t = self.resolve(name)
        with self._lock:
            e = self._entry(t.name)
            e["waits"].append(float(seconds))
            if len(e["waits"]) > _WAIT_RESERVOIR:
                del e["waits"][:len(e["waits"]) - _WAIT_RESERVOIR]

    def note_miss(self, name: str | None, n: int = 1) -> None:
        t = self.resolve(name)
        with self._lock:
            self._entry(t.name)["deadline_misses"] += n

    def slo_report(self, *, include_waits: bool | int = False) -> dict:
        """The SLO ledger as one JSON document: per tenant, the class/
        weight/quota declaration, the intake/drain/shed/miss counters,
        the p50/p99 queue wait over the reservoir, and — when the
        tenant declared ``slo_wait_s`` — whether p99 currently meets it
        (``slo_ok``; misses count against it too).

        ``include_waits`` additionally exports the newest tail of each
        tenant's wait reservoir as a ``waits`` list (True = the
        ``_WAIT_EXPORT`` default cap, an int = that cap) — the raw
        samples the fleet aggregator quantile-merges across processes;
        the per-process p50/p99 rows alone cannot be merged."""
        cap = 0
        if include_waits:
            cap = (_WAIT_EXPORT if include_waits is True
                   else max(1, int(include_waits)))
        with self._lock:
            out = {}
            names = set(self._ledger) | set(self._tenants)
            for name in sorted(names):
                t = self._tenants.get(name, Tenant(name))
                e = self._ledger.get(name, {})
                waits = sorted(e.get("waits", ()))
                row = {
                    "class": t.klass,
                    "weight": t.weight,
                    "rate": t.rate,
                    "submits": e.get("submits", 0),
                    "transforms": e.get("transforms", 0),
                    "quota_shed": e.get("quota_shed", 0),
                    "deadline_misses": e.get("deadline_misses", 0),
                    "preemptions": e.get("preemptions", 0),
                    "wait_p50_s": _quantile(waits, 0.50),
                    "wait_p99_s": _quantile(waits, 0.99),
                    "slo_wait_s": t.slo_wait_s,
                }
                if t.slo_wait_s is not None:
                    p99 = row["wait_p99_s"]
                    row["slo_ok"] = (row["deadline_misses"] == 0
                                     and (p99 is None
                                          or p99 <= t.slo_wait_s))
                if cap:
                    raw = e.get("waits", ())
                    row["waits"] = [round(float(w), 6)
                                    for w in list(raw)[-cap:]]
                out[name] = row
        return {"schema": 1, "tenants": out}

    def ledger_json(self) -> str:
        return json.dumps(self.slo_report(), indent=2, sort_keys=True)

    # ------------------------------------------------------------- env

    @classmethod
    def from_spec(cls, raw: str) -> "QosPolicy | None":
        """Parse one ``DFFT_QOS`` spec string (module docstring grammar)
        into a policy; empty/whitespace -> None (no policy)."""
        tenants = parse_qos(raw)
        return cls(tenants) if tenants else None

    @classmethod
    def from_env(cls) -> "QosPolicy | None":
        return cls.from_spec(os.environ.get("DFFT_QOS", ""))


def _quantile(sorted_vals, q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def parse_qos(raw: str) -> list[Tenant]:
    """``DFFT_QOS`` spec string -> tenants. Raises ``ValueError`` on a
    malformed clause — a policy that silently drops a tenant would let
    its traffic bypass every quota."""
    tenants: list[Tenant] = []
    for clause in (raw or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if ":" not in clause:
            raise ValueError(
                f"DFFT_QOS clause {clause!r} lacks a ':' (name:kv,...)")
        name, _, body = clause.partition(":")
        kw: dict = {"name": name.strip()}
        for directive in body.split(","):
            directive = directive.strip()
            if not directive:
                continue
            k, sep, v = directive.partition("=")
            k, v = k.strip(), v.strip()
            if not sep or not v:
                raise ValueError(
                    f"DFFT_QOS clause {clause!r}: directive "
                    f"{directive!r} is not key=value")
            try:
                if k == "class":
                    kw["klass"] = v
                elif k == "weight":
                    kw["weight"] = float(v)
                elif k == "rate":
                    kw["rate"] = float(v)
                elif k == "burst":
                    kw["burst"] = float(v)
                elif k == "slo":
                    kw["slo_wait_s"] = float(v)
                else:
                    raise ValueError(f"unknown key {k!r}")
            except ValueError as e:
                raise ValueError(
                    f"DFFT_QOS clause {clause!r}: {e}") from None
        tenants.append(Tenant(**kw))
    return tenants


def write_ledger(policy: QosPolicy, path: str) -> str:
    """Persist the policy's SLO ledger as JSON (line-atomic replace) —
    the file ``report qos --ledger`` reads."""
    from .utils.atomicio import replace_file

    replace_file(path, policy.ledger_json() + "\n")
    return path
