"""Benchmark regression tracking — run records, history store, compare.

The reference judges itself by one-shot printf runs against heFFTe
(``README.md:44-77``); this repo had grown the same problem at larger
scale: rounds of ``BENCH_r*.json``, a ``benchmarks/results/`` campaign
directory, and per-stage t0..t3 telemetry — every number interpreted by
a human with no baseline and no gate. This module closes the loop:

1. **Run records** — one normalized JSON object per benchmark run:
   the bench/speed3d result line, the per-stage t0..t3 aggregates, the
   roofline block, and the metrics snapshot, stamped with
   commit/config/device-kind (:func:`normalize_bench_line`,
   :func:`make_run_record`).
2. **History store** — an append-only JSONL file
   (``benchmarks/results/history.jsonl`` by default; ``DFFT_BENCH_HISTORY``
   overrides, empty/``0`` disables). Existing artifacts — the driver's
   ``BENCH_r*.json`` wrappers, raw bench-line JSONL — ingest via
   :func:`records_from_artifact`.
3. **Compare engine** — rolling-window baseline per (metric, config,
   device_kind), median + MAD bounds (robust to the flaky-tunnel
   CPU-fallback outliers, which are additionally flagged ``fallback``
   and excluded from every baseline), verdicts of improved /
   within-noise / regressed, and stage-level localization: when the
   headline regresses, the report names which of t0..t3 moved
   (:func:`compare_record`).

CLI: ``python -m distributedfft_tpu.report {record,history,compare}``
(see :mod:`.report`); ``compare --gate`` exits nonzero on a confirmed
regression, for CI / round-driver use.

Import discipline: stdlib only — ``bench.py``'s orchestrator loads this
file directly (no package ``__init__``, no jax) so a sick TPU transport
can never hang the history append.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

__all__ = [
    "SCHEMA",
    "default_history_path",
    "git_commit",
    "make_run_record",
    "normalize_bench_line",
    "records_from_artifact",
    "append_records",
    "load_history",
    "config_signature",
    "group_key",
    "robust_stats",
    "metric_direction",
    "compare_record",
    "regressed_metrics",
    "wisdom_verdict",
    "format_compare",
    "summarize_history",
]

SCHEMA = 1

# Compare-engine defaults (every knob has a CLI flag in report.py).
DEFAULT_WINDOW = 8        # rolling baseline size per group
DEFAULT_MADS = 3.0        # noise band half-width in scaled MADs
DEFAULT_MIN_REL = 0.05    # noise-band floor as a fraction of the median
DEFAULT_MIN_SAMPLES = 2   # baseline records required for a verdict

#: Auxiliary metrics of the record's ``cost`` block (the explain-layer
#: compiled cost/memory view) that compare/gate alongside the headline:
#: a change can hold wall time steady while regressing its HBM
#: footprint or compile bill, and the gate must still catch it. Both
#: are smaller-is-better; both use the same median+MAD noise model.
AUX_COST_METRICS = ("peak_hbm_bytes", "compile_seconds")

#: Auxiliary metrics of the record's ``rates`` block (throughput stamps
#: like the serving tier's ``transforms_per_s`` and the spectral-
#: operator tier's ``solves_per_s``): same noise model,
#: larger-is-better per :func:`metric_direction`'s ``_per_s`` rule. The
#: gate fails on a confirmed throughput regression even when the
#: GFlop/s headline is within noise (per-transform flops shrink when a
#: batched program degrades to serialized exchanges, but the flagship
#: headline may not move enough to trip alone). ``solves_per_s`` rows
#: additionally live in their own baseline group: the operator name is
#: keyed into the record config (``op``), so operator runs never share
#: baselines with bare transforms.
AUX_RATE_METRICS = ("transforms_per_s", "solves_per_s",
                    "concurrent_transforms_per_s", "waves_per_s")

_MAD_SCALE = 1.4826       # MAD -> sigma under a normal noise model


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_history_path() -> str | None:
    """The history store path: ``DFFT_BENCH_HISTORY`` when set (empty or
    ``0`` disables appends entirely -> None), else the repo's
    ``benchmarks/results/history.jsonl``."""
    env = os.environ.get("DFFT_BENCH_HISTORY")
    if env is not None:
        env = env.strip()
        return None if env in ("", "0") else env
    return os.path.join(_repo_root(), "benchmarks", "results",
                        "history.jsonl")


def git_commit() -> str | None:
    """Best-effort short commit sha of the repo this module lives in;
    None when git is unavailable. Never raises."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=_repo_root(),
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:  # noqa: BLE001 — metadata only, never fatal
        return None


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S")


# ------------------------------------------------------------- records

def make_run_record(
    *,
    metric: str,
    value: float,
    unit: str = "GFlops/s",
    seconds: float | None = None,
    config: dict | None = None,
    backend: str | None = None,
    device_kind: str | None = None,
    fallback: bool = False,
    stages: dict | None = None,
    roofline: dict | None = None,
    metrics: dict | None = None,
    cost: dict | None = None,
    rates: dict | None = None,
    explain: dict | None = None,
    qos: dict | None = None,
    health: dict | None = None,
    numerics: dict | None = None,
    source: str = "",
    commit: str | None = None,
    recorded_at: str | None = None,
    extra: dict | None = None,
) -> dict:
    """One normalized run record. ``config`` holds the knobs that define
    the baseline group (dtype, devices, ...); ``device_kind`` defaults to
    ``backend`` so a CPU row can never enter a TPU baseline. ``cost`` is
    the explain layer's compiled cost/memory block (peak-HBM /
    compile-seconds, baselined by :func:`compare_record` alongside the
    headline); ``rates`` the throughput block (``transforms_per_s`` —
    larger-is-better, gated the same way); ``explain`` the full
    attribution record for ``report explain``. A metrics snapshot's own
    schema version is lifted to ``metrics_schema`` so registry drift is
    detectable without parsing the block."""
    rec = {
        "schema": SCHEMA,
        "recorded_at": recorded_at or _now_iso(),
        "source": source,
        "commit": commit,
        "metric": metric,
        "value": float(value),
        "unit": unit,
        "seconds": seconds,
        "backend": backend,
        "device_kind": device_kind or backend or "unknown",
        "fallback": bool(fallback),
        "ok": float(value) > 0.0,
        "config": dict(config or {}),
    }
    if stages:
        rec["stages"] = {str(k): float(v) for k, v in stages.items()}
    if roofline:
        rec["roofline"] = roofline
    if metrics:
        rec["metrics"] = metrics
        if isinstance(metrics, dict) and metrics.get("schema") is not None:
            rec["metrics_schema"] = metrics["schema"]
    if cost:
        rec["cost"] = cost
    if rates:
        rec["rates"] = {str(k): float(v) for k, v in rates.items()
                        if isinstance(v, (int, float))}
    if explain:
        rec["explain"] = explain
    if qos:
        # The serving tier's per-tenant SLO ledger (QosPolicy
        # .slo_report()) — surfaced offline by ``report qos``.
        rec["qos"] = qos
    if health:
        # The live monitor's health verdict (monitor.health_snapshot()
        # — stall/SLO-burn/quota alerts); gated by compare_record /
        # regressed_metrics alongside cost/rates and surfaced offline
        # by ``report health``.
        rec["health"] = health
    if numerics:
        # The numerics plane's drift/sentinel ledger (numerics
        # .numerics_snapshot()); gated by regressed_metrics (drifting
        # buckets, non-finite outputs) and surfaced by ``report
        # numerics``.
        rec["numerics"] = numerics
    if extra:
        rec["extra"] = extra
    return rec


def _is_fallback_line(obj: dict) -> bool:
    """A bench line produced because the TPU transport was down (the
    flagged-so-excluded-from-TPU-baselines condition)."""
    status = (obj.get("telemetry") or {}).get("status") or {}
    if status:
        return status.get("tpu_available") is False
    err = obj.get("error")
    return isinstance(err, str) and err.startswith("tpu unavailable")


def normalize_bench_line(
    obj: dict,
    *,
    source: str,
    commit: str | None = None,
    recorded_at: str | None = None,
    extra: dict | None = None,
) -> dict | None:
    """A ``bench.py`` result line -> run record; None when ``obj`` is not
    a bench line (no ``metric``/``value``)."""
    if not isinstance(obj, dict) or "metric" not in obj:
        return None
    try:
        value = float(obj.get("value", 0.0))
    except (TypeError, ValueError):
        return None
    config = {}
    # "overlap" (PlanOptions.overlap_chunks != 1), "tuned" (the
    # autotuner's winner tuple), and "batch" (a coalesced multi-request
    # program) are part of the baseline group: an overlapped, tuned, or
    # batched run must never be judged against a monolithic /
    # heuristic / single-transform baseline or vice versa — they compile
    # different programs (the tuned tuple may even move between
    # re-tunes, which the label then keys into separate baselines).
    # "profile" is the hardware-profile source ("calibrated" — stamped
    # by bench.py only when a calibrated profile was live, so that
    # calibrated-model runs and default-constant runs never share a
    # baseline; default rows keep the old schema AND the old groups).
    # "wire_dtype" (on-wire compressed exchange — any registered codec,
    # bf16 or int8) and "transport" (a non-default exchange algorithm,
    # hierarchical included) are keyed for the same reason: a
    # compressed-wire or two-leg run compiles a different collective
    # program than the exact flat exchange, so compressed and exact
    # runs (and different codecs) never share a baseline; default rows
    # (exact wire, alltoall) keep the old schema and groups.
    # "op" is the fused spectral-operator name (DFFT_BENCH_OP /
    # speed3d -op): an operator run executes a different program class
    # (forward + pointwise + inverse, double the exchanges) than a bare
    # transform, so operator rows form their own baseline groups and
    # their solves_per_s rate never compares against transform rows;
    # transform rows keep the old schema.
    # "degraded" marks a run produced by the executor-fallback chain
    # (docs/ROBUSTNESS.md): the matmul-DFT fallback runs a different —
    # typically slower — program class than the configured executor, so
    # degraded rows form their own baseline group and can never poison
    # the fast baselines (nor be judged against them). Non-degraded
    # rows keep the old schema exactly.
    # "precision" is the plan-scoped matmul accuracy tier
    # (PlanOptions.mm_precision, the executor label's :bf16/:f32
    # suffix): a reduced-precision run trades accuracy for MXU rate and
    # must never share a baseline with exact runs (nor its faster
    # numbers poison them); full-precision rows keep the old schema.
    # "concurrent" is the multi-transform schedule width (DFFT_BENCH_
    # CONCURRENT / speed3d -concurrent): a schedule_concurrent run
    # executes N merged stage DAGs as one interleaved program — a
    # different program class than N sequential dispatches — so
    # concurrent rows form their own baseline group and their
    # concurrent_transforms_per_s rate never compares against
    # sequential rows; sequential rows keep the old schema.
    # "tenant_class" is the QoS priority class a serving-tier run was
    # measured under (docs/SERVING_QOS.md): a realtime run drains ahead
    # of the backlog while a batch run waits out its promotion clock —
    # different latency/throughput regimes by construction — so
    # realtime and batch runs never share a compare baseline;
    # policy-free rows keep the old schema and groups.
    # "procs"/"topology" are the multi-process shape (jax
    # process_count / the mesh's cross-host layout): a 4-process run
    # pays DCN hops a single-process run never sees, so single- and
    # multi-process runs must never share a compare baseline;
    # single-process rows keep the old schema and groups.
    # "fusion" is the Pallas fusion tier (executor label ":fuse" —
    # adjacent stage pairs collapsed into shape-specialized
    # mega-kernels, the inter-stage HBM round-trip elided): a fused run
    # compiles a different program class than the unfused chain, so
    # fused rows form their own baseline group and never poison (nor
    # are judged against) unfused baselines; unfused rows keep the old
    # schema and groups.
    # "scheduler" is the serving dispatch mode (DFFT_BENCH_SERVE /
    # bench.py --serve-streaming): a streaming run keeps a rolling wave
    # program in flight (admission overlaps the previous wave's drain)
    # while a flush run pays a full barrier per dispatch — different
    # latency/occupancy regimes by construction — so streaming and
    # flush rows form their own baseline groups and waves_per_s never
    # compares across modes; non-serving rows keep the old schema.
    for k in ("dtype", "devices", "decomposition", "overlap", "tuned",
              "batch", "profile", "wire_dtype", "transport", "op",
              "degraded", "precision", "fusion", "concurrent",
              "tenant_class", "procs", "topology", "scheduler"):
        if obj.get(k) is not None:
            config[k] = obj[k]
    ex: dict = {}
    for k in ("executor", "donated", "vs_baseline", "max_roundtrip_err",
              "all", "host", "pid", "process_index"):
        if obj.get(k) is not None:
            ex[k] = obj[k]
    if extra:
        ex.update(extra)
    telemetry = obj.get("telemetry") or {}
    if telemetry.get("status"):
        ex["status"] = telemetry["status"]
    # The explain layer's compiled cost/memory block rides either at the
    # line's top level or inside the telemetry block; only keep it when
    # at least one value is non-null (a CPU-fallback line stamps nulls).
    cost = obj.get("cost") or telemetry.get("cost")
    if not (isinstance(cost, dict)
            and any(v is not None for v in cost.values())):
        cost = None
    explain = obj.get("explain")
    if not isinstance(explain, dict):
        explain = None
    qos = obj.get("qos")
    if not isinstance(qos, dict):
        qos = None
    health = obj.get("health")
    if not isinstance(health, dict):
        health = None
    numerics = obj.get("numerics")
    if not isinstance(numerics, dict):
        numerics = None
    rates = {k: obj[k] for k in AUX_RATE_METRICS
             if isinstance(obj.get(k), (int, float))}
    return make_run_record(
        metric=obj["metric"],
        value=value,
        unit=obj.get("unit", "GFlops/s"),
        seconds=obj.get("seconds"),
        config=config,
        backend=obj.get("backend"),
        device_kind=obj.get("device_kind"),
        fallback=_is_fallback_line(obj),
        stages=obj.get("stages"),
        roofline=obj.get("roofline"),
        metrics=telemetry.get("metrics"),
        cost=cost,
        rates=rates or None,
        explain=explain,
        qos=qos,
        health=health,
        numerics=numerics,
        source=source,
        commit=commit,
        recorded_at=recorded_at,
        extra=ex or None,
    )


def records_from_artifact(
    text: str, *, source: str, recorded_at: str | None = None,
    commit: str | None = None,
) -> tuple[list[dict], int]:
    """Run records from one benchmark artifact, format auto-detected:

    - run-record JSONL (a prior history file; records pass through),
    - raw bench-line JSONL (``benchmarks/results/hw_bench*.json`` style),
    - the round driver's ``BENCH_r*.json`` wrapper
      (``{"n", "cmd", "rc", "tail", "parsed"}`` — the parsed line is the
      record; a null parse yields no record, never an error).

    Returns ``(records, skipped)`` where ``skipped`` counts JSON lines
    that matched no format (a wrapper with ``"parsed": null`` counts as
    skipped so ingest reports are honest about silent rounds).
    """
    stripped = text.strip()
    if not stripped:
        return [], 0
    records: list[dict] = []
    skipped = 0

    def from_obj(obj) -> dict | None:
        if not isinstance(obj, dict):
            return None
        if obj.get("schema") == SCHEMA and "metric" in obj \
                and "device_kind" in obj:
            return obj  # already a run record — pass through
        if "parsed" in obj and "cmd" in obj:  # driver wrapper
            parsed = obj.get("parsed")
            if not isinstance(parsed, dict):
                return None
            x = {"round": obj.get("n")}
            return normalize_bench_line(
                parsed, source=source, recorded_at=recorded_at,
                commit=commit, extra=x)
        return normalize_bench_line(
            obj, source=source, recorded_at=recorded_at, commit=commit)

    # Whole-document JSON (the driver wrapper is one multi-line object).
    try:
        doc = json.loads(stripped)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        rec = from_obj(doc)
        return ([rec], 0) if rec else ([], 1)
    if isinstance(doc, list):
        for entry in doc:
            rec = from_obj(entry)
            if rec is None:
                skipped += 1
            else:
                records.append(rec)
        return records, skipped

    for line in stripped.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            skipped += 1
            continue
        rec = from_obj(obj)
        if rec is None:
            skipped += 1
        else:
            records.append(rec)
    return records, skipped


# ------------------------------------------------------------- storage

def append_records(records: list[dict], path: str) -> None:
    """Append run records to the JSONL history store (created, with
    parent directory, on first use). One ``O_APPEND`` write for the
    whole batch, so concurrent writers (parallel bench rounds, the
    record CLI) land line-atomically — no interleaved/torn lines.
    Inlined rather than imported from ``utils.atomicio`` because this
    module must stay loadable from its file path alone (bench.py's
    orchestrator discipline: no package imports)."""
    if not records:
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    payload = "".join(
        json.dumps(rec, sort_keys=True) + "\n" for rec in records
    ).encode("utf-8")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, payload)
    finally:
        os.close(fd)


def load_history(path: str) -> tuple[list[dict], int]:
    """Load the JSONL history store leniently: ``(records, dropped)``
    where malformed lines (truncated tail from a killed writer, non-JSON,
    records without the baseline-key fields) are counted, not raised."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return [], 0
    records: list[dict] = []
    dropped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            dropped += 1
            continue
        if not isinstance(obj, dict) or "metric" not in obj \
                or "value" not in obj:
            dropped += 1
            continue
        records.append(obj)
    return records, dropped


# ------------------------------------------------------------- compare

def config_signature(record: dict) -> str:
    """Deterministic short signature of the record's config dict — the
    config part of the baseline group key."""
    cfg = record.get("config") or {}
    return ",".join(f"{k}={cfg[k]}" for k in sorted(cfg))


def group_key(record: dict) -> tuple[str, str, str]:
    """Baseline group: (metric, config signature, device_kind). Records
    from different device kinds never compare against each other."""
    return (str(record.get("metric")), config_signature(record),
            str(record.get("device_kind", "unknown")))


def _baseline_eligible(rec: dict) -> bool:
    """Fallback runs (TPU transport down) and failed runs (value<=0)
    never poison a baseline."""
    return not rec.get("fallback") and rec.get("ok", True) \
        and float(rec.get("value", 0.0)) > 0.0


def robust_stats(values: list[float]) -> tuple[float, float]:
    """(median, MAD) of ``values`` — the noise model robust to the odd
    flaky-transport outlier that mean/stddev is not."""
    if not values:
        return math.nan, math.nan
    s = sorted(values)
    n = len(s)
    med = (s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2]))
    dev = sorted(abs(v - med) for v in s)
    mad = (dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
    return med, mad


def metric_direction(metric: str, unit: str | None = None) -> int:
    """+1 when larger is better (throughput), -1 when smaller is better
    (latency, byte footprints). Stage times and the cost-block metrics
    (``peak_hbm_bytes``, ``compile_seconds``) always compare
    smaller-is-better. Rates (``*_per_s`` — ``transforms_per_s``, the
    batched-serving throughput stamp) are larger-is-better and must be
    classified BEFORE the latency rules: ``transforms_per_s`` also ends
    with ``_s``, and misreading it would gate throughput improvements
    as regressions."""
    m, u = metric.lower(), (unit or "").lower()
    if m.endswith("_per_s") or u.endswith("/s"):
        return 1
    if "seconds" in m or m.endswith("_s") or u in ("s", "seconds", "ms"):
        return -1
    if m.endswith("_bytes") or u in ("b", "bytes"):
        return -1
    return 1


def _band(med: float, mad: float, mads: float, min_rel: float) -> float:
    """Half-width of the within-noise band around the baseline median."""
    return max(_MAD_SCALE * mads * mad, min_rel * abs(med))


def compare_record(
    record: dict,
    history: list[dict],
    *,
    window: int = DEFAULT_WINDOW,
    mads: float = DEFAULT_MADS,
    min_rel: float = DEFAULT_MIN_REL,
    min_samples: int = DEFAULT_MIN_SAMPLES,
) -> dict:
    """Verdict of one run record against its rolling-window baseline.

    The baseline is the last ``window`` eligible records in ``history``
    sharing the record's group key (same metric, config signature, and
    device_kind — mixed device kinds never compare), excluding fallback
    and failed runs. Bounds are median +/- max(``mads`` scaled MADs,
    ``min_rel`` x median): inside is ``within-noise``, the good side is
    ``improved``, the bad side is ``regressed``. Fewer than
    ``min_samples`` baseline records -> ``no-baseline`` (never gates).

    On a regression, per-stage t0..t3 localization runs the same noise
    model over ``record["stages"]`` vs the baseline records' stages, so
    the report can say *which* stage moved ("t2_exchange +31%").
    """
    key = group_key(record)
    base = [r for r in history
            if r is not record and group_key(r) == key
            and _baseline_eligible(r)]
    base = base[-window:]
    value = float(record.get("value", 0.0))
    out = {
        "metric": record.get("metric"),
        "device_kind": record.get("device_kind"),
        "config": config_signature(record),
        "unit": record.get("unit"),
        "value": value,
        "fallback": bool(record.get("fallback")),
        "baseline": {"n": len(base), "window": window},
        "verdict": "no-baseline",
        "localization": [],
    }
    health = record.get("health")
    if isinstance(health, dict) and health.get("status") not in (
            None, "ok", "unknown"):
        # The live monitor's verdict needs no baseline: a firing alert
        # (stall, SLO burn) is absolute badness, copied through even
        # for a no-baseline record so regressed_metrics gates on it
        # alongside the compare verdicts.
        out["health"] = {
            "status": health.get("status"),
            "alerts": [
                {"name": a.get("name"), "severity": a.get("severity"),
                 **({"tenant": a["tenant"]} if a.get("tenant") else {})}
                for a in health.get("alerts") or []
                if isinstance(a, dict)],
        }
    numerics = record.get("numerics")
    if isinstance(numerics, dict):
        # Like health, the numerics verdict needs no baseline: a
        # drifting plan bucket or a non-finite-output sentinel is
        # absolute badness. Copied through (drop the raw error tails)
        # so regressed_metrics gates on it.
        out["numerics"] = {
            "nonfinite": dict(numerics.get("nonfinite") or {}),
            "plans": {
                key: {k: v for k, v in b.items() if k != "errors"}
                for key, b in (numerics.get("plans") or {}).items()
                if isinstance(b, dict)},
        }
    if len(base) < min_samples:
        return out
    med, mad = robust_stats([float(r["value"]) for r in base])
    band = _band(med, mad, mads, min_rel)
    out["baseline"].update(median=med, mad=mad, band=band)
    out["delta_pct"] = 100.0 * (value - med) / med if med else math.inf
    direction = metric_direction(str(record.get("metric")),
                                 record.get("unit"))
    if abs(value - med) <= band:
        out["verdict"] = "within-noise"
    elif (value - med) * direction > 0:
        out["verdict"] = "improved"
    else:
        out["verdict"] = "regressed"
        out["localization"] = _localize_stages(
            record, base, mads=mads, min_rel=min_rel,
            min_samples=min_samples)
    aux = _compare_block(record, base, "cost", AUX_COST_METRICS,
                         mads=mads, min_rel=min_rel,
                         min_samples=min_samples)
    aux += _compare_block(record, base, "rates", AUX_RATE_METRICS,
                          mads=mads, min_rel=min_rel,
                          min_samples=min_samples)
    if aux:
        out["aux"] = aux
    return out


def _compare_block(
    record: dict, base: list[dict], block: str, names, *, mads: float,
    min_rel: float, min_samples: int,
) -> list[dict]:
    """Verdicts of one auxiliary record block against the baseline
    records' same block — the gate extension beyond the headline:
    ``cost`` (peak-HBM / compile seconds, smaller-is-better) catches a
    wall-time-neutral footprint regression; ``rates``
    (``transforms_per_s``, larger-is-better via the ``_per_s`` rule)
    catches a throughput regression of the batched serving tier. Same
    median+MAD noise model as the headline; direction per
    :func:`metric_direction`."""
    vals = record.get(block)
    if not isinstance(vals, dict):
        return []
    rows: list[dict] = []
    for name in names:
        val = vals.get(name)
        if not isinstance(val, (int, float)):
            continue
        samples = []
        for r in base:
            c = r.get(block)
            if isinstance(c, dict) and isinstance(c.get(name),
                                                  (int, float)):
                samples.append(float(c[name]))
        row = {"metric": name, "block": block, "value": float(val),
               "baseline": {"n": len(samples)}, "verdict": "no-baseline"}
        if len(samples) >= min_samples:
            med, mad = robust_stats(samples)
            band = _band(med, mad, mads, min_rel)
            row["baseline"].update(median=med, mad=mad, band=band)
            row["delta_pct"] = (100.0 * (val - med) / med if med
                                else math.inf)
            if abs(val - med) <= band:
                row["verdict"] = "within-noise"
            elif (val - med) * metric_direction(name) > 0:
                row["verdict"] = "improved"
            else:
                row["verdict"] = "regressed"
        rows.append(row)
    return rows


def regressed_metrics(result: dict) -> list[str]:
    """Every regressed metric of one :func:`compare_record` result —
    the headline, any aux cost/rate metric, and any firing (severity
    ``alert``) live-monitor health alert. The gate trips when this is
    non-empty (one shared rule for the CLI and any caller)."""
    out = []
    if result.get("verdict") == "regressed":
        out.append(str(result.get("metric")))
    for row in result.get("aux") or []:
        if row.get("verdict") == "regressed":
            out.append(f"{result.get('metric')}:{row['metric']}")
    for alert in (result.get("health") or {}).get("alerts") or []:
        if alert.get("severity") == "alert":
            name = alert.get("name")
            if alert.get("tenant"):
                name = f"{name}[{alert['tenant']}]"
            out.append(f"health:{name}")
    # Numerics-plane drift (docs/OBSERVABILITY.md "Numerics plane"): a
    # run whose shadow audit judged a plan bucket drifting — or whose
    # sentinels caught non-finite outputs — regressed numerically even
    # when every timing metric improved. Fast-but-newly-wrong must not
    # pass the perf gate.
    numerics = result.get("numerics") or {}
    for key, b in sorted((numerics.get("plans") or {}).items()):
        if b.get("drifting"):
            out.append(f"numerics:drift:{key}")
    nf_out = sum(v for k, v in (numerics.get("nonfinite") or {}).items()
                 if k.startswith("output:"))
    if nf_out > 0:
        out.append("numerics:nonfinite")
    return out


def _localize_stages(
    record: dict, base: list[dict], *, mads: float, min_rel: float,
    min_samples: int,
) -> list[dict]:
    """Per-stage verdicts for a regressed headline: every stage of the
    record with enough baseline samples, flagged ``regressed`` when its
    time moved above the noise band, sorted worst-regression first."""
    stages = record.get("stages") or {}
    rows: list[dict] = []
    for name, val in stages.items():
        samples = [float(r["stages"][name]) for r in base
                   if isinstance(r.get("stages"), dict)
                   and name in r["stages"]]
        if len(samples) < min_samples:
            continue
        med, mad = robust_stats(samples)
        if not med:
            continue
        val = float(val)
        band = _band(med, mad, mads, min_rel)
        rows.append({
            "stage": name,
            "value": val,
            "baseline_median": med,
            "delta_pct": 100.0 * (val - med) / med,
            # Stage times are latencies: regressed means slower.
            "regressed": (val - med) > band,
        })
    rows.sort(key=lambda r: (-r["regressed"], -r["delta_pct"], r["stage"]))
    return rows


def wisdom_verdict(
    stored_seconds: float,
    fresh_seconds: list[float],
    *,
    mads: float = DEFAULT_MADS,
    min_rel: float = DEFAULT_MIN_REL,
    min_samples: int = DEFAULT_MIN_SAMPLES,
) -> dict:
    """Is a stored tuning winner still as fast as its recorded tournament
    time? ``stored_seconds`` is the wisdom entry's measured per-execute
    time; ``fresh_seconds`` are per-execute times of later benchmark runs
    of that same winner tuple (from the history store). Same noise model
    as :func:`compare_record` (median + MAD band; seconds are latencies,
    larger = worse): ``regressed`` means the winner now runs slower than
    when it won — stale wisdom that should be re-measured. Fewer than
    ``min_samples`` fresh runs -> ``no-baseline`` (never gates)."""
    out = {
        "stored_seconds": float(stored_seconds),
        "fresh": {"n": len(fresh_seconds)},
        "verdict": "no-baseline",
    }
    if len(fresh_seconds) < min_samples:
        return out
    med, mad = robust_stats([float(v) for v in fresh_seconds])
    band = _band(med, mad, mads, min_rel)
    out["fresh"].update(median=med, mad=mad, band=band)
    out["delta_pct"] = (100.0 * (med - stored_seconds) / stored_seconds
                        if stored_seconds else math.inf)
    if abs(med - stored_seconds) <= band:
        out["verdict"] = "within-noise"
    elif med < stored_seconds:
        out["verdict"] = "improved"
    else:
        out["verdict"] = "regressed"
    return out


def format_compare(results: list[dict]) -> str:
    """Human-readable compare report: one verdict line per record, with
    the stage localization indented under a regression."""
    if not results:
        return "(no records to compare)"
    lines: list[str] = []
    for res in results:
        head = (f"{res['verdict']:<12}  {res['metric']}  "
                f"[{res['device_kind']}"
                + (f"; {res['config']}" if res["config"] else "") + "]")
        b = res.get("baseline", {})
        if "median" in b:
            head += (f"  value={res['value']:g} vs median={b['median']:g}"
                     f" (n={b['n']}, band=+/-{b['band']:g})"
                     f" {res.get('delta_pct', 0.0):+.1f}%")
        else:
            head += (f"  value={res['value']:g}"
                     f" (baseline n={b.get('n', 0)} < min samples)")
        if res.get("fallback"):
            head += "  [fallback run; excluded from future baselines]"
        lines.append(head)
        for row in res.get("localization", []):
            tag = "REGRESSED" if row["regressed"] else "within noise"
            lines.append(
                f"    {row['stage']:<20} {row['delta_pct']:+.1f}%  "
                f"({row['value']:.6f}s vs {row['baseline_median']:.6f}s; "
                f"{tag})")
        for row in res.get("aux", []):
            b = row.get("baseline", {})
            label = f"{row.get('block', 'cost')}.{row['metric']}"
            if "median" in b:
                lines.append(
                    f"    {label:<22} "
                    f"{row.get('delta_pct', 0.0):+.1f}%  "
                    f"({row['value']:g} vs {b['median']:g}; "
                    f"{row['verdict']})")
            else:
                lines.append(
                    f"    {label:<22} value={row['value']:g} "
                    f"(baseline n={b.get('n', 0)} < min samples)")
    return "\n".join(lines)


def summarize_history(records: list[dict]) -> list[dict]:
    """Per-group summary rows (newest-last ordering preserved within a
    group): n, eligible n, last value, median of eligible values."""
    groups: dict[tuple, list[dict]] = {}
    for rec in records:
        groups.setdefault(group_key(rec), []).append(rec)
    rows = []
    for (metric, sig, kind), recs in sorted(groups.items()):
        eligible = [float(r["value"]) for r in recs if _baseline_eligible(r)]
        med, _ = robust_stats(eligible)
        rows.append({
            "metric": metric,
            "config": sig,
            "device_kind": kind,
            "n": len(recs),
            "eligible": len(eligible),
            "last_value": float(recs[-1].get("value", 0.0)),
            "last_recorded_at": recs[-1].get("recorded_at"),
            "median": None if math.isnan(med) else med,
            "unit": recs[-1].get("unit"),
        })
    return rows
