"""Trace report CLI — merge per-process logs, print the stage summary.

The reference writes one trace log per MPI rank and leaves correlation
to the reader (``heffte_trace.h:98-118``); heFFTe's ``finalize_tracing``
at least prints a per-event aggregate on shutdown. This module is both,
offline::

    python -m distributedfft_tpu.report dfft_trace_0.log dfft_trace_1.log
    python -m distributedfft_tpu.report 'dfft_trace_*' -o merged.json

It accepts any mix of the text log format and the Chrome-trace JSON
format (``DFFT_TRACE_FORMAT=chrome``), merges every process's events
onto one timeline, prints the per-stage aggregate table
(count/total/mean/min/max — the heFFTe finalize summary), and with
``-o`` writes a merged Chrome-trace JSON to load in ui.perfetto.dev.

Timeline caveat: text logs store per-process *relative* start times
(each process's first event is t=0), so merging text logs aligns the
processes at their first event; chrome logs carry a shared wall-clock
axis and merge exactly.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import sys

__all__ = [
    "load_events",
    "merge_files",
    "aggregate",
    "format_table",
    "write_chrome",
    "main",
]


def _parse_text_log(text: str, default_pid: int = 0) -> list[dict]:
    """Parse the heFFTe-style per-rank text log: a ``process I of N``
    banner, then ``start  duration  name`` rows (seconds, relative to the
    process's first event)."""
    events: list[dict] = []
    pid = default_pid
    for line in text.splitlines():
        if line.startswith("process "):
            parts = line.split()
            if len(parts) >= 2 and parts[1].isdigit():
                pid = int(parts[1])
            continue
        parts = line.split(None, 2)
        if len(parts) < 3:
            continue
        try:
            start, dur = float(parts[0]), float(parts[1])
        except ValueError:
            continue
        events.append({"name": parts[2].strip(), "pid": pid,
                       "ts": start * 1e6, "dur": dur * 1e6})
    return events


def _parse_chrome(obj) -> list[dict]:
    """Flatten a Chrome-trace document to complete events. ``B``/``E``
    pairs are matched per (pid, tid, name) LIFO — the nesting discipline
    the writer guarantees; ``X`` events pass through."""
    raw = obj.get("traceEvents", []) if isinstance(obj, dict) else obj
    events: list[dict] = []
    open_stacks: dict[tuple, list[float]] = {}
    for e in sorted(raw, key=lambda ev: ev.get("ts", 0.0)):
        ph = e.get("ph")
        pid, tid = e.get("pid", 0), e.get("tid", 0)
        name = e.get("name", "")
        if ph == "X":
            events.append({"name": name, "pid": pid,
                           "ts": float(e.get("ts", 0.0)),
                           "dur": float(e.get("dur", 0.0))})
        elif ph == "B":
            open_stacks.setdefault((pid, tid, name), []).append(
                float(e.get("ts", 0.0)))
        elif ph == "E":
            stack = open_stacks.get((pid, tid, name))
            if stack:
                ts = stack.pop()
                events.append({"name": name, "pid": pid, "ts": ts,
                               "dur": float(e.get("ts", 0.0)) - ts})
    return events


def load_events(path: str) -> list[dict]:
    """Events of one per-process trace file (either format), each as
    ``{"name", "pid", "ts", "dur"}`` with ts/dur in microseconds."""
    with open(path) as f:
        text = f.read()
    head = text.lstrip()[:1]
    if head in ("{", "["):
        return _parse_chrome(json.loads(text))
    return _parse_text_log(text)


def merge_files(paths: list[str]) -> list[dict]:
    """One timeline from many per-process files, sorted by start time."""
    events: list[dict] = []
    for path in paths:
        events.extend(load_events(path))
    events.sort(key=lambda e: (e["ts"], e["pid"]))
    return events


def aggregate(events: list[dict]) -> dict[str, dict]:
    """Per-stage statistics in seconds: name -> {count, total, mean,
    min, max} (the heFFTe ``finalize_tracing`` summary)."""
    agg: dict[str, dict] = {}
    for e in events:
        dur_s = e["dur"] / 1e6
        a = agg.get(e["name"])
        if a is None:
            agg[e["name"]] = {"count": 1, "total": dur_s,
                              "min": dur_s, "max": dur_s}
        else:
            a["count"] += 1
            a["total"] += dur_s
            a["min"] = min(a["min"], dur_s)
            a["max"] = max(a["max"], dur_s)
    for a in agg.values():
        a["mean"] = a["total"] / a["count"]
    return agg


def format_table(agg: dict[str, dict], sort: str = "total") -> str:
    """Fixed-width aggregate table, widest column first."""
    if not agg:
        return "(no events)"
    if sort == "name":
        rows = sorted(agg.items())
    else:
        rows = sorted(agg.items(), key=lambda kv: -kv[1][sort])
    width = max(len("stage"), max(len(n) for n in agg))
    lines = [
        f"{'stage':<{width}}  {'count':>7}  {'total':>12}  {'mean':>12}  "
        f"{'min':>12}  {'max':>12}"
    ]
    for name, a in rows:
        lines.append(
            f"{name:<{width}}  {a['count']:>7d}  {a['total']:>12.6f}  "
            f"{a['mean']:>12.6f}  {a['min']:>12.6f}  {a['max']:>12.6f}"
        )
    return "\n".join(lines)


def write_chrome(events: list[dict], path: str) -> None:
    """Write a merged timeline as Chrome-trace JSON (``X`` complete
    events, one ``pid`` lane per source process)."""
    with open(path, "w") as f:
        json.dump(
            {
                "displayTimeUnit": "ms",
                "traceEvents": [
                    {"name": e["name"], "cat": "dfft", "ph": "X",
                     "pid": e["pid"], "tid": 0, "ts": e["ts"],
                     "dur": e["dur"]}
                    for e in events
                ],
            },
            f,
        )


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="+",
                   help="per-process trace files (.log or .json); shell "
                        "globs that reached us unexpanded are expanded")
    p.add_argument("-o", "--out", default=None, metavar="MERGED.json",
                   help="write the merged Chrome-trace JSON here "
                        "(open in ui.perfetto.dev)")
    p.add_argument("--sort", default="total",
                   choices=("total", "count", "mean", "max", "name"),
                   help="aggregate table sort key (default: total)")
    args = p.parse_args(argv)

    paths: list[str] = []
    for pat in args.paths:
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else [pat])
    try:
        events = merge_files(paths)
    except OSError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    pids = sorted({e["pid"] for e in events})
    print(f"{len(events)} events from {len(paths)} file(s), "
          f"{len(pids)} process(es)")
    print(format_table(aggregate(events), sort=args.sort))
    if args.out:
        write_chrome(events, args.out)
        print(f"merged timeline written to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
