"""Benchmark report CLI — trace merging and regression tracking.

Subcommands (``merge`` is the default for backward compatibility: an
argv whose first token is not a subcommand name is treated as ``merge``
arguments)::

    python -m distributedfft_tpu.report merge dfft_trace_*.log -o out.json
    python -m distributedfft_tpu.report record BENCH_r*.json
    python -m distributedfft_tpu.report history [--config SUBSTR]
    python -m distributedfft_tpu.report compare --gate
    python -m distributedfft_tpu.report wisdom --gate
    python -m distributedfft_tpu.report explain [--json]
    python -m distributedfft_tpu.report explain --plan 256,256,256 -n 8
    python -m distributedfft_tpu.report explain --trend [--config SUBSTR]
    python -m distributedfft_tpu.report calibrate
    python -m distributedfft_tpu.report qos [--ledger FILE] [--gate]
    python -m distributedfft_tpu.report health [--series FILE] [--gate]
    python -m distributedfft_tpu.report live --series FILE [--prom]

**merge** — the trace tool. The reference writes one trace log per MPI
rank and leaves correlation to the reader (``heffte_trace.h:98-118``);
``merge`` accepts any mix of the text log format and the Chrome-trace
JSON format (``DFFT_TRACE_FORMAT=chrome``), merges every process's
events onto one timeline, prints the per-stage aggregate table
(count/total/mean/min/max — the heFFTe finalize summary), and with
``-o`` writes a merged Chrome-trace JSON to load in ui.perfetto.dev.
Malformed events (missing ts/dur, the truncated tail of a
watchdog-killed worker's log) are skipped and counted on stderr, never
fatal. Timeline caveat: text logs store per-process *relative* start
times, so merging text logs aligns processes at their first event;
chrome logs carry a shared wall-clock axis and merge exactly.

**wisdom** — inspect the tuner's persistent wisdom store
(``DFFT_WISDOM``; see :mod:`.tuner` and docs/TUNING.md): one row per
stored tournament winner. ``--gate`` cross-checks each stored winner
against *fresh* history records of the same winner tuple (the
``tuned=...`` baseline group bench.py/speed3d stamp) with the regress
median+MAD noise model, and exits 1 when a stored winner now runs
slower than its recorded tournament time beyond noise — stale wisdom
that should be re-measured.

**explain** — the plan explain & attribution view (:mod:`.explain`;
docs/OBSERVABILITY.md "Explain & attribution"): the per-stage
model/compiled/measured join with MFU, ICI utilization, and divergence
flags. Reads the explain block of a history record (newest by default,
``--record FILE`` for an artifact, a bare ``--json`` dump of a prior
explain also parses), or builds and explains a LIVE plan with
``--plan NX,NY,NZ`` (imports jax; every plan knob has a flag —
``--device-timing`` attributes stages from the jax.profiler device
timeline, ``--allgather`` merges per-host stage medians).
``--trend`` instead tabulates the model-vs-measured trajectory across
every history record carrying an explain block (``--config SUBSTR``
narrows to one baseline group) — the calibration-quality view.

**calibrate** — measure this machine's hardware profile (HBM/ICI/matmul
microbenchmarks; :mod:`.calibrate`) and persist it next to the wisdom
store so ``dfft.explain`` divergence-gates against measured constants
(``hw.source == "calibrated"``) and the tuner's pruning model applies
persisted per-transport corrections (docs/OBSERVABILITY.md
"Calibration").

**record / history / compare** — the regression-tracking loop over the
append-only run-record store (``benchmarks/results/history.jsonl``; see
:mod:`.regress` and docs/OBSERVABILITY.md). ``record`` normalizes and
appends benchmark artifacts (bench.py lines, ``BENCH_r*.json`` driver
wrappers, prior history files); ``history`` summarizes the store per
(metric, config, device_kind) group; ``compare`` runs the noise-aware
verdict of the newest record(s) against their rolling baselines, with
per-stage t0..t3 localization on a regression. ``compare --gate`` exits
1 on a confirmed regression (0 = clean, 2 = usage/IO error) so CI and
the round driver can gate mechanically.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import re
import sys
import time

from . import regress

__all__ = [
    "load_events",
    "merge_files",
    "aggregate",
    "format_table",
    "write_chrome",
    "main",
]


def _clean_events(raw: list[dict]) -> tuple[list[dict], int]:
    """Keep events with a name and numeric ts/dur; count the rest."""
    events: list[dict] = []
    dropped = 0
    for e in raw:
        try:
            events.append({
                "name": str(e["name"]),
                "pid": int(e.get("pid", 0)),
                "ts": float(e["ts"]),
                "dur": float(e["dur"]),
            })
        except (KeyError, TypeError, ValueError):
            dropped += 1
    return events, dropped


def _parse_text_log(text: str, default_pid: int = 0) -> tuple[list[dict], int]:
    """Parse the heFFTe-style per-rank text log: a ``process I of N``
    banner, then ``start  duration  name`` rows (seconds, relative to the
    process's first event). Rows that fail to parse — the truncated tail
    a watchdog-killed worker leaves behind — are counted, not fatal."""
    events: list[dict] = []
    dropped = 0
    pid = default_pid
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("process "):
            parts = line.split()
            if len(parts) >= 2 and parts[1].isdigit():
                pid = int(parts[1])
            continue
        if line.startswith("dropped_events "):
            # The writer's ring-eviction banner (DFFT_TRACE_MAX_EVENTS)
            # — metadata, not a malformed row; ring_dropped() reads it.
            continue
        parts = line.split(None, 2)
        if len(parts) < 3:
            dropped += 1  # truncated row: fields missing
            continue
        try:
            start, dur = float(parts[0]), float(parts[1])
        except ValueError:
            dropped += 1
            continue
        events.append({"name": parts[2].strip(), "pid": pid,
                       "ts": start * 1e6, "dur": dur * 1e6})
    return events, dropped


def _parse_chrome(obj) -> tuple[list[dict], int]:
    """Flatten a Chrome-trace document to complete events. ``B``/``E``
    pairs are matched per (pid, tid, name) LIFO — the nesting discipline
    the writer guarantees; ``X`` events pass through. Events without a
    usable ts (or non-dict entries) are counted as dropped; an unpaired
    ``B`` at the tail of a truncated log counts too."""
    raw = obj.get("traceEvents", []) if isinstance(obj, dict) else obj
    if not isinstance(raw, list):
        return [], 1
    events: list[dict] = []
    dropped = 0
    open_stacks: dict[tuple, list[float]] = {}
    entries = [e for e in raw if isinstance(e, dict)]
    dropped += len(raw) - len(entries)

    def ts_key(ev):
        try:
            return float(ev.get("ts") or 0.0)
        except (TypeError, ValueError):
            return 0.0  # dropped below; any position sorts consistently

    for e in sorted(entries, key=ts_key):
        ph = e.get("ph")
        pid, tid = e.get("pid", 0), e.get("tid", 0)
        name = e.get("name", "")
        try:
            ts = float(e["ts"])
        except (KeyError, TypeError, ValueError):
            dropped += 1
            continue
        if ph == "X":
            try:
                dur = float(e["dur"])
            except (KeyError, TypeError, ValueError):
                dropped += 1
                continue
            events.append({"name": name, "pid": pid, "ts": ts, "dur": dur})
        elif ph == "B":
            open_stacks.setdefault((pid, tid, name), []).append(ts)
        elif ph == "E":
            stack = open_stacks.get((pid, tid, name))
            if stack:
                start = stack.pop()
                events.append({"name": name, "pid": pid, "ts": start,
                               "dur": ts - start})
            else:
                dropped += 1  # E without a matching B
    dropped += sum(len(s) for s in open_stacks.values())  # unclosed B's
    return events, dropped


def _parse_chrome_text(text: str) -> tuple[list[dict], int]:
    """Chrome-trace JSON, lenient: a complete document parses exactly;
    a truncated one (killed mid-write) recovers every complete event
    object before the cut and counts the tail as one dropped event."""
    try:
        return _parse_chrome(json.loads(text))
    except json.JSONDecodeError:
        pass
    # Find the traceEvents array (or a bare top-level array) and decode
    # object by object until the truncation point.
    idx = text.find('"traceEvents"')
    start = text.find("[", idx if idx >= 0 else 0)
    if start < 0:
        return [], 1
    dec = json.JSONDecoder()
    pos = start + 1
    raw: list[dict] = []
    n = len(text)
    while True:
        while pos < n and text[pos] in " \t\r\n,":
            pos += 1
        if pos >= n or text[pos] == "]":
            break
        try:
            obj, end = dec.raw_decode(text, pos)
        except json.JSONDecodeError:
            break
        raw.append(obj)
        pos = end
    events, dropped = _parse_chrome(raw)
    return events, dropped + 1  # +1 for the truncated tail itself


def _load_events(path: str) -> tuple[list[dict], int]:
    with open(path) as f:
        text = f.read()
    head = text.lstrip()[:1]
    if head in ("{", "["):
        events, dropped = _parse_chrome_text(text)
    else:
        events, dropped = _parse_text_log(text)
    events, bad = _clean_events(events)
    return events, dropped + bad


def load_events(path: str) -> list[dict]:
    """Events of one per-process trace file (either format), each as
    ``{"name", "pid", "ts", "dur"}`` with ts/dur in microseconds.
    Malformed events are skipped with a count on stderr."""
    events, dropped = _load_events(path)
    if dropped:
        print(f"report: {path}: skipped {dropped} malformed event(s)",
              file=sys.stderr)
    return events


def ring_dropped(path: str) -> int:
    """Events the writer's in-memory ring evicted before this file was
    written (``DFFT_TRACE_MAX_EVENTS``): the ``dropped_events N`` text
    banner, or the chrome document's ``metadata.dropped_events``. 0 on
    any parse/IO trouble — the count is advisory."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return 0
    head = text.lstrip()[:1]
    if head in ("{", "["):
        m = re.search(r'"dropped_events"\s*:\s*(\d+)', text)
        return int(m.group(1)) if m else 0
    for line in text.splitlines():
        if line.startswith("dropped_events "):
            parts = line.split()
            if len(parts) >= 2 and parts[1].isdigit():
                return int(parts[1])
    return 0


def merge_files(paths: list[str], *, align: str = "none",
                offsets_s: dict[int, float] | None = None) -> list[dict]:
    """One timeline from many per-process files, sorted by start time.
    Malformed events across all files are skipped with one total count
    on stderr (partial logs from killed workers are a normal input).

    ``align`` handles files whose clocks do not share an origin:
    ``"none"`` (default) keeps every timestamp as written;
    ``"start"`` shifts each FILE so its earliest event starts at t=0 —
    the right mode for per-process text logs, whose stamps are relative
    to each process's own trace start. ``offsets_s`` applies measured
    wall-clock skew on top: per trace-lane pid (the jax process index
    the recorder stamps), that many seconds are SUBTRACTED from the
    lane's events — pair it with the fleet aggregator's
    :func:`..fleet.estimate_offsets` (``report merge --monitor-dir``)
    for one clock-aligned Perfetto timeline across processes."""
    if align not in ("none", "start"):
        raise ValueError(f"align must be 'none' or 'start', "
                         f"got {align!r}")
    events: list[dict] = []
    dropped = 0
    for path in paths:
        evs, d = _load_events(path)
        if align == "start" and evs:
            t0 = min(e["ts"] for e in evs)
            for e in evs:
                e["ts"] -= t0
        events.extend(evs)
        dropped += d
    if offsets_s:
        for e in events:
            off = offsets_s.get(e["pid"])
            if off:
                e["ts"] -= off * 1e6
    if dropped:
        print(f"report: skipped {dropped} malformed event(s) across "
              f"{len(paths)} file(s)", file=sys.stderr)
    events.sort(key=lambda e: (e["ts"], e["pid"]))
    return events


def aggregate(events: list[dict]) -> dict[str, dict]:
    """Per-stage statistics in seconds: name -> {count, total, mean,
    min, max} (the heFFTe ``finalize_tracing`` summary)."""
    agg: dict[str, dict] = {}
    for e in events:
        dur_s = e["dur"] / 1e6
        a = agg.get(e["name"])
        if a is None:
            agg[e["name"]] = {"count": 1, "total": dur_s,
                              "min": dur_s, "max": dur_s}
        else:
            a["count"] += 1
            a["total"] += dur_s
            a["min"] = min(a["min"], dur_s)
            a["max"] = max(a["max"], dur_s)
    for a in agg.values():
        a["mean"] = a["total"] / a["count"]
    return agg


def format_table(agg: dict[str, dict], sort: str = "total") -> str:
    """Fixed-width aggregate table, widest column first. Ties on the
    sort column break by stage name, so the ordering is stable across
    runs and dict insertion orders."""
    if not agg:
        return "(no events)"
    if sort == "name":
        rows = sorted(agg.items())
    else:
        rows = sorted(agg.items(), key=lambda kv: (-kv[1][sort], kv[0]))
    width = max(len("stage"), max(len(n) for n in agg))
    lines = [
        f"{'stage':<{width}}  {'count':>7}  {'total':>12}  {'mean':>12}  "
        f"{'min':>12}  {'max':>12}"
    ]
    for name, a in rows:
        lines.append(
            f"{name:<{width}}  {a['count']:>7d}  {a['total']:>12.6f}  "
            f"{a['mean']:>12.6f}  {a['min']:>12.6f}  {a['max']:>12.6f}"
        )
    return "\n".join(lines)


def write_chrome(events: list[dict], path: str) -> None:
    """Write a merged timeline as Chrome-trace JSON (``X`` complete
    events, one ``pid`` lane per source process)."""
    with open(path, "w") as f:
        json.dump(
            {
                "displayTimeUnit": "ms",
                "traceEvents": [
                    {"name": e["name"], "cat": "dfft", "ph": "X",
                     "pid": e["pid"], "tid": 0, "ts": e["ts"],
                     "dur": e["dur"]}
                    for e in events
                ],
            },
            f,
        )


# ----------------------------------------------------------- merge CLI

def _main_merge(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report merge",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="+",
                   help="per-process trace files (.log or .json); shell "
                        "globs that reached us unexpanded are expanded")
    p.add_argument("-o", "--out", default=None, metavar="MERGED.json",
                   help="write the merged Chrome-trace JSON here "
                        "(open in ui.perfetto.dev)")
    p.add_argument("--sort", default="total",
                   choices=("total", "count", "mean", "min", "max", "name"),
                   help="aggregate table sort key (default: total)")
    p.add_argument("--align", default="none", choices=("none", "start"),
                   help="'start' re-origins each FILE's clock at its "
                        "first event — per-process text logs stamp "
                        "relative times, so merging without alignment "
                        "interleaves incomparable clocks")
    p.add_argument("--monitor-dir", default=None, metavar="DIR",
                   help="fleet monitor-series directory "
                        "(DFFT_MONITOR_DIR): estimate each process's "
                        "wall-clock skew from its monitor stream and "
                        "subtract it from its trace lane (matched on "
                        "jax process index)")
    args = p.parse_args(argv)

    paths: list[str] = []
    for pat in args.paths:
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else [pat])
    offsets_s = None
    if args.monitor_dir:
        from .fleet import estimate_offsets, load_fleet

        streams = load_fleet(args.monitor_dir)
        stream_offsets = estimate_offsets(streams)
        offsets_s = {}
        for sid, samples in streams.items():
            pi = samples[-1].get("process_index")
            off = stream_offsets.get(sid, 0.0)
            if isinstance(pi, int) and off:
                offsets_s[pi] = off
        if not streams:
            print(f"report: {args.monitor_dir}: no monitor series — "
                  f"merging without skew correction", file=sys.stderr)
    try:
        events = merge_files(paths, align=args.align,
                             offsets_s=offsets_s)
    except OSError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    pids = sorted({e["pid"] for e in events})
    print(f"{len(events)} events from {len(paths)} file(s), "
          f"{len(pids)} process(es)")
    ring = sum(ring_dropped(p) for p in paths)
    if ring:
        print(f"{ring} event(s) evicted by the in-memory ring before "
              f"writing (DFFT_TRACE_MAX_EVENTS) — the aggregate below "
              f"undercounts by that many")
    print(format_table(aggregate(events), sort=args.sort))
    if args.out:
        write_chrome(events, args.out)
        print(f"merged timeline written to {args.out}")
    return 0


# ------------------------------------------------------ regression CLI

def _history_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--history", default=None, metavar="PATH",
                   help="run-record JSONL store (default: "
                        "DFFT_BENCH_HISTORY or "
                        "benchmarks/results/history.jsonl)")


def _resolve_history(args) -> str | None:
    return args.history or regress.default_history_path()


def _main_record(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report record",
        description="Normalize benchmark artifacts into run records and "
                    "append them to the history store. Accepts bench.py "
                    "result-line JSON(L), the round driver's BENCH_r*.json "
                    "wrappers, and prior run-record JSONL; '-' reads one "
                    "artifact from stdin.")
    p.add_argument("paths", nargs="+",
                   help="artifact files (globs expanded) or '-' for stdin")
    _history_arg(p)
    p.add_argument("--source", default=None,
                   help="source label override (default: the file name)")
    p.add_argument("--commit", default=None,
                   help="commit sha to stamp (default: git rev-parse, "
                        "best-effort)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the normalized records as JSONL on stdout "
                        "instead of appending to the store")
    args = p.parse_args(argv)

    history = _resolve_history(args)
    if history is None and not args.dry_run:
        print("report record: history store disabled "
              "(DFFT_BENCH_HISTORY is empty)", file=sys.stderr)
        return 2
    commit = args.commit or regress.git_commit()

    paths: list[str] = []
    for pat in args.paths:
        if pat == "-":
            paths.append(pat)
            continue
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else [pat])

    records: list[dict] = []
    skipped = 0
    for path in paths:
        try:
            if path == "-":
                text = sys.stdin.read()
            else:
                with open(path) as f:
                    text = f.read()
        except OSError as e:
            print(f"report record: {e}", file=sys.stderr)
            return 2
        recs, skip = regress.records_from_artifact(
            text, source=args.source or (path if path != "-" else "stdin"),
            commit=commit)
        records.extend(recs)
        skipped += skip
    if args.dry_run:
        for rec in records:
            print(json.dumps(rec, sort_keys=True))
    else:
        regress.append_records(records, history)
    dest = "stdout (dry run)" if args.dry_run else history
    print(f"recorded {len(records)} run record(s) from {len(paths)} "
          f"artifact(s) to {dest}"
          + (f"; {skipped} line(s) held no result" if skipped else ""),
          file=sys.stderr)
    return 0


def _main_history(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report history",
        description="Summarize the run-record store per "
                    "(metric, config, device_kind) baseline group.")
    _history_arg(p)
    p.add_argument("--metric", default=None,
                   help="only groups whose metric contains this substring")
    p.add_argument("--config", default=None,
                   help="only groups whose config signature contains this "
                        "substring (e.g. 'tuned=' or "
                        "'overlap=4,tuned=slab/alltoall/xla/ov4') — lists "
                        "one (shape, decomp, transport, overlap, tuned) "
                        "group without running a compare")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON instead of the table")
    args = p.parse_args(argv)

    history = _resolve_history(args)
    records, dropped = regress.load_history(history) if history else ([], 0)
    if dropped:
        print(f"report history: skipped {dropped} malformed line(s) in "
              f"{history}", file=sys.stderr)
    rows = regress.summarize_history(records)
    if args.metric:
        rows = [r for r in rows if args.metric in r["metric"]]
    if args.config:
        rows = [r for r in rows if args.config in r["config"]]
    if args.json:
        print(json.dumps(rows, sort_keys=True))
        return 0
    if not rows:
        print("(empty history)")
        return 0
    wm = max(len("metric"), max(len(r["metric"]) for r in rows))
    wk = max(len("device_kind"), max(len(r["device_kind"]) for r in rows))
    wc = max(len("config"), max(len(r["config"]) for r in rows))
    print(f"{'metric':<{wm}}  {'device_kind':<{wk}}  {'config':<{wc}}  "
          f"{'n':>4}  {'ok':>4}  {'median':>10}  {'last':>10}")
    for r in rows:
        med = "-" if r["median"] is None else f"{r['median']:.1f}"
        print(f"{r['metric']:<{wm}}  {r['device_kind']:<{wk}}  "
              f"{r['config']:<{wc}}  {r['n']:>4d}  {r['eligible']:>4d}  "
              f"{med:>10}  {r['last_value']:>10.1f}")
    return 0


def _main_compare(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report compare",
        description="Noise-aware verdict of the newest run record(s) "
                    "against their rolling-window baselines (median + MAD "
                    "bounds; per-stage t0..t3 localization on a "
                    "regression). Exit codes: 0 clean, 1 confirmed "
                    "regression (with --gate), 2 usage/IO error.")
    _history_arg(p)
    p.add_argument("--record", default=None, metavar="FILE",
                   help="compare this artifact (bench line or run record) "
                        "instead of the newest history record")
    p.add_argument("--last", type=int, default=1, metavar="N",
                   help="compare the N newest history records "
                        "(default: 1)")
    p.add_argument("--window", type=int, default=regress.DEFAULT_WINDOW,
                   help="rolling baseline size per group (default: "
                        f"{regress.DEFAULT_WINDOW})")
    p.add_argument("--mads", type=float, default=regress.DEFAULT_MADS,
                   help="noise band half-width in scaled MADs (default: "
                        f"{regress.DEFAULT_MADS})")
    p.add_argument("--min-rel", type=float, default=regress.DEFAULT_MIN_REL,
                   help="noise band floor as a fraction of the median "
                        f"(default: {regress.DEFAULT_MIN_REL})")
    p.add_argument("--min-samples", type=int,
                   default=regress.DEFAULT_MIN_SAMPLES,
                   help="baseline records required for a verdict "
                        f"(default: {regress.DEFAULT_MIN_SAMPLES})")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 when any compared record regressed")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON instead of the report")
    args = p.parse_args(argv)

    history = _resolve_history(args)
    records, dropped = regress.load_history(history) if history else ([], 0)
    if dropped:
        print(f"report compare: skipped {dropped} malformed line(s) in "
              f"{history}", file=sys.stderr)

    if args.record:
        try:
            with open(args.record) as f:
                text = f.read()
        except OSError as e:
            print(f"report compare: {e}", file=sys.stderr)
            return 2
        subjects, _ = regress.records_from_artifact(
            text, source=args.record)
        if not subjects:
            print(f"report compare: no run record in {args.record}",
                  file=sys.stderr)
            return 2
    else:
        if not records:
            print(f"report compare: empty history "
                  f"({history or 'store disabled'})", file=sys.stderr)
            return 2
        subjects = records[-max(1, args.last):]

    kw = dict(window=args.window, mads=args.mads, min_rel=args.min_rel,
              min_samples=args.min_samples)
    results = [regress.compare_record(rec, records, **kw)
               for rec in subjects]
    if args.json:
        print(json.dumps(results, sort_keys=True))
    else:
        print(regress.format_compare(results))
    regressed = [m for r in results for m in regress.regressed_metrics(r)]
    if regressed and not args.json:
        print(f"{len(regressed)} confirmed regression(s): "
              f"{', '.join(regressed)}", file=sys.stderr)
    return 1 if (args.gate and regressed) else 0


# --------------------------------------------------------- explain CLI

def _explain_blocks_from_text(text: str) -> list[dict]:
    """Every explain block found in one artifact: a bare explain JSON
    document (a prior ``explain --json`` dump), a run record carrying
    an ``explain`` field, or JSONL of either — oldest first."""
    from .explain import explain_from_record

    stripped = text.strip()
    if not stripped:
        return []
    out: list[dict] = []
    try:
        doc = json.loads(stripped)
    except json.JSONDecodeError:
        doc = None
    entries = (doc if isinstance(doc, list)
               else [doc] if isinstance(doc, dict) else None)
    if entries is None:
        entries = []
        for line in stripped.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    for obj in entries:
        blk = explain_from_record(obj)
        if blk is not None:
            out.append(blk)
    return out


def _explain_live(args) -> dict | int:
    """Build a plan from the CLI knobs and explain it (imports jax)."""
    import distributedfft_tpu as dfft

    try:
        shape = tuple(int(s) for s in args.plan.replace("x", ",").split(","))
        if len(shape) != 3:
            raise ValueError
    except ValueError:
        print(f"report explain: --plan wants NX,NY,NZ, got {args.plan!r}",
              file=sys.stderr)
        return 2
    import jax

    ndev = args.ndev if args.ndev is not None else len(jax.devices())
    direction = dfft.FORWARD if args.direction == "forward" else dfft.BACKWARD
    plan_fn = (dfft.plan_dft_r2c_3d if args.kind in ("r2c", "c2r")
               else dfft.plan_dft_c2c_3d)
    kw: dict = dict(direction=direction, executor=args.executor,
                    algorithm=args.algorithm,
                    decomposition=args.decomposition)
    if args.kind == "c2r":
        kw["direction"] = dfft.BACKWARD
    if args.overlap is not None:
        kw["overlap_chunks"] = args.overlap
    try:
        plan = plan_fn(shape, ndev if ndev > 1 else None, **kw)
        return dfft.explain(plan, iters=args.iters,
                            measure=not args.no_measure,
                            device_timing=args.device_timing or None,
                            allgather=args.allgather)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"report explain: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


def _explain_trend(args) -> int:
    """``report explain --trend``: the model-quality trajectory. One row
    per history record carrying an explain block (oldest first), with
    per-stage measured seconds and the measured/model ratios — is the
    model's t2 prediction converging on reality (calibration working)
    or drifting (stale profile, changed fabric)?"""
    from .explain import explain_from_record

    keys = ("t0", "t1", "t2", "t3")
    history = _resolve_history(args)
    records, dropped = (regress.load_history(history) if history
                        else ([], 0))
    if args.record:
        try:
            with open(args.record) as f:
                extra, _ = regress.records_from_artifact(
                    f.read(), source=args.record)
        except OSError as e:
            print(f"report explain: {e}", file=sys.stderr)
            return 2
        records = records + extra
    if dropped:
        print(f"report explain: skipped {dropped} malformed line(s) in "
              f"{history}", file=sys.stderr)
    rows: list[dict] = []
    for rec in records:
        blk = explain_from_record(rec)
        if blk is None:
            continue
        cfg = regress.config_signature(rec) if rec is not blk else ""
        if args.config and args.config not in cfg:
            continue
        stages = blk.get("stages") or {}
        totals = blk.get("totals") or {}
        row: dict = {
            "recorded_at": rec.get("recorded_at")
            or blk.get("generated_at"),
            "config": cfg,
            "hw_source": (blk.get("hw") or {}).get("source"),
            "model_seconds": totals.get("model_seconds"),
            "measured_seconds": totals.get("measured_stage_seconds"),
            "diverged": (blk.get("divergence") or {}).get("stages") or [],
        }
        for k in keys:
            st = stages.get(k) or {}
            row[k] = (st.get("measured") or {}).get("seconds")
            if k == "t2":
                m2 = (st.get("model") or {}).get("seconds")
                row["t2_ratio"] = (row[k] / m2 if row[k] and m2 else None)
        ms, mod = row["measured_seconds"], row["model_seconds"]
        row["ratio"] = (ms / mod) if ms and mod else None
        rows.append(row)
    if not rows:
        print(f"report explain: no explain block matches "
              f"({history or 'store disabled'}"
              + (f", config~{args.config!r}" if args.config else "") + ")",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(rows, sort_keys=True))
        return 0

    def s(v):
        return "-" if v is None else f"{v:.6f}"

    def r(v):
        return "-" if v is None else f"{v:.2f}x"

    print(f"{'recorded_at':<19}  {'t0(s)':>10} {'t1(s)':>10} "
          f"{'t2(s)':>10} {'t3(s)':>10}  {'meas/model':>10} "
          f"{'t2 ratio':>9}  {'hw':<10}  diverged")
    for row in rows:
        print(f"{str(row['recorded_at'] or '-'):<19}  "
              f"{s(row['t0']):>10} {s(row['t1']):>10} {s(row['t2']):>10} "
              f"{s(row['t3']):>10}  {r(row['ratio']):>10} "
              f"{r(row['t2_ratio']):>9}  "
              f"{str(row['hw_source'] or '-'):<10}  "
              f"{','.join(row['diverged']) or '-'}")
    return 0


def _main_explain(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report explain",
        description="Plan explain & attribution: the per-stage t0..t3 "
                    "model/compiled/measured join with MFU, ICI "
                    "utilization, and model-vs-measured divergence "
                    "flags. Default: render the newest history record "
                    "that carries an explain block; --record FILE reads "
                    "an artifact (run record or a prior --json dump); "
                    "--plan NX,NY,NZ builds and explains a live plan "
                    "(imports jax). Exit codes: 0 ok, 2 usage/IO error "
                    "or no explain block found.")
    _history_arg(p)
    p.add_argument("--record", default=None, metavar="FILE",
                   help="read the explain block from this artifact "
                        "instead of the history store")
    p.add_argument("--plan", default=None, metavar="NX,NY,NZ",
                   help="build and explain a live plan of this shape")
    p.add_argument("--ndev", "-n", type=int, default=None,
                   help="device count for --plan (default: all)")
    p.add_argument("--kind", default="c2c", choices=("c2c", "r2c", "c2r"),
                   help="plan family for --plan (default c2c)")
    p.add_argument("--direction", default="forward",
                   choices=("forward", "backward"))
    p.add_argument("--executor", default="xla")
    p.add_argument("--algorithm", default="alltoall",
                   choices=("alltoall", "alltoallv", "ppermute"))
    p.add_argument("--decomposition", default=None,
                   help="auto|single|slab|pencil for --plan")
    p.add_argument("--overlap", default=None, metavar="K",
                   help="overlap_chunks for --plan (int or 'auto')")
    p.add_argument("--iters", type=int, default=3,
                   help="measured warm passes for --plan (default 3)")
    p.add_argument("--no-measure", action="store_true",
                   help="model + compiled views only; skip every "
                        "execution (for --plan)")
    p.add_argument("--device-timing", action="store_true",
                   help="attribute stage times from the jax.profiler "
                        "device timeline for --plan (falls back to host "
                        "brackets where no device lanes exist)")
    p.add_argument("--allgather", action="store_true",
                   help="merge per-process stage medians into "
                        "min/median/max-across-hosts rows for --plan "
                        "(collective: every process must run it)")
    p.add_argument("--trend", action="store_true",
                   help="tabulate model-vs-measured ratio and per-stage "
                        "times across ALL history records carrying an "
                        "explain block (newest last) instead of "
                        "rendering one record; --config filters by the "
                        "baseline config signature")
    p.add_argument("--config", default=None, metavar="SUBSTR",
                   help="with --trend: only records whose config "
                        "signature contains this substring (e.g. "
                        "'devices=8' or 'tuned=')")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON instead of the table")
    args = p.parse_args(argv)

    from .explain import explain_from_record, format_explain

    if args.trend:
        return _explain_trend(args)
    if args.plan:
        rec = _explain_live(args)
        if isinstance(rec, int):
            return rec
    elif args.record:
        try:
            with open(args.record) as f:
                text = f.read()
        except OSError as e:
            print(f"report explain: {e}", file=sys.stderr)
            return 2
        blocks = _explain_blocks_from_text(text)
        if not blocks:
            print(f"report explain: no explain block in {args.record}",
                  file=sys.stderr)
            return 2
        rec = blocks[-1]
    else:
        history = _resolve_history(args)
        records, dropped = (regress.load_history(history) if history
                            else ([], 0))
        if dropped:
            print(f"report explain: skipped {dropped} malformed line(s) "
                  f"in {history}", file=sys.stderr)
        blocks = [b for b in (explain_from_record(r)
                              for r in records) if b is not None]
        if not blocks:
            print(f"report explain: no history record carries an explain "
                  f"block ({history or 'store disabled'}); run "
                  f"'report explain --plan ...' or 'speed3d -explain'",
                  file=sys.stderr)
            return 2
        rec = blocks[-1]

    if args.json:
        print(json.dumps(rec, sort_keys=True))
    else:
        print(format_explain(rec))
    return 0


# ------------------------------------------------------- calibrate CLI

def _main_calibrate(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report calibrate",
        description="Measure this machine's hardware profile (HBM "
                    "bandwidth, ICI link bandwidth, matmul peak, launch "
                    "floor) with short microbenchmarks and persist it "
                    "next to the wisdom store, so dfft.explain computes "
                    "divergence against measured constants "
                    "(hw.source == 'calibrated') and the tuner's pruning "
                    "model reads persisted per-transport corrections. "
                    "Exit codes: 0 ok, 2 backend/IO error.")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="profile file (default: DFFT_HW_PROFILE or "
                        "<compile cache dir>/hwprofile.json)")
    p.add_argument("--iters", type=int, default=10,
                   help="amortized timing iterations per microbenchmark "
                        "(default 10)")
    p.add_argument("--no-wire", action="store_true",
                   help="skip the multi-device ICI/link microbenchmark")
    p.add_argument("--dry-run", action="store_true",
                   help="measure and print, write nothing")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON instead of the summary")
    args = p.parse_args(argv)

    from . import calibrate as _cal

    try:
        prof = _cal.calibrate(iters=max(1, args.iters),
                              wire=not args.no_wire)
    except Exception as e:  # noqa: BLE001 — CLI boundary (sick backend)
        print(f"report calibrate: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    path = None
    if not args.dry_run:
        path = _cal.write_profile(prof, args.out)
        if path is None:
            print("report calibrate: profile store disabled "
                  "(DFFT_HW_PROFILE is empty); use --out or --dry-run",
                  file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps({"profile": prof, "path": path}, sort_keys=True))
    else:
        print(_cal.format_profile(prof))
        print(f"profile written to {path}" if path
              else "(dry run: nothing written)")
    return 0


# ---------------------------------------------------------- wisdom CLI

def _kind_matches(a: str, b: str) -> bool:
    """Lenient device-kind equality: run records may carry the backend
    name ("tpu") where the wisdom key carries the device kind ("TPU v5
    lite") — substring match either way, case-insensitive."""
    a, b = a.lower(), b.lower()
    return a == b or a in b or b in a


def _winner_label(w: dict) -> str:
    """One wisdom winner dict as the compact label benchmark lines stamp
    (``decomposition/transport/executor/ovK[+wDTYPE]`` — must agree with
    ``tuner.Candidate.label``, wire suffix included, or compressed
    winners silently never match their history rows). Precision-extended
    winners need no extra join term: the tier rides INSIDE the executor
    string itself (``matmul:bf16`` — Candidate.executor and
    ``plan.executor`` carry the same canonical tiered label), so the
    label agrees by construction; a stray ``mm_precision`` field in the
    winner dict must still match the executor suffix (older/foreign
    entries), or the label would lie about what won."""
    ex = str(w.get("executor"))
    mm = w.get("mm_precision")
    if mm and f":{mm}" not in ex:
        # Defensive join for entries that recorded the tier out-of-band:
        # fold it into the executor term so the label matches what a
        # tiered plan stamps.
        ex = f"{ex}:{mm}"
    label = (f"{w.get('decomposition')}/{w.get('algorithm')}"
             f"/{ex}/ov{w.get('overlap_chunks')}")
    if w.get("wire_dtype"):
        label += f"+w{w['wire_dtype']}"
    return label


def _wisdom_fresh_seconds(entry: dict, records: list[dict]) -> list[float]:
    """Per-execute seconds of fresh history records matching one wisdom
    entry's winner tuple (the ``tuned=<label>`` baseline group) on the
    same hardware, eligible runs only."""
    label = _winner_label(entry.get("winner") or {})
    kind = str((entry.get("key") or {}).get("device_kind", ""))
    out = []
    for rec in records:
        cfg = rec.get("config") or {}
        if cfg.get("tuned") != label:
            continue
        if not _kind_matches(str(rec.get("device_kind", "")), kind):
            continue
        if rec.get("fallback") or not rec.get("ok", True):
            continue
        sec = rec.get("seconds")
        if isinstance(sec, (int, float)) and sec > 0:
            out.append(float(sec))
    return out


def _wisdom_summary(entry: dict) -> tuple[str, str]:
    """(key summary, winner label) display columns of one entry."""
    key = entry.get("key") or {}
    shape = "x".join(str(s) for s in key.get("shape") or [])
    mesh = key.get("mesh")
    where = ("mesh " + "x".join(str(d) for d in mesh) if mesh
             else f"{key.get('ndev', '?')}dev")
    k = (f"{key.get('kind', '?')} {shape} {key.get('dtype', '?')} "
         f"dir{key.get('direction', '?')} {where} "
         f"[{key.get('device_kind', '?')}]")
    if key.get("mm_precision"):
        # Tier-pinned tournaments (PlanOptions.mm_precision) are their
        # own wisdom identity; surface the pin next to the key.
        k += f" mm={key['mm_precision']}"
    return k, _winner_label(entry.get("winner") or {})


def _main_wisdom(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report wisdom",
        description="Inspect the tuner's persistent wisdom store; with "
                    "--gate, cross-check each stored winner against fresh "
                    "history records of the same winner tuple (median + "
                    "MAD noise model) and exit 1 when a stored winner "
                    "regressed beyond noise (stale wisdom). Exit codes: "
                    "0 clean, 1 stale winner (with --gate), 2 usage/IO "
                    "error.")
    p.add_argument("--wisdom", default=None, metavar="PATH",
                   help="wisdom store (default: DFFT_WISDOM or "
                        "<compile cache dir>/wisdom.jsonl)")
    _history_arg(p)
    p.add_argument("--gate", action="store_true",
                   help="exit 1 when any stored winner regressed vs fresh "
                        "history records of its tuple")
    p.add_argument("--mads", type=float, default=regress.DEFAULT_MADS,
                   help="noise band half-width in scaled MADs (default: "
                        f"{regress.DEFAULT_MADS})")
    p.add_argument("--min-rel", type=float, default=regress.DEFAULT_MIN_REL,
                   help="noise band floor as a fraction of the median "
                        f"(default: {regress.DEFAULT_MIN_REL})")
    p.add_argument("--min-samples", type=int,
                   default=regress.DEFAULT_MIN_SAMPLES,
                   help="fresh records required for a verdict "
                        f"(default: {regress.DEFAULT_MIN_SAMPLES})")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON instead of the table")
    args = p.parse_args(argv)

    from . import tuner

    path = args.wisdom or tuner.default_wisdom_path()
    if path is None:
        print("report wisdom: store disabled (DFFT_WISDOM is empty)",
              file=sys.stderr)
        return 2
    entries, dropped = tuner.load_wisdom(path)
    if dropped:
        print(f"report wisdom: skipped {dropped} malformed line(s) in "
              f"{path}", file=sys.stderr)
    records: list[dict] = []
    if args.gate or args.history:
        history = _resolve_history(args)
        records, hdropped = (regress.load_history(history) if history
                             else ([], 0))
        if hdropped:
            print(f"report wisdom: skipped {hdropped} malformed line(s) in "
                  f"{history}", file=sys.stderr)

    rows = []
    for entry in entries.values():
        key_s, label = _wisdom_summary(entry)
        row = {
            "key": entry.get("key"),
            "winner": label,
            "seconds": entry.get("seconds"),
            "recorded_at": entry.get("recorded_at"),
        }
        if args.gate:
            fresh = _wisdom_fresh_seconds(entry, records)
            row["gate"] = regress.wisdom_verdict(
                float(entry.get("seconds") or 0.0), fresh,
                mads=args.mads, min_rel=args.min_rel,
                min_samples=args.min_samples)
        rows.append((key_s, row))

    if args.json:
        print(json.dumps([r for _, r in rows], sort_keys=True))
    elif not rows:
        print(f"(empty wisdom store: {path})")
    else:
        for key_s, row in rows:
            sec = row["seconds"]
            line = (f"{key_s}  ->  {row['winner']}  "
                    f"{'' if sec is None else f'{sec:.6f}s  '}"
                    f"({row['recorded_at']})")
            gate = row.get("gate")
            if gate is not None:
                line += f"  [{gate['verdict']}"
                if "delta_pct" in gate:
                    line += f" {gate['delta_pct']:+.1f}%"
                line += f", fresh n={gate['fresh']['n']}]"
            print(line)
    stale = [r for _, r in rows
             if (r.get("gate") or {}).get("verdict") == "regressed"]
    if stale and not args.json:
        print(f"{len(stale)} stale wisdom winner(s)", file=sys.stderr)
    return 1 if (args.gate and stale) else 0


def _format_qos_table(doc: dict) -> str:
    """The SLO-ledger table of ``report qos``: one row per tenant with
    the declaration (class/weight/rate), the intake/drain/shed/miss
    counters, the p50/p99 queue wait, and the SLO verdict when the
    tenant declared a target."""
    head = ("tenant", "class", "weight", "rate/s", "submits",
            "transforms", "shed", "misses", "wait_p50", "wait_p99",
            "slo", "verdict")
    rows = [head]

    def s(v, fmt="{:g}"):
        return "-" if v is None else fmt.format(v)

    for name, t in sorted((doc.get("tenants") or {}).items()):
        verdict = "-"
        if t.get("slo_wait_s") is not None:
            verdict = "ok" if t.get("slo_ok") else "MISSED"
        rows.append((
            name, str(t.get("class", "-")), s(t.get("weight")),
            s(t.get("rate")), str(t.get("submits", 0)),
            str(t.get("transforms", 0)), str(t.get("quota_shed", 0)),
            str(t.get("deadline_misses", 0)),
            s(t.get("wait_p50_s"), "{:.6f}"),
            s(t.get("wait_p99_s"), "{:.6f}"),
            s(t.get("slo_wait_s")), verdict))
    widths = [max(len(r[i]) for r in rows) for i in range(len(head))]
    return "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        for r in rows)


def _main_qos(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report qos",
        description="Per-tenant QoS/SLO ledger (docs/SERVING_QOS.md): "
                    "submits, drained transforms, quota sheds, deadline "
                    "misses, and p50/p99 queue wait against each "
                    "tenant's declared SLO target. Reads a ledger JSON "
                    "written by qos.write_ledger / "
                    "QosPolicy.ledger_json (--ledger), or the newest "
                    "history run record carrying a 'qos' block.")
    p.add_argument("--ledger", default=None, metavar="FILE",
                   help="SLO-ledger JSON file (qos.write_ledger)")
    _history_arg(p)
    p.add_argument("--json", action="store_true",
                   help="print the ledger document as JSON")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 when any tenant with a declared SLO "
                        "target currently misses it")
    args = p.parse_args(argv)

    if args.ledger:
        try:
            with open(args.ledger) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"report qos: {e}", file=sys.stderr)
            return 2
    else:
        history = _resolve_history(args)
        records = regress.load_history(history)[0] if history else []
        doc = next((r["qos"] for r in reversed(records)
                    if isinstance(r.get("qos"), dict)), None)
        if doc is None:
            print("report qos: no --ledger given and no history record "
                  "carries a qos block", file=sys.stderr)
            return 2
    if not isinstance(doc.get("tenants"), dict):
        print("report qos: document has no tenants table",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(_format_qos_table(doc))
    missed = [name for name, t in doc["tenants"].items()
              if t.get("slo_wait_s") is not None and not t.get("slo_ok")]
    if missed and not args.json:
        print(f"{len(missed)} tenant(s) missing their SLO: "
              f"{sorted(missed)}", file=sys.stderr)
    return 1 if (args.gate and missed) else 0


def _format_health(verdict: dict) -> str:
    lines = [f"status: {verdict.get('status', 'unknown')}   "
             f"(samples={verdict.get('samples', 0)})"]
    totals = verdict.get("totals") or {}
    if totals:
        lines.append("totals: " + "  ".join(
            f"{k}={v:g}" for k, v in sorted(totals.items())
            if isinstance(v, (int, float))))
    alerts = verdict.get("alerts") or []
    if not alerts:
        lines.append("no alerts")
    for a in alerts:
        tenant = f" tenant={a['tenant']}" if a.get("tenant") else ""
        lines.append(f"[{a.get('severity', '?'):5s}] "
                     f"{a.get('name', '?')}{tenant}: "
                     f"{a.get('detail', '')}")
    return "\n".join(lines)


def _main_health(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report health",
        description="Live-monitor health verdicts (docs/OBSERVABILITY"
                    ".md 'Live monitoring & health'): windowed SLO "
                    "burn rate, queue stalls, quota pressure, and "
                    "degraded-execution alerts. Reads a monitor JSONL "
                    "series (--series; DFFT_MONITOR=interval,path "
                    "streams one), or the newest history run record "
                    "carrying a 'health' block.")
    p.add_argument("--series", default=None, metavar="FILE",
                   help="monitor JSONL series (Monitor(path=...) / "
                        "DFFT_MONITOR=interval,path)")
    _history_arg(p)
    p.add_argument("--fast-window", type=float, default=None,
                   metavar="S", help="fast burn window, seconds")
    p.add_argument("--slow-window", type=float, default=None,
                   metavar="S", help="slow burn window, seconds")
    p.add_argument("--burn-threshold", type=float, default=None,
                   metavar="FRAC",
                   help="windowed bad-submit fraction that fires "
                        "slo_burn")
    p.add_argument("--json", action="store_true",
                   help="print the verdict document as JSON")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 when any severity-'alert' alert fires "
                        "(stall, slo_burn)")
    args = p.parse_args(argv)

    from .monitor import health_from_samples, load_series

    if args.series:
        samples = load_series(args.series)
        if not samples:
            print(f"report health: {args.series}: no monitor samples",
                  file=sys.stderr)
            return 2
        kw = {}
        if args.fast_window is not None:
            kw["fast_window_s"] = args.fast_window
        if args.slow_window is not None:
            kw["slow_window_s"] = args.slow_window
        if args.burn_threshold is not None:
            kw["burn_threshold"] = args.burn_threshold
        verdict = health_from_samples(samples, **kw)
    else:
        history = _resolve_history(args)
        records = regress.load_history(history)[0] if history else []
        verdict = next((r["health"] for r in reversed(records)
                        if isinstance(r.get("health"), dict)), None)
        if verdict is None:
            print("report health: no --series given and no history "
                  "record carries a health block", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    else:
        print(_format_health(verdict))
    firing = [a for a in verdict.get("alerts") or []
              if a.get("severity") == "alert"]
    if firing and not args.json:
        print(f"{len(firing)} alert(s) firing: "
              f"{sorted(a.get('name', '?') for a in firing)}",
              file=sys.stderr)
    return 1 if (args.gate and firing) else 0


def _format_numerics(block: dict) -> str:
    lines = [
        f"numerics: {block.get('sampled', 0)} sampled, "
        f"{block.get('audited', 0)} audited"
        + (f" ({block.get('audit_failures', 0)} failed)"
           if block.get("audit_failures") else "")
        + f", slack {block.get('slack', 0.0):g}x"]
    nf = block.get("nonfinite") or {}
    lines.append("non-finite: " + (", ".join(
        f"{k} {v}" for k, v in sorted(nf.items())) if nf else "none"))
    plans = block.get("plans") or {}
    if plans:
        lines.append(f"{'plan bucket':44} {'n':>5} {'admitted':>9} "
                     f"{'p50':>9} {'p99':>9} {'drift':>8}")
        for key, b in sorted(plans.items()):
            lines.append(
                f"{key:44} {b.get('n', 0):>5} "
                f"{b.get('admitted_err', 0.0):>9.3g} "
                f"{b.get('realized_p50', 0.0):>9.3g} "
                f"{b.get('realized_p99', 0.0):>9.3g} "
                f"{b.get('drift_ratio', 0.0):>7.3g}x"
                + ("  DRIFTING" if b.get("drifting") else ""))
    else:
        lines.append("no audited plan buckets")
    return "\n".join(lines)


def _numerics_firing(block: dict) -> list[str]:
    """What would trip ``report numerics --gate``: drifting plan
    buckets and output-site non-finite sentinels (input-site ones are
    the caller's — surfaced, never gating)."""
    firing = [f"accuracy_drift:{key}"
              for key, b in sorted((block.get("plans") or {}).items())
              if b.get("drifting")]
    nf_out = sum(v for k, v in (block.get("nonfinite") or {}).items()
                 if k.startswith("output:"))
    if nf_out > 0:
        firing.append(f"nonfinite:output:{nf_out:g}")
    return firing


def _main_numerics(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report numerics",
        description="Numerics-plane ledger (docs/OBSERVABILITY.md "
                    "'Numerics plane'): shadow-sampled realized error "
                    "per plan bucket against the admitted budget "
                    "(drift verdicts), plus the non-finite sentinel "
                    "counters. Reads a monitor JSONL series "
                    "(--series), a fleet directory (--dir; ledgers "
                    "pool cross-process — concatenated reservoir "
                    "tails, re-ranked quantiles), or this process's "
                    "live ledger.")
    p.add_argument("--series", default=None, metavar="FILE",
                   help="monitor JSONL series (DFFT_MONITOR=interval,"
                        "path)")
    p.add_argument("--dir", dest="dir_", default=None, metavar="DIR",
                   help="fleet series directory (DFFT_MONITOR_DIR)")
    p.add_argument("--json", action="store_true",
                   help="print the pooled numerics block as JSON")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 on accuracy drift or non-finite "
                        "outputs (the CI verdict)")
    args = p.parse_args(argv)
    if args.series and args.dir_:
        print("report numerics: pass --series or --dir, not both",
              file=sys.stderr)
        return 2

    block = None
    if args.dir_:
        from . import fleet as _fleet

        streams = _fleet.load_fleet(args.dir_)
        if not streams:
            print(f"report numerics: {args.dir_}: no monitor series",
                  file=sys.stderr)
            return 2
        merged = _fleet.merge_streams(streams)
        block = next((m["numerics"] for m in reversed(merged)
                      if isinstance(m.get("numerics"), dict)), None)
    elif args.series:
        from .monitor import load_series

        samples = load_series(args.series)
        if not samples:
            print(f"report numerics: {args.series}: no monitor "
                  f"samples", file=sys.stderr)
            return 2
        block = next((s["numerics"] for s in reversed(samples)
                      if isinstance(s.get("numerics"), dict)), None)
    else:
        from .numerics import numerics_snapshot

        block = numerics_snapshot()
    if block is None:
        print("report numerics: no numerics block — the plane is dark "
              "(arm it with DFFT_SHADOW_RATE=p[,seed])",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(block, indent=2, sort_keys=True))
    else:
        print(_format_numerics(block))
    firing = _numerics_firing(block)
    if firing and not args.json:
        print(f"{len(firing)} numerics verdict(s) firing: {firing}",
              file=sys.stderr)
    return 1 if (args.gate and firing) else 0


def _main_live(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report live",
        description="The newest sample of a live-monitor JSONL series "
                    "(DFFT_MONITOR=interval,path): queue depth and "
                    "pending age, stall count, per-tenant SLO "
                    "standing. --prom renders it in Prometheus text "
                    "exposition format for scraping.")
    p.add_argument("--series", required=True, metavar="FILE",
                   help="monitor JSONL series")
    p.add_argument("--prom", action="store_true",
                   help="Prometheus text exposition of the newest "
                        "sample")
    p.add_argument("--json", action="store_true",
                   help="print the newest sample document as JSON")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="follow mode: re-read and re-render the newest "
                        "sample every N seconds until interrupted")
    p.add_argument("--watch-max", type=int, default=None,
                   help=argparse.SUPPRESS)  # bound iterations (tests)
    args = p.parse_args(argv)

    from .monitor import load_series, prometheus_from_sample

    def render() -> tuple[str, int]:
        samples = load_series(args.series)
        if not samples:
            return (f"report live: {args.series}: no monitor samples", 2)
        newest = samples[-1]
        if args.prom:
            return (prometheus_from_sample(newest).rstrip("\n"), 0)
        if args.json:
            return (json.dumps(newest, indent=2, sort_keys=True), 0)
        lines = [f"{len(samples)} sample(s); "
                 f"newest seq={newest.get('seq')} "
                 f"pid={newest.get('pid')}"
                 + (f" host={newest['host']}"
                    if newest.get("host") else "")]
        qb = newest.get("queue") or {}
        if qb:
            lines.append(
                f"queue[{qb.get('kind')}]: depth={qb.get('depth')} "
                f"groups={qb.get('groups')} "
                f"oldest_age={qb.get('oldest_pending_age_s', 0.0):.3f}s "
                f"stalls={qb.get('stalls_total', 0)}")
        wv = qb.get("waves") or {}
        if wv:
            # Scheduler-occupancy line (schema-3 samples; docs/
            # OBSERVABILITY.md "Wave scheduler occupancy").
            mode = "streaming" if qb.get("streaming") else "flush"
            idle = wv.get("idle_fraction")
            wm = wv.get("width_mean")
            lines.append(
                f"waves[{mode}]: n={wv.get('waves', 0)} "
                f"width_mean={'-' if wm is None else f'{wm:.2f}'} "
                f"idle={'-' if idle is None else f'{idle:.0%}'} "
                f"preempt={wv.get('preemptions', 0)} "
                f"bumped={wv.get('bumped_transforms', 0)}")
            for klass, aw in sorted((wv.get("admit_wait") or {}).items()):
                p99 = aw.get("p99_s")
                lines.append(
                    f"  admit[{klass}]: n={aw.get('n', 0)} "
                    f"p50={aw.get('p50_s', 0.0):.6f}s "
                    f"p99={'-' if p99 is None else f'{p99:.6f}'}s")
        tenants = ((newest.get("qos") or {}).get("tenants") or {})
        for name, t in sorted(tenants.items()):
            slo = ("-" if t.get("slo_ok") is None
                   else "ok" if t["slo_ok"] else "MISS")
            lines.append(
                f"tenant {name}: submits={t.get('submits', 0)} "
                f"misses={t.get('deadline_misses', 0)} "
                f"shed={t.get('quota_shed', 0)} slo={slo}")
        return ("\n".join(lines), 0)

    if args.watch is None:
        text, rc = render()
        print(text, file=sys.stderr if rc else sys.stdout)
        return rc
    if args.watch <= 0:
        print("report live: --watch must be a positive interval",
              file=sys.stderr)
        return 2
    # Follow mode: terminal refresh on a tty (clear + home), plain
    # re-render blocks otherwise (pipes, tests, CI logs). A series that
    # has not appeared yet is watched patiently, not a hard error.
    n = 0
    try:
        while True:
            text, _rc = render()
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(text)
            sys.stdout.flush()
            n += 1
            if args.watch_max is not None and n >= args.watch_max:
                return 0
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:  # |head closed the pipe — a clean exit
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 0


def _main_fleet(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributedfft_tpu.report fleet",
        description="Fleet view over a shared monitor-series directory "
                    "(DFFT_MONITOR_DIR; docs/OBSERVABILITY.md 'Fleet "
                    "view & load generation'): per-process series are "
                    "clock-aligned and merged into fleet samples, "
                    "judged by the fleet health engine — per-member "
                    "verdicts plus cross-stream straggler/imbalance/"
                    "fleet-stall alerts.")
    p.add_argument("--dir", default=None, metavar="DIR",
                   help="monitor-series directory (default: "
                        "DFFT_MONITOR_DIR)")
    p.add_argument("--prom", action="store_true",
                   help="Prometheus text exposition: every member's "
                        "newest sample with proc/host labels plus the "
                        "dfft_fleet_* aggregates")
    p.add_argument("--json", action="store_true",
                   help="print the fleet verdict document as JSON")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 when the fleet verdict is 'alert' "
                        "(member stall/burn, fleet_stall, "
                        "straggler_skew)")
    p.add_argument("--fast-window", type=float, default=None,
                   metavar="S", help="fast burn window, seconds")
    p.add_argument("--slow-window", type=float, default=None,
                   metavar="S", help="slow burn window, seconds")
    p.add_argument("--burn-threshold", type=float, default=None,
                   metavar="FRAC",
                   help="windowed bad-submit fraction that fires "
                        "slo_burn")
    p.add_argument("--bucket", type=float, default=None, metavar="S",
                   help="merge bucket width, seconds (default: the "
                        "fleet's median sampling interval)")
    args = p.parse_args(argv)

    from . import fleet as _fleet

    dir_ = args.dir or _fleet.monitor_dir_from_env()
    if not dir_:
        print("report fleet: no --dir given and DFFT_MONITOR_DIR is "
              "unset", file=sys.stderr)
        return 2
    streams = _fleet.load_fleet(dir_)
    if not streams:
        print(f"report fleet: {dir_}: no monitor series",
              file=sys.stderr)
        return 2
    if args.prom:
        print(_fleet.prometheus_from_fleet(streams), end="")
        return 0
    kw = {}
    if args.fast_window is not None:
        kw["fast_window_s"] = args.fast_window
    if args.slow_window is not None:
        kw["slow_window_s"] = args.slow_window
    if args.burn_threshold is not None:
        kw["burn_threshold"] = args.burn_threshold
    if args.bucket is not None:
        kw["bucket_s"] = args.bucket
    doc = _fleet.fleet_health(streams, **kw)
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(_fleet.format_fleet(doc))
    return 1 if (args.gate and doc.get("status") == "alert") else 0


_SUBCOMMANDS = {
    "merge": _main_merge,
    "record": _main_record,
    "history": _main_history,
    "compare": _main_compare,
    "wisdom": _main_wisdom,
    "explain": _main_explain,
    "calibrate": _main_calibrate,
    "qos": _main_qos,
    "health": _main_health,
    "live": _main_live,
    "fleet": _main_fleet,
    "numerics": _main_numerics,
}


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in _SUBCOMMANDS:
        return _SUBCOMMANDS[argv[0]](argv[1:])
    # Backward compatibility: a bare file list is a merge (the original
    # single-purpose CLI contract; the round scripts rely on it).
    return _main_merge(argv)


if __name__ == "__main__":
    sys.exit(main())
