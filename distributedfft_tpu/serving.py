"""Batched multi-request serving tier: async submit/await + coalescing.

Everything below the plan layer executes one transform at a time; a
serving tier for heavy traffic needs many *independent* same-shape FFTs
coalesced into ONE device program — "Large-Scale Discrete Fourier
Transform on TPUs" (arXiv 2002.03260) reaches peak TPU utilization with
batched device programs, and DaggerFFT (arXiv 2601.12209) frames
scheduling concurrent transforms onto one mesh as the distributed-FFT
throughput play. This module is that tier, three pieces:

1. :func:`submit` / :class:`Handle` — async execute-and-await. JAX
   dispatch is already asynchronous, so ``submit(plan, x)`` returns the
   moment the program is enqueued; ``handle.result()`` blocks. Donated
   plans (``plan_dft_c2c_3d(..., donate=True)``) consume the submitted
   buffer, halving the resident HBM per in-flight request.
2. :class:`CoalescingQueue` — groups pending requests by
   ``(shape, dtype, direction)`` (exactly the tuple the PR 4 wisdom
   store keys) and executes each group through ONE batched plan
   (``plan(batch=B)``): B transforms, one collective latency per t2
   stage. Plans come from the memoized plan cache, so a steady-state
   queue replays warm executables and never re-plans.
3. :func:`warm_pool` — preplans the top-N (shape, dtype, direction[,
   batch]) tuples recorded in the persistent wisdom store at startup, so
   the first requests of a fresh process hit warm plans instead of
   paying a compile (``tune="wisdom"`` replays each stored winner with
   zero timing executions).

Throughput accounting: every flush observes ``serving_batch_size`` and
bumps ``serving_transforms`` in the metrics registry; bench.py stamps
``transforms_per_s`` into its result lines and the regress gate treats
``*_per_s`` as larger-is-better (docs/OBSERVABILITY.md "Batched serving
& throughput").

**Flight recorder** (docs/OBSERVABILITY.md "Flight recorder"): with
tracing enabled (``DFFT_TRACE=1`` / ``init_tracing``) every request is
assigned a process-unique id and its full lifecycle lands in the trace
timeline next to the chain builders' t0..t3 stage spans —
``serve_submit[<id>]`` (the enqueue), ``serve_wait[<id>]`` (enqueue ->
flush, recorded retroactively via :func:`..utils.trace.record_span`),
``serve_flush[<kind>:b<B>:<reason>]`` wrapping each group's
``serve_plan``/``serve_execute``, and ``serve_result[<id>]`` (the
caller's await). Metrics grow ``serving_queue_depth`` (gauge),
``serving_wait_seconds`` (histogram), and ``serving_flush_reasons``
(counter; reason = ``full`` | ``manual`` | ``result`` |
``deadline`` — the latter from the ``max_wait_s`` coalescing deadline).
With tracing AND metrics disabled every hook is a flag check — the
queue's execution behavior is byte-identical either way (the deadline
timer stamps enqueue times regardless: the deadline is behavior, not
telemetry).
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import nullcontext
from typing import Any

import jax
import jax.numpy as jnp

from .local import FORWARD
from .ops.executors import Scale
from .utils import metrics as _metrics
from .utils.trace import add_trace, record_span, tracing_enabled

__all__ = ["Handle", "submit", "CoalescingQueue", "warm_pool"]

#: Process-global request ids — the correlation key of one request's
#: submit/wait/result spans across threads (the MPI-tag role).
_REQ_IDS = itertools.count(1)


def _span(name: str, on: bool):
    """A live trace span when the recorder is on, else a no-op context —
    the disabled path must not even construct the annotation object."""
    return add_trace(name) if on else nullcontext()


class Handle:
    """Awaitable result of one submitted transform.

    Two lifecycles: a direct :func:`submit` handle is born resolved (the
    async-dispatched output array is already attached — ``result()``
    only blocks on the device); a :class:`CoalescingQueue` handle stays
    pending until its group flushes (``result()`` triggers the flush
    when the caller outruns the coalescer)."""

    __slots__ = ("_value", "_error", "_event", "_queue", "_req_id",
                 "_enqueued")

    def __init__(self, queue: "CoalescingQueue | None" = None):
        self._value: Any = None
        self._error: BaseException | None = None
        self._event = threading.Event()
        self._queue = queue
        # Flight-recorder fields: the request id of this handle's spans
        # and its enqueue timestamp (perf_counter) — both None when
        # tracing/metrics were off at submit, so the disabled path pays
        # nothing and records nothing.
        self._req_id: int | None = None
        self._enqueued: float | None = None

    @classmethod
    def _resolved(cls, value) -> "Handle":
        h = cls()
        h._set(value)
        return h

    def _set(self, value) -> None:
        self._value = value
        self._queue = None
        self._event.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._queue = None
        self._event.set()

    def done(self) -> bool:
        """True when the result (or failure) is attached AND device
        execution has finished — ``result()`` will not block."""
        if not self._event.is_set():
            return False
        if self._error is not None:
            return True
        try:
            return bool(self._value.is_ready())
        except AttributeError:  # non-jax value (already materialized)
            return True

    def result(self, timeout: float | None = None):
        """The transform output, blocking until it exists. A pending
        queue handle flushes its queue first (the caller demanding a
        result IS the coalescing deadline)."""
        rid = self._req_id
        with _span(f"serve_result[{rid}]",
                   rid is not None and tracing_enabled()):
            if not self._event.is_set() and self._queue is not None:
                self._queue.flush(reason="result")
            if not self._event.wait(timeout):
                raise TimeoutError("submitted transform still pending")
            if self._error is not None:
                raise self._error
            return jax.block_until_ready(self._value)


def submit(plan, x, *, scale: Scale = Scale.NONE) -> Handle:
    """Asynchronously execute ``plan`` on ``x`` -> :class:`Handle`.

    JAX dispatch is async: this returns as soon as the compiled program
    is enqueued, with the transfer/compute in flight — the caller
    overlaps host work (or more submits) with device execution and
    awaits via ``handle.result()``. With a donated plan the submitted
    buffer is consumed (the bufferDev ping-pong discipline at the
    serving tier). ``plan`` is any :class:`..api.Plan3D` — batched plans
    take the stacked ``[B, ...]`` input."""
    from .api import execute

    if _metrics._enabled:
        _metrics.inc("serving_submits", kind="direct")
    tracing = tracing_enabled()
    rid = next(_REQ_IDS) if tracing else None
    with _span(f"serve_submit[{rid}]", tracing):
        h = Handle._resolved(execute(plan, x, scale=scale))
    h._req_id = rid
    return h


class CoalescingQueue:
    """Request-coalescing front of the serving tier.

    ``submit(x)`` enqueues one transform of ``x``'s shape and returns a
    :class:`Handle`; pending requests with the same ``(shape, dtype,
    direction)`` are grouped and executed as ONE batched device program
    when the group reaches ``max_batch`` (auto-flush), on ``flush()``,
    or when any handle's ``result()`` is awaited. Batched plans build
    through the memoized plan cache, so each (tuple, B) pair compiles
    once and every later flush replays it warm — :func:`warm_pool` (or
    ``queue.warm(...)``) preplans the hot tuples at startup.

    ``kind``: ``"c2c"`` (default) or ``"r2c"`` (forward real input /
    backward half-spectrum input, canonical ``r2c_axis=2``). ``donate``
    donates the queue-owned stacked buffer of batched flushes to the
    device program (singleton flushes never donate — the caller's array
    must survive). Thread-safe: submits/flushes serialize on one lock.

    ``max_wait_s`` is the coalescing deadline (the first step of the
    multi-tenant fairness/deadline policy): a pending group whose
    OLDEST request ages past it is flushed at whatever batch size it
    reached, so a trickle of traffic never waits unboundedly for a
    full batch. The flush is driven by a daemon timer armed when a
    group forms; its reason stamps ``"deadline"`` into the
    ``serving_flush_reasons`` counter and the ``serve_flush`` span
    label. ``None`` (the default) keeps today's behavior: groups wait
    for max_batch, an explicit ``flush()``, or a ``result()``.
    """

    def __init__(
        self,
        mesh=None,
        *,
        kind: str = "c2c",
        max_batch: int = 8,
        donate: bool = False,
        max_wait_s: float | None = None,
        **plan_kw,
    ):
        if kind not in ("c2c", "r2c"):
            raise ValueError(f"kind must be c2c|r2c, got {kind!r}")
        if not isinstance(max_batch, int) or max_batch < 1:
            raise ValueError(f"max_batch must be an int >= 1, "
                             f"got {max_batch!r}")
        if max_wait_s is not None and (
                isinstance(max_wait_s, bool)
                or not isinstance(max_wait_s, (int, float))
                or not max_wait_s > 0):
            raise ValueError(f"max_wait_s must be a positive number or "
                             f"None, got {max_wait_s!r}")
        for bad in ("batch", "donate", "in_spec", "out_spec"):
            if bad in plan_kw:
                raise ValueError(f"{bad!r} is owned by the queue; do not "
                                 f"pass it in plan_kw")
        self.mesh = mesh
        self.kind = kind
        self.max_batch = max_batch
        self.donate = bool(donate)
        self.max_wait_s = None if max_wait_s is None else float(max_wait_s)
        self.plan_kw = dict(plan_kw)
        self._lock = threading.RLock()
        # (shape, dtype str, direction) -> list of (array, handle)
        self._pending: dict[tuple, list[tuple]] = {}

    # ------------------------------------------------------------ intake

    def _planner(self):
        from . import api

        return (api.plan_dft_r2c_3d if self.kind == "r2c"
                else api.plan_dft_c2c_3d)

    def _plan(self, key: tuple, batch: int | None, donate: bool):
        shape, dtype, direction = key
        kw = dict(self.plan_kw, direction=direction, batch=batch,
                  donate=donate)
        if dtype is not None:
            kw["dtype"] = dtype
        return self._planner()(shape, self.mesh, **kw)

    def submit(self, x, *, direction: int = FORWARD,
               scale: Scale = Scale.NONE) -> Handle:
        """Enqueue one transform of ``x`` (the plan's unbatched input
        shape: the 3D world for c2c / forward r2c, the half-spectrum
        world for backward r2c). Returns immediately; the group executes
        at ``max_batch``, on :meth:`flush`, or on ``result()``."""
        tracing = tracing_enabled()
        recording = tracing or _metrics._enabled
        rid = next(_REQ_IDS) if recording else None
        with _span(f"serve_submit[{rid}]", tracing):
            shape, dtype, x = self._coerce(x, direction)
            key = (shape, dtype, direction)
            handle = Handle(queue=self)
            if recording:
                handle._req_id = rid
                handle._enqueued = time.perf_counter()
            if _metrics._enabled:
                _metrics.inc("serving_submits", kind=self.kind)
            with self._lock:
                group = self._pending.setdefault(key, [])
                first = not group
                group.append((x, handle, scale))
                full = len(group) >= self.max_batch
                if self.max_wait_s is not None:
                    # The deadline clock runs even with the recorder
                    # off: the timer callback judges the group's oldest
                    # enqueue stamp against max_wait_s.
                    if handle._enqueued is None:
                        handle._enqueued = time.perf_counter()
                    if first and not full:
                        t = threading.Timer(self.max_wait_s,
                                            self._deadline_flush, (key,))
                        t.daemon = True
                        t.start()
                if _metrics._enabled:
                    _metrics.set_gauge(
                        "serving_queue_depth",
                        float(sum(len(g) for g in self._pending.values())),
                        kind=self.kind)
        if full:
            self.flush(key, reason="full")
        return handle

    def _deadline_flush(self, key: tuple) -> None:
        """Timer callback of the ``max_wait_s`` deadline: flush ``key``'s
        group iff its oldest request has aged past the deadline. A group
        that already flushed (and possibly re-formed with younger
        requests) is left alone — the newer generation armed its own
        timer when it formed."""
        with self._lock:
            group = self._pending.get(key)
            if not group:
                return
            oldest = group[0][1]._enqueued
            if oldest is None or (time.perf_counter() - oldest
                                  < self.max_wait_s * 0.999):
                return
        self.flush(key, reason="deadline")

    def _coerce(self, x, direction: int):
        """Validate/convert one request array against the plan family's
        unbatched input contract; returns (world shape, dtype str, x)."""
        plan0 = self._plan_for_probe(jnp.shape(x), direction)
        x = jnp.asarray(x, dtype=plan0.in_dtype)
        if x.shape != plan0.in_shape:
            raise ValueError(
                f"queue expects the unbatched plan input shape "
                f"{plan0.in_shape}, got {x.shape}")
        return plan0.shape, str(jnp.dtype(plan0.dtype)), x

    def _plan_for_probe(self, in_shape, direction: int):
        """The unbatched plan for a request of ``in_shape`` — resolves
        the world shape for r2c backward (half-spectrum input) without
        duplicating that geometry here. Memoized by the plan cache."""
        if len(in_shape) != 3:
            raise ValueError(
                f"submit takes one unbatched 3D input, got {in_shape}")
        shape = tuple(int(s) for s in in_shape)
        if self.kind == "r2c" and direction != FORWARD:
            # Half-spectrum input [n0, n1, n2h]: the world's true n2 is
            # ambiguous from n2h alone (n2 = 2*(n2h-1) or 2*n2h-1), so
            # backward r2c groups must declare it via plan_kw["shape"]—
            # or simply use submit_plan with an explicit plan.
            raise ValueError(
                "backward r2c coalescing needs the real-space world "
                "shape; use CoalescingQueue(kind='r2c') for forward "
                "only, or submit(plan, x) with an explicit c2r plan")
        return self._plan((shape, self.plan_kw.get("dtype"), direction),
                          None, False)

    # ------------------------------------------------------------- flush

    def pending(self) -> int:
        """Number of requests waiting to be coalesced."""
        with self._lock:
            return sum(len(g) for g in self._pending.values())

    def flush(self, key: tuple | None = None, *,
              reason: str = "manual") -> int:
        """Execute every pending group (or just ``key``'s) as batched
        programs; returns the number of transforms dispatched. Handles
        resolve to async in-flight arrays (result() blocks on device).
        ``reason`` tags the flight-recorder spans/metrics with what
        triggered the flush: ``full`` (a group reached max_batch),
        ``manual`` (this call), ``result`` (a caller's await outran
        the coalescer), or ``deadline`` (the oldest request aged past
        ``max_wait_s``)."""
        done = 0
        recording = tracing_enabled() or _metrics._enabled
        flushed_at = time.perf_counter() if recording else 0.0
        with self._lock:
            keys = [key] if key is not None else list(self._pending)
            groups = [(k, self._pending.pop(k)) for k in keys
                      if self._pending.get(k)]
            for k, group in groups:
                done += self._execute_group(k, group, reason=reason,
                                            flushed_at=flushed_at)
            if recording and _metrics._enabled and groups:
                _metrics.set_gauge(
                    "serving_queue_depth",
                    float(sum(len(g) for g in self._pending.values())),
                    kind=self.kind)
        return done

    def _execute_group(self, key: tuple, group: list, *,
                       reason: str = "manual",
                       flushed_at: float = 0.0) -> int:
        b = len(group)
        tracing = tracing_enabled()
        tag = f"{self.kind}:b{b}:{reason}"
        if tracing or _metrics._enabled:
            # Close every request's queue-wait interval: enqueue ->
            # flush. Retroactive (record_span) because only now is the
            # wait's end — and the batch it coalesced into — known.
            for _, handle, _ in group:
                if handle._enqueued is None:
                    continue
                if tracing and handle._req_id is not None:
                    record_span(f"serve_wait[{handle._req_id}]",
                                handle._enqueued, flushed_at)
                if _metrics._enabled:
                    _metrics.observe(
                        "serving_wait_seconds",
                        max(0.0, flushed_at - handle._enqueued),
                        kind=self.kind)
        try:
            with _span(f"serve_flush[{tag}]", tracing):
                if b == 1:
                    x, handle, scale = group[0]
                    from .api import execute

                    with _span(f"serve_plan[{tag}]", tracing):
                        plan = self._plan(key, None, False)
                    with _span(f"serve_execute[{tag}]", tracing):
                        handle._set(execute(plan, x, scale=scale))
                else:
                    with _span(f"serve_plan[{tag}]", tracing):
                        plan = self._plan(key, b, self.donate)
                    stacked = jnp.stack([x for x, _, _ in group])
                    from .api import _spec_divides

                    if plan.in_sharding is not None and _spec_divides(
                            plan.in_sharding.mesh, plan.in_sharding.spec,
                            stacked.shape):
                        # Pre-place the stack on the plan's input layout;
                        # uneven worlds let the chain's own pad/crop
                        # shard it (the alloc_local rule).
                        stacked = jax.device_put(stacked, plan.in_sharding)
                    with _span(f"serve_execute[{tag}]", tracing):
                        y = plan(stacked)
                        for i, (_, handle, scale) in enumerate(group):
                            out = y[i]
                            if scale != Scale.NONE:
                                from .ops.executors import apply_scale

                                out = apply_scale(out, scale,
                                                  plan.world_size)
                            handle._set(out)
        except Exception as e:  # noqa: BLE001 — fail the group's handles
            for _, handle, _ in group:
                handle._fail(e)
            raise
        if _metrics._enabled:
            _metrics.inc("serving_flushes", kind=self.kind)
            _metrics.inc("serving_flush_reasons", kind=self.kind,
                         reason=reason)
            _metrics.inc("serving_transforms", float(b), kind=self.kind)
            _metrics.observe("serving_batch_size", float(b), kind=self.kind)
        return b

    # -------------------------------------------------------------- warm

    def warm(self, shapes, *, batches=(None,),
             direction: int = FORWARD) -> int:
        """Preplan (and thereby plan-cache) the given world shapes at the
        given batch sizes — the explicit-tuple warm path (the wisdom-
        driven one is :func:`warm_pool`). Returns plans built."""
        n = 0
        for shape in shapes:
            for b in batches:
                self._plan((tuple(int(s) for s in shape),
                            self.plan_kw.get("dtype"), direction), b, False)
                n += 1
        return n


def warm_pool(mesh=None, top_n: int = 4, *, path: str | None = None,
              max_batch: int | None = None) -> list:
    """Preplan the top-N problem tuples of the persistent wisdom store.

    The PR 4 wisdom store keys measured winners by exactly the serving
    tuple — (kind, shape, dtype, direction[, batch], mesh, hardware) —
    so the hottest entries ARE the shapes a fresh serving process will
    see first. This reads the store (``DFFT_WISDOM`` / the compile-cache
    default), keeps entries matching the current platform/x64/device
    count (``mesh``: a Mesh, int device count, or None = single device),
    orders newest-first, and builds each of the top ``top_n`` through
    ``tune="wisdom"`` — replaying the stored winner with zero timing
    executions into the memoized plan cache. ``max_batch`` additionally
    preplans each tuple at that batch size, warming the coalescer's
    full-group program too. Returns the built plans."""
    import math

    from . import api, tuner

    entries = tuner._read_wisdom(path if path is not None
                                 else tuner.default_wisdom_path())
    if isinstance(mesh, int):
        ndev = mesh
    elif mesh is None:
        ndev = 1
    else:
        ndev = int(math.prod(mesh.devices.shape))
    platform = jax.default_backend()
    x64 = bool(jax.config.jax_enable_x64)

    def eligible(entry) -> bool:
        k = entry.get("key", {})
        return (k.get("kind") in ("c2c", "r2c")
                and k.get("ndev") == ndev
                and k.get("platform") == platform
                and k.get("x64") == x64
                and k.get("layouts") is None)

    ranked = sorted((e for e in entries.values() if eligible(e)),
                    key=lambda e: str(e.get("recorded_at", "")),
                    reverse=True)[:max(0, int(top_n))]
    plans = []
    on = tracing_enabled()
    for entry in ranked:
        k = entry["key"]
        plan_fn = (api.plan_dft_r2c_3d if k["kind"] == "r2c"
                   else api.plan_dft_c2c_3d)
        batches = {k.get("batch")}
        if max_batch is not None:
            batches.add(int(max_batch))
        for b in sorted(batches, key=lambda v: (v is not None, v)):
            # One flight-recorder span per preplanned build (same naming
            # scheme as serve_plan), so a pool warm-up is attributable
            # on the merged timeline next to the serving spans.
            name = (f"warm_plan[{k['kind']}:"
                    f"{'x'.join(str(s) for s in k['shape'])}"
                    + (f":b{b}" if b else "") + "]") if on else ""
            try:
                with _span(name, on):
                    plans.append(plan_fn(
                        tuple(k["shape"]), mesh, direction=k["direction"],
                        dtype=jnp.dtype(k["dtype"]), tune="wisdom", batch=b))
            except Exception:  # noqa: BLE001 — a stale tuple never
                continue       # blocks the rest of the pool
    if _metrics._enabled:
        _metrics.set_gauge("serving_warm_pool_plans", float(len(plans)))
    return plans
