"""Batched multi-request serving tier: async submit/await + coalescing.

Everything below the plan layer executes one transform at a time; a
serving tier for heavy traffic needs many *independent* same-shape FFTs
coalesced into ONE device program — "Large-Scale Discrete Fourier
Transform on TPUs" (arXiv 2002.03260) reaches peak TPU utilization with
batched device programs, and DaggerFFT (arXiv 2601.12209) frames
scheduling concurrent transforms onto one mesh as the distributed-FFT
throughput play. This module is that tier, four pieces:

1. :func:`submit` / :class:`Handle` — async execute-and-await. JAX
   dispatch is already asynchronous, so ``submit(plan, x)`` returns the
   moment the program is enqueued; ``handle.result()`` blocks. Donated
   plans (``plan_dft_c2c_3d(..., donate=True)``) consume the submitted
   buffer, halving the resident HBM per in-flight request.
2. :class:`CoalescingQueue` — groups pending requests by
   ``(shape, dtype, direction)`` (exactly the tuple the PR 4 wisdom
   store keys) and executes each group through ONE batched plan
   (``plan(batch=B)``): B transforms, one collective latency per t2
   stage. Plans come from the memoized plan cache, so a steady-state
   queue replays warm executables and never re-plans.
3. :func:`warm_pool` — preplans the top-N (shape, dtype, direction[,
   batch]) tuples recorded in the persistent wisdom store at startup, so
   the first requests of a fresh process hit warm plans instead of
   paying a compile (``tune="wisdom"`` replays each stored winner with
   zero timing executions).
4. **Fault tolerance** (docs/ROBUSTNESS.md): with the retry machinery
   armed (``retry_max=``/``DFFT_RETRY_MAX``), a failed flush is
   classified (:func:`..faults.classify`) and recovered instead of
   failing every co-batched request: transient errors retry with
   bounded exponential backoff (``DFFT_RETRY_BACKOFF_S``), persistent
   failures rebuild the group on the degraded matmul-DFT executor
   (``DFFT_FALLBACK_EXECUTOR`` — :mod:`..ops.dft_matmul` shares no code
   with the XLA fft thunk), and a batched flush that still fails
   *bisects*: each request re-runs unbatched (with its own degraded
   fallback) so one poisoned buffer fails alone while its cohort
   completes. ``submit(..., deadline_s=T)`` cancels a request that
   waits past T with :class:`DeadlineExceeded` (queue-wait breakdown
   attached); ``max_pending``/``admission`` bound the queue depth so
   overload degrades predictably (:class:`QueueFull`). With none of
   these knobs set the queue's behavior is byte-identical to the
   pre-robustness tier — one classification-free try/except per flush.
5. **Multi-tenant QoS** (docs/SERVING_QOS.md): with a
   :class:`..qos.QosPolicy` armed (``policy=`` / the ``DFFT_QOS`` spec
   string) every request belongs to a registered :class:`..qos.Tenant`
   (``submit(..., tenant=)``; groups then key per tenant) and the
   policy decides three things — **admission** (an over-quota submit is
   shed with :class:`..qos.QuotaExceeded` under ``admission="raise"``
   or parked until its token bucket refills under ``"block"``; realtime
   tenants never shed before batch ones), **drain order** (strict
   priority class, weighted-fair queueing across tenants within a
   class, a starvation clock that promotes any group older than
   ``max_wait_s x starvation_factor``), and **concurrent-wave
   placement** (higher classes take the earliest waves of a merged
   schedule; a realtime group never rides a batch cohort). Retries and
   degraded rebuilds are charged to the owning tenant's bucket.
   Accounting rides the flight recorder: ``serving_tenant_*`` metrics,
   ``tenant=`` attributes on the ``serve_submit``/``serve_flush`` span
   names, and the policy's SLO ledger (``report qos``). With no policy
   configured everything below is byte-identical to the policy-free
   tier, and the flush drain order is the documented FIFO: oldest
   formed group first (an explicit per-group formation stamp, not a
   dict-iteration accident).

Throughput accounting: every flush observes ``serving_batch_size`` and
bumps ``serving_transforms`` in the metrics registry; bench.py stamps
``transforms_per_s`` into its result lines and the regress gate treats
``*_per_s`` as larger-is-better (docs/OBSERVABILITY.md "Batched serving
& throughput").

**Flight recorder** (docs/OBSERVABILITY.md "Flight recorder"): with
tracing enabled (``DFFT_TRACE=1`` / ``init_tracing``) every request is
assigned a process-unique id and its full lifecycle lands in the trace
timeline next to the chain builders' t0..t3 stage spans —
``serve_submit[<id>]`` (the enqueue), ``serve_wait[<id>]`` (enqueue ->
flush, recorded retroactively via :func:`..utils.trace.record_span`),
``serve_flush[<kind>:b<B>:<reason>]`` wrapping each group's
``serve_plan``/``serve_execute``, and ``serve_result[<id>]`` (the
caller's await). Recovery paths add ``serve_retry[<tag>:a<N>]`` (the
Nth backoff retry), ``serve_degraded[<tag>:<executor>]`` (the fallback
rebuild), and ``serve_expire[<id>]`` (a deadline cancellation,
retroactive like ``serve_wait``). Metrics grow ``serving_queue_depth``
(gauge), ``serving_wait_seconds`` (histogram), ``serving_flush_reasons``
(counter; reason = ``full`` | ``manual`` | ``result`` | ``deadline`` —
the latter from the ``max_wait_s`` coalescing deadline), and the
recovery counters ``serving_retries`` / ``serving_isolated_failures`` /
``serving_degraded`` / ``serving_expired`` / ``serving_rejected``.
With tracing AND metrics disabled every hook is a flag check — the
queue's execution behavior is byte-identical either way (the deadline
timer stamps enqueue times regardless: the deadline is behavior, not
telemetry).
"""

from __future__ import annotations

import itertools
import os
import queue as _queuelib
import sys
import threading
import time
from contextlib import nullcontext
from typing import Any

import jax
import jax.numpy as jnp

from . import faults as _faults
from . import numerics as _numerics
from .local import FORWARD
from .ops.executors import Scale
from .qos import QosPolicy, QuotaExceeded
from .utils import metrics as _metrics
from .utils.trace import add_trace, record_span, tracing_enabled

__all__ = ["Handle", "submit", "CoalescingQueue", "warm_pool",
           "DeadlineExceeded", "QueueFull", "QuotaExceeded"]

#: Process-global request ids — the correlation key of one request's
#: submit/wait/result spans across threads (the MPI-tag role).
_REQ_IDS = itertools.count(1)

#: Default backoff base of the transient-retry loop (seconds; doubled
#: per attempt). ``DFFT_RETRY_BACKOFF_S`` / ``retry_backoff_s`` override.
DEFAULT_RETRY_BACKOFF_S = 0.05


class DeadlineExceeded(TimeoutError):
    """A request's ``deadline_s`` elapsed before it executed. Carries
    the queue-wait breakdown: ``waited_s`` (how long the request sat),
    ``deadline_s`` (its budget), and ``stage`` — ``"queued"`` (expired
    while coalescing) or ``"admission"`` (never admitted past the
    bounded queue depth). The request never executed; no partial result
    exists."""

    def __init__(self, *, waited_s: float, deadline_s: float,
                 stage: str = "queued"):
        super().__init__(
            f"request deadline of {deadline_s:g}s exceeded after "
            f"{waited_s:.3f}s in the {stage} stage (never executed)")
        self.waited_s = waited_s
        self.deadline_s = deadline_s
        self.stage = stage


class QueueFull(RuntimeError):
    """Admission rejected: the queue is at ``max_pending`` and was
    constructed with ``admission="raise"`` — the caller sheds the load
    instead of growing an unbounded backlog."""


def _span(name: str, on: bool):
    """A live trace span when the recorder is on, else a no-op context —
    the disabled path must not even construct the annotation object."""
    return add_trace(name) if on else nullcontext()


class Handle:
    """Awaitable result of one submitted transform.

    Two lifecycles: a direct :func:`submit` handle is born resolved (the
    async-dispatched output array is already attached — ``result()``
    only blocks on the device); a :class:`CoalescingQueue` handle stays
    pending until its group flushes (``result()`` triggers the flush
    when the caller outruns the coalescer). ``degraded`` is True when
    the result was produced by the executor-fallback chain rather than
    the queue's configured executor (docs/ROBUSTNESS.md)."""

    __slots__ = ("_value", "_error", "_event", "_queue", "_req_id",
                 "_enqueued", "_key", "degraded")

    def __init__(self, queue: "CoalescingQueue | None" = None):
        self._value: Any = None
        self._error: BaseException | None = None
        self._event = threading.Event()
        self._queue = queue
        # The handle's own group key, so result() can flush just its
        # group (None for direct submits — nothing pending to flush).
        self._key: tuple | None = None
        self.degraded = False
        # Flight-recorder fields: the request id of this handle's spans
        # and its enqueue timestamp (perf_counter) — both None when
        # tracing/metrics were off at submit, so the disabled path pays
        # nothing and records nothing.
        self._req_id: int | None = None
        self._enqueued: float | None = None

    @classmethod
    def _resolved(cls, value) -> "Handle":
        h = cls()
        h._set(value)
        return h

    def _set(self, value) -> None:
        self._value = value
        self._queue = None
        self._event.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._queue = None
        self._event.set()

    def done(self) -> bool:
        """True when the result (or failure) is attached AND device
        execution has finished — ``result()`` will not block."""
        if not self._event.is_set():
            return False
        if self._error is not None:
            return True
        try:
            return bool(self._value.is_ready())
        except AttributeError:  # non-jax value (already materialized)
            return True

    def result(self, timeout: float | None = None):
        """The transform output, blocking until it exists.

        Ordering contract: a pending queue handle triggers the lazy
        flush of its own group BEFORE the ``timeout`` wait begins (the
        caller demanding a result IS the coalescing deadline), so the
        timeout bounds only execution/completion wait — a singleton
        request in a never-filled group can never burn its whole
        timeout waiting for a flush that only this call would trigger.
        Raises the request's failure (retry-exhausted error,
        :class:`DeadlineExceeded`, ...) when the queue failed it."""
        rid = self._req_id
        with _span(f"serve_result[{rid}]",
                   rid is not None and tracing_enabled()):
            q = self._queue
            if not self._event.is_set() and q is not None:
                q.flush(self._key, reason="result")
                if not self._event.is_set() and self._queue is not None:
                    # Raced a concurrent submit/flush cycle: another
                    # thread may hold this group popped mid-execution.
                    # Drain everything as the pre-keyed path did.
                    q.flush(reason="result")
            if not self._event.wait(timeout):
                raise TimeoutError("submitted transform still pending")
            if self._error is not None:
                raise self._error
            return jax.block_until_ready(self._value)


def submit(plan, x, *, scale: Scale = Scale.NONE) -> Handle:
    """Asynchronously execute ``plan`` on ``x`` -> :class:`Handle`.

    JAX dispatch is async: this returns as soon as the compiled program
    is enqueued, with the transfer/compute in flight — the caller
    overlaps host work (or more submits) with device execution and
    awaits via ``handle.result()``. With a donated plan the submitted
    buffer is consumed (the bufferDev ping-pong discipline at the
    serving tier). ``plan`` is any :class:`..api.Plan3D` — batched plans
    take the stacked ``[B, ...]`` input."""
    from .api import execute

    if _metrics._enabled:
        _metrics.inc("serving_submits", kind="direct")
    tracing = tracing_enabled()
    rid = next(_REQ_IDS) if tracing else None
    with _span(f"serve_submit[{rid}]", tracing):
        h = Handle._resolved(execute(plan, x, scale=scale))
    h._req_id = rid
    return h


class _Req:
    """One pending request of a coalescing group: the coerced array, its
    handle, the scale to apply at resolve, the owning tenant (QoS-armed
    queues only), and — deadline requests only — the absolute expiry
    stamp (perf_counter axis)."""

    __slots__ = ("x", "handle", "scale", "expires", "deadline_s",
                 "tenant")

    def __init__(self, x, handle: Handle, scale: Scale,
                 expires: float | None = None,
                 deadline_s: float | None = None,
                 tenant: str | None = None):
        self.x = x
        self.handle = handle
        self.scale = scale
        self.expires = expires
        self.deadline_s = deadline_s
        self.tenant = tenant


def _quantile(sorted_vals: list, q: float) -> float | None:
    """Nearest-rank quantile over an already-sorted sample list."""
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return float(sorted_vals[i])


class _WaveStats:
    """Wave-level scheduler occupancy accounting (docs/OBSERVABILITY.md
    "Scheduler occupancy"): dispatched waves and their widths, per-class
    admit-to-dispatch latency, inter-wave device idle vs busy time, and
    preemption counts. One *wave* is one dispatch cohort — a streaming
    loop iteration's admitted set, or (baseline mode) one ``flush()``'s
    drained set, so the streaming-vs-flush idle comparison the PR 18
    acceptance gate makes is like-for-like.

    Armed by the streaming drain loop (:meth:`CoalescingQueue.serve`)
    and by monitor-armed queues in flush mode (the baseline); a queue
    with neither carries ``None`` and no hot path takes a hook.

    Completion stamps come from a dedicated daemon *stamper* thread
    that ``block_until_ready``'s each wave's output arrays in dispatch
    order — the dispatch path never blocks on the device. Inter-wave
    idle is the gap between one wave's drain and the next wave's
    dispatch while nothing else was in flight: exactly the device gap
    the streaming scheduler exists to close. With waves in flight
    back-to-back (dispatch k+1 before drain k) no idle accrues."""

    _RESERVOIR = 2048

    def __init__(self, kind: str = "c2c"):
        self.kind = kind
        self._lock = threading.Lock()
        self.waves = 0
        self.preemptions = 0       # preemption events (waves that bumped)
        self.bumped_groups = 0
        self.bumped_transforms = 0
        self.idle_s = 0.0
        self.busy_s = 0.0
        self._widths: list[float] = []
        self._durations: list[float] = []    # dispatch -> drain, seconds
        self._periods: list[float] = []      # dispatch -> next dispatch
        self._admit: dict[str, list[float]] = {}  # class -> waits
        self._last_dispatch: float | None = None
        self._q: _queuelib.Queue = _queuelib.Queue()
        self._thread: threading.Thread | None = None

    def _push(self, vals: list, v: float) -> None:
        # Caller holds the lock. Bounded reservoir: drop the oldest half
        # once full (recent waves are what occupancy questions are
        # about).
        if len(vals) >= self._RESERVOIR:
            del vals[:self._RESERVOIR // 2]
        vals.append(float(v))

    def note_wave(self, *, width: int, t_dispatch: float, outputs,
                  waits=()) -> None:
        """Record one dispatched wave. ``outputs`` are the wave's async
        output arrays (handed to the stamper thread for the drain
        stamp); ``waits`` is ``[(class, admit_to_dispatch_s), ...]``,
        one entry per request the wave admitted."""
        with self._lock:
            self.waves += 1
            self._push(self._widths, float(width))
            if self._last_dispatch is not None:
                self._push(self._periods,
                           max(0.0, t_dispatch - self._last_dispatch))
            self._last_dispatch = t_dispatch
            for klass, w in waits:
                self._push(self._admit.setdefault(klass or "none", []), w)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._stamper, name="dfft-wave-stamper",
                    daemon=True)
                self._thread.start()
        if _metrics._enabled:
            _metrics.inc("serving_waves", kind=self.kind)
            _metrics.observe("serving_wave_width", float(width),
                             kind=self.kind)
            for klass, w in waits:
                _metrics.observe("serving_wave_admit_seconds", w,
                                 kind=self.kind,
                                 tenant_class=klass or "none")
        self._q.put((t_dispatch, outputs))

    def note_preemption(self, groups: int, transforms: int) -> None:
        """Record one wave-admission preemption event: ``groups`` bumped
        groups totalling ``transforms`` transforms."""
        with self._lock:
            self.preemptions += 1
            self.bumped_groups += int(groups)
            self.bumped_transforms += int(transforms)
        if _metrics._enabled:
            _metrics.inc("serving_wave_preemptions", kind=self.kind)
            _metrics.inc("serving_wave_bumped", float(transforms),
                         kind=self.kind)

    def _stamper(self) -> None:
        last_drain: float | None = None
        while True:
            item = self._q.get()
            if item is None:
                return
            t_dispatch, outputs = item
            try:
                jax.block_until_ready(outputs)
            except Exception:  # noqa: BLE001 — a failed wave still
                pass           # closes its accounting interval
            t_drain = time.perf_counter()
            idle = busy = 0.0
            if last_drain is None or t_dispatch > last_drain:
                if last_drain is not None:
                    idle = t_dispatch - last_drain
                busy = max(0.0, t_drain - t_dispatch)
            else:
                busy = max(0.0, t_drain - last_drain)
            last_drain = max(t_drain, last_drain or t_drain)
            with self._lock:
                self.idle_s += idle
                self.busy_s += busy
                self._push(self._durations, max(0.0, t_drain - t_dispatch))
            if _metrics._enabled:
                if idle > 0:
                    _metrics.inc("serving_wave_idle_seconds", idle,
                                 kind=self.kind)
                if busy > 0:
                    _metrics.inc("serving_wave_busy_seconds", busy,
                                 kind=self.kind)

    def stop(self) -> None:
        """Let the stamper thread exit once the queue drains (daemon —
        safe to skip; a later :meth:`note_wave` restarts it)."""
        self._q.put(None)

    def snapshot(self) -> dict:
        """One JSON-ready occupancy document (the monitor's ``waves``
        sample block, schema v3)."""
        with self._lock:
            widths = sorted(self._widths)
            durs = sorted(self._durations)
            periods = sorted(self._periods)
            total = self.idle_s + self.busy_s
            admit = {}
            for klass, vals in self._admit.items():
                s = sorted(vals)
                admit[klass] = {
                    "n": len(s),
                    "p50_s": _quantile(s, 0.50),
                    "p99_s": _quantile(s, 0.99),
                    "max_s": s[-1] if s else None,
                }
            return {
                "waves": self.waves,
                "preemptions": self.preemptions,
                "bumped_groups": self.bumped_groups,
                "bumped_transforms": self.bumped_transforms,
                "width_mean": (sum(widths) / len(widths)
                               if widths else None),
                "width_max": widths[-1] if widths else None,
                "wave_duration_p50_s": _quantile(durs, 0.50),
                "wave_duration_max_s": durs[-1] if durs else None,
                "wave_period_p50_s": _quantile(periods, 0.50),
                "idle_s": self.idle_s,
                "busy_s": self.busy_s,
                "idle_fraction": (self.idle_s / total
                                  if total > 0 else None),
                "admit_wait": admit,
            }


def _env_int(name: str) -> int | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None


class CoalescingQueue:
    """Request-coalescing front of the serving tier.

    ``submit(x)`` enqueues one transform of ``x``'s shape and returns a
    :class:`Handle`; pending requests with the same ``(shape, dtype,
    direction)`` are grouped and executed as ONE batched device program
    when the group reaches ``max_batch`` (auto-flush), on ``flush()``,
    or when any handle's ``result()`` is awaited. Batched plans build
    through the memoized plan cache, so each (tuple, B) pair compiles
    once and every later flush replays it warm — :func:`warm_pool` (or
    ``queue.warm(...)``) preplans the hot tuples at startup.

    ``kind``: ``"c2c"`` (default) or ``"r2c"`` (forward real input /
    backward half-spectrum input, canonical ``r2c_axis=2``). ``donate``
    donates the queue-owned stacked buffer of batched flushes to the
    device program (singleton flushes never donate — the caller's array
    must survive). Thread-safe: submits/flushes serialize on one lock.

    ``max_wait_s`` is the coalescing deadline (the first step of the
    multi-tenant fairness/deadline policy): a pending group whose
    OLDEST request ages past it is flushed at whatever batch size it
    reached, so a trickle of traffic never waits unboundedly for a
    full batch. The flush is driven by a daemon timer armed when a
    group forms; its reason stamps ``"deadline"`` into the
    ``serving_flush_reasons`` counter and the ``serve_flush`` span
    label. ``None`` (the default) keeps today's behavior: groups wait
    for max_batch, an explicit ``flush()``, or a ``result()``.

    ``concurrent_groups`` (env ``DFFT_CONCURRENT_GROUPS``) arms the
    multi-group flush: a flush draining more than one pending group
    schedules up to this many compatible-mesh groups as ONE interleaved
    device program (:func:`..stagegraph.schedule_concurrent` — the
    DaggerFFT framing), so group A's t2 collectives issue while group
    B's t0/t3 FFTs run and exchange wire time hides under *another*
    tenant's compute. Bit-identical outputs to per-group flushes
    (pinned); groups whose plans sit below the stage-graph IR
    (single-device, dd) or that fail to schedule fall back to the
    per-group path, which owns the fault-tolerance chain. ``None``/1
    (default) keeps today's per-group flushes. Metrics grow
    ``serving_concurrent_dispatches`` / ``serving_concurrent_
    transforms`` / ``serving_concurrent_groups``; bench stamps
    ``concurrent_transforms_per_s`` (``DFFT_BENCH_CONCURRENT``).
    ``concurrent_groups="auto"`` picks the width per flush from the
    analytic schedule model (:func:`..plan_logic
    .model_concurrent_seconds` over widths 1..4 — the width with the
    highest modeled transforms/s wins; plans below the IR tier fall
    back to sequential flushes).

    ``policy`` (default: parsed from the ``DFFT_QOS`` spec string) arms
    the multi-tenant QoS tier (docs/SERVING_QOS.md): requests carry
    ``submit(..., tenant=)``, groups key per tenant, and the
    :class:`..qos.QosPolicy` governs admission (token-bucket quotas;
    over-quota submits shed with :class:`..qos.QuotaExceeded` under
    ``admission="raise"`` or park until the bucket refills under
    ``"block"``), the flush drain order (strict priority class >
    weighted-fair within a class > starvation promotion), and
    concurrent-wave placement (a realtime group never rides a batch
    cohort). ``policy="off"`` forces the policy-free tier even when
    ``DFFT_QOS`` is set. With no policy the queue is byte-identical to
    the anonymous tier (pinned) and ``flush()`` drains groups
    oldest-formed-first — the documented FIFO contract (an explicit
    per-group formation stamp, not dict-iteration order).
    ``flush(limit=N)`` bounds one call to N transforms (the last group
    splits at the boundary; the rest stay queued) — the drain quantum
    the weighted-fair shares are measured over.

    Robustness knobs (docs/ROBUSTNESS.md; all default-off — the queue
    is byte-identical to the pre-robustness tier without them):

    - ``retry_max`` (env ``DFFT_RETRY_MAX``) arms the fault-tolerant
      dispatch: transient flush errors retry up to this many times with
      exponential backoff from ``retry_backoff_s`` (env
      ``DFFT_RETRY_BACKOFF_S``, default 0.05 s); persistent failures
      fall through the degraded-executor rebuild and, for batched
      groups, per-request bisection — failures then surface ONLY
      through the failed requests' handles, never by poisoning the
      whole cohort or raising out of ``flush()``. ``retry_max=0``
      enables isolation/degradation with zero retries.
    - ``fallback_executor`` (env ``DFFT_FALLBACK_EXECUTOR``, default
      ``"matmul"``; ``""``/``"0"``/``"none"`` disables) names the
      degraded-mode executor — the matmul-DFT engine never touches the
      XLA fft thunk. Handles resolved through it set
      ``handle.degraded``.
    - ``max_pending`` bounds the total queued depth; ``admission``
      picks the overload policy: ``"block"`` (default) parks ``submit``
      until a flush frees space (pair it with ``max_wait_s`` or
      another consumer so the queue drains), ``"raise"`` sheds load
      with :class:`QueueFull`. Both count ``serving_rejected``.
    - ``submit(..., deadline_s=T)`` cancels the request with
      :class:`DeadlineExceeded` if it has not executed within T
      seconds (admission wait included).
    """

    def __init__(
        self,
        mesh=None,
        *,
        kind: str = "c2c",
        max_batch: int = 8,
        donate: bool = False,
        max_wait_s: float | None = None,
        max_pending: int | None = None,
        admission: str = "block",
        retry_max: int | None = None,
        retry_backoff_s: float | None = None,
        fallback_executor: str | None = None,
        concurrent_groups: int | str | None = None,
        policy: "QosPolicy | str | None" = None,
        streaming: bool | None = None,
        **plan_kw,
    ):
        if kind not in ("c2c", "r2c"):
            raise ValueError(f"kind must be c2c|r2c, got {kind!r}")
        if streaming is None:
            streaming = os.environ.get(
                "DFFT_SERVE_STREAMING", "").strip() not in ("", "0")
        if concurrent_groups is None:
            raw = os.environ.get("DFFT_CONCURRENT_GROUPS", "").strip()
            concurrent_groups = ("auto" if raw == "auto"
                                 else _env_int("DFFT_CONCURRENT_GROUPS"))
        if concurrent_groups is not None and concurrent_groups != "auto" \
                and (isinstance(concurrent_groups, bool)
                     or not isinstance(concurrent_groups, int)
                     or concurrent_groups < 1):
            raise ValueError(f"concurrent_groups must be an int >= 1, "
                             f"'auto', or None, got {concurrent_groups!r}")
        if policy is None:
            policy = QosPolicy.from_env()
        elif policy == "off" or policy is False:
            policy = None
        elif not isinstance(policy, QosPolicy):
            raise ValueError(f"policy must be a QosPolicy, 'off', or "
                             f"None, got {policy!r}")
        if not isinstance(max_batch, int) or max_batch < 1:
            raise ValueError(f"max_batch must be an int >= 1, "
                             f"got {max_batch!r}")
        if max_wait_s is not None and (
                isinstance(max_wait_s, bool)
                or not isinstance(max_wait_s, (int, float))
                or not max_wait_s > 0):
            raise ValueError(f"max_wait_s must be a positive number or "
                             f"None, got {max_wait_s!r}")
        if max_pending is not None and (
                isinstance(max_pending, bool)
                or not isinstance(max_pending, int) or max_pending < 1):
            raise ValueError(f"max_pending must be an int >= 1 or None, "
                             f"got {max_pending!r}")
        if admission not in ("block", "raise"):
            raise ValueError(f"admission must be block|raise, "
                             f"got {admission!r}")
        if retry_max is None:
            retry_max = _env_int("DFFT_RETRY_MAX")
        if retry_max is not None and (
                isinstance(retry_max, bool)
                or not isinstance(retry_max, int) or retry_max < 0):
            raise ValueError(f"retry_max must be an int >= 0 or None, "
                             f"got {retry_max!r}")
        if retry_backoff_s is None:
            retry_backoff_s = _env_float("DFFT_RETRY_BACKOFF_S")
        if retry_backoff_s is None:
            retry_backoff_s = DEFAULT_RETRY_BACKOFF_S
        if (isinstance(retry_backoff_s, bool)
                or not isinstance(retry_backoff_s, (int, float))
                or retry_backoff_s < 0):
            raise ValueError(f"retry_backoff_s must be a number >= 0, "
                             f"got {retry_backoff_s!r}")
        if fallback_executor is None:
            fallback_executor = os.environ.get(
                "DFFT_FALLBACK_EXECUTOR", "matmul")
        fallback_executor = fallback_executor.strip()
        if fallback_executor in ("", "0", "none"):
            fallback_executor = ""
        for bad in ("batch", "donate", "in_spec", "out_spec"):
            if bad in plan_kw:
                raise ValueError(f"{bad!r} is owned by the queue; do not "
                                 f"pass it in plan_kw")
        self.mesh = mesh
        self.kind = kind
        self.max_batch = max_batch
        self.donate = bool(donate)
        self.max_wait_s = None if max_wait_s is None else float(max_wait_s)
        self.max_pending = max_pending
        self.admission = admission
        self._retry_max = retry_max          # None = legacy dispatch
        self._retry_backoff = float(retry_backoff_s)
        self._fallback_executor = fallback_executor
        self.concurrent_groups = concurrent_groups
        self.policy = policy
        self.plan_kw = dict(plan_kw)
        self._lock = threading.RLock()
        # Admission waiters park here; notified whenever a flush or an
        # expiry frees queue depth.
        self._space = threading.Condition(self._lock)
        # (shape, dtype str, direction[, tenant]) -> list of _Req (the
        # tenant element exists only on QoS-armed queues).
        self._pending: dict[tuple, list[_Req]] = {}
        # Group-formation stamps: key -> (sequence, perf_counter at
        # formation). The sequence is the policy-free FIFO drain order
        # (documented contract: oldest formed group flushes first); the
        # timestamp feeds the QoS starvation clock. Popped with the
        # group.
        self._order = itertools.count()
        self._formed: dict[tuple, tuple[int, float]] = {}
        # concurrent_groups="auto": modeled width per plan tuple.
        self._auto_widths: dict[tuple, int] = {}
        # Flush-progress sequence — bumped whenever a flush pops groups.
        # The live monitor's stall watchdog compares it across samples;
        # a plain int bump keeps the disarmed hot path byte-identical.
        self._flush_seq = 0
        # DFFT_MONITOR=interval[,path] arms a live sampler per queue
        # (docs/OBSERVABILITY.md "Live monitoring & health");
        # DFFT_MONITOR_DIR=dir arms one too, streaming into the shared
        # fleet directory as monitor-<host>-<pid>.jsonl (docs/
        # OBSERVABILITY.md "Fleet view & load generation"). With both
        # unset the queue carries no monitor and takes no hook anywhere.
        self._monitor = None
        # DFFT_SHADOW_RATE=p[,seed] arms the numerics plane (docs/
        # OBSERVABILITY.md "Numerics plane"): shadow-sampled accuracy
        # audits against a memoized exact reference plan plus
        # non-finite sentinels with quarantine. Unset ⇒ None, and the
        # serving path takes zero numerics branches — byte-identical
        # behavior and HLO (pinned by tests/test_a2r_numerics.py).
        self._numerics = _numerics.NumericsPlane.from_env()
        # plan-tuple key[:3] -> exact reference plan (or None when the
        # reference cannot build — audits for that tuple are skipped).
        self._shadow_plans: dict[tuple, Any] = {}
        # Streaming drain-loop state (docs/SERVING_QOS.md "Streaming
        # scheduler & wave preemption"): serve()/stop() manage the
        # persistent loop; _arrival wakes it (set by submit only while
        # streaming is armed — the disarmed submit path is one flag
        # check away from byte-identical); _wave_stats carries the
        # occupancy accounting (also armed, flush-mode, on monitored
        # queues so the idle-fraction baseline exists).
        self._streaming = False
        self._serve_thread: threading.Thread | None = None
        self._serve_stop = threading.Event()
        self._drain_on_stop = True
        self._arrival = threading.Event()
        self._wave_stats: _WaveStats | None = None
        if (os.environ.get("DFFT_MONITOR", "").strip() not in ("", "0")
                or os.environ.get("DFFT_MONITOR_DIR", "").strip()):
            from .monitor import Monitor

            self._monitor = Monitor.from_env(self)
            if self._monitor is not None:
                self._monitor.start()
        if self._monitor is not None:
            self._wave_stats = _WaveStats(self.kind)
        if streaming:
            self.serve()

    # ------------------------------------------------------------ intake

    def _planner(self):
        from . import api

        return (api.plan_dft_r2c_3d if self.kind == "r2c"
                else api.plan_dft_c2c_3d)

    def _plan(self, key: tuple, batch: int | None, donate: bool,
              executor: str | None = None):
        # QoS-armed group keys carry the tenant as a 4th element; the
        # plan identity is the first three (tenancy never changes what
        # a plan compiles to).
        shape, dtype, direction = key[:3]
        kw = dict(self.plan_kw, direction=direction, batch=batch,
                  donate=donate)
        if executor is not None:
            kw["executor"] = executor  # the degraded-mode rebuild
        if dtype is not None:
            kw["dtype"] = dtype
        return self._planner()(shape, self.mesh, **kw)

    def _admit(self, deadline_s: float | None) -> None:
        """Bounded-depth admission gate (caller holds the queue lock;
        ``Condition.wait`` releases it while parked). ``"raise"`` sheds
        immediately; ``"block"`` parks until a flush/expiry frees depth,
        bounded by the request's own ``deadline_s`` when it has one."""
        if self.max_pending is None:
            return
        start = time.perf_counter()
        while (sum(len(g) for g in self._pending.values())
               >= self.max_pending):
            if self.admission == "raise":
                if _metrics._enabled:
                    _metrics.inc("serving_rejected", kind=self.kind)
                raise QueueFull(
                    f"queue depth is at max_pending={self.max_pending} "
                    f"(admission='raise'); shed or await pending results")
            timeout = None
            if deadline_s is not None:
                timeout = deadline_s - (time.perf_counter() - start)
                if timeout <= 0:
                    if _metrics._enabled:
                        _metrics.inc("serving_rejected", kind=self.kind)
                    raise DeadlineExceeded(
                        waited_s=time.perf_counter() - start,
                        deadline_s=deadline_s, stage="admission")
            self._space.wait(timeout)

    def _quota_admit(self, tenant: str, deadline_s: float | None) -> None:
        """Token-bucket admission gate of one QoS-armed submit (called
        outside the queue lock — a quota park must not block peers).
        ``admission="raise"`` sheds an over-quota submit with
        :class:`..qos.QuotaExceeded`; ``"block"`` parks until the
        tenant's bucket can cover it, bounded by the request's own
        deadline (overrun -> :class:`DeadlineExceeded`,
        ``stage="admission"``, counted as the tenant's deadline miss)."""
        pol = self.policy
        start = time.perf_counter()
        while True:
            wait = pol.admit(tenant)
            if wait <= 0:
                return
            if self.admission == "raise":
                if _metrics._enabled:
                    _metrics.inc("serving_rejected", kind=self.kind)
                    _metrics.inc("serving_tenant_quota_shed",
                                 kind=self.kind, tenant=tenant)
                pol.note_shed(tenant)
                raise QuotaExceeded(tenant, wait)
            if deadline_s is not None:
                waited = time.perf_counter() - start
                if waited + wait > deadline_s:
                    if _metrics._enabled:
                        _metrics.inc("serving_rejected", kind=self.kind)
                        _metrics.inc("serving_tenant_deadline_misses",
                                     kind=self.kind, tenant=tenant)
                    pol.note_miss(tenant)
                    raise DeadlineExceeded(
                        waited_s=waited, deadline_s=deadline_s,
                        stage="admission")
            time.sleep(wait)

    def submit(self, x, *, direction: int = FORWARD,
               scale: Scale = Scale.NONE,
               deadline_s: float | None = None,
               tenant: str | None = None) -> Handle:
        """Enqueue one transform of ``x`` (the plan's unbatched input
        shape: the 3D world for c2c / forward r2c, the half-spectrum
        world for backward r2c). Returns immediately; the group executes
        at ``max_batch``, on :meth:`flush`, or on ``result()``.

        ``deadline_s`` bounds this request's total queue time: a
        request that has not begun executing within it is cancelled —
        its handle raises :class:`DeadlineExceeded` with the queue-wait
        breakdown — while its group's survivors stay queued.

        ``tenant`` names the request's owner (docs/SERVING_QOS.md).
        With a :class:`..qos.QosPolicy` armed it must be a registered
        tenant (``None`` maps to the implicit ``default`` tenant) and
        the policy's quota/fairness machinery applies; without a policy
        it is an accounting label only (``serving_tenant_*`` metrics +
        span attribute) and changes no behavior."""
        if deadline_s is not None and (
                isinstance(deadline_s, bool)
                or not isinstance(deadline_s, (int, float))
                or not deadline_s > 0):
            raise ValueError(f"deadline_s must be a positive number or "
                             f"None, got {deadline_s!r}")
        if tenant is not None and not isinstance(tenant, str):
            raise ValueError(f"tenant must be a string or None, "
                             f"got {tenant!r}")
        pol = self.policy
        tname = tenant
        if pol is not None:
            tname = pol.resolve(tenant).name
            pol.note_submit(tname)
        tracing = tracing_enabled()
        recording = tracing or _metrics._enabled
        rid = next(_REQ_IDS) if recording else None
        ttag = f":tenant={tname}" if tname is not None else ""
        with _span(f"serve_submit[{rid}{ttag}]", tracing):
            shape, dtype, x = self._coerce(x, direction)
            key = (shape, dtype, direction)
            if pol is not None:
                key = key + (tname,)
                self._quota_admit(tname, deadline_s)
            handle = Handle(queue=self)
            handle._key = key
            if recording:
                handle._req_id = rid
                handle._enqueued = time.perf_counter()
            if _metrics._enabled:
                _metrics.inc("serving_submits", kind=self.kind)
                if tname is not None:
                    _metrics.inc("serving_tenant_submits",
                                 kind=self.kind, tenant=tname)
            with self._lock:
                self._admit(deadline_s)
                group = self._pending.setdefault(key, [])
                first = not group
                if first:
                    self._formed[key] = (next(self._order),
                                         time.perf_counter())
                req = _Req(x, handle, scale, tenant=tname)
                if self._streaming and handle._enqueued is None:
                    # The wave scheduler's admit-to-dispatch latency
                    # (and its realtime-SLO acceptance gate) needs the
                    # enqueue stamp even with the recorder off — the
                    # deadline-timer precedent.
                    handle._enqueued = time.perf_counter()
                if pol is not None and handle._enqueued is None:
                    # The QoS ledger's wait/starvation clocks need the
                    # enqueue stamp even with the recorder off (the
                    # deadline-timer precedent: behavior, not telemetry).
                    handle._enqueued = time.perf_counter()
                if deadline_s is not None:
                    # The deadline clock needs the enqueue stamp even
                    # with the recorder off (behavior, not telemetry).
                    if handle._enqueued is None:
                        handle._enqueued = time.perf_counter()
                    req.deadline_s = float(deadline_s)
                    req.expires = handle._enqueued + req.deadline_s
                    t = threading.Timer(req.deadline_s, self._expire,
                                        (key,))
                    t.daemon = True
                    t.start()
                group.append(req)
                full = len(group) >= self.max_batch
                if self._streaming:
                    # The drain loop owns ALL dispatch while streaming:
                    # wake it instead of auto-flushing from the submit
                    # thread (a full group is simply ripe for the next
                    # wave; _next_wave splits it at max_batch).
                    full = False
                    self._arrival.set()
                if self.max_wait_s is not None:
                    # The deadline clock runs even with the recorder
                    # off: the timer callback judges the group's oldest
                    # enqueue stamp against max_wait_s.
                    if handle._enqueued is None:
                        handle._enqueued = time.perf_counter()
                    if first and not full:
                        t = threading.Timer(self.max_wait_s,
                                            self._deadline_flush, (key,))
                        t.daemon = True
                        t.start()
                if _metrics._enabled:
                    _metrics.set_gauge(
                        "serving_queue_depth",
                        float(sum(len(g) for g in self._pending.values())),
                        kind=self.kind)
        if full:
            self.flush(key, reason="full")
        return handle

    def _deadline_flush(self, key: tuple) -> None:
        """Timer callback of the ``max_wait_s`` deadline: flush ``key``'s
        group iff its oldest request has aged past the deadline. A group
        that already flushed (and possibly re-formed with younger
        requests) is left alone — the newer generation armed its own
        timer when it formed."""
        with self._lock:
            group = self._pending.get(key)
            if not group:
                return
            oldest = group[0].handle._enqueued
            if oldest is None or (time.perf_counter() - oldest
                                  < self.max_wait_s * 0.999):
                return
        self.flush(key, reason="deadline")

    def _fail_expired(self, req: _Req, now: float) -> None:
        """Cancel one expired request: DeadlineExceeded (with the
        queue-wait breakdown) onto its handle, a retroactive
        ``serve_expire`` span, and the ``serving_expired`` counter."""
        waited = (now - req.handle._enqueued
                  if req.handle._enqueued is not None else 0.0)
        if _metrics._enabled:
            _metrics.inc("serving_expired", kind=self.kind)
            if req.tenant is not None:
                _metrics.inc("serving_tenant_deadline_misses",
                             kind=self.kind, tenant=req.tenant)
        if self.policy is not None and req.tenant is not None:
            self.policy.note_miss(req.tenant)
        if (tracing_enabled() and req.handle._req_id is not None
                and req.handle._enqueued is not None):
            record_span(f"serve_expire[{req.handle._req_id}]",
                        req.handle._enqueued, now)
        req.handle._fail(DeadlineExceeded(
            waited_s=waited, deadline_s=req.deadline_s or 0.0,
            stage="queued"))

    def _expire(self, key: tuple) -> None:
        """Deadline timer callback: cancel every expired request of
        ``key``'s group; survivors stay queued (their own timers run)."""
        now = time.perf_counter()
        with self._lock:
            group = self._pending.get(key)
            if not group:
                return
            live = [r for r in group
                    if r.expires is None or r.expires > now]
            if len(live) == len(group):
                return
            expired = [r for r in group if r not in live]
            if live:
                self._pending[key] = live
            else:
                self._pending.pop(key, None)
                self._formed.pop(key, None)
            for r in expired:
                self._fail_expired(r, now)
            if _metrics._enabled:
                _metrics.set_gauge(
                    "serving_queue_depth",
                    float(sum(len(g) for g in self._pending.values())),
                    kind=self.kind)
            self._space.notify_all()

    def _coerce(self, x, direction: int):
        """Validate/convert one request array against the plan family's
        unbatched input contract; returns (world shape, dtype str, x)."""
        plan0 = self._plan_for_probe(jnp.shape(x), direction)
        x = jnp.asarray(x, dtype=plan0.in_dtype)
        if x.shape != plan0.in_shape:
            raise ValueError(
                f"queue expects the unbatched plan input shape "
                f"{plan0.in_shape}, got {x.shape}")
        return plan0.shape, str(jnp.dtype(plan0.dtype)), x

    def _plan_for_probe(self, in_shape, direction: int):
        """The unbatched plan for a request of ``in_shape`` — resolves
        the world shape for r2c backward (half-spectrum input) without
        duplicating that geometry here. Memoized by the plan cache."""
        if len(in_shape) != 3:
            raise ValueError(
                f"submit takes one unbatched 3D input, got {in_shape}")
        shape = tuple(int(s) for s in in_shape)
        if self.kind == "r2c" and direction != FORWARD:
            # Half-spectrum input [n0, n1, n2h]: the world's true n2 is
            # ambiguous from n2h alone (n2 = 2*(n2h-1) or 2*n2h-1), so
            # backward r2c groups must declare it via plan_kw["shape"]—
            # or simply use submit_plan with an explicit plan.
            raise ValueError(
                "backward r2c coalescing needs the real-space world "
                "shape; use CoalescingQueue(kind='r2c') for forward "
                "only, or submit(plan, x) with an explicit c2r plan")
        return self._plan((shape, self.plan_kw.get("dtype"), direction),
                          None, False)

    # ------------------------------------------------------------- flush

    def pending(self) -> int:
        """Number of requests waiting to be coalesced."""
        with self._lock:
            return sum(len(g) for g in self._pending.values())

    def _tenant_of(self, key: tuple) -> str | None:
        """The owning tenant of a group key (QoS-armed keys carry it as
        the 4th element); None on the anonymous tier."""
        return key[3] if len(key) > 3 else None

    def _drain_order(self, now: float) -> list[tuple]:
        """Pending group keys in drain order (caller holds the lock).
        Policy-free: the documented FIFO — oldest formed group first,
        by the explicit formation sequence (never dict-iteration
        order). With a policy: strict class > weighted-fair within a
        class > starvation promotion (:meth:`..qos.QosPolicy
        .order_groups`)."""
        keys = [k for k, g in self._pending.items() if g]
        if self.policy is None:
            return sorted(keys,
                          key=lambda k: self._formed.get(k, (0, 0.0))[0])
        infos = []
        for k in keys:
            g = self._pending[k]
            _, t0 = self._formed.get(k, (0, now))
            oldest = min((r.handle._enqueued for r in g
                          if r.handle._enqueued is not None), default=t0)
            infos.append({"key": k, "tenant": self._tenant_of(k),
                          "n": len(g), "age_s": max(0.0, now - oldest)})
        ordered = self.policy.order_groups(infos,
                                           max_wait_s=self.max_wait_s)
        return [i["key"] for i in ordered]

    def _concurrent_chunks(self, groups: list, ncc: int) -> list:
        """Partition drained groups into the cohorts one concurrent
        dispatch merges. Policy-free: plain runs of ``ncc``. With a
        policy: class-compatible runs — a realtime group never rides a
        batch cohort (:meth:`..qos.QosPolicy.concurrent_chunks`), and
        drain order = schedule order, so higher classes keep the
        earliest waves."""
        if self.policy is None:
            return [groups[i:i + ncc]
                    for i in range(0, len(groups), ncc)]
        by_key = {k: g for k, g in groups}
        infos = [{"key": k, "tenant": self._tenant_of(k), "n": len(g)}
                 for k, g in groups]
        return [[(i["key"], by_key[i["key"]]) for i in chunk]
                for chunk in self.policy.concurrent_chunks(infos, ncc)]

    def flush(self, key: tuple | None = None, *,
              reason: str = "manual", limit: int | None = None) -> int:
        """Execute pending groups (or just ``key``'s) as batched
        programs; returns the number of transforms dispatched. Handles
        resolve to async in-flight arrays (result() blocks on device).
        ``reason`` tags the flight-recorder spans/metrics with what
        triggered the flush: ``full`` (a group reached max_batch),
        ``manual`` (this call), ``result`` (a caller's await outran
        the coalescer), or ``deadline`` (the oldest request aged past
        ``max_wait_s``).

        Drain order is the documented FIFO (oldest formed group first)
        on the policy-free tier, the QoS order with a policy armed.
        ``limit`` bounds this call to at most that many transforms —
        groups are taken in drain order and the last one splits at the
        boundary (the remainder stays queued under its original
        formation stamp); ``None`` drains everything. With the retry
        machinery armed (``retry_max=``/``DFFT_RETRY_MAX``), flush
        errors are recovered per docs/ROBUSTNESS.md and surface only
        through the failed requests' handles; without it a failed group
        fails every handle and re-raises (the legacy contract)."""
        if limit is not None and (
                isinstance(limit, bool) or not isinstance(limit, int)
                or limit < 1):
            raise ValueError(f"limit must be an int >= 1 or None, "
                             f"got {limit!r}")
        done = 0
        recording = tracing_enabled() or _metrics._enabled
        flushed_at = (time.perf_counter()
                      if recording or self.policy is not None
                      or self._wave_stats is not None else 0.0)
        with self._lock:
            keys = ([key] if key is not None
                    else self._drain_order(flushed_at))
            groups = []
            budget = limit
            for k in keys:
                g = self._pending.get(k)
                if not g:
                    continue
                if budget is not None and len(g) > budget:
                    # Split at the drain quantum: the taken slice
                    # executes now, the remainder keeps the group's
                    # formation stamp (and its own deadline timers).
                    self._pending[k] = g[budget:]
                    groups.append((k, g[:budget]))
                    budget = 0
                    break
                self._pending.pop(k)
                self._formed.pop(k, None)
                groups.append((k, g))
                if budget is not None:
                    budget -= len(g)
                    if budget <= 0:
                        break
            if groups:
                self._flush_seq += 1  # stall-watchdog progress marker
                self._space.notify_all()  # admission waiters: depth fell
            ncc = self._concurrent_width(groups)
            if ncc > 1 and len(groups) > 1:
                # Multi-group flush: drain up to concurrent_groups
                # compatible-mesh groups into ONE scheduled dispatch
                # (schedule_concurrent interleaves their stage DAGs so
                # one group's t2 wire hides under another's FFTs).
                for chunk in self._concurrent_chunks(groups, ncc):
                    done += self._execute_concurrent(
                        chunk, reason=reason, flushed_at=flushed_at)
            else:
                for k, group in groups:
                    done += self._execute_group(k, group, reason=reason,
                                                flushed_at=flushed_at)
            ws = self._wave_stats
            if ws is not None and groups:
                # Baseline occupancy: one flush cohort = one wave, so
                # the monitor's idle-fraction comparison against the
                # streaming loop is like-for-like.
                outs = [r.handle._value for _, g in groups for r in g
                        if r.handle._event.is_set()
                        and r.handle._error is None]
                ws.note_wave(width=len(groups), t_dispatch=flushed_at,
                             outputs=outs,
                             waits=self._admit_waits(groups, flushed_at))
            if recording and _metrics._enabled and groups:
                _metrics.set_gauge(
                    "serving_queue_depth",
                    float(sum(len(g) for g in self._pending.values())),
                    kind=self.kind)
        return done

    def _concurrent_width(self, groups: list) -> int:
        """The concurrent-flush width of this drain: the configured
        int, or — ``concurrent_groups="auto"`` (the model-driven
        default) — the width in 1..4 whose
        :func:`..plan_logic.model_concurrent_seconds` price yields the
        highest modeled transforms/s for the groups at hand. Plans
        below the IR tier (no stage graph / logic skeleton) and any
        modeling failure fall back to sequential flushes; widths are
        memoized per plan tuple (the steady-state queue re-flushes the
        same group pattern)."""
        ncc = self.concurrent_groups
        if ncc is None:
            return 1
        if ncc != "auto":
            return ncc
        if len(groups) < 2:
            return 1
        try:
            plans, counts = [], []
            for k, g in groups[:4]:
                p = self._plan(k, len(g) if len(g) > 1 else None, False)
                if p.graph is None or p.logic is None:
                    return 1
                plans.append(p)
                counts.append(len(g))
            memo_key = tuple(id(p) for p in plans)
            hit = self._auto_widths.get(memo_key)
            if hit is not None:
                return hit
            from .tuner import tune_concurrent_width

            # Measured width tournament (DFFT_WIDTH_TOURNAMENT,
            # docs/SERVING_QOS.md): time the live plan tuple's prefixes as
            # real interleaved programs and rank widths by measured
            # throughput — wisdom-keyed (kind="concurrent"), so a
            # stored winner replays with zero timing executions and a
            # fixed wisdom file makes the width deterministic. Returns
            # None when disarmed; the analytic model below then prices
            # the widths as before.
            measured = tune_concurrent_width(plans, counts)
            if measured is not None:
                if len(self._auto_widths) >= 64:
                    self._auto_widths.pop(next(iter(self._auto_widths)))
                self._auto_widths[memo_key] = measured
                return measured
            from .calibrate import model_correction
            from .explain import _model_shape_itemsize, device_profile
            from .plan_logic import model_concurrent_seconds

            hw = device_profile()
            triples = []
            for p in plans:
                shape, itemsize = _model_shape_itemsize(p)
                triples.append((p.logic, shape, itemsize))
            # Measured realized-overlap feedback: explain's overlap
            # attribution persists measured/model hide ratios under
            # this key, so auto-width pricing learns from dispatch
            # reality (1.0 until a measurement lands).
            hide_corr = model_correction("concurrent_hide")
            best_w, best_rate = 1, -1.0
            for w in range(1, len(plans) + 1):
                m = model_concurrent_seconds(
                    triples[:w], hbm_gbps=hw["hbm_gbps"],
                    wire_gbps=hw["wire_gbps"],
                    launch_seconds=hw["launch_seconds"],
                    dcn_gbps=hw.get("dcn_gbps"),
                    hide_correction=hide_corr)
                secs = m["concurrent_seconds"]
                rate = sum(counts[:w]) / secs if secs > 0 else 0.0
                if rate > best_rate:
                    best_w, best_rate = w, rate
            if len(self._auto_widths) >= 64:
                self._auto_widths.pop(next(iter(self._auto_widths)))
            self._auto_widths[memo_key] = best_w
            return best_w
        except Exception:  # noqa: BLE001 — the model must never block
            return 1       # a drain; sequential is always correct

    def _live(self, group: list) -> list:
        """Expiry filter of one popped group: fail every request whose
        deadline passed while it waited; return the survivors."""
        now = time.perf_counter()
        live = []
        for r in group:
            if r.expires is not None and r.expires <= now:
                self._fail_expired(r, now)
            else:
                live.append(r)
        return live

    def _note_waits(self, group: list, flushed_at: float,
                    tracing: bool) -> None:
        """Close every request's queue-wait interval: enqueue -> flush.
        Retroactive (record_span) because only now is the wait's end —
        and the batch it coalesced into — known. QoS-armed queues also
        feed the per-tenant wait histogram and the policy's SLO
        ledger."""
        pol = self.policy
        for r in group:
            if r.handle._enqueued is None:
                continue
            wait = max(0.0, flushed_at - r.handle._enqueued)
            if tracing and r.handle._req_id is not None:
                record_span(f"serve_wait[{r.handle._req_id}]",
                            r.handle._enqueued, flushed_at)
            if _metrics._enabled:
                _metrics.observe("serving_wait_seconds", wait,
                                 kind=self.kind)
                if r.tenant is not None:
                    _metrics.observe("serving_tenant_wait_seconds", wait,
                                     kind=self.kind, tenant=r.tenant)
            if pol is not None and r.tenant is not None:
                pol.note_wait(r.tenant, wait)

    def _admit_waits(self, groups: list, now: float) -> list:
        """Per-request admit-to-dispatch intervals of one wave as
        ``[(tenant class, seconds), ...]`` — the wave-stats sample that
        backs the realtime-latency SLO gate. Requests without an
        enqueue stamp (recorder, policy, deadline, and streaming all
        disarmed) contribute nothing."""
        pol = self.policy
        waits = []
        for k, g in groups:
            klass = None
            if pol is not None:
                try:
                    klass = pol.resolve(self._tenant_of(k)).klass
                except Exception:  # noqa: BLE001 — unregistered tenant
                    klass = None
            for r in g:
                if r.handle._enqueued is not None:
                    waits.append((klass,
                                  max(0.0, now - r.handle._enqueued)))
        return waits

    def _execute_concurrent(self, chunk: list, *, reason: str,
                            flushed_at: float) -> int:
        """Execute up to ``concurrent_groups`` popped groups as ONE
        interleaved device program (:func:`..stagegraph
        .schedule_concurrent`): each group becomes its (batched) plan,
        the plans' stage DAGs merge into one staggered schedule, and
        group A's t2 collectives issue while group B's t0/t3 FFTs run.
        Falls back to per-group execution — which owns the full
        fault-tolerance chain — whenever the chunk cannot be scheduled
        (plans below the IR tier, mesh mismatch, scheduling or
        execution failure). Concurrent dispatch never donates (plans
        build donate=False; the per-group path keeps the queue's
        donation policy on fallback... and fallback after a failed
        execution re-plans, so no buffer was consumed)."""
        live_groups = [(k, self._live(g)) for k, g in chunk]
        live_groups = [(k, g) for k, g in live_groups if g]

        def sequential() -> int:
            return sum(self._execute_group(k, g, reason=reason,
                                           flushed_at=flushed_at)
                       for k, g in live_groups)

        if len(live_groups) < 2:
            return sequential()
        tracing = tracing_enabled()
        try:
            from .stagegraph import schedule_concurrent

            plans = [self._plan(k, len(g) if len(g) > 1 else None, False)
                     for k, g in live_groups]
            if any(p.graph is None for p in plans):
                return sequential()
            cp = schedule_concurrent(plans)
        except Exception:  # noqa: BLE001 — per-group path owns failures
            return sequential()
        for _, g in live_groups:
            self._note_waits(g, flushed_at, tracing)
        inputs = []
        from .api import _spec_divides

        for plan, (_, g) in zip(plans, live_groups):
            x = g[0].x if len(g) == 1 else jnp.stack([r.x for r in g])
            if plan.in_sharding is not None and _spec_divides(
                    plan.in_sharding.mesh, plan.in_sharding.spec, x.shape):
                x = jax.device_put(x, plan.in_sharding)
            inputs.append(x)
        b_total = sum(len(g) for _, g in live_groups)
        tnames = [self._tenant_of(k) for k, _ in live_groups]
        ttag = ("" if all(t is None for t in tnames) else
                ":tenants=" + "+".join(t or "-" for t in tnames))
        tag = f"{self.kind}:g{len(live_groups)}:b{b_total}:{reason}{ttag}"
        try:
            with _span(f"serve_flush[concurrent:{tag}]", tracing):
                ys = cp(*inputs)
        except Exception:  # noqa: BLE001 — no handle touched yet: the
            return sequential()  # per-group path re-runs with its own
        #                          retry/degraded/bisect chain.
        from .ops.executors import apply_scale

        g_outs = []
        for plan, y, (_, g) in zip(plans, ys, live_groups):
            outs = []
            for i, r in enumerate(g):
                out = y if len(g) == 1 else y[i]
                if r.scale != Scale.NONE:
                    out = apply_scale(out, r.scale, plan.world_size)
                outs.append(out)
            g_outs.append(outs)
        if self._numerics is not None:
            # Sentinel sweep before ANY handle resolves: a non-finite
            # output must not leak through the concurrent fast path.
            # The per-group fallback owns the retry -> exact-rebuild ->
            # bisect quarantine chain, so route the whole chunk there.
            try:
                for (_, g), outs in zip(live_groups, g_outs):
                    self._guard_nonfinite(g, outs, tag, tracing)
            except _numerics.NonFiniteResult:
                return sequential()
        for plan, (k, g), outs in zip(plans, live_groups, g_outs):
            gt = self._tenant_of(k)
            for r, out in zip(g, outs):
                r.handle._set(out)
            if _metrics._enabled:
                _metrics.inc("serving_flushes", kind=self.kind)
                _metrics.inc("serving_flush_reasons", kind=self.kind,
                             reason=reason)
                _metrics.inc("serving_transforms", float(len(g)),
                             kind=self.kind)
                _metrics.observe("serving_batch_size", float(len(g)),
                                 kind=self.kind)
                if gt is not None:
                    _metrics.inc("serving_tenant_transforms",
                                 float(len(g)), kind=self.kind,
                                 tenant=gt)
            if self.policy is not None and gt is not None:
                self.policy.account_drain(gt, len(g))
        if _metrics._enabled:
            _metrics.inc("serving_concurrent_dispatches", kind=self.kind)
            _metrics.inc("serving_concurrent_transforms", float(b_total),
                         kind=self.kind)
            _metrics.observe("serving_concurrent_groups",
                             float(len(live_groups)), kind=self.kind)
        if self._numerics is not None:
            for plan, (k, g), outs in zip(plans, live_groups, g_outs):
                self._shadow_audit(k, plan, g, outs, tag, tracing)
        return b_total

    def _execute_group(self, key: tuple, group: list, *,
                       reason: str = "manual",
                       flushed_at: float = 0.0) -> int:
        group = self._live(group)
        if not group:
            return 0
        b = len(group)
        tname = self._tenant_of(key)
        tracing = tracing_enabled()
        tag = (f"{self.kind}:b{b}:{reason}"
               + (f":tenant={tname}" if tname is not None else ""))
        if tracing or _metrics._enabled or self.policy is not None:
            self._note_waits(group, flushed_at, tracing)
        if self._retry_max is None:
            # Legacy dispatch: one try, a failure fails every co-batched
            # handle and re-raises (byte-identical to the pre-robustness
            # tier — no classification, no recovery).
            try:
                with _span(f"serve_flush[{tag}]", tracing):
                    self._run_group(key, group, tag, tracing)
            except Exception as e:  # noqa: BLE001 — fail the handles
                for r in group:
                    r.handle._fail(e)
                raise
        else:
            with _span(f"serve_flush[{tag}]", tracing):
                self._dispatch_ft(key, group, tag, tracing)
        if _metrics._enabled:
            _metrics.inc("serving_flushes", kind=self.kind)
            _metrics.inc("serving_flush_reasons", kind=self.kind,
                         reason=reason)
            _metrics.inc("serving_transforms", float(b), kind=self.kind)
            _metrics.observe("serving_batch_size", float(b), kind=self.kind)
            if tname is not None:
                _metrics.inc("serving_tenant_transforms", float(b),
                             kind=self.kind, tenant=tname)
        if self.policy is not None and tname is not None:
            self.policy.account_drain(tname, b)
        return b

    def _run_group(self, key: tuple, group: list, tag: str, tracing: bool,
                   *, executor: str | None = None):
        """One execution attempt of ``group`` (singleton direct, >1
        batched through a ``batch=B`` plan). Resolves every handle on
        success and returns the plan used; on failure raises with NO
        handle touched — the dispatcher owns the failure policy.
        ``executor`` overrides the queue's executor (the degraded-mode
        rebuild)."""
        from .api import execute

        if len(group) == 1:
            r = group[0]
            with _span(f"serve_plan[{tag}]", tracing):
                plan = self._plan(key, None, False, executor=executor)
            with _span(f"serve_execute[{tag}]", tracing):
                out = execute(plan, r.x, scale=r.scale)
                if self._numerics is not None:
                    self._guard_nonfinite(group, [out], tag, tracing)
                if executor is not None:
                    r.handle.degraded = True
                r.handle._set(out)
            if self._numerics is not None and executor is None:
                self._shadow_audit(key, plan, group, [out], tag,
                                   tracing)
            return plan
        with _span(f"serve_plan[{tag}]", tracing):
            plan = self._plan(key, len(group), self.donate,
                              executor=executor)
        stacked = jnp.stack([r.x for r in group])
        from .api import _spec_divides

        if plan.in_sharding is not None and _spec_divides(
                plan.in_sharding.mesh, plan.in_sharding.spec,
                stacked.shape):
            # Pre-place the stack on the plan's input layout; uneven
            # worlds let the chain's own pad/crop shard it (the
            # alloc_local rule).
            stacked = jax.device_put(stacked, plan.in_sharding)
        with _span(f"serve_execute[{tag}]", tracing):
            y = plan(stacked)
            outs = []
            for i, r in enumerate(group):
                out = y[i]
                if r.scale != Scale.NONE:
                    from .ops.executors import apply_scale

                    out = apply_scale(out, r.scale, plan.world_size)
                outs.append(out)
            if self._numerics is not None:
                self._guard_nonfinite(group, outs, tag, tracing)
            for r, out in zip(group, outs):
                if executor is not None:
                    r.handle.degraded = True
                r.handle._set(out)
        if self._numerics is not None and executor is None:
            self._shadow_audit(key, plan, group, outs, tag, tracing)
        return plan

    # --------------------------------------------------- numerics plane

    def _guard_nonfinite(self, group: list, outs: list, tag: str,
                         tracing: bool) -> None:
        """Armed-only non-finite sentinel at the output boundary
        (docs/OBSERVABILITY.md "Numerics plane"). The *input* is
        checked first so a caller's NaN/Inf is distinguished from
        codec/executor damage: a non-finite input is counted
        (``numerics_nonfinite{site=input}``) and its output delivered
        as-is — the caller's problem, never retried. A non-finite
        output from a finite input raises :class:`~.numerics
        .NonFiniteResult` BEFORE any handle resolves, so the fault
        chain (retry → exact-rebuild → bisect) quarantines the
        poisoned request while finite cohort members complete
        bit-correct."""
        for r, out in zip(group, outs):
            ikind = _numerics.nonfinite_kind(r.x)
            if ikind is not None:
                with _span("numerics_nonfinite[input]", tracing):
                    _numerics.record_nonfinite("input", ikind)
                continue
            okind = _numerics.nonfinite_kind(out)
            if okind is not None:
                with _span("numerics_nonfinite[output]", tracing):
                    _numerics.record_nonfinite("output", okind)
                raise _numerics.NonFiniteResult(
                    f"non-finite ({okind}) output from a finite input "
                    f"[{tag}]", site="output", kind=okind)

    def _shadow_plan(self, key: tuple):
        """The memoized exact reference plan for ``key``'s plan tuple:
        same geometry and direction, exact wire (``wire_dtype="none"``
        pins the uncompressed exchange regardless of DFFT_WIRE_DTYPE),
        exact executor tier, fusion and tuner off — the yardstick every
        shadow audit compares against. Unbuildable references memoize
        None (that tuple's audits are skipped, counted as failures)."""
        pk = key[:3]
        if pk in self._shadow_plans:
            return self._shadow_plans[pk]
        shape, dtype, direction = pk
        kw = dict(self.plan_kw, direction=direction, batch=None,
                  donate=False, wire_dtype="none", fuse=False,
                  tune="off")
        for tiered in ("mm_precision", "mm_complex",
                       "max_roundtrip_err"):
            kw.pop(tiered, None)
        if dtype is not None:
            kw["dtype"] = dtype
        ex = kw.pop("executor", None)
        if ex:
            from .ops.executors import (MM_EXECUTOR_BASES,
                                        split_executor, split_fuse,
                                        tiered_name)

            base, _tier, _cmode = split_executor(split_fuse(ex)[0])
            kw["executor"] = (tiered_name(base, "highest")
                              if base in MM_EXECUTOR_BASES else base)
        try:
            plan = self._planner()(shape, self.mesh, **kw)
        except Exception:  # noqa: BLE001 — no reference, no audit
            plan = None
        self._shadow_plans[pk] = plan
        return plan

    def _plan_label(self, key: tuple, plan) -> str:
        """The ledger bucket label of a plan tuple — readable, stable
        across processes (the fleet pools on it)."""
        import numpy as np

        from .plan_logic import resolve_wire_dtype

        sh = "x".join(str(n) for n in key[0])
        try:
            # Meshless (single-device) plans never exchange — no wire
            # codec runs, whatever DFFT_WIRE_DTYPE says.
            if getattr(plan, "mesh", None) is None:
                wd = "exact"
            else:
                wd = resolve_wire_dtype(plan.options.wire_dtype) or "exact"
        except Exception:  # noqa: BLE001
            wd = "exact"
        d = "fwd" if getattr(plan, "forward", True) else "inv"
        return (f"{self.kind}:{sh}:{np.dtype(plan.dtype).name}:{d}:"
                f"{plan.executor}:{wd}")

    def _admitted_err(self, plan) -> float:
        """The plan's admitted error budget — the seeded plan-time
        figures the tuner's ONE-budget admission rule consumed
        (docs/TUNING.md): wire-compression + executor-tier roundtrip.
        The drift verdict compares realized error against this."""
        from .ops.executors import executor_roundtrip_error
        from .parallel.exchange import wire_roundtrip_error
        from .plan_logic import resolve_wire_dtype

        err = 0.0
        try:
            wd = (None if getattr(plan, "mesh", None) is None
                  else resolve_wire_dtype(plan.options.wire_dtype))
            if wd:
                err += wire_roundtrip_error(plan.dtype, wd)
        except Exception:  # noqa: BLE001 — unknown codec: no budget
            pass
        try:
            err += executor_roundtrip_error(plan.executor, plan.dtype)
        except Exception:  # noqa: BLE001 — bare label: no tier budget
            pass
        return err

    def _shadow_audit(self, key: tuple, plan, group: list, outs: list,
                      tag: str, tracing: bool) -> None:
        """Shadow-sampled accuracy audit: picked requests re-execute
        through the memoized exact reference plan after their primary
        execution resolved; the realized L2-relative error lands in the
        process-global ledger against the plan's admitted budget.
        Shadow work is charged traffic (the owning tenant's bucket pays
        for the re-execution, like recovery work — docs/SERVING_QOS
        .md); audit failures are counted, never raised — telemetry
        must not fail serving."""
        ns = self._numerics
        picked = [(r, out) for r, out in zip(group, outs)
                  if ns.pick()]
        if not picked:
            return
        from .api import execute

        label = self._plan_label(key, plan)
        tenant = self._tenant_of(key)
        for r, out in picked:
            _numerics.record_sampled()
            try:
                ref = self._shadow_plan(key)
                if ref is None:
                    _numerics.record_audit_failure()
                    continue
                with _span(f"shadow_audit[{tag}]", tracing):
                    yref = execute(ref, r.x, scale=r.scale)
                    realized = _numerics.realized_error(out, yref)
                _numerics.record_audit(
                    label, tenant, realized, self._admitted_err(plan),
                    _numerics.drift_floor(
                        getattr(yref, "dtype", plan.dtype)))
            except Exception:  # noqa: BLE001 — telemetry never fails
                _numerics.record_audit_failure()
                continue
            if self.policy is not None and r.tenant:
                self.policy.charge(r.tenant, 1)

    # ------------------------------------------------- fault tolerance

    def _dispatch_ft(self, key: tuple, group: list, tag: str,
                     tracing: bool) -> None:
        """The fault-tolerant dispatch chain (docs/ROBUSTNESS.md):

        1. the group, with transient retries (:meth:`_attempt`);
        2. the whole group rebuilt on the degraded executor;
        3. batched groups only: per-request bisection — each request
           re-runs unbatched (retries + its own degraded fallback), so
           one poisoned request fails alone while its cohort completes.

        Failures surface ONLY through the failed requests' handles;
        this method never raises — a caller awaiting an unrelated
        handle must not catch another tenant's error."""
        try:
            self._attempt(key, group, tag, tracing)
            return
        except Exception as err:  # noqa: BLE001 — classified upstream
            last = err
        if self._try_degraded(key, group, tag, tracing):
            return
        if len(group) > 1:
            for i, r in enumerate(group):
                sub = [r]
                subtag = f"{tag}:iso{i}"
                try:
                    self._attempt(key, sub, subtag, tracing)
                    continue
                except Exception as e:  # noqa: BLE001
                    iso_err = e
                if self._try_degraded(key, sub, subtag, tracing):
                    continue
                if _metrics._enabled:
                    _metrics.inc("serving_isolated_failures",
                                 kind=self.kind)
                r.handle._fail(iso_err)
            return
        group[0].handle._fail(last)

    def _attempt(self, key: tuple, group: list, tag: str, tracing: bool,
                 *, executor: str | None = None):
        """One logical execution with the bounded transient-retry loop:
        a failure classified transient (:func:`..faults.classify`)
        retries up to ``retry_max`` times under exponential backoff
        (``serve_retry[<tag>:a<N>]`` spans, ``serving_retries``
        counter); deterministic failures raise immediately."""
        delay = self._retry_backoff
        attempt = 0
        while True:
            try:
                if attempt == 0:
                    return self._run_group(key, group, tag, tracing,
                                           executor=executor)
                with _span(f"serve_retry[{tag}:a{attempt}]", tracing):
                    return self._run_group(key, group, tag, tracing,
                                           executor=executor)
            except Exception as e:  # noqa: BLE001 — classified below
                if (attempt >= self._retry_max
                        or _faults.classify(e) != "transient"):
                    raise
            attempt += 1
            if _metrics._enabled:
                _metrics.inc("serving_retries", kind=self.kind)
            if self.policy is not None and group and group[0].tenant:
                # Recovery work is traffic: the retry re-executes the
                # whole group on the owning tenant's behalf, so its
                # bucket pays for it (docs/SERVING_QOS.md).
                self.policy.charge(group[0].tenant, len(group))
            if delay > 0:
                time.sleep(delay)
            delay *= 2

    def _try_degraded(self, key: tuple, group: list, tag: str,
                      tracing: bool) -> bool:
        """Degraded-mode executor fallback: rebuild the group's plan on
        ``fallback_executor`` (matmul-DFT by default — it never touches
        the XLA fft thunk) and execute. Resolved handles are stamped
        ``degraded``; the fallback is recorded under its own wisdom
        annotation so replay is intentional, never sticky. Returns True
        on success; False (never raises) when disabled, pointless (the
        queue already runs the fallback executor), or itself failing."""
        fb = self._fallback_executor
        if not fb or self.plan_kw.get("executor") == fb:
            return False
        try:
            with _span(f"serve_degraded[{tag}:{fb}]", tracing):
                plan = self._run_group(key, group, tag, tracing,
                                       executor=fb)
        except Exception:  # noqa: BLE001 — the chain's last resort failed
            return False
        if _metrics._enabled:
            _metrics.inc("serving_degraded", float(len(group)),
                         kind=self.kind, executor=fb)
        if self.policy is not None and group and group[0].tenant:
            # The degraded rebuild re-ran the whole group: charge the
            # owning tenant's bucket (recovery work is traffic).
            self.policy.charge(group[0].tenant, len(group))
        self._annotate_degraded(key, plan, len(group))
        return True

    def _annotate_degraded(self, key: tuple, plan, b: int) -> None:
        """Append the executor fallback to the wisdom store under a
        ``{"annotation": "degraded"}``-marked key: the event is durable
        and inspectable (``report wisdom``), but a normal wisdom lookup
        or :func:`warm_pool` never matches the annotated key — replay
        of the degraded winner stays intentional, not sticky.
        Best-effort telemetry, never fatal."""
        try:
            import math

            from . import tuner

            shape, dtype, direction = key[:3]
            if isinstance(self.mesh, int):
                ndev = self.mesh
            elif self.mesh is None:
                ndev = 1
            else:
                ndev = int(math.prod(self.mesh.devices.shape))
            wkey = tuner.wisdom_key(
                kind=self.kind, shape=shape,
                dtype=dtype if dtype is not None else plan.dtype,
                direction=direction, ndev=ndev,
                batch=None if b == 1 else b)
            wkey["annotation"] = "degraded"
            tuner.record_wisdom(
                wkey,
                tuner.Candidate(
                    decomposition=plan.decomposition,
                    algorithm=plan.options.algorithm,
                    executor=plan.executor,
                    overlap_chunks=int(plan.options.overlap_chunks or 1)),
                0.0)
        except Exception:  # noqa: BLE001 — annotation is telemetry
            pass

    # ------------------------------------------------- streaming waves

    def serve(self, *, poll_s: float = 0.05) -> "CoalescingQueue":
        """Start the persistent streaming drain loop (docs/
        SERVING_QOS.md "Streaming scheduler & wave preemption") — the
        PR 18 lift from discrete ``flush()`` cohorts to a continuous
        scheduler. A daemon thread keeps a rolling interleaved program
        in flight: each iteration assembles the next *wave* (up to the
        concurrent width's groups, in QoS drain order, with realtime
        wave-preemption), dispatches it asynchronously, and only then
        blocks on the *previous* wave — so newly formed groups are
        admitted into the next wave of an already-running schedule
        instead of waiting for the current dispatch, and under heavy
        traffic the device never waits for the queue.

        While streaming, submit's ``max_batch`` auto-flush is routed to
        the loop (a wakeup instead of a dispatch from the submit
        thread); explicit ``flush()``/``result()`` still work and stay
        byte-identical on non-streaming queues (pinned). Idempotent;
        also armed at construction by ``streaming=True`` or
        ``DFFT_SERVE_STREAMING=1``. ``poll_s`` bounds the idle wakeup
        (arrivals wake the loop immediately via an event)."""
        with self._lock:
            if self._serve_thread is not None \
                    and self._serve_thread.is_alive():
                return self
            if self._wave_stats is None:
                self._wave_stats = _WaveStats(self.kind)
            self._serve_stop = threading.Event()
            self._drain_on_stop = True
            self._streaming = True
            t = threading.Thread(target=self._serve_loop,
                                 args=(float(poll_s),),
                                 name="dfft-serve", daemon=True)
            self._serve_thread = t
            t.start()
        return self

    def stop(self, *, drain: bool = True,
             timeout: float | None = 30.0) -> None:
        """Stop the streaming drain loop. ``drain=True`` (default) lets
        the loop dispatch every pending group and retire its in-flight
        waves first — a clean shutdown loses no admitted work;
        ``drain=False`` exits after the wave in flight (pending groups
        stay queued and the queue remains fully usable in flush mode).
        Idempotent; ``serve()`` may re-arm afterwards."""
        with self._lock:
            t = self._serve_thread
            self._streaming = False  # new submits stop waking the loop
            if t is None:
                return
            self._drain_on_stop = bool(drain)
            self._serve_stop.set()
        self._arrival.set()  # wake a loop parked on an empty queue
        if t.is_alive():
            t.join(timeout)
        with self._lock:
            if self._serve_thread is t:
                self._serve_thread = None

    def _serve_loop(self, poll_s: float) -> None:
        """The persistent drain loop body. ``prev`` holds the previous
        wave's async outputs: dispatching wave k+1 BEFORE blocking on
        wave k is what keeps the device busy across the admission
        point — at most two waves are in flight, and the barrier wait
        (where newly arrived work coalesces into the next wave) happens
        under the younger wave's device time."""
        stop = self._serve_stop
        prev: list = []
        while True:
            stopping = stop.is_set()
            if stopping and not self._drain_on_stop:
                break
            wave = self._next_wave()
            if wave is None:
                if stopping:
                    break  # drained: nothing pending, nothing admitted
                self._arrival.clear()
                # Re-check under the cleared event so an arrival racing
                # the clear is never lost (it set the event after the
                # probe; wait() then returns immediately).
                if self.pending() == 0:
                    self._arrival.wait(poll_s)
                continue
            groups, waits = wave
            t_dispatch = time.perf_counter()
            outs = self._execute_wave(groups, flushed_at=t_dispatch)
            ws = self._wave_stats
            if ws is not None:
                ws.note_wave(width=len(groups), t_dispatch=t_dispatch,
                             outputs=outs, waits=waits)
            # Admission point: retire the PREVIOUS wave. The current
            # one keeps executing while we block here, and every
            # arrival during this wait lands in the next wave.
            if prev:
                try:
                    jax.block_until_ready(prev)
                except Exception:  # noqa: BLE001 — failed handles
                    pass           # already carry their errors
            prev = outs
        if prev:
            try:
                jax.block_until_ready(prev)
            except Exception:  # noqa: BLE001
                pass

    def _next_wave(self):
        """Assemble the next admission wave under the lock (streaming
        loop only): up to the concurrent width's groups, popped in QoS
        drain order, with **wave-level preemption** — a realtime-class
        group is guaranteed a slot in THIS wave, bumping later-class
        members when the width is saturated; bumped groups stay queued
        with their formation stamps (and starvation clocks) intact, so
        they sit at the front of the next drain order, and the
        preempting tenant's quota is charged (:meth:`..qos.QosPolicy
        .preempt_wave`). Groups larger than ``max_batch`` split at the
        boundary exactly like ``flush(limit=)``. Returns ``(groups,
        waits)`` or ``None`` when nothing is pending."""
        now = time.perf_counter()
        with self._lock:
            keys = self._drain_order(now)
            if not keys:
                return None
            probe = [(k, self._pending[k]) for k in keys
                     if self._pending.get(k)]
            if not probe:
                return None
            width = max(1, self._concurrent_width(probe[:4]))
            take = [k for k, _ in probe[:width]]
            if self.policy is not None and len(probe) > width:
                infos = [{"key": k, "tenant": self._tenant_of(k),
                          "n": len(g)} for k, g in probe]
                admit, bumped, _charges = self.policy.preempt_wave(
                    infos, width)
                take = [i["key"] for i in admit]
                if bumped:
                    ws = self._wave_stats
                    if ws is not None:
                        ws.note_preemption(
                            len(bumped), sum(i["n"] for i in bumped))
            groups = []
            for k in take:
                g = self._pending.get(k)
                if not g:
                    continue
                if len(g) > self.max_batch:
                    # Split at the batch quantum: the remainder keeps
                    # the group's formation stamp (and its deadline
                    # timers), exactly the flush(limit=) discipline.
                    self._pending[k] = g[self.max_batch:]
                    g = g[:self.max_batch]
                else:
                    self._pending.pop(k)
                    self._formed.pop(k, None)
                groups.append((k, g))
            if not groups:
                return None
            self._flush_seq += 1  # stall-watchdog progress marker
            self._space.notify_all()  # admission waiters: depth fell
            waits = self._admit_waits(groups, now)
            if _metrics._enabled:
                _metrics.set_gauge(
                    "serving_queue_depth",
                    float(sum(len(g) for g in self._pending.values())),
                    kind=self.kind)
        return groups, waits

    def _execute_wave(self, groups: list, *, flushed_at: float) -> list:
        """Dispatch one assembled wave OUTSIDE the queue lock (submits
        must never wait on a dispatch): the flush dispatch body at wave
        granularity — multi-group waves interleave through
        :meth:`_execute_concurrent` (which owns the sequential
        fallback), singletons take :meth:`_execute_group` and its
        retry/degraded/bisect chain. Returns the wave's resolved async
        output arrays (the loop's admission barrier blocks on them).

        A fault mid-wave never wedges the loop: the legacy
        (``retry_max=None``) dispatch re-raises after failing its
        group's handles, but a streaming wave has no caller to re-raise
        to — the error is absorbed, any handle the abort left
        unresolved is failed with it, and the wave's remaining chunks
        (and the loop) keep going."""
        if len(groups) > 1:
            chunks = self._concurrent_chunks(groups, len(groups))
        else:
            chunks = [groups]
        for chunk in chunks:
            try:
                if len(chunk) > 1:
                    self._execute_concurrent(chunk, reason="stream",
                                             flushed_at=flushed_at)
                else:
                    k, g = chunk[0]
                    self._execute_group(k, g, reason="stream",
                                        flushed_at=flushed_at)
            except Exception as e:  # noqa: BLE001 — see docstring
                for k, g in chunk:
                    for r in g:
                        if not r.handle._event.is_set():
                            r.handle._fail(e)
        outs = []
        for _, g in groups:
            for r in g:
                h = r.handle
                if h._event.is_set() and h._error is None \
                        and h._value is not None:
                    outs.append(h._value)
        return outs

    # -------------------------------------------------------------- warm

    def warm(self, shapes, *, batches=(None,),
             direction: int = FORWARD) -> int:
        """Preplan (and thereby plan-cache) the given world shapes at the
        given batch sizes — the explicit-tuple warm path (the wisdom-
        driven one is :func:`warm_pool`). Returns plans built."""
        n = 0
        for shape in shapes:
            for b in batches:
                self._plan((tuple(int(s) for s in shape),
                            self.plan_kw.get("dtype"), direction), b, False)
                n += 1
        return n

    def close(self) -> None:
        """Drain the queue (stopping the streaming loop with a full
        drain when armed, plus a final manual flush) and tear down the
        attached live monitor's sampler thread, if any. Idempotent;
        the queue stays usable afterwards (close is a quiesce point,
        not a poison pill)."""
        self.stop(drain=True)
        self.flush(reason="manual")
        m = self._monitor
        if m is not None:
            m.stop()
        ws = self._wave_stats
        if ws is not None:
            ws.stop()


def warm_pool(mesh=None, top_n: int = 4, *, path: str | None = None,
              max_batch: int | None = None) -> list:
    """Preplan the top-N problem tuples of the persistent wisdom store.

    The PR 4 wisdom store keys measured winners by exactly the serving
    tuple — (kind, shape, dtype, direction[, batch], mesh, hardware) —
    so the hottest entries ARE the shapes a fresh serving process will
    see first. This reads the store (``DFFT_WISDOM`` / the compile-cache
    default), keeps entries matching the current platform/x64/device
    count (``mesh``: a Mesh, int device count, or None = single device;
    annotated entries — the degraded-fallback records — are never
    replayed), orders newest-first, and builds each of the top ``top_n``
    through ``tune="wisdom"`` — replaying the stored winner with zero
    timing executions into the memoized plan cache. ``max_batch``
    additionally preplans each tuple at that batch size, warming the
    coalescer's full-group program too. Returns the built plans.

    Stale tuples (a stored winner the current build can no longer plan)
    are skipped, never fatal — but no longer silently: skips are
    counted into the ``serving_warm_pool_skipped`` metric and one
    stderr summary line; ``KeyboardInterrupt``/``SystemExit`` always
    propagate (a Ctrl-C during warm-up must stop the process, not the
    pool loop)."""
    import math

    from . import api, tuner

    entries = tuner._read_wisdom(path if path is not None
                                 else tuner.default_wisdom_path())
    if isinstance(mesh, int):
        ndev = mesh
    elif mesh is None:
        ndev = 1
    else:
        ndev = int(math.prod(mesh.devices.shape))
    platform = jax.default_backend()
    x64 = bool(jax.config.jax_enable_x64)

    def eligible(entry) -> bool:
        k = entry.get("key", {})
        return (k.get("kind") in ("c2c", "r2c")
                and k.get("ndev") == ndev
                and k.get("platform") == platform
                and k.get("x64") == x64
                and k.get("layouts") is None
                and not k.get("annotation"))  # degraded records: never
        #                                       preplanned (not sticky)

    ranked = sorted((e for e in entries.values() if eligible(e)),
                    key=lambda e: str(e.get("recorded_at", "")),
                    reverse=True)[:max(0, int(top_n))]
    plans = []
    skipped = 0
    on = tracing_enabled()
    for entry in ranked:
        k = entry["key"]
        plan_fn = (api.plan_dft_r2c_3d if k["kind"] == "r2c"
                   else api.plan_dft_c2c_3d)
        batches = {k.get("batch")}
        if max_batch is not None:
            batches.add(int(max_batch))
        for b in sorted(batches, key=lambda v: (v is not None, v)):
            # One flight-recorder span per preplanned build (same naming
            # scheme as serve_plan), so a pool warm-up is attributable
            # on the merged timeline next to the serving spans.
            name = (f"warm_plan[{k['kind']}:"
                    f"{'x'.join(str(s) for s in k['shape'])}"
                    + (f":b{b}" if b else "") + "]") if on else ""
            try:
                with _span(name, on):
                    plans.append(plan_fn(
                        tuple(k["shape"]), mesh, direction=k["direction"],
                        dtype=jnp.dtype(k["dtype"]), tune="wisdom", batch=b))
            except (KeyboardInterrupt, SystemExit):
                raise  # never eaten: interrupts must stop the process
            except Exception:  # noqa: BLE001 — a stale tuple never
                skipped += 1   # blocks the rest of the pool
                continue
    if skipped:
        print(f"serving: warm_pool skipped {skipped} stale wisdom "
              f"tuple(s) of {len(ranked)} eligible", file=sys.stderr)
        if _metrics._enabled:
            _metrics.inc("serving_warm_pool_skipped", float(skipped))
    if _metrics._enabled:
        _metrics.set_gauge("serving_warm_pool_plans", float(len(plans)))
    return plans
