"""Stage-graph chain IR: declarative t0..t3 graphs + ONE compiler/executor.

Every chain builder in :mod:`.parallel` used to hand-thread the same
four concerns — the t0..t3 stage taxonomy with its trace spans, the
ceil-pad/crop geometry, the exchange transport (with overlap-K chunk
interleaving and the hierarchical leg pipeline), and the jit wrapper
(donation, sharding pins) — through near-duplicate code, so every new
feature cost one edit per builder. This module is the refactor the
ROADMAP names: builders now *emit a small declarative stage graph*
(nodes: stage kind, axes, transport, codec, chunking, dependencies) and
ONE compiler executes it. DaggerFFT (arXiv 2601.12209) is the model for
the second half: a stage graph is a schedulable DAG, so N *independent*
transforms' graphs can be merged into one interleaved program
(:func:`schedule_concurrent`) that issues transform A's t2 collectives
while transform B's t0/t3 FFTs run — cross-transform exchange hiding,
the same play the overlap-K chunk pipeline makes within one transform.

The compiler has two backends sharing the node vocabulary:

- :func:`compile_fused` — the end-to-end jitted program (one
  ``shard_map`` + jit): exchanges fuse with their downstream compute
  through :func:`..parallel.exchange.exchange_overlapped` (per-chunk
  interleaving, leg pipelining, wire codecs all live there).
- :func:`compile_staged` — the separately-jitted per-stage pipeline of
  the timing harness (:func:`..utils.timing.time_staged`), stage
  boundaries carrying global arrays, exchanges through
  :func:`..parallel.exchange.exchange_chunked`.

**Migration safety net** (the PR 3 discipline): the graphs the migrated
builders emit compile *byte-identical* StableHLO to the pre-migration
hand-threaded chains — pinned against on-disk captures in
``tests/test_a2m_stagegraph.py`` / ``tests/_hlo_pin_cases.py``. The op
interpreter therefore mirrors the historical trace order exactly (pads
as no-op-when-even ``_pad_axis`` calls, spans entered even around
skipped packs, midpoint ``axis_index`` offsets emitted at their
original trace position via node *factories*).

Not yet migrated (the named remainder): ``parallel/ddslab.py`` (the
double-double tier). The ``parallel/bricks.py`` brick-I/O edges migrated
in PR 18: their wrapper program is now a declarative
:class:`BrickEdgeGraph` compiled by :func:`compile_brick_io` (pinned
byte-identical to the pre-refactor hand-threaded jit in api.py).

See ``docs/ARCHITECTURE.md`` ("Stage-graph chain IR") for the node
schema, the compiler contract, and the concurrent-scheduler policy.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from .utils.trace import add_trace, trace_stages

__all__ = [
    "STAGE_KINDS",
    "LocalNode",
    "ExchangeNode",
    "StageGraph",
    "StagedStage",
    "StagedGraph",
    "BrickEdgeGraph",
    "local_node",
    "exchange_node",
    "compile_fused",
    "plan_fusion",
    "compile_staged",
    "compile_brick_io",
    "apply_multiplier",
    "apply_midpoint",
    "graph_of",
    "ConcurrentPlan",
    "WaveSchedule",
    "schedule_concurrent",
    "schedule_waves",
]

#: The stage-kind registry — every node kind a chain graph may carry.
#: ``docs/ARCHITECTURE.md``'s stage-node table must be a superset of
#: this tuple (pinned by the conftest-tier lint in
#: ``tests/test_a2m_stagegraph.py``).
STAGE_KINDS = ("t0", "t1", "t2", "t2a", "t2b", "t_mid", "t3")

#: Kinds an :class:`ExchangeNode` may carry (⊂ STAGE_KINDS).
EXCHANGE_KINDS = ("t2", "t2a", "t2b")


# --------------------------------------------------------------- nodes

@dataclass(frozen=True)
class LocalNode:
    """One local (per-shard, collective-free) stage of a chain.

    ``ops`` is the declarative op list the interpreter executes in
    order: ``("fft", axes, forward)``, ``("r2c", axis)``,
    ``("c2r", n, axis)``, ``("pad", axis, to)``, ``("crop", axis, to)``,
    ``("pack", axis, to)`` (a pad the ragged transport skips — dense
    algorithms ship ceil-padded splits, alltoallv ships true slices),
    or ``("call", fn)`` (an opaque per-shard callable — the midpoint
    escape hatch).

    ``fuse=True`` marks this node as the *per-chunk compute* of the
    exchange node immediately before it: the fused compiler hands it to
    :func:`..parallel.exchange.exchange_overlapped` as the ``compute``
    callback (pipelined under the exchange at overlap-K), the staged
    compiler gives it its own stage jit. ``factory`` (exclusive with
    ``ops``) is a zero-arg callable invoked at trace time right before
    the exchange issues, returning the compute callable — the hook that
    lets midpoint closures emit their per-shard wavenumber offsets
    (``lax.axis_index``) at the exact trace position the hand-threaded
    chains did. ``takes_bounds`` adds the chunk's static (lo, hi)
    bounds along the exchange's chunk axis to the call.
    """

    kind: str
    name: str
    ops: tuple = ()
    fuse: bool = False
    takes_bounds: bool = False
    factory: Callable | None = field(default=None, compare=False)

    def __post_init__(self):
        if self.kind not in STAGE_KINDS:
            raise ValueError(
                f"unknown stage kind {self.kind!r}; use one of "
                f"{STAGE_KINDS}")


@dataclass(frozen=True)
class ExchangeNode:
    """One global-transpose (t2-tier) stage of a chain.

    ``mesh_axis`` is the mesh axis name (or the (dcn, ici) tuple of the
    hierarchical transport), ``parts`` its total extent, ``split`` /
    ``concat`` the tiled-all-to-all axes, ``chunk_axis`` the bystander
    axis overlap-K chunks along. The transport algorithm, wire codec,
    and K live on the graph (one chain = one transport policy); per-node
    ``axis_sizes`` carries the hierarchical (dcn, ici) factor pair.
    """

    kind: str
    name: str
    mesh_axis: Any
    parts: int
    split: int
    concat: int
    chunk_axis: int
    axis_sizes: tuple | None = None

    def __post_init__(self):
        if self.kind not in EXCHANGE_KINDS:
            raise ValueError(
                f"exchange node kind must be one of {EXCHANGE_KINDS}, "
                f"got {self.kind!r}")


def local_node(kind: str, name: str, *ops, fuse: bool = False,
               takes_bounds: bool = False,
               factory: Callable | None = None) -> LocalNode:
    return LocalNode(kind=kind, name=name, ops=tuple(ops), fuse=fuse,
                     takes_bounds=takes_bounds, factory=factory)


def exchange_node(kind: str, name: str, *, mesh_axis, parts: int,
                  split: int, concat: int, chunk_axis: int,
                  axis_sizes: tuple | None = None) -> ExchangeNode:
    return ExchangeNode(kind=kind, name=name, mesh_axis=mesh_axis,
                        parts=int(parts), split=split, concat=concat,
                        chunk_axis=chunk_axis, axis_sizes=axis_sizes)


@dataclass(frozen=True)
class StageGraph:
    """One fused chain as a declarative stage DAG (a linear chain with
    each exchange's fused compute as its dependent node — the general
    DAG form shows up when :func:`schedule_concurrent` merges graphs).

    ``pre`` / ``post`` are the jit-boundary global ops (ceil pads in,
    crops out); ``in_pspec`` / ``out_pspec`` the (batch-adjusted) chain
    endpoint layouts; ``even`` pins them as jit shardings (uneven
    chains move the constraint inside, after the pad). ``executor`` is
    a registered executor name or a callable; ``platform`` feeds the
    ragged transport's CPU-mirror routing. ``meta`` carries planner
    metadata (shape, batch, direction, decomposition) for scheduling
    and pricing — never read by the compiler itself.
    """

    mesh: Mesh
    nodes: tuple
    in_pspec: P
    out_pspec: P
    pre: tuple = ()
    post: tuple = ()
    even: bool = True
    donate: bool = False
    algorithm: str = "alltoall"
    platform: str | None = None
    wire_dtype: str | None = None
    overlap_chunks: int = 1
    executor: Any = "xla"
    meta: dict = field(default_factory=dict, compare=False)

    def validate(self) -> "StageGraph":
        nodes = self.nodes
        for i, n in enumerate(nodes):
            if isinstance(n, ExchangeNode):
                if i + 1 >= len(nodes) or not isinstance(
                        nodes[i + 1], LocalNode) or not nodes[i + 1].fuse:
                    raise ValueError(
                        f"exchange node {n.name!r} must be followed by "
                        f"its fused compute node (LocalNode(fuse=True))")
            elif n.fuse and (i == 0 or not isinstance(
                    nodes[i - 1], ExchangeNode)):
                raise ValueError(
                    f"fused node {n.name!r} has no preceding exchange")
        return self

    @property
    def stage_kinds(self) -> tuple:
        return tuple(n.kind for n in self.nodes)


# ------------------------------------------------------ op interpreter

def _tree_pad(x, axis: int, to: int):
    from .parallel.exchange import _pad_axis

    return jax.tree_util.tree_map(
        lambda u: _pad_axis(u, axis, to), x)


def _tree_crop(x, axis: int, to: int):
    from .parallel.exchange import _crop_axis

    return jax.tree_util.tree_map(
        lambda u: _crop_axis(u, axis, to), x)


class _Interp:
    """The shared op interpreter: executor resolution done once, ops
    applied in declared order. Tree-generic for pads/crops (the staged
    pencil pipeline carries the dd tier's (hi, lo) pytree); ``fft``
    hands the whole value to the executor (a callable executor owns its
    own pytree handling, exactly as the hand-threaded stages did)."""

    def __init__(self, executor, algorithm: str):
        from .ops.executors import get_c2r, get_executor, get_r2c

        if isinstance(executor, str):
            self.ex = get_executor(executor)
            self._r2c = get_r2c(executor)
            self._c2r = get_c2r(executor)
        else:
            self.ex = executor
            self._r2c = self._c2r = None
        self.algorithm = algorithm

    def run(self, ops, y, bounds=None):
        for op in ops:
            tag = op[0]
            if tag == "fft":
                y = self.ex(y, op[1], op[2])
            elif tag == "pack":
                if self.algorithm != "alltoallv":
                    y = _tree_pad(y, op[1], op[2])
            elif tag == "pad":
                y = _tree_pad(y, op[1], op[2])
            elif tag == "crop":
                y = _tree_crop(y, op[1], op[2])
            elif tag == "r2c":
                y = self._r2c(y, op[1])
            elif tag == "c2r":
                y = self._c2r(y, op[1], op[2])
            elif tag == "call":
                y = op[1](y, *bounds) if bounds is not None else op[1](y)
            else:
                raise ValueError(f"unknown stage op {tag!r}")
        return y


def apply_multiplier(u: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Pointwise spectral multiply without dtype surprises: a real
    multiplier casts to the payload's component dtype (f64 constants
    must not promote a c64 chain to c128), a complex one to the payload
    dtype. ``m`` is rank-3 (spatial) and broadcasts over any leading
    batch axis."""
    if jnp.issubdtype(m.dtype, jnp.complexfloating):
        return u * m.astype(u.dtype)
    rdt = jnp.float64 if u.dtype == jnp.dtype(jnp.complex128) else jnp.float32
    return u * m.astype(rdt)


def apply_midpoint(u, multiplier: Callable, grids: tuple):
    """The ``t_mid`` pointwise stage: generate the wavenumber-diagonal
    multiplier over the shard/chunk's global index ``grids`` and apply
    it, under the ``t_mid_pointwise`` sub-span (mapped to no stage key
    by :func:`..utils.trace.stage_key` — nested inside ``t_mid``, never
    double-counted). The ONE place operator chains emit the span, so
    migrated builders never hand-thread it."""
    with add_trace("t_mid_pointwise"):
        return apply_multiplier(u, multiplier(*grids))


# -------------------------------------------------------- fusion pass

def plan_fusion(graph: StageGraph) -> dict:
    """The Pallas fusion tier's graph-level gate (docs/TUNING.md,
    "Pallas fusion tier"; docs/ARCHITECTURE.md, "Fusion pass").

    Fusion is requested by the ``:fuse`` executor flag
    (:func:`..ops.executors.split_fuse`) and activates only when the
    whole-graph preconditions hold; the returned dict is stored as
    ``graph.meta["fusion"]`` for the explain layer:

    - ``requested``: the executor carries the flag;
    - ``active``: requested and every gate passed — the compiler routes
      each exchange through :func:`_run_fused_site`;
    - ``reasons``: the failed gates when requested but inactive
      (``no_wire_codec`` — there is no codec stream to fuse into the
      stage kernels; ``overlap_k`` — chunked exchanges pipeline through
      :func:`..parallel.exchange.exchange_overlapped`, whose per-chunk
      compute the mega-kernels cannot subdivide; ``no_exchange``), each
      counted into the ``fusion_fallback`` series with site ``graph``;
    - ``sites``: per-exchange trace-time records (sender/receiver route
      and kernel-fallback reason), filled in as the program traces.

    An inactive gate NEVER errors: the graph compiles exactly as the
    unfused executor would (byte-identical program — the flag itself
    changes nothing until every gate passes)."""
    from .ops.executors import split_fuse

    info: dict = {"requested": False, "active": False, "reasons": (),
                  "sites": {}}
    ex = graph.executor
    if not isinstance(ex, str):
        return info
    try:
        _, fused = split_fuse(ex)
    except ValueError:
        return info
    if not fused:
        return info
    info["requested"] = True
    reasons = []
    if graph.wire_dtype is None:
        reasons.append("no_wire_codec")
    if graph.overlap_chunks != 1:
        reasons.append("overlap_k")
    if not any(isinstance(n, ExchangeNode) for n in graph.nodes):
        reasons.append("no_exchange")
    info["reasons"] = tuple(reasons)
    info["active"] = not reasons
    if reasons:
        from .ops.pallas_fuse import record_fusion_fallback

        for r in reasons:
            record_fusion_fallback("graph", r)
    return info


def _fused_senders(nodes: tuple) -> tuple[dict, set]:
    """Map each exchange index to the maximal run of non-fused local
    nodes immediately before it (its *sender* — the stage whose output
    feeds the wire), plus the set of consumed indices the main walk
    skips. A fused node or another exchange breaks the run, so pair-(b)
    receivers (t_mid) re-encode with an EMPTY sender."""
    sender_of: dict = {}
    consumed: set = set()
    for i, n in enumerate(nodes):
        if not isinstance(n, ExchangeNode):
            continue
        js: list = []
        j = i - 1
        while (j >= 0 and isinstance(nodes[j], LocalNode)
               and not nodes[j].fuse and j not in consumed):
            js.append(j)
            j -= 1
        js.reverse()
        sender_of[i] = tuple(js)
        consumed |= set(js)
    return sender_of, consumed


def _run_fused_site(y, graph: StageGraph, interp: "_Interp",
                    n: ExchangeNode, nxt: LocalNode,
                    senders: tuple, site: dict):
    """Trace one fused exchange site: sender stage + wire encode (ONE
    Pallas mega-kernel when the stage is a single kernel-eligible FFT
    along the split axis), the collective shipping the *wire parts*
    through :func:`..parallel.exchange.exchange_uneven` with the codec
    already applied, then wire decode + receiver stage (the receiver
    mega-kernel, or the pure decode + interpreter/factory compute).

    Bit-parity with the unfused transport: the codec calls, part
    shipping, and pad/crop geometry are exactly what the in-transport
    wire path performs (dense transports ship ceil-padded splits whose
    quantized tail zeros decode to zero — the same bytes the transport
    itself would have produced); the mega-kernels' mirrors route
    through the unfused executor + codec, so any kernel fallback is
    value-identical by construction. Trace attribution moves the codec
    out of the exchange span into the stage spans it fused with —
    that is the observable win, documented in docs/OBSERVABILITY.md."""
    from .ops import pallas_fuse
    from .parallel.exchange import wire_codec

    codec = wire_codec(graph.wire_dtype)
    sender_ops = tuple(op for nd in senders for op in nd.ops)
    packs = [op for op in sender_ops if op[0] == "pack"]
    core = [op for op in sender_ops if op[0] != "pack"]
    run_pack = graph.algorithm != "alltoallv"
    packs_noop = all(
        (not run_pack) or y.shape[op[1]] == op[2] for op in packs)

    kernel_reason = None
    if not senders:
        site["sender"] = "encode_only"
    elif (len(core) == 1 and core[0][0] == "fft"
          and len(core[0][1]) == 1 and packs_noop):
        site["sender"] = "kernel"
    else:
        if len(core) == 1 and core[0][0] == "fft" and len(core[0][1]) > 1:
            kernel_reason = "multi_axis"
        elif not packs_noop:
            kernel_reason = "uneven_pack"
        else:
            kernel_reason = "ops"
        site["sender"] = kernel_reason

    if site["sender"] == "kernel":
        fft_node = next(nd for nd in senders
                        if any(op[0] == "fft" for op in nd.ops))
        with add_trace(fft_node.name):
            parts = pallas_fuse.fused_fft_encode(
                y, fft_axis=core[0][1][0], forward=core[0][2],
                tile_axis=n.split, tiles=n.parts,
                wire_dtype=graph.wire_dtype,
                site=f"{n.name}:sender")
        payload_dtype = y.dtype
    else:
        if kernel_reason is not None:
            pallas_fuse.record_fusion_fallback(
                f"{n.name}:sender", kernel_reason)
        for nd in senders:
            with add_trace(nd.name):
                y = interp.run(nd.ops, y)
        payload_dtype = y.dtype
        parts = codec.encode(y, tile_axis=n.split, tiles=n.parts)

    from .parallel.exchange import exchange_uneven

    with add_trace(n.name):
        shipped = tuple(
            exchange_uneven(
                p, n.mesh_axis, split_axis=n.split, concat_axis=n.concat,
                axis_size=n.parts, algorithm=graph.algorithm,
                platform=graph.platform, axis_sizes=n.axis_sizes,
                wire_dtype=None)
            for p in parts)

    rshape = shipped[0].shape[:-1]
    rops = nxt.ops
    recv_kernel = (
        nxt.factory is None and not nxt.takes_bounds
        and 1 <= len(rops) <= 2 and rops[-1][0] == "fft"
        and len(rops[-1][1]) == 1
        and (len(rops) == 1
             or (rops[0][0] == "crop"
                 and rshape[rops[0][1]] == rops[0][2])))
    if recv_kernel:
        site["receiver"] = "kernel"
        with add_trace(nxt.name):
            y = pallas_fuse.fused_decode_fft(
                shipped, payload_dtype, fft_axis=rops[-1][1][0],
                forward=rops[-1][2], tile_axis=n.concat, tiles=n.parts,
                wire_dtype=graph.wire_dtype,
                site=f"{nxt.name}:receiver")
        return y
    site["receiver"] = ("factory" if nxt.factory is not None else "ops")
    if nxt.factory is None:
        pallas_fuse.record_fusion_fallback(f"{nxt.name}:receiver", "ops")
    with add_trace(nxt.name):
        w = codec.decode(shipped, payload_dtype, tile_axis=n.concat,
                         tiles=n.parts)
        if nxt.factory is not None:
            compute = nxt.factory()
            extent = jax.tree_util.tree_leaves(w)[0].shape[n.chunk_axis]
            return compute(w, 0, extent) if nxt.takes_bounds else compute(w)
        if nxt.takes_bounds:
            extent = jax.tree_util.tree_leaves(w)[0].shape[n.chunk_axis]
            return interp.run(nxt.ops, w, bounds=(0, extent))
        return interp.run(nxt.ops, w)


# ------------------------------------------------------ fused compiler

def compile_fused(graph: StageGraph):
    """Compile a :class:`StageGraph` into the fused end-to-end jitted
    program (the contract every fused chain builder used to hand-write):
    one ``shard_map`` over the chain's local program — non-fused local
    nodes run under their own trace span, each exchange node runs
    through :func:`..parallel.exchange.exchange_overlapped` with its
    fused successor as the per-chunk compute (overlap-K interleaving,
    leg pipelining, wire codec all inherited) — wrapped in a jit doing
    the boundary pads, the input sharding constraint, and the output
    crops, with donation and even-shape sharding pins from the graph.

    The compiled callable carries the graph as ``fn.stage_graph`` (the
    handle :func:`schedule_concurrent` and the plan layer read back)."""
    from .parallel.exchange import exchange_overlapped

    graph.validate()
    interp = _Interp(graph.executor, graph.algorithm)
    nodes = graph.nodes
    fusion = plan_fusion(graph)
    graph.meta["fusion"] = fusion
    if fusion["active"]:
        sender_of, consumed = _fused_senders(nodes)
    else:
        sender_of, consumed = {}, set()

    def local_fn(x):
        y = x
        i = 0
        while i < len(nodes):
            if i in consumed:  # sender nodes run inside their fused site
                i += 1
                continue
            n = nodes[i]
            if isinstance(n, ExchangeNode):
                nxt = nodes[i + 1]
                if fusion["active"]:
                    site = fusion["sites"].setdefault(
                        i, {"exchange": n.name})
                    y = _run_fused_site(
                        y, graph, interp, n, nxt,
                        tuple(nodes[j] for j in sender_of[i]), site)
                    i += 2
                    continue
                if nxt.factory is not None:
                    compute = nxt.factory()
                elif nxt.takes_bounds:
                    compute = (lambda v, lo, hi, _n=nxt: interp.run(
                        _n.ops, v, bounds=(lo, hi)))
                else:
                    compute = (lambda v, _n=nxt: interp.run(_n.ops, v))
                y = exchange_overlapped(
                    y, n.mesh_axis, split_axis=n.split,
                    concat_axis=n.concat, axis_size=n.parts,
                    algorithm=graph.algorithm, platform=graph.platform,
                    axis_sizes=n.axis_sizes,
                    wire_dtype=graph.wire_dtype, compute=compute,
                    compute_takes_bounds=nxt.takes_bounds,
                    overlap_chunks=graph.overlap_chunks,
                    chunk_axis=n.chunk_axis, exchange_name=n.name,
                    compute_name=nxt.name)
                i += 2
            else:
                with add_trace(n.name):
                    y = interp.run(n.ops, y)
                i += 1
        return y

    mapped = _shard_map(local_fn, mesh=graph.mesh,
                        in_specs=(graph.in_pspec,),
                        out_specs=graph.out_pspec)
    in_sh = NamedSharding(graph.mesh, graph.in_pspec)
    out_sh = NamedSharding(graph.mesh, graph.out_pspec)
    jit_kw: dict = {"donate_argnums": 0} if graph.donate else {}
    if graph.even:
        jit_kw |= {"in_shardings": in_sh, "out_shardings": out_sh}

    @functools.partial(jax.jit, **jit_kw)
    def fn(x):
        for op in graph.pre:
            x = _tree_pad(x, op[1], op[2])
        x = lax.with_sharding_constraint(x, in_sh)
        y = mapped(x)
        for op in graph.post:
            y = _tree_crop(y, op[1], op[2])
        return y

    fn.stage_graph = graph
    return fn


def graph_of(fn) -> StageGraph | None:
    """The :class:`StageGraph` a compiled chain callable carries, or
    None for chains not (yet) built through the IR — the feature-
    detection hook of the plan layer and the concurrent scheduler."""
    return getattr(fn, "stage_graph", None)


# ----------------------------------------------------- staged compiler

@dataclass(frozen=True)
class StagedStage:
    """One separately-jitted stage of a staged pipeline.

    Execution order inside the stage jit:
    ``pre`` global ops -> ``wsc_in`` sharding constraint -> the
    ``shard_map``'d body (``local`` ops, an ``exchange``, or a
    hierarchical ``leg``) over ``smap_in``/``smap_out`` -> ``post``
    global ops -> ``wsc_out``. ``pin_in``/``pin_out`` instead pin the
    boundary shardings on the jit itself (the slab-staged convention;
    the pencil/r2c pipelines constrain inside — both orders are kept
    verbatim for the HLO pins). ``jit_name`` is the ``__name__`` the
    stage function is given before jit (the lowered module's name, part
    of the byte-identity contract)."""

    kind: str
    name: str
    jit_name: str = "<lambda>"
    smap_in: Any = None
    smap_out: Any = None
    local: tuple | None = None
    exchange: dict | None = None
    leg: dict | None = None
    pre: tuple = ()
    post: tuple = ()
    wsc_in: Any = None
    wsc_out: Any = None
    pin_in: Any = None
    pin_out: Any = None

    def __post_init__(self):
        if self.kind not in STAGE_KINDS:
            raise ValueError(
                f"unknown stage kind {self.kind!r}; use one of "
                f"{STAGE_KINDS}")


@dataclass(frozen=True)
class StagedGraph:
    """A staged pipeline: the per-stage twin of :class:`StageGraph`,
    consumed by :func:`compile_staged` into the ``[(name, jit), ...]``
    stage list of the timing harness."""

    mesh: Mesh
    stages: tuple
    algorithm: str = "alltoall"
    platform: str | None = None
    wire_dtype: str | None = None
    overlap_chunks: int = 1
    executor: Any = "xla"
    meta: dict = field(default_factory=dict, compare=False)


def _leg_body(stage: StagedStage, graph: StagedGraph):
    """The hierarchical staged tier's per-leg body (K=1 only): ONE leg
    of :func:`..parallel.exchange.hierarchical_legs`, wrapped in the
    per-leg wire cast pair when the graph compresses the wire. Every
    registered codec round-trips idempotently (bf16 by value, int8 by
    its power-of-two steps), so leg-boundary decode/re-encode is
    bit-identical to the fused chain's single cast pair around both
    legs; the legs permute peer tiles and sidecar slots identically, so
    decode aligns on the axis the tiles sit on at the leg's exit
    (``tile_axis_out``)."""
    from .parallel.exchange import hierarchical_legs, wire_codec

    cfg = stage.leg
    leg_ici, leg_dcn = hierarchical_legs(
        cfg["mesh_axis"], split_axis=cfg["split"], concat_axis=cfg["concat"],
        axis_sizes=cfg["axis_sizes"])
    leg = leg_ici if cfg["which"] == "ici" else leg_dcn
    if graph.wire_dtype is None:
        return leg
    codec = wire_codec(graph.wire_dtype)
    p, split, out_ax = cfg["parts"], cfg["split"], cfg["tile_axis_out"]

    def run(u):
        parts = codec.encode(u, tile_axis=split, tiles=p)
        done = tuple(leg(w) for w in parts)
        return codec.decode(done, u.dtype, tile_axis=out_ax, tiles=p)

    return run


def compile_staged(graph: StagedGraph):
    """Compile a :class:`StagedGraph` into the traced
    ``[(name, stage_jit), ...]`` list of the per-stage timing harness
    (each stage wrapped by :func:`..utils.trace.traced_stage`, its
    underlying jit reachable via ``__wrapped__`` for the explain
    layer's per-stage lowering)."""
    from .parallel.exchange import exchange_chunked

    interp = _Interp(graph.executor, graph.algorithm)
    mesh = graph.mesh

    def build_stage(stage: StagedStage):
        def smap(f):
            return _shard_map(f, mesh=mesh, in_specs=(stage.smap_in,),
                              out_specs=stage.smap_out)

        if stage.exchange is not None:
            cfg = dict(stage.exchange)
            body = smap(lambda v: exchange_chunked(
                v, cfg["mesh_axis"], split_axis=cfg["split"],
                concat_axis=cfg["concat"], axis_size=cfg["parts"],
                algorithm=graph.algorithm,
                axis_sizes=cfg.get("axis_sizes"),
                wire_dtype=graph.wire_dtype,
                overlap_chunks=graph.overlap_chunks,
                chunk_axis=cfg["chunk_axis"],
                uneven=cfg.get("uneven", False),
                platform=graph.platform,
                **({"exchange_name": cfg["exchange_name"]}
                   if "exchange_name" in cfg else {})))
        elif stage.leg is not None:
            body = smap(_leg_body(stage, graph))
        else:
            body = smap(lambda v: interp.run(stage.local, v))

        def run(x):
            for op in stage.pre:
                x = _tree_pad(x, op[1], op[2]) if op[0] in (
                    "pad", "pack") else _tree_crop(x, op[1], op[2])
            if stage.wsc_in is not None:
                x = lax.with_sharding_constraint(
                    x, NamedSharding(mesh, stage.wsc_in))
            y = body(x)
            for op in stage.post:
                y = _tree_pad(y, op[1], op[2]) if op[0] in (
                    "pad", "pack") else _tree_crop(y, op[1], op[2])
            if stage.wsc_out is not None:
                y = lax.with_sharding_constraint(
                    y, NamedSharding(mesh, stage.wsc_out))
            return y

        run.__name__ = stage.jit_name
        jit_kw: dict = {}
        if stage.pin_in is not None:
            jit_kw["in_shardings"] = NamedSharding(mesh, stage.pin_in)
        if stage.pin_out is not None:
            jit_kw["out_shardings"] = NamedSharding(mesh, stage.pin_out)
        return jax.jit(run, **jit_kw)

    return trace_stages(
        [(s.name, build_stage(s)) for s in graph.stages])


# ------------------------------------------------- brick-I/O edge tier

@dataclass(frozen=True)
class BrickEdgeGraph:
    """Declarative description of a brick-I/O wrapper program — the
    named IR remainder of ``parallel/bricks.py``, migrated here in
    PR 18 so ONE compiler owns every jitted chain program.

    The wrapper brackets a canonical-chain program with the overlap-map
    edges: ``edge_in`` is the ``(reorder | None, reshape)`` pair applied
    to the caller's brick stack on entry (storage-order canonicalization
    then the bricks->spec reshape), ``edge_out`` the ``(reshape,
    reorder | None)`` pair on exit (spec->bricks then the inverse order
    edge). The callables are the shard_map'd plan-time programs built by
    :mod:`..parallel.bricks` (or the crop/transpose pair of the
    single-device tier); this graph only declares how they compose and
    :func:`compile_brick_io` is the one place the jit is built.
    ``specs`` carries the ``(in, out)`` :class:`..parallel.bricks
    .BrickSpec` accounting pair (None on the single-device tier);
    ``meta`` planner metadata — neither is read by the compiler."""

    edge_in: tuple
    edge_out: tuple
    donate: bool = False
    specs: tuple | None = None
    meta: dict = field(default_factory=dict, compare=False)

    def __post_init__(self):
        for label, pair in (("edge_in", self.edge_in),
                            ("edge_out", self.edge_out)):
            if len(pair) != 2:
                raise ValueError(
                    f"{label} must be a (reorder|None, reshape) pair "
                    f"(edge_out: (reshape, reorder|None)), got {pair!r}")


def compile_brick_io(graph: BrickEdgeGraph, inner_fn):
    """Compile a :class:`BrickEdgeGraph` around a canonical-chain
    program into the brick plan's end-to-end jitted ``fn`` — exactly
    the wrapper the brick planners used to hand-thread (byte-identical
    StableHLO, pinned in ``tests/_hlo_pin_cases.py``'s ``brick_*``
    cases): optional order edge in, bricks->spec reshape, the inner
    chain, spec->bricks reshape, optional order edge out, one jit with
    the chain's donation policy.

    The compiled callable carries the graph as ``fn.brick_edges`` (the
    feature-detection twin of ``fn.stage_graph``)."""
    in_reorder, in_reshape = graph.edge_in
    out_reshape, out_reorder = graph.edge_out

    jit_kw: dict = {"donate_argnums": 0} if graph.donate else {}

    @functools.partial(jax.jit, **jit_kw)
    def fn(stack):
        x = stack if in_reorder is None else in_reorder(stack)
        y = out_reshape(inner_fn(in_reshape(x)))
        return y if out_reorder is None else out_reorder(y)

    fn.brick_edges = graph
    return fn


# ----------------------------------------------- concurrent scheduling

@dataclass
class ConcurrentPlan:
    """N independent transforms scheduled as ONE interleaved program.

    ``fn`` takes the N input arrays (one per plan, each plan's own
    ``in_shape``) and returns the N outputs; calling the object does
    the same. ``plans`` are the source plans in schedule order. The
    program's dispatch-side trace spans carry ``cc<j>:`` prefixes
    (transform j's stage), so the interleave is visible on the PR 1
    timeline; :func:`..utils.trace.stage_key` strips the prefix, so
    rollups attribute each span to its t0..t3 key as usual."""

    fn: Callable
    plans: tuple
    mesh: Mesh
    in_shardings: tuple
    out_shardings: tuple

    def __call__(self, *xs):
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
            xs = tuple(xs[0])
        if len(xs) != len(self.plans):
            raise ValueError(
                f"concurrent schedule of {len(self.plans)} transforms "
                f"takes {len(self.plans)} inputs, got {len(xs)}")
        return self.fn(*xs)


def _graph_steps(graph: StageGraph, interp: _Interp):
    """The chain's local program as a list of ``(kind, name, run)``
    schedulable steps — stage granularity: each exchange is its own
    step (its overlap-K chunking preserved through
    :func:`..parallel.exchange.exchange_chunked`), each local stage
    its own step. The per-step math is exactly the fused chain's, so
    any interleave of two graphs' steps is bit-identical to executing
    the chains back-to-back; only the issue order changes — which is
    the whole point."""
    from .parallel.exchange import exchange_chunked

    steps = []
    nodes = graph.nodes
    i = 0
    while i < len(nodes):
        n = nodes[i]
        if isinstance(n, ExchangeNode):
            def ex_run(y, _n=n):
                return exchange_chunked(
                    y, _n.mesh_axis, split_axis=_n.split,
                    concat_axis=_n.concat, axis_size=_n.parts,
                    algorithm=graph.algorithm,
                    overlap_chunks=graph.overlap_chunks,
                    chunk_axis=_n.chunk_axis, exchange_name=_n.name,
                    uneven=True, platform=graph.platform,
                    axis_sizes=_n.axis_sizes,
                    wire_dtype=graph.wire_dtype)

            steps.append((n.kind, n.name, ex_run))
            nxt = nodes[i + 1]

            def co_run(y, _n=nxt, _ax=n.chunk_axis):
                if _n.factory is not None:
                    fn = _n.factory()
                    extent = jax.tree_util.tree_leaves(y)[0].shape[_ax]
                    return fn(y, 0, extent)
                if _n.takes_bounds:
                    extent = jax.tree_util.tree_leaves(y)[0].shape[_ax]
                    return interp.run(_n.ops, y, bounds=(0, extent))
                return interp.run(_n.ops, y)

            steps.append((nxt.kind, nxt.name, co_run))
            i += 2
        else:
            steps.append((n.kind, n.name,
                          lambda y, _n=n: interp.run(_n.ops, y)))
            i += 1
    return steps


#: Memoized concurrent programs: same plan tuple -> same compiled
#: schedule (the serving tier flushes the same group pattern over and
#: over; plans themselves are plan-cache memoized, so identity keys are
#: stable). Values hold the plan refs, keeping the ids valid.
_CONCURRENT_CACHE: dict = {}


def schedule_concurrent(plans: Sequence) -> ConcurrentPlan:
    """Merge N independent transforms' stage graphs into ONE interleaved
    device program — the DaggerFFT scheduling framing: each transform's
    chain is a schedulable stage DAG, and merging them lets transform
    A's t2 collectives issue while transform B's t0/t3 FFTs run, so
    exchange wire time hides under *another* transform's compute even
    when each transform alone has nothing left to hide it under.

    Schedule policy (documented in docs/ARCHITECTURE.md): transform
    ``j``'s steps are issued staggered ``j`` waves behind transform
    ``j-1``'s, and within a wave later-stage steps issue first — so in
    the canonical 2-transform slab case the trace order is ``A.t0,
    A.t2, B.t0, A.t3, B.t2, B.t3``: A's exchange is in flight exactly
    while B's t0 runs (XLA's async collectives are free to overlap
    them; there is no data dependency between transforms).

    Requirements: every plan was built through the stage-graph IR
    (``plan.graph`` is set) on the SAME mesh. Bit-identity: each
    transform's per-step math is exactly its fused chain's (pinned in
    ``tests/test_a2m_stagegraph.py``'s parity matrix), so outputs are
    bit-identical to executing the plans sequentially.

    Programs are memoized per plan tuple: a serving tier flushing the
    same group combination replays the compiled schedule warm."""
    plans = tuple(plans)
    if len(plans) < 1:
        raise ValueError("schedule_concurrent takes at least one plan")
    key = tuple(id(p) for p in plans)
    hit = _CONCURRENT_CACHE.get(key)
    if hit is not None:
        return hit[1]
    cp = _build_concurrent(plans)
    if len(_CONCURRENT_CACHE) >= 64:  # bound the program memo
        _CONCURRENT_CACHE.pop(next(iter(_CONCURRENT_CACHE)))
    _CONCURRENT_CACHE[key] = (plans, cp)
    return cp


def _build_concurrent(plans: tuple) -> ConcurrentPlan:
    """Uncached :func:`schedule_concurrent` body. The monitor's overlap
    attribution calls this directly: it needs a FRESH ``jax.jit`` object
    so abstract evaluation re-traces the merged program (and so emits
    the ``cc<j>:``/per-chunk dispatch spans) even when the memoized
    schedule has already been traced."""
    graphs = []
    for p in plans:
        g = getattr(p, "graph", None) or graph_of(getattr(p, "fn", p))
        if g is None:
            raise ValueError(
                "schedule_concurrent needs plans built through the "
                "stage-graph IR (slab/pencil chains); got a plan "
                f"without a stage graph: {p!r}")
        graphs.append(g)
    mesh = graphs[0].mesh
    for g in graphs[1:]:
        if g.mesh is not mesh and not (
                g.mesh.shape == mesh.shape
                and list(g.mesh.devices.flat) == list(mesh.devices.flat)
                and g.mesh.axis_names == mesh.axis_names):
            raise ValueError(
                "schedule_concurrent requires one shared mesh; got "
                f"{g.mesh} vs {mesh}")
    progs = [
        _graph_steps(g, _Interp(g.executor, g.algorithm)) for g in graphs
    ]
    lens = [len(p) for p in progs]
    n = len(progs)

    def local_fn(*xs):
        states = list(xs)
        # Staggered wave order: transform j runs its step (wave - j);
        # within a wave, lower j (= deeper into its chain) issues
        # first, so exchanges enter the trace before the younger
        # transforms' compute of the same wave.
        for wave in range(max(lens) + n - 1):
            for j in range(n):
                k = wave - j
                if 0 <= k < lens[j]:
                    kind, name, run = progs[j][k]
                    with add_trace(f"cc{j}:{name}"):
                        states[j] = run(states[j])
        return tuple(states)

    mapped = _shard_map(
        local_fn, mesh=mesh,
        in_specs=tuple(g.in_pspec for g in graphs),
        out_specs=tuple(g.out_pspec for g in graphs))
    in_shs = tuple(NamedSharding(mesh, g.in_pspec) for g in graphs)
    out_shs = tuple(NamedSharding(mesh, g.out_pspec) for g in graphs)

    @jax.jit
    def fn(*xs):
        staged = []
        for g, sh, x in zip(graphs, in_shs, xs):
            for op in g.pre:
                x = _tree_pad(x, op[1], op[2])
            staged.append(lax.with_sharding_constraint(x, sh))
        ys = mapped(*staged)
        outs = []
        for g, y in zip(graphs, ys):
            for op in g.post:
                y = _tree_crop(y, op[1], op[2])
            outs.append(y)
        return tuple(outs)

    return ConcurrentPlan(fn=fn, plans=plans, mesh=mesh,
                          in_shardings=in_shs, out_shardings=out_shs)


# ----------------------------------------------------- wave scheduling

def _mesh_compatible(a, b) -> bool:
    """Same physical mesh under :func:`schedule_concurrent`'s rule:
    identity, or equal shape + device order + axis names."""
    return a is b or (
        a.shape == b.shape
        and list(a.devices.flat) == list(b.devices.flat)
        and a.axis_names == b.axis_names)


def schedule_waves(plans: Sequence, width: int = 4) -> list[tuple]:
    """Partition N plans into dispatch *waves* — the unit the streaming
    scheduler (``CoalescingQueue.serve()``) keeps rolling. A wave is a
    consecutive run of at most ``width`` mutually schedulable plans:
    all built through the stage-graph IR on one shared mesh, so the run
    interleaves into a single program via :func:`schedule_concurrent`.
    A plan below the IR tier — no ``plan.graph`` — or on a different
    mesh breaks the run and rides a singleton wave (it still dispatches,
    it just cannot interleave). Order-preserving: the caller's drain
    order (QoS order in serving) is the admission order.
    """
    if not isinstance(width, int) or width < 1:
        raise ValueError(f"wave width must be a positive int, got {width!r}")
    waves: list[tuple] = []
    cur: list = []
    cur_mesh = None
    for p in plans:
        g = getattr(p, "graph", None)
        if g is None:
            if cur:
                waves.append(tuple(cur))
                cur, cur_mesh = [], None
            waves.append((p,))
            continue
        if cur and (len(cur) >= width
                    or not _mesh_compatible(g.mesh, cur_mesh)):
            waves.append(tuple(cur))
            cur = []
        if not cur:
            cur_mesh = g.mesh
        cur.append(p)
    if cur:
        waves.append(tuple(cur))
    return waves


class WaveSchedule:
    """Rolling wave-at-a-time orchestration over
    :func:`schedule_concurrent` — the abstraction the streaming serving
    loop dispatches through (docs/SERVING_QOS.md, "Streaming scheduler
    & wave preemption").

    A *wave* is the set of transforms whose stage DAGs are interleaved
    into one device program. :meth:`dispatch` issues a wave
    asynchronously (JAX dispatch returns while the outputs are still in
    flight) and enqueues it as the newest in-flight wave;
    :meth:`barrier` blocks until the *oldest* in-flight wave has fully
    drained and retires it. The barrier is the **admission point**:
    with ``depth=2`` (the default), wave ``k+1`` is assembled and
    dispatched while wave ``k`` still executes, so newly formed work
    joins the next wave instead of waiting for the running dispatch —
    host-side assembly hides under device time, and the device never
    waits for the queue as long as one wave's worth of work is pending.

    Bit-exactness is :func:`schedule_concurrent`'s: each transform's
    per-step math is its fused chain's, only issue order changes.
    """

    def __init__(self, *, max_width: int = 4, depth: int = 2):
        if not isinstance(max_width, int) or max_width < 1:
            raise ValueError(
                f"max_width must be a positive int, got {max_width!r}")
        if not isinstance(depth, int) or depth < 1:
            raise ValueError(f"depth must be a positive int, got {depth!r}")
        self.max_width = max_width
        self.depth = depth
        self.waves = 0  # waves dispatched over the schedule's lifetime
        self.records: list[dict] = []  # retired waves, barrier order
        self._inflight: deque = deque()  # (record, outputs)

    @property
    def inflight(self) -> int:
        """Waves dispatched but not yet retired by a barrier."""
        return len(self._inflight)

    def dispatch(self, plans: Sequence, inputs: Sequence) -> tuple:
        """Issue one wave and return its (asynchronous) outputs.

        ``plans``/``inputs`` pair one input array per plan. Two or more
        IR-tier plans on a shared mesh interleave through
        :func:`schedule_concurrent`; anything else — a singleton wave,
        or members below the IR tier — dispatches per-plan in order
        (still asynchronous, just not interleaved). If the schedule is
        already ``depth`` waves deep, blocks on :meth:`barrier` first
        so at most ``depth`` waves are ever in flight."""
        plans = tuple(plans)
        inputs = tuple(inputs)
        if len(plans) != len(inputs):
            raise ValueError(
                f"wave of {len(plans)} plans takes {len(plans)} inputs, "
                f"got {len(inputs)}")
        if not plans:
            raise ValueError("cannot dispatch an empty wave")
        if len(plans) > self.max_width:
            raise ValueError(
                f"wave of {len(plans)} plans exceeds max_width="
                f"{self.max_width}; partition with schedule_waves first")
        while len(self._inflight) >= self.depth:
            self.barrier()
        interleaved = len(plans) >= 2 and all(
            getattr(p, "graph", None) is not None for p in plans) and all(
            _mesh_compatible(p.graph.mesh, plans[0].graph.mesh)
            for p in plans[1:])
        if interleaved:
            outs = schedule_concurrent(plans)(*inputs)
        else:
            outs = tuple(p.fn(x) for p, x in zip(plans, inputs))
        rec = {"index": self.waves, "width": len(plans),
               "interleaved": interleaved,
               "dispatched_at": time.perf_counter()}
        self.waves += 1
        self._inflight.append((rec, outs))
        return outs

    def barrier(self) -> dict | None:
        """Retire the oldest in-flight wave: block until its outputs are
        ready, stamp drain time/duration, append to :attr:`records`, and
        return the record (``None`` when nothing is in flight). This is
        the admission point — callers assemble the next wave from work
        that arrived while the retired wave ran."""
        if not self._inflight:
            return None
        rec, outs = self._inflight.popleft()
        try:
            jax.block_until_ready(outs)
        finally:
            rec["drained_at"] = time.perf_counter()
            rec["duration_s"] = rec["drained_at"] - rec["dispatched_at"]
            self.records.append(rec)
        return rec

    def drain(self) -> list[dict]:
        """Barrier until nothing is in flight; returns the retired
        records in barrier order."""
        recs = []
        while self._inflight:
            recs.append(self.barrier())
        return recs
