"""Correctness-suite utilities, modeled on the reference's test architecture.

The reference's real test pattern is vendored heFFTe's
(``heffte/heffteBenchmark/test/test_common.h``): deterministic seeded world
data (``test_fft3d.h:20-28``, minstd_rand(4242)), a serial reference transform
of the full world (``test_fft3d.h:91-108``), per-rank subbox extraction, and
tolerance tiers (float 5e-4, double 1e-11, ``test_common.h:137-140``).

Here the same roles are played by numpy: seeded data from a fixed PCG64
stream, ``numpy.fft`` as the serial reference, and :func:`subbox` extraction
via :class:`~distributedfft_tpu.geometry.Box3` slices. Multi-device runs use a
virtual CPU mesh (``--xla_force_host_platform_device_count``), the TPU analog
of heFFTe's "mpirun -np N on one box" CI strategy
(``test/CMakeLists.txt:1-7``).
"""

from __future__ import annotations

import numpy as np

from .geometry import Box3

# Tolerance tiers, cf. heffte test_common.h:137-140 (float 5e-4, double 1e-11).
TOLERANCE = {
    np.dtype(np.complex64): 5e-4,
    np.dtype(np.complex128): 1e-11,
    np.dtype(np.float32): 5e-4,
    np.dtype(np.float64): 1e-11,
}


def tolerance(dtype) -> float:
    return TOLERANCE[np.dtype(dtype)]


def make_world_data(shape, dtype=np.complex128, seed: int = 4242) -> np.ndarray:
    """Deterministic full-world input data (heFFTe seeds minstd_rand(4242),
    ``test_fft3d.h:20-28``; values in [0,1))."""
    rng = np.random.default_rng(seed)
    dtype = np.dtype(dtype)
    if dtype.kind == "c":
        real_dt = np.float64 if dtype == np.complex128 else np.float32
        re = rng.random(shape, dtype=np.float64).astype(real_dt)
        im = rng.random(shape, dtype=np.float64).astype(real_dt)
        return (re + 1j * im).astype(dtype)
    return rng.random(shape, dtype=np.float64).astype(dtype)


def make_ramp_data(shape, dtype=np.complex128) -> np.ndarray:
    """Linear-ramp input matching the first-party driver's init
    (``3dmpifft_opt/fftSpeed3d_c2c.cpp:61-63``: value = flat index); useful for
    layout debugging exactly as ``debugLocalData`` decodes coordinates from
    ramp values (``fft_mpi_3d_api.cpp:729-733``)."""
    n = int(np.prod(shape))
    return np.arange(n, dtype=np.float64).reshape(shape).astype(dtype)


def reference_fftn(world: np.ndarray, forward: bool = True) -> np.ndarray:
    """Serial reference transform of the full world in double precision
    (the role of heFFTe's serial 3x1D reference, ``test_fft3d.h:91-108``).
    No normalization on forward; inverse uses numpy's 1/N convention.
    """
    w = world.astype(np.complex128)
    return np.fft.fftn(w) if forward else np.fft.ifftn(w)


def subbox(world: np.ndarray, box: Box3) -> np.ndarray:
    """Extract one rank's box out of the world array."""
    return world[box.slices()]


def rel_error(result: np.ndarray, reference: np.ndarray) -> float:
    """Max absolute error normalized by the reference's max magnitude — the
    comparison used by both the heFFTe tests (``approx``,
    ``test_common.h:143-151``) and the first-party roundtrip check
    (``fftSpeed3d_c2c.cpp:85-91``)."""
    denom = float(np.max(np.abs(reference)))
    if denom == 0.0:
        denom = 1.0
    return float(np.max(np.abs(np.asarray(result) - reference))) / denom


def assert_approx(result, reference, dtype=None, factor: float = 1.0) -> None:
    dtype = dtype or np.asarray(result).dtype
    tol = tolerance(dtype) * factor
    err = rel_error(np.asarray(result), np.asarray(reference))
    assert err <= tol, f"error {err:.3e} > tol {tol:.3e} for {np.dtype(dtype)}"
