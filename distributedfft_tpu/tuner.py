"""Measured plan autotuner with a persistent wisdom cache.

The reference's plan-and-pick discipline builds hipfft, rocfft, and
templateFFT plans side by side and keeps the measured winner
(``setFFTPlans``, ``fft_mpi_3d_api.cpp:318-429``) — but only over the
*executor* axis. heFFTe's headline result (and AccFFT's before it) is
that the best decomposition/communication combination is
configuration-dependent and must be **searched, not modeled**; FFTW's
wisdom mechanism shows the search cost can be paid once and persisted.
This module generalizes the tournament across the full joint space

    decomposition (slab | pencil) x transport (alltoall | alltoallv |
    ppermute) x executor x overlap_chunks K

with three tiers:

1. **Candidate generation** (:func:`enumerate_candidates` +
   :func:`prune_candidates`) — the joint space is enumerated, then
   pruned to <= ~8 survivors by the analytical payload model
   (:func:`..plan_logic.exchange_payloads` wire bytes under each
   transport + the 3-pass HBM roofline of ``docs/MFU_ANALYSIS.md``)
   *before any compile* — the model is trusted to rank, never to pick.
2. **Lockstep tournament** (:func:`measured_select`) — the generic
   measured-selection engine (also backing ``executor="auto"``):
   multi-host processes agree on the candidate set before any timing
   execution, time in identical order, allgather the full time matrix,
   and decide the winner from process 0's row restricted to candidates
   finite on EVERY process — a candidate that failed timing on any
   process can never be broadcast as winner (the build-phase flag
   discipline extended to the timing phase).
3. **Persistent wisdom** — winners appended to a JSONL store
   (``DFFT_WISDOM`` path; default ``<compile cache dir>/wisdom.jsonl``)
   keyed by (plan family, shape, dtype, direction, mesh shape,
   device_kind, library versions), consulted by
   ``PlanOptions.tune="wisdom"|"measure"`` so an identically-keyed
   planner call in a fresh process builds the winner with zero timing
   executions. Inspect/gate via ``python -m distributedfft_tpu.report
   wisdom``.

Env knobs: ``DFFT_TUNE`` (default tune mode), ``DFFT_WISDOM`` (store
path; empty/``0`` disables), ``DFFT_TUNE_ITERS`` (timing budget,
``ITERS`` or ``ITERSxREPEATS``), ``DFFT_TUNE_MAX`` (survivor cap),
``DFFT_AUTO_EXECUTORS`` (executor axis). Full schema: ``docs/TUNING.md``.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from dataclasses import dataclass, replace
from typing import Any, Callable, Sequence

import numpy as np

from .parallel.exchange import WIRE_BYTE_KEYS
from .plan_logic import (
    PlanOptions,
    auto_overlap_chunks,
    eligible_decompositions,
    exchange_payloads,
    logic_plan3d,
    resolve_tune_mode,
)
from .utils import metrics as _metrics
from .utils.cache import compile_cache_dir, enable_compile_cache
from .utils.trace import timed_span

__all__ = [
    "Candidate",
    "enumerate_candidates",
    "prune_candidates",
    "model_cost",
    "tune_budget",
    "agree_winner",
    "measured_select",
    "default_wisdom_path",
    "wisdom_key",
    "load_wisdom",
    "lookup_wisdom",
    "record_wisdom",
    "stale_wisdom_entries",
    "tuned_plan",
    "tuned_label",
    "width_budget",
    "concurrent_width_key",
    "tune_concurrent_width",
]

WISDOM_SCHEMA = 1

#: Survivor cap of the pruning stage (``DFFT_TUNE_MAX`` overrides): past
#: ~8 candidates the tournament's compile bill outweighs what measuring
#: also-rans can recover.
DEFAULT_MAX_CANDIDATES = 8

# Analytical-model constants — RANKING constants, not predictions: the
# model orders candidates for pruning and is never trusted to pick a
# winner (that is what the measurement is for), so rough cross-platform
# magnitudes suffice.  Wire bandwidth ~ one v5e ICI link, HBM ~ v5e, and
# a O(100us) fixed cost per collective launch (dispatch + barrier + DMA
# setup; the same floor OVERLAP_AUTO_MIN_CHUNK_BYTES models).
MODEL_WIRE_GBPS = 45.0
MODEL_HBM_GBPS = 819.0
MODEL_LAUNCH_SECONDS = 100e-6
#: DCN (inter-slice) leg bandwidth of the ranking model — roughly one
#: host's share of a data-center NIC, an order of magnitude below ICI.
#: A calibrated profile's measured ``dcn_gbps`` overrides it.
MODEL_DCN_GBPS = 11.0
#: Matmul throughput per precision tier (TFlop/s) — ranking constants in
#: the v5e ballpark: the bf16 tier is one MXU pass, the f32 tier the
#: 3-pass refinement (~1/3 rate), the exact default tier ~6 passes.
#: A calibrated profile's measured ``mm_bf16_tflops``/``mm_f32_tflops``
#: fields override (:func:`mm_tier_tflops`).
MODEL_MM_TFLOPS = {"bf16": 197.0, "f32": 66.0, "highest": 33.0}

#: Executor preference order when the model cannot rank them (it models
#: geometry only): the menu order of ``api._AUTO_CANDIDATES``.
_EXECUTOR_RANK = ("xla", "xla_minor", "matmul", "pallas")


@dataclass(frozen=True)
class Candidate:
    """One point of the joint search space (the tuple a wisdom entry
    records and a tuned plan stamps into benchmark result lines).
    ``wire_dtype`` is the on-wire compression dimension (None = exact);
    compressed candidates enter the space only for plans that declare a
    ``max_roundtrip_err`` budget."""

    decomposition: str
    algorithm: str
    executor: str
    overlap_chunks: int
    wire_dtype: str | None = None

    @property
    def label(self) -> str:
        base = (f"{self.decomposition}/{self.algorithm}/{self.executor}"
                f"/ov{self.overlap_chunks}")
        return base + (f"+w{self.wire_dtype}" if self.wire_dtype else "")


def tuned_label(plan) -> str:
    """The winner tuple of a tuned plan as the compact
    ``decomposition/transport/executor/ovK[+wDTYPE]`` label benchmark
    result lines stamp (and the regress store keys baselines by)."""
    return Candidate(
        decomposition=plan.decomposition,
        algorithm=plan.options.algorithm,
        executor=plan.executor,
        overlap_chunks=int(plan.options.overlap_chunks or 1),
        wire_dtype=getattr(plan.options, "wire_dtype", None),
    ).label


# ------------------------------------------------------------ candidates

def mm_tier_tflops(executor: str) -> float | None:
    """The matmul throughput (TFlop/s) the ranking model prices a
    matmul-family executor's contractions at: the label's precision tier
    resolved against the calibrated profile's measured
    ``mm_bf16_tflops``/``mm_f32_tflops`` fields when present
    (:mod:`..calibrate`), else the :data:`MODEL_MM_TFLOPS` ranking
    constants. Bare labels price at the exact (``highest``) tier — the
    env default's pass count. None for executors whose compute is not a
    matmul (the HBM roofline alone prices those)."""
    from .calibrate import matching_profile
    from .ops.executors import MM_EXECUTOR_BASES, split_executor

    base = executor.split(":", 1)[0]
    if not base.startswith(MM_EXECUTOR_BASES):
        return None
    tier = (split_executor(executor)[1] or "highest") if ":" in executor \
        else "highest"
    prof = matching_profile()
    if prof is not None:
        bf16 = prof.get("mm_bf16_tflops")
        f32 = prof.get("mm_f32_tflops")
        if tier == "bf16" and isinstance(bf16, (int, float)) and bf16 > 0:
            return float(bf16)
        if isinstance(f32, (int, float)) and f32 > 0:
            # The exact tier is ~2x the f32 tier's pass count (6-pass vs
            # 3-pass bf16 refinement) — derived, not separately measured.
            return float(f32) if tier == "f32" else float(f32) / 2.0
    return MODEL_MM_TFLOPS[tier]


def candidate_roundtrip_error(cand: Candidate, dtype) -> float:
    """The measured round-trip error one candidate's reduced-accuracy
    axes cost TOGETHER: the wire cast's error
    (:func:`..parallel.exchange.wire_roundtrip_error`) plus the executor
    tier's (:func:`..ops.executors.executor_roundtrip_error`) — the sum
    the plan's single ``max_roundtrip_err`` budget governs (compressed
    wire and reduced precision compose; admitting each against the full
    budget separately would let the pair overshoot it). 0.0 for an
    exact-wire, exact-tier candidate. Both terms are seeded and cached —
    per-candidate pruning never re-measures."""
    from .ops.executors import executor_roundtrip_error
    from .parallel.exchange import wire_roundtrip_error

    err = 0.0
    if cand.wire_dtype is not None:
        err += wire_roundtrip_error(dtype, cand.wire_dtype)
    err += executor_roundtrip_error(cand.executor, dtype)
    return err


def _cross_tiers(execs: Sequence[str],
                 mm_tiers: Sequence[str | None]) -> list[str]:
    """Cross the executor axis with the precision-tier axis: each
    matmul-family base gains one tiered label per non-None tier
    (``matmul`` x ``bf16`` -> ``matmul:bf16``); non-matmul executors and
    the ``None`` tier keep the bare name. Order-preserving, deduped."""
    from .ops.executors import MM_EXECUTOR_BASES, tiered_name

    out: list[str] = []
    for ex in execs:
        for tier in mm_tiers:
            if (tier is not None
                    and ex.split(":", 1)[0].startswith(MM_EXECUTOR_BASES)
                    and ":" not in ex):
                name = tiered_name(ex, tier)
            else:
                name = ex  # tier axis is meaningless for this base
            if name not in out:
                out.append(name)
    return out


def _default_executors() -> list[str]:
    """Executor search axis: ``DFFT_AUTO_EXECUTORS`` (the same knob the
    ``executor="auto"`` tournament honors) or the built-in menu, minus
    ``auto`` itself (would recurse) and minus Pallas off-TPU (it runs in
    the Python interpreter there — meaningless to measure, same rule as
    bench.py's candidate menu)."""
    from .api import _AUTO_CANDIDATES

    names = [e.strip() for e in os.environ.get(
        "DFFT_AUTO_EXECUTORS", ",".join(_AUTO_CANDIDATES)).split(",")
        if e.strip() and e.strip() != "auto"]
    import jax

    if jax.default_backend() != "tpu":
        names = [n for n in names if not n.startswith("pallas")] or ["xla"]
    return names


def _overlap_values(shape, ndev: int, itemsize: int) -> list[int]:
    """The K axis: monolithic, the analytical auto model's pick, and
    double it — the measurement brackets the model (docs/MFU_ANALYSIS.md
    "measured vs model K")."""
    k = auto_overlap_chunks(shape, ndev, itemsize)
    return sorted({1, k, 2 * k}) if k > 1 else [1]


def enumerate_candidates(
    shape: Sequence[int],
    ndev: int,
    *,
    mesh_dims: tuple[int, ...] | None = None,
    executors: Sequence[str] | None = None,
    itemsize: int = 8,
    batch: int | None = None,
    hybrid: bool = False,
    wire_dtypes: Sequence[str | None] = (None,),
    mm_tiers: Sequence[str | None] = (None,),
) -> list[Candidate]:
    """Enumerate the joint (decomposition x transport x executor x K x
    wire x precision) space for one plan. ``mesh_dims`` (a caller-fixed
    Mesh) pins the decomposition axis — a 1D mesh can only run slab
    chains, a 2D mesh only pencil; an int device count leaves both in
    play. ``batch`` scales the per-device block the K axis brackets (a
    batched plan's auto-K crossover moves with the B-fold payload).

    ``hybrid=True`` (the caller's mesh is a dcn x ici hybrid,
    :func:`..parallel.multihost.is_hybrid_mesh`) adds the hierarchical
    two-leg slab transport next to the flat-transport pencil chains.
    ``wire_dtypes`` is the on-wire compression axis — ``(None,)`` by
    default; the tuned planner widens it to the full registered codec
    menu (``exchange.WIRE_DTYPES``: exact, bf16 pairs, int8
    block-scaled) only for plans that declare a ``max_roundtrip_err``
    budget. ``mm_tiers`` is
    the matmul precision axis, crossed with the matmul-family executors
    only (``None`` = the bare label; ``"bf16"`` -> ``matmul:bf16``, a
    distinct executor whose accuracy the same budget admits — the
    tuned planner widens it to ``(None, "bf16", "f32")`` under a budget,
    or pins it to an explicit ``PlanOptions.mm_precision``).

    Fusion axis: every Pallas-family executor in the menu additionally
    enters as its fused label (``pallas`` -> ``pallas:fuse``, the
    stage-pair mega-kernel tier) — but only crossed with a real wire
    codec at ``K=1``, because those are exactly the plans whose fusion
    pass activates (:func:`..stagegraph.plan_fusion` gates on a wire
    codec and monolithic exchanges); anywhere else the fused plan is
    byte-identical to the unfused one and would waste a tournament
    slot. Since compressed wire enters the space only under a
    ``max_roundtrip_err`` budget, fused candidates are budget-gated for
    free, and their accuracy cost is the codec's alone
    (``executor_roundtrip_error("pallas:fuse") == 0``: the kernel
    reproduces the unfused arithmetic)."""
    from .ops.executors import FUSE_BASES, fused_name, split_fuse
    from .parallel.exchange import FLAT_ALGORITHMS

    shape = tuple(int(s) for s in shape)
    if hybrid:
        # Hybrid (dcn x ici) mesh: the pencil chain runs each exchange
        # on one mesh axis (flat transports), and the slab chain runs
        # over the combined axis — reachable only through the two-leg
        # hierarchical transport.
        pairs = [("pencil", alg) for alg in FLAT_ALGORITHMS]
        pairs += [("slab", "hierarchical")]
    else:
        if mesh_dims is not None:
            decomps: tuple[str, ...] = (
                "slab" if len(mesh_dims) == 1 else "pencil",)
        else:
            decomps = tuple(d for d in eligible_decompositions(shape, ndev)
                            if d != "single")
        pairs = [(d, alg) for d in decomps for alg in FLAT_ALGORITHMS]
    execs = _cross_tiers(
        list(executors) if executors is not None else _default_executors(),
        mm_tiers)
    fused_execs = []
    for ex in execs:
        try:
            bare, has_fuse = split_fuse(ex)
        except ValueError:
            continue
        if not has_fuse and bare.split(":", 1)[0] in FUSE_BASES:
            fused_execs.append(fused_name(ex, True))
    ks = _overlap_values(shape, ndev, itemsize * (batch or 1))
    out = []
    for d, alg in pairs:
        for wd in wire_dtypes:
            for k in ks:
                for ex in execs:
                    out.append(Candidate(d, alg, ex, k, wd))
                if wd is not None and k == 1:
                    # Fused labels only where the fusion pass can
                    # activate: real wire codec, monolithic exchange.
                    for ex in fused_execs:
                        out.append(Candidate(d, alg, ex, k, wd))
    return out


def model_cost(
    cand: Candidate,
    shape: Sequence[int],
    mesh,
    *,
    itemsize: int = 8,
    batch: int | None = None,
    corrected: bool = True,
) -> float:
    """Analytical seconds estimate of one candidate — the pruning model.

    Compute is the 3-pass HBM stream bound of ``docs/MFU_ANALYSIS.md``;
    each exchange's wire bytes come from
    :func:`..plan_logic.exchange_payloads` under the candidate's
    transport (dense ships split+concat padding, ragged strips the split
    pads, the ring ships dense bytes over P-1 latency-serialized steps);
    overlap at K chunks shrinks the exposed exchange to
    ``t2/K + max(0, t2 - t3)(K-1)/K`` and adds K-1 extra launches per
    exchange (the crossover model ``auto_overlap_chunks`` implements).
    Used to *rank* candidates before any compile, never to pick a
    winner. ``batch=B`` prices the B-fold payload/compute of a batched
    serving plan (launch counts stay per-exchange — the batched win).

    When a calibrated hardware profile stores a ``model_correction``
    ratio for the candidate's transport (the persisted
    ``tune_model_measured_ratio`` feedback of earlier tournaments on
    this hardware — :mod:`..calibrate`), the exchange term is scaled by
    it, so a transport the ideal model consistently underprices on this
    fabric stops crowding better candidates out of the survivor set.
    ``DFFT_TUNE_CORRECTION=0`` (or ``corrected=False`` — how the
    divergence audit computes the *raw* ratio it persists, so the
    feedback never compounds with itself) disables the scaling.
    """
    from .calibrate import model_correction
    from .parallel.exchange import exchange_model_seconds

    corr = 1.0
    if corrected and os.environ.get("DFFT_TUNE_CORRECTION", "1") != "0":
        corr = model_correction(cand.algorithm)
    shape = tuple(int(s) for s in shape)
    lp = logic_plan3d(shape, mesh, PlanOptions(
        decomposition=cand.decomposition, algorithm=cand.algorithm,
        wire_dtype=cand.wire_dtype or "none", tune="off"), batch=batch)
    ndev = (math.prod(lp.mesh.devices.shape) if lp.mesh is not None else 1)
    world_bytes = itemsize * math.prod(shape) * (batch or 1)
    t_fft = 3 * 2 * (world_bytes / ndev) / (MODEL_HBM_GBPS * 1e9)
    mm_rate = mm_tier_tflops(cand.executor)
    if mm_rate is not None:
        # Matmul-family executors: the dense-tier contraction flops
        # priced at the tier's measured/ranking MXU rate — the term that
        # lets pruning rank bf16 vs f32 vs exact BEFORE any compile
        # (8*N*n real flops per transformed axis; the HBM stream stays
        # the floor, so a memory-bound shape doesn't pretend a tier win).
        from .plan_logic import mm_dft_flops

        t_mm = (mm_dft_flops(shape) * (batch or 1) / ndev) / (
            mm_rate * 1e12)
        t_fft = max(t_fft, t_mm)
    if cand.wire_dtype is not None and cand.overlap_chunks == 1:
        # Fused-tier HBM discount, mirroring fused_model_stages /
        # model_stage_seconds: each fused stage keeps one c64 stream
        # and trades the other for wire bytes, so its 2B read+write
        # pair shrinks to B(1 + wire_factor) — a (1+wf)/2 scale on
        # that stage's share of the 3-stage roofline. Pencil fuses all
        # three compute stages (t0/t1 as sender kernels, t3 as the
        # receiver kernel); slab only the receiver side of its single
        # exchange (the 2-axis t0 sender stays unfused).
        from .ops.executors import split_fuse as _split_fuse

        try:
            _, _has_fuse = _split_fuse(cand.executor)
        except ValueError:
            _has_fuse = False
        if _has_fuse:
            from .parallel.exchange import wire_itemsize

            wf = wire_itemsize(itemsize, cand.wire_dtype) / float(itemsize)
            if wf < 1.0:
                nf = 3 if cand.decomposition == "pencil" else 1
                t_fft *= 1.0 - nf * (1.0 - wf) / 6.0
    payloads = exchange_payloads(lp, shape, itemsize)
    # Downstream FFT time each exchange can hide under: one chain stage.
    t_stage = t_fft / (len(payloads) + 1)
    # Leg-level pipelining of the hierarchical transport at K > 1: the
    # ICI leg additionally hides under the previous chunk's DCN leg
    # (exchange._hierarchical_pipelined), mirroring
    # plan_logic.model_stage_seconds so pruning and explain agree.
    leg_pipelined = (cand.algorithm == "hierarchical"
                     and cand.overlap_chunks > 1)
    dcn_raw = 0.0
    if leg_pipelined:
        for e in payloads:
            if e["stage"] == "t2b":
                wb = (e[WIRE_BYTE_KEYS[cand.algorithm]]
                      * e.get("wire_factor", 1.0) / ndev)
                gb = (MODEL_DCN_GBPS if e.get("link") == "dcn"
                      else MODEL_WIRE_GBPS)
                dcn_raw = exchange_model_seconds(
                    wb, e["parts"], cand.algorithm, wire_gbps=gb,
                    launch_seconds=MODEL_LAUNCH_SECONDS)["seconds"]
                break
    total = t_fft
    for e in payloads:
        # Per-leg pricing: the DCN leg of a hierarchical (or hybrid-mesh
        # pencil) exchange rides the slow fabric; wire_factor scales for
        # the candidate's on-wire compression.
        gbps = (MODEL_DCN_GBPS if e.get("link") == "dcn"
                else MODEL_WIRE_GBPS)
        wire = (e[WIRE_BYTE_KEYS[cand.algorithm]]
                * e.get("wire_factor", 1.0) / ndev)
        hide = t_stage
        if leg_pipelined and e["stage"] == "t2a":
            hide += dcn_raw
        total += exchange_model_seconds(
            wire, e["parts"], cand.algorithm,
            wire_gbps=gbps,
            launch_seconds=MODEL_LAUNCH_SECONDS,
            overlap_chunks=cand.overlap_chunks,
            hide_seconds=hide)["exposed_seconds"] * corr
    return total


def prune_candidates(
    candidates: Sequence[Candidate],
    shape: Sequence[int],
    mesh,
    *,
    itemsize: int = 8,
    limit: int | None = None,
    batch: int | None = None,
    max_err: float | None = None,
    dtype=None,
) -> list[Candidate]:
    """Prune the enumerated space to <= ``limit`` survivors before any
    compile: geometry tuples (decomposition, transport, K, wire) are
    ranked by :func:`model_cost`, then crossed with the executor axis
    (which the payload model cannot rank — executors differ in compute
    kernels, not wire bytes) best-geometry-first, so the survivor set
    always measures every executor on the model's preferred geometry
    before spending compiles on runner-up geometries.

    ``max_err`` is the plan's round-trip error budget: reduced-accuracy
    candidates — compressed wire, reduced precision tier, or both —
    whose COMBINED measured round-trip error
    (:func:`candidate_roundtrip_error` at ``dtype``: the wire cast's
    error plus the executor tier's, one budget governing the sum)
    exceeds it are filtered out before any ranking — a candidate the
    budget can never admit must not crowd the survivor set."""
    if max_err is not None:
        dt = dtype if dtype is not None else np.complex64
        candidates = [
            c for c in candidates
            if candidate_roundtrip_error(c, dt) <= max_err]
    if limit is None:
        limit = int(os.environ.get("DFFT_TUNE_MAX", DEFAULT_MAX_CANDIDATES))
    limit = max(1, limit)
    geos: dict[tuple, list[Candidate]] = {}
    for c in candidates:
        geos.setdefault(
            (c.decomposition, c.algorithm, c.overlap_chunks,
             c.wire_dtype or ""), []).append(c)

    def geo_cost(key) -> float:
        probe = geos[key][0]
        return model_cost(probe, shape, mesh, itemsize=itemsize,
                          batch=batch)

    ranked = sorted(geos, key=lambda g: (geo_cost(g), g))

    def exec_rank(c: Candidate) -> tuple:
        base = c.executor.split(":", 1)[0]
        try:
            return (_EXECUTOR_RANK.index(base), c.executor)
        except ValueError:
            return (len(_EXECUTOR_RANK), c.executor)

    out: list[Candidate] = []
    for g in ranked:
        # Within a geometry, the model CAN rank the matmul family's
        # precision tiers (each tier's contraction flops price at its
        # own MXU rate — mm_tier_tflops); executors it cannot tell apart
        # fall back to the menu order. Ranking precision before any
        # compile is what lets a tight survivor cap still measure the
        # promising tier.
        def tier_cost(c: Candidate) -> float:
            return model_cost(c, shape, mesh, itemsize=itemsize,
                              batch=batch)

        for c in sorted(geos[g], key=lambda c: (tier_cost(c),
                                                exec_rank(c))):
            out.append(c)
            if len(out) >= limit:
                return out
    return out


# ------------------------------------------------------------ tournament

def tune_budget() -> tuple[int, int]:
    """(iters, repeats) of each candidate's amortized timing —
    ``DFFT_TUNE_ITERS`` as ``"ITERS"`` or ``"ITERSxREPEATS"`` (default
    10x2). Amortized timing (>= iters dispatches per host sync) so a
    noisy transport's per-call latency cannot pick the wrong winner —
    the reference times ``nt`` executes inside one ``MPI_Wtime`` pair
    (``fftSpeed3d_c2c.cpp:94-98``) for the same reason."""
    raw = os.environ.get("DFFT_TUNE_ITERS", "").strip()
    if not raw:
        return 10, 2
    parts = raw.lower().split("x")
    try:
        if len(parts) == 1:
            it, rep = int(parts[0]), 2
        elif len(parts) == 2:
            it, rep = int(parts[0]), int(parts[1])
        else:
            raise ValueError
        if it < 1 or rep < 1:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"DFFT_TUNE_ITERS must be 'ITERS' or 'ITERSxREPEATS' "
            f"(ints >= 1), got {raw!r}") from None
    return it, rep


def _process_count() -> int:
    import jax

    return jax.process_count()


def _allgather_rows(vec: np.ndarray) -> np.ndarray:
    """Allgather one float row per process -> (nproc, len(vec)) matrix.
    Module-level indirection so tests can simulate multi-host
    reconciliation without a real distributed runtime."""
    from jax.experimental import multihost_utils

    out = np.asarray(multihost_utils.process_allgather(vec))
    return out.reshape(-1, len(vec))


def agree_winner(times: np.ndarray, names: Sequence[str]) -> str:
    """The winner decision, as a pure function of the allgathered
    (nproc, ncand) time matrix — every process computes it from the same
    matrix, so the choice is deterministic across hosts with no separate
    broadcast step.

    Eligible candidates are those with a finite time on EVERY process (a
    candidate that failed timing anywhere must be excluded everywhere,
    or the processes would build different collective programs — the
    timing-phase analog of the build-phase flag agreement); among those,
    process 0's clock picks (wall clocks differ per process, so one
    process's ordering must be authoritative)."""
    times = np.asarray(times, np.float64).reshape(-1, len(names))
    eligible = np.isfinite(times).all(axis=0)
    if not eligible.any():
        raise ValueError(
            "no candidate was timed successfully on every process")
    row0 = np.where(eligible, times[0], np.inf)
    return list(names)[int(np.argmin(row0))]


def measured_select(
    names: Sequence[str],
    build: Callable[[str], Any],
    measure: Callable[[Any], float],
    *,
    what: str = "candidate",
) -> tuple[str, dict[str, Any], dict[str, float]]:
    """The generic measured-selection engine: build every candidate, time
    the ones every process built, keep the fastest. Backs both the
    multi-axis tuner and ``executor="auto"`` (``api._autotune``).

    Returns ``(winner, built, times)``. Per-candidate build and measure
    costs are emitted as ``tune_build_*``/``tune_measure_*`` trace spans
    and metrics histograms. The persistent XLA compile cache is enabled
    first (``DFFT_NO_COMPILE_CACHE=1`` opts out), so candidate compiles
    are cached across re-tunes and process restarts — a replayed
    tournament mostly just measures.

    Multi-host discipline: (1) candidates that built on only some
    processes are timed on none (build-flag allgather) — otherwise the
    processes that have one enter collective executions the others never
    join; (2) timing runs in identical order and execution count on
    every process; (3) the winner comes from :func:`agree_winner` over
    the allgathered time matrix — finite on every process, ranked by
    process 0's clock. Failures are never fatal per candidate; only an
    empty survivor set raises (jointly, after the collectives, so no
    process is stranded mid-protocol).
    """
    enable_compile_cache()
    names = list(names)
    errors: list[str] = []

    # Phase 1: build (jit is lazy, so building is host-local and never
    # emits collectives).
    built: dict[str, Any] = {}
    for nm in names:
        try:
            with timed_span(f"tune_build_{nm}") as span:
                obj = build(nm)
        except Exception as e:  # noqa: BLE001 — candidate skipped
            errors.append(f"{nm}: {type(e).__name__}")
            continue
        built[nm] = obj
        _metrics.observe("tune_build_seconds", span["seconds"], candidate=nm)
    multi = _process_count() > 1
    if not built and not multi:
        # Multi-host must NOT raise here: every process has to reach the
        # reconciliation collectives below even with an empty local set,
        # or the others block in them forever.
        raise ValueError(
            f"no {what} succeeded ({'; '.join(errors)})")

    candidates = [nm for nm in names if nm in built]
    if multi:
        flags = np.array([1.0 if nm in built else 0.0 for nm in names])
        common = _allgather_rows(flags).min(axis=0) > 0
        candidates = [nm for i, nm in enumerate(names) if common[i]]
        if not candidates:
            raise ValueError(
                f"no {what} built on every process "
                f"(local: {sorted(built)}; errors: {'; '.join(errors)})")

    # Phase 2: time the agreed candidates in lockstep.
    times: dict[str, float] = {}
    for nm in candidates:
        try:
            with timed_span(f"tune_measure_{nm}") as span:
                t = float(measure(built[nm]))
        except Exception as e:  # noqa: BLE001
            errors.append(f"{nm}: {type(e).__name__}")
            t = math.inf
        times[nm] = t
        _metrics.inc("tune_timing_executions", candidate=nm)
        _metrics.observe("tune_measure_seconds", span["seconds"],
                         candidate=nm)

    # Phase 3: reconcile and pick. The all-failed decision is made from
    # the allgathered matrix on every process — a local raise before the
    # collective would strand the other processes in it.
    vec = np.array([times[nm] for nm in candidates], np.float64)
    matrix = _allgather_rows(vec) if multi else vec.reshape(1, -1)
    try:
        winner = agree_winner(matrix, candidates)
    except ValueError:
        raise ValueError(
            f"every {what} failed timing"
            + (f" ({'; '.join(errors)})" if errors else "")) from None
    return winner, built, times


# ---------------------------------------------------------------- wisdom

def default_wisdom_path() -> str | None:
    """The wisdom store path: ``DFFT_WISDOM`` when set (empty or ``0``
    disables the store entirely -> None), else ``wisdom.jsonl`` under
    the persistent compile-cache directory (both artifacts are derived,
    hardware-keyed, and safe to delete together)."""
    env = os.environ.get("DFFT_WISDOM")
    if env is not None:
        env = env.strip()
        return None if env in ("", "0") else env
    return os.path.join(compile_cache_dir(), "wisdom.jsonl")


def wisdom_key(
    *,
    kind: str,
    shape: Sequence[int],
    dtype,
    direction: int,
    ndev: int,
    mesh_dims: Sequence[int] | None = None,
    layouts: str | None = None,
    device_kind: str | None = None,
    platform: str | None = None,
    batch: int | None = None,
    err_budget: float | None = None,
    mm_precision: str | None = None,
) -> dict:
    """The identity a wisdom entry is valid for. A measured winner
    transfers only within one (plan family, problem, mesh, hardware,
    code version) tuple — FFTW's wisdom scoping, plus the library
    versions because a new release may change what any candidate
    compiles to. ``batch`` keys batched serving plans separately: a
    B-fold exchange payload moves the transport/overlap crossovers, so a
    winner measured unbatched must never be replayed for a batched
    program (or vice versa). ``err_budget`` (the plan's
    ``max_roundtrip_err``) keys budgeted and exact-only plans apart: the
    budget changes the admissible candidate space, so a winner measured
    under one budget must never replay into a plan with another.
    ``mm_precision`` (an explicit ``PlanOptions.mm_precision`` pin) keys
    tier-pinned tournaments apart from open-tier ones for the same
    reason — a pinned search never saw the bare-label candidates, and an
    open search's winner must not override a caller's pinned tier."""
    import jax

    from . import __version__

    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — backendless key (tests, CLI)
            device_kind = "unknown"
    return {
        "kind": str(kind),
        "shape": [int(s) for s in shape],
        "dtype": str(np.dtype(dtype)),
        "direction": int(direction),
        "ndev": int(ndev),
        "mesh": None if mesh_dims is None else [int(d) for d in mesh_dims],
        "layouts": layouts,
        "batch": None if batch is None else int(batch),
        "err_budget": None if err_budget is None else float(err_budget),
        "mm_precision": mm_precision,
        "device_kind": str(device_kind),
        "platform": platform or jax.default_backend(),
        "x64": bool(jax.config.jax_enable_x64),
        "version": __version__,
        "jax": jax.__version__,
    }


def _key_id(key: dict) -> str:
    return json.dumps(key, sort_keys=True)


def load_wisdom(path: str | None) -> tuple[dict[str, dict], int]:
    """Load the JSONL wisdom store leniently: ``({key_id: entry},
    dropped)`` where malformed lines (the truncated tail of a killed
    writer, non-JSON, entries without key/winner) are counted, never
    raised — the report-merge discipline. Append-only store: the newest
    entry per key wins."""
    if path is None:
        return {}, 0
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return {}, 0
    entries: dict[str, dict] = {}
    dropped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            dropped += 1
            continue
        if (not isinstance(obj, dict) or not isinstance(obj.get("key"), dict)
                or not isinstance(obj.get("winner"), dict)):
            dropped += 1
            continue
        entries[_key_id(obj["key"])] = obj
    return entries, dropped


#: Key fields every CURRENT wisdom entry carries (the wisdom_key
#: schema). An entry recorded before a key field existed (PR 8 added
#: err_budget, PR 12 mm_precision) can never match a current lookup —
#: the diagnostic below counts those instead of silently never
#: matching, so a store orphaned by a schema change says so once.
_CURRENT_KEY_FIELDS = frozenset((
    "kind", "shape", "dtype", "direction", "ndev", "mesh", "layouts",
    "batch", "err_budget", "mm_precision", "device_kind", "platform",
    "x64", "version", "jax",
))

_STALE_KEY_WARNED: set = set()


def stale_wisdom_entries(entries: dict[str, dict]) -> int:
    """Count loaded entries whose key is missing current
    :func:`wisdom_key` fields (recorded under an older key schema —
    they will never match a lookup until re-measured)."""
    return sum(
        1 for e in entries.values()
        if not _CURRENT_KEY_FIELDS <= set(e.get("key", {})))


def _read_wisdom(path: str | None) -> dict[str, dict]:
    entries, dropped = load_wisdom(path)
    if dropped:
        print(f"tuner: {path}: skipped {dropped} malformed wisdom line(s)",
              file=sys.stderr)
    stale = stale_wisdom_entries(entries)
    if stale and path not in _STALE_KEY_WARNED:
        # Warn once per store per process: these entries are not
        # corrupt, they just predate a key-schema change (e.g. the
        # mm_precision field) and can never match — re-measuring
        # repopulates them under the current key.
        _STALE_KEY_WARNED.add(path)
        print(
            f"tuner: {path}: {stale} wisdom entr"
            f"{'y' if stale == 1 else 'ies'} recorded under an older "
            f"key schema (missing current wisdom_key fields); they "
            f"will never match — re-measure to repopulate",
            file=sys.stderr)
    return entries


def lookup_wisdom(key: dict, path: str | None = None) -> dict | None:
    """The newest stored entry for ``key`` (exact identity match), or
    None. Malformed lines are skipped with a count on stderr."""
    if path is None:
        path = default_wisdom_path()
    return _read_wisdom(path).get(_key_id(key))


def record_wisdom(
    key: dict,
    winner: Candidate,
    seconds: float,
    *,
    path: str | None = None,
    times: dict[str, float] | None = None,
) -> dict | None:
    """Append one tournament result to the wisdom store (created, with
    parent directory, on first use). Returns the entry, or None when the
    store is disabled."""
    if path is None:
        path = default_wisdom_path()
    if path is None:
        return None
    it, rep = tune_budget()
    entry = {
        "schema": WISDOM_SCHEMA,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "key": key,
        "winner": {
            "decomposition": winner.decomposition,
            "algorithm": winner.algorithm,
            "executor": winner.executor,
            "overlap_chunks": int(winner.overlap_chunks),
            "wire_dtype": winner.wire_dtype,
        },
        "seconds": float(seconds),
        "budget": [it, rep],
    }
    if winner.wire_dtype is not None:
        # The measured wire round-trip error of the compressed winner:
        # the number the replay-side budget check admits against.
        from .parallel.exchange import wire_roundtrip_error

        entry["compression_err"] = wire_roundtrip_error(
            key.get("dtype", "complex64"), winner.wire_dtype)
    from .ops.executors import executor_roundtrip_error

    prec_err = executor_roundtrip_error(
        winner.executor, key.get("dtype", "complex64"))
    if prec_err:
        # The measured round-trip error of the reduced-precision tier:
        # replay admission sums it with the wire error against the
        # plan's single budget (the two reduced-accuracy axes compose).
        entry["precision_err"] = prec_err
    if times:
        entry["times"] = {
            nm: (None if not math.isfinite(t) else float(t))
            for nm, t in times.items()}
    # One O_APPEND write per entry: concurrent tournaments (multi-host
    # jobs, parallel benchmark workers) append line-atomically — no
    # torn/interleaved lines for load_wisdom's lenient parser to drop.
    from .utils.atomicio import append_line

    append_line(path, json.dumps(entry, sort_keys=True))
    return entry


def _log_model_divergence(
    by_label: dict[str, Candidate],
    times: dict[str, float],
    winner: str,
    shape,
    mesh,
    *,
    itemsize: int = 8,
    batch: int | None = None,
) -> None:
    """Audit the pruning model against the tournament it pruned for:
    per candidate, the measured/predicted ratio goes into the
    ``tune_model_measured_ratio`` gauge (fuel for ``dfft.explain`` /
    prune-quality analysis), the per-transport median of the *raw*
    (uncorrected) ratios is persisted into the hardware profile's
    ``model_correction`` block (:func:`..calibrate
    .update_model_correction`) so the NEXT pruning pass prices each
    transport at its observed cost on this fabric, and when the model's
    own favorite is not the measured winner one stderr line names the
    disagreement — the signal that the ranking constants are
    mis-ordering THIS configuration's candidates. Best-effort: never
    fatal, never changes the winner."""
    try:
        model = {label: model_cost(c, shape, mesh, itemsize=itemsize,
                                   batch=batch)
                 for label, c in by_label.items()
                 if label in times and math.isfinite(times[label])}
        for label, m in model.items():
            if m > 0:
                _metrics.set_gauge("tune_model_measured_ratio",
                                   times[label] / m, candidate=label)
        if not model:
            return
        # Persist the raw measured/model ratio per transport (median
        # across the transport's candidates): the feedback loop the
        # calibrated profile carries and model_cost reads back.
        try:
            from .calibrate import update_model_correction
            from .regress import robust_stats

            raw: dict[str, list[float]] = {}
            for label, c in by_label.items():
                if label not in times or not math.isfinite(times[label]):
                    continue
                m0 = model_cost(c, shape, mesh, itemsize=itemsize,
                                batch=batch, corrected=False)
                if m0 > 0:
                    raw.setdefault(c.algorithm, []).append(
                        times[label] / m0)
            update_model_correction(
                {alg: robust_stats(v)[0] for alg, v in raw.items() if v})
        except Exception:  # noqa: BLE001 — feedback is best-effort
            pass
        model_pick = min(model, key=model.__getitem__)
        if model_pick != winner and model_pick in times:
            print(
                f"tuner: model/measured divergence: model ranked "
                f"{model_pick!r} first "
                f"({model[model_pick]:.6f}s predicted, "
                f"{times[model_pick]:.6f}s measured) but "
                f"{winner!r} won ({model.get(winner, math.nan):.6f}s "
                f"predicted, {times[winner]:.6f}s measured)",
                file=sys.stderr)
    except Exception:  # noqa: BLE001 — audit trail only
        pass


# ------------------------------------------------------ planner dispatch

def _mesh_context(mesh) -> tuple[int, tuple[int, ...] | None]:
    """(device count, fixed mesh dims or None) of a planner mesh arg."""
    if mesh is None:
        return 1, None
    if isinstance(mesh, int):
        return mesh, None
    return int(math.prod(mesh.devices.shape)), tuple(mesh.devices.shape)


def _build_candidate(kind: str, shape, mesh, base: PlanOptions, plan_kw: dict,
                     cand: Candidate, *, donate: bool):
    """Build one concrete plan for a candidate tuple (always with
    ``tune="off"`` — the recursion fence)."""
    from . import api

    opts = replace(
        base, tune="off", decomposition=cand.decomposition,
        algorithm=cand.algorithm, executor=cand.executor,
        overlap_chunks=int(cand.overlap_chunks), donate=donate,
        # "none" pins the exact wire (None would re-defer to the env).
        wire_dtype=cand.wire_dtype or "none")
    plan_fn = api.plan_dft_r2c_3d if kind == "r2c" else api.plan_dft_c2c_3d
    return plan_fn(shape, mesh, options=opts, **plan_kw)


def tuned_plan(kind: str, shape, mesh, options: PlanOptions,
               plan_kw: dict):
    """The tuned tier of the public planners (``tune="wisdom"`` /
    ``"measure"``): consult wisdom first; on a miss either fall back to
    the static heuristics (wisdom mode — never measures) or run the
    pruned tournament and record the winner (measure mode). The caller's
    ``donate`` is honored by rebuilding the winner (tournament plans are
    built donation-free: a donated buffer cannot be re-executed for
    timing)."""
    from . import api

    mode = resolve_tune_mode(options.tune)
    shape = tuple(int(s) for s in shape)
    # Candidate executors carry their own (possibly tiered) labels; the
    # caller's mm tier choice re-enters below as the pinned tier axis,
    # not as option fields (a field pin would conflict with every
    # non-matmul candidate's label).
    base = replace(options, tune="off", donate=False,
                   executor=options.executor.split(":", 1)[0],
                   mm_precision=None, mm_complex=None, fuse=None)
    ndev, mesh_dims = _mesh_context(mesh)
    heuristic = replace(options, tune="off")
    if ndev <= 1:
        # Single device: no decomposition/transport/K to search, and the
        # executor menu already has its own measured path (executor=
        # "auto") — nothing a tournament could add.
        plan_fn = (api.plan_dft_r2c_3d if kind == "r2c"
                   else api.plan_dft_c2c_3d)
        return plan_fn(shape, mesh, options=heuristic, **plan_kw)

    dtype = api._default_cdtype(plan_kw.get("dtype"))
    in_spec, out_spec = plan_kw.get("in_spec"), plan_kw.get("out_spec")
    batch = plan_kw.get("batch")
    err_budget = options.max_roundtrip_err
    layouts = (f"{in_spec}|{out_spec}"
               if (in_spec is not None or out_spec is not None) else None)
    key = wisdom_key(
        kind=kind, shape=shape, dtype=dtype,
        direction=plan_kw.get("direction", -1),
        ndev=ndev, mesh_dims=mesh_dims, layouts=layouts, batch=batch,
        err_budget=err_budget, mm_precision=options.mm_precision)
    path = default_wisdom_path()

    entry = lookup_wisdom(key, path) if path is not None else None
    if entry is not None:
        from .ops.executors import (
            REDUCED_TIERS, executor_roundtrip_error, split_executor,
            split_fuse,
        )

        _metrics.inc("tune_wisdom_hits", kind=kind)
        wd = entry["winner"].get("wire_dtype")
        ex = str(entry["winner"]["executor"])
        tier = split_executor(ex)[1] if ":" in ex else None
        reduced_tier = tier in REDUCED_TIERS
        if wd is not None or reduced_tier:
            # A reduced-accuracy winner — compressed wire, reduced
            # precision tier, or both — replays only into plans whose
            # error budget admits the SUM of its recorded errors (one
            # budget governs both axes); anything else (no budget,
            # tighter budget, missing error records) rebuilds the winner
            # tuple exact: exact wire AND the bare executor label.
            total = 0.0
            if wd is not None:
                rec_err = entry.get("compression_err")
                if rec_err is None:
                    from .parallel.exchange import wire_roundtrip_error

                    rec_err = wire_roundtrip_error(dtype, wd)
                total += float(rec_err)
            if reduced_tier:
                rec_prec = entry.get("precision_err")
                if rec_prec is None:
                    rec_prec = executor_roundtrip_error(ex, dtype)
                total += float(rec_prec)
            if err_budget is None or total > err_budget:
                wd = None
                if reduced_tier:
                    ex = split_executor(ex)[0]  # the exact bare label
                # Exact wire means the fusion pass could only gate out
                # (no_wire_codec) — replay the bare unfused label.
                ex = split_fuse(ex)[0]
        cand = Candidate(
            decomposition=str(entry["winner"]["decomposition"]),
            algorithm=str(entry["winner"]["algorithm"]),
            executor=ex,
            overlap_chunks=int(entry["winner"]["overlap_chunks"]),
            wire_dtype=wd,
        )
        return _build_candidate(kind, shape, mesh, base, plan_kw, cand,
                                donate=options.donate)
    _metrics.inc("tune_wisdom_misses", kind=kind)
    if mode == "wisdom":
        # Wisdom-only mode never pays a measurement: the static
        # heuristics plan exactly as tune="off" would.
        plan_fn = (api.plan_dft_r2c_3d if kind == "r2c"
                   else api.plan_dft_c2c_3d)
        return plan_fn(shape, mesh, options=heuristic, **plan_kw)

    from .parallel.multihost import is_hybrid_mesh

    itemsize = np.dtype(dtype).itemsize
    # Reduced-accuracy axes enter the search only for plans that declare
    # an error budget — on-wire compression AND the matmul precision
    # tiers (one budget governs the sum; prune_candidates filters the
    # combinations it can never admit). An explicit PlanOptions.mm_
    # precision instead PINS the tier axis: every matmul-family
    # candidate carries that tier, budget or not (the caller chose the
    # accuracy; the tournament chooses everything else). The
    # hierarchical transport enters only on hybrid meshes (and only for
    # the c2c chains — the r2c builders run flat).
    wire_dtypes: tuple = (None,)
    mm_tiers: tuple = (None,)
    if err_budget is not None:
        # Every registered wire codec enters the budgeted search
        # (exchange.WIRE_DTYPES: exact, bf16, int8 block-scaled, ...);
        # prune_candidates filters the ones the budget can never admit.
        from .parallel.exchange import WIRE_DTYPES

        wire_dtypes = tuple(WIRE_DTYPES)
        mm_tiers = (None, "bf16", "f32")
    if options.mm_precision is not None:
        mm_tiers = (options.mm_precision,)
    hybrid = kind == "c2c" and is_hybrid_mesh(mesh)
    cands = prune_candidates(
        enumerate_candidates(shape, ndev, mesh_dims=mesh_dims,
                             itemsize=itemsize, batch=batch,
                             hybrid=hybrid, wire_dtypes=wire_dtypes,
                             mm_tiers=mm_tiers),
        shape, mesh, itemsize=itemsize, batch=batch,
        max_err=err_budget, dtype=dtype)
    _metrics.set_gauge("tune_candidates", len(cands), kind=kind,
                       stage="pruned")
    by_label = {c.label: c for c in cands}
    _metrics.inc("tune_tournaments", kind=kind)
    iters, repeats = tune_budget()

    def build(label: str):
        return _build_candidate(kind, shape, mesh, base, plan_kw,
                                by_label[label], donate=False)

    def measure(plan) -> float:
        from .utils.timing import time_fn_amortized

        x = api.alloc_local(plan)
        t, _ = time_fn_amortized(plan.fn, x, iters=iters, repeats=repeats)
        return t

    winner, built, times = measured_select(
        list(by_label), build, measure, what=f"{kind} tune candidate")
    _log_model_divergence(by_label, times, winner, shape, mesh,
                          itemsize=itemsize, batch=batch)
    record_wisdom(key, by_label[winner], times[winner], path=path,
                  times=times)
    if options.donate:
        return _build_candidate(kind, shape, mesh, base, plan_kw,
                                by_label[winner], donate=True)
    return built[winner]


# -------------------------------------------- concurrent-width tournament

def width_budget() -> tuple[int, int] | None:
    """(iters, repeats) of the concurrent-width tournament, from
    ``DFFT_WIDTH_TOURNAMENT`` as ``"ITERS"`` or ``"ITERSxREPEATS"``
    (repeats default 2). Unset / ``""`` / ``"0"`` / ``"off"`` -> None:
    the tournament is disarmed and ``concurrent_groups="auto"`` stays
    on the analytic overlap model (:func:`..monitor.model_concurrent_seconds`)
    — measuring widths executes real programs, so it is opt-in the same
    way ``DFFT_TUNE_ITERS`` gates the plan tournaments."""
    raw = os.environ.get("DFFT_WIDTH_TOURNAMENT", "").strip()
    if raw.lower() in ("", "0", "off"):
        return None
    parts = raw.lower().split("x")
    try:
        if len(parts) == 1:
            iters, repeats = int(parts[0]), 2
        elif len(parts) == 2:
            iters, repeats = int(parts[0]), int(parts[1])
        else:
            raise ValueError
        if iters < 1 or repeats < 1:
            raise ValueError
    except ValueError:
        raise ValueError(
            "DFFT_WIDTH_TOURNAMENT must be 'ITERS' or 'ITERSxREPEATS' "
            f"(positive ints), or ''/'0'/'off' to disarm; got {raw!r}"
        ) from None
    return iters, repeats


def concurrent_width_key(plans: Sequence, counts: Sequence[int]) -> dict:
    """The wisdom identity of one width tournament: the lead plan's
    problem tuple under ``kind="concurrent"``, extended with a
    ``"tuple"`` field naming EVERY member plan (shape × dtype ×
    direction × batch, in drain order) and the live per-group transform
    ``"counts"`` — a width measured on one plan tuple must never replay
    into another, exactly the scoping discipline :func:`wisdom_key`
    applies to batch/err_budget. Extra fields are schema-safe: lookups
    match the full JSON identity, and the staleness check is a
    subset test on the standard fields."""
    p0 = plans[0]
    mesh = getattr(p0, "mesh", None)
    ndev = int(math.prod(mesh.devices.shape)) if mesh is not None else 1
    key = wisdom_key(
        kind="concurrent",
        shape=p0.shape,
        dtype=getattr(p0, "in_dtype", None) or p0.dtype,
        direction=p0.direction,
        ndev=ndev,
        mesh_dims=tuple(mesh.devices.shape) if mesh is not None else None,
        batch=getattr(p0, "batch", None),
    )
    key["tuple"] = [
        "x".join(str(s) for s in p.shape)
        + f":{np.dtype(getattr(p, 'in_dtype', None) or p.dtype)}"
        + f":d{p.direction}:b{getattr(p, 'batch', None) or 1}"
        for p in plans
    ]
    key["counts"] = [int(c) for c in counts]
    return key


def tune_concurrent_width(
    plans: Sequence,
    counts: Sequence[int],
    *,
    path: str | None = None,
) -> int | None:
    """Measured tournament over concurrent flush/wave widths — the PR 18
    replacement for the model-only ``concurrent_groups="auto"``: rank
    width ``w`` by the measured throughput of the live plan tuple's
    first ``w`` groups scheduled as ONE interleaved program
    (:func:`..stagegraph.schedule_concurrent`), i.e. waves/s scaled by
    the wave's transform count (``counts[:w]`` transforms retire per
    wave, so seconds-per-transform is the scale-free rank).

    Returns the winning width, or ``None`` when the tournament is
    disarmed (:func:`width_budget` is None) — the caller then falls
    back to the analytic model. Wisdom-keyed like the plan tournaments
    (``kind="concurrent"``): a hit replays the stored width with ZERO
    timing executions, so a fixed wisdom file makes the choice
    deterministic; a measured winner is appended with its per-width
    times, waves/s, and budget so ``report wisdom`` shows the margin.
    Multi-host safe: widths build/time/decide through
    :func:`measured_select`'s lockstep protocol."""
    budget = width_budget()
    if budget is None:
        return None
    plans = list(plans)
    counts = [int(c) for c in counts]
    if len(plans) < 2:
        return max(1, len(plans))
    if path is None:
        path = default_wisdom_path()
    key = concurrent_width_key(plans, counts)
    if path is not None:
        entry = lookup_wisdom(key, path)
        if entry is not None:
            w = entry.get("winner", {}).get("width")
            if isinstance(w, int) and 1 <= w <= len(plans):
                _metrics.inc("tune_wisdom_hits", kind="concurrent")
                return w
    _metrics.inc("tune_wisdom_misses", kind="concurrent")

    from . import api
    from .stagegraph import schedule_concurrent
    from .utils.timing import time_fn_amortized

    iters, repeats = budget
    names = [f"w{w}" for w in range(1, len(plans) + 1)]

    def build(nm):
        w = int(nm[1:])
        if w == 1:
            fn = plans[0].fn
        else:
            fn = schedule_concurrent(plans[:w]).fn
        xs = tuple(api.alloc_local(p) for p in plans[:w])
        return w, fn, xs

    def measure(built_obj):
        w, fn, xs = built_obj
        t, _ = time_fn_amortized(fn, *xs, iters=iters, repeats=repeats)
        return t / sum(counts[:w])  # seconds per transform

    winner, built, times = measured_select(
        names, build, measure, what="concurrent width")
    w = built[winner][0]
    if path is not None:
        per_transform = times[winner]
        secs = per_transform * sum(counts[:w])
        entry = {
            "schema": WISDOM_SCHEMA,
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "key": key,
            "winner": {"width": int(w)},
            "seconds": float(secs),
            "waves_per_s": (1.0 / secs) if secs > 0 else None,
            "transforms_per_s":
                (1.0 / per_transform) if per_transform > 0 else None,
            "times": {nm: (float(t) if math.isfinite(t) else None)
                      for nm, t in times.items()},
            "budget": [iters, repeats],
        }
        from .utils.atomicio import append_line

        append_line(path, json.dumps(entry, sort_keys=True))
    return int(w)
