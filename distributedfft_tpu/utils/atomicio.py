"""Concurrent-writer-safe file primitives for the append-only stores.

Three stores accumulate machine-local state across processes: the
tuner's wisdom JSONL, the regress history JSONL (both append-only), and
the calibrated hardware profile JSON (whole-document replace). Multiple
benchmark workers, serving processes, and tuning tournaments write them
concurrently, and the old ``open(path, "a"); f.write(...)`` pattern
gives no interleaving guarantee: Python's buffered layer may split one
logical line into several OS ``write()`` calls, and two processes'
fragments can interleave into a torn line that the lenient loaders then
silently drop.

This module is the one shared discipline:

- :func:`append_line` / :func:`append_lines` — ``O_APPEND`` +
  exactly ONE ``os.write`` per call. POSIX guarantees the file offset
  update and the write are atomic with ``O_APPEND``, so concurrent
  appenders' payloads land whole, in some order, never interleaved
  (line-atomic). Windows ``O_APPEND`` emulation gives the same
  practical guarantee for the file sizes at play.
- :func:`replace_file` — write-to-temp + ``os.replace``, so a
  concurrent reader sees either the old or the new document, never a
  half-written one (the hwprofile discipline, factored here).

Stdlib-only (no jax): ``regress.py`` loads from its file path directly
and must stay importable with a sick TPU transport.
"""

from __future__ import annotations

import os

__all__ = ["append_line", "append_lines", "replace_file"]


def _ensure_parent(path: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)


def append_lines(path: str, lines: list[str]) -> None:
    """Append ``lines`` (newlines added where missing) to ``path`` as one
    ``O_APPEND`` ``os.write`` — concurrent appenders from other
    processes can never tear or interleave within the payload. Creates
    the file (and parent directory) on first use."""
    if not lines:
        return
    _ensure_parent(path)
    payload = "".join(
        ln if ln.endswith("\n") else ln + "\n" for ln in lines
    ).encode("utf-8")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        # One write() call: with O_APPEND the offset update + write are
        # atomic on POSIX, so the whole payload lands contiguously.
        os.write(fd, payload)
    finally:
        os.close(fd)


def append_line(path: str, line: str) -> None:
    """Append one line to ``path`` atomically (see :func:`append_lines`)."""
    append_lines(path, [line])


def replace_file(path: str, text: str) -> None:
    """Replace ``path``'s contents atomically: write a same-directory
    temp file, then ``os.replace`` — a concurrent reader sees the old or
    the new document, never a torn one."""
    _ensure_parent(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
