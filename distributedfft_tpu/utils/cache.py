"""Persistent XLA compilation cache setup, shared by every benchmark
driver: on the flaky TPU tunnel a retry must not pay the 20-40s compile
again. One definition so the knob names, default directory, and threshold
cannot drift between drivers."""

from __future__ import annotations

import os


def enable_compile_cache() -> None:
    """Point JAX at a persistent compile cache (no-op when
    ``DFFT_NO_COMPILE_CACHE=1``; directory override via
    ``DFFT_COMPILE_CACHE``)."""
    if os.environ.get("DFFT_NO_COMPILE_CACHE") == "1":
        return
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("DFFT_COMPILE_CACHE", "/tmp/dfft_xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — the cache is an optimization only
        pass
