"""Persistent XLA compilation cache setup, shared by every benchmark
driver and the measured autotuner: on the flaky TPU tunnel a retry must
not pay the 20-40s compile again, and a re-tune (or a restarted process
replaying a tournament) must pay each candidate's compile at most once.
One definition so the knob names, default directory, and threshold
cannot drift between drivers."""

from __future__ import annotations

import os


def compile_cache_dir() -> str:
    """The persistent plan/compile cache directory (``DFFT_COMPILE_CACHE``
    override). Also the default home of the tuner's wisdom store — both
    artifacts have the same lifecycle: derived, hardware-keyed, safe to
    delete."""
    return os.environ.get("DFFT_COMPILE_CACHE", "/tmp/dfft_xla_cache")


def enable_compile_cache() -> None:
    """Point JAX at a persistent compile cache (no-op when
    ``DFFT_NO_COMPILE_CACHE=1``; directory override via
    ``DFFT_COMPILE_CACHE``)."""
    if os.environ.get("DFFT_NO_COMPILE_CACHE") == "1":
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", compile_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — the cache is an optimization only
        pass
