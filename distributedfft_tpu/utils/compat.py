"""Version shims for JAX APIs still in motion.

The shard_map varying-axes discipline (every operand of a collective or a
pallas_call must carry the right varying-across-mesh-axes set) is spelled
``lax.pcast(..., to="varying")`` from JAX 0.9; older releases spell it
``lax.pvary``. One shim here so call sites stay warning-free on both.
"""

from __future__ import annotations

from jax import lax


def pvary(x, axes: tuple):
    """Mark replicated ``x`` as varying over mesh ``axes``."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    return lax.pvary(x, tuple(axes))  # pragma: no cover — jax < 0.9
