"""Version shims for JAX APIs still in motion.

The shard_map varying-axes discipline (every operand of a collective or a
pallas_call must carry the right varying-across-mesh-axes set) is spelled
``lax.pcast(..., to="varying")`` from JAX 0.9; older releases spell it
``lax.pvary``. One shim here so call sites stay warning-free on both.
"""

from __future__ import annotations

from jax import lax


def pvary(x, axes: tuple):
    """Mark replicated ``x`` as varying over mesh ``axes``."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    return lax.pvary(x, tuple(axes))  # pragma: no cover — jax < 0.9


def force_real_lowering() -> bool:
    """True when DFFT_FORCE_REAL_LOWERING=1: trace the REAL target paths
    (Pallas kernels instead of interpret/jnp mirrors, ragged collectives
    instead of the dense CPU stand-in) regardless of the host backend.
    The resulting program cannot *execute* on CPU — the switch exists so
    ``jax.export``-based lowering tests can build the actual TPU modules
    (Mosaic kernels, ragged all-to-all) on a chipless host
    (tests/test_tpu_lowering.py). One switch for every mirror site, so a
    lowering test can never silently embed a mirror."""
    import os

    return os.environ.get("DFFT_FORCE_REAL_LOWERING") == "1"
