"""Version shims for JAX APIs still in motion.

The shard_map varying-axes discipline (every operand of a collective or a
pallas_call must carry the right varying-across-mesh-axes set) is spelled
``lax.pcast(..., to="varying")`` from JAX 0.9; older releases spell it
``lax.pvary``, and releases before the vma discipline existed (<= 0.4.x)
spell it not at all — there ``jax.typeof`` is missing too, every
varying-set reads as empty, and the marking is a no-op. Same story for
``jax.ShapeDtypeStruct(..., vma=)`` and the Pallas TPU compiler-params
rename (``TPUCompilerParams`` -> ``CompilerParams``). One shim each here
so call sites stay warning-free — and importable — on every supported
release.
"""

from __future__ import annotations

import jax
from jax import lax


def pvary(x, axes: tuple):
    """Mark replicated ``x`` as varying over mesh ``axes`` (identity on
    releases without the vma discipline — nothing to mark there)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, tuple(axes))
    return x  # pre-vma jax: varying sets do not exist


def typeof_vma(x) -> frozenset:
    """Varying-across-mesh-axes set of a traced value — empty outside
    shard_map. On releases without ``jax.typeof``/the vma discipline the
    tracing axis environment stands in: every mesh axis in scope (the
    consumers use the set to gate interpret-mode mirrors and to mark
    operands varying — :func:`pvary` is the identity there, and
    :func:`shape_dtype_struct` drops the declaration, so the coarser set
    is safe)."""
    try:
        return frozenset(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        pass
    try:  # pre-vma jax: the axis env knows whether shard_map is tracing
        from jax._src.core import unsafe_get_axis_names

        return frozenset(unsafe_get_axis_names())
    except Exception:  # noqa: BLE001 — chipless/newer internals moved on
        return frozenset()


def shape_dtype_struct(shape, dtype, vma: frozenset = frozenset()):
    """``jax.ShapeDtypeStruct`` with the varying set where the release
    supports declaring one (pallas_call out_shape under shard_map);
    silently without it elsewhere — matching :func:`typeof_vma`, which
    reads every set as empty there."""
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # pre-vma jax
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def tpu_compiler_params(**kw):
    """The Pallas TPU compiler-params object under its current name
    (``pltpu.CompilerParams``; ``TPUCompilerParams`` before the
    rename)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)


def force_real_lowering() -> bool:
    """True when DFFT_FORCE_REAL_LOWERING=1: trace the REAL target paths
    (Pallas kernels instead of interpret/jnp mirrors, ragged collectives
    instead of the dense CPU stand-in) regardless of the host backend.
    The resulting program cannot *execute* on CPU — the switch exists so
    ``jax.export``-based lowering tests can build the actual TPU modules
    (Mosaic kernels, ragged all-to-all) on a chipless host
    (tests/test_tpu_lowering.py). One switch for every mirror site, so a
    lowering test can never silently embed a mirror."""
    import os

    return os.environ.get("DFFT_FORCE_REAL_LOWERING") == "1"
