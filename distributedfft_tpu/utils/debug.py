"""Debug aids: per-shard data dumps and layout validation.

The reference ships two debug tools instead of unit tests (SURVEY.md §4.1):
``outputPlanInfo`` writes each rank's plan/exchange tables to
``rank_i_gpu_j.txt`` (``fft_mpi_3d_api.cpp:433-464``) and ``debugLocalData``
dumps device buffers to CSV, with a mode that decodes linear-ramp values
back into (x, y, z) coordinates to verify layouts (``:701-750``, type 0 at
``:729-733``). These are their TPU-native equivalents, plus a sharding
validator that checks a global array's shards against a plan's box
metadata — the layout-bug detector the coordinate-decode trick exists for.
"""

from __future__ import annotations

import jax
import numpy as np

from ..geometry import Box3


def ramp_world(shape, dtype=np.complex128) -> np.ndarray:
    """Linear-ramp world data v[i,j,k] = flat index (the reference's init
    pattern, ``fftSpeed3d_c2c.cpp:61-63``): every value names its own global
    coordinate, so any misplaced element is detectable after a reshape."""
    n = int(np.prod(shape))
    return np.arange(n, dtype=dtype).reshape(tuple(shape))


def decode_ramp(value: float, shape) -> tuple[int, int, int]:
    """Invert the ramp: flat value -> (x, y, z) world coordinate (the
    type-0 decode of ``debugLocalData``, ``fft_mpi_3d_api.cpp:729-733``)."""
    v = int(round(float(value)))
    _, n1, n2 = (int(s) for s in shape)
    return v // (n1 * n2), (v // n2) % n1, v % n2


def dump_local_data(x, prefix: str = "dfft_debug") -> list[str]:
    """Write one CSV per addressable shard of ``x``:
    ``<prefix>_shard<i>.csv`` with rows ``local_index,value`` plus a header
    naming the device and the shard's index window — the ``debugLocalData``
    dump (``fft_mpi_3d_api.cpp:701-750``). Returns the paths written."""
    paths = []
    for i, s in enumerate(x.addressable_shards):
        path = f"{prefix}_shard{i}.csv"
        data = np.asarray(s.data).ravel()
        window = tuple(
            (idx.start or 0, idx.stop if idx.stop is not None else dim)
            for idx, dim in zip(s.index, x.shape)
        )
        with open(path, "w") as f:
            f.write(f"# device={s.device} window={window}\n")
            f.write("local_index,value\n")
            for j, v in enumerate(data):
                f.write(f"{j},{v}\n")
        paths.append(path)
    return paths


def check_layout(x, boxes: list[Box3]) -> None:
    """Validate that the addressable shards of ``x`` tile exactly the given
    per-device boxes (a plan's ``in_boxes``/``out_boxes``). Raises
    AssertionError naming the first mismatching device — the layout check
    the reference performs by eye on decoded ramp dumps."""
    shards = sorted(x.addressable_shards, key=lambda s: s.device.id)
    if len(boxes) != len(shards):
        raise AssertionError(
            f"{len(shards)} addressable shards but {len(boxes)} boxes "
            "(multi-host arrays validate only their local shards)"
        )
    for s, b in zip(shards, boxes):
        got = tuple(
            (idx.start or 0, idx.stop if idx.stop is not None else dim)
            for idx, dim in zip(s.index, x.shape)
        )
        want = tuple((int(lo), int(hi)) for lo, hi in zip(b.low, b.high))
        if got != want:
            raise AssertionError(
                f"device {s.device}: shard window {got} != plan box {want}"
            )


def write_plan_info(plan, prefix: str = "dfft_plan") -> str:
    """Write the plan dump to ``<prefix>_<process>.txt`` — the
    ``outputPlanInfo`` per-rank file (``fft_mpi_3d_api.cpp:433-464``;
    there ``rank_i_gpu_j.txt``)."""
    from .trace import plan_info

    path = f"{prefix}_{jax.process_index()}.txt"
    with open(path, "w") as f:
        f.write(plan_info(plan) + "\n")
    return path


def ramp_roundtrip_check(plan_fwd, plan_bwd, tol: float | None = None) -> float:
    """Plan-pair self-check on ramp data: max |x - IFFT(FFT(x))| relative to
    the ramp magnitude (the reference's inline validation,
    ``fftSpeed3d_c2c.cpp:85-91``). Returns the relative error; raises when a
    tolerance is given and exceeded."""
    import jax.numpy as jnp

    x = jnp.asarray(ramp_world(plan_fwd.in_shape, np.complex128).astype(
        np.dtype(plan_fwd.in_dtype)))
    r = plan_bwd(plan_fwd(x))
    err = float(jnp.max(jnp.abs(r - x)) / jnp.max(jnp.abs(x)))
    if tol is not None and not err < tol:
        raise AssertionError(f"ramp roundtrip error {err} exceeds {tol}")
    return err
