"""Structured metrics registry — process-global counters/gauges/histograms.

The reference surfaces its runtime health as printf noise (per-execute
t0..t3 lines, ``fft_mpi_3d_api.cpp:184-201``) that callers string-grep;
this module is the structured replacement: named series with labels,
snapshot-able as one JSON document, so benchmark harnesses (``bench.py``,
``benchmarks/speed3d.py``) can attach a telemetry block to every result
line instead of ad-hoc string fields.

Registered series (wired in :mod:`..api`):

- ``plan_builds`` (counter; kind/decomposition/executor) — actual plan
  constructions, cache misses included.
- ``plan_cache_hits`` / ``plan_cache_misses`` (counter; kind) — the
  plan-cache outcome of every public planner call.
- ``plan_build_seconds`` / ``compile_seconds`` (histogram) — plan-time
  cost, the hipRTC-compile-cost analog.
- ``executes`` (counter; kind/decomposition/executor) — one per
  ``execute()``.
- ``exchange_true_bytes`` / ``exchange_wire_bytes`` (counter) — per
  execute, the true information moved vs the bytes the plan's exchange
  algorithm ships (``plan_logic.exchange_payloads`` accounting).

Tuner series (wired in :mod:`..tuner`):

- ``tune_tournaments`` (counter; kind) — measured-selection tournaments
  actually run (wisdom hits skip these entirely).
- ``tune_timing_executions`` (counter; candidate) — one per candidate
  timed in a tournament; zero across a planner call proves the wisdom
  path was taken.
- ``tune_wisdom_hits`` / ``tune_wisdom_misses`` (counter; kind) — the
  wisdom-store outcome of every tuned planner call.
- ``tune_build_seconds`` / ``tune_measure_seconds`` (histogram;
  candidate) — per-candidate plan-build/compile and timing cost, also
  emitted as ``tune_build_*``/``tune_measure_*`` trace spans.

Serving / flight-recorder series (wired in :mod:`..serving`; see
docs/OBSERVABILITY.md "Flight recorder"):

- ``serving_submits`` / ``serving_flushes`` / ``serving_transforms``
  (counter; kind) — request intake and group execution.
- ``serving_flush_reasons`` (counter; kind/reason) — what triggered
  each flush: ``full`` (a group reached max_batch), ``manual``
  (an explicit ``flush()``), ``result`` (a caller's await outran the
  coalescer — the batch-size-vs-latency tell).
- ``serving_queue_depth`` (gauge; kind) — pending requests after every
  submit/flush.
- ``serving_wait_seconds`` (histogram; kind) — per-request
  enqueue-to-flush latency, the queue-wait of the request spans.
- ``serving_batch_size`` (histogram; kind) — transforms per flush.

Fault-tolerance series (wired in :mod:`..serving` / :mod:`..faults`;
see docs/ROBUSTNESS.md):

- ``fault_injected`` (counter; point/kind) — injected faults fired.
- ``serving_retries`` / ``serving_isolated_failures`` /
  ``serving_degraded`` / ``serving_expired`` / ``serving_rejected``
  (counter; kind, +executor on degraded) — the recovery chain's
  accounting: transient retries, bisection-isolated failures,
  fallback-executor resolutions, deadline cancellations, admission
  rejections.
- ``serving_warm_pool_skipped`` (counter) — stale wisdom tuples
  skipped during pool warm-up.

Multi-tenant QoS series (wired in :mod:`..serving`; see
docs/SERVING_QOS.md):

- ``serving_tenant_submits`` / ``serving_tenant_transforms`` (counter;
  kind/tenant) — per-tenant intake and drained transforms.
- ``serving_tenant_quota_shed`` (counter; kind/tenant) — submits shed
  with ``QuotaExceeded`` (over-quota under ``admission="raise"``).
- ``serving_tenant_deadline_misses`` (counter; kind/tenant) — deadline
  cancellations charged to the owning tenant.
- ``serving_tenant_wait_seconds`` (histogram; kind/tenant) — the
  per-tenant queue-wait distribution (the SLO ledger's p50/p99 ride
  the policy's in-process reservoir; ``report qos``).

Live-monitor series (wired in :mod:`..monitor` / :mod:`.trace`; see
docs/OBSERVABILITY.md "Live monitoring & health"):

- ``serving_stalls`` (counter; kind) — queue-stall watchdog firings: a
  pending group aged past ``stall_factor × max_wait_s`` with no flush
  progress between monitor samples.
- ``trace_dropped_events`` (counter) — flight-recorder events evicted
  by the in-memory ring cap (``DFFT_TRACE_MAX_EVENTS``).

Wait histograms additionally keep a fixed-size sampling reservoir
(:data:`RESERVOIR_SERIES`) so snapshots carry p50/p99; the per-series
``exact`` flag says whether the quantiles were computed over every
observation or a uniform sample (Algorithm R) once the count outgrows
the reservoir.

Disabled-path discipline: everything is gated on one module-level flag
(the ``tracing_enabled()`` pattern of :mod:`.trace`) — with metrics off
(the default) every hook is a single attribute check and early return,
no allocation, no lock. Enable with :func:`enable_metrics` or
``DFFT_METRICS=1``.
"""

from __future__ import annotations

import os
import random
import threading
import time

__all__ = [
    "METRICS_SCHEMA",
    "enable_metrics",
    "metrics_enabled",
    "inc",
    "set_gauge",
    "observe",
    "counter_total",
    "metrics_snapshot",
    "metrics_reset",
]

#: Snapshot document format version — bumped whenever the snapshot's
#: shape changes, and stamped into every snapshot (and from there into
#: the regress run records that embed one) so schema drift across
#: releases is detectable offline instead of silently misparsed.
METRICS_SCHEMA = 1

_enabled = os.environ.get("DFFT_METRICS", "") not in ("", "0")
_lock = threading.Lock()
# Keyed (name, ((label, value), ...)) with label values stringified —
# one flat series table per instrument family.
_counters: dict[tuple, float] = {}
_gauges: dict[tuple, float] = {}
_histograms: dict[tuple, list] = {}  # [count, total, min, max]

#: Histogram series that keep a bounded sampling reservoir for snapshot
#: quantiles. The wait distributions are the SLO-facing ones; the other
#: histograms stay pure count/total/min/max aggregates.
RESERVOIR_SERIES = frozenset(
    {"serving_wait_seconds", "serving_tenant_wait_seconds"})
#: Reservoir capacity per labeled series — beyond this many
#: observations, Algorithm R keeps a uniform sample (exact=False).
RESERVOIR_SIZE = 2048
_reservoirs: dict[tuple, list] = {}
_res_rng = random.Random(0x0FF7)  # deterministic per process


def metrics_enabled() -> bool:
    return _enabled


def enable_metrics(on: bool = True) -> None:
    """Turn the registry on (or off with ``on=False``). Off is the
    default; the recording hooks are single-check no-ops while off."""
    global _enabled
    _enabled = bool(on)


def _key(name: str, labels: dict) -> tuple:
    return (name, tuple(sorted((k, str(v)) for k, v in labels.items())))


def inc(name: str, value: float = 1.0, **labels) -> None:
    """Add ``value`` to the counter series ``name`` at ``labels``."""
    if not _enabled:
        return
    k = _key(name, labels)
    with _lock:
        _counters[k] = _counters.get(k, 0.0) + value


def set_gauge(name: str, value: float, **labels) -> None:
    """Set the gauge series ``name`` at ``labels`` to ``value``."""
    if not _enabled:
        return
    k = _key(name, labels)
    with _lock:
        _gauges[k] = float(value)


def observe(name: str, value: float, **labels) -> None:
    """Record one observation into the histogram series ``name`` —
    aggregated as count/total/min/max (the heFFTe finalize-summary
    statistics, not bucketed)."""
    if not _enabled:
        return
    k = _key(name, labels)
    value = float(value)
    with _lock:
        h = _histograms.get(k)
        if h is None:
            h = _histograms[k] = [1, value, value, value]
        else:
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)
        if name in RESERVOIR_SERIES:
            r = _reservoirs.get(k)
            if r is None:
                r = _reservoirs[k] = []
            if len(r) < RESERVOIR_SIZE:
                r.append(value)
            else:
                # Algorithm R: each of the h[0] observations so far ends
                # up in the sample with probability RESERVOIR_SIZE/h[0].
                j = _res_rng.randrange(h[0])
                if j < RESERVOIR_SIZE:
                    r[j] = value


def counter_total(name: str) -> float:
    """Sum of the counter ``name`` across every label combination."""
    with _lock:
        return sum(v for (n, _), v in _counters.items() if n == name)


def _label_str(labels: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in labels)


def _quantile(sorted_vals: list, q: float) -> float:
    """Linear-interpolated quantile of an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def metrics_snapshot() -> dict:
    """One JSON-serializable document of every recorded series.

    Shape: ``{"schema", "captured_at_monotonic", "enabled", "counters":
    {name: {"label=value,...": total}}, "gauges": {...}, "histograms":
    {name: {labels: {count,total,mean,min,max}}}}`` (the empty string
    keys a label-less series). ``schema`` is :data:`METRICS_SCHEMA`;
    ``captured_at_monotonic`` is ``time.monotonic()`` at capture — a
    per-process ordering stamp (two snapshots from one process order by
    it; it is NOT wall clock and never compares across processes).
    Reset with :func:`metrics_reset`.
    """
    with _lock:
        counters: dict = {}
        for (name, labels), v in sorted(_counters.items()):
            counters.setdefault(name, {})[_label_str(labels)] = v
        gauges: dict = {}
        for (name, labels), v in sorted(_gauges.items()):
            gauges.setdefault(name, {})[_label_str(labels)] = v
        hists: dict = {}
        for (name, labels), (cnt, total, lo, hi) in sorted(
                _histograms.items()):
            entry = {
                "count": cnt,
                "total": total,
                "mean": total / cnt,
                "min": lo,
                "max": hi,
            }
            r = _reservoirs.get((name, labels))
            if r is not None:
                s = sorted(r)
                entry["p50"] = _quantile(s, 0.50)
                entry["p99"] = _quantile(s, 0.99)
                # Exact while every observation is still in the sample;
                # a uniform Algorithm-R estimate once the count outgrew
                # the reservoir.
                entry["exact"] = cnt <= RESERVOIR_SIZE
            hists.setdefault(name, {})[_label_str(labels)] = entry
    return {
        "schema": METRICS_SCHEMA,
        "captured_at_monotonic": time.monotonic(),
        "enabled": _enabled,
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
    }


def metrics_reset() -> None:
    """Drop every recorded series (the enabled flag is left as is)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        _reservoirs.clear()
