"""Timing + reporting utilities.

Reproduces the reference's measurement conventions: per-stage wall deltas
printed as t0..t3 on every execute (``fft_mpi_3d_api.cpp:184-201``), GFlops
= 5 N log2 N / t (``fftSpeed3d_c2c.cpp:128``), and the README-style result
block (``/root/reference/README.md:44-58``).

On the axon TPU tunnel ``block_until_ready`` can return before the device
work is observable, so :func:`sync` forces completion by fetching a scalar
slice to the host.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable

import jax
import numpy as np


def sync(x) -> None:
    """Force completion of all computation feeding ``x``."""
    import jax.numpy as jnp

    x = jax.tree_util.tree_leaves(x)[-1]
    x.block_until_ready()
    # Fetch one element; device->host read cannot complete before the
    # producing computation does (robust under the axon async tunnel). The
    # fetched value is made real-valued: complex host transfers are
    # unimplemented on some TPU transports.
    idx = tuple(0 for _ in range(x.ndim))
    v = x[idx]
    if jnp.issubdtype(v.dtype, jnp.complexfloating):
        v = jnp.real(v)
    np.asarray(jax.device_get(v))


def time_fn(fn: Callable, *args, iters: int = 5, warmup: int = 1) -> tuple[float, object]:
    """Best-of-``iters`` wall time of ``fn(*args)`` with forced completion.
    Returns (seconds, last_result)."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
        sync(out)
    best = math.inf
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def time_fn_amortized(
    fn: Callable, *args, iters: int = 10, repeats: int = 3
) -> tuple[float, object]:
    """Per-execution wall time with host-sync latency amortized out.

    JAX dispatch is asynchronous: ``iters`` executions are enqueued
    back-to-back and completion is forced once, so the fixed host<->device
    round-trip (≈80 ms through the axon tunnel; nonzero on any transport)
    is paid once per batch instead of once per execution. Best of
    ``repeats`` batches. This matches the reference's methodology of timing
    ``nt`` executes inside one MPI_Wtime pair (``fftSpeed3d_c2c.cpp:94-98``
    loops `nt` forward executes between two timestamps).
    """
    out = fn(*args)
    sync(out)  # compile + warm
    best = math.inf
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        sync(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best, out


def gflops(shape, seconds: float, real: bool = False) -> float:
    """5 N log2 N / t for complex transforms; a real transform does half the
    work (heFFTe applies the same 0.5 factor for r2c in its benchmark flop
    count), so ``real=True`` halves the model."""
    n = math.prod(shape)
    f = 2.5 if real else 5.0
    return f * n * math.log2(n) / seconds / 1e9


@jax.jit
def _rel_err(result, reference):
    import jax.numpy as jnp

    return jnp.max(jnp.abs(result - reference)) / jnp.max(jnp.abs(reference))


def max_rel_err(result, reference) -> float:
    """Device-side max |result - reference| / max |reference| — the
    roundtrip-error metric of every reference harness
    (``fftSpeed3d_c2c.cpp:85-91``, ``Test_1D.cpp:169-176``)."""
    return float(_rel_err(result, reference))


@dataclass
class StageTimes:
    """t0..t3 stage breakdown (``README.md:44-58`` taxonomy)."""

    times: dict[str, float] = field(default_factory=dict)

    @property
    def total(self) -> float:
        return sum(self.times.values())

    def report(self) -> str:
        lines = [f"  {k}: {v:.6f} s" for k, v in self.times.items()]
        return "\n".join(lines)


def time_staged(stages, x, iters: int = 3) -> tuple[StageTimes, object]:
    """Time a list of (name, fn) stages; each stage's output feeds the next.
    Per-stage times are best-of-``iters`` measured on a fresh pipeline pass
    (stage outputs are re-materialized each iteration since stage jits donate
    their inputs)."""
    best: dict[str, float] = {}
    out = None
    for it in range(iters + 1):  # +1 warmup/compile pass
        cur = x
        for name, fn in stages:
            sync(cur)
            t0 = time.perf_counter()
            cur = fn(cur)
            sync(cur)
            dt = time.perf_counter() - t0
            if it > 0:
                best[name] = min(best.get(name, math.inf), dt)
        out = cur
    return StageTimes(best), out


def result_block(
    shape, ranks: int, seconds: float, max_err: float,
    stage_times: StageTimes | None = None, real: bool = False,
) -> str:
    """Human-readable result in the spirit of the reference's sample output
    (``README.md:44-58``)."""
    lines = []
    if stage_times is not None:
        lines.append(stage_times.report())
    lines += [
        f"size: {shape[0]} {shape[1]} {shape[2]}, ranks: {ranks}",
        f"time: {seconds:.6f} s",
        f"gflops: {gflops(shape, seconds, real=real):.1f}",
        f"max error: {max_err:.3e}",
    ]
    return "\n".join(lines)
