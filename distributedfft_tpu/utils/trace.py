"""Event tracing — the heFFTe tracing subsystem, TPU-native.

The reference gates RAII wall-clock events behind a compile flag and writes
one log file per MPI rank (``heffte/heffteBenchmark/include/heffte_trace.h:48-127``:
``add_trace name("...")`` objects record ``MPI_Wtime`` pairs;
``init_tracing``/``finalize_tracing`` manage a per-rank
``heffte_trace_<id>.log``). The first-party engine prints per-stage wall
deltas on every execute (``fft_mpi_3d_api.cpp:184-201``).

Here the same surface is a runtime-gated (env ``DFFT_TRACE=1`` or
:func:`init_tracing`) context manager that records host-side wall-clock
events per process, doubles as a ``jax.profiler.TraceAnnotation`` so events
land in XLA profiler timelines too, and writes one log per process
(``jax.process_index`` plays the MPI-rank role on multi-host).
"""

from __future__ import annotations

import json
import os
import socket
import time
import math
from contextlib import contextmanager
from dataclasses import dataclass

import jax
import numpy as np

_MB = 1.0 / (1024 * 1024)

TRACE_FORMATS = ("log", "chrome")

_events: list[tuple[str, float, float]] | None = None
_trace_root: str | None = None
_native_rec = None  # native.NativeTrace when the C recorder is in use
_session = 0  # bumped by init/finalize: stale in-flight events are dropped
_format = "log"  # "log" (heFFTe per-rank text) | "chrome" (Perfetto JSON)
# Wall-clock anchor of the current session: events are perf_counter
# pairs; adding _epoch maps them onto the time.time() axis so Chrome
# traces from different processes of one job share a timeline.
_epoch = 0.0
# Tee buffer for capture_events(): spans are appended here IN ADDITION
# to (or instead of) the session recorder while a capture is active.
_capture: list[tuple[str, float, float]] | None = None

#: Default ring capacity of the in-memory Python recorder. Generous — a
#: bench campaign's worth of spans — but finite, so a long-lived serving
#: process with tracing armed reaches a steady footprint instead of
#: growing without bound. Override with ``DFFT_TRACE_MAX_EVENTS`` (0 =
#: unbounded, the pre-ring behavior).
DEFAULT_TRACE_MAX_EVENTS = 1 << 20

_max_events = DEFAULT_TRACE_MAX_EVENTS
_dropped = 0  # oldest-events evicted by the ring this session


def dropped_events() -> int:
    """Events evicted by the ring cap in the current trace session."""
    return _dropped


def _push(ev: list, name: str, start: float, stop: float) -> None:
    """Append one event, evicting the oldest past the ring cap. The ring
    keeps the NEWEST events (under a stall you want the spans nearest
    the incident, not the warm-up) and evicts a capacity/16 chunk at a
    time so the list-shift cost amortizes to O(1) per append."""
    global _dropped
    if _max_events and len(ev) >= _max_events:
        cut = max(1, len(ev) - _max_events + max(1, _max_events // 16))
        del ev[:cut]
        _dropped += cut
        from . import metrics as _metrics

        _metrics.inc("trace_dropped_events", cut)
    ev.append((name, start, stop))


def tracing_enabled() -> bool:
    return _events is not None or _native_rec is not None


def _try_native():
    """The C trace recorder (``dfft_trace_*``, ``native/dfft_native.cpp``)
    when the library is built — lower per-event overhead than the Python
    list (the compile-gated-to-zero-cost discipline of
    ``Heffte_ENABLE_TRACING``). ``DFFT_TRACE_NATIVE=0`` forces the Python
    recorder."""
    if os.environ.get("DFFT_TRACE_NATIVE", "1") == "0":
        return None
    try:
        from .. import native

        rec = native.NativeTrace()
        if not rec.available:
            return None
        rec.init()
        return rec
    except Exception:  # noqa: BLE001 — recorder is best-effort
        return None


def init_tracing(root: str = "", format: str | None = None) -> None:
    """Start collecting events (``init_tracing``, ``heffte_trace.h:90``).
    ``root`` prefixes the log filename written by :func:`finalize_tracing`.

    ``format`` (default: env ``DFFT_TRACE_FORMAT``, else ``"log"``) picks
    the output: ``"log"`` is the heFFTe per-rank text log, ``"chrome"`` a
    Chrome-trace/Perfetto JSON (load in ui.perfetto.dev, or merge across
    processes with ``python -m distributedfft_tpu.report``).

    Re-init while a session is open finalizes the open session first
    (writing its log) — its events are never silently discarded, and a
    native recorder is never dropped with events still buffered.
    """
    global _events, _trace_root, _native_rec, _session, _format, _epoch
    global _max_events, _dropped
    if tracing_enabled():
        finalize_tracing()
    fmt = format or os.environ.get("DFFT_TRACE_FORMAT", "") or "log"
    if fmt not in TRACE_FORMATS:
        raise ValueError(
            f"unknown trace format {fmt!r}; use one of {TRACE_FORMATS}")
    _session += 1
    _trace_root = root or "dfft_trace"
    _format = fmt
    _epoch = time.time() - time.perf_counter()
    try:
        _max_events = int(
            os.environ.get("DFFT_TRACE_MAX_EVENTS", "")
            or DEFAULT_TRACE_MAX_EVENTS)
    except ValueError:
        _max_events = DEFAULT_TRACE_MAX_EVENTS
    _dropped = 0
    # The C recorder dumps the text format only; chrome sessions use the
    # Python recorder (its event list is what the JSON writer serializes).
    _native_rec = _try_native() if fmt == "log" else None
    _events = None if _native_rec is not None else []


def _write_chrome(path: str, events, proc: int, nprocs: int) -> None:
    """Serialize one session's events as Chrome-trace JSON: a ``B``/``E``
    pair per event, ``pid`` = the process index (the MPI-rank role),
    ``ts`` in wall-clock microseconds so per-process files merge onto one
    timeline."""
    trace_events = []
    for name, start, stop in events:
        b = {"name": name, "cat": "dfft", "ph": "B", "pid": proc, "tid": 0,
             "ts": (start + _epoch) * 1e6}
        e = dict(b, ph="E", ts=(stop + _epoch) * 1e6)
        trace_events.extend((b, e))
    # Chrome requires in-order begin/end nesting per (pid, tid). Events
    # are appended at END time (inner before outer); a stable sort on ts
    # with B before E at ties restores begin order and keeps zero-length
    # inner pairs inside their enclosing span.
    trace_events.sort(key=lambda ev: (ev["ts"], ev["ph"] != "B"))
    with open(path, "w") as f:
        # Writer identity: the trace lane's pid is the jax process
        # index (the MPI-rank role), so the OS-level identity rides in
        # metadata — the fleet tooling (report merge --monitor-dir)
        # matches trace lanes to monitor streams through it.
        meta = {"process": proc, "process_count": nprocs,
                "host": socket.gethostname(), "os_pid": os.getpid()}
        if _dropped:
            meta["dropped_events"] = _dropped
        json.dump(
            {
                "displayTimeUnit": "ms",
                "metadata": meta,
                "traceEvents": trace_events,
            },
            f,
        )


def finalize_tracing() -> str | None:
    """Write ``<root>_<process>.log`` (or ``.json`` for the chrome
    format) and stop tracing (``finalize_tracing``,
    ``heffte_trace.h:98-118``). Returns the path."""
    global _events, _trace_root, _native_rec, _session, _dropped
    if not tracing_enabled():
        return None
    _session += 1
    proc, nprocs = jax.process_index(), jax.process_count()
    if _native_rec is not None:
        path = f"{_trace_root}_{proc}.log"
        ok = _native_rec.dump(path, proc, nprocs)
        if not ok:
            # Same contract as the Python recorder's open() raising: a
            # failed dump must not silently discard the events.
            raise OSError(f"native trace dump to {path!r} failed")
        _native_rec = None
    elif _format == "chrome":
        path = f"{_trace_root}_{proc}.json"
        _write_chrome(path, _events, proc, nprocs)
    else:
        path = f"{_trace_root}_{proc}.log"
        t0 = _events[0][1] if _events else 0.0
        with open(path, "w") as f:
            f.write(f"process {proc} of {nprocs}\n")
            if _dropped:
                # Ring-cap evictions, parsed back out by ``report merge``
                # so a truncated timeline is never mistaken for a full one.
                f.write(f"dropped_events {_dropped}\n")
            for name, start, stop in _events:
                f.write(f"{start - t0:14.6f}  {stop - start:12.6f}  {name}\n")
    _events, _trace_root = None, None
    _dropped = 0
    return path


if os.environ.get("DFFT_TRACE", "") not in ("", "0"):
    init_tracing(os.environ.get("DFFT_TRACE_ROOT", "dfft_trace"))


@contextmanager
def add_trace(name: str):
    """Record one named event (RAII ``add_trace``, ``heffte_trace.h:48-66``).

    Always annotates the XLA profiler timeline; wall-clock capture only when
    tracing is initialized. Note: under jit tracing this wraps *dispatch*,
    not device execution — wrap ``block_until_ready`` sections (as the
    benchmark harness does) for true device timings.
    """
    with jax.profiler.TraceAnnotation(name):
        # The C recorder's event table is process-global, so binding the
        # Python handle alone cannot isolate an in-flight event from a
        # finalize/re-init happening inside the block: a stale event id
        # would land in the NEW session's table. The session generation
        # drops such events instead (for the Python recorder, binding the
        # list suffices — a stale append goes to the discarded list).
        sess = _session
        rec = _native_rec
        cap = _capture
        if rec is not None:
            eid = rec.begin(name)
            start = time.perf_counter() if cap is not None else 0.0
            try:
                yield
            finally:
                if cap is not None:
                    cap.append((name, start, time.perf_counter()))
                if _session == sess:
                    rec.end(eid)
            return
        ev = _events
        if ev is None and cap is None:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            stop = time.perf_counter()
            if ev is not None:
                _push(ev, name, start, stop)
            if cap is not None:
                cap.append((name, start, stop))


def record_span(name: str, start: float, stop: float) -> bool:
    """Record one ALREADY-COMPLETED event with explicit
    ``time.perf_counter()`` endpoints — the retroactive counterpart of
    :func:`add_trace` for spans whose start crossed a function boundary
    before anyone knew the span would exist (the serving tier's
    per-request queue-wait: the wait begins at ``submit`` but is only
    attributable when the flush fires). Returns True when the event was
    captured.

    Python recorder only: the C recorder's begin/end API cannot take
    explicit timestamps, and a retroactive span can by definition not
    annotate the XLA profiler timeline — callers that need the native
    path wrap live code in :func:`add_trace` instead."""
    ev = _events
    if ev is None:
        return False
    _push(ev, name, float(start), float(stop))
    return True


@contextmanager
def capture_events():
    """Tee: while the block is active, every Python-recorder span
    (:func:`add_trace`) is ALSO appended to the yielded
    ``(name, start, stop)`` list — even when no trace session is open,
    and without consuming ring capacity from one that is. The overlap
    attribution path (:mod:`...monitor`) wraps one fresh program trace
    in this to read the dispatch interleave without arming or
    disturbing a global session. Captures nest (inner shadows outer);
    the buffer is process-global, so concurrent captures from other
    threads land in the innermost active one."""
    global _capture
    prev = _capture
    buf: list[tuple[str, float, float]] = []
    _capture = buf
    try:
        yield buf
    finally:
        _capture = prev


@contextmanager
def timed_span(name: str):
    """:func:`add_trace` plus wall-clock capture: yields a dict whose
    ``"seconds"`` is filled on exit. For callers that feed the duration
    to the metrics registry as well as the trace timeline (the tuner's
    per-candidate compile/measure spans) — one clock read serves both,
    so the two surfaces can never disagree about a span's length."""
    out = {"seconds": 0.0}
    with add_trace(name):
        start = time.perf_counter()
        try:
            yield out
        finally:
            out["seconds"] = time.perf_counter() - start


#: Canonical stage keys of the reference's per-execute breakdown
#: (``fft_mpi_3d_api.cpp:184-201``) — the join axis of the explain layer.
STAGE_KEYS = ("t0", "t1", "t2", "t3")

#: Stage keys of a fused spectral-operator chain (:mod:`...operators`):
#: the transform taxonomy plus the ``t_mid`` pointwise stage between the
#: forward and inverse halves (final forward FFT, wavenumber-diagonal
#: multiply, first inverse FFT — all in the transposed midpoint layout).
OP_STAGE_KEYS = ("t0", "t1", "t2", "t_mid", "t3")


def stage_key(name: str) -> str | None:
    """Canonical ``t0..t3`` / ``t_mid`` key of a stage/span name, or None.

    Normalizes every variant the chain builders emit — ``t0_fft_yz``,
    ``t2_all_to_all``, ``t2a_exchange_x``/``t2b_exchange_y`` (both map
    to ``t2``), per-chunk overlap spans ``t3_fft_x[4]``, the operator
    chains' ``t_mid``/``t_mid[k]`` midpoint spans — so the
    explain/attribution layer and the regress localization agree on one
    stage taxonomy regardless of which builder produced the span.
    ``t_mid_pointwise`` (the multiply sub-span nested inside ``t_mid``)
    maps to None so device-trace attribution never double-counts it.
    Concurrent-schedule spans (``cc<j>:t2_exchange_...`` — transform j
    of a :func:`~..stagegraph.schedule_concurrent` program) drop the
    transform prefix first, so rollups attribute each interleaved span
    to its t0..t3 key like any other."""
    if name.startswith("cc"):
        head, sep, rest = name.partition(":")
        if sep and head[2:].isdigit():
            name = rest
    if name.startswith("t_mid"):
        rest = name[5:]
        return "t_mid" if (not rest or rest[0] == "[") else None
    if len(name) >= 2 and name[0] == "t" and name[1] in "0123":
        key = name[:2]
        rest = name[2:]
        if not rest or rest[0] in "_[" or rest[:1] in ("a", "b"):
            return key
    return None


def traced_stage(name: str, fn):
    """Wrap one staged-pipeline callable so every call records a named
    event (the per-stage breakdown of ``fft_mpi_3d_api.cpp:184-201`` as
    trace spans). Dispatch-side by the :func:`add_trace` contract — the
    timing harness's sync bracketing still owns true device timings.
    The wrapped callable (usually a jit) stays reachable via
    ``__wrapped__`` so the explain layer can lower/compile individual
    stages for cost analysis."""

    def run(x):
        with add_trace(name):
            return fn(x)

    run.__wrapped__ = fn
    return run


def trace_stages(stages):
    """Apply :func:`traced_stage` to a ``[(name, fn), ...]`` stage list."""
    return [(name, traced_stage(name, fn)) for name, fn in stages]


@dataclass
class CsvRecorder:
    """Benchmark CSV writer, the batchTest recording pattern
    (``templateFFT/batchTest/Test_1D.cpp:186-190`` appends
    size/batch/time/gflops/error rows; outputs mirror
    ``templateFFT/csv/*.csv``)."""

    path: str
    header: tuple[str, ...]

    def __post_init__(self) -> None:
        if not os.path.exists(self.path) or os.path.getsize(self.path) == 0:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "w") as f:
                f.write(",".join(self.header) + "\n")
            return
        # Appending to an existing file: its header must match, or every
        # appended row would be silently misaligned against the columns a
        # downstream reader infers from line 1.
        with open(self.path) as f:
            existing = f.readline().rstrip("\n")
        want = ",".join(self.header)
        if existing != want:
            raise ValueError(
                f"CSV {self.path!r} has header {existing!r}, recorder "
                f"expects {want!r}; refusing to append misaligned rows "
                f"(use a fresh path or matching header)")

    def record(self, *row) -> None:
        if len(row) != len(self.header):
            raise ValueError(f"expected {len(self.header)} fields, got {len(row)}")
        with open(self.path, "a") as f:
            f.write(",".join(str(v) for v in row) + "\n")


def plan_info(plan) -> str:
    """Human-readable plan dump — the ``outputPlanInfo`` analog
    (``fft_mpi_3d_api.cpp:433-464`` writes per-rank plan/exchange tables to
    ``rank_i_gpu_j.txt``); here one string covering every device."""
    if not hasattr(plan, "executor"):  # DDPlan3D: the emulated-f64 tier
        lines = [
            f"plan: {plan.shape} "
            f"({'forward' if plan.forward else 'backward'}, dd tier)",
            f"decomposition: {plan.decomposition}",
            "executor: dd (double-double over exact-sliced bf16 matmuls)",
        ]
        if plan.mesh is not None:
            lines.append(
                "mesh: "
                + " x ".join(f"{n}={s}" for n, s in plan.mesh.shape.items())
                + f" ({plan.mesh.devices.size} devices)"
            )
            lines.append(f"in sharding:  {plan.in_sharding.spec}")
            lines.append(f"out sharding: {plan.out_sharding.spec}")
        return "\n".join(lines)
    lines = [
        f"plan: {plan.in_shape} -> {plan.out_shape} "
        f"({'forward' if plan.forward else 'backward'}"
        f"{', r2c' if plan.real and plan.forward else ''}"
        f"{', c2r' if plan.real and not plan.forward else ''})",
        f"decomposition: {plan.decomposition}",
        f"executor: {plan.executor}",
        f"algorithm: {plan.options.algorithm}",
        f"dtype: {plan.in_dtype} -> {plan.out_dtype}",
    ]
    _oc = getattr(plan.options, "overlap_chunks", None)
    if _oc not in (None, 1):
        lines.append(
            f"overlap: {_oc} chunks (pipelined t2/t3 exchange-compute "
            f"interleave along the bystander axis)")
    _b = getattr(plan, "batch", None)
    if _b is not None:
        lines.append(
            f"batch: {_b} coalesced transforms (one shared exchange per "
            f"t2 stage; batch rides the collectives as a bystander dim)")
    _op = getattr(plan, "op", "")
    if _op:
        lines.append(
            f"operator: fused {_op} (FFT -> pointwise -> iFFT in one "
            f"program; multiplier applied at the transposed t_mid "
            f"midpoint, skipping the cancelling transpose pair)")
    if plan.mesh is not None:
        lines.append(
            "mesh: "
            + " x ".join(f"{n}={s}" for n, s in plan.mesh.shape.items())
            + f" ({plan.mesh.devices.size} devices)"
        )
        lines.append(f"in sharding:  {plan.in_sharding.spec}")
        lines.append(f"out sharding: {plan.out_sharding.spec}")
    if plan.real:
        # The halved axis travels on the plan (Plan3D.r2c_axis) — shape
        # diffing is ambiguous for extents 1 and 2 where N//2+1 == N.
        ax = getattr(plan, "r2c_axis", 2)
        if ax != 2:
            lines.append(
                f"r2c axis: {ax} (canonical chain runs on the transposed "
                f"view; spec/logic rows below are in chain convention)")
    lp = getattr(plan, "logic", None)
    if lp is not None:
        if lp.slab_axes is not None:
            lines.append(f"slab chain: in axis {lp.slab_axes[0]} -> out axis "
                         f"{lp.slab_axes[1]}")
        if lp.pencil_perm is not None:
            lines.append(f"pencil chain: perm {lp.pencil_perm} "
                         f"({lp.pencil_order})")
        if not (lp.in_absorbed and lp.out_absorbed):
            edges = [s for s, ok in (("in", lp.in_absorbed),
                                     ("out", lp.out_absorbed)) if not ok]
            lines.append(f"edge reshards: {', '.join(edges)}")
        if lp.negotiated is not None:
            req, used, reason = lp.negotiated
            lines.append(
                f"device negotiation: requested {req} -> using {used} ({reason})"
            )
        # Exchange payload accounting: true information moved vs bytes on
        # the wire per algorithm (the count-table role of TransInfo /
        # outputPlanInfo, fft_mpi_3d_api.cpp:84-133,433-464).
        if lp.mesh is not None:
            from ..plan_logic import exchange_payloads

            shape_eff = plan.out_shape if (plan.real and plan.forward) else (
                plan.in_shape if plan.real else plan.shape
            )
            itemsize = np.dtype(plan.dtype).itemsize
            for e in exchange_payloads(lp, shape_eff, itemsize):
                t, d, v = e["true_bytes"], e["alltoall_bytes"], e["alltoallv_bytes"]
                ov = lambda x: f"+{(x / t - 1) * 100:.1f}%" if t else "n/a"
                lines.append(
                    f"exchange {e['stage']} ({e['mesh_axis']}, {e['parts']}-way): "
                    f"true {t * _MB:.2f} MB | alltoall {d * _MB:.2f} MB ({ov(d)}) | "
                    f"alltoallv {v * _MB:.2f} MB ({ov(v)})"
                )
        if (lp.decomposition == "slab" and lp.mesh is not None
                and not plan.real):
            # Rank-0 row of the exact per-peer count tables (TransInfo
            # semantics; full tables via native.exchange_table).
            from .. import native

            p = lp.mesh.devices.size
            a_in, a_out = lp.slab_axes or (0, 1)
            oth = 3 - a_in - a_out
            sc, _, rc, _ = native.exchange_table(
                plan.shape[a_in], plan.shape[a_out], plan.shape[oth], p, 0
            )
            lines.append(f"exchange counts[rank0]: send {sc} recv {rc}")
    if getattr(plan, "brick_edges", None) is not None:
        # Overlap-map ring accounting for the brick-I/O edges: true
        # intersection payload vs what the padded ring ships (the
        # send_size/recv_size table role of heffte_reshape3d's overlap
        # maps).
        itemsize = np.dtype(plan.dtype).itemsize
        for label, bs in zip(("in->chain", "chain->out"), plan.brick_edges):
            t = bs.payload_elems * itemsize
            w = bs.wire_elems * itemsize
            ov = f"ratio {bs.wire_ratio:.2f}x" if t else "ratio n/a"
            how = (f"{len(bs.steps)} ring steps" if bs.algorithm == "ring"
                   else "a2av exact counts")
            tbl = ("" if bs.a2av_table_bytes is None else
                   f" | index tables {bs.a2av_table_bytes / 1024:.1f} "
                   f"KB/device (RLE)")
            lines.append(
                f"brick edge {label}: {how}, "
                f"payload {t * _MB:.2f} MB | wire {w * _MB:.2f} MB ({ov})"
                + tbl
            )
    # Per-device memory footprint estimate — the heFFTe benchmark's
    # "MB/rank" report (benchmarks/speed3d.h:156-181) and the reference's
    # getMaxDataCount allocation sizing (fft_mpi_3d_api.cpp:289-316).
    # Intermediates are sized at the plan's PADDED extents (ceil-split
    # pad/crop discipline), which is what the chain actually allocates.
    ndev = plan.mesh.devices.size if plan.mesh is not None else 1
    in_b = math.prod(plan.in_shape) * np.dtype(plan.in_dtype).itemsize
    out_b = math.prod(plan.out_shape) * np.dtype(plan.out_dtype).itemsize
    work = max(in_b, out_b)  # one staged intermediate at a time under jit
    spec = plan.spec
    if spec is not None and hasattr(spec, "in_padded"):
        isz = np.dtype(plan.in_dtype).itemsize
        work = max(work, math.prod(spec.in_padded) * isz,
                   math.prod(spec.out_padded) * isz)
    total = (in_b + out_b + (0 if plan.options.donate else work)) / ndev
    lines.append(
        f"memory/device (est): in {in_b / ndev * _MB:.1f} MB + out "
        f"{out_b / ndev * _MB:.1f} MB"
        + ("" if plan.options.donate else
           f" + work {work / ndev * _MB:.1f} MB")
        + f" ~= {total * _MB:.1f} MB"
        + (" (donating)" if plan.options.donate else "")
    )
    if plan.spec is not None:
        lines.append(f"padded extents: {plan.spec}")
    for label, boxes in (("in", plan.in_boxes), ("out", plan.out_boxes)):
        for i, b in enumerate(boxes):
            lines.append(f"{label} box[{i}]: low={b.low} high={b.high} shape={b.shape}")
    return "\n".join(lines)
