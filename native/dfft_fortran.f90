! dfft_fortran — Fortran 2003 bindings for the transform-time C API.
!
! The heFFTe Fortran surface (SWIG-generated modules over heffte_c,
! heffte/heffteBenchmark/fortran/generated/*.f90) re-designed as a plain
! ISO_C_BINDING module over this framework's C ABI (native/dfft_native.cpp:
! dfft_plan_c2c_3d / dfft_execute_c2c / dfft_destroy_plan_c). Usable from
! any F2003+ compiler inside a Python-hosted process after
! distributedfft_tpu.capi.install_c_api() has been called (see
! distributedfft_tpu/capi.py for the hosting contract).
!
! Buffers are interleaved single-precision complex (complex(c_float_complex)
! arrays pass through unchanged), C-order [nx][ny][nz] worlds — note the
! layout is C-order, so a Fortran-natural (nz, ny, nx) array maps directly.
!
! No Fortran toolchain ships in this repo's build image, so this module is
! provided as source and is NOT exercised by CI (PARITY.md H10 records the
! gap); it compiles with gfortran >= 5 / flang against libdfft_native.so.

module dfft
  use, intrinsic :: iso_c_binding
  implicit none

  integer(c_int), parameter :: DFFT_FORWARD = -1
  integer(c_int), parameter :: DFFT_BACKWARD = 1

  interface
     ! long long dfft_plan_c2c_3d(long long nx, ny, nz, int direction)
     function dfft_plan_c2c_3d(nx, ny, nz, direction) bind(c) result(plan)
       import :: c_long_long, c_int
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: direction
       integer(c_long_long) :: plan
     end function dfft_plan_c2c_3d

     ! int dfft_execute_c2c(long long plan, const float* in, float* out)
     function dfft_execute_c2c(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_float_complex
       integer(c_long_long), value :: plan
       complex(c_float_complex), dimension(*), intent(in) :: input
       complex(c_float_complex), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_c2c

     ! void dfft_destroy_plan_c(long long plan)
     subroutine dfft_destroy_plan_c(plan) bind(c)
       import :: c_long_long
       integer(c_long_long), value :: plan
     end subroutine dfft_destroy_plan_c

     ! int dfft_c_api_ready(void)
     function dfft_c_api_ready() bind(c) result(ready)
       import :: c_int
       integer(c_int) :: ready
     end function dfft_c_api_ready

     ! double dfft_c_selftest(long long nx, ny, nz)
     function dfft_c_selftest(nx, ny, nz) bind(c) result(err)
       import :: c_long_long, c_double
       integer(c_long_long), value :: nx, ny, nz
       real(c_double) :: err
     end function dfft_c_selftest
  end interface

end module dfft
