! dfft_fortran — Fortran 2003 bindings for the transform-time C API.
!
! The heFFTe Fortran surface (SWIG-generated modules over heffte_c,
! heffte/heffteBenchmark/fortran/generated/*.f90) re-designed as a plain
! ISO_C_BINDING module over this framework's C ABI (native/dfft_native.cpp:
! dfft_plan_c2c_3d / dfft_execute_c2c / dfft_destroy_plan_c). Usable from
! any F2003+ compiler inside a Python-hosted process after
! distributedfft_tpu.capi.install_c_api() has been called (see
! distributedfft_tpu/capi.py for the hosting contract).
!
! Buffers are interleaved single-precision complex (complex(c_float_complex)
! arrays pass through unchanged), C-order [nx][ny][nz] worlds — note the
! layout is C-order, so a Fortran-natural (nz, ny, nx) array maps directly.
!
! Verification: tests/test_fortran_binding.py cross-validates every
! bind(c) interface below against the extern "C" declarations in
! dfft_native.cpp (a vendored checker — no Fortran toolchain ships in
! this repo's build image), and CI installs gfortran to compile this
! module plus dfft_fortran_smoke.f90 and run a transform driven from
! Fortran (make -C native fortran).

module dfft
  use, intrinsic :: iso_c_binding
  implicit none

  integer(c_int), parameter :: DFFT_FORWARD = -1
  integer(c_int), parameter :: DFFT_BACKWARD = 1

  interface
     ! long long dfft_plan_c2c_3d(long long nx, ny, nz, int direction)
     function dfft_plan_c2c_3d(nx, ny, nz, direction) bind(c) result(plan)
       import :: c_long_long, c_int
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: direction
       integer(c_long_long) :: plan
     end function dfft_plan_c2c_3d

     ! int dfft_execute_c2c(long long plan, const float* in, float* out)
     function dfft_execute_c2c(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_float_complex
       integer(c_long_long), value :: plan
       complex(c_float_complex), dimension(*), intent(in) :: input
       complex(c_float_complex), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_c2c

     ! void dfft_destroy_plan_c(long long plan)
     subroutine dfft_destroy_plan_c(plan) bind(c)
       import :: c_long_long
       integer(c_long_long), value :: plan
     end subroutine dfft_destroy_plan_c

     ! int dfft_c_api_ready(void)
     function dfft_c_api_ready() bind(c) result(ready)
       import :: c_int
       integer(c_int) :: ready
     end function dfft_c_api_ready

     ! double dfft_c_selftest(long long nx, ny, nz)
     function dfft_c_selftest(nx, ny, nz) bind(c) result(err)
       import :: c_long_long, c_double
       integer(c_long_long), value :: nx, ny, nz
       real(c_double) :: err
     end function dfft_c_selftest

     ! --- typed surface (heffte_c.h:63,141-179 parity) ---

     ! long long dfft_plan_r2c_3d(nx, ny, nz, int direction, int r2c_axis)
     function dfft_plan_r2c_3d(nx, ny, nz, direction, r2c_axis) &
          bind(c) result(plan)
       import :: c_long_long, c_int
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: direction, r2c_axis
       integer(c_long_long) :: plan
     end function dfft_plan_r2c_3d

     ! int dfft_execute_r2c(long long plan, const float* in, float* out)
     function dfft_execute_r2c(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_float
       integer(c_long_long), value :: plan
       real(c_float), dimension(*), intent(in) :: input
       real(c_float), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_r2c

     ! int dfft_execute_c2r(long long plan, const float* in, float* out)
     function dfft_execute_c2r(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_float
       integer(c_long_long), value :: plan
       real(c_float), dimension(*), intent(in) :: input
       real(c_float), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_c2r

     ! long long dfft_plan_z2z_3d(nx, ny, nz, int direction)  (double tier)
     function dfft_plan_z2z_3d(nx, ny, nz, direction) bind(c) result(plan)
       import :: c_long_long, c_int
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: direction
       integer(c_long_long) :: plan
     end function dfft_plan_z2z_3d

     ! int dfft_execute_z2z(long long plan, const double* in, double* out)
     function dfft_execute_z2z(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_double
       integer(c_long_long), value :: plan
       real(c_double), dimension(*), intent(in) :: input
       real(c_double), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_z2z

     ! long long dfft_plan_d2z_3d(nx, ny, nz, int direction, int r2c_axis)
     function dfft_plan_d2z_3d(nx, ny, nz, direction, r2c_axis) &
          bind(c) result(plan)
       import :: c_long_long, c_int
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: direction, r2c_axis
       integer(c_long_long) :: plan
     end function dfft_plan_d2z_3d

     ! int dfft_execute_d2z(long long plan, const double* in, double* out)
     function dfft_execute_d2z(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_double
       integer(c_long_long), value :: plan
       real(c_double), dimension(*), intent(in) :: input
       real(c_double), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_d2z

     ! int dfft_execute_z2d(long long plan, const double* in, double* out)
     function dfft_execute_z2d(plan, input, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_double
       integer(c_long_long), value :: plan
       real(c_double), dimension(*), intent(in) :: input
       real(c_double), dimension(*), intent(out) :: output
       integer(c_int) :: rc
     end function dfft_execute_z2d

     ! --- plan-resident device buffers ---

     ! int dfft_upload(long long plan, const void* in)
     function dfft_upload(plan, input) bind(c) result(rc)
       import :: c_long_long, c_int, c_ptr
       integer(c_long_long), value :: plan
       type(c_ptr), value :: input
       integer(c_int) :: rc
     end function dfft_upload

     ! int dfft_execute_resident(long long plan)
     function dfft_execute_resident(plan) bind(c) result(rc)
       import :: c_long_long, c_int
       integer(c_long_long), value :: plan
       integer(c_int) :: rc
     end function dfft_execute_resident

     ! int dfft_download(long long plan, void* out)
     function dfft_download(plan, output) bind(c) result(rc)
       import :: c_long_long, c_int, c_ptr
       integer(c_long_long), value :: plan
       type(c_ptr), value :: output
       integer(c_int) :: rc
     end function dfft_download

     ! --- typed selftests ---

     ! double dfft_c_selftest_r2c(nx, ny, nz, int r2c_axis)
     function dfft_c_selftest_r2c(nx, ny, nz, r2c_axis) &
          bind(c) result(err)
       import :: c_long_long, c_int, c_double
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: r2c_axis
       real(c_double) :: err
     end function dfft_c_selftest_r2c

     ! double dfft_c_selftest_z2z(nx, ny, nz)
     function dfft_c_selftest_z2z(nx, ny, nz) bind(c) result(err)
       import :: c_long_long, c_double
       integer(c_long_long), value :: nx, ny, nz
       real(c_double) :: err
     end function dfft_c_selftest_z2z

     ! double dfft_c_selftest_resident(nx, ny, nz, int repeats)
     function dfft_c_selftest_resident(nx, ny, nz, repeats) &
          bind(c) result(err)
       import :: c_long_long, c_int, c_double
       integer(c_long_long), value :: nx, ny, nz
       integer(c_int), value :: repeats
       real(c_double) :: err
     end function dfft_c_selftest_resident
  end interface

end module dfft
