! dfft_fortran_smoke — a transform driven from Fortran, end to end.
!
! The run-one-smoke-from-Fortran proof for the binding module (the role
! of heFFTe's fortran test programs over its SWIG modules). Compiled as
! a shared library (make -C native fortran) and invoked from a
! Python-hosted process after distributedfft_tpu.capi.install_c_api():
! the exported entry below plans, executes (forward + backward), and
! destroys a 3D C2C transform purely through the dfft module, computing
! the roundtrip error in Fortran (the reference driver's gate,
! 3dmpifft_opt/fftSpeed3d_c2c.cpp:85-91).
!
! Returns the relative roundtrip max error; negative codes mirror the C
! selftests (-1 bridge missing, -4 execution failure).

function dfft_fortran_smoke(nx, ny, nz) bind(c) result(err)
  use, intrinsic :: iso_c_binding
  use dfft
  implicit none

  integer(c_long_long), value :: nx, ny, nz
  real(c_double) :: err

  integer(c_long_long) :: n, i, fwd, bwd
  complex(c_float_complex), allocatable :: x(:), y(:), z(:)
  real(c_double) :: mx, d

  err = -1.0_c_double
  if (dfft_c_api_ready() == 0) return

  n = nx * ny * nz
  allocate(x(n), y(n), z(n))
  do i = 1, n
     ! the reference driver's ramp init (fftSpeed3d_c2c.cpp:61-63)
     x(i) = cmplx(real(mod(i, 97_c_long_long)) * 1.0e-2, &
                  real(mod(i, 89_c_long_long)) * (-1.0e-2), &
                  kind=c_float_complex)
  end do

  err = -4.0_c_double
  fwd = dfft_plan_c2c_3d(nx, ny, nz, DFFT_FORWARD)
  bwd = dfft_plan_c2c_3d(nx, ny, nz, DFFT_BACKWARD)
  if (fwd >= 0 .and. bwd >= 0) then
     if (dfft_execute_c2c(fwd, x, y) == 0 .and. &
         dfft_execute_c2c(bwd, y, z) == 0) then
        mx = 0.0_c_double
        err = 0.0_c_double
        do i = 1, n
           d = abs(real(z(i) - x(i), c_double))
           if (d > err) err = d
           d = abs(real(aimag(z(i) - x(i)), c_double))
           if (d > err) err = d
           d = abs(real(x(i), c_double))
           if (d > mx) mx = d
           d = abs(real(aimag(x(i)), c_double))
           if (d > mx) mx = d
        end do
        if (mx > 0.0_c_double) err = err / mx
     end if
  end if
  if (fwd >= 0) call dfft_destroy_plan_c(fwd)
  if (bwd >= 0) call dfft_destroy_plan_c(bwd)
  deallocate(x, y, z)
end function dfft_fortran_smoke

! The typed double tier driven from Fortran: z2z roundtrip through the
! dd engine, expected to meet the 1e-11 double gate (test_common.h:138).
function dfft_fortran_smoke_z2z(nx, ny, nz) bind(c) result(err)
  use, intrinsic :: iso_c_binding
  use dfft
  implicit none

  integer(c_long_long), value :: nx, ny, nz
  real(c_double) :: err

  err = -1.0_c_double
  if (dfft_c_api_ready() == 0) return
  err = dfft_c_selftest_z2z(nx, ny, nz)
end function dfft_fortran_smoke_z2z
