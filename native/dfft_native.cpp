// dfft_native — native runtime core for distributedfft_tpu.
//
// TPU-native re-design of the reference's C++ runtime layer: the plan-time
// scheduler that splits one FFT axis into bounded passes (the FFTScheduler
// role, templateFFT/src/templateFFT.cpp:3941-4100 — there bounded by GPU
// shared memory, here by VMEM/MXU factor limits), the processor-grid
// geometry searches (make_procgrid / proc_setup_min_surface,
// heffte_geometry.h:303,589), the uneven-slab exchange count/offset tables
// (TransInfo construction, 3dmpifft_opt/include/fft_mpi_3d_api.cpp:84-133),
// and a low-overhead thread-safe trace-event recorder (the heffte_trace.h
// RAII event log, :48-127).
//
// Pure planning/observability code: no device API calls — device compute
// belongs to XLA/Pallas. Exposed as a C API for ctypes binding
// (distributedfft_tpu/native.py); the Python layer keeps equivalent
// fallbacks, and tests assert bit-identical results between the two.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- version

int dfft_abi_version() { return 3; }

// ------------------------------------------------------------- scheduler
//
// Factor n into at most max_passes factors, each <= max_factor, balanced so
// the largest factor is as small as possible (matmul stages closest to
// square use the MXU best). Returns the number of passes and writes the
// factors (descending) into splits_out, or returns:
//   -1  if n has a prime factor > max_factor (caller switches to Bluestein)
//   -2  if n needs more than max_passes factors of size <= max_factor

static void prime_factors(long long n, std::vector<long long>& out) {
  for (long long p = 2; p * p <= n; ++p) {
    while (n % p == 0) {
      out.push_back(p);
      n /= p;
    }
  }
  if (n > 1) out.push_back(n);
}

int dfft_schedule_axis(long long n, long long max_factor, int max_passes,
                       long long* splits_out) {
  if (n < 1 || max_factor < 2 || max_passes < 1) return -3;
  if (n == 1) {
    splits_out[0] = 1;
    return 1;
  }
  std::vector<long long> primes;
  prime_factors(n, primes);
  for (long long p : primes)
    if (p > max_factor) return -1;

  // Find the smallest pass count that can work at all.
  for (int passes = 1; passes <= max_passes; ++passes) {
    // Feasibility: product must fit in passes factors of <= max_factor.
    // Greedy first-fit-decreasing into `passes` bins (product-balanced).
    std::sort(primes.begin(), primes.end(), std::greater<long long>());
    std::vector<long long> bins(passes, 1);
    bool ok = true;
    for (long long p : primes) {
      // Place into the fullest bin that still fits (keeps factors large and
      // count small), else the emptiest.
      int best = -1;
      for (int b = 0; b < passes; ++b)
        if (bins[b] * p <= max_factor && (best < 0 || bins[b] > bins[best]))
          best = b;
      if (best < 0) {
        ok = false;
        break;
      }
      bins[best] *= p;
    }
    if (!ok) continue;
    // Rebalance pass: repeatedly move a prime from the largest bin to the
    // smallest when that reduces the max factor (keeps stages square-ish).
    for (int iter = 0; iter < 64; ++iter) {
      std::sort(bins.begin(), bins.end(), std::greater<long long>());
      if (bins.back() == 1 && bins.size() > 1) {
        bins.pop_back();  // unused pass
        continue;
      }
      std::vector<long long> f;
      prime_factors(bins.front(), f);
      std::sort(f.begin(), f.end());
      bool moved = false;
      for (long long p : f) {
        long long big = bins.front() / p, small = bins.back() * p;
        if (small <= max_factor &&
            std::max(big, small) < bins.front()) {
          bins.front() = big;
          bins.back() = small;
          moved = true;
          break;
        }
      }
      if (!moved) break;
    }
    std::sort(bins.begin(), bins.end(), std::greater<long long>());
    for (size_t i = 0; i < bins.size(); ++i) splits_out[i] = bins[i];
    return static_cast<int>(bins.size());
  }
  return -2;
}

// -------------------------------------------------------------- geometry

void dfft_procgrid2(long long p, long long* a, long long* b) {
  long long ba = 1, bb = p;
  for (long long x = 1; x * x <= p; ++x)
    if (p % x == 0) {
      ba = x;
      bb = p / x;
    }
  *a = ba;
  *b = bb;
}

void dfft_min_surface_grid(long long nx, long long ny, long long nz,
                           long long p, long long* out3) {
  double best = -1.0;
  for (long long a = 1; a <= p; ++a) {
    if (p % a) continue;
    long long q = p / a;
    for (long long b = 1; b <= q; ++b) {
      if (q % b) continue;
      long long c = q / b;
      double sx = double(nx) / a, sy = double(ny) / b, sz = double(nz) / c;
      double cost = sx * sy + sy * sz + sx * sz;
      if (best < 0.0 || cost < best) {
        best = cost;
        out3[0] = a;
        out3[1] = b;
        out3[2] = c;
      }
    }
  }
}

// 2D pencil grid (rows over axis 0, cols over axis 1) minimizing the input
// z-pencil box surface — the pencil-planner analog of the min-surface
// search above; consulted by logic_plan3d when building a mesh from a
// device count. Ties prefer more rows (the most-square heritage
// orientation). Kept in float lockstep with
// geometry.pencil_grid_min_surface.
void dfft_pencil_grid(long long n0, long long n1, long long n2, long long p,
                      long long* out2) {
  double best = -1.0;
  long long br = 1, bc = p;
  for (long long r = 1; r <= p; ++r) {
    if (p % r) continue;
    long long c = p / r;
    double sx = double(n0) / r, sy = double(n1) / c;
    double cost = sx * sy + sy * double(n2) + sx * double(n2);
    if (best < 0.0 || cost < best || (cost == best && r > br)) {
      best = cost;
      br = r;
      bc = c;
    }
  }
  out2[0] = br;
  out2[1] = bc;
}

// Balanced bounded divisor pair: (n1, n2) with n1 <= n2 <= max_factor and
// n1 maximal (closest to sqrt(n)) — the split rule shared by the MXU-matmul
// four-step recursion and the fused Pallas kernel (the per-axis split
// decision of the reference's FFTScheduler, templateFFT.cpp:3941-4100).
// Returns 0 on success; -1 when no such pair exists (prime n, or n too
// large for the bound).
int dfft_balanced_split(long long n, long long max_factor, long long* out2) {
  for (long long d = (long long)std::sqrt((double)n) + 1; d >= 2; --d) {
    if (d > n) continue;
    if (n % d) continue;
    long long other = n / d;
    if (d > other) continue;  // keep n1 <= n2
    if (other > max_factor) return -1;  // even the most balanced n2 too big
    out2[0] = d;
    out2[1] = other;
    return 0;
  }
  return -1;
}

// -------------------------------------------------------- exchange tables
//
// Uneven-slab redistribution bookkeeping: device r holds X-rows
// [r*c0, min(n0,(r+1)*c0)) with c0 = ceil(n0/p) and after the global
// transpose holds Y-columns [r*c1, min(n1,(r+1)*c1)). The element counts
// each peer pair exchanges are the count tables the reference builds per
// plan (sendCounts/recvCounts/offsets incl. the asymmetric last device,
// fft_mpi_3d_api.cpp:84-133). On TPU the collective itself is a padded
// all_to_all; these tables size the true payloads for plan_info, cost
// models, and the alltoallv-style masked path.

static inline long long owned(long long n, long long chunk, long long r) {
  long long lo = r * chunk;
  if (lo >= n) return 0;
  return std::min(n, lo + chunk) - lo;
}

void dfft_exchange_table(long long n0, long long n1, long long n2,
                         long long p, long long rank,
                         long long* send_counts, long long* send_offsets,
                         long long* recv_counts, long long* recv_offsets) {
  long long c0 = (n0 + p - 1) / p, c1 = (n1 + p - 1) / p;
  long long my_rows = owned(n0, c0, rank);
  long long my_cols = owned(n1, c1, rank);
  long long soff = 0, roff = 0;
  for (long long j = 0; j < p; ++j) {
    long long sc = my_rows * owned(n1, c1, j) * n2;
    long long rc = owned(n0, c0, j) * my_cols * n2;
    send_counts[j] = sc;
    send_offsets[j] = soff;
    recv_counts[j] = rc;
    recv_offsets[j] = roff;
    soff += sc;
    roff += rc;
  }
}

// ----------------------------------------------------------------- trace
//
// Steady-clock event recorder: begin/end pairs by id, dump to a per-process
// log in the same "start  duration  name" shape as the Python tracer (which
// mirrors heffte_trace.h's finalize format).

namespace {
struct TraceEvent {
  std::string name;
  double start;
  double stop;  // < 0 while open
};
std::vector<TraceEvent> g_events;
std::mutex g_mu;
bool g_on = false;

double now_s() {
  using clk = std::chrono::steady_clock;
  return std::chrono::duration<double>(clk::now().time_since_epoch()).count();
}
}  // namespace

void dfft_trace_init() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.clear();
  g_on = true;
}

long long dfft_trace_begin(const char* name) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (!g_on) return -1;
  g_events.push_back({name ? name : "", now_s(), -1.0});
  return static_cast<long long>(g_events.size()) - 1;
}

void dfft_trace_end(long long id) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (!g_on || id < 0 || id >= (long long)g_events.size()) return;
  g_events[id].stop = now_s();
}

long long dfft_trace_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return static_cast<long long>(g_events.size());
}

// ------------------------------------------------------------------ C API
// Transform-time C entries — the heffte_c surface
// (heffte_c.h:52-179: heffte_plan_create / heffte_forward / heffte_backward
// / heffte_plan_destroy) re-designed for a Python-hosted runtime. The
// device runtime of this framework is JAX/XLA; rather than embedding an
// interpreter, the .so holds a function-pointer table that the Python side
// installs at init (distributedfft_tpu.capi.install_c_api — the inverse of
// heffte.py's ctypes-over-libheffte direction). Any C/C++/Fortran code in
// a Python-hosted process can then plan, execute, and destroy transforms
// through the plain C ABI below; buffers are interleaved complex64
// (float re, float im), C-order [nx][ny][nz], full world per call.

typedef long long (*dfft_plan_cb)(long long nx, long long ny, long long nz,
                                  int direction);
typedef int (*dfft_exec_cb)(long long plan_id, const float* in, float* out);
typedef void (*dfft_destroy_cb)(long long plan_id);

// Callback slots are atomics: install/reinstall (e.g. switching the
// active mesh) may race a concurrent native reader, and the Python-side
// lock cannot cover C threads already inside dfft_execute_c2c. Atomics
// rule out torn installs; a reinstall while an execute is in flight is
// still the caller's quiescence problem (the old callback may run one
// more time), which install_c_api documents.
static std::atomic<dfft_plan_cb> g_plan_cb{0};
static std::atomic<dfft_exec_cb> g_exec_cb{0};
static std::atomic<dfft_destroy_cb> g_destroy_cb{0};

void dfft_c_api_install(dfft_plan_cb p, dfft_exec_cb e, dfft_destroy_cb d) {
  g_plan_cb.store(p, std::memory_order_release);
  g_exec_cb.store(e, std::memory_order_release);
  g_destroy_cb.store(d, std::memory_order_release);
}

int dfft_c_api_ready() {
  return (g_plan_cb.load(std::memory_order_acquire) &&
          g_exec_cb.load(std::memory_order_acquire) &&
          g_destroy_cb.load(std::memory_order_acquire))
             ? 1
             : 0;
}

// direction: -1 forward / +1 backward (FFTW sign convention, matching
// distributedfft_tpu.FORWARD/BACKWARD). Returns a plan handle >= 0, or
// -1 when the bridge is not installed / planning failed.
long long dfft_plan_c2c_3d(long long nx, long long ny, long long nz,
                           int direction) {
  dfft_plan_cb cb = g_plan_cb.load(std::memory_order_acquire);
  if (!cb) return -1;
  return cb(nx, ny, nz, direction);
}

// Executes the planned transform: 0 on success.
int dfft_execute_c2c(long long plan, const float* in, float* out) {
  dfft_exec_cb cb = g_exec_cb.load(std::memory_order_acquire);
  if (!cb) return 1;
  return cb(plan, in, out);
}

void dfft_destroy_plan_c(long long plan) {
  dfft_destroy_cb cb = g_destroy_cb.load(std::memory_order_acquire);
  if (cb) cb(plan);
}

// Self-test driven entirely from compiled C: ramp data (the reference
// driver's init, fftSpeed3d_c2c.cpp:61-63), forward + backward through
// the C ABI, returns the relative roundtrip max error (negative on any
// failure). The proof that a C caller owns the full transform lifecycle.
double dfft_c_selftest(long long nx, long long ny, long long nz) {
  if (!dfft_c_api_ready()) return -1.0;
  long long n = nx * ny * nz;
  if (n <= 0) return -2.0;
  float* x = (float*)std::malloc(sizeof(float) * 2 * n);
  float* y = (float*)std::malloc(sizeof(float) * 2 * n);
  float* z = (float*)std::malloc(sizeof(float) * 2 * n);
  if (!x || !y || !z) {
    std::free(x); std::free(y); std::free(z);
    return -3.0;
  }
  for (long long i = 0; i < n; ++i) {
    x[2 * i] = (float)(i % 97) * 1e-2f;      // re
    x[2 * i + 1] = (float)(i % 89) * -1e-2f; // im
  }
  double err = -4.0;
  long long fwd = dfft_plan_c2c_3d(nx, ny, nz, -1);
  long long bwd = dfft_plan_c2c_3d(nx, ny, nz, +1);
  if (fwd >= 0 && bwd >= 0 && dfft_execute_c2c(fwd, x, y) == 0 &&
      dfft_execute_c2c(bwd, y, z) == 0) {
    double mx = 0.0, me = 0.0;
    for (long long i = 0; i < 2 * n; ++i) {
      double ax = x[i] < 0 ? -x[i] : x[i];
      double d = (double)z[i] - (double)x[i];
      if (d < 0) d = -d;
      if (ax > mx) mx = ax;
      if (d > me) me = d;
    }
    err = mx > 0 ? me / mx : me;
  }
  if (fwd >= 0) dfft_destroy_plan_c(fwd);
  if (bwd >= 0) dfft_destroy_plan_c(bwd);
  std::free(x); std::free(y); std::free(z);
  return err;
}

// ------------------------------------------------------ typed C API (v2)
// The full heffte_c type matrix (heffte_c.h:63,141-179): float r2c/c2r
// plans with a selectable halved axis (heffte r2c_direction), and DOUBLE
// transforms — z2z (complex<->complex) and d2z/z2d (real<->complex) —
// carried by the dd (double-double) tier, the framework's f64 surface on
// f32/bf16 hardware. Plus plan-resident device buffers
// (upload / execute_resident / download) so a C driver can repeat-execute
// without a host round-trip per call — the reference benchmark pattern
// (warm + timed loop, fftSpeed3d_c2c.cpp:94-98).
//
// Dispatch rides two generic callbacks the Python runtime installs; the
// typed entry points below are the stable C surface.

// kind: 0 = c2c complex64, 1 = r2c float32/complex64,
//       2 = z2z double (dd tier), 3 = d2z double real (dd tier)
typedef long long (*dfft_plan2_cb)(int kind, long long nx, long long ny,
                                   long long nz, int direction, int axis);
// op: 0 = execute host->host, 1 = upload resident input,
//     2 = execute resident, 3 = download resident output
typedef int (*dfft_exec2_cb)(long long plan, int op, const void* in,
                             void* out);

static std::atomic<dfft_plan2_cb> g_plan2_cb{0};
static std::atomic<dfft_exec2_cb> g_exec2_cb{0};

void dfft_c_api_install_typed(dfft_plan2_cb p, dfft_exec2_cb e) {
  g_plan2_cb.store(p, std::memory_order_release);
  g_exec2_cb.store(e, std::memory_order_release);
}

int dfft_c_api_typed_ready() {
  return (g_plan2_cb.load(std::memory_order_acquire) &&
          g_exec2_cb.load(std::memory_order_acquire))
             ? 1
             : 0;
}

static long long dfft_plan2(int kind, long long nx, long long ny,
                            long long nz, int direction, int axis) {
  dfft_plan2_cb cb = g_plan2_cb.load(std::memory_order_acquire);
  if (!cb) return -1;
  return cb(kind, nx, ny, nz, direction, axis);
}

static int dfft_exec2(long long plan, int op, const void* in, void* out) {
  dfft_exec2_cb cb = g_exec2_cb.load(std::memory_order_acquire);
  if (!cb) return 1;
  return cb(plan, op, in, out);
}

// r2c/c2r, float tier. direction -1 = r2c forward (real in, interleaved
// complex64 half-spectrum out: axis extent naxis/2+1), +1 = c2r inverse.
// r2c_axis in {0,1,2} is heFFTe's r2c_direction.
long long dfft_plan_r2c_3d(long long nx, long long ny, long long nz,
                           int direction, int r2c_axis) {
  return dfft_plan2(1, nx, ny, nz, direction, r2c_axis);
}
int dfft_execute_r2c(long long plan, const float* in, float* out) {
  return dfft_exec2(plan, 0, in, out);
}
int dfft_execute_c2r(long long plan, const float* in, float* out) {
  return dfft_exec2(plan, 0, in, out);
}

// Double tier (dd): buffers are plain C doubles — interleaved complex
// for z2z, real for the d2z input / z2d output. The bridge splits each
// value into the (hi, lo) float32 dd pair on upload and recombines on
// download; accuracy rides the 1e-11 double gate (test_common.h:138).
long long dfft_plan_z2z_3d(long long nx, long long ny, long long nz,
                           int direction) {
  return dfft_plan2(2, nx, ny, nz, direction, 2);
}
int dfft_execute_z2z(long long plan, const double* in, double* out) {
  return dfft_exec2(plan, 0, in, out);
}
long long dfft_plan_d2z_3d(long long nx, long long ny, long long nz,
                           int direction, int r2c_axis) {
  return dfft_plan2(3, nx, ny, nz, direction, r2c_axis);
}
int dfft_execute_d2z(long long plan, const double* in, double* out) {
  return dfft_exec2(plan, 0, in, out);
}
int dfft_execute_z2d(long long plan, const double* in, double* out) {
  return dfft_exec2(plan, 0, in, out);
}

// Plan-resident device buffers (any plan kind): upload once, execute any
// number of times device-side, download once.
int dfft_upload(long long plan, const void* in) {
  return dfft_exec2(plan, 1, in, 0);
}
int dfft_execute_resident(long long plan) {
  return dfft_exec2(plan, 2, 0, 0);
}
int dfft_download(long long plan, void* out) {
  return dfft_exec2(plan, 3, 0, out);
}

// --- C-driven selftests for the typed surface (the proof each typed
// entry carries a real transform end to end from compiled C).

// r2c float: ramp real world, r2c forward then c2r inverse, relative
// roundtrip max error (negative = failure).
double dfft_c_selftest_r2c(long long nx, long long ny, long long nz,
                           int r2c_axis) {
  if (!dfft_c_api_typed_ready()) return -1.0;
  long long n = nx * ny * nz;
  if (n <= 0 || r2c_axis < 0 || r2c_axis > 2) return -2.0;
  long long dims[3] = {nx, ny, nz};
  long long hdims[3] = {nx, ny, nz};
  hdims[r2c_axis] = dims[r2c_axis] / 2 + 1;
  long long nh = hdims[0] * hdims[1] * hdims[2];
  float* x = (float*)std::malloc(sizeof(float) * n);
  float* y = (float*)std::malloc(sizeof(float) * 2 * nh);
  float* z = (float*)std::malloc(sizeof(float) * n);
  if (!x || !y || !z) {
    std::free(x); std::free(y); std::free(z);
    return -3.0;
  }
  for (long long i = 0; i < n; ++i) x[i] = (float)(i % 101) * 1e-2f;
  double err = -4.0;
  long long fwd = dfft_plan_r2c_3d(nx, ny, nz, -1, r2c_axis);
  long long bwd = dfft_plan_r2c_3d(nx, ny, nz, +1, r2c_axis);
  if (fwd >= 0 && bwd >= 0 && dfft_execute_r2c(fwd, x, y) == 0 &&
      dfft_execute_c2r(bwd, y, z) == 0) {
    double mx = 0.0, me = 0.0;
    for (long long i = 0; i < n; ++i) {
      double ax = x[i] < 0 ? -x[i] : x[i];
      double d = (double)z[i] - (double)x[i];
      if (d < 0) d = -d;
      if (ax > mx) mx = ax;
      if (d > me) me = d;
    }
    err = mx > 0 ? me / mx : me;
  }
  if (fwd >= 0) dfft_destroy_plan_c(fwd);
  if (bwd >= 0) dfft_destroy_plan_c(bwd);
  std::free(x); std::free(y); std::free(z);
  return err;
}

// Double z2z roundtrip through the dd tier — the 1e-11 double-gate
// proof from compiled C.
double dfft_c_selftest_z2z(long long nx, long long ny, long long nz) {
  if (!dfft_c_api_typed_ready()) return -1.0;
  long long n = nx * ny * nz;
  if (n <= 0) return -2.0;
  double* x = (double*)std::malloc(sizeof(double) * 2 * n);
  double* y = (double*)std::malloc(sizeof(double) * 2 * n);
  double* z = (double*)std::malloc(sizeof(double) * 2 * n);
  if (!x || !y || !z) {
    std::free(x); std::free(y); std::free(z);
    return -3.0;
  }
  for (long long i = 0; i < n; ++i) {
    x[2 * i] = (double)(i % 97) * 1e-2 + 1e-9 * (double)(i % 7);
    x[2 * i + 1] = (double)(i % 89) * -1e-2;
  }
  double err = -4.0;
  long long fwd = dfft_plan_z2z_3d(nx, ny, nz, -1);
  long long bwd = dfft_plan_z2z_3d(nx, ny, nz, +1);
  if (fwd >= 0 && bwd >= 0 && dfft_execute_z2z(fwd, x, y) == 0 &&
      dfft_execute_z2z(bwd, y, z) == 0) {
    double mx = 0.0, me = 0.0;
    for (long long i = 0; i < 2 * n; ++i) {
      double ax = x[i] < 0 ? -x[i] : x[i];
      double d = z[i] - x[i];
      if (d < 0) d = -d;
      if (ax > mx) mx = ax;
      if (d > me) me = d;
    }
    err = mx > 0 ? me / mx : me;
  }
  if (fwd >= 0) dfft_destroy_plan_c(fwd);
  if (bwd >= 0) dfft_destroy_plan_c(bwd);
  std::free(x); std::free(y); std::free(z);
  return err;
}

// Resident-buffer lifecycle from C: upload once, execute `repeats`
// times device-side, download once; inverse likewise; returns the
// roundtrip error (proves repeat execution without per-call host trips).
double dfft_c_selftest_resident(long long nx, long long ny, long long nz,
                                int repeats) {
  if (!dfft_c_api_ready() || !dfft_c_api_typed_ready()) return -1.0;
  long long n = nx * ny * nz;
  if (n <= 0 || repeats < 1) return -2.0;
  float* x = (float*)std::malloc(sizeof(float) * 2 * n);
  float* y = (float*)std::malloc(sizeof(float) * 2 * n);
  float* z = (float*)std::malloc(sizeof(float) * 2 * n);
  if (!x || !y || !z) {
    std::free(x); std::free(y); std::free(z);
    return -3.0;
  }
  for (long long i = 0; i < n; ++i) {
    x[2 * i] = (float)(i % 61) * 1e-2f;
    x[2 * i + 1] = (float)(i % 53) * -1e-2f;
  }
  double err = -4.0;
  long long fwd = dfft_plan_c2c_3d(nx, ny, nz, -1);
  long long bwd = dfft_plan_c2c_3d(nx, ny, nz, +1);
  if (fwd >= 0 && bwd >= 0 && dfft_upload(fwd, x) == 0) {
    int ok = 0;
    for (int r = 0; r < repeats; ++r) ok |= dfft_execute_resident(fwd);
    if (ok == 0 && dfft_download(fwd, y) == 0 &&
        dfft_upload(bwd, y) == 0 && dfft_execute_resident(bwd) == 0 &&
        dfft_download(bwd, z) == 0) {
      double mx = 0.0, me = 0.0;
      for (long long i = 0; i < 2 * n; ++i) {
        double ax = x[i] < 0 ? -x[i] : x[i];
        double d = (double)z[i] - (double)x[i];
        if (d < 0) d = -d;
        if (ax > mx) mx = ax;
        if (d > me) me = d;
      }
      err = mx > 0 ? me / mx : me;
    }
  }
  if (fwd >= 0) dfft_destroy_plan_c(fwd);
  if (bwd >= 0) dfft_destroy_plan_c(bwd);
  std::free(x); std::free(y); std::free(z);
  return err;
}

int dfft_trace_dump(const char* path, long long process, long long nprocs) {
  std::lock_guard<std::mutex> lk(g_mu);
  std::FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fprintf(f, "process %lld of %lld\n", process, nprocs);
  double t0 = g_events.empty() ? 0.0 : g_events.front().start;
  for (const auto& e : g_events) {
    double dur = e.stop < 0 ? 0.0 : e.stop - e.start;
    std::fprintf(f, "%14.6f  %12.6f  %s\n", e.start - t0, dur,
                 e.name.c_str());
  }
  std::fclose(f);
  g_events.clear();
  g_on = false;
  return 0;
}

}  // extern "C"
