"""Vendored Fortran-interface checker for ``dfft_fortran.f90``.

No Fortran compiler ships in this repo's build image, so an unchecked
``.f90`` would be a claim rather than a component (round-4 verdict, H10).
This checker closes the gap that matters without a toolchain: it parses
every ``bind(c)`` interface in the Fortran module and cross-validates it
— name, arity, argument C-types, pass-by-value vs pointer, return type —
against the *actual* ``extern "C"`` declarations in ``dfft_native.cpp``.
A drifting signature (the bug class a compiler would catch at link/call
time) fails ``tests/test_fortran_binding.py`` on every platform; full
compilation and a Fortran-driven transform run in CI where gfortran is
installed (``make -C native fortran``).

The parser is deliberately narrow: it understands exactly the F2003
ISO_C_BINDING subset the module uses (interface blocks of functions and
subroutines with scalar ``value`` dummies, assumed-size array dummies,
and ``type(c_ptr), value``), and raises on anything it cannot classify —
unknown constructs fail the check rather than pass silently.
"""

from __future__ import annotations

import re
from pathlib import Path

# Fortran declaration -> C type, keyed by (type spec, is_value, is_array).
_F2C = {
    ("integer(c_long_long)", True, False): "long long",
    ("integer(c_int)", True, False): "int",
    ("real(c_double)", True, False): "double",
    ("real(c_float)", False, True): "float*",
    ("real(c_double)", False, True): "double*",
    ("complex(c_float_complex)", False, True): "float*",
    ("type(c_ptr)", True, False): "void*",
}

_F2C_RESULT = {
    "integer(c_long_long)": "long long",
    "integer(c_int)": "int",
    "real(c_double)": "double",
}


def _strip(line: str) -> str:
    return line.split("!", 1)[0].strip()


def _join_continuations(lines):
    out, cur = [], ""
    for raw in lines:
        line = _strip(raw)
        if not line:
            continue
        if cur:
            line = cur + " " + line
            cur = ""
        if line.endswith("&"):
            cur = line[:-1].rstrip()
            continue
        out.append(line)
    if cur:
        out.append(cur)
    return out


def parse_fortran_interfaces(path: str | Path) -> dict[str, dict]:
    """Parse ``bind(c)`` interface bodies: name -> {args, result}.

    ``args`` is an ordered list of (dummy name, c type string); ``result``
    the C return type ("void" for subroutines).
    """
    lines = _join_continuations(Path(path).read_text().splitlines())
    sigs: dict[str, dict] = {}
    i = 0
    head = re.compile(
        r"^(function|subroutine)\s+(\w+)\s*\(([^)]*)\)\s*bind\(c\)"
        r"(?:\s*result\s*\((\w+)\))?\s*$", re.I)
    while i < len(lines):
        m = head.match(lines[i])
        if not m:
            i += 1
            continue
        kind, name, argstr, result_var = m.groups()
        dummies = [a.strip().lower() for a in argstr.split(",") if a.strip()]
        decls: dict[str, tuple[str, bool, bool]] = {}
        i += 1
        while i < len(lines) and not re.match(
                rf"^end\s+{kind}\b", lines[i], re.I):
            line = lines[i]
            i += 1
            if re.match(r"^import\b", line, re.I):
                continue
            dm = re.match(
                r"^(integer\([\w]+\)|real\([\w]+\)|complex\([\w]+\)|"
                r"type\([\w]+\))\s*(.*?)::\s*(.+)$", line, re.I)
            if not dm:
                raise ValueError(f"{name}: unparsed declaration: {line!r}")
            spec, attrs, names = dm.groups()
            spec = spec.lower().replace(" ", "")
            attrs = attrs.lower()
            is_value = "value" in attrs
            is_array = "dimension(*)" in attrs.replace(" ", "")
            for nm in (x.strip().lower() for x in names.split(",")):
                decls[nm] = (spec, is_value, is_array)
        if kind.lower() == "function":
            rv = (result_var or name).lower()
            if rv not in decls:
                raise ValueError(f"{name}: result {rv} undeclared")
            spec, _, _ = decls.pop(rv)
            if spec not in _F2C_RESULT:
                raise ValueError(f"{name}: unmapped result type {spec}")
            result = _F2C_RESULT[spec]
        else:
            result = "void"
        args = []
        for nm in dummies:
            if nm not in decls:
                raise ValueError(f"{name}: dummy {nm} undeclared")
            key = decls[nm]
            if key not in _F2C:
                raise ValueError(f"{name}: unmapped dummy {nm}: {key}")
            args.append((nm, _F2C[key]))
        sigs[name.lower()] = {"args": args, "result": result}
        i += 1
    if not sigs:
        raise ValueError(f"no bind(c) interfaces found in {path}")
    return sigs


_C_TYPE = r"(?:const\s+)?(?:long\s+long|int|double|float|void|char)\s*\**"


def parse_c_exports(path: str | Path, names) -> dict[str, dict]:
    """Extract the extern-C signatures of ``names`` from the C++ source."""
    text = Path(path).read_text()
    out: dict[str, dict] = {}
    for name in names:
        m = re.search(
            rf"^((?:long\s+long|int|double|void))\s+{name}\s*\(([^)]*)\)",
            text, re.M | re.S)
        if not m:
            continue
        ret, argstr = m.groups()
        args = []
        for a in argstr.split(","):
            a = " ".join(a.split())
            if not a:
                continue
            am = re.match(rf"^({_C_TYPE})\s*(\w+)?$", a)
            if not am:
                raise ValueError(f"{name}: unparsed C arg {a!r}")
            t = am.group(1).replace("const ", "").replace(" *", "*").strip()
            # Pointer-ness collapses to one level; spaces normalized.
            t = re.sub(r"\s*\*+", "*", t)
            args.append(t)
        out[name] = {"args": args, "result": " ".join(ret.split())}
    return out


def check(f90_path: str | Path, cpp_path: str | Path) -> list[str]:
    """Return a list of mismatch messages (empty = interfaces line up)."""
    fsigs = parse_fortran_interfaces(f90_path)
    csigs = parse_c_exports(cpp_path, fsigs)
    problems = []
    for name, fs in fsigs.items():
        cs = csigs.get(name)
        if cs is None:
            problems.append(f"{name}: no extern-C definition found")
            continue
        if fs["result"] != cs["result"]:
            problems.append(
                f"{name}: result {fs['result']} (fortran) != "
                f"{cs['result']} (C)")
        fargs = [t for _, t in fs["args"]]
        if len(fargs) != len(cs["args"]):
            problems.append(
                f"{name}: arity {len(fargs)} (fortran) != "
                f"{len(cs['args'])} (C)")
            continue
        for j, (ft, ct) in enumerate(zip(fargs, cs["args"])):
            if ft == "void*" and ct.endswith("*"):
                continue  # type(c_ptr) matches any C pointer
            if ft != ct:
                problems.append(
                    f"{name}: arg {j} {ft} (fortran) != {ct} (C)")
    return problems


if __name__ == "__main__":
    import sys

    here = Path(__file__).parent
    issues = check(here / "dfft_fortran.f90", here / "dfft_native.cpp")
    for msg in issues:
        print("MISMATCH:", msg)
    print(f"{'FAIL' if issues else 'OK'}: "
          f"{len(parse_fortran_interfaces(here / 'dfft_fortran.f90'))} "
          f"interfaces checked")
    sys.exit(1 if issues else 0)
