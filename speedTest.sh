#!/usr/bin/env bash
# Launcher parity with the reference's speedTest.sh
# (3dmpifft_opt/speedTest.sh: `mpirun -np $1 ./distFFTOpt $2 $3 $4 1`):
#
#   ./speedTest.sh <ndev> <NX> <NY> <NZ> [extra speed3d.py flags...]
#
# The MPI rank count becomes the device-mesh size; on a machine without that
# many accelerators, add -cpu to provision a virtual CPU mesh.
#
# WIRE=bf16|int8|none sweeps the on-wire exchange codec column without
# editing the invocation: the value is forwarded as -wire, so a
# campaign runner can do `WIRE=none ./speedTest.sh ...` then
# `WIRE=bf16 ./speedTest.sh ...` then `WIRE=int8 ./speedTest.sh ...`
# and the CSV algorithm column keys the rows apart ('alltoall' vs
# 'alltoall+wbf16' vs 'alltoall+wint8').
#
# MONITOR=<interval_s> (e.g. MONITOR=1) arms the live serving monitor
# (docs/OBSERVABILITY.md "Live monitoring & health"): any serving queue
# the run constructs streams its JSONL sample series into
# benchmarks/results/, archived next to the campaign evidence so
# `report live`/`report health` can replay the run afterwards.
set -euo pipefail
if [ $# -lt 4 ]; then
    echo "usage: $0 <ndev> <NX> <NY> <NZ> [flags...]" >&2
    exit 1
fi
NDEV=$1; NX=$2; NY=$3; NZ=$4; shift 4
HERE="$(dirname "$0")"
if [ -n "${MONITOR:-}" ] && [ "${MONITOR}" != "0" ]; then
    MONITOR_SERIES="$HERE/benchmarks/results/monitor_$(date +%Y%m%d_%H%M%S)_$$.jsonl"
    export DFFT_MONITOR="${MONITOR},${MONITOR_SERIES}"
    echo "live monitor armed: interval=${MONITOR}s series=${MONITOR_SERIES}" >&2
fi
exec python "$HERE/benchmarks/speed3d.py" c2c single \
    "$NX" "$NY" "$NZ" -ndev "$NDEV" ${WIRE:+-wire "$WIRE"} "$@"
