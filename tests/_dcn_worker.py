"""Worker process for the two-process DCN smoke test (test_multihost.py).

Each process owns 4 virtual CPU devices; two processes form the hybrid
(dcn=2) x (slab=4) mesh — the "multiple ranks on one box" strategy of the
reference's test suite (``heffte_add_mpi_test`` -> ``mpiexec -np N``,
``test/CMakeLists.txt:1-7``), with ``jax.distributed.initialize`` playing
MPI_Init (``fftSpeed3d_c2c.cpp:18``).

Usage: python tests/_dcn_worker.py <coordinator_port> <process_id>
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
from jax.sharding import PartitionSpec as P


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import distributedfft_tpu as dfft
    from distributedfft_tpu.parallel import multihost as mh

    mesh = mh.fft_mesh_for()
    assert dict(mesh.shape) == {"dcn": 2, "slab": 4}, dict(mesh.shape)

    shape = (8, 12, 16)
    fwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=np.complex128)
    bwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=np.complex128,
                               direction=dfft.BACKWARD)
    assert fwd.decomposition == "pencil"

    # Deterministic world; every process holds the full reference copy and
    # feeds only its own host-local block (fftSpeed3d_c2c.cpp:59-72 fills
    # each rank's slab the same way).
    rng = np.random.default_rng(4242)
    world = (rng.standard_normal(shape)
             + 1j * rng.standard_normal(shape)).astype(np.complex128)
    # in sharding P('dcn','slab',None): the dcn axis shards axis 0 across
    # processes -> this process's host-local block is its axis-0 slice.
    rows = shape[0] // 2
    local = world[pid * rows:(pid + 1) * rows]
    x = mh.host_local_to_global(mesh, P("dcn", "slab", None), local)

    y = fwd(x)
    got = mh.global_to_host_local(y)
    ref = np.fft.fftn(world)
    err = np.max(np.abs(got - ref)) / np.max(np.abs(ref))
    assert err < 1e-11, f"forward err {err}"

    r = mh.global_to_host_local(bwd(y))
    rerr = np.max(np.abs(r - world))
    assert rerr < 1e-11, f"roundtrip err {rerr}"

    # Arbitrary-brick reshape across the hybrid mesh: the overlap-map ring
    # spans both tiers (some hops cross the process boundary — the DCN
    # analog of heFFTe's multi-rank reshape tests, test_reshape3d.cpp).
    from distributedfft_tpu.geometry import (
        ceil_splits, make_slabs, world_box,
    )
    from distributedfft_tpu.parallel.bricks import plan_brick_reshape

    w = world_box(shape)
    ins = make_slabs(w, 8, axis=2, rule=ceil_splits)
    outs = make_slabs(w, 8, axis=1)
    # BOTH transports cross the process boundary: the padded ppermute
    # ring and the exact-count a2av tier (RLE tables expanded on device;
    # on the CPU backend its all_gather emulation runs the same maps).
    for alg in ("ring", "a2av"):
        fn, bspec = plan_brick_reshape(mesh, ins, outs, algorithm=alg)
        local_stack = np.zeros((4,) + bspec.in_pad, world.dtype)
        for k in range(4):
            b = ins[pid * 4 + k]
            s = b.shape
            local_stack[k, :s[0], :s[1], :s[2]] = world[b.slices()]
        xs = mh.host_local_to_global(
            mesh, P(("dcn", "slab"), None, None, None), local_stack)
        # global_to_host_local allgathers the FULL output stack to every
        # host; validate all 8 bricks (4 landed across the boundary).
        got_stack = np.asarray(mh.global_to_host_local(fn(xs)))
        assert got_stack.shape[0] == 8, got_stack.shape
        for j, b in enumerate(outs):
            s = b.shape
            np.testing.assert_array_equal(
                got_stack[j, :s[0], :s[1], :s[2]], world[b.slices()],
                err_msg=f"algorithm={alg} brick {j}")

    if os.environ.get("DFFT_DCN_DD") == "1":
        # The emulated-double tier across the process boundary: a dd
        # pencil plan over the hybrid (dcn=2) x (slab=4) mesh — the
        # reference's distributed-f64 capability spanning the DCN tier.
        rshape = (8, 8, 8)
        rworld = (rng.standard_normal(rshape)
                  + 1j * rng.standard_normal(rshape)).astype(np.complex128)
        hi, lo = dfft.dd_from_host(rworld)
        pf = dfft.plan_dd_dft_c2c_3d(rshape, mesh)
        pb = dfft.plan_dd_dft_c2c_3d(rshape, mesh, direction=dfft.BACKWARD)
        assert pf.decomposition == "pencil"
        yh, yl = pf(hi, lo)
        got_dd = dfft.dd_to_host(mh.global_to_host_local(yh),
                                 mh.global_to_host_local(yl))
        dd_ref = np.fft.fftn(rworld)
        dd_err = np.max(np.abs(got_dd - dd_ref)) / np.max(np.abs(dd_ref))
        assert dd_err < 1e-11, f"dd forward err {dd_err}"
        bh, bl = pb(yh, yl)
        back = dfft.dd_to_host(mh.global_to_host_local(bh),
                               mh.global_to_host_local(bl))
        dd_rerr = np.max(np.abs(back - rworld)) / np.max(np.abs(rworld))
        assert dd_rerr < 1e-11, f"dd roundtrip err {dd_rerr}"

    mh.sync_global_devices("dcn-smoke-done")
    print(f"DCN_WORKER_OK pid={pid} err={err:.3e} rerr={rerr:.3e}", flush=True)


if __name__ == "__main__":
    main()
