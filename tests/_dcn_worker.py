"""Worker process for the two-process DCN smoke test (test_multihost.py).

Each process owns 4 virtual CPU devices; two processes form the hybrid
(dcn=2) x (slab=4) mesh — the "multiple ranks on one box" strategy of the
reference's test suite (``heffte_add_mpi_test`` -> ``mpiexec -np N``,
``test/CMakeLists.txt:1-7``), with ``jax.distributed.initialize`` playing
MPI_Init (``fftSpeed3d_c2c.cpp:18``).

Usage: python tests/_dcn_worker.py <coordinator_port> <process_id>
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
from jax.sharding import PartitionSpec as P


def main() -> None:
    port, pid = int(sys.argv[1]), int(sys.argv[2])
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import distributedfft_tpu as dfft
    from distributedfft_tpu.parallel import multihost as mh

    mesh = mh.fft_mesh_for()
    assert dict(mesh.shape) == {"dcn": 2, "slab": 4}, dict(mesh.shape)

    shape = (8, 12, 16)
    fwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=np.complex128)
    bwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=np.complex128,
                               direction=dfft.BACKWARD)
    assert fwd.decomposition == "pencil"

    # Deterministic world; every process holds the full reference copy and
    # feeds only its own host-local block (fftSpeed3d_c2c.cpp:59-72 fills
    # each rank's slab the same way).
    rng = np.random.default_rng(4242)
    world = (rng.standard_normal(shape)
             + 1j * rng.standard_normal(shape)).astype(np.complex128)
    # in sharding P('dcn','slab',None): the dcn axis shards axis 0 across
    # processes -> this process's host-local block is its axis-0 slice.
    rows = shape[0] // 2
    local = world[pid * rows:(pid + 1) * rows]
    x = mh.host_local_to_global(mesh, P("dcn", "slab", None), local)

    y = fwd(x)
    got = mh.global_to_host_local(y)
    ref = np.fft.fftn(world)
    err = np.max(np.abs(got - ref)) / np.max(np.abs(ref))
    assert err < 1e-11, f"forward err {err}"

    r = mh.global_to_host_local(bwd(y))
    rerr = np.max(np.abs(r - world))
    assert rerr < 1e-11, f"roundtrip err {rerr}"

    mh.sync_global_devices("dcn-smoke-done")
    print(f"DCN_WORKER_OK pid={pid} err={err:.3e} rerr={rerr:.3e}", flush=True)


if __name__ == "__main__":
    main()
