"""HLO byte-identity pin cases for the stage-graph IR migration.

The chain builders in ``parallel/{slab,pencil,staged}.py`` were migrated
onto the declarative stage-graph IR (``distributedfft_tpu/stagegraph.py``)
with the PR 3 safety net: default plans must compile **byte-identical**
StableHLO before vs after the migration. This module is the single
source of truth for the pinned case matrix — every migrated builder at
its default knobs plus the variant axes (bf16/int8 wire, hierarchical
transport, overlap-K, batch, uneven extents, r2c, fused operators,
staged pipelines).

Two consumers:

- ``python tests/_hlo_pin_cases.py write`` — run against the
  PRE-refactor builders, captures every case's lowered text into
  ``tests/data/hlo_pins/`` plus a manifest recording the jax version
  and environment fingerprint.
- ``tests/test_a2m_stagegraph.py`` — run against the migrated builders,
  compares each case byte-for-byte against the stored capture (skipping
  when the environment fingerprint no longer matches: the pins describe
  THIS container's jax/XLA, not every future one).

Cases lower at the **builder** level (not the plan layer) so the pins
keep meaning even as plan-layer plumbing moves around them.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

# Mirror tests/conftest.py for standalone (capture-time) runs; under
# pytest the conftest already did all of this before we import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DFFT_HW_PROFILE", "0")
os.environ.setdefault("DFFT_THUNK_GUARD", "matmul")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

PIN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "hlo_pins")
MANIFEST = os.path.join(PIN_DIR, "manifest.json")

EVEN = (16, 16, 16)
UNEVEN = (12, 10, 9)
CDT = np.complex128
RDT = np.float64


def _mesh8() -> Mesh:
    from distributedfft_tpu.parallel.mesh import make_mesh

    return make_mesh(8)


def _mesh24() -> Mesh:
    from distributedfft_tpu.parallel.mesh import make_mesh

    return make_mesh((2, 4))


def _hybrid() -> Mesh:
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici"))


def _poisson_mult(shape, cdtype=CDT):
    from distributedfft_tpu.operators import _multiplier_fn, poisson

    return _multiplier_fn(poisson(), shape, cdtype)


def _lower(fn, shape, dtype) -> str:
    return fn.lower(jax.ShapeDtypeStruct(shape, dtype)).as_text()


def _fused(build, in_shape, in_dtype):
    """One fused-builder case: the jitted end-to-end program's text."""

    def run():
        fn, _ = build()
        return [("fn", _lower(fn, in_shape, in_dtype))]

    return run


def _staged(build, in_shape, in_dtype):
    """One staged-builder case: every stage jit's text, chained through
    ``eval_shape`` so each stage lowers on its true boundary shape."""

    def run():
        stages, _ = build()
        out = []
        spec = jax.ShapeDtypeStruct(in_shape, in_dtype)
        for name, fn in stages:
            inner = getattr(fn, "__wrapped__", fn)
            out.append((name, _lower(inner, spec.shape, spec.dtype)))
            spec = jax.eval_shape(inner, spec)
        return out

    return run


def _plan_case(build):
    """One plan-level case: the plan's end-to-end jitted ``fn`` lowered
    on its own declared I/O contract. Used for the brick-I/O edge
    wrappers (whose jit lives above the chain builders) and the serving
    flush dispatch programs (the batched plans ``CoalescingQueue.flush``
    builds) — both must stay byte-identical through the PR 18
    streaming-scheduler / brick-migration refactor."""

    def run():
        plan = build()
        return [("fn", _lower(plan.fn, plan.in_shape, plan.in_dtype))]

    return run


def _brick_boxes():
    """Deterministic uneven box lists over the EVEN world: a non-grid
    unequal-bisection tree in (the general brick case no PartitionSpec
    expresses) and y-slabs out."""
    from distributedfft_tpu.geometry import Box3, make_slabs, world_box

    w = world_box(EVEN)

    def bisect(box, depth):
        if depth == 0:
            return [box]
        ax = max(range(3), key=lambda d: box.shape[d])
        lo, hi = box.low[ax], box.high[ax]
        cut = lo + max(1, (hi - lo) * 2 // 5)  # deliberately unequal
        la = list(box.low), list(box.high)
        la[1][ax] = cut
        lb = list(box.low), list(box.high)
        lb[0][ax] = cut
        a = Box3(tuple(la[0]), tuple(la[1]))
        b = Box3(tuple(lb[0]), tuple(lb[1]))
        return bisect(a, depth - 1) + bisect(b, depth - 1)

    return bisect(w, 3), make_slabs(w, 8, axis=1)


def build_cases() -> dict:
    """name -> zero-arg callable returning ``[(subname, text), ...]``."""
    from distributedfft_tpu.parallel.pencil import (
        build_pencil_fft3d, build_pencil_rfft3d, build_pencil_spectral_op,
    )
    from distributedfft_tpu.parallel.slab import (
        build_slab_fft3d, build_slab_rfft3d, build_slab_spectral_op,
        build_slab_stages,
    )
    from distributedfft_tpu.parallel.staged import (
        build_pencil_rfft_stages, build_pencil_stages,
        build_slab_op_stages, build_slab_rfft_stages,
    )

    m8, m24 = _mesh8(), _mesh24()
    hy = _hybrid()
    n2h = EVEN[2] // 2 + 1
    cases = {
        # ---- fused slab c2c -------------------------------------------
        "slab_c2c_fwd_even": _fused(
            lambda: build_slab_fft3d(m8, EVEN), EVEN, CDT),
        "slab_c2c_bwd_even": _fused(
            lambda: build_slab_fft3d(m8, EVEN, forward=False), EVEN, CDT),
        "slab_c2c_fwd_uneven": _fused(
            lambda: build_slab_fft3d(m8, UNEVEN), UNEVEN, CDT),
        "slab_c2c_fwd_k4": _fused(
            lambda: build_slab_fft3d(m8, EVEN, overlap_chunks=4), EVEN, CDT),
        "slab_c2c_fwd_b3": _fused(
            lambda: build_slab_fft3d(m8, EVEN, batch=3), (3,) + EVEN, CDT),
        "slab_c2c_fwd_bf16": _fused(
            lambda: build_slab_fft3d(m8, EVEN, wire_dtype="bf16"),
            EVEN, CDT),
        "slab_c2c_fwd_int8": _fused(
            lambda: build_slab_fft3d(m8, EVEN, wire_dtype="int8"),
            EVEN, CDT),
        "slab_c2c_fwd_a2av_uneven": _fused(
            lambda: build_slab_fft3d(m8, UNEVEN, algorithm="alltoallv"),
            UNEVEN, CDT),
        "slab_c2c_fwd_ppermute": _fused(
            lambda: build_slab_fft3d(m8, EVEN, algorithm="ppermute"),
            EVEN, CDT),
        "slab_c2c_fwd_hier": _fused(
            lambda: build_slab_fft3d(hy, EVEN, axis_name=("dcn", "ici"),
                                     algorithm="hierarchical"), EVEN, CDT),
        "slab_c2c_fwd_hier_k2": _fused(
            lambda: build_slab_fft3d(hy, EVEN, axis_name=("dcn", "ici"),
                                     algorithm="hierarchical",
                                     overlap_chunks=2), EVEN, CDT),
        "slab_c2c_fwd_donate": _fused(
            lambda: build_slab_fft3d(m8, EVEN, donate=True), EVEN, CDT),
        # ---- fused slab r2c / operator --------------------------------
        "slab_rfft_fwd": _fused(
            lambda: build_slab_rfft3d(m8, EVEN), EVEN, RDT),
        "slab_rfft_bwd": _fused(
            lambda: build_slab_rfft3d(m8, EVEN, forward=False),
            EVEN[:2] + (n2h,), CDT),
        "slab_op_poisson": _fused(
            lambda: build_slab_spectral_op(m8, EVEN, _poisson_mult(EVEN)),
            EVEN, CDT),
        "slab_op_poisson_k2_bf16": _fused(
            lambda: build_slab_spectral_op(
                m8, EVEN, _poisson_mult(EVEN), overlap_chunks=2,
                wire_dtype="bf16"), EVEN, CDT),
        # ---- fused pencil ---------------------------------------------
        "pencil_c2c_fwd_even": _fused(
            lambda: build_pencil_fft3d(m24, EVEN), EVEN, CDT),
        "pencil_c2c_bwd_even": _fused(
            lambda: build_pencil_fft3d(m24, EVEN, forward=False), EVEN, CDT),
        "pencil_c2c_fwd_uneven": _fused(
            lambda: build_pencil_fft3d(m24, UNEVEN), UNEVEN, CDT),
        "pencil_c2c_fwd_k2": _fused(
            lambda: build_pencil_fft3d(m24, EVEN, overlap_chunks=2),
            EVEN, CDT),
        "pencil_c2c_fwd_b2": _fused(
            lambda: build_pencil_fft3d(m24, EVEN, batch=2), (2,) + EVEN,
            CDT),
        "pencil_c2c_fwd_int8": _fused(
            lambda: build_pencil_fft3d(m24, EVEN, wire_dtype="int8"),
            EVEN, CDT),
        "pencil_rfft_fwd": _fused(
            lambda: build_pencil_rfft3d(m24, EVEN), EVEN, RDT),
        "pencil_rfft_bwd": _fused(
            lambda: build_pencil_rfft3d(m24, EVEN, forward=False),
            EVEN[:2] + (n2h,), CDT),
        "pencil_op_poisson": _fused(
            lambda: build_pencil_spectral_op(m24, EVEN,
                                             _poisson_mult(EVEN)),
            EVEN, CDT),
        # ---- staged pipelines -----------------------------------------
        "slab_stages_fwd": _staged(
            lambda: build_slab_stages(m8, EVEN), EVEN, CDT),
        "slab_stages_fwd_k4": _staged(
            lambda: build_slab_stages(m8, EVEN, overlap_chunks=4),
            EVEN, CDT),
        "slab_stages_bwd": _staged(
            lambda: build_slab_stages(m8, EVEN, forward=False), EVEN, CDT),
        "slab_stages_hier": _staged(
            lambda: build_slab_stages(hy, EVEN, axis_name=("dcn", "ici"),
                                      algorithm="hierarchical"), EVEN, CDT),
        "slab_stages_hier_k2": _staged(
            lambda: build_slab_stages(hy, EVEN, axis_name=("dcn", "ici"),
                                      algorithm="hierarchical",
                                      overlap_chunks=2), EVEN, CDT),
        "pencil_stages_fwd": _staged(
            lambda: build_pencil_stages(m24, EVEN), EVEN, CDT),
        "pencil_stages_bwd": _staged(
            lambda: build_pencil_stages(m24, EVEN, forward=False),
            EVEN, CDT),
        "pencil_stages_fwd_b2": _staged(
            lambda: build_pencil_stages(m24, EVEN, batch=2), (2,) + EVEN,
            CDT),
        "slab_rfft_stages_fwd": _staged(
            lambda: build_slab_rfft_stages(m8, EVEN), EVEN, RDT),
        "slab_rfft_stages_bwd": _staged(
            lambda: build_slab_rfft_stages(m8, EVEN, forward=False),
            EVEN[:2] + (n2h,), CDT),
        "pencil_rfft_stages_fwd": _staged(
            lambda: build_pencil_rfft_stages(m24, EVEN), EVEN, RDT),
        "pencil_rfft_stages_bwd": _staged(
            lambda: build_pencil_rfft_stages(m24, EVEN, forward=False),
            EVEN[:2] + (n2h,), CDT),
        "slab_op_stages_poisson": _staged(
            lambda: build_slab_op_stages(m8, EVEN, _poisson_mult(EVEN)),
            EVEN, CDT),
    }
    cases.update(_brick_and_serve_cases(m8))
    return cases


def _brick_and_serve_cases(m8) -> dict:
    """The PR 18 pin additions: the brick-I/O edge wrappers (captured
    before their migration onto the stagegraph builders) and the serving
    flush dispatch programs (captured before the streaming-scheduler
    refactor — the non-streaming ``flush()`` path must stay
    byte-identical)."""
    import distributedfft_tpu as dfft
    from distributedfft_tpu.geometry import make_slabs, world_box

    ins, outs = _brick_boxes()
    n2h = EVEN[2] // 2 + 1
    r2c_outs = make_slabs(world_box(EVEN[:2] + (n2h,)), 8, axis=0)
    ins_ord = [b.with_order((1, 2, 0)) if i % 3 == 0 else b
               for i, b in enumerate(ins)]
    outs_ord = [b.with_order((2, 0, 1)) if i == 1 else b
                for i, b in enumerate(outs)]
    solo_in = [world_box(EVEN).with_order((2, 0, 1))]
    solo_out = [world_box(EVEN)]
    return {
        # ---- brick-I/O edges (migrated onto stagegraph builders) ------
        "brick_c2c_ring": _plan_case(
            lambda: dfft.plan_brick_dft_c2c_3d(EVEN, m8, ins, outs,
                                               dtype=CDT)),
        "brick_c2c_a2av": _plan_case(
            lambda: dfft.plan_brick_dft_c2c_3d(EVEN, m8, ins, outs,
                                               dtype=CDT,
                                               algorithm="alltoallv")),
        "brick_c2c_order": _plan_case(
            lambda: dfft.plan_brick_dft_c2c_3d(EVEN, m8, ins_ord,
                                               outs_ord, dtype=CDT)),
        "brick_c2c_donate": _plan_case(
            lambda: dfft.plan_brick_dft_c2c_3d(EVEN, m8, ins, outs,
                                               dtype=CDT, donate=True)),
        "brick_r2c_fwd": _plan_case(
            lambda: dfft.plan_brick_dft_r2c_3d(EVEN, m8, ins, r2c_outs,
                                               dtype=CDT)),
        "brick_c2c_single": _plan_case(
            lambda: dfft.plan_brick_dft_c2c_3d(EVEN, None, solo_in,
                                               solo_out, dtype=CDT)),
        # ---- serving flush dispatch programs --------------------------
        "serve_flush_b1": _plan_case(
            lambda: dfft.plan_dft_c2c_3d(EVEN, m8, dtype=CDT)),
        "serve_flush_b3": _plan_case(
            lambda: dfft.plan_dft_c2c_3d(EVEN, m8, dtype=CDT, batch=3)),
    }


def env_fingerprint() -> dict:
    """What the captures are pinned to: a byte-level HLO pin only means
    something on the same jax/numpy/x64/device-count stack."""
    return {
        "jax": jax.__version__,
        "numpy": np.__version__,
        "x64": bool(jax.config.jax_enable_x64),
        "devices": len(jax.devices()),
        "platform": jax.default_backend(),
    }


def _case_path(name: str, sub: str) -> str:
    return os.path.join(PIN_DIR, f"{name}__{sub}.txt")


def write_captures() -> None:
    os.makedirs(PIN_DIR, exist_ok=True)
    manifest = {"env": env_fingerprint(), "cases": {}}
    for name, run in sorted(build_cases().items()):
        subs = {}
        for sub, text in run():
            path = _case_path(name, sub)
            with open(path, "w") as f:
                f.write(text)
            subs[sub] = hashlib.sha256(text.encode()).hexdigest()
            print(f"captured {name}__{sub}: {len(text)} bytes")
        manifest["cases"][name] = subs
    with open(MANIFEST, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    print(f"wrote {MANIFEST}")


def write_new_captures() -> None:
    """Capture ONLY cases absent from the existing manifest and merge
    them in — the targeted pre-refactor capture for pin additions
    (``write`` would re-capture everything, silently re-baselining any
    regression in the already-pinned cases)."""
    man = read_manifest()
    if man is None:
        write_captures()
        return
    if man.get("env") != env_fingerprint():
        raise SystemExit(
            f"environment moved since the original capture: "
            f"{man.get('env')} != {env_fingerprint()}; a merged manifest "
            f"would mix incomparable pins")
    fresh = 0
    for name, run in sorted(build_cases().items()):
        if name in man["cases"]:
            continue
        subs = {}
        for sub, text in run():
            with open(_case_path(name, sub), "w") as f:
                f.write(text)
            subs[sub] = hashlib.sha256(text.encode()).hexdigest()
            print(f"captured {name}__{sub}: {len(text)} bytes")
        man["cases"][name] = subs
        fresh += 1
    with open(MANIFEST, "w") as f:
        json.dump(man, f, indent=1, sort_keys=True)
    print(f"merged {fresh} new case(s) into {MANIFEST}")


def read_manifest() -> dict | None:
    try:
        with open(MANIFEST) as f:
            return json.load(f)
    except OSError:
        return None


def load_capture(name: str, sub: str) -> str:
    with open(_case_path(name, sub)) as f:
        return f.read()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "write":
        write_captures()
    elif len(sys.argv) > 1 and sys.argv[1] == "write-new":
        write_new_captures()
    else:
        print(__doc__)
