"""Test harness configuration.

Multi-device tests run on a virtual 8-device CPU mesh — the TPU-framework
analog of heFFTe's "multiple MPI ranks on one machine" CI strategy
(``heffte/heffteBenchmark/test/CMakeLists.txt:1-7``). x64 is enabled so the
double-precision 1e-11 tolerance tier (``test/test_common.h:138``) is
meaningful; the real-TPU benchmark path runs complex64 (TPU has no C128).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# The calibrated hardware profile is machine-local mutable state (and
# every measured tournament persists model-correction ratios into it);
# reading a developer's real profile — or writing into it — would make
# model-ranking tests nondeterministic across machines. Disabled here;
# the profile tests point DFFT_HW_PROFILE at their own tmp files.
os.environ.setdefault("DFFT_HW_PROFILE", "0")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu" at
# interpreter start, overriding the env var — point the config back at cpu
# before any backend is initialized.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the dd tier's programs are hundreds of
# matmuls and dominate suite wall time on a small box; repeat runs (the
# driver's test gate, local iteration) hit the cache and skip those
# compiles entirely. Cold runs are unaffected. The default path is
# per-user (uid suffix) so a shared /tmp can be neither pre-squatted
# (permission failures) nor poisoned by another account.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "DFFT_TEST_CACHE", f"/tmp/dfft_jax_cache_{os.getuid()}"
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (big-compile duplicates and "
             "deep parameterizations)")


@pytest.fixture
def chaos():
    """Deterministic fault injection scoped to one test: yields ``arm``,
    a callable that sets ``DFFT_FAULT_INJECT`` to a spec (see
    docs/ROBUSTNESS.md for the grammar) with fresh counters/seeds.
    Teardown restores the prior env value and resets every armed fault —
    even when the test fails — so chaos can never leak into the next
    test (the tier-1 suite depends on the default path staying clean)."""
    from distributedfft_tpu import faults

    old = os.environ.get("DFFT_FAULT_INJECT")

    def arm(spec: str) -> None:
        os.environ["DFFT_FAULT_INJECT"] = spec
        faults.reset()  # fresh counters: each arm starts sequence #1

    try:
        yield arm
    finally:
        if old is None:
            os.environ.pop("DFFT_FAULT_INJECT", None)
        else:
            os.environ["DFFT_FAULT_INJECT"] = old
        faults.reset()


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    # config.args holds only the positional selectors (never option
    # values like --deselect's), so a "::" here is a real node ID.
    if any("::" in a for a in config.args):
        return  # an explicitly-named node ID always runs
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
