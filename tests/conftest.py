"""Test harness configuration.

Multi-device tests run on a virtual 8-device CPU mesh — the TPU-framework
analog of heFFTe's "multiple MPI ranks on one machine" CI strategy
(``heffte/heffteBenchmark/test/CMakeLists.txt:1-7``). x64 is enabled so the
double-precision 1e-11 tolerance tier (``test/test_common.h:138``) is
meaningful; the real-TPU benchmark path runs complex64 (TPU has no C128).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# The calibrated hardware profile is machine-local mutable state (and
# every measured tournament persists model-correction ratios into it);
# reading a developer's real profile — or writing into it — would make
# model-ranking tests nondeterministic across machines. Disabled here;
# the profile tests point DFFT_HW_PROFILE at their own tmp files.
os.environ.setdefault("DFFT_HW_PROFILE", "0")
# fft-thunk retirement opt-in: the environment's XLA:CPU has a known
# fft-thunk layout bug (fft_thunk.cc:69 RET_CHECK on uneven inverse
# pencil chains) whose INTERNAL error permanently poisons the process's
# sharded dispatch stream — for years the single fault cascaded into
# ~177 collateral tier-1 failures. The guard routes exactly those
# chain geometries through the matmul executor (dot_generals never
# touch the FFT thunk; api._thunk_guard_executor documents the class),
# so the fault never fires and every downstream 8-device test sees a
# clean backend. Unset outside the suite: default planning is
# HLO-identical to the unguarded build.
os.environ.setdefault("DFFT_THUNK_GUARD", "matmul")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon TPU plugin (sitecustomize) force-sets jax_platforms="axon,cpu" at
# interpreter start, overriding the env var — point the config back at cpu
# before any backend is initialized.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the dd tier's programs are hundreds of
# matmuls and dominate suite wall time on a small box; repeat runs (the
# driver's test gate, local iteration) hit the cache and skip those
# compiles entirely. Cold runs are unaffected. The default path is
# per-user (uid suffix) so a shared /tmp can be neither pre-squatted
# (permission failures) nor poisoned by another account.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "DFFT_TEST_CACHE", f"/tmp/dfft_jax_cache_{os.getuid()}"
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (big-compile duplicates and "
             "deep parameterizations)")


@pytest.fixture
def chaos():
    """Deterministic fault injection scoped to one test: yields ``arm``,
    a callable that sets ``DFFT_FAULT_INJECT`` to a spec (see
    docs/ROBUSTNESS.md for the grammar) with fresh counters/seeds.
    Teardown restores the prior env value and resets every armed fault —
    even when the test fails — so chaos can never leak into the next
    test (the tier-1 suite depends on the default path staying clean)."""
    from distributedfft_tpu import faults

    old = os.environ.get("DFFT_FAULT_INJECT")

    def arm(spec: str) -> None:
        os.environ["DFFT_FAULT_INJECT"] = spec
        faults.reset()  # fresh counters: each arm starts sequence #1

    try:
        yield arm
    finally:
        if old is None:
            os.environ.pop("DFFT_FAULT_INJECT", None)
        else:
            os.environ["DFFT_FAULT_INJECT"] = old
        faults.reset()


def pytest_collection_modifyitems(config, items):
    # An explicit file/node selection on the command line orders items
    # by the invocation, deliberately — the convention governs the
    # alphabetical DIRECTORY collection the tier-1 suite runs with.
    if not any(a.endswith(".py") or "::" in a for a in config.args):
        _check_poison_collection_order(items)
    if config.getoption("--runslow"):
        return
    # config.args holds only the positional selectors (never option
    # values like --deselect's), so a "::" here is a real node ID.
    if any("::" in a for a in config.args):
        return  # an explicitly-named node ID always runs
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


#: Filename convention of the clean-backend test tier: files whose
#: 8-device executions require an unpoisoned dispatch stream are named
#: ``test_a2<letter>_*.py`` so alphabetical collection places them before
#: ``test_alltoallv.py`` (the first file whose chains may trip the
#: XLA:CPU fft-thunk fault when the guard above is off). One conftest
#: check derives the rule from the convention — PRs add a file matching
#: the pattern and are covered automatically, instead of hand-extending
#: a name list every time (the pre-PR-12 maintenance rule in
#: test_explain.py).
CLEAN_BACKEND_PATTERN = "test_a2"
POISON_FILE = "test_alltoallv.py"


def clean_backend_files() -> list[str]:
    """Every committed clean-backend-tier test file (the convention the
    collection-order check below and test_explain's guard both derive
    from)."""
    tests = os.path.dirname(os.path.abspath(__file__))
    return sorted(n for n in os.listdir(tests)
                  if n.startswith(CLEAN_BACKEND_PATTERN)
                  and n.endswith(".py"))


def _check_poison_collection_order(items) -> None:
    """Fail the run loudly at collection when any clean-backend-tier
    file would collect after the poison file — a renamed file silently
    breaking the convention used to resurface as hundreds of mysterious
    downstream failures."""
    first_poison = None
    for idx, item in enumerate(items):
        name = os.path.basename(str(getattr(item, "fspath", "")))
        if name == POISON_FILE and first_poison is None:
            first_poison = idx
        elif (name.startswith(CLEAN_BACKEND_PATTERN)
              and first_poison is not None):
            raise pytest.UsageError(
                f"{name} collected after {POISON_FILE}: the clean-"
                f"backend tier (files named {CLEAN_BACKEND_PATTERN}*) "
                f"must collect first — rename the file to keep the "
                f"alphabetical convention")
