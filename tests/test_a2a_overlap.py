"""Pipelined exchange/compute overlap (``overlap_chunks``).

The overlapped mode re-expresses the reference's ``MPI_Waitany`` overlap
loop (``fft_mpi_3d_api.cpp:610-699``; heFFTe pipelined p2p,
``src/heffte_reshape3d.cpp:497-625``) as K independent per-chunk
collectives XLA's async scheduler can hoist under compute. These tests
pin its two contracts on the 8-way CPU mesh:

1. **Bit parity** — chunking is along a batch (bystander) axis only, so
   every per-chunk exchange and FFT sees exactly the lines the monolithic
   path sees: ``overlap_chunks=K`` output must equal ``overlap_chunks=1``
   bit for bit, for every transport x decomposition, even and uneven
   shapes, K dividing the batch axis or not.
2. **Lowering** — ``overlap_chunks=K`` compiles to exactly K mesh
   collectives per exchange (no silent fusion back to 1, no accidental
   2K); the ppermute ring scales its (P-1) steps by K. The
   ``test_plan_min_reshape`` HLO-count pattern.

Plus the plumbing: ``DFFT_OVERLAP`` env -> PlanOptions -> builders,
the ``auto`` block-bytes heuristic, per-chunk trace spans, and the
run-record schema rule that overlapped and monolithic records never
share a compare baseline.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py``. The environment's XLA:CPU has a known fft-thunk
layout bug (``fft_thunk.cc:69`` RET_CHECK on uneven r2c/c2r pencil
chains — pre-existing, fails at K=1 on the untouched chain too) whose
INTERNAL error permanently poisons the process's sharded dispatch
stream; once any earlier test trips it, every later 8-device execute
fails regardless of correctness. The bit-parity assertions here need a
clean backend, and this file itself triggers no fft-layout fault (it
avoids the one bad chain geometry), so running first is safe for the
rest of the suite.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import regress
from distributedfft_tpu.parallel.exchange import overlap_chunk_bounds
from distributedfft_tpu.plan_logic import (
    OVERLAP_AUTO_MAX_CHUNKS,
    PlanOptions,
    auto_overlap_chunks,
    resolve_overlap_chunks,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 16)
UNEVEN = (12, 10, 9)
CDT = jnp.complex128

ALGS = ("alltoall", "alltoallv", "ppermute")

_COLLECTIVE = re.compile(
    r"\b(all-to-all|all-gather|all-reduce|collective-permute)(?:-start)?\("
)


def _collectives(plan) -> list[str]:
    txt = plan.fn.lower(
        jax.ShapeDtypeStruct(plan.in_shape, plan.in_dtype)
    ).compile().as_text()
    return _COLLECTIVE.findall(txt)


def _world(shape=SHAPE, seed=7, real=False):
    rng = np.random.default_rng(seed)
    r = rng.standard_normal(shape)
    return r if real else r + 1j * rng.standard_normal(shape)


def _pair(plan_kw_base: dict, k: int):
    """(monolithic, overlapped-K) plan pair sharing every other knob."""
    mono = dfft.plan_dft_c2c_3d(**plan_kw_base)
    over = dfft.plan_dft_c2c_3d(**plan_kw_base, overlap_chunks=k)
    return mono, over


# ------------------------------------------------------------ chunk bounds

def test_overlap_chunk_bounds():
    # Balanced splits: K not dividing the extent still yields K non-empty
    # chunks that tile the axis in order.
    assert overlap_chunk_bounds(10, 4) == [(0, 3), (3, 6), (6, 8), (8, 10)]
    assert overlap_chunk_bounds(16, 2) == [(0, 8), (8, 16)]
    # K past the extent clamps to one chunk per element; K<=1 is one chunk.
    assert overlap_chunk_bounds(3, 16) == [(0, 1), (1, 2), (2, 3)]
    assert overlap_chunk_bounds(10, 1) == [(0, 10)]
    for extent, k in [(10, 4), (9, 3), (7, 5), (1, 4)]:
        b = overlap_chunk_bounds(extent, k)
        assert b[0][0] == 0 and b[-1][1] == extent
        assert all(b[i][1] == b[i + 1][0] for i in range(len(b) - 1))
        assert all(hi > lo for lo, hi in b)


# ------------------------------------------------------------- bit parity

@pytest.mark.parametrize("alg", ALGS)
@pytest.mark.parametrize("shape", [SHAPE, UNEVEN])
@pytest.mark.parametrize("k", [2, 3])
def test_slab_parity_bitwise(alg, shape, k):
    """K=3 never divides these batch axes (16, 9): the balanced-split
    bounds must still reproduce the monolithic result exactly."""
    mesh = dfft.make_mesh(8)
    mono, over = _pair(
        dict(shape=shape, mesh=mesh, dtype=CDT, algorithm=alg), k)
    x = jnp.asarray(_world(shape))
    assert np.array_equal(np.asarray(over(x)), np.asarray(mono(x)))


@pytest.mark.parametrize("alg", ALGS)
@pytest.mark.parametrize("shape,k", [(SHAPE, 2), (UNEVEN, 3)])
def test_pencil_parity_bitwise(alg, shape, k):
    mesh = dfft.make_mesh((2, 4))
    mono, over = _pair(
        dict(shape=shape, mesh=mesh, dtype=CDT, algorithm=alg), k)
    x = jnp.asarray(_world(shape))
    assert np.array_equal(np.asarray(over(x)), np.asarray(mono(x)))


def test_overlap_exceeding_batch_axis_clamps():
    """K far past the bystander extent clamps to one chunk per line and
    stays exact."""
    mesh = dfft.make_mesh(8)
    mono, over = _pair(dict(shape=UNEVEN, mesh=mesh, dtype=CDT), 64)
    x = jnp.asarray(_world(UNEVEN))
    assert np.array_equal(np.asarray(over(x)), np.asarray(mono(x)))


@pytest.mark.parametrize("direction", [dfft.FORWARD, dfft.BACKWARD])
@pytest.mark.parametrize("shape", [SHAPE, UNEVEN])
def test_slab_r2c_c2r_parity_bitwise(direction, shape):
    mesh = dfft.make_mesh(8)
    kw = dict(mesh=mesh, dtype=CDT, direction=direction)
    mono = dfft.plan_dft_r2c_3d(shape, **kw)
    over = dfft.plan_dft_r2c_3d(shape, **kw, overlap_chunks=3)
    if direction == dfft.FORWARD:
        x = jnp.asarray(_world(shape, real=True))
    else:
        x = jnp.asarray(np.fft.rfftn(_world(shape, real=True)))
    assert np.array_equal(np.asarray(over(x)), np.asarray(mono(x)))


@pytest.mark.parametrize("direction", [dfft.FORWARD, dfft.BACKWARD])
def test_pencil_r2c_c2r_parity_bitwise(direction):
    # Backward uses the even shape: the uneven pencil c2r chain trips a
    # pre-existing XLA:CPU fft-thunk layout RET_CHECK at K=1 already
    # (irfft of an unevenly-cropped pencil operand) — independent of the
    # overlap mode, whose parity is what this test pins.
    shape = UNEVEN if direction == dfft.FORWARD else SHAPE
    mesh = dfft.make_mesh((2, 4))
    kw = dict(mesh=mesh, dtype=CDT, direction=direction)
    mono = dfft.plan_dft_r2c_3d(shape, **kw)
    over = dfft.plan_dft_r2c_3d(shape, **kw, overlap_chunks=2)
    if direction == dfft.FORWARD:
        x = jnp.asarray(_world(shape, real=True))
    else:
        x = jnp.asarray(np.fft.rfftn(_world(shape, real=True)))
    assert np.array_equal(np.asarray(over(x)), np.asarray(mono(x)))


@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_dd_parity_bitwise(mesh_shape):
    """Both dd components ride the chunked schedule; the dd matmul engine
    is line-independent, so the pair stays bit-identical too."""
    mesh = dfft.make_mesh(mesh_shape)
    mono = dfft.plan_dd_dft_c2c_3d(SHAPE, mesh)
    over = dfft.plan_dd_dft_c2c_3d(SHAPE, mesh, overlap_chunks=3)
    rng = np.random.default_rng(3)
    hi = jnp.asarray((rng.standard_normal(SHAPE)
                      + 1j * rng.standard_normal(SHAPE)).astype(np.complex64))
    lo = jnp.asarray((rng.standard_normal(SHAPE) * 2.0 ** -25
                      + 0j).astype(np.complex64))
    a, b = mono(hi, lo), over(hi, lo)
    for u, v in zip(a, b):
        assert np.array_equal(np.asarray(u), np.asarray(v))


@pytest.mark.parametrize("alg", ALGS)
def test_staged_slab_parity_bitwise(alg):
    """The staged t2 stage with overlap_chunks=K produces the exact
    monolithic stage output (chunks of one exchange, concatenated)."""
    from distributedfft_tpu.parallel.slab import build_slab_stages

    mesh = dfft.make_mesh(8)
    s1, _ = build_slab_stages(mesh, SHAPE, algorithm=alg, overlap_chunks=1)
    s3, _ = build_slab_stages(mesh, SHAPE, algorithm=alg, overlap_chunks=3)
    x = jnp.asarray(_world())
    a, b = x, x
    for (_, f1), (_, f3) in zip(s1, s3):
        a, b = f1(a), f3(b)
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_staged_pencil_parity_bitwise():
    from distributedfft_tpu.parallel.staged import build_pencil_stages

    mesh = dfft.make_mesh((2, 4))
    s1, _ = build_pencil_stages(mesh, UNEVEN, overlap_chunks=1)
    s2, _ = build_pencil_stages(mesh, UNEVEN, overlap_chunks=2)
    x = jnp.asarray(_world(UNEVEN))
    a, b = x, x
    for (_, f1), (_, f2) in zip(s1, s2):
        a, b = f1(a), f2(b)
    assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------- lowering pins

@pytest.mark.parametrize("k", [1, 2, 4])
@pytest.mark.parametrize("alg,per_exchange", [
    ("alltoall", 1),
    ("alltoallv", 1),   # CPU mirrors the ragged op densely: still 1/chunk
    ("ppermute", 7),    # (P-1)-step ring per chunk
])
def test_slab_compiles_to_k_collectives(alg, k, per_exchange):
    """overlap_chunks=K must survive to the compiled HLO as exactly K
    collectives per exchange — no silent fusion back to 1, no accidental
    2K (default K=1 keeps today's count)."""
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, algorithm=alg,
                                overlap_chunks=k)
    assert len(_collectives(plan)) == k * per_exchange


@pytest.mark.parametrize("k", [1, 3])
def test_pencil_compiles_to_2k_collectives(k):
    mesh = dfft.make_mesh((2, 4))
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, overlap_chunks=k)
    assert len(_collectives(plan)) == 2 * k


def test_default_plan_hlo_unchanged():
    """overlap_chunks default (1) and explicit 1 compile the same program
    as an unadorned plan — today's HLO exactly."""
    mesh = dfft.make_mesh(8)
    base = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    pinned = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, overlap_chunks=1)
    assert base.options.overlap_chunks == 1
    t_base = base.fn.lower(
        jax.ShapeDtypeStruct(base.in_shape, base.in_dtype)).as_text()
    t_pin = pinned.fn.lower(
        jax.ShapeDtypeStruct(base.in_shape, base.in_dtype)).as_text()
    assert t_base == t_pin


def test_staged_t2_compiles_to_k_collectives():
    from distributedfft_tpu.parallel.slab import build_slab_stages

    mesh = dfft.make_mesh(8)
    stages, _ = build_slab_stages(mesh, SHAPE, overlap_chunks=4)
    # traced_stage wraps the stage jits, so count collectives by lowering
    # the t2 wrapper on the t0 stage's output spec.
    x = jnp.asarray(_world())
    t0 = dict(stages)["t0_fft_yz"]
    y = t0(x)
    inner = stages[1][1]  # traced wrapper; call through for compile
    txt = jax.jit(lambda v: inner(v)).lower(
        jax.ShapeDtypeStruct(y.shape, y.dtype)).compile().as_text()
    assert len(_COLLECTIVE.findall(txt)) == 4


# ------------------------------------------------------------- plumbing

def test_env_override(monkeypatch):
    monkeypatch.setenv("DFFT_OVERLAP", "3")
    assert resolve_overlap_chunks(None) == 3
    mesh = dfft.make_mesh(8)
    # The plan cache keys on DFFT_OVERLAP, so this cannot collide with
    # the default-K plans built by other tests.
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    assert plan.options.overlap_chunks == 3
    assert len(_collectives(plan)) == 3
    x = jnp.asarray(_world())
    monkeypatch.delenv("DFFT_OVERLAP")
    mono = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    assert mono.options.overlap_chunks == 1
    assert np.array_equal(np.asarray(plan(x)), np.asarray(mono(x)))


def test_env_and_auto_resolution_rules(monkeypatch):
    monkeypatch.setenv("DFFT_OVERLAP", "auto")
    # env "auto" routes through the heuristic (tiny block -> 1).
    assert resolve_overlap_chunks(None, shape=SHAPE, ndev=8) == 1
    monkeypatch.setenv("DFFT_OVERLAP", "junk")
    with pytest.raises(ValueError, match="DFFT_OVERLAP"):
        resolve_overlap_chunks(None, shape=SHAPE, ndev=8)
    monkeypatch.delenv("DFFT_OVERLAP")
    # Explicit values beat the (now absent) env; validation bites.
    assert resolve_overlap_chunks(4) == 4
    assert resolve_overlap_chunks("2") == 2
    with pytest.raises(ValueError):
        resolve_overlap_chunks(0)


def test_plan_options_validation():
    assert PlanOptions(overlap_chunks=4).overlap_chunks == 4
    assert PlanOptions(overlap_chunks="8").overlap_chunks == 8
    assert PlanOptions(overlap_chunks="auto").overlap_chunks == "auto"
    assert PlanOptions().overlap_chunks is None  # deferred to plan time
    with pytest.raises(ValueError, match="overlap_chunks"):
        PlanOptions(overlap_chunks=0)
    with pytest.raises(ValueError, match="overlap_chunks"):
        PlanOptions(overlap_chunks="fast")


def test_auto_heuristic():
    # 512^3 c64 on 4 devices: 268 MB/device >> the 4 MiB chunk floor ->
    # capped at the max chunk count.
    assert auto_overlap_chunks((512, 512, 512), 4) == OVERLAP_AUTO_MAX_CHUNKS
    # Tiny blocks stay monolithic; single device has nothing to overlap.
    assert auto_overlap_chunks((64, 64, 64), 8) == 1
    assert auto_overlap_chunks((512, 512, 512), 1) == 1
    # Mid-size: 256^3 c64 / 8 devices = 16 MiB -> 4 chunks.
    assert auto_overlap_chunks((256, 256, 256), 8) == 4
    # Plan-level "auto" resolves to a concrete int on the plan's mesh.
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8), dtype=CDT,
                                overlap_chunks="auto")
    assert plan.options.overlap_chunks == 1


def test_single_device_forces_one():
    plan = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, overlap_chunks=4)
    assert plan.options.overlap_chunks == 1  # no exchange to overlap


def test_per_chunk_trace_spans(monkeypatch):
    """The PR 1 timeline must show the interleave: t2[k]/t3[k] spans per
    chunk (recorded dispatch-side when the jit first traces)."""
    from distributedfft_tpu.utils import trace as tr

    # Python recorder: the test reads the in-memory event list, which the
    # native C recorder bypasses.
    monkeypatch.setenv("DFFT_TRACE_NATIVE", "0")
    mesh = dfft.make_mesh(8)
    shape = (8, 16, 10)  # unique shape: plan cache must retrace under us
    tr.init_tracing("/tmp/dfft_overlap_spans")
    try:
        plan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT,
                                    overlap_chunks=2)
        plan(jnp.asarray(_world(shape)))
        names = {e[0] for e in tr._events}
    finally:
        tr.finalize_tracing()
    assert "t2_exchange_slab[0]" in names and "t2_exchange_slab[1]" in names
    assert "t3_fft_x[0]" in names and "t3_fft_x[1]" in names


def test_plan_info_reports_overlap():
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, overlap_chunks=2)
    assert "overlap: 2 chunks" in dfft.plan_info(plan)
    mono = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    assert "overlap:" not in dfft.plan_info(mono)


def test_options_and_kw_conflict():
    with pytest.raises(ValueError, match="not both"):
        dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8), dtype=CDT,
                             overlap_chunks=2,
                             options=PlanOptions(overlap_chunks=2))


# ------------------------------------------------------ run-record schema

def test_overlapped_records_never_share_baseline():
    """The PR 2 compare engine groups baselines by (metric, config,
    device_kind); the overlap knob is part of config, so an overlapped
    run can never be judged against a monolithic baseline (or poison
    one)."""
    line = {
        "metric": "fft3d_c2c_512_forward_gflops", "value": 200.0,
        "unit": "GFlops/s", "dtype": "complex64", "devices": 4,
        "decomposition": "slab", "backend": "tpu",
    }
    mono = regress.normalize_bench_line(dict(line), source="t")
    over = regress.normalize_bench_line(dict(line, overlap=4), source="t")
    assert regress.group_key(mono) != regress.group_key(over)
    assert "overlap=4" in regress.config_signature(over)
    # And the compare engine keeps them apart: a history of monolithic
    # records yields no baseline for the overlapped run.
    history = [regress.normalize_bench_line(dict(line, value=v), source="t")
               for v in (200.0, 201.0, 199.0)]
    res = regress.compare_record(over, history)
    assert res["verdict"] == "no-baseline"
    res_mono = regress.compare_record(
        regress.normalize_bench_line(dict(line), source="t"), history)
    assert res_mono["verdict"] == "within-noise"


def test_bench_emit_stamps_overlap(capsys):
    """bench.py result lines carry the overlap knob (non-default only:
    default rows keep the old schema)."""
    import sys as _sys
    import os as _os

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    import bench
    import json

    bench._emit(16, 0.01, 1e-7, "xla", 8, "slab", {"xla": 0.01}, overlap=4)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["overlap"] == 4
    bench._emit(16, 0.01, 1e-7, "xla", 8, "slab", {"xla": 0.01}, overlap=1)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "overlap" not in out
