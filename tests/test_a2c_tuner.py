"""Measured plan autotuner + persistent wisdom (``distributedfft_tpu/tuner.py``).

The multi-axis generalization of the ``setFFTPlans`` plan-and-pick
discipline: candidate generation and analytical pruning, the lockstep
tournament engine (multi-host build- AND timing-flag agreement, winner
from the allgathered time matrix), and the FFTW-style wisdom store
(measure once, build winners from disk forever after). The contracts
pinned here:

1. **Round trip** — ``tune="measure"`` runs one pruned tournament; an
   identically-keyed planner call afterwards builds the winner from
   wisdom with ZERO timing executions (metrics registry asserted).
2. **Key isolation** — a different device_kind / mesh / dtype never
   reuses an entry.
3. **Store robustness** — corrupt/truncated wisdom lines are skipped
   with a stderr count (the report-merge discipline), never fatal.
4. **Winner determinism** — the decision is a pure function of the
   allgathered time matrix: every process computes the same winner, and
   a candidate that failed timing on ANY process can never win (the
   divergence the build-phase-only flag agreement used to allow).
5. **Default off** — ``tune`` unset never dispatches to the tuner.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` — the environment's XLA:CPU fft-thunk layout bug
(see ``test_a2a_overlap.py``'s header) permanently poisons the
process's sharded dispatch stream once tripped, and the tournament
executions here need a clean backend. This file itself triggers no
fft-layout fault (tournaments run c2c chains, and the r2c test pins a
1D mesh — the bad geometry is the uneven r2c *pencil* chain).
"""

import json
import math

import numpy as np
import pytest

import jax

import distributedfft_tpu as dfft
from distributedfft_tpu import report, tuner
from distributedfft_tpu import testing as tu
from distributedfft_tpu import regress
from distributedfft_tpu.plan_logic import PlanOptions, resolve_tune_mode
from distributedfft_tpu.utils import metrics as m

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)


@pytest.fixture
def wisdom_path(tmp_path, monkeypatch):
    """Isolated wisdom store (and compile cache) for one test."""
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "wisdom.jsonl"))
    monkeypatch.setenv("DFFT_COMPILE_CACHE", str(tmp_path / "xla_cache"))
    return str(tmp_path / "wisdom.jsonl")


@pytest.fixture
def fast_budget(monkeypatch):
    """Smallest legal tournament: 1 iter x 1 repeat, 3 survivors."""
    monkeypatch.setenv("DFFT_TUNE_ITERS", "1x1")
    monkeypatch.setenv("DFFT_TUNE_MAX", "3")


@pytest.fixture
def metrics_on():
    dfft.clear_plan_cache()
    m.metrics_reset()
    m.enable_metrics()
    yield
    m.enable_metrics(False)
    m.metrics_reset()
    dfft.clear_plan_cache()


# ----------------------------------------------------- options plumbing

def test_plan_options_validates_tune():
    assert PlanOptions(tune="measure").tune == "measure"
    assert PlanOptions().tune is None
    with pytest.raises(ValueError, match="tune"):
        PlanOptions(tune="bogus")


def test_resolve_tune_mode_env(monkeypatch):
    monkeypatch.delenv("DFFT_TUNE", raising=False)
    assert resolve_tune_mode(None) == "off"
    assert resolve_tune_mode("wisdom") == "wisdom"
    monkeypatch.setenv("DFFT_TUNE", "measure")
    assert resolve_tune_mode(None) == "measure"
    monkeypatch.setenv("DFFT_TUNE", "nonsense")
    with pytest.raises(ValueError, match="DFFT_TUNE"):
        resolve_tune_mode(None)


def test_default_off_never_dispatches_to_tuner(monkeypatch):
    """tune unset (and DFFT_TUNE unset) must plan exactly the legacy
    path — the tuner is never even consulted."""
    monkeypatch.delenv("DFFT_TUNE", raising=False)

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("tuner dispatched on a default planner call")

    monkeypatch.setattr(tuner, "tuned_plan", boom)
    dfft.clear_plan_cache()
    plan = dfft.plan_dft_c2c_3d((8, 6, 4), dfft.make_mesh(2),
                                dtype=np.complex64)
    assert plan.options.tune in (None, "off")
    x = tu.make_world_data((8, 6, 4), dtype=np.complex64)
    got = np.asarray(plan(x))
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 5e-4


def test_tune_budget_parsing(monkeypatch):
    monkeypatch.delenv("DFFT_TUNE_ITERS", raising=False)
    assert tuner.tune_budget() == (10, 2)
    monkeypatch.setenv("DFFT_TUNE_ITERS", "6")
    assert tuner.tune_budget() == (6, 2)
    monkeypatch.setenv("DFFT_TUNE_ITERS", "4x3")
    assert tuner.tune_budget() == (4, 3)
    for bad in ("0", "x", "3x0", "abc", "1x2x3"):
        monkeypatch.setenv("DFFT_TUNE_ITERS", bad)
        with pytest.raises(ValueError, match="DFFT_TUNE_ITERS"):
            tuner.tune_budget()


# ------------------------------------------------- candidates + pruning

@needs_mesh
def test_enumerate_and_prune_candidates():
    shape = (64, 64, 64)
    cands = tuner.enumerate_candidates(
        shape, 8, executors=["xla", "matmul"])
    # Joint space: both decompositions, all three transports, both
    # executors, K in {1, K_auto, 2 K_auto}.
    assert {c.decomposition for c in cands} == {"slab", "pencil"}
    assert {c.algorithm for c in cands} == {
        "alltoall", "alltoallv", "ppermute"}
    assert {c.executor for c in cands} == {"xla", "matmul"}
    survivors = tuner.prune_candidates(cands, shape, 8, limit=4)
    assert len(survivors) == 4
    assert all(s in cands for s in survivors)
    # The executor axis is crossed onto the model's best geometry first:
    # the leading survivors share one geometry and cover both executors.
    g0 = (survivors[0].decomposition, survivors[0].algorithm,
          survivors[0].overlap_chunks)
    lead = [s for s in survivors
            if (s.decomposition, s.algorithm, s.overlap_chunks) == g0]
    assert {s.executor for s in lead} == {"xla", "matmul"}


@needs_mesh
def test_enumerate_respects_fixed_mesh_dims():
    cands = tuner.enumerate_candidates(
        (16, 16, 16), 8, mesh_dims=(8,), executors=["xla"])
    assert {c.decomposition for c in cands} == {"slab"}
    cands = tuner.enumerate_candidates(
        (16, 16, 16), 8, mesh_dims=(2, 4), executors=["xla"])
    assert {c.decomposition for c in cands} == {"pencil"}


@needs_mesh
def test_model_cost_prefers_fewer_exchanges_small_mesh():
    """On a small mesh with slab-friendly extents the one-exchange slab
    chain must model cheaper than the two-exchange ring pencil chain —
    the ordering the pruning stage relies on."""
    shape = (64, 64, 64)
    slab = tuner.Candidate("slab", "alltoall", "xla", 1)
    ring_pencil = tuner.Candidate("pencil", "ppermute", "xla", 1)
    assert (tuner.model_cost(slab, shape, 8)
            < tuner.model_cost(ring_pencil, shape, 8))


# ------------------------------------------------------- winner picking

def test_agree_winner_is_deterministic_and_uses_process0_clock():
    names = ["a", "b"]
    times = np.array([[2.0, 1.0],   # process 0: b faster
                      [1.0, 2.0]])  # process 1 disagrees (its own clock)
    # Every process computes from the same matrix -> same winner, ranked
    # by process 0's row.
    assert tuner.agree_winner(times, names) == "b"
    assert tuner.agree_winner(times.copy(), names) == "b"


def test_agree_winner_excludes_candidate_failing_anywhere():
    """The satellite fix: a candidate that timed fastest on process 0
    but failed (inf) on another process must NOT win — the old
    broadcast-only reconciliation would have picked it and diverged."""
    names = ["fast_but_broken", "steady"]
    times = np.array([[0.001, 0.002],
                      [np.inf, 0.002]])
    assert tuner.agree_winner(times, names) == "steady"
    with pytest.raises(ValueError, match="every process"):
        tuner.agree_winner(np.array([[np.inf], [np.inf]]), ["only"])


def test_measured_select_multihost_timing_divergence(monkeypatch):
    """End-to-end through the engine: simulate two processes where one
    candidate builds everywhere but fails timing on the OTHER process
    only. The local (process-0) view times it fastest; the reconciled
    winner must still be the candidate finite everywhere."""
    monkeypatch.setattr(tuner, "_process_count", lambda: 2)
    calls = []

    def fake_allgather(vec):
        calls.append(np.array(vec))
        if len(calls) == 1:  # build flags: both processes built both
            return np.stack([vec, vec])
        other = np.array(vec)
        other[0] = np.inf    # candidate 0 failed timing on process 1
        return np.stack([vec, other])

    monkeypatch.setattr(tuner, "_allgather_rows", fake_allgather)
    local_times = {"quick": 0.001, "steady": 0.002}
    winner, built, times = tuner.measured_select(
        ["quick", "steady"], build=lambda nm: nm,
        measure=lambda nm: local_times[nm])
    assert winner == "steady"
    assert built == {"quick": "quick", "steady": "steady"}
    assert len(calls) == 2  # one flags round, one timing round


def test_measured_select_skips_failed_builds():
    def build(nm):
        if nm == "broken":
            raise RuntimeError("no such executor")
        return nm

    winner, built, _ = tuner.measured_select(
        ["broken", "ok"], build=build, measure=lambda nm: 1.0)
    assert winner == "ok"
    assert "broken" not in built
    with pytest.raises(ValueError, match="no thing succeeded"):
        tuner.measured_select(
            ["a"], build=lambda nm: 1 / 0, measure=lambda nm: 1.0,
            what="thing")


# --------------------------------------------------------------- wisdom

def _fake_key(**over):
    kw = dict(kind="c2c", shape=(16, 16, 16), dtype=np.complex64,
              direction=-1, ndev=8, mesh_dims=None,
              device_kind="cpu", platform="cpu")
    kw.update(over)
    return tuner.wisdom_key(**kw)


def test_wisdom_key_isolation(wisdom_path):
    cand = tuner.Candidate("slab", "alltoall", "xla", 1)
    key = _fake_key()
    tuner.record_wisdom(key, cand, 0.001, path=wisdom_path)
    assert tuner.lookup_wisdom(key, wisdom_path) is not None
    # A different device kind, mesh shape, device count, dtype, or
    # direction must never reuse the entry.
    for other in (
        _fake_key(device_kind="TPU v5 lite"),
        _fake_key(mesh_dims=(2, 4)),
        _fake_key(ndev=4),
        _fake_key(dtype=np.complex128),
        _fake_key(direction=+1),
        _fake_key(shape=(16, 16, 8)),
        _fake_key(kind="r2c"),
    ):
        assert tuner.lookup_wisdom(other, wisdom_path) is None


def test_wisdom_newest_entry_wins(wisdom_path):
    key = _fake_key()
    tuner.record_wisdom(key, tuner.Candidate("slab", "alltoall", "xla", 1),
                        0.001, path=wisdom_path)
    tuner.record_wisdom(key, tuner.Candidate("pencil", "ppermute", "matmul",
                                             2), 0.0005, path=wisdom_path)
    entry = tuner.lookup_wisdom(key, wisdom_path)
    assert entry["winner"]["decomposition"] == "pencil"
    assert entry["winner"]["overlap_chunks"] == 2


def test_corrupt_wisdom_lines_skipped(wisdom_path, capsys):
    key = _fake_key()
    entry = tuner.record_wisdom(
        key, tuner.Candidate("slab", "alltoall", "xla", 1), 0.001,
        path=wisdom_path)
    with open(wisdom_path, "a") as f:
        f.write("not json at all\n")
        f.write(json.dumps({"schema": 1, "no_key": True}) + "\n")
        # The truncated tail a killed writer leaves behind.
        f.write(json.dumps(entry)[: len(json.dumps(entry)) // 2] + "\n")
    entries, dropped = tuner.load_wisdom(wisdom_path)
    assert len(entries) == 1 and dropped == 3
    # The lookup path reports the skip count on stderr, never raises.
    assert tuner.lookup_wisdom(key, wisdom_path) is not None
    err = capsys.readouterr().err
    assert "skipped 3 malformed wisdom line" in err


def test_load_wisdom_missing_or_disabled(tmp_path, monkeypatch):
    assert tuner.load_wisdom(str(tmp_path / "absent.jsonl")) == ({}, 0)
    assert tuner.load_wisdom(None) == ({}, 0)
    monkeypatch.setenv("DFFT_WISDOM", "")
    assert tuner.default_wisdom_path() is None
    monkeypatch.setenv("DFFT_WISDOM", "0")
    assert tuner.default_wisdom_path() is None
    monkeypatch.delenv("DFFT_WISDOM", raising=False)
    monkeypatch.setenv("DFFT_COMPILE_CACHE", str(tmp_path / "cc"))
    assert tuner.default_wisdom_path() == str(tmp_path / "cc" /
                                              "wisdom.jsonl")


# --------------------------------------------- tuned planning (8-way)

@needs_mesh
def test_measure_round_trip_wisdom(wisdom_path, fast_budget, metrics_on):
    """The acceptance loop: a pruned multi-axis tournament runs once;
    the identically-keyed second planner call (fresh plan cache) builds
    the winner from wisdom with zero timing executions."""
    shape = (16, 12, 8)
    plan = dfft.plan_dft_c2c_3d(shape, 8, dtype=np.complex64,
                                tune="measure")
    assert m.counter_total("tune_tournaments") == 1
    assert m.counter_total("tune_timing_executions") >= 2
    assert m.counter_total("tune_wisdom_misses") == 1
    label = tuner.tuned_label(plan)

    # Correctness of whatever won.
    x = tu.make_world_data(shape, dtype=np.complex64)
    got = np.asarray(plan(x))
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 5e-4

    # Fresh process analog: drop the in-memory plan cache, keep the
    # on-disk wisdom. The second call must not time anything.
    dfft.clear_plan_cache()
    m.metrics_reset()
    plan2 = dfft.plan_dft_c2c_3d(shape, 8, dtype=np.complex64,
                                 tune="measure")
    assert m.counter_total("tune_timing_executions") == 0
    assert m.counter_total("tune_tournaments") == 0
    assert m.counter_total("tune_wisdom_hits") == 1
    assert tuner.tuned_label(plan2) == label
    got2 = np.asarray(plan2(x))
    assert np.max(np.abs(got2 - want)) / np.abs(want).max() < 5e-4


@needs_mesh
def test_wisdom_mode_never_measures(wisdom_path, fast_budget, metrics_on):
    """tune="wisdom" with an empty store: static-heuristic plan, zero
    timing executions, miss counted."""
    plan = dfft.plan_dft_c2c_3d((16, 16, 16), 8, dtype=np.complex64,
                                tune="wisdom")
    assert m.counter_total("tune_timing_executions") == 0
    assert m.counter_total("tune_tournaments") == 0
    assert m.counter_total("tune_wisdom_misses") == 1
    # 8 devices <= min(16, 16): the static heuristic picks slab.
    assert plan.decomposition == "slab"
    assert plan.executor == "xla"


@needs_mesh
def test_measure_honors_donate_by_rebuilding(wisdom_path, monkeypatch,
                                             metrics_on):
    monkeypatch.setenv("DFFT_TUNE_ITERS", "1x1")
    monkeypatch.setenv("DFFT_TUNE_MAX", "1")
    plan = dfft.plan_dft_c2c_3d((8, 8, 8), 8, dtype=np.complex64,
                                tune="measure", donate=True)
    assert plan.options.donate is True
    x = dfft.alloc_local(plan, fill=tu.make_world_data((8, 8, 8),
                                                       dtype=np.complex64))
    y = plan(x)  # consumes x
    assert y.shape == (8, 8, 8)


@needs_mesh
def test_r2c_tuned_on_fixed_slab_mesh(wisdom_path, fast_budget, metrics_on):
    """r2c through the tuner on a pinned 1D mesh (the mesh pins the
    decomposition axis to slab — also keeps this file clear of the
    environment's uneven-r2c-pencil fft-thunk fault)."""
    shape = (8, 8, 16)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_r2c_3d(shape, mesh, tune="measure")
    assert plan.decomposition == "slab"
    assert m.counter_total("tune_tournaments") == 1
    x = tu.make_world_data(shape, dtype=np.float64)
    got = np.asarray(plan(x))
    want = np.fft.rfftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 1e-10

    dfft.clear_plan_cache()
    m.metrics_reset()
    plan2 = dfft.plan_dft_r2c_3d(shape, mesh, tune="measure")
    assert m.counter_total("tune_timing_executions") == 0
    assert tuner.tuned_label(plan2) == tuner.tuned_label(plan)


def test_single_device_tune_short_circuits(wisdom_path, metrics_on):
    """No mesh -> nothing to search: the tuned tier builds the plain
    single-device plan without a tournament or a wisdom entry."""
    plan = dfft.plan_dft_c2c_3d((8, 8, 8), None, dtype=np.complex64,
                                tune="measure")
    assert plan.decomposition == "single"
    assert m.counter_total("tune_tournaments") == 0
    assert tuner.load_wisdom(tuner.default_wisdom_path())[0] == {}


# ------------------------------------------------- wisdom gate (report)

def test_wisdom_verdict_math():
    v = regress.wisdom_verdict(0.001, [0.002, 0.0021, 0.002, 0.0019])
    assert v["verdict"] == "regressed"
    v = regress.wisdom_verdict(0.001, [0.00101, 0.00099, 0.001])
    assert v["verdict"] == "within-noise"
    v = regress.wisdom_verdict(0.002, [0.001, 0.00101, 0.00099])
    assert v["verdict"] == "improved"
    assert regress.wisdom_verdict(0.001, [0.002])["verdict"] == "no-baseline"


def _history_with(tmp_path, label, seconds_list):
    path = tmp_path / "history.jsonl"
    recs = [
        regress.make_run_record(
            metric="fft3d_c2c_16_forward_gflops", value=10.0,
            seconds=s, config={"tuned": label}, backend="cpu",
            device_kind="cpu", source="test")
        for s in seconds_list
    ]
    regress.append_records(recs, str(path))
    return str(path)


def test_report_wisdom_gate(tmp_path, wisdom_path, capsys):
    key = _fake_key()
    cand = tuner.Candidate("slab", "alltoall", "xla", 1)
    tuner.record_wisdom(key, cand, 0.001, path=wisdom_path)

    # Fresh runs of the same winner tuple 2x slower -> stale, gate fires.
    hist = _history_with(tmp_path, cand.label, [0.002, 0.0021, 0.002])
    rc = report.main(["wisdom", "--gate", "--wisdom", wisdom_path,
                      "--history", hist])
    assert rc == 1
    out = capsys.readouterr()
    assert "regressed" in out.out and "stale" in out.err

    # Fresh runs at the recorded speed -> clean.
    hist2 = _history_with(tmp_path / "ok", cand.label,
                          [0.001, 0.00101, 0.00099])
    assert report.main(["wisdom", "--gate", "--wisdom", wisdom_path,
                        "--history", hist2]) == 0
    # Listing without --gate never gates.
    assert report.main(["wisdom", "--wisdom", wisdom_path]) == 0


def test_regress_tuned_keys_baseline_group():
    """Tuned and untuned bench lines never share a compare baseline —
    the same separation rule overlap established."""
    base = {"metric": "m", "value": 1.0, "dtype": "complex64",
            "devices": 8}
    plain = regress.normalize_bench_line(dict(base), source="t")
    tuned = regress.normalize_bench_line(
        dict(base, tuned="slab/alltoall/xla/ov1"), source="t")
    assert plain["config"].get("tuned") is None
    assert tuned["config"]["tuned"] == "slab/alltoall/xla/ov1"
    assert regress.group_key(plain) != regress.group_key(tuned)
