"""Explain/attribution layer — the execution tier (``dfft.explain`` on
live CPU-mesh plans: the model/compiled/measured join, per-stage AOT
cost analysis, MFU/ICI ratios).

Pure-python explain tests (divergence gate, report CLI, regress
cost-block gating) live in ``tests/test_explain.py``; this module holds
everything that *executes* 8-device plans.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` — the environment's pre-existing XLA:CPU
fft-thunk layout bug poisons the process's sharded dispatch stream for
every later 8-device test (HEAD baseline: the in-suite failure set),
and the measured sections here need a clean backend. Same ordering rule
as ``test_a2a_overlap.py`` / ``test_a2c_tuner.py``; the guard in
``test_explain.py::test_poison_ordering_guard`` asserts the names keep
sorting this way.
"""

import json

import jax
import pytest

import distributedfft_tpu as dfft
from distributedfft_tpu.explain import (
    compiled_summary,
    format_explain,
    model_stage_estimates,
)
from distributedfft_tpu.utils.trace import STAGE_KEYS

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

SHAPE = (16, 16, 16)


def _assert_sections(record):
    assert tuple(sorted(record["stages"])) == tuple(sorted(STAGE_KEYS))
    for key in STAGE_KEYS:
        st = record["stages"][key]
        for section in ("model", "compiled", "measured"):
            assert section in st, (key, section)
        assert "seconds" in st["model"]
        assert "divergence" in st


def test_cpu_slab_explain_roundtrip():
    """The acceptance path: a CPU 8-device slab plan explains with all
    three sections present for exactly t0..t3, and the record is one
    JSON document (the run-record store embeds it verbatim)."""
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8))
    rec = dfft.explain(plan, iters=3)
    _assert_sections(rec)
    assert rec["staged_available"]
    # The slab chain measures t0/t2/t3 (no separate t1 stage jit).
    for key in ("t0", "t2", "t3"):
        meas = rec["stages"][key]["measured"]
        assert meas["available"] and meas["seconds"] > 0
        assert len(meas["samples"]) == 3
    assert rec["stages"]["t1"]["measured"]["available"] is False
    # Model side: one exchange's wire bytes, zero for the FFT stages.
    assert rec["stages"]["t2"]["model"]["wire_bytes"] > 0
    assert rec["stages"]["t0"]["model"]["flops"] > 0
    # Whole-program compiled view feeds the regress cost block.
    assert rec["compiled"]["peak_hbm_bytes"] > 0
    assert rec["compiled"]["compile_seconds"] > 0
    json.dumps(rec)  # must serialize round-trip clean


def test_per_stage_compiled_analysis_present():
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8))
    rec = dfft.explain(plan, iters=2)
    t0 = rec["stages"]["t0"]["compiled"]
    assert t0.get("available")
    assert t0["flops"] and t0["flops"] > 0
    assert t0["peak_hbm_bytes"] and t0["peak_hbm_bytes"] > 0
    # The exchange stage has no FFT flops but does have HBM footprint.
    t2 = rec["stages"]["t2"]["compiled"]
    assert t2.get("available")
    assert t2["peak_hbm_bytes"] and t2["peak_hbm_bytes"] > 0


def test_pencil_explain_fills_t1_and_both_exchanges():
    """The pencil chain's mid FFT is t1 and BOTH exchanges land in t2
    (t2a/t2b measured samples are summed per pass)."""
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh((4, 2)))
    rec = dfft.explain(plan, iters=2)
    _assert_sections(rec)
    assert rec["stages"]["t1"]["model"]["seconds"] > 0
    assert rec["stages"]["t1"]["measured"]["available"]
    assert rec["stages"]["t2"]["model"]["steps"] >= 2
    assert rec["stages"]["t2"]["measured"]["seconds"] > 0


def test_single_device_explain_sections():
    plan = dfft.plan_dft_c2c_3d((8, 8, 8))
    rec = dfft.explain(plan, iters=2)
    _assert_sections(rec)
    assert rec["stages"]["t2"]["model"]["seconds"] == 0.0
    assert rec["stages"]["t0"]["measured"]["available"]


def test_measure_false_skips_every_execution():
    dfft.metrics_reset()
    dfft.enable_metrics()
    try:
        plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8),
                                    algorithm="ppermute")
        before = dfft.metrics_snapshot()["counters"].get("executes", {})
        rec = dfft.explain(plan, measure=False)
        after = dfft.metrics_snapshot()["counters"].get("executes", {})
        assert before == after
        for key in STAGE_KEYS:
            assert rec["stages"][key]["measured"]["available"] is False
        # Model and whole-plan compiled views still fully populate.
        assert rec["stages"]["t2"]["model"]["wire_bytes"] > 0
        assert rec["compiled"]["peak_hbm_bytes"] > 0
    finally:
        dfft.enable_metrics(False)
        dfft.metrics_reset()


def test_compiled_summary_cached_and_shaped():
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8))
    cs = compiled_summary(plan)
    assert cs is not None
    assert cs["peak_hbm_bytes"] == (cs["argument_bytes"]
                                    + cs["output_bytes"]
                                    + cs["temp_bytes"])
    assert cs["compile_seconds"] > 0
    assert compiled_summary(plan) is cs  # cached on the plan object


def test_model_estimates_match_plan_transport():
    """The model side prices the plan's OWN transport: the padded ring
    ships dense bytes over P-1 launch steps, so its t2 prediction must
    exceed the fused all-to-all's at the same geometry."""
    mesh = dfft.make_mesh(8)
    a2a = model_stage_estimates(dfft.plan_dft_c2c_3d(SHAPE, mesh))
    ring = model_stage_estimates(
        dfft.plan_dft_c2c_3d(SHAPE, mesh, algorithm="ppermute"))
    assert ring["t2"]["steps"] == 7
    assert a2a["t2"]["steps"] == 1
    assert ring["t2"]["seconds"] > a2a["t2"]["seconds"]


def test_format_explain_renders_live_record():
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8))
    text = format_explain(dfft.explain(plan, iters=2))
    assert "t0" in text and "t3" in text
    assert "compiled (whole plan)" in text
