"""Batched multi-request execution: the leading batch axis + coalescing.

``plan(..., batch=B)`` threads a leading batch axis through the chain
builders: batched t0/t3 FFT stages, batched pads/crops, and ONE shared
collective per (chunk, exchange) with the batch riding as a bystander
dim — B transforms pay one collective latency. These tests pin the
tentpole's three contracts on the 8-way CPU mesh:

1. **Bit parity** — the batch axis is a pure bystander, so a batch=B
   execution must equal B sequential executes of the unbatched plan bit
   for bit, across slab/pencil/staged/dd x every transport x overlap
   K in {1, 2}.
2. **batch=1 is free** — ``batch=1`` (and None) compiles byte-identical
   HLO to an unadorned plan: the serving tier's singleton path costs
   nothing.
3. **One shared exchange** — the compiled collective count of a batch=B
   plan equals the batch=1 count for every transport (dense K, ring
   K*(P-1), pencil 2K): batching must never serialize into per-element
   collectives.

Plus the serving tier riding on it: the coalescing queue groups pending
same-(shape, dtype, direction) requests into one batched execution.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` — the environment's XLA:CPU fft-thunk layout bug
poisons the process's sharded dispatch stream for every later 8-device
execute once tripped (see ``test_a2a_overlap.py``; the guard in
``test_explain.py`` pins the ordering). This file avoids the one bad
chain geometry, so running first is safe for the rest of the suite.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu.parallel.slab import batch_pspec, check_batch

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 16)
UNEVEN = (12, 10, 9)
CDT = jnp.complex128
B = 3

ALGS = ("alltoall", "alltoallv", "ppermute")

_COLLECTIVE = re.compile(
    r"\b(all-to-all|all-gather|all-reduce|collective-permute)(?:-start)?\("
)


def _collectives(plan) -> int:
    txt = plan.fn.lower(
        jax.ShapeDtypeStruct(plan.in_shape, plan.in_dtype)
    ).compile().as_text()
    return len(_COLLECTIVE.findall(txt))


def _world(shape=SHAPE, seed=7, real=False, batch=None):
    rng = np.random.default_rng(seed)
    full = shape if batch is None else (batch,) + tuple(shape)
    r = rng.standard_normal(full)
    return r if real else r + 1j * rng.standard_normal(full)


def _assert_batch_equals_sequential(pb, p1, x):
    """The acceptance contract: batch=B output bit-identical to B
    sequential executes of the unbatched plan."""
    yb = np.asarray(pb(jnp.asarray(x)))
    ys = np.stack([np.asarray(p1(jnp.asarray(x[i])))
                   for i in range(x.shape[0])])
    assert np.array_equal(yb, ys)


# ------------------------------------------------------------- bit parity

@pytest.mark.parametrize("alg", ALGS)
@pytest.mark.parametrize("k", [1, 2])
def test_slab_batch_parity_bitwise(alg, k):
    mesh = dfft.make_mesh(8)
    kw = dict(mesh=mesh, dtype=CDT, algorithm=alg, overlap_chunks=k)
    pb = dfft.plan_dft_c2c_3d(SHAPE, **kw, batch=B)
    p1 = dfft.plan_dft_c2c_3d(SHAPE, **kw)
    _assert_batch_equals_sequential(pb, p1, _world(batch=B))


@pytest.mark.parametrize("alg", ALGS)
@pytest.mark.parametrize("k", [1, 2])
def test_pencil_batch_parity_bitwise(alg, k):
    mesh = dfft.make_mesh((2, 4))
    kw = dict(mesh=mesh, dtype=CDT, algorithm=alg, overlap_chunks=k)
    pb = dfft.plan_dft_c2c_3d(SHAPE, **kw, batch=B)
    p1 = dfft.plan_dft_c2c_3d(SHAPE, **kw)
    _assert_batch_equals_sequential(pb, p1, _world(batch=B))


@pytest.mark.parametrize("alg", ALGS)
def test_uneven_batch_parity_bitwise(alg):
    """Uneven worlds exercise the batched pad/crop path (pads ride at
    spatial-axis + 1); K=2 does not divide the 9-extent bystander."""
    mesh = dfft.make_mesh(8)
    kw = dict(mesh=mesh, dtype=CDT, algorithm=alg, overlap_chunks=2)
    pb = dfft.plan_dft_c2c_3d(UNEVEN, **kw, batch=B)
    p1 = dfft.plan_dft_c2c_3d(UNEVEN, **kw)
    _assert_batch_equals_sequential(pb, p1, _world(UNEVEN, batch=B))


@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_r2c_batch_parity_bitwise(mesh_shape):
    mesh = dfft.make_mesh(mesh_shape)
    pb = dfft.plan_dft_r2c_3d(SHAPE, mesh, batch=B)
    p1 = dfft.plan_dft_r2c_3d(SHAPE, mesh)
    _assert_batch_equals_sequential(pb, p1, _world(real=True, batch=B))
    assert pb.in_shape == (B,) + SHAPE
    assert pb.out_shape == (B, 16, 16, 9)


def test_c2r_batch_parity_bitwise():
    mesh = dfft.make_mesh(8)
    kw = dict(mesh=mesh, direction=dfft.BACKWARD)
    pb = dfft.plan_dft_r2c_3d(SHAPE, **kw, batch=B)
    p1 = dfft.plan_dft_r2c_3d(SHAPE, **kw)
    spec = np.stack([np.fft.rfftn(np.asarray(w))
                     for w in _world(real=True, batch=B)])
    _assert_batch_equals_sequential(pb, p1, spec)


@pytest.mark.parametrize("alg,k", [("alltoall", 1), ("alltoall", 2),
                                   ("alltoallv", 2), ("ppermute", 2)])
def test_staged_slab_batch_parity_bitwise(alg, k):
    """The staged t0/t2/t3 pipeline at batch=B reproduces the unbatched
    stages applied per element, stage by stage."""
    from distributedfft_tpu.parallel.slab import build_slab_stages

    mesh = dfft.make_mesh(8)
    sb, _ = build_slab_stages(mesh, SHAPE, algorithm=alg,
                              overlap_chunks=k, batch=B)
    s1, _ = build_slab_stages(mesh, SHAPE, algorithm=alg, overlap_chunks=k)
    x = _world(batch=B)
    b = jnp.asarray(x)
    seq = [jnp.asarray(x[i]) for i in range(B)]
    for (_, fb), (_, f1) in zip(sb, s1):
        b = fb(b)
        seq = [f1(v) for v in seq]
        assert np.array_equal(
            np.asarray(b), np.stack([np.asarray(v) for v in seq]))


def test_staged_pencil_batch_parity_bitwise():
    from distributedfft_tpu.parallel.staged import build_pencil_stages

    mesh = dfft.make_mesh((2, 4))
    sb, _ = build_pencil_stages(mesh, UNEVEN, overlap_chunks=2, batch=B)
    s1, _ = build_pencil_stages(mesh, UNEVEN, overlap_chunks=2)
    x = _world(UNEVEN, batch=B)
    b = jnp.asarray(x)
    seq = [jnp.asarray(x[i]) for i in range(B)]
    for (_, fb), (_, f1) in zip(sb, s1):
        b, seq = fb(b), [f1(v) for v in seq]
    assert np.array_equal(
        np.asarray(b), np.stack([np.asarray(v) for v in seq]))


def _dd_pair(seed=3, batch=None):
    rng = np.random.default_rng(seed)
    full = SHAPE if batch is None else (batch,) + SHAPE
    hi = jnp.asarray((rng.standard_normal(full)
                      + 1j * rng.standard_normal(full)).astype(np.complex64))
    lo = jnp.asarray((rng.standard_normal(full) * 2.0 ** -25
                      + 0j).astype(np.complex64))
    return hi, lo


@pytest.mark.parametrize("alg,k", [("alltoall", 1), ("alltoall", 2),
                                   ("alltoallv", 2), ("ppermute", 2)])
def test_dd_slab_batch_parity_bitwise(alg, k):
    """Both dd components carry the batch axis through the shared
    collectives; the dd matmul engine is line-independent, so batch=B
    stays bit-identical to sequential executes."""
    from distributedfft_tpu.parallel.ddslab import build_dd_slab_fft3d

    mesh = dfft.make_mesh(8)
    fb, _ = build_dd_slab_fft3d(mesh, SHAPE, algorithm=alg,
                                overlap_chunks=k, batch=B)
    f1, _ = build_dd_slab_fft3d(mesh, SHAPE, algorithm=alg,
                                overlap_chunks=k)
    hi, lo = _dd_pair(batch=B)
    bh, bl = fb(hi, lo)
    for i in range(B):
        sh, sl = f1(hi[i], lo[i])
        assert np.array_equal(np.asarray(bh[i]), np.asarray(sh))
        assert np.array_equal(np.asarray(bl[i]), np.asarray(sl))


def test_dd_pencil_batch_parity_bitwise():
    mesh = dfft.make_mesh((2, 4))
    pb = dfft.plan_dd_dft_c2c_3d(SHAPE, mesh, batch=B, overlap_chunks=2)
    p1 = dfft.plan_dd_dft_c2c_3d(SHAPE, mesh, overlap_chunks=2)
    assert pb.batch == B
    hi, lo = _dd_pair(batch=B)
    bh, bl = pb(hi, lo)
    for i in range(B):
        sh, sl = p1(hi[i], lo[i])
        assert np.array_equal(np.asarray(bh[i]), np.asarray(sh))
        assert np.array_equal(np.asarray(bl[i]), np.asarray(sl))


# ----------------------------------------------------------- lowering pins

@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_batch1_hlo_byte_identical(mesh_shape):
    """batch=1 (and None) IS the unbatched plan: byte-identical HLO, no
    [1, ...] program for the serving tier's singleton path."""
    mesh = dfft.make_mesh(mesh_shape)
    base = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    b1 = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=1)
    assert b1.batch is None and b1.in_shape == SHAPE
    t_base = base.fn.lower(
        jax.ShapeDtypeStruct(base.in_shape, base.in_dtype)).as_text()
    t_b1 = b1.fn.lower(
        jax.ShapeDtypeStruct(b1.in_shape, b1.in_dtype)).as_text()
    assert t_base == t_b1


@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.parametrize("alg,per_exchange", [
    ("alltoall", 1),
    ("alltoallv", 1),   # CPU mirrors the ragged op densely: still 1/chunk
    ("ppermute", 7),    # (P-1)-step ring per chunk
])
def test_batch_collective_count_matches_unbatched(alg, k, per_exchange):
    """One SHARED exchange per (chunk, exchange) regardless of B: the
    compiled collective count of a batch=B plan equals the batch=1
    count for every transport — batching must never serialize into
    per-element collectives (that would forfeit the whole win)."""
    mesh = dfft.make_mesh(8)
    kw = dict(dtype=CDT, algorithm=alg, overlap_chunks=k)
    pb = dfft.plan_dft_c2c_3d(SHAPE, mesh, **kw, batch=4)
    p1 = dfft.plan_dft_c2c_3d(SHAPE, mesh, **kw)
    assert _collectives(pb) == _collectives(p1) == k * per_exchange


@pytest.mark.parametrize("k", [1, 2])
def test_pencil_batch_compiles_to_2k_collectives(k):
    mesh = dfft.make_mesh((2, 4))
    pb = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, overlap_chunks=k,
                              batch=4)
    assert _collectives(pb) == 2 * k


# ------------------------------------------------------------- plan layer

def test_batched_plan_metadata_and_info():
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=B)
    assert plan.batch == B
    assert plan.in_shape == (B,) + SHAPE
    assert plan.logic.batch == B
    assert plan.in_sharding.spec == batch_pspec(plan.spec.in_pspec, B)
    info = dfft.plan_info(plan)
    assert f"batch: {B} coalesced transforms" in info
    # Boxes stay per-transform (every batch element shares the geometry).
    assert plan.in_boxes[0].shape == (2, 16, 16)


def test_batched_exchange_bytes_scale_with_b():
    from distributedfft_tpu.api import _plan_exchange_bytes

    mesh = dfft.make_mesh(8)
    pb = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=B)
    p1 = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    tb, wb = _plan_exchange_bytes(pb)
    t1, w1 = _plan_exchange_bytes(p1)
    assert tb == B * t1 and wb == B * w1


def test_batch_validation():
    mesh = dfft.make_mesh(8)
    with pytest.raises(ValueError, match="batch"):
        dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=0)
    with pytest.raises(ValueError, match="batch"):
        dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=2.5)
    from jax.sharding import PartitionSpec as P

    with pytest.raises(ValueError, match="in_spec"):
        dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=2,
                             in_spec=P("slab", None, None))
    with pytest.raises(ValueError, match="r2c_axis"):
        dfft.plan_dft_r2c_3d(SHAPE, mesh, batch=2, r2c_axis=0)
    assert check_batch(None) is None and check_batch(4) == 4


def test_batched_model_scales_with_b():
    """exchange_model_seconds / model_stage_seconds price the B-fold
    payload (tuner pruning and explain attribution stay honest)."""
    from distributedfft_tpu.parallel.exchange import exchange_model_seconds
    from distributedfft_tpu.plan_logic import model_stage_seconds

    m1 = exchange_model_seconds(1e6, 8, "alltoall", wire_gbps=45.0,
                                launch_seconds=1e-4)
    mb = exchange_model_seconds(1e6, 8, "alltoall", wire_gbps=45.0,
                                launch_seconds=1e-4, batch=4)
    wire1 = m1["seconds"] - 1e-4
    wireb = mb["seconds"] - 1e-4
    assert abs(wireb - 4 * wire1) < 1e-12  # launches paid once

    mesh = dfft.make_mesh(8)
    pb = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, batch=B)
    p1 = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    kw = dict(hbm_gbps=819.0, wire_gbps=45.0, launch_seconds=1e-4)
    s1 = model_stage_seconds(p1.logic, SHAPE, 16, **kw)
    sb = model_stage_seconds(pb.logic, SHAPE, 16, **kw)
    for st in ("t0", "t3"):
        assert abs(sb[st]["hbm_bytes"] - B * s1[st]["hbm_bytes"]) < 1e-9
        assert abs(sb[st]["flops"] - B * s1[st]["flops"]) < 1e-6
    assert abs(sb["t2"]["wire_bytes"] - B * s1["t2"]["wire_bytes"]) < 1e-9


def test_wisdom_key_separates_batched_plans():
    from distributedfft_tpu import tuner

    k1 = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=CDT,
                          direction=-1, ndev=8, mesh_dims=(8,))
    kb = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=CDT,
                          direction=-1, ndev=8, mesh_dims=(8,), batch=8)
    assert k1["batch"] is None and kb["batch"] == 8
    assert tuner._key_id(k1) != tuner._key_id(kb)


# ------------------------------------------------------------ serving tier

def test_coalescing_queue_one_batched_execute_on_mesh():
    """Three pending same-tuple requests flush as ONE batched device
    program (metrics prove a single batch=3 execute), bit-identical to
    direct unbatched executes."""
    from distributedfft_tpu.utils import metrics as _m

    mesh = dfft.make_mesh(8)
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    q = dfft.CoalescingQueue(mesh, max_batch=8, dtype=CDT)
    xs = [_world(seed=s) for s in (1, 2, 3)]
    dfft.enable_metrics()
    _m.metrics_reset()
    handles = [q.submit(jnp.asarray(v)) for v in xs]
    assert q.pending() == 3
    assert q.flush() == 3
    snap = dfft.metrics_snapshot()
    assert snap["counters"]["serving_flushes"]["kind=c2c"] == 1.0
    assert snap["counters"]["serving_transforms"]["kind=c2c"] == 3.0
    # Exactly one (batched) chain execute ran for the whole group.
    execs = snap["counters"]["executes"]
    assert sum(execs.values()) == 1.0
    for v, h in zip(xs, handles):
        assert np.array_equal(np.asarray(h.result()),
                              np.asarray(ref(jnp.asarray(v))))


def test_queue_auto_flush_and_lazy_result():
    mesh = dfft.make_mesh(8)
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    q = dfft.CoalescingQueue(mesh, max_batch=2, dtype=CDT)
    x1, x2 = _world(seed=11), _world(seed=12)
    h1 = q.submit(jnp.asarray(x1))
    h2 = q.submit(jnp.asarray(x2))  # reaches max_batch -> auto-flush
    assert q.pending() == 0
    assert np.array_equal(np.asarray(h1.result()),
                          np.asarray(ref(jnp.asarray(x1))))
    # A singleton group flushes through the UNBATCHED plan on result().
    h3 = q.submit(jnp.asarray(x1))
    assert q.pending() == 1
    assert np.array_equal(np.asarray(h3.result()),
                          np.asarray(ref(jnp.asarray(x1))))
    assert q.pending() == 0


def test_submit_await_direct():
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    x = _world(seed=21)
    h = dfft.submit(plan, jnp.asarray(x))
    y = h.result()
    assert h.done()
    assert np.array_equal(np.asarray(y), np.asarray(plan(jnp.asarray(x))))
