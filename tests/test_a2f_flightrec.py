"""Flight recorder — the execution tier (8-device CPU-mesh runs).

The acceptance path of the end-to-end flight recorder: a coalesced
serving run with tracing enabled produces request spans
(submit/wait/flush/execute/result) that round-trip through ``report
merge`` into ONE Chrome/Perfetto timeline alongside the chain builders'
t0..t3 stage spans; ``dfft.explain`` falls back cleanly from the
device-timeline capture on CPU and produces across-hosts rows under
``allgather=True``. Pure-python flight-recorder tests (trace parser,
calibration store, trend CLI) live in ``tests/test_explain.py`` and
``tests/test_serving.py``.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` — the environment's pre-existing XLA:CPU
fft-thunk layout bug poisons the process's sharded dispatch stream for
every later 8-device test, and the executions here need a clean
backend. Same ordering rule as ``test_a2a_overlap.py`` /
``test_a2c_tuner.py`` / ``test_a2d_explain.py`` / ``test_a2e_batch.py``;
the guard in ``test_explain.py::test_poison_ordering_guard`` asserts
the names keep sorting this way.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import report
from distributedfft_tpu.utils import metrics as _m
from distributedfft_tpu.utils import trace as tr

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

SHAPE = (8, 8, 8)
CDT = jnp.complex128


def _world(seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(SHAPE) + 1j * rng.standard_normal(SHAPE)


@pytest.fixture
def recorder(tmp_path):
    """A chrome trace session + enabled metrics, torn down clean."""
    tr.init_tracing(str(tmp_path / "frec"), format="chrome")
    dfft.enable_metrics()
    _m.metrics_reset()
    yield tmp_path
    if tr.tracing_enabled():
        tr.finalize_tracing()
    _m.metrics_reset()
    dfft.enable_metrics(False)


def test_request_spans_merge_with_stage_spans(recorder):
    """THE acceptance criterion: one coalesced queue run -> request
    spans and t0..t3 stage spans in the same merged Perfetto trace."""
    mesh = dfft.make_mesh(8)
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    q = dfft.CoalescingQueue(mesh, max_batch=8, dtype=CDT)
    xs = [_world(s) for s in (1, 2, 3)]
    hs = [q.submit(jnp.asarray(v)) for v in xs]
    assert q.flush() == 3
    for v, h in zip(xs, hs):
        assert np.array_equal(np.asarray(h.result()),
                              np.asarray(ref(jnp.asarray(v))))
    path = tr.finalize_tracing()
    events = report.load_events(path)
    names = {e["name"] for e in events}
    # Request lifecycle spans, with ids and the batch/reason tags.
    assert any(n.startswith("serve_submit[") for n in names)
    assert sum(n.startswith("serve_wait[") for n in names) == 3
    assert "serve_flush[c2c:b3:manual]" in names
    assert "serve_plan[c2c:b3:manual]" in names
    assert "serve_execute[c2c:b3:manual]" in names
    # ... on the same timeline as the chain's stage spans.
    stage_keys = {tr.stage_key(n) for n in names} - {None}
    assert {"t0", "t2", "t3"} <= stage_keys
    # The wait interval closes before its group's flush span ends.
    flush = next(e for e in events
                 if e["name"] == "serve_flush[c2c:b3:manual]")
    for e in events:
        if e["name"].startswith("serve_wait["):
            assert e["ts"] + e["dur"] <= flush["ts"] + flush["dur"] + 1e3
    # Round-trip: the merged chrome artifact re-loads with every span.
    merged = str(recorder / "merged.json")
    report.write_chrome(events, merged)
    again = {e["name"] for e in report.load_events(merged)}
    assert names == again
    # Metrics side of the recorder.
    snap = dfft.metrics_snapshot()
    assert snap["counters"]["serving_flush_reasons"][
        "kind=c2c,reason=manual"] == 1.0
    assert snap["histograms"]["serving_wait_seconds"][
        "kind=c2c"]["count"] == 3
    assert snap["gauges"]["serving_queue_depth"]["kind=c2c"] == 0.0


def test_auto_flush_reason_and_result_reason(recorder):
    mesh = dfft.make_mesh(8)
    q = dfft.CoalescingQueue(mesh, max_batch=2, dtype=CDT)
    h1 = q.submit(jnp.asarray(_world(11)))
    q.submit(jnp.asarray(_world(12)))  # hits max_batch -> reason "full"
    h1.result()
    h3 = q.submit(jnp.asarray(_world(13)))
    h3.result()                        # await outruns -> reason "result"
    reasons = dfft.metrics_snapshot()["counters"]["serving_flush_reasons"]
    assert reasons["kind=c2c,reason=full"] == 1.0
    assert reasons["kind=c2c,reason=result"] == 1.0
    path = tr.finalize_tracing()
    names = {e["name"] for e in report.load_events(path)}
    assert "serve_flush[c2c:b2:full]" in names
    assert "serve_flush[c2c:b1:result]" in names
    assert any(n.startswith("serve_result[") for n in names)


def test_queue_behavior_identical_with_recorder_off():
    """The disabled path: no tracing, no metrics -> no ids, no
    timestamps, and the exact same results (mesh tier)."""
    assert not tr.tracing_enabled() and not _m.metrics_enabled()
    mesh = dfft.make_mesh(8)
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    q = dfft.CoalescingQueue(mesh, max_batch=8, dtype=CDT)
    xs = [_world(s) for s in (21, 22, 23)]
    hs = [q.submit(jnp.asarray(v)) for v in xs]
    assert all(h._req_id is None and h._enqueued is None for h in hs)
    assert q.flush() == 3
    for v, h in zip(xs, hs):
        assert np.array_equal(np.asarray(h.result()),
                              np.asarray(ref(jnp.asarray(v))))
    assert dfft.metrics_snapshot()["counters"] == {}


def test_explain_device_timing_falls_back_cleanly_on_cpu():
    """DFFT_DEVICE_TIMING on the CPU backend: the capture attempt runs,
    finds no device lanes, and the record says so — host samples and
    divergence machinery intact (the acceptance fallback path)."""
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8), dtype=CDT)
    rec = dfft.explain(plan, iters=2, device_timing=True)
    assert rec["timing"]["device_requested"] is True
    assert rec["timing"]["source"] == "host"
    assert rec["timing"]["fallback_reason"]
    for key in ("t0", "t2", "t3"):
        assert rec["stages"][key]["measured"]["available"]
    # JSON-serializable end to end (run records embed it verbatim).
    json.dumps(rec)


def test_explain_allgather_single_process_rows():
    plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8), dtype=CDT)
    rec = dfft.explain(plan, iters=2, allgather=True)
    ah = rec["across_hosts"]
    assert ah["processes"] == 1
    for key in ("t0", "t2", "t3"):
        row = ah["stages"][key]
        assert row["n"] == 1
        assert row["min"] == row["median"] == row["max"] > 0
