"""Topology-aware t2 exchange (PR 8): hierarchical ICI/DCN two-leg
transport + on-wire bf16 compression, through the tuner/explain/regress
loop.

Contracts pinned on the 8-way CPU mesh:

1. **Defaults are free** — ``wire_dtype=None`` (env unset) and the
   default transport compile byte-identical HLO to an explicitly exact
   plan; no bf16 collective sneaks into a default program (the batch=1 /
   overlap-K=1 pin discipline).
2. **bf16 wire halves t2 bytes** — `WIRE_BYTE_KEYS`-accounted wire
   bytes are exactly halved for c64 across all three flat transports x
   slab/pencil x K in {1,2} x batch in {None, B}, the lowered StableHLO
   carries the bf16 collective, and the measured round-trip error is
   bounded (<= 1e-2 rel for the c64 smoke shapes).
3. **Hierarchical = flat, bit for bit** — the two-leg transport on a
   2x4 (dcn x ici) hybrid mesh reproduces the flat slab exchange exactly
   (even and uneven extents, c64 and c128, composed with the bf16 wire),
   and its legs surface as separate ``t2a``/``t2b`` stages/rows in the
   staged pipeline and ``dfft.explain``.
4. **Tuner integration** — both dimensions enumerate (hybrid pairing,
   budget-gated wire axis), prune under the per-leg model, persist to
   wisdom with the extended key, and compressed winners replay only into
   plans whose error budget admits their recorded round-trip error.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` (alphabetical collection). The environment's
XLA:CPU has a known fft-thunk layout bug whose INTERNAL error
permanently poisons the process's sharded dispatch stream; once any
earlier test trips it, every later 8-device execute fails regardless of
correctness. The parity assertions here need a clean backend, and this
file itself triggers no fft-layout fault. The guard in
``test_explain.py::test_poison_ordering_guard`` pins the name.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import distributedfft_tpu as dfft
from distributedfft_tpu import regress, report, tuner
from distributedfft_tpu.parallel import multihost
from distributedfft_tpu.parallel.exchange import (
    ALGORITHMS,
    FLAT_ALGORITHMS,
    WIRE_DTYPES,
    wire_decode,
    wire_encode,
    wire_itemsize,
    wire_roundtrip_error,
)
from distributedfft_tpu.plan_logic import (
    PlanOptions,
    exchange_payloads,
    model_stage_seconds,
    resolve_wire_dtype,
)

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 8)
UNEVEN = (12, 10, 9)
CDT = jnp.complex64
ERR_BOUND = 1e-2  # acceptance bound for c64 smoke shapes


def _hybrid_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici"))


def _world(shape=SHAPE, seed=7):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


def _rel_err(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return float(np.max(np.abs(a - b)) / np.max(np.abs(b)))


@pytest.fixture
def wisdom_path(tmp_path, monkeypatch):
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "wisdom.jsonl"))
    monkeypatch.setenv("DFFT_COMPILE_CACHE", str(tmp_path / "xla_cache"))
    return str(tmp_path / "wisdom.jsonl")


# ------------------------------------------------------- wire primitives

def test_wire_itemsize():
    assert wire_itemsize(8, None) == 8
    assert wire_itemsize(16, None) == 16
    assert wire_itemsize(8, "bf16") == 4    # c64 -> bf16 pair: half
    assert wire_itemsize(16, "bf16") == 4   # c128 -> bf16 pair: quarter
    with pytest.raises(ValueError, match="wire_dtype"):
        wire_itemsize(8, "fp8")


def test_wire_encode_decode_roundtrip():
    x = jnp.asarray(_world((4, 5, 3)))
    w = wire_encode(x, "bf16")
    assert w.dtype == jnp.bfloat16 and w.shape == x.shape + (2,)
    y = wire_decode(w, x.dtype)
    assert y.dtype == x.dtype and y.shape == x.shape
    assert _rel_err(y, x) <= ERR_BOUND
    # bf16 round-trips are idempotent: a second cast pair is exact (the
    # staged per-leg decode/encode boundary relies on this).
    assert np.array_equal(
        np.asarray(wire_decode(wire_encode(y, "bf16"), y.dtype)),
        np.asarray(y))
    with pytest.raises(TypeError, match="complex"):
        wire_encode(jnp.zeros((3,), jnp.float32), "bf16")
    with pytest.raises(ValueError, match="wire_dtype"):
        wire_encode(x, "fp8")  # unregistered codec


def test_wire_roundtrip_error_measured_and_cached():
    assert wire_roundtrip_error(np.complex64, None) == 0.0
    e64 = wire_roundtrip_error(np.complex64, "bf16")
    assert 0.0 < e64 <= ERR_BOUND
    e128 = wire_roundtrip_error(np.complex128, "bf16")
    assert 0.0 < e128 <= ERR_BOUND
    # Deterministic (seeded + cached): the tuner's per-candidate budget
    # filter must see one number, not a noise source.
    assert wire_roundtrip_error(np.complex64, "bf16") == e64


# -------------------------------------------------- options / env plumbing

def test_plan_options_validate_wire():
    assert PlanOptions(wire_dtype="bf16").wire_dtype == "bf16"
    assert PlanOptions(wire_dtype="BF16").wire_dtype == "bf16"
    assert PlanOptions(wire_dtype=None).wire_dtype is None
    assert PlanOptions(wire_dtype="none").wire_dtype == "none"
    with pytest.raises(ValueError, match="wire_dtype"):
        PlanOptions(wire_dtype="fp8")
    assert PlanOptions(max_roundtrip_err=1e-2).max_roundtrip_err == 1e-2
    for bad in (0.0, -1.0, True, "x"):
        with pytest.raises(ValueError, match="max_roundtrip_err"):
            PlanOptions(max_roundtrip_err=bad)
    assert "hierarchical" in ALGORITHMS
    assert "hierarchical" not in FLAT_ALGORITHMS
    assert None in WIRE_DTYPES and "bf16" in WIRE_DTYPES


def test_resolve_wire_dtype_env(monkeypatch):
    monkeypatch.delenv("DFFT_WIRE_DTYPE", raising=False)
    assert resolve_wire_dtype(None) is None
    monkeypatch.setenv("DFFT_WIRE_DTYPE", "bf16")
    assert resolve_wire_dtype(None) == "bf16"
    # "none" pins the exact wire regardless of the env.
    assert resolve_wire_dtype("none") is None
    monkeypatch.setenv("DFFT_WIRE_DTYPE", "fp8")
    with pytest.raises(ValueError, match="DFFT_WIRE_DTYPE"):
        resolve_wire_dtype(None)


# ----------------------------------------------------------- default pin

@needs_mesh
@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_default_hlo_byte_identical(mesh_shape, monkeypatch):
    """wire_dtype=None (env unset) IS the exact plan: byte-identical
    lowered HLO, no bf16 collective — the batch=1 / K=1 pin rule."""
    monkeypatch.delenv("DFFT_WIRE_DTYPE", raising=False)
    mesh = dfft.make_mesh(mesh_shape)
    base = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    pinned = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                                  wire_dtype="none")
    assert base.options.wire_dtype is None
    t_base = base.fn.lower(
        jax.ShapeDtypeStruct(base.in_shape, base.in_dtype)).as_text()
    t_pin = pinned.fn.lower(
        jax.ShapeDtypeStruct(pinned.in_shape, pinned.in_dtype)).as_text()
    assert t_base == t_pin
    assert "bf16" not in t_base


# --------------------------------------------------- bf16 wire acceptance

@needs_mesh
@pytest.mark.parametrize("alg", FLAT_ALGORITHMS)
@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.parametrize("batch", [None, 3])
def test_bf16_wire_bytes_halved(alg, mesh_shape, k, batch):
    """The acceptance matrix: c64 wire bytes exactly halved (per the
    shared WIRE_BYTE_KEYS accounting) on all three flat transports x
    slab/pencil x K in {1,2} x batch in {None, B}, with the bf16
    collective visible in the lowered program."""
    from distributedfft_tpu.api import _plan_exchange_bytes

    mesh = dfft.make_mesh(mesh_shape)
    kw = dict(dtype=CDT, algorithm=alg, overlap_chunks=k, batch=batch)
    exact = dfft.plan_dft_c2c_3d(SHAPE, mesh, **kw)
    comp = dfft.plan_dft_c2c_3d(SHAPE, mesh, wire_dtype="bf16", **kw)
    t_e, w_e = _plan_exchange_bytes(exact)
    t_c, w_c = _plan_exchange_bytes(comp)
    assert t_c == t_e                  # true information is unchanged
    assert w_c * 2 == w_e              # wire bytes exactly halved
    txt = comp.fn.lower(
        jax.ShapeDtypeStruct(comp.in_shape, comp.in_dtype)).as_text()
    assert "bf16" in txt


@needs_mesh
@pytest.mark.parametrize("alg", FLAT_ALGORITHMS)
@pytest.mark.parametrize("shape", [SHAPE, UNEVEN])
def test_bf16_roundtrip_error_bounded(alg, shape):
    """Compressed forward output vs the exact plan's: bounded by the
    measured one-cast error (x2 slack for the two exchanges of a pencil
    chain and accumulation through the FFTs)."""
    mesh = dfft.make_mesh(8)
    exact = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, algorithm=alg)
    comp = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, algorithm=alg,
                                wire_dtype="bf16")
    x = jnp.asarray(_world(shape))
    assert _rel_err(comp(x), exact(x)) <= ERR_BOUND


@needs_mesh
def test_bf16_env_resolves_into_plan(monkeypatch):
    monkeypatch.setenv("DFFT_WIRE_DTYPE", "bf16")
    dfft.clear_plan_cache()
    try:
        plan = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8), dtype=CDT)
        assert plan.options.wire_dtype == "bf16"
    finally:
        dfft.clear_plan_cache()


def test_payload_wire_factor_single_device():
    # Single-device plans have no wire to compress: the option resolves
    # to None and the payload list stays empty.
    plan = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, wire_dtype="bf16")
    assert plan.options.wire_dtype is None


# ------------------------------------------------- hierarchical transport

def test_hier_validation():
    with pytest.raises(ValueError, match="hybrid"):
        dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8),
                             algorithm="hierarchical", dtype=CDT)
    with pytest.raises(ValueError, match="slab"):
        dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(),
                             algorithm="hierarchical",
                             decomposition="pencil", dtype=CDT)
    with pytest.raises(ValueError, match="c2c"):
        dfft.plan_dft_r2c_3d(SHAPE, _hybrid_mesh(),
                             algorithm="hierarchical")


@needs_mesh
@pytest.mark.parametrize("shape", [SHAPE, UNEVEN])
@pytest.mark.parametrize("cdt", [jnp.complex64, jnp.complex128])
@pytest.mark.parametrize("direction", [dfft.FORWARD, dfft.BACKWARD])
def test_hier_parity_bitwise(shape, cdt, direction):
    """Bit parity with the flat slab exchange over the combined axis,
    even and uneven extents, both directions, both widths."""
    hier = dfft.plan_dft_c2c_3d(shape, _hybrid_mesh(), dtype=cdt,
                                algorithm="hierarchical",
                                direction=direction)
    flat = dfft.plan_dft_c2c_3d(shape, dfft.make_mesh(8), dtype=cdt,
                                decomposition="slab", direction=direction)
    assert hier.decomposition == "slab"
    x = jnp.asarray(_world(shape).astype(np.dtype(cdt)))
    assert np.array_equal(np.asarray(hier(x)), np.asarray(flat(x)))


@needs_mesh
def test_hier_composes_with_wire_and_overlap():
    """hier+bf16 == flat+bf16 bitwise (the legs are exact reorderings of
    the encoded payload), and overlap-K keeps parity too."""
    x = jnp.asarray(_world())
    hier = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=CDT,
                                algorithm="hierarchical",
                                wire_dtype="bf16")
    flat = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8), dtype=CDT,
                                decomposition="slab", wire_dtype="bf16")
    assert np.array_equal(np.asarray(hier(x)), np.asarray(flat(x)))
    hk = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=CDT,
                              algorithm="hierarchical", overlap_chunks=2)
    base = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=CDT,
                                algorithm="hierarchical")
    assert np.array_equal(np.asarray(hk(x)), np.asarray(base(x)))


@needs_mesh
def test_hier_staged_legs_parity_and_names():
    """The staged pipeline splits the hierarchical t2 into separately
    jitted per-leg stages (t2a on the ICI axis, t2b on the DCN axis)
    whose composition matches the fused plan bitwise."""
    from distributedfft_tpu.parallel.slab import build_slab_stages

    mesh = _hybrid_mesh()
    stages, _ = build_slab_stages(mesh, SHAPE,
                                  axis_name=("dcn", "ici"),
                                  algorithm="hierarchical")
    names = [n for n, _ in stages]
    assert "t2a_exchange_ici" in names
    assert "t2b_exchange_dcn" in names
    fused = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                                 algorithm="hierarchical")
    x = jnp.asarray(_world())
    cur = x
    for _, fn in stages:
        cur = fn(cur)
    assert np.array_equal(np.asarray(cur), np.asarray(fused(x)))


def test_hier_payload_entries():
    """Per-leg byte accounting: one entry per leg, tagged with its link
    and the wire factor of the plan's compression."""
    mesh = _hybrid_mesh()
    lp = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                              algorithm="hierarchical").logic
    entries = exchange_payloads(lp, SHAPE, 8)
    assert [e["stage"] for e in entries] == ["t2a", "t2b"]
    assert [e["link"] for e in entries] == ["ici", "dcn"]
    assert [e["parts"] for e in entries] == [4, 2]
    assert all(e["wire_factor"] == 1.0 for e in entries)
    lpc = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                               algorithm="hierarchical",
                               wire_dtype="bf16").logic
    assert all(e["wire_factor"] == 0.5
               for e in exchange_payloads(lpc, SHAPE, 8))
    # Each leg ships fraction (parts-1)/parts of the world on ITS axis.
    world = int(np.prod(SHAPE)) * 8
    assert entries[0]["alltoall_bytes"] == world * 3 // 4
    assert entries[1]["alltoall_bytes"] == world // 2


def test_hier_model_prices_dcn_leg():
    """The per-leg model: the DCN leg is priced at dcn_gbps, the ICI leg
    at wire_gbps — visible in the t2 legs rows."""
    lp = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=CDT,
                              algorithm="hierarchical").logic
    out = model_stage_seconds(lp, SHAPE, 8, hbm_gbps=819.0,
                              wire_gbps=45.0, launch_seconds=1e-4,
                              dcn_gbps=1.0, algorithm="hierarchical")
    legs = {leg["stage"]: leg for leg in out["t2"]["legs"]}
    assert legs["t2a"]["link"] == "ici" and legs["t2a"]["wire_gbps"] == 45.0
    assert legs["t2b"]["link"] == "dcn" and legs["t2b"]["wire_gbps"] == 1.0
    # Same wire bytes per device on the DCN leg would take ~45x longer at
    # 1 GB/s; the leg rows carry that asymmetry.
    assert legs["t2b"]["raw_seconds"] > legs["t2a"]["raw_seconds"]
    # No dcn figure -> both legs priced at the flat wire number.
    out2 = model_stage_seconds(lp, SHAPE, 8, hbm_gbps=819.0,
                               wire_gbps=45.0, launch_seconds=1e-4,
                               algorithm="hierarchical")
    legs2 = {leg["stage"]: leg for leg in out2["t2"]["legs"]}
    assert legs2["t2b"]["wire_gbps"] == 45.0


@needs_mesh
def test_hier_explain_legs_and_wire_block():
    """Acceptance: the two legs appear as distinct t2a/t2b rows in
    dfft.explain with per-leg modeled AND measured times, and the wire
    block surfaces the measured compression error."""
    plan = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=CDT,
                                algorithm="hierarchical",
                                wire_dtype="bf16")
    rec = dfft.explain(plan, iters=2)
    legs = {leg["stage"]: leg for leg in rec["stages"]["t2"]["legs"]}
    assert set(legs) == {"t2a", "t2b"}
    for leg in legs.values():
        assert leg["seconds"] > 0            # modeled
        assert leg["measured_seconds"] > 0   # measured
    assert legs["t2a"]["link"] == "ici"
    assert legs["t2b"]["link"] == "dcn"
    assert rec["plan"]["wire_dtype"] == "bf16"
    wire = rec["wire"]
    assert wire["wire_dtype"] == "bf16"
    assert 0.0 < wire["compression_err"] <= ERR_BOUND
    assert wire["wire_factor"] == 0.5
    # The rendered table carries the per-leg rows and the wire line.
    txt = dfft.explain_mod.format_explain(rec)
    assert "t2a" in txt and "t2b" in txt and "bf16" in txt


def test_is_hybrid_mesh():
    assert multihost.is_hybrid_mesh(_hybrid_mesh())
    assert not multihost.is_hybrid_mesh(dfft.make_mesh(8))
    assert not multihost.is_hybrid_mesh(dfft.make_mesh((2, 4)))


# ------------------------------------------------------ tuner integration

def test_enumerate_hybrid_pairs():
    cands = tuner.enumerate_candidates(SHAPE, 8, hybrid=True,
                                       executors=("xla",))
    pairs = {(c.decomposition, c.algorithm) for c in cands}
    assert ("slab", "hierarchical") in pairs
    assert all(alg == "hierarchical" for d, alg in pairs if d == "slab")
    assert {("pencil", a) for a in FLAT_ALGORITHMS} <= pairs
    # Flat (non-hybrid) spaces never contain the two-leg transport.
    flat = tuner.enumerate_candidates(SHAPE, 8, executors=("xla",))
    assert all(c.algorithm != "hierarchical" for c in flat)


def test_enumerate_wire_axis_and_labels():
    cands = tuner.enumerate_candidates(
        SHAPE, 8, executors=("xla",), wire_dtypes=(None, "bf16"))
    by_wire = {c.wire_dtype for c in cands}
    assert by_wire == {None, "bf16"}
    comp = next(c for c in cands if c.wire_dtype == "bf16")
    assert comp.label.endswith("+wbf16")
    # Default axis is exact-only (today's space).
    assert {c.wire_dtype for c in tuner.enumerate_candidates(
        SHAPE, 8, executors=("xla",))} == {None}


def test_prune_budget_filters_compressed():
    cands = tuner.enumerate_candidates(
        SHAPE, 8, executors=("xla",), wire_dtypes=(None, "bf16"))
    # A budget below the measured cast error: compressed candidates are
    # inadmissible and must not crowd the survivor set.
    tight = tuner.prune_candidates(cands, SHAPE, 8, limit=32,
                                   max_err=1e-9, dtype=np.complex64)
    assert tight and all(c.wire_dtype is None for c in tight)
    # A budget above it keeps the wire axis in play.
    loose = tuner.prune_candidates(cands, SHAPE, 8, limit=32,
                                   max_err=1e-1, dtype=np.complex64)
    assert any(c.wire_dtype == "bf16" for c in loose)


def test_wisdom_key_err_budget_isolated():
    base = dict(kind="c2c", shape=SHAPE, dtype=np.complex64,
                direction=-1, ndev=8, mesh_dims=None,
                device_kind="cpu", platform="cpu")
    k0 = tuner.wisdom_key(**base)
    kb = tuner.wisdom_key(**base, err_budget=1e-2)
    assert k0["err_budget"] is None and kb["err_budget"] == 1e-2
    assert tuner._key_id(k0) != tuner._key_id(kb)


def test_record_wisdom_stamps_compression_err(wisdom_path):
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=-1, ndev=8, mesh_dims=None,
                           device_kind="cpu", platform="cpu",
                           err_budget=1e-2)
    cand = tuner.Candidate("slab", "alltoall", "xla", 1, "bf16")
    entry = tuner.record_wisdom(key, cand, 0.001, path=wisdom_path)
    assert entry["winner"]["wire_dtype"] == "bf16"
    assert 0.0 < entry["compression_err"] <= ERR_BOUND
    # Exact winners carry no error stamp (old schema preserved).
    exact = tuner.record_wisdom(
        tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                         direction=-1, ndev=8, mesh_dims=None,
                         device_kind="cpu", platform="cpu"),
        tuner.Candidate("slab", "alltoall", "xla", 1), 0.001,
        path=wisdom_path)
    assert "compression_err" not in exact
    assert exact["winner"]["wire_dtype"] is None


def _replay_entry(wisdom_path, err_budget, compression_err):
    """Hand-write one compressed-winner entry under the key the tuned
    planner will look up for (SHAPE, c64, forward, ndev=8)."""
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=dfft.FORWARD, ndev=8,
                           mesh_dims=None, err_budget=err_budget)
    entry = {
        "schema": tuner.WISDOM_SCHEMA,
        "recorded_at": "2026-08-01T00:00:00", "key": key,
        "winner": {"decomposition": "slab", "algorithm": "alltoall",
                   "executor": "xla", "overlap_chunks": 1,
                   "wire_dtype": "bf16"},
        "seconds": 0.001, "compression_err": compression_err,
    }
    with open(wisdom_path, "a") as f:
        f.write(json.dumps(entry) + "\n")


@needs_mesh
def test_compressed_winner_replay_admission(wisdom_path):
    """A stored compressed winner replays only into plans whose error
    budget admits its recorded round-trip error; a stale entry whose
    recorded error exceeds the plan's budget rebuilds on the exact
    wire."""
    dfft.clear_plan_cache()
    try:
        _replay_entry(wisdom_path, err_budget=1e-2, compression_err=3e-3)
        ok = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, tune="wisdom",
                                  max_roundtrip_err=1e-2)
        assert ok.options.wire_dtype == "bf16"
        assert ok.options.algorithm == "alltoall"
    finally:
        dfft.clear_plan_cache()


@needs_mesh
def test_compressed_winner_rejected_over_budget(wisdom_path):
    dfft.clear_plan_cache()
    try:
        # Recorded error ABOVE the (identical) budget: the tuple replays
        # but on the exact wire.
        _replay_entry(wisdom_path, err_budget=1e-4, compression_err=0.5)
        plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, tune="wisdom",
                                    max_roundtrip_err=1e-4)
        assert plan.options.wire_dtype is None
        assert plan.decomposition == "slab"
    finally:
        dfft.clear_plan_cache()


@needs_mesh
def test_measure_tournament_hybrid_with_budget(wisdom_path, monkeypatch):
    """End-to-end: a measured tournament on the hybrid mesh with an
    error budget enumerates the hierarchical and wire dimensions,
    records the winner under the extended key, and replays it from
    wisdom with zero further measurement."""
    from distributedfft_tpu.utils import metrics as m

    monkeypatch.setenv("DFFT_TUNE_ITERS", "1x1")
    monkeypatch.setenv("DFFT_TUNE_MAX", "3")
    dfft.clear_plan_cache()
    m.metrics_reset()
    m.enable_metrics()
    try:
        mesh = _hybrid_mesh()
        plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                                    tune="measure",
                                    max_roundtrip_err=1e-2)
        assert m.counter_total("tune_tournaments") == 1
        assert plan.decomposition in ("slab", "pencil")
        if plan.decomposition == "slab":
            assert plan.options.algorithm == "hierarchical"
        entries = tuner._read_wisdom(wisdom_path)
        assert len(entries) == 1
        entry = next(iter(entries.values()))
        assert entry["key"]["err_budget"] == 1e-2
        assert "wire_dtype" in entry["winner"]
        # Replay: same key, zero timing executions.
        m.metrics_reset()
        dfft.clear_plan_cache()
        replay = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                                      tune="wisdom",
                                      max_roundtrip_err=1e-2)
        assert m.counter_total("tune_timing_executions") == 0
        assert m.counter_total("tune_wisdom_hits") == 1
        assert replay.decomposition == plan.decomposition
        assert replay.options.algorithm == plan.options.algorithm
    finally:
        m.enable_metrics(False)
        m.metrics_reset()
        dfft.clear_plan_cache()


def test_report_wisdom_gate_extended_keys(tmp_path, wisdom_path, capsys):
    """`report wisdom --gate` still verdicts on the extended keys: a
    compressed winner gates against fresh history rows of its own
    +wbf16 label."""
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=-1, ndev=8, mesh_dims=None,
                           device_kind="cpu", platform="cpu",
                           err_budget=1e-2)
    cand = tuner.Candidate("slab", "alltoall", "xla", 1, "bf16")
    assert cand.label == "slab/alltoall/xla/ov1+wbf16"
    tuner.record_wisdom(key, cand, 0.001, path=wisdom_path)
    def hist_with(sub, seconds_list):
        path = tmp_path / sub / "history.jsonl"
        regress.append_records([
            regress.make_run_record(
                metric="fft3d_c2c_16_forward_gflops", value=10.0,
                seconds=s, config={"tuned": cand.label}, backend="cpu",
                device_kind="cpu", source="test")
            for s in seconds_list], str(path))
        return str(path)

    # Fresh rows at the recorded speed: the compressed label MATCHES
    # (fresh n=3, not no-baseline) and the gate passes.
    ok = hist_with("ok", (0.001, 0.00101, 0.00099))
    assert report.main(["wisdom", "--gate", "--wisdom", wisdom_path,
                        "--history", ok]) == 0
    out = capsys.readouterr().out
    assert "+wbf16" in out and "n=3" in out
    # Fresh rows 2x slower: stale, the gate fires on the extended key.
    stale = hist_with("stale", (0.002, 0.0021, 0.002))
    assert report.main(["wisdom", "--gate", "--wisdom", wisdom_path,
                        "--history", stale]) == 1
    assert "regressed" in capsys.readouterr().out


# --------------------------------------------------- driver / regress tier

def test_regress_wire_and_transport_key_baseline_group():
    """Compressed / two-leg runs never share a compare baseline with
    exact flat-exchange runs; default rows keep the old group."""
    base = {"metric": "fft3d_c2c_512_forward_gflops", "value": 100.0,
            "dtype": "complex64", "devices": 8, "decomposition": "slab",
            "backend": "tpu", "device_kind": "TPU v5 lite"}
    r0 = regress.normalize_bench_line(dict(base), source="test")
    rw = regress.normalize_bench_line(dict(base, wire_dtype="bf16"),
                                      source="test")
    rt = regress.normalize_bench_line(dict(base, transport="hierarchical"),
                                      source="test")
    assert "wire_dtype" not in r0["config"]
    assert rw["config"]["wire_dtype"] == "bf16"
    assert rt["config"]["transport"] == "hierarchical"
    keys = {regress.group_key(r) for r in (r0, rw, rt)}
    assert len(keys) == 3


def test_bench_emit_stamps_wire_and_transport(capsys):
    import os
    import sys
    TESTS = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(TESTS))
    import bench

    out = bench._emit(16, 1e-4, 1e-7, "xla", 8, "slab", {"xla": 1e-4},
                      wire_dtype="bf16", transport="hierarchical")
    capsys.readouterr()
    assert out["wire_dtype"] == "bf16"
    assert out["transport"] == "hierarchical"
    # Default rows keep the old schema.
    dflt = bench._emit(16, 1e-4, 1e-7, "xla", 8, "slab", {"xla": 1e-4},
                       wire_dtype=None, transport="alltoall")
    capsys.readouterr()
    assert "wire_dtype" not in dflt and "transport" not in dflt


def test_speed3d_wire_label():
    import os
    import sys
    TESTS = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(os.path.dirname(TESTS), "benchmarks"))
    from speed3d import _algorithm_label

    assert _algorithm_label("alltoall", 1, wire="bf16") == "alltoall+wbf16"
    assert _algorithm_label("alltoall", 4, batch=8,
                            wire="bf16") == "alltoall+ov4+b8+wbf16"
    assert _algorithm_label("alltoall", 1) == "alltoall"


def test_tuned_label_carries_wire(wisdom_path):
    plan = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, wire_dtype="bf16")
    # Single-device plans resolve wire to None: label stays bare.
    assert tuner.tuned_label(plan) == "single/alltoall/xla/ov1"
