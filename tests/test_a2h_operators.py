"""Spectral-operator subsystem: fused FFT -> pointwise -> iFFT plans.

``plan_spectral_op`` (and the ``solve_poisson``/``spectral_gradient``/
``gaussian_filter``/``fft_convolve`` planners) compose a forward chain
that STOPS in the transposed midpoint layout, a wavenumber-indexed
multiplier generated per shard right there (the ``t_mid`` stage), and
an inverse chain that retraces the exchanges — skipping the cancelling
transpose pair a natural-layout unfused composition pays. These tests
pin the tentpole's contracts on the 8-way CPU mesh:

1. **Fused == unfused** — the fused solve matches the unfused
   composition (forward plan x full-grid multiplier x inverse plan)
   within dtype tolerance, across slab/pencil x transports x overlap
   K in {1, 2} x batch in {None, 3}, uneven worlds, bf16 wire, and the
   hierarchical two-leg transport.
2. **Half the collectives** — the fused slab solve (K=1) compiles
   EXACTLY half the all-to-all collectives of the unfused
   natural-layout forward-then-inverse pair (the acceptance HLO pin),
   and the fused collective count scales as 2K (slab) / 4K (pencil) /
   2K(P-1) (ring).
3. **Own wisdom kind** — operator tournaments record under
   ``op:<name>``; transform planners never cross-replay them and the
   stored op winner replays with zero timing executions.
4. **dd r2c batch** (the PR 6 scope-gap satellite) — ``plan_dd_dft_
   r2c_3d(batch=B)`` is bit-identical to B sequential executes on
   single/slab/pencil, and ``batch=1`` compiles byte-identical HLO.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` — the environment's XLA:CPU fft-thunk layout bug
poisons the process's sharded dispatch stream for every later 8-device
execute once tripped (see ``test_a2a_overlap.py``; the guard in
``test_explain.py`` pins the ordering). This file avoids the one bad
chain geometry, so running first is safe for the rest of the suite.
"""

import json
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import operators

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 16)
UNEVEN = (12, 10, 9)
CDT = jnp.complex128
B = 3
TOL = 1e-11   # c128 tier: fused and unfused differ only by fp op order

ALGS = ("alltoall", "alltoallv", "ppermute")

_COLLECTIVE = re.compile(
    r"\b(all-to-all|all-gather|all-reduce|collective-permute)(?:-start)?\("
)


def _collectives_of(fn, in_shape, in_dtype) -> int:
    txt = fn.lower(
        jax.ShapeDtypeStruct(in_shape, in_dtype)).compile().as_text()
    return len(_COLLECTIVE.findall(txt))


def _world(shape=SHAPE, seed=7, batch=None):
    rng = np.random.default_rng(seed)
    full = shape if batch is None else (batch,) + tuple(shape)
    return rng.standard_normal(full) + 1j * rng.standard_normal(full)


def _unfused(op, x3, mesh, shape=SHAPE, dtype=CDT):
    """The reference composition: forward transform, full-grid
    multiplier, inverse transform (plan-cache-memoized per config)."""
    fwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=dtype)
    bwd = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD,
                               dtype=dtype)
    m = np.asarray(operators.multiplier_grid(op, shape, dtype))
    return np.asarray(bwd(m * np.asarray(fwd(jnp.asarray(x3)))))


def _relerr(got, ref) -> float:
    scale = max(float(np.max(np.abs(ref))), 1e-300)
    return float(np.max(np.abs(np.asarray(got) - ref))) / scale


# --------------------------------------------------- fused == unfused

@pytest.mark.parametrize("alg", ALGS)
@pytest.mark.parametrize("k", [1, 2])
def test_slab_fused_matches_unfused(alg, k):
    mesh = dfft.make_mesh(8)
    plan = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.poisson(), dtype=CDT, algorithm=alg,
        overlap_chunks=k)
    x = _world()
    assert _relerr(plan(x), _unfused(operators.poisson(), x, mesh)) < TOL


@pytest.mark.parametrize("k", [1, 2])
def test_pencil_fused_matches_unfused(k):
    mesh = dfft.make_mesh((2, 4))
    plan = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.gaussian(0.3), dtype=CDT,
        overlap_chunks=k)
    x = _world()
    assert _relerr(plan(x),
                   _unfused(operators.gaussian(0.3), x, mesh)) < TOL


@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_batched_op_matches_per_element_unfused(mesh_shape):
    """The batch axis is a pure bystander of the fused chain (closing
    PR 6's "batched spectral-operator fusion" leftover): each batch
    element matches the unfused composition of that element."""
    mesh = dfft.make_mesh(mesh_shape)
    op = operators.gradient(0)
    plan = operators.plan_spectral_op(
        SHAPE, mesh, op=op, dtype=CDT, batch=B,
        overlap_chunks=2 if mesh_shape == 8 else 1)
    xb = _world(batch=B)
    yb = np.asarray(plan(xb))
    assert plan.in_shape == (B,) + SHAPE and plan.batch == B
    for i in range(B):
        assert _relerr(yb[i], _unfused(op, xb[i], mesh)) < TOL


def test_uneven_fused_matches_unfused():
    """Uneven worlds exercise the ceil-pad/crop path of both legs (and
    the midpoint crop before the inverse transform)."""
    mesh = dfft.make_mesh(8)
    plan = operators.plan_spectral_op(
        UNEVEN, mesh, op=operators.poisson(), dtype=CDT,
        overlap_chunks=2)
    x = _world(UNEVEN)
    assert _relerr(
        plan(x), _unfused(operators.poisson(), x, mesh, UNEVEN)) < TOL


def test_wire_bf16_op_within_compression_tolerance():
    """The multiplier applies on the DECODED payload, so a bf16-wire
    solve differs from exact only by the per-leg cast error."""
    mesh = dfft.make_mesh(8)
    plan = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.poisson(), dtype=jnp.complex64,
        wire_dtype="bf16")
    x = _world().astype(np.complex64)
    err = _relerr(plan(x),
                  _unfused(operators.poisson(), x, mesh,
                           dtype=jnp.complex64))
    assert err < 2e-2  # four bf16 wire casts of a c64 chain
    assert plan.options.wire_dtype == "bf16"


def test_hierarchical_op_matches_flat():
    """Each leg of the fused chain runs the two-leg ICI/DCN transport
    over a hybrid mesh, bit-compatible with the flat unfused result."""
    from distributedfft_tpu.parallel.multihost import make_hybrid_mesh

    hm = make_hybrid_mesh()
    plan = operators.plan_spectral_op(
        SHAPE, hm, op=operators.poisson(), dtype=CDT,
        algorithm="hierarchical")
    x = _world()
    ref = _unfused(operators.poisson(), x, dfft.make_mesh(8))
    assert _relerr(plan(x), ref) < TOL


def test_single_device_fused_matches_unfused():
    plan = operators.plan_spectral_op(SHAPE, None,
                                      op=operators.poisson(), dtype=CDT)
    x = _world()
    assert plan.mesh is None and plan.decomposition == "single"
    assert _relerr(plan(x),
                   _unfused(operators.poisson(), x, None)) < TOL


# ----------------------------------------------------- operator menu

def test_solve_poisson_inverts_the_laplacian():
    """Physics acceptance: laplacian(solve(f)) == f - mean(f) (the
    solution is mean-free; numpy-side spectral laplacian as the
    independent reference)."""
    mesh = dfft.make_mesh(8)
    x = _world()
    u = np.asarray(dfft.solve_poisson(SHAPE, mesh, dtype=CDT)(x))
    f = np.fft.fftfreq(16) * 16
    kk = 2 * np.pi * f
    k2 = (kk[:, None, None] ** 2 + kk[None, :, None] ** 2
          + kk[None, None, :] ** 2)
    lap = np.fft.ifftn(-k2 * np.fft.fftn(u))
    assert _relerr(lap, x - x.mean()) < 1e-9


def test_spectral_gradient_matches_numpy():
    mesh = dfft.make_mesh(8)
    x = _world()
    got = np.asarray(dfft.spectral_gradient(SHAPE, mesh, axis=1,
                                            dtype=CDT)(x))
    f = np.fft.fftfreq(16) * 16
    ik = 1j * 2 * np.pi * f
    ref = np.fft.ifftn(ik[None, :, None] * np.fft.fftn(x))
    assert _relerr(got, ref) < 1e-10


def test_fft_convolve_delta_and_shift():
    """A delta kernel at the origin is the identity; a delta at +1 on
    axis 2 is a circular shift (two independent kernels must also never
    share a plan-cache entry — the content-digest identity)."""
    mesh = dfft.make_mesh(8)
    x = _world()
    k0 = np.zeros(SHAPE)
    k0[0, 0, 0] = 1.0
    p0 = dfft.fft_convolve(SHAPE, mesh, kernel=k0, dtype=CDT)
    assert _relerr(p0(x), x) < TOL
    k1 = np.zeros(SHAPE)
    k1[0, 0, 1] = 1.0
    p1 = dfft.fft_convolve(SHAPE, mesh, kernel=k1, dtype=CDT)
    assert p1 is not p0  # digest-keyed: different kernels, different plans
    assert _relerr(p1(x), np.roll(x, 1, axis=2)) < TOL


def test_custom_unit_multiplier_is_identity():
    mesh = dfft.make_mesh(8)
    op = operators.custom("unit", lambda i0, i1, i2: jnp.float32(1.0))
    plan = operators.plan_spectral_op(SHAPE, mesh, op=op, dtype=CDT)
    x = _world()
    assert _relerr(plan(x), x) < TOL


def test_gaussian_filter_preserves_mean_and_damps():
    mesh = dfft.make_mesh(8)
    x = _world()
    y = np.asarray(dfft.gaussian_filter(SHAPE, mesh, sigma=0.2,
                                        dtype=CDT)(x))
    # k=0 multiplier is exactly 1: the mean survives; energy shrinks.
    assert abs(y.mean() - x.mean()) < 1e-12
    assert np.linalg.norm(y) < np.linalg.norm(x)


# -------------------------------------------------------- HLO pins

def test_fused_poisson_half_the_collectives_of_unfused_pair():
    """THE acceptance pin: the fused slab solve (K=1) compiles exactly
    half the all-to-all collectives of the unfused natural-layout
    forward-then-inverse pair (multiplier applied in the caller's
    X-slab layout, the layout round trip the fusion cancels)."""
    from jax import lax

    mesh = dfft.make_mesh(8)
    plan = dfft.solve_poisson(SHAPE, mesh, dtype=CDT)
    fused = _collectives_of(plan.fn, plan.in_shape, plan.in_dtype)
    assert fused == 2  # one outbound + one return exchange

    fwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    bwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, direction=dfft.BACKWARD,
                               dtype=CDT)
    m = jnp.asarray(operators.multiplier_grid(operators.poisson(),
                                              SHAPE, CDT))

    @jax.jit
    def unfused(v):
        s = fwd.fn(v)
        # The natural-layout multiply: the caller's field AND multiplier
        # live in the input X-slab layout, so the spectrum reshards back
        # before the pointwise stage and out again for the inverse.
        s = lax.with_sharding_constraint(s, fwd.in_sharding)
        s = s * m
        s = lax.with_sharding_constraint(s, bwd.in_sharding)
        return bwd.fn(s)

    pair = _collectives_of(unfused, SHAPE, np.dtype(np.complex128))
    assert pair == 2 * fused == 4


def test_fused_collective_counts_scale_with_k_and_transport():
    mesh8 = dfft.make_mesh(8)
    mesh24 = dfft.make_mesh((2, 4))
    p_k2 = operators.plan_spectral_op(
        SHAPE, mesh8, op=operators.poisson(), dtype=CDT,
        overlap_chunks=2)
    assert _collectives_of(p_k2.fn, p_k2.in_shape, p_k2.in_dtype) == 4
    p_ring = operators.plan_spectral_op(
        SHAPE, mesh8, op=operators.poisson(), dtype=CDT,
        algorithm="ppermute")
    assert _collectives_of(p_ring.fn, p_ring.in_shape,
                           p_ring.in_dtype) == 2 * 7  # 2 legs x (P-1)
    p_pencil = operators.plan_spectral_op(
        SHAPE, mesh24, op=operators.poisson(), dtype=CDT)
    assert _collectives_of(p_pencil.fn, p_pencil.in_shape,
                           p_pencil.in_dtype) == 4  # t2a/t2b out + back


def test_batched_op_collective_count_matches_unbatched():
    """One SHARED exchange per leg regardless of B — the batched
    spectral-operator fusion contract."""
    mesh = dfft.make_mesh(8)
    p1 = operators.plan_spectral_op(SHAPE, mesh, op=operators.poisson(),
                                    dtype=CDT)
    pb = operators.plan_spectral_op(SHAPE, mesh, op=operators.poisson(),
                                    dtype=CDT, batch=B)
    assert (_collectives_of(pb.fn, pb.in_shape, pb.in_dtype)
            == _collectives_of(p1.fn, p1.in_shape, p1.in_dtype))


# ------------------------------------------------- model/explain join

def test_model_and_explain_carry_t_mid():
    from distributedfft_tpu.explain import (
        format_explain, model_stage_estimates,
    )

    mesh = dfft.make_mesh(8)
    plan = dfft.solve_poisson(SHAPE, mesh, dtype=CDT)
    model = model_stage_estimates(plan)
    assert set(model) == {"t0", "t1", "t2", "t_mid", "t3"}
    assert model["t_mid"]["seconds"] > 0
    assert model["t2"]["wire_bytes"] > 0

    rec = dfft.explain(plan, iters=2)
    assert rec["plan"]["op"] == "poisson"
    assert rec["plan"]["kind"] == "op_poisson"
    st = rec["stages"]
    assert "t_mid" in st
    # The staged op pipeline measures t_mid next to t0/t2/t3.
    assert rec["staged_available"]
    assert st["t_mid"]["measured"]["available"]
    assert st["t2"]["measured"]["available"]
    txt = format_explain(rec)
    assert "t_mid" in txt and "poisson" in txt


def test_staged_op_pipeline_matches_fused():
    from distributedfft_tpu.parallel.staged import build_slab_op_stages

    mesh = dfft.make_mesh(8)
    plan = dfft.solve_poisson(SHAPE, mesh, dtype=CDT)
    stages, _ = build_slab_op_stages(
        mesh, SHAPE, plan.multiplier, axis_name=mesh.axis_names[0])
    names = [n for n, _ in stages]
    assert names == ["t0_fft_yz", "t2_exchange_out", "t_mid",
                     "t2_exchange_back", "t3_ifft_yz"]
    x = _world()
    cur = jnp.asarray(x)
    for _, fn in stages:
        cur = fn(cur)
    assert np.max(np.abs(np.asarray(cur) - np.asarray(plan(x)))) < 1e-12


def test_exchange_byte_counters_cover_both_legs():
    """One fused solve moves exactly twice a transform's t2 bytes."""
    from distributedfft_tpu.api import _plan_exchange_bytes

    mesh = dfft.make_mesh(8)
    plan = dfft.solve_poisson(SHAPE, mesh, dtype=CDT)
    fwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    op_true, op_wire = _plan_exchange_bytes(plan)
    tr_true, tr_wire = _plan_exchange_bytes(fwd)
    assert op_true == 2 * tr_true and op_wire == 2 * tr_wire
    assert plan.logic.num_exchanges == 2 * fwd.logic.num_exchanges


def test_op_plan_metadata_and_cache():
    mesh = dfft.make_mesh(8)
    plan = dfft.solve_poisson(SHAPE, mesh, dtype=CDT)
    assert isinstance(plan, dfft.OpPlan3D)
    assert plan.op == "poisson" and plan.op_spec == operators.poisson()
    assert plan.in_sharding == plan.out_sharding
    assert plan.in_shape == plan.out_shape == SHAPE
    # Memoized: the same (shape, mesh, op, knobs) tuple is one plan.
    assert dfft.solve_poisson(SHAPE, mesh, dtype=CDT) is plan
    assert dfft.plan_spectral_op(SHAPE, mesh, op=operators.poisson(),
                                 dtype=CDT) is plan
    info = dfft.plan_info(plan)
    assert "operator: fused poisson" in info
    with pytest.raises(TypeError):
        operators.plan_spectral_op(SHAPE, mesh, op="poisson")
    with pytest.raises(ValueError):
        operators.gradient(3)
    with pytest.raises(ValueError):
        operators.named_op("bogus")
    with pytest.raises(ValueError):
        operators.gaussian(0.0)


# ------------------------------------------------------- wisdom kind

def test_op_wisdom_kind_never_cross_replays(tmp_path, monkeypatch):
    """Operator tournaments record under kind "op:<name>": a transform
    planner's wisdom lookup misses them (and vice versa), and the
    stored op winner replays with zero timing executions."""
    from distributedfft_tpu import tuner
    from distributedfft_tpu.utils.metrics import (
        metrics_reset, metrics_snapshot,
    )

    wisdom = tmp_path / "wisdom.jsonl"
    monkeypatch.setenv("DFFT_WISDOM", str(wisdom))
    monkeypatch.setenv("DFFT_TUNE_MAX", "2")
    monkeypatch.setenv("DFFT_TUNE_ITERS", "1x1")
    mesh = dfft.make_mesh(8)
    shape = (8, 8, 8)
    won = operators.plan_spectral_op(
        shape, mesh, op=operators.poisson(), dtype=CDT, tune="measure")
    entries = [json.loads(ln) for ln in wisdom.read_text().splitlines()]
    assert [e["key"]["kind"] for e in entries] == ["op:poisson"]

    # The c2c transform key misses the op entry entirely.
    key = tuner.wisdom_key(kind="c2c", shape=shape, dtype=CDT,
                           direction=dfft.FORWARD, ndev=8,
                           mesh_dims=(8,))
    assert tuner.lookup_wisdom(key, str(wisdom)) is None
    # ... and a tune="wisdom" transform plan falls back to heuristics.
    tplan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, tune="wisdom")
    assert tplan.decomposition == "slab"

    # Replay: the op winner rebuilds with ZERO timing executions.
    dfft.enable_metrics()
    metrics_reset()
    dfft.clear_plan_cache()
    replay = operators.plan_spectral_op(
        shape, mesh, op=operators.poisson(), dtype=CDT, tune="wisdom")
    counters = metrics_snapshot()["counters"]
    assert "tune_timing_executions" not in counters
    assert (replay.decomposition, replay.executor,
            replay.options.algorithm) == (
        won.decomposition, won.executor, won.options.algorithm)


# ----------------------------------------------------- dd r2c batch

def _dd_real_pair(seed=3, batch=None):
    rng = np.random.default_rng(seed)
    full = SHAPE if batch is None else (batch,) + SHAPE
    hi = jnp.asarray(rng.standard_normal(full).astype(np.float32))
    lo = jnp.asarray((rng.standard_normal(full) * 2.0 ** -25
                      ).astype(np.float32))
    return hi, lo


@pytest.mark.parametrize("mesh_shape", [None, 8, (2, 4)])
def test_dd_r2c_batch_parity_bitwise(mesh_shape):
    """Both dd components carry the batch axis; the dd engine is
    line-independent, so batch=B is bit-identical to B sequential
    executes — single-device, slab, and pencil tiers (the PR 6 dd r2c
    scope gap)."""
    mesh = None if mesh_shape is None else dfft.make_mesh(mesh_shape)
    pb = dfft.plan_dd_dft_r2c_3d(SHAPE, mesh, batch=B)
    p1 = dfft.plan_dd_dft_r2c_3d(SHAPE, mesh)
    assert pb.batch == B and p1.batch is None
    hi, lo = _dd_real_pair(batch=B)
    bh, bl = pb(hi, lo)
    assert bh.shape == (B, 16, 16, 9)
    for i in range(B):
        sh, sl = p1(hi[i], lo[i])
        assert np.array_equal(np.asarray(bh[i]), np.asarray(sh))
        assert np.array_equal(np.asarray(bl[i]), np.asarray(sl))


def test_dd_c2r_batch_parity_bitwise():
    mesh = dfft.make_mesh(8)
    r2c = dfft.plan_dd_dft_r2c_3d(SHAPE, mesh)
    hi, lo = _dd_real_pair(batch=B)
    spec = [r2c(hi[i], lo[i]) for i in range(B)]
    chi = jnp.stack([s[0] for s in spec])
    clo = jnp.stack([s[1] for s in spec])
    cb = dfft.plan_dd_dft_c2r_3d(SHAPE, mesh, batch=B)
    c1 = dfft.plan_dd_dft_c2r_3d(SHAPE, mesh)
    rh, rl = cb(chi, clo)
    for i in range(B):
        sh, sl = c1(chi[i], clo[i])
        assert np.array_equal(np.asarray(rh[i]), np.asarray(sh))
        assert np.array_equal(np.asarray(rl[i]), np.asarray(sl))


@pytest.mark.parametrize("mesh_shape", [None, 8, (2, 4)])
def test_dd_r2c_batch1_hlo_byte_identical(mesh_shape):
    mesh = None if mesh_shape is None else dfft.make_mesh(mesh_shape)
    base = dfft.plan_dd_dft_r2c_3d(SHAPE, mesh)
    b1 = dfft.plan_dd_dft_r2c_3d(SHAPE, mesh, batch=1)
    assert b1.batch is None
    args = (jax.ShapeDtypeStruct(SHAPE, jnp.float32),
            jax.ShapeDtypeStruct(SHAPE, jnp.float32))
    assert base.fn.lower(*args).as_text() == b1.fn.lower(*args).as_text()


def test_dd_r2c_batch_rejects_transposed_axis():
    with pytest.raises(ValueError, match="canonical r2c_axis=2"):
        dfft.plan_dd_dft_r2c_3d(SHAPE, None, r2c_axis=0, batch=B)


# ------------------------------------------------------ driver stamps

def test_bench_emit_stamps_op_and_solves_per_s(capsys):
    """The operator result line: spectral_* metric, op + solves_per_s
    stamped (own baseline group), transforms_per_s absent."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import bench

    line = bench._emit(16, 1e-3, 1e-8, "xla", 8, "slab",
                       {"xla+oppoisson": 1e-3}, op="poisson", batch=4)
    capsys.readouterr()
    assert line["metric"] == "spectral_poisson_16_gflops"
    assert line["op"] == "poisson"
    assert line["solves_per_s"] == pytest.approx(4000.0)
    assert "transforms_per_s" not in line
    assert line["batch"] == 4
    plain = bench._emit(16, 1e-3, 1e-8, "xla", 8, "slab", {"xla": 1e-3})
    capsys.readouterr()
    assert "op" not in plain and "solves_per_s" not in plain
    assert plain["transforms_per_s"] == pytest.approx(1000.0)


def test_speed3d_algorithm_label_stamps_op():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    from speed3d import _algorithm_label

    assert _algorithm_label("alltoall", 1, op="poisson") == \
        "alltoall+oppoisson"
    assert _algorithm_label("alltoall", 2, batch=4, op="gauss") == \
        "alltoall+ov2+b4+opgauss"
    assert _algorithm_label("alltoall", 1) == "alltoall"


# ------------------------- higher-order operators & chaining (PR 14)

def test_biharmonic_parity_with_composed_poisson():
    """biharmonic() is multiplier-identical to two composed Poisson
    solves (1/|k|^4 == (-1/|k|^2)^2, zero mode nulled) — the ROADMAP's
    "trivial multiplier add" parity pin, at the multiplier level AND
    through the fused chain."""
    m_bi = np.asarray(operators.multiplier_grid(
        operators.biharmonic(), SHAPE, CDT))
    m_po = np.asarray(operators.multiplier_grid(
        operators.poisson(), SHAPE, CDT))
    np.testing.assert_allclose(m_bi, m_po * m_po, rtol=1e-13, atol=0)
    mesh = dfft.make_mesh(8)
    x = _world(seed=31)
    plan = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.biharmonic(), dtype=CDT)
    solve_p = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.poisson(), dtype=CDT)
    ref = np.asarray(solve_p(np.asarray(solve_p(x))))
    assert _relerr(plan(x), ref) < TOL


def test_helmholtz_identity_and_zero_shift_parity():
    """(shift + |k|^2) * helmholtz multiplier == 1 (the solve inverts
    the screened operator exactly, every mode); shift == 0 degenerates
    to the NEGATIVE Poisson solve (mean-free convention)."""
    shift = 2.5
    m_h = np.asarray(operators.multiplier_grid(
        operators.helmholtz(shift), SHAPE, CDT))
    i0, i1, i2 = np.meshgrid(*(np.arange(n) for n in SHAPE),
                             indexing="ij")

    def k_of(i, n):
        f = np.where(i < (n + 1) // 2, i, i - n).astype(float)
        return 2.0 * np.pi * f

    ksq = sum(k_of(i, n) ** 2
              for i, n in zip((i0, i1, i2), SHAPE))
    np.testing.assert_allclose(m_h * (shift + ksq),
                               np.ones(SHAPE), rtol=1e-12)
    m_h0 = np.asarray(operators.multiplier_grid(
        operators.helmholtz(0.0), SHAPE, CDT))
    m_po = np.asarray(operators.multiplier_grid(
        operators.poisson(), SHAPE, CDT))
    np.testing.assert_allclose(m_h0, -m_po, rtol=1e-13, atol=0)
    with pytest.raises(ValueError, match="shift"):
        operators.helmholtz(-1.0)
    # Operator-level inversion: (shift - laplacian) applied spectrally
    # to the fused solve's output recovers f.
    mesh = dfft.make_mesh(8)
    f = _world(seed=33)
    u = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.helmholtz(shift), dtype=CDT)(f)
    fwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    bwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, direction=dfft.BACKWARD,
                               dtype=CDT)
    back = np.asarray(bwd((shift + ksq) * np.asarray(fwd(u))))
    assert _relerr(back, np.asarray(f)) < 1e-10


def test_chain_composes_multipliers_at_one_t_mid():
    """plan_spectral_op(op=[op1, op2]) == applying the ops in sequence,
    while compiling EXACTLY the collective count of a single-op fused
    plan — one forward, one multiplied t_mid, one inverse per SET."""
    mesh = dfft.make_mesh(8)
    ops = [operators.gaussian(0.4), operators.gradient(1)]
    chained = operators.plan_spectral_op(SHAPE, mesh, op=ops, dtype=CDT)
    single = operators.plan_spectral_op(
        SHAPE, mesh, op=operators.poisson(), dtype=CDT)
    assert (_collectives_of(chained.fn, chained.in_shape,
                            chained.in_dtype)
            == _collectives_of(single.fn, single.in_shape,
                               single.in_dtype))
    x = _world(seed=35)
    g = operators.plan_spectral_op(SHAPE, mesh,
                                   op=operators.gaussian(0.4), dtype=CDT)
    d = operators.plan_spectral_op(SHAPE, mesh,
                                   op=operators.gradient(1), dtype=CDT)
    ref = np.asarray(d(np.asarray(g(x))))
    assert _relerr(chained(x), ref) < TOL
    # Identity & cache metadata: a chain is its own op label/kind.
    assert chained.op == "chain(gaussian+gradient1)"
    c1 = operators.chain(ops)
    assert c1 == operators.chain(
        [operators.gaussian(0.4), operators.gradient(1)])
    assert c1 != operators.chain(
        [operators.gradient(1), operators.gaussian(0.4)])
    assert operators.chain([operators.poisson()]) == operators.poisson()
    with pytest.raises(ValueError, match="at least one"):
        operators.chain([])
    with pytest.raises(TypeError, match="SpectralOp"):
        operators.chain([operators.poisson(), "nope"])


def test_named_op_higher_order_menu():
    assert operators.named_op("biharm") == operators.biharmonic()
    assert (operators.named_op("helmholtz", shift=3.0)
            == operators.helmholtz(3.0))
    assert "biharm" in operators.OP_NAMES
    assert "helmholtz" in operators.OP_NAMES
