"""Fault-tolerance tests: injection matrix, retry, isolation, degraded.

Named ``test_a2i_*`` to collect BEFORE ``test_alltoallv.py`` (the
XLA:CPU fft-thunk poisoning rule of PRs 3-5 — the collection-order
guard in ``test_explain.py`` pins the name): the exchange-point tests
below run 8-device plans and need a clean backend.

The acceptance matrix (ISSUE 11): an injected fault at each injection
point (plan, compile, execute, exchange) x {transient, deterministic}
is respectively retried-to-success or degraded onto the matmul-DFT
fallback, with zero wrong numerical results ever returned to a Handle —
and a batched flush with exactly one poisoned request fails only that
request's handle while its cohort completes bit-correct.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import faults
from distributedfft_tpu.utils import metrics as m

SHAPE = (8, 8, 8)
CDT = jnp.complex128


def _world(seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(SHAPE) + 1j * rng.standard_normal(SHAPE)


def _counter(snap, name: str, **labels) -> float:
    rows = snap["counters"].get(name, {})
    want = [f"{k}={v}" for k, v in labels.items()]
    return sum(v for lbl, v in rows.items()
               if all(w in lbl for w in want))


@pytest.fixture
def metrics_on():
    m.enable_metrics()
    m.metrics_reset()
    try:
        yield
    finally:
        m.metrics_reset()
        m.enable_metrics(False)


# ------------------------------------------------------------- spec grammar

def test_fault_spec_grammar_parses_every_directive():
    pts = faults.parse_spec(
        "execute:every=3; plan:once; exchange:seed=7,p=0.25;"
        "compile:at=1+3,kind=deterministic,times=2,match=xla")
    assert [p.point for p in pts] == ["execute", "plan", "exchange",
                                      "compile"]
    assert pts[0].mode == "every" and pts[0].n == 3
    assert pts[1].mode == "once" and pts[1].times == 1
    assert pts[2].mode == "p" and pts[2].p == 0.25
    assert pts[3].at == frozenset({1, 3})
    assert pts[3].kind == "deterministic"
    assert pts[3].match == "xla"


def test_fault_spec_grammar_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.parse_spec("warp:once")
    with pytest.raises(ValueError, match="lacks a ':'"):
        faults.parse_spec("execute")
    with pytest.raises(ValueError, match="exactly one of"):
        faults.parse_spec("execute:once,every=2")
    with pytest.raises(ValueError, match="exactly one of"):
        faults.parse_spec("execute:kind=transient")  # no firing mode
    with pytest.raises(ValueError, match="unknown directive"):
        faults.parse_spec("execute:frobnicate=1")
    with pytest.raises(ValueError, match="transient|deterministic"):
        faults.parse_spec("execute:once,kind=sometimes")


def test_fault_seeded_probability_is_reproducible():
    a = faults.parse_spec("execute:seed=7,p=0.5")[0]
    b = faults.parse_spec("execute:seed=7,p=0.5")[0]
    fires_a = [a.should_fire("") for _ in range(64)]
    fires_b = [b.should_fire("") for _ in range(64)]
    assert fires_a == fires_b       # seeded: identical sequences
    assert any(fires_a) and not all(fires_a)


def test_programmatic_injected_scopes_and_clears():
    with faults.injected("execute", every=1, kind="deterministic"):
        with pytest.raises(dfft.InjectedFault) as ei:
            faults.check("execute")
        assert not ei.value.transient and ei.value.point == "execute"
    faults.check("execute")  # disarmed on exit — no raise
    faults.inject("plan", once=True)
    faults.clear()
    faults.check("plan")     # clear() disarmed it


def test_classify_taxonomy():
    assert faults.classify(dfft.InjectedFault("execute", "transient", 1)) \
        == "transient"
    assert faults.classify(
        dfft.InjectedFault("plan", "deterministic", 1)) == "deterministic"
    assert faults.classify(TimeoutError()) == "transient"
    assert faults.classify(RuntimeError("RESOURCE_EXHAUSTED: oom")) \
        == "transient"
    assert faults.classify(ValueError("bad shape")) == "deterministic"


# ------------------------------------------------- the fault-sweep matrix

#: Single-device injection points; "exchange" (needs a mesh plan) is
#: exercised by the mesh tests below.
POINTS = ("plan", "compile", "execute")


@pytest.mark.parametrize("point", POINTS)
def test_transient_fault_is_retried_to_success(chaos, metrics_on, point):
    """Matrix row {point} x transient: one bounded-backoff retry
    recovers the flush; every handle resolves bit-correct against the
    reference plan, nothing degrades."""
    dfft.clear_plan_cache()
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=4, retry_max=2,
                             retry_backoff_s=0.001)
    xs = [_world(1), _world(2)]
    hs = [q.submit(jnp.asarray(v)) for v in xs]  # probe plan: pre-chaos
    chaos(f"{point}:once")
    assert q.flush() == 2
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    for v, h in zip(xs, hs):
        assert np.array_equal(np.asarray(h.result(timeout=60)),
                              np.asarray(ref(jnp.asarray(v))))
        assert not h.degraded
    snap = dfft.metrics_snapshot()
    assert _counter(snap, "fault_injected", point=point,
                    kind="transient") == 1
    assert _counter(snap, "serving_retries") == 1
    assert _counter(snap, "serving_isolated_failures") == 0


@pytest.mark.parametrize("point", POINTS)
def test_deterministic_fault_degrades_to_matmul(chaos, metrics_on,
                                                point, tmp_path,
                                                monkeypatch):
    """Matrix row {point} x deterministic: no retry (it would reproduce
    the fault) — the whole group rebuilds on the matmul-DFT fallback,
    bit-identical to a directly-built matmul plan, and the fallback is
    recorded under a degraded-annotated wisdom key."""
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "wisdom.jsonl"))
    dfft.clear_plan_cache()
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=4, retry_max=1,
                             retry_backoff_s=0.001)
    xs = [_world(3), _world(4)]
    hs = [q.submit(jnp.asarray(v)) for v in xs]
    chaos(f"{point}:once,kind=deterministic")
    assert q.flush() == 2
    mm = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, executor="matmul",
                              batch=2)
    want = mm(jnp.stack([jnp.asarray(v, CDT) for v in xs]))
    for i, h in enumerate(hs):
        assert np.array_equal(np.asarray(h.result(timeout=60)),
                              np.asarray(want[i]))
        assert h.degraded
    snap = dfft.metrics_snapshot()
    assert _counter(snap, "fault_injected", point=point,
                    kind="deterministic") == 1
    assert _counter(snap, "serving_retries") == 0  # deterministic: none
    assert _counter(snap, "serving_degraded", executor="matmul") == 2
    # The wisdom annotation: durable, inspectable, and never replayed.
    entries = [json.loads(ln)
               for ln in open(tmp_path / "wisdom.jsonl")]
    assert entries and all(
        e["key"]["annotation"] == "degraded"
        and e["winner"]["executor"] == "matmul" for e in entries)
    assert dfft.warm_pool(None, top_n=8,
                          path=str(tmp_path / "wisdom.jsonl")) == []


@pytest.mark.parametrize("kind", ["transient", "deterministic"])
def test_exchange_fault_matrix_on_mesh(chaos, metrics_on, kind,
                                       tmp_path, monkeypatch):
    """Matrix rows exchange x {transient, deterministic} on a real
    8-device mesh plan: transient retries to success on the same chain;
    deterministic degrades onto the distributed matmul chain. Either
    way the handle's numbers are bit-correct for the chain that
    produced them."""
    # The degraded branch annotates the wisdom store: point it at a tmp
    # file so tests never write the machine-global store.
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "w.jsonl"))
    mesh = dfft.make_mesh(8)
    dfft.clear_plan_cache()
    q = dfft.CoalescingQueue(mesh, dtype=CDT, max_batch=4, retry_max=2,
                             retry_backoff_s=0.001)
    x = _world(5)
    h = q.submit(jnp.asarray(x))
    chaos(f"exchange:once,kind={kind}")
    assert q.flush() == 1
    ex = "matmul" if kind == "deterministic" else "xla"
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, executor=ex)
    assert np.array_equal(np.asarray(h.result(timeout=120)),
                          np.asarray(ref(jnp.asarray(x, CDT))))
    assert h.degraded == (kind == "deterministic")
    snap = dfft.metrics_snapshot()
    assert _counter(snap, "fault_injected", point="exchange",
                    kind=kind) == 1


def test_every_n_fault_fires_on_schedule(chaos):
    """``every=3`` fires on checks 3, 6, ... — the count-based
    reproducibility contract of the spec grammar."""
    chaos("execute:every=3,kind=deterministic")
    fired = []
    for i in range(1, 7):
        try:
            faults.check("execute")
            fired.append(False)
        except dfft.InjectedFault:
            fired.append(True)
    assert fired == [False, False, True, False, False, True]


# --------------------------------------------------- batch isolation

def test_batched_flush_isolates_single_poisoned_request(chaos,
                                                        metrics_on):
    """THE isolation acceptance: a batched flush with exactly one
    poisoned request fails only that request's handle; both co-batched
    handles complete with bit-correct output. Fault schedule: execute
    check #1 is the batched attempt, #2..#4 the bisected singletons —
    ``at=1+3`` poisons the batch and the middle request only.
    Fallback disabled so the bisection path itself is under test."""
    dfft.clear_plan_cache()
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=3, retry_max=0,
                             fallback_executor="0")
    xs = [_world(s) for s in (6, 7, 8)]
    hs = []
    for i, v in enumerate(xs):
        if i == len(xs) - 1:
            chaos("execute:at=1+3,kind=deterministic")
        hs.append(q.submit(jnp.asarray(v)))  # 3rd submit auto-flushes
    assert q.pending() == 0
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    with pytest.raises(dfft.InjectedFault):
        hs[1].result(timeout=60)
    for i in (0, 2):
        assert np.array_equal(np.asarray(hs[i].result(timeout=60)),
                              np.asarray(ref(jnp.asarray(xs[i]))))
        assert not hs[i].degraded
    snap = dfft.metrics_snapshot()
    assert _counter(snap, "serving_isolated_failures") == 1
    # The flush itself never raised: the cohort's verdicts are all that
    # escaped (delivered per-handle).


def test_bisected_request_recovers_via_degraded_fallback(chaos,
                                                         metrics_on,
                                                         tmp_path,
                                                         monkeypatch):
    """The full recovery chain in one flush: batched attempt fails,
    the whole-group degraded rebuild fails too, bisection finds one
    healthy request (resolved on the configured executor) and one
    poisoned request whose own degraded fallback finally lands it —
    degraded — instead of failing. Fault schedule over execute checks:
    #1 batched xla, #2 batched matmul rebuild, #3 iso0 xla (passes),
    #4 iso1 xla; iso1's matmul rebuild (#5) passes."""
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "w.jsonl"))
    dfft.clear_plan_cache()
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=2, retry_max=0)
    xs = [_world(9), _world(10)]
    hs = [q.submit(jnp.asarray(xs[0]))]
    chaos("execute:at=1+2+4,kind=deterministic")
    hs.append(q.submit(jnp.asarray(xs[1])))  # auto-flush at max_batch
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    mm = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, executor="matmul")
    got0 = np.asarray(hs[0].result(timeout=60))
    got1 = np.asarray(hs[1].result(timeout=60))
    assert not hs[0].degraded and hs[1].degraded
    assert np.array_equal(got0, np.asarray(ref(jnp.asarray(xs[0], CDT))))
    assert np.array_equal(got1, np.asarray(mm(jnp.asarray(xs[1], CDT))))
    snap = dfft.metrics_snapshot()
    assert _counter(snap, "serving_degraded", executor="matmul") == 1
    assert _counter(snap, "serving_isolated_failures") == 0


# ------------------------------------------------- degraded-mode parity

def test_degraded_parity_bit_identical_to_direct_matmul(chaos,
                                                        tmp_path,
                                                        monkeypatch):
    """Degraded-mode parity (satellite): a request forced onto the
    matmul fallback produces output BIT-IDENTICAL to a directly-built
    matmul plan — the fallback plumbing adds no numerical difference —
    and agrees with the healthy reference executor to roundtrip
    tolerance."""
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "w.jsonl"))
    dfft.clear_plan_cache()
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=8, retry_max=0)
    x = _world(11)
    h = q.submit(jnp.asarray(x))
    chaos("execute:every=1,kind=deterministic,match=xla")
    q.flush()
    got = np.asarray(h.result(timeout=60))
    assert h.degraded
    mm = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, executor="matmul")
    assert np.array_equal(got, np.asarray(mm(jnp.asarray(x, CDT))))
    ref = np.fft.fftn(x)
    assert np.allclose(got, ref, rtol=1e-9, atol=1e-9)


def test_regress_never_groups_degraded_with_healthy_records():
    """Degraded run records form their own baseline group (satellite):
    the compare engine must never judge a matmul-fallback run against
    the fast baselines, or vice versa."""
    from distributedfft_tpu import regress

    line = {"metric": "fft3d_c2c_256_forward_gflops", "value": 100.0,
            "dtype": "complex64", "devices": 8, "backend": "cpu"}
    healthy = regress.normalize_bench_line(dict(line), source="t")
    degraded = regress.normalize_bench_line(dict(line, degraded=True),
                                            source="t")
    assert degraded["config"]["degraded"] is True
    assert "degraded" not in healthy["config"]  # old schema preserved
    assert regress.group_key(healthy) != regress.group_key(degraded)
    # A degraded record compared against a healthy-only history:
    # no-baseline, never a verdict against the fast group.
    hist = [dict(healthy, value=v) for v in (100.0, 101.0, 99.0)]
    res = regress.compare_record(degraded, hist)
    assert res["verdict"] == "no-baseline"


def test_bench_emit_stamps_degraded_into_result_lines(capsys):
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    out = bench._emit(16, 1e-4, 1e-7, "matmul", 1, "single",
                      {"matmul": 1e-4}, degraded=True)
    capsys.readouterr()
    assert out["degraded"] is True
    healthy = bench._emit(16, 1e-4, 1e-7, "xla", 1, "single",
                          {"xla": 1e-4})
    capsys.readouterr()
    assert "degraded" not in healthy  # default rows keep the old schema


# ------------------------------------------------------ default purity

def test_no_knobs_means_no_fault_tolerance_state(monkeypatch):
    """Defaults-unchanged acceptance: without DFFT_FAULT_*/DFFT_RETRY_*
    and no deadline_s, the queue runs the legacy dispatch (retry
    machinery off) and a flush failure fails every co-batched handle
    AND re-raises — byte-identical to the pre-robustness tier."""
    for var in ("DFFT_FAULT_INJECT", "DFFT_RETRY_MAX",
                "DFFT_RETRY_BACKOFF_S", "DFFT_FALLBACK_EXECUTOR"):
        monkeypatch.delenv(var, raising=False)
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=8)
    assert q._retry_max is None
    hs = [q.submit(jnp.asarray(_world(s))) for s in (12, 13)]
    with faults.injected("execute", every=1, kind="transient"):
        with pytest.raises(dfft.InjectedFault):
            q.flush()  # legacy contract: the flush itself raises
    for h in hs:
        with pytest.raises(dfft.InjectedFault):
            h.result(timeout=10)


def test_retry_knobs_resolve_from_env(monkeypatch):
    monkeypatch.setenv("DFFT_RETRY_MAX", "3")
    monkeypatch.setenv("DFFT_RETRY_BACKOFF_S", "0.25")
    monkeypatch.setenv("DFFT_FALLBACK_EXECUTOR", "none")
    q = dfft.CoalescingQueue(None, dtype=CDT)
    assert q._retry_max == 3
    assert q._retry_backoff == 0.25
    assert q._fallback_executor == ""
    monkeypatch.setenv("DFFT_RETRY_MAX", "nope")
    with pytest.raises(ValueError, match="DFFT_RETRY_MAX"):
        dfft.CoalescingQueue(None, dtype=CDT)
    with pytest.raises(ValueError, match="retry_max"):
        dfft.CoalescingQueue(None, dtype=CDT, retry_max=-1)
