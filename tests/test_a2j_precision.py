"""Mixed-precision MXU executor tiers (``ops/executors.py`` tier labels,
``PlanOptions.mm_precision``, the tuner's precision axis, and the
fft-thunk retirement path).

The accuracy tier of the matmul-family executors used to be a
process-global trace-time env read (``DFFT_MM_PRECISION``) — invisible
to the tuner and racy between a warm-pool preplan and a concurrent
tournament in one process. These tests pin the plan-scoped replacement:

1. **Tier labels are distinct executors** — ``matmul:bf16`` /
   ``matmul:f32`` / ``matmul:highest`` (and ``:gauss``) parse, compose
   idempotently, scope ``dft_matmul.mm_scope`` over their own trace,
   and two tiers coexist in one process (the global-knob race
   regression).
2. **Accuracy is a tuned dimension** — the candidate space crosses
   executors with tiers under a ``max_roundtrip_err`` budget, the
   measured tier error (``executor_roundtrip_error``) composes with the
   wire error into ONE budget, a stored reduced-precision winner never
   replays into a plan whose budget its recorded error violates, and an
   admissible replay pays zero timing executions.
3. **Thunk retirement** — with ``DFFT_THUNK_GUARD=matmul`` (armed by
   conftest for the whole suite) the known-poisoned chain class (CPU,
   uneven inverse pencil) plans through the matmul executor and
   executes correctly; everything outside the class keeps its executor.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` (the ``test_a2*`` clean-backend convention —
``conftest._check_poison_collection_order`` enforces it on every run).
This file itself triggers no fft-layout fault: its only uneven inverse
pencil executions run the matmul executor, which never touches the FFT
thunk.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import report, tuner
from distributedfft_tpu import testing as tu
from distributedfft_tpu.ops import dft_matmul, executors
from distributedfft_tpu.plan_logic import (
    PlanOptions,
    mm_dft_flops,
    model_stage_seconds,
)
from distributedfft_tpu.utils import metrics as m

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (8, 8, 8)
UNEVEN = (10, 9, 7)


@pytest.fixture
def wisdom_path(tmp_path, monkeypatch):
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "wisdom.jsonl"))
    monkeypatch.setenv("DFFT_COMPILE_CACHE", str(tmp_path / "xla_cache"))
    return str(tmp_path / "wisdom.jsonl")


@pytest.fixture
def fast_budget(monkeypatch):
    monkeypatch.setenv("DFFT_TUNE_ITERS", "1x1")


@pytest.fixture
def metrics_on():
    dfft.clear_plan_cache()
    m.metrics_reset()
    m.enable_metrics()
    yield
    m.enable_metrics(False)
    m.metrics_reset()
    dfft.clear_plan_cache()


# ------------------------------------------------------- label algebra

def test_split_executor_grammar():
    assert executors.split_executor("matmul") == ("matmul", None, None)
    assert executors.split_executor("matmul:bf16") == (
        "matmul", "bf16", None)
    assert executors.split_executor("matmul:bf16:gauss") == (
        "matmul", "bf16", "gauss")
    assert executors.split_executor("pallas:f32") == ("pallas", "f32", None)
    # The lax-name spellings of the bench menu grammar normalize.
    assert executors.split_executor("matmul:high") == ("matmul", "f32", None)
    assert executors.split_executor("matmul:default") == (
        "matmul", "bf16", None)
    with pytest.raises(ValueError, match="suffix"):
        executors.split_executor("matmul:fast")
    with pytest.raises(ValueError, match="two precision tiers"):
        executors.split_executor("matmul:bf16:f32")
    with pytest.raises(ValueError, match="matmul precision"):
        executors.split_executor("xla:bf16")


def test_tiered_name_composes_and_is_idempotent():
    assert executors.tiered_name("matmul", "bf16") == "matmul:bf16"
    assert executors.tiered_name("matmul:bf16") == "matmul:bf16"
    assert executors.tiered_name("matmul:bf16", "bf16") == "matmul:bf16"
    assert executors.tiered_name("matmul", "high") == "matmul:f32"
    assert executors.tiered_name("matmul", None, "gauss") == "matmul:gauss"
    assert executors.tiered_name("matmul", None, "native") == "matmul"
    assert executors.tiered_name("xla") == "xla"
    with pytest.raises(ValueError, match="already pins"):
        executors.tiered_name("matmul:bf16", "highest")
    with pytest.raises(ValueError, match="matmul precision"):
        executors.tiered_name("xla", "bf16")


def test_get_executor_accepts_tiered_labels():
    for name in ("matmul:bf16", "matmul:f32", "matmul:highest",
                 "matmul:gauss", "matmul:bf16:gauss"):
        fn = executors.get_executor(name)
        x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8))
                        + 1j * np.random.default_rng(1).standard_normal(
                            (4, 8))).astype(jnp.complex64)
        y = np.asarray(fn(fn(x, (1,), True), (1,), False))
        assert np.max(np.abs(y - np.asarray(x))) < 1e-3
    with pytest.raises(ValueError, match="unknown executor"):
        executors.get_executor("nope")
    with pytest.raises(ValueError, match="matmul precision"):
        executors.get_executor("xla:bf16")


def test_mm_scope_overrides_env(monkeypatch):
    from jax import lax

    monkeypatch.setenv("DFFT_MM_PRECISION", "highest")
    monkeypatch.setenv("DFFT_MM_COMPLEX", "native")
    assert dft_matmul.mm_precision() == lax.Precision.HIGHEST
    with dft_matmul.mm_scope(precision="default", complex_mode="gauss"):
        assert dft_matmul.mm_precision() == lax.Precision.DEFAULT
        assert dft_matmul.complex_mode() == "gauss"
        with dft_matmul.mm_scope(precision="high"):
            assert dft_matmul.mm_precision() == lax.Precision.HIGH
            assert dft_matmul.complex_mode() == "gauss"  # outer survives
        assert dft_matmul.mm_precision() == lax.Precision.DEFAULT
    # The env default is back in force after the scope exits.
    assert dft_matmul.mm_precision() == lax.Precision.HIGHEST
    assert dft_matmul.complex_mode() == "native"


# ---------------------------------------------- plan-scoped tier plans

def test_two_tiers_coexist_in_one_process():
    """The global-knob race regression: two precision tiers planned
    back-to-back in one process are DISTINCT plans (labels, options,
    cache entries) and both execute correctly — the env knob is a
    default, not shared state."""
    dfft.clear_plan_cache()
    hi = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                              mm_precision="highest", dtype=np.complex64)
    lo = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                              mm_precision="bf16", dtype=np.complex64)
    assert hi.executor == "matmul:highest" and lo.executor == "matmul:bf16"
    assert hi.options.mm_precision == "highest"
    assert lo.options.mm_precision == "bf16"
    assert hi is not lo and hi.fn is not lo.fn
    x = tu.make_world_data(SHAPE, dtype=np.complex64)
    want = np.fft.fftn(x)
    for plan in (hi, lo):
        got = np.asarray(plan(x))
        assert np.max(np.abs(got - want)) / np.abs(want).max() < 1e-3
    # Same call again hits the plan cache per tier (no cross-tier mixup).
    assert dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                                mm_precision="bf16",
                                dtype=np.complex64) is lo


def test_executor_label_spelling_backfills_options():
    plan = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul:high:gauss",
                                dtype=np.complex64)
    assert plan.options.mm_precision == "f32"
    assert plan.options.mm_complex == "gauss"
    assert plan.executor == "matmul:f32:gauss"  # canonical label


def test_tier_equals_env_default_hlo_pin(monkeypatch):
    """Byte-identical pin: an explicit tier compiles exactly the program
    the same tier reaches via the env default — the scope changes WHERE
    the knob is read, never what is traced. (And mm_precision=None with
    no env knobs is the bare executor unchanged.)"""
    dfft.clear_plan_cache()
    monkeypatch.delenv("DFFT_MM_PRECISION", raising=False)
    x = jnp.zeros(SHAPE, jnp.complex64)
    scoped = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                                  mm_precision="bf16", dtype=np.complex64)
    monkeypatch.setenv("DFFT_MM_PRECISION", "default")
    dfft.clear_plan_cache()
    env = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                               dtype=np.complex64)
    assert env.executor == "matmul"  # env default: bare label, old path
    a = jax.jit(scoped.fn).lower(x).as_text()
    b = jax.jit(env.fn).lower(x).as_text()
    assert a == b
    # The exact tier == the unset-env default program, byte for byte.
    monkeypatch.delenv("DFFT_MM_PRECISION", raising=False)
    dfft.clear_plan_cache()
    bare = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                                dtype=np.complex64)
    exact = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul",
                                 mm_precision="highest",
                                 dtype=np.complex64)
    assert (jax.jit(bare.fn).lower(x).as_text()
            == jax.jit(exact.fn).lower(x).as_text())


@needs_mesh
@pytest.mark.parametrize("tier", ["bf16", "f32"])
@pytest.mark.parametrize("shape,mesh_dims,batch", [
    (SHAPE, None, None),          # slab (1D from int), even
    (UNEVEN, (2, 4), None),       # pencil, uneven
    (SHAPE, None, 3),             # slab, batched
])
def test_c64_roundtrip_bounds_per_tier(tier, shape, mesh_dims, batch):
    """c64 forward->inverse round trip stays within the tier's measured
    error envelope across slab/pencil x uneven x batch — the bound the
    budget admission is declared against."""
    mesh = dfft.make_mesh(mesh_dims) if mesh_dims else dfft.make_mesh(8)
    kw = dict(dtype=np.complex64, executor=f"matmul:{tier}")
    if batch:
        kw["batch"] = batch
    fwd = dfft.plan_dft_c2c_3d(shape, mesh, **kw)
    bwd = dfft.plan_dft_c2c_3d(shape, mesh, direction=dfft.BACKWARD, **kw)
    full = shape if not batch else (batch,) + tuple(shape)
    x = tu.make_world_data(full, dtype=np.complex64)
    r = np.asarray(bwd(fwd(x)))
    err = np.max(np.abs(r - np.asarray(x))) / np.abs(np.asarray(x)).max()
    # Generous per-tier envelope: honest on TPU (bf16 ~1e-2) and tiny on
    # the CPU backend (lax precision collapses to native kernels there).
    bound = 2e-2 if tier == "bf16" else 1e-3
    assert err < bound, (tier, shape, batch, err)


# ------------------------------------------------ measured tier errors

def test_executor_roundtrip_error_conventions():
    assert executors.executor_roundtrip_error("xla", np.complex64) == 0.0
    assert executors.executor_roundtrip_error("matmul", np.complex64) == 0.0
    assert executors.executor_roundtrip_error(
        "matmul:highest", np.complex64) == 0.0  # the exact tier
    assert executors.executor_roundtrip_error(
        "matmul:gauss", np.complex64) == 0.0
    e1 = executors.executor_roundtrip_error("matmul:bf16", np.complex64)
    assert e1 >= 0.0
    # Cached: the second call returns the identical float (no re-measure).
    assert executors.executor_roundtrip_error(
        "matmul:bf16", np.complex64) == e1


def test_candidate_roundtrip_error_sums_axes():
    from distributedfft_tpu.parallel.exchange import wire_roundtrip_error

    wire = wire_roundtrip_error(np.complex64, "bf16")
    tier = executors.executor_roundtrip_error("matmul:bf16", np.complex64)
    c = tuner.Candidate("slab", "alltoall", "matmul:bf16", 1, "bf16")
    assert tuner.candidate_roundtrip_error(c, np.complex64) == pytest.approx(
        wire + tier)
    exact = tuner.Candidate("slab", "alltoall", "xla", 1, None)
    assert tuner.candidate_roundtrip_error(exact, np.complex64) == 0.0


def test_enumerate_crosses_tiers_and_prune_filters():
    cands = tuner.enumerate_candidates(
        (16, 16, 16), 8, executors=["xla", "matmul"],
        mm_tiers=(None, "bf16", "f32"))
    assert {c.executor for c in cands} == {
        "xla", "matmul", "matmul:bf16", "matmul:f32"}
    # An impossible budget strips every reduced-accuracy candidate ...
    tight = tuner.prune_candidates(cands, (16, 16, 16), 8, limit=64,
                                   max_err=1e-30, dtype=np.complex64)
    assert tight
    assert all(c.wire_dtype is None and ":" not in c.executor
               for c in tight)
    # ... while a loose one keeps the tier axis in play.
    loose = tuner.prune_candidates(cands, (16, 16, 16), 8, limit=64,
                                   max_err=1e-1, dtype=np.complex64)
    assert any(":bf16" in c.executor for c in loose)


def test_model_cost_ranks_tiers_before_any_compile(monkeypatch):
    """At a compute-bound shape the bf16 tier's modeled cost undercuts
    f32 undercuts exact — precision is rankable pre-compile."""
    monkeypatch.setenv("DFFT_HW_PROFILE", "0")
    shape = (512, 512, 512)

    def cost(ex):
        return tuner.model_cost(
            tuner.Candidate("slab", "alltoall", ex, 1), shape, 8)

    assert cost("matmul:bf16") < cost("matmul:f32") <= cost("matmul")
    # Non-matmul executors are untouched by the tier term.
    assert cost("xla") <= cost("matmul")


def test_mm_tier_tflops_profile_override(tmp_path, monkeypatch):
    from distributedfft_tpu import calibrate

    assert tuner.mm_tier_tflops("xla") is None
    assert tuner.mm_tier_tflops("matmul") == tuner.MODEL_MM_TFLOPS[
        "highest"]
    assert tuner.mm_tier_tflops("matmul:bf16") == tuner.MODEL_MM_TFLOPS[
        "bf16"]
    path = str(tmp_path / "hw.json")
    monkeypatch.setenv("DFFT_HW_PROFILE", path)
    kind, platform = calibrate._current_identity()
    calibrate.write_profile({
        "schema": calibrate.PROFILE_SCHEMA, "device_kind": kind,
        "platform": platform, "hbm_gbps": 100.0,
        "mm_bf16_tflops": 40.0, "mm_f32_tflops": 10.0}, path)
    assert tuner.mm_tier_tflops("matmul:bf16") == 40.0
    assert tuner.mm_tier_tflops("matmul:f32") == 10.0
    assert tuner.mm_tier_tflops("matmul") == 5.0        # derived: f32/2
    assert tuner.mm_tier_tflops("matmul:highest") == 5.0


def test_calibrate_measures_mm_tier_fields(monkeypatch):
    from distributedfft_tpu import calibrate

    prof = calibrate.calibrate(iters=1, wire=False)
    assert prof["mm_bf16_tflops"] is None or prof["mm_bf16_tflops"] > 0
    assert prof["mm_f32_tflops"] is None or prof["mm_f32_tflops"] > 0
    text = calibrate.format_profile(prof)
    assert "matmul bf16" in text and "matmul f32" in text


def test_model_stage_seconds_mm_pricing():
    from distributedfft_tpu.plan_logic import logic_plan3d

    lp = logic_plan3d((64, 64, 64), None, PlanOptions(tune="off"))
    base = model_stage_seconds(lp, (64, 64, 64), 8, hbm_gbps=819.0,
                               wire_gbps=45.0, launch_seconds=1e-4)
    slow = model_stage_seconds(lp, (64, 64, 64), 8, hbm_gbps=819.0,
                               wire_gbps=45.0, launch_seconds=1e-4,
                               mm_tflops=0.001)  # absurdly slow tier
    assert "mm_flops" not in base["t0"]
    assert slow["t0"]["mm_flops"] > 0
    assert slow["t0"]["seconds"] > base["t0"]["seconds"]
    # A fast tier floors at the HBM stream — never faster than memory.
    fast = model_stage_seconds(lp, (64, 64, 64), 8, hbm_gbps=819.0,
                               wire_gbps=45.0, launch_seconds=1e-4,
                               mm_tflops=1e9)
    assert fast["t0"]["seconds"] == base["t0"]["seconds"]
    assert mm_dft_flops((4, 4, 4)) == 3 * 8.0 * 64 * 4
    assert mm_dft_flops((4, 4, 4), (2,)) == 8.0 * 64 * 4


# ------------------------------------------- budget admission (wisdom)

def _seed_entry(path, key, executor, wire_dtype=None, precision_err=None,
                compression_err=None):
    entry = {
        "schema": tuner.WISDOM_SCHEMA, "key": key,
        "winner": {"decomposition": "slab", "algorithm": "alltoall",
                   "executor": executor, "overlap_chunks": 1,
                   "wire_dtype": wire_dtype},
        "seconds": 1e-3,
    }
    if precision_err is not None:
        entry["precision_err"] = precision_err
    if compression_err is not None:
        entry["compression_err"] = compression_err
    with open(path, "a") as f:
        f.write(json.dumps(entry) + "\n")


@needs_mesh
def test_tier_winner_never_replays_into_tighter_budget(
        wisdom_path, fast_budget, metrics_on):
    """Property sweep: a stored bf16-tier winner replays tiered only
    into plans whose budget admits its recorded error; a tighter budget
    rebuilds the exact bare tuple — with zero timing executions either
    way (the lookup is a hit in both cases)."""
    rec_err = 1e-3
    for budget, admitted in ((5e-4, False), (1e-3, True), (1e-2, True),
                             (9.9e-4, False)):
        key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                               direction=-1, ndev=8, err_budget=budget)
        _seed_entry(wisdom_path, key, "matmul:bf16",
                    precision_err=rec_err)
        dfft.clear_plan_cache()
        m.metrics_reset()
        plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                    tune="measure",
                                    max_roundtrip_err=budget)
        assert m.counter_total("tune_timing_executions") == 0, budget
        if admitted:
            assert plan.executor == "matmul:bf16", (budget, plan.executor)
        else:
            assert plan.executor == "matmul", (budget, plan.executor)


@needs_mesh
def test_combined_wire_and_tier_errors_share_one_budget(
        wisdom_path, fast_budget, metrics_on):
    """Each axis alone fits the budget; the sum does not — the stored
    compressed+tiered winner must rebuild fully exact (bare label AND
    exact wire)."""
    budget = 1e-2
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=-1, ndev=8, err_budget=budget)
    _seed_entry(wisdom_path, key, "matmul:bf16", wire_dtype="bf16",
                precision_err=6e-3, compression_err=6e-3)
    dfft.clear_plan_cache()
    plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                tune="measure", max_roundtrip_err=budget)
    assert plan.executor == "matmul"
    assert plan.options.wire_dtype is None
    assert m.counter_total("tune_timing_executions") == 0
    # And when the sum fits, both axes replay.
    key2 = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                            direction=1, ndev=8, err_budget=budget)
    _seed_entry(wisdom_path, key2, "matmul:bf16", wire_dtype="bf16",
                precision_err=4e-3, compression_err=4e-3)
    dfft.clear_plan_cache()
    plan2 = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                 direction=dfft.BACKWARD, tune="measure",
                                 max_roundtrip_err=budget)
    assert plan2.executor == "matmul:bf16"
    assert plan2.options.wire_dtype == "bf16"


@needs_mesh
def test_budgetless_plan_never_replays_reduced_tier(
        wisdom_path, fast_budget, metrics_on):
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=-1, ndev=8)
    _seed_entry(wisdom_path, key, "matmul:bf16", precision_err=1e-7)
    dfft.clear_plan_cache()
    plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                tune="measure")
    assert plan.executor == "matmul"  # exact rebuild, tier stripped


@needs_mesh
def test_measure_tournament_precision_axis_end_to_end(
        wisdom_path, fast_budget, metrics_on, monkeypatch):
    """Acceptance: a measure tournament over the joint
    (precision x wire x transport) space under a budget selects a
    winner, records its tier/errors, and an identically-keyed call
    replays it with ZERO timing executions."""
    monkeypatch.setenv("DFFT_TUNE_MAX", "12")
    monkeypatch.setenv("DFFT_AUTO_EXECUTORS", "xla,matmul")
    plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                tune="measure", max_roundtrip_err=1e-2)
    assert m.counter_total("tune_tournaments") == 1
    entries, dropped = tuner.load_wisdom(wisdom_path)
    assert dropped == 0 and len(entries) == 1
    entry = list(entries.values())[0]
    timed = set(entry["times"])
    # The measured space really crossed precision with wire/transport.
    assert any(":bf16" in t for t in timed), timed
    assert any("+wbf16" in t for t in timed), timed
    assert any(t.split("/")[1] != "alltoall" for t in timed), timed
    lbl = tuner.tuned_label(plan)
    assert lbl in timed
    dfft.clear_plan_cache()
    m.metrics_reset()
    plan2 = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                 tune="measure", max_roundtrip_err=1e-2)
    assert m.counter_total("tune_timing_executions") == 0
    assert m.counter_total("tune_tournaments") == 0
    assert tuner.tuned_label(plan2) == lbl
    x = tu.make_world_data(SHAPE, dtype=np.complex64)
    got = np.asarray(plan2(x))
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 1e-2


@needs_mesh
def test_explicit_tier_pin_isolated_in_wisdom(wisdom_path, fast_budget,
                                              metrics_on):
    k_open = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                              direction=-1, ndev=8)
    k_pin = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                             direction=-1, ndev=8, mm_precision="bf16")
    assert tuner._key_id(k_open) != tuner._key_id(k_pin)
    plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=np.complex64,
                                tune="measure", executor="matmul",
                                mm_precision="bf16")
    entries, _ = tuner.load_wisdom(wisdom_path)
    entry = list(entries.values())[0]
    assert entry["key"]["mm_precision"] == "bf16"
    # Every matmul-family candidate carried the pinned tier; no bare
    # matmul label entered the pinned tournament.
    assert all(":bf16" in t for t in entry["times"]
               if t.split("/")[2].startswith("matmul")), entry["times"]


# ----------------------------------------------- labels, stamps, gates

def test_winner_label_agreement_for_precision_tuples():
    c = tuner.Candidate("slab", "alltoall", "matmul:bf16", 2, "bf16")
    w = {"decomposition": "slab", "algorithm": "alltoall",
         "executor": "matmul:bf16", "overlap_chunks": 2,
         "wire_dtype": "bf16"}
    assert report._winner_label(w) == c.label
    # Out-of-band tier field (older/foreign entries) folds into the
    # executor term instead of silently never matching history rows.
    w2 = {"decomposition": "slab", "algorithm": "alltoall",
          "executor": "matmul", "overlap_chunks": 2,
          "wire_dtype": None, "mm_precision": "bf16"}
    assert report._winner_label(w2) == "slab/alltoall/matmul:bf16/ov2"


def test_regress_keys_precision_into_baseline_group():
    from distributedfft_tpu import regress

    base = {"metric": "fft3d_c2c_64_forward_gflops", "value": 10.0,
            "unit": "GFlops/s", "seconds": 0.1, "dtype": "complex64",
            "backend": "cpu", "devices": 8, "decomposition": "slab",
            "executor": "matmul"}
    exact = regress.normalize_bench_line(dict(base), source="t")
    tiered = regress.normalize_bench_line(dict(base, precision="bf16"),
                                          source="t")
    assert "precision" not in exact["config"]
    assert tiered["config"]["precision"] == "bf16"
    assert "precision=bf16" in regress.config_signature(tiered)
    assert regress.group_key(exact) != regress.group_key(tiered)


def test_bench_stamps_precision(tmp_path, monkeypatch):
    import bench

    class P:  # minimal plan stand-in
        class options:
            wire_dtype = None
            algorithm = "alltoall"
            mm_precision = "bf16"

    kw = bench._plan_wire_kw(P)
    assert kw["precision"] == "bf16"
    monkeypatch.setenv("DFFT_BENCH_HISTORY", "0")
    out = bench._emit(8, 0.5, 1e-6, "matmul:bf16", 1, "single",
                      {"matmul:bf16": 0.5}, **kw)
    assert out["precision"] == "bf16"
    out2 = bench._emit(8, 0.5, 1e-6, "xla", 1, "single", {"xla": 0.5},
                       wire_dtype=None, transport="alltoall",
                       precision=None)
    assert "precision" not in out2  # default rows keep the old schema


def test_speed3d_algorithm_label_mm_suffix():
    from benchmarks.speed3d import _algorithm_label, _executor_label

    assert _algorithm_label("alltoall", 1, mm="bf16") == "alltoall+mmbf16"
    assert _algorithm_label("alltoall", 1) == "alltoall"
    # A tiered label pins its own knobs: the env suffix must not lie.
    import os

    old = os.environ.get("DFFT_MM_PRECISION")
    os.environ["DFFT_MM_PRECISION"] = "high"
    try:
        assert _executor_label("matmul:bf16") == "matmul:bf16"
        assert "high" in _executor_label("matmul")
    finally:
        if old is None:
            os.environ.pop("DFFT_MM_PRECISION", None)
        else:
            os.environ["DFFT_MM_PRECISION"] = old


def test_explain_stamps_tier(metrics_on):
    plan = dfft.plan_dft_c2c_3d(SHAPE, None, executor="matmul:bf16",
                                dtype=np.complex64)
    rec = dfft.explain(plan, measure=False)
    assert rec["plan"]["mm_precision"] == "bf16"
    assert rec["plan"]["mm_tflops"] == tuner.MODEL_MM_TFLOPS["bf16"]
    assert rec["stages"]["t0"]["model"].get("mm_flops", 0) > 0


# --------------------------------------------------- thunk retirement

@needs_mesh
def test_thunk_guard_routes_poisoned_class_only():
    """conftest arms DFFT_THUNK_GUARD=matmul: the uneven inverse pencil
    class (the fft_thunk.cc:69 RET_CHECK geometry) plans through the
    matmul executor and executes CORRECTLY; everything outside the
    class keeps its requested executor."""
    mesh = dfft.make_mesh((2, 4))
    bwd = dfft.plan_dft_c2c_3d(UNEVEN, mesh, dtype=np.complex128,
                               direction=dfft.BACKWARD)
    assert bwd.executor == "matmul"
    assert bwd.options.executor == "matmul"
    x = tu.make_world_data(UNEVEN, dtype=np.complex128)
    got = np.asarray(bwd(x))
    want = np.fft.ifftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 1e-11
    # c2r over the same geometry is in the class too.
    c2r = dfft.plan_dft_c2r_3d(UNEVEN, mesh, dtype=np.complex128)
    assert c2r.executor == "matmul"
    # The starved MINOR-AXIS slab chain (input slabs on axis 2 with
    # zero-extent shards) is the second class.
    from jax.sharding import PartitionSpec as P

    sl = dfft.plan_dft_c2c_3d((8, 8, 6), dfft.make_mesh(7),
                              dtype=np.complex128,
                              in_spec=P(None, None, "slab"))
    assert sl.logic.slab_axes[0] == 2
    assert sl.executor == "matmul"
    xs = tu.make_world_data((8, 8, 6), dtype=np.complex128)
    got = np.asarray(sl(xs))
    want = np.fft.fftn(xs)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 1e-11
    # Outside the classes: forward uneven pencil, even inverse pencil,
    # and major-axis slab chains — starved or merely uneven — all
    # untouched (substituting there would break the executor-sensitive
    # bitwise-parity contracts for no protection).
    assert dfft.plan_dft_c2c_3d(UNEVEN, mesh,
                                dtype=np.complex128).executor == "xla"
    assert dfft.plan_dft_c2c_3d((16, 12, 20), mesh, dtype=np.complex128,
                                direction=dfft.BACKWARD).executor == "xla"
    assert dfft.plan_dft_c2c_3d(UNEVEN, dfft.make_mesh(8),
                                dtype=np.complex128,
                                direction=dfft.BACKWARD).executor == "xla"
    assert dfft.plan_dft_c2c_3d((14, 12, 9), dfft.make_mesh(4),
                                dtype=np.complex128,
                                direction=dfft.BACKWARD).executor == "xla"


@needs_mesh
def test_thunk_guard_off_leaves_planning_untouched(monkeypatch):
    monkeypatch.setenv("DFFT_THUNK_GUARD", "")
    dfft.clear_plan_cache()
    mesh = dfft.make_mesh((2, 4))
    # Build only — executing this plan would trip the fault and poison
    # the process for every later 8-device test (jit traces lazily, so
    # planning is safe).
    bwd = dfft.plan_dft_c2c_3d(UNEVEN, mesh, dtype=np.complex128,
                               direction=dfft.BACKWARD)
    assert bwd.executor == "xla"
    dfft.clear_plan_cache()


def test_default_executor_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("DFFT_EXECUTOR", "matmul")
    dfft.clear_plan_cache()
    plan = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=np.complex64)
    assert plan.executor == "matmul"
    # An explicitly non-default executor always wins over the env.
    plan2 = dfft.plan_dft_c2c_3d(SHAPE, None, executor="xla_minor",
                                 dtype=np.complex64)
    assert plan2.executor == "xla_minor"
    monkeypatch.delenv("DFFT_EXECUTOR")
    dfft.clear_plan_cache()
    assert dfft.plan_dft_c2c_3d(SHAPE, None,
                                dtype=np.complex64).executor == "xla"


def test_plan_options_validates_tiers():
    assert PlanOptions(mm_precision="bf16").mm_precision == "bf16"
    assert PlanOptions(mm_precision="high").mm_precision == "f32"
    assert PlanOptions(mm_precision=" ").mm_precision is None
    assert PlanOptions(mm_complex="gauss").mm_complex == "gauss"
    with pytest.raises(ValueError, match="mm_precision"):
        PlanOptions(mm_precision="fast")
    with pytest.raises(ValueError, match="mm_complex"):
        PlanOptions(mm_complex="karatsuba")
