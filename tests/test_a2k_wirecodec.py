"""Pluggable wire-codec registry (PR 13): block-scaled int8 next to
bf16, through the payload accounting / tuner / drivers loop.

Contracts pinned on the 8-way CPU mesh:

1. **The registry is the menu** — `exchange.WIRE_CODECS` drives
   `WIRE_DTYPES`, `wire_itemsize`, validation messages (unknown codec
   strings fail at plan time with the registered menu), and every
   registered codec has a `pair_bytes` figure, a measured-error path,
   and a documented TUNING.md table row (registry completeness).
2. **int8 quarters the wire** — `WIRE_BYTE_KEYS`-accounted wire bytes
   are exactly quartered for c64 across all three flat transports x
   slab/pencil x K in {1,2} x batch in {None, B}, and the lowered HLO's
   collective operand bytes land at ~1/4 of the exact plan's (the f32
   scale sidecar riding the same collective stage is the small
   remainder).
3. **Accuracy is measured and idempotent** — int8 c64 round-trip error
   is bounded (<= 1e-2 on unit-scale data; power-of-two steps), the
   cast pair is exactly idempotent (the staged per-leg boundary
   contract), and the tuner admits/replays int8 winners strictly under
   the one `max_roundtrip_err` budget.
4. **Wisdom schema staleness is diagnosed** — entries recorded under an
   older key schema (missing current `wisdom_key` fields) are counted
   and warned about once, instead of silently never matching.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` (alphabetical collection) — the XLA:CPU fft-thunk
poisoning rule; see ``tests/test_a2g_wire.py``.
"""

import json
import math
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import regress, tuner
from distributedfft_tpu.parallel.exchange import (
    FLAT_ALGORITHMS,
    WIRE_CODECS,
    WIRE_DTYPES,
    wire_codec,
    wire_encode,
    wire_itemsize,
    wire_roundtrip_error,
)
from distributedfft_tpu.plan_logic import (
    PlanOptions,
    exchange_payloads,
    resolve_wire_dtype,
)

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 8)
HLO_SHAPE = (32, 16, 16)  # big enough that the scale sidecar is small
CDT = jnp.complex64
ERR_BOUND = 1e-2  # int8 acceptance bound for c64 unit-scale data


def _world(shape=SHAPE, seed=7):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


@pytest.fixture
def wisdom_path(tmp_path, monkeypatch):
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "wisdom.jsonl"))
    monkeypatch.setenv("DFFT_COMPILE_CACHE", str(tmp_path / "xla_cache"))
    return str(tmp_path / "wisdom.jsonl")


# --------------------------------------------------------- the registry

def test_registry_menu_and_itemsize():
    assert WIRE_DTYPES[0] is None
    assert "bf16" in WIRE_DTYPES and "int8" in WIRE_DTYPES
    assert set(WIRE_CODECS) == set(w for w in WIRE_DTYPES if w)
    assert wire_itemsize(8, "int8") == 2    # c64 -> int8 pair: quarter
    assert wire_itemsize(16, "int8") == 2   # c128 -> int8 pair: eighth
    assert wire_itemsize(8, "bf16") == 4
    with pytest.raises(ValueError, match="wire_dtype"):
        wire_itemsize(8, "fp8")
    with pytest.raises(ValueError, match="int8"):
        wire_codec("fp8")  # the menu is in the message


def test_registry_completeness():
    """Every registered codec carries its accounting figure, a measured
    round-trip error, and a documented TUNING.md table row — the CI
    check that a new codec cannot land half-wired."""
    import os

    tuning = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "TUNING.md")).read()
    for name, codec in WIRE_CODECS.items():
        assert codec.pair_bytes > 0, name
        assert wire_itemsize(8, name) == codec.pair_bytes, name
        err = wire_roundtrip_error(np.complex64, name)
        assert 0.0 < err <= 1e-1, (name, err)
        assert f"`{name}`" in tuning, f"no TUNING.md row for {name!r}"


def test_unknown_codec_fails_at_plan_time_with_menu():
    with pytest.raises(ValueError) as ei:
        PlanOptions(wire_dtype="fp8")
    assert "bf16" in str(ei.value) and "int8" in str(ei.value)
    with pytest.raises(ValueError, match="DFFT_WIRE_DTYPE"):
        resolve_wire_dtype("fp8")
    with pytest.raises(ValueError, match="wire_dtype"):
        dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT, wire_dtype="fp8")


# ------------------------------------------------------- the int8 codec

def test_int8_encode_decode_roundtrip_and_idempotent():
    codec = wire_codec("int8")
    x = jnp.asarray(_world((8, 12, 5)))
    q, scales = codec.encode(x, tile_axis=1, tiles=4)
    assert q.dtype == jnp.int8 and q.shape == x.shape + (2,)
    # One f32 power-of-two step per (peer tile, component plane).
    assert scales.dtype == jnp.float32
    assert scales.shape == (1, 4, 1, 2)
    s = np.asarray(scales)
    assert np.all(np.exp2(np.round(np.log2(s))) == s)  # powers of two
    y = codec.decode((q, scales), x.dtype, tile_axis=1, tiles=4)
    assert y.dtype == x.dtype and y.shape == x.shape
    err = float(np.max(np.abs(np.asarray(y) - np.asarray(x)))
                / np.max(np.abs(np.asarray(x))))
    assert err <= ERR_BOUND
    # Exact idempotence (power-of-two steps): the staged per-leg
    # decode/re-encode boundary must be bit-identical to one cast pair.
    q2, s2 = codec.encode(y, tile_axis=1, tiles=4)
    assert np.array_equal(np.asarray(q2), np.asarray(q))
    assert np.array_equal(np.asarray(s2), np.asarray(scales))
    y2 = codec.decode((q2, s2), x.dtype, tile_axis=1, tiles=4)
    assert np.array_equal(np.asarray(y2), np.asarray(y))
    # The legacy single-array API rejects the multi-part wire form.
    with pytest.raises(ValueError, match="sidecar"):
        wire_encode(x, "int8")
    with pytest.raises(TypeError, match="complex"):
        codec.encode(jnp.zeros((3,), jnp.float32), tile_axis=0, tiles=1)


def test_int8_roundtrip_error_measured_and_cached():
    e64 = wire_roundtrip_error(np.complex64, "int8")
    assert 0.0 < e64 <= ERR_BOUND
    e128 = wire_roundtrip_error(np.complex128, "int8")
    assert 0.0 < e128 <= ERR_BOUND
    assert wire_roundtrip_error(np.complex64, "int8") == e64


def test_plan_options_accept_int8():
    assert PlanOptions(wire_dtype="int8").wire_dtype == "int8"
    assert PlanOptions(wire_dtype="INT8").wire_dtype == "int8"
    assert resolve_wire_dtype("int8") == "int8"


def test_int8_env_resolves(monkeypatch):
    monkeypatch.setenv("DFFT_WIRE_DTYPE", "int8")
    assert resolve_wire_dtype(None) == "int8"
    assert resolve_wire_dtype("none") is None


# ---------------------------------------------------- byte accounting

def test_payload_wire_factor_int8():
    mesh_lp = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT,
                                   wire_dtype="int8").logic
    entries = exchange_payloads(mesh_lp, SHAPE, 8)
    assert entries and all(e["wire_factor"] == 0.25 for e in entries)
    # c128 payloads: 2 wire bytes against 16 -> 0.125.
    assert all(e["wire_factor"] == 0.125
               for e in exchange_payloads(mesh_lp, SHAPE, 16))


@needs_mesh
@pytest.mark.parametrize("alg", FLAT_ALGORITHMS)
@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.parametrize("batch", [None, 3])
def test_int8_wire_bytes_quartered(alg, mesh_shape, k, batch):
    """The acceptance matrix: c64 wire bytes exactly quartered (per the
    shared WIRE_BYTE_KEYS accounting) on all three flat transports x
    slab/pencil x K in {1,2} x batch in {None, B}."""
    from distributedfft_tpu.api import _plan_exchange_bytes

    mesh = dfft.make_mesh(mesh_shape)
    kw = dict(dtype=CDT, algorithm=alg, overlap_chunks=k, batch=batch)
    exact = dfft.plan_dft_c2c_3d(SHAPE, mesh, **kw)
    comp = dfft.plan_dft_c2c_3d(SHAPE, mesh, wire_dtype="int8", **kw)
    t_e, w_e = _plan_exchange_bytes(exact)
    t_c, w_c = _plan_exchange_bytes(comp)
    assert t_c == t_e                  # true information is unchanged
    assert w_c * 4 == w_e              # wire bytes exactly quartered


_TENSOR = re.compile(
    r"tensor<((?:\d+x)*)(complex<f32>|complex<f64>|f64|f32|bf16|f16"
    r"|i8|i16|i32|i64|ui8)>")
_TBYTES = {"complex<f32>": 8, "complex<f64>": 16, "f64": 8, "f32": 4,
           "bf16": 2, "f16": 2, "i8": 1, "i16": 2, "i32": 4, "i64": 8,
           "ui8": 1}


def _collective_operand_bytes(txt: str) -> int:
    """Sum the operand bytes of every collective op in a lowered
    StableHLO text — the HLO-level wire-byte pin."""
    total = 0
    for line in txt.splitlines():
        if ("stablehlo.all_to_all" not in line
                and "stablehlo.collective_permute" not in line):
            continue
        sig = line.rsplit(":", 1)[-1].split("->")[0]
        for m in _TENSOR.finditer(sig):
            dims = [int(d) for d in m.group(1).split("x") if d]
            total += math.prod(dims or [1]) * _TBYTES[m.group(2)]
    return total


@needs_mesh
@pytest.mark.parametrize("alg", FLAT_ALGORITHMS)
def test_int8_hlo_collective_bytes_quartered(alg):
    """The HLO collective-byte pin: the lowered program's collective
    operands carry ~1/4 of the exact plan's bytes (int8 payload plus
    the small f32 scale sidecar riding the same collective stage)."""
    mesh = dfft.make_mesh(8)
    exact = dfft.plan_dft_c2c_3d(HLO_SHAPE, mesh, dtype=CDT,
                                 algorithm=alg)
    comp = dfft.plan_dft_c2c_3d(HLO_SHAPE, mesh, dtype=CDT,
                                algorithm=alg, wire_dtype="int8")
    t_e = exact.fn.lower(
        jax.ShapeDtypeStruct(exact.in_shape, exact.in_dtype)).as_text()
    t_c = comp.fn.lower(
        jax.ShapeDtypeStruct(comp.in_shape, comp.in_dtype)).as_text()
    b_e = _collective_operand_bytes(t_e)
    b_c = _collective_operand_bytes(t_c)
    assert b_e > 0 and b_c > 0
    ratio = b_c / b_e
    assert 0.2 <= ratio <= 0.32, (alg, ratio)
    assert "i8" in t_c  # the int8 collective is really on the wire


@needs_mesh
def test_default_hlo_unchanged_by_registry(monkeypatch):
    """wire_dtype=None (env unset) after the registry refactor still IS
    the exact plan: byte-identical lowered HLO to an explicit
    wire_dtype='none' build, no compressed collective."""
    monkeypatch.delenv("DFFT_WIRE_DTYPE", raising=False)
    mesh = dfft.make_mesh(8)
    base = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT)
    pinned = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                                  wire_dtype="none")
    t_base = base.fn.lower(
        jax.ShapeDtypeStruct(base.in_shape, base.in_dtype)).as_text()
    t_pin = pinned.fn.lower(
        jax.ShapeDtypeStruct(pinned.in_shape, pinned.in_dtype)).as_text()
    assert t_base == t_pin
    assert "bf16" not in t_base and "i8" not in t_base


@needs_mesh
@pytest.mark.parametrize("alg", FLAT_ALGORITHMS)
@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_int8_accuracy_through_plans(alg, mesh_shape):
    mesh = dfft.make_mesh(mesh_shape)
    exact = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, algorithm=alg)
    comp = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, algorithm=alg,
                                wire_dtype="int8")
    x = jnp.asarray(_world())
    ref = np.asarray(exact(x))
    err = float(np.max(np.abs(np.asarray(comp(x)) - ref))
                / np.max(np.abs(ref)))
    # x2 slack: two exchanges on the pencil mesh + FFT accumulation.
    assert err <= 2 * ERR_BOUND, (alg, mesh_shape, err)


# ------------------------------------------------------ tuner integration

def test_enumerate_budget_widens_to_registry():
    cands = tuner.enumerate_candidates(
        SHAPE, 8, executors=("xla",), wire_dtypes=WIRE_DTYPES)
    # The enumerated wire axis IS the registry menu (plus exact) — it
    # widens automatically as codecs register (PR 19 added "split").
    assert {c.wire_dtype for c in cands} == set(WIRE_DTYPES)
    assert {None, "bf16", "int8", "split"} <= set(WIRE_DTYPES)
    comp = next(c for c in cands if c.wire_dtype == "int8")
    assert comp.label.endswith("+wint8")


def test_prune_budget_orders_codecs():
    """One budget governs every codec: a budget between the bf16 and
    int8 measured errors admits bf16 and filters int8; a loose budget
    keeps both; a tight one keeps exact only."""
    e_bf16 = wire_roundtrip_error(np.complex64, "bf16")
    e_int8 = wire_roundtrip_error(np.complex64, "int8")
    assert e_bf16 < e_int8  # the premise of the mid-budget case
    cands = tuner.enumerate_candidates(
        SHAPE, 8, executors=("xla",), wire_dtypes=WIRE_DTYPES)
    mid = tuner.prune_candidates(
        cands, SHAPE, 8, limit=64, dtype=np.complex64,
        max_err=(e_bf16 + e_int8) / 2)
    assert any(c.wire_dtype == "bf16" for c in mid)
    assert all(c.wire_dtype != "int8" for c in mid)
    loose = tuner.prune_candidates(cands, SHAPE, 8, limit=64,
                                   max_err=1e-1, dtype=np.complex64)
    assert any(c.wire_dtype == "int8" for c in loose)
    tight = tuner.prune_candidates(cands, SHAPE, 8, limit=64,
                                   max_err=1e-9, dtype=np.complex64)
    assert tight and all(c.wire_dtype is None for c in tight)


def test_record_wisdom_stamps_int8_compression_err(wisdom_path):
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=-1, ndev=8, mesh_dims=None,
                           device_kind="cpu", platform="cpu",
                           err_budget=1e-2)
    cand = tuner.Candidate("slab", "alltoall", "xla", 1, "int8")
    entry = tuner.record_wisdom(key, cand, 0.001, path=wisdom_path)
    assert entry["schema"] == tuner.WISDOM_SCHEMA
    assert entry["winner"]["wire_dtype"] == "int8"
    assert entry["compression_err"] == wire_roundtrip_error(
        np.complex64, "int8")


def _replay_entry(wisdom_path, err_budget, compression_err):
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=dfft.FORWARD, ndev=8,
                           mesh_dims=None, err_budget=err_budget)
    entry = {
        "schema": tuner.WISDOM_SCHEMA,
        "recorded_at": "2026-08-01T00:00:00", "key": key,
        "winner": {"decomposition": "slab", "algorithm": "alltoall",
                   "executor": "xla", "overlap_chunks": 1,
                   "wire_dtype": "int8"},
        "seconds": 0.001, "compression_err": compression_err,
    }
    with open(wisdom_path, "a") as f:
        f.write(json.dumps(entry) + "\n")


@needs_mesh
def test_int8_winner_replay_admission(wisdom_path):
    """A stored int8 winner replays only into plans whose budget admits
    its recorded error — with zero timing executions; over budget, the
    tuple rebuilds on the exact wire."""
    from distributedfft_tpu.utils import metrics as m

    dfft.clear_plan_cache()
    m.metrics_reset()
    m.enable_metrics()
    try:
        _replay_entry(wisdom_path, err_budget=1e-2, compression_err=6e-3)
        ok = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, tune="wisdom",
                                  max_roundtrip_err=1e-2)
        assert ok.options.wire_dtype == "int8"
        assert m.counter_total("tune_timing_executions") == 0
    finally:
        m.enable_metrics(False)
        m.metrics_reset()
        dfft.clear_plan_cache()


@needs_mesh
def test_int8_winner_rejected_over_budget(wisdom_path):
    dfft.clear_plan_cache()
    try:
        _replay_entry(wisdom_path, err_budget=1e-4, compression_err=6e-3)
        plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, tune="wisdom",
                                    max_roundtrip_err=1e-4)
        assert plan.options.wire_dtype is None
        assert plan.decomposition == "slab"
    finally:
        dfft.clear_plan_cache()


# -------------------------------------------------- wisdom schema lint

def test_wisdom_stale_key_warning(tmp_path, capsys):
    """Entries recorded under an older key schema (missing current
    wisdom_key fields) are counted and warned about once per store —
    never silently unmatched (the PR 12 mm_precision lesson)."""
    path = str(tmp_path / "w.jsonl")
    old_key = tuner.wisdom_key(kind="c2c", shape=SHAPE,
                               dtype=np.complex64, direction=-1, ndev=8,
                               device_kind="cpu", platform="cpu")
    del old_key["mm_precision"]  # a pre-PR12 store
    stale = {"schema": 1, "key": old_key,
             "winner": {"decomposition": "slab", "algorithm": "alltoall",
                        "executor": "xla", "overlap_chunks": 1},
             "seconds": 0.001}
    fresh = dict(stale, key=tuner.wisdom_key(
        kind="c2c", shape=SHAPE, dtype=np.complex64, direction=1,
        ndev=8, device_kind="cpu", platform="cpu"))
    with open(path, "w") as f:
        f.write(json.dumps(stale) + "\n")
        f.write(json.dumps(fresh) + "\n")
    entries = tuner._read_wisdom(path)
    assert len(entries) == 2
    assert tuner.stale_wisdom_entries(entries) == 1
    err = capsys.readouterr().err
    assert "older key schema" in err and "1 wisdom entry" in err
    # Once per store: a second read does not repeat the warning.
    tuner._read_wisdom(path)
    assert "older key schema" not in capsys.readouterr().err
    # Fully-current stores never warn.
    path2 = str(tmp_path / "w2.jsonl")
    with open(path2, "w") as f:
        f.write(json.dumps(fresh) + "\n")
    assert tuner.stale_wisdom_entries(tuner._read_wisdom(path2)) == 0


def test_record_wisdom_keys_are_current():
    """What record_wisdom writes today must never trip the staleness
    diagnostic — the two sides of the schema contract stay in sync."""
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=-1, ndev=8, device_kind="cpu",
                           platform="cpu")
    assert tuner._CURRENT_KEY_FIELDS <= set(key)


# --------------------------------------------------- driver / regress tier

def test_regress_int8_baseline_group():
    base = {"metric": "fft3d_c2c_512_forward_gflops", "value": 100.0,
            "dtype": "complex64", "devices": 8, "decomposition": "slab",
            "backend": "tpu", "device_kind": "TPU v5 lite"}
    r0 = regress.normalize_bench_line(dict(base), source="test")
    r8 = regress.normalize_bench_line(dict(base, wire_dtype="int8"),
                                      source="test")
    rb = regress.normalize_bench_line(dict(base, wire_dtype="bf16"),
                                      source="test")
    assert r8["config"]["wire_dtype"] == "int8"
    keys = {regress.group_key(r) for r in (r0, r8, rb)}
    assert len(keys) == 3  # exact / int8 / bf16 never share a baseline


def test_bench_emit_stamps_int8(capsys):
    import os
    import sys
    TESTS = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(TESTS))
    import bench

    out = bench._emit(16, 1e-4, 1e-7, "xla", 8, "slab", {"xla": 1e-4},
                      wire_dtype="int8")
    capsys.readouterr()
    assert out["wire_dtype"] == "int8"


def test_speed3d_wire_label_int8():
    import os
    import sys
    TESTS = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(os.path.dirname(TESTS), "benchmarks"))
    from speed3d import _algorithm_label

    assert _algorithm_label("alltoall", 1, wire="int8") == "alltoall+wint8"
    assert _algorithm_label("alltoall", 2, batch=4,
                            wire="int8") == "alltoall+ov2+b4+wint8"


def test_tuned_label_carries_int8():
    cand = tuner.Candidate("slab", "alltoall", "xla", 1, "int8")
    assert cand.label == "slab/alltoall/xla/ov1+wint8"
