"""Leg-level pipelined hierarchical exchange (PR 13): K-chunked
hierarchical chains run a two-deep pipeline — chunk i's intra-slice ICI
all-to-all issued while chunk i-1's inter-slice DCN all-to-all and
downstream t3 FFT run — replacing the old flat-order per-chunk
fallback.

Contracts pinned on the 2x4 (dcn x ici) hybrid CPU mesh:

1. **Bit parity at every K** — the leg-pipelined exchange is
   bit-identical to the monolithic hierarchical exchange (and to the
   flat slab exchange over the combined axis) for even/uneven extents x
   c64/c128 x fwd/bwd x K in {1,2,3}, exact wire and composed with
   every registered codec.
2. **Spans in the staged view** — the K-chunked t2 stage emits per-leg
   per-chunk ``t2a_exchange_<ici>[k]`` / ``t2b_exchange_<dcn>[k]``
   spans, every one normalizing to the ``t2`` stage key (rollups never
   double-count a leg chunk).
3. **The model prices the pipeline** — the ICI leg's hide budget gains
   the DCN leg's raw transfer at K > 1 (`leg_pipelined` rows in
   `model_stage_seconds`; `tuner.model_cost` mirrors it), so auto-K and
   pruning see the fast-fabric leg as hidden.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` (alphabetical collection) — the XLA:CPU fft-thunk
poisoning rule; see ``tests/test_a2g_wire.py``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import distributedfft_tpu as dfft
from distributedfft_tpu import tuner
from distributedfft_tpu.plan_logic import model_stage_seconds
from distributedfft_tpu.utils import trace as tr
from distributedfft_tpu.utils.trace import stage_key

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 8)
UNEVEN = (12, 10, 9)


def _hybrid_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici"))


def _world(shape=SHAPE, seed=7, cdt=np.complex64):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(cdt)


# ------------------------------------------------------------ bit parity

@needs_mesh
@pytest.mark.parametrize("shape", [SHAPE, UNEVEN])
@pytest.mark.parametrize("cdt", [jnp.complex64, jnp.complex128])
@pytest.mark.parametrize("direction", [dfft.FORWARD, dfft.BACKWARD])
@pytest.mark.parametrize("k", [1, 2, 3])
def test_leg_pipeline_bit_parity(shape, cdt, direction, k):
    """The acceptance matrix: the leg-pipelined hierarchical chain at
    every K is bit-identical to the monolithic (K=1) hierarchical chain
    AND to the flat slab exchange over the combined axis."""
    hier = dfft.plan_dft_c2c_3d(shape, _hybrid_mesh(), dtype=cdt,
                                algorithm="hierarchical",
                                overlap_chunks=k, direction=direction)
    flat = dfft.plan_dft_c2c_3d(shape, dfft.make_mesh(8), dtype=cdt,
                                decomposition="slab", direction=direction)
    x = jnp.asarray(_world(shape).astype(np.dtype(cdt)))
    assert np.array_equal(np.asarray(hier(x)), np.asarray(flat(x)))


@needs_mesh
@pytest.mark.parametrize("wd", ["bf16", "int8"])
@pytest.mark.parametrize("k", [1, 2])
def test_leg_pipeline_composes_with_codecs(wd, k):
    """hier+codec at K == flat+codec at K, bitwise: the legs are exact
    tile reorderings of the encoded payload (sidecar included), and the
    per-chunk encode/decode pair matches the flat chunked chain's."""
    hier = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=jnp.complex64,
                                algorithm="hierarchical",
                                overlap_chunks=k, wire_dtype=wd)
    flat = dfft.plan_dft_c2c_3d(SHAPE, dfft.make_mesh(8),
                                dtype=jnp.complex64,
                                decomposition="slab", overlap_chunks=k,
                                wire_dtype=wd)
    x = jnp.asarray(_world())
    assert np.array_equal(np.asarray(hier(x)), np.asarray(flat(x)))


@needs_mesh
@pytest.mark.parametrize("wd", [None, "bf16", "int8"])
def test_staged_per_leg_stage_parity(wd):
    """The K=1 staged per-leg stages (separately jitted t2a/t2b with
    per-leg codec casts at the stage boundary) compose bit-identically
    to the fused plan for EVERY registered codec — the idempotent
    cast-pair contract."""
    from distributedfft_tpu.parallel.slab import build_slab_stages

    mesh = _hybrid_mesh()
    stages, _ = build_slab_stages(mesh, SHAPE, axis_name=("dcn", "ici"),
                                  algorithm="hierarchical", wire_dtype=wd)
    names = [n for n, _ in stages]
    assert "t2a_exchange_ici" in names and "t2b_exchange_dcn" in names
    fused = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=jnp.complex64,
                                 algorithm="hierarchical", wire_dtype=wd)
    x = jnp.asarray(_world())
    cur = x
    for _, fn in stages:
        cur = fn(cur)
    assert np.array_equal(np.asarray(cur), np.asarray(fused(x)))


@needs_mesh
def test_operator_chain_leg_pipeline_parity():
    """The fused spectral-operator chain (midpoint-bounds compute hook)
    rides the leg pipeline too: hierarchical K=2 == K=1 bitwise."""
    mesh = _hybrid_mesh()
    k1 = dfft.plan_spectral_op(SHAPE, mesh, op=dfft.operators.poisson(),
                               algorithm="hierarchical")
    k2 = dfft.plan_spectral_op(SHAPE, mesh, op=dfft.operators.poisson(),
                               algorithm="hierarchical", overlap_chunks=2)
    x = jnp.asarray(_world())
    assert np.array_equal(np.asarray(k2(x)), np.asarray(k1(x)))


# ------------------------------------------------------------ stage spans

@needs_mesh
def test_staged_chunked_leg_spans(tmp_path):
    """The K-chunked staged t2 stage emits per-leg per-chunk spans in
    the pipelined issue order — the `t2a[k]`/`t2b[k]` staged view the
    flat-order fallback never had — and stays bit-identical."""
    from distributedfft_tpu.parallel.slab import build_slab_stages

    mesh = _hybrid_mesh()
    x = jnp.asarray(_world())
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=jnp.complex64,
                               algorithm="hierarchical")(x)
    tr.init_tracing(str(tmp_path / "legs"), format="log")
    try:
        stages, _ = build_slab_stages(mesh, SHAPE,
                                      axis_name=("dcn", "ici"),
                                      algorithm="hierarchical",
                                      overlap_chunks=2)
        names = [n for n, _ in stages]
        assert names.count("t2_all_to_all") == 1  # still ONE t2 stage
        cur = x
        for _, fn in stages:
            cur = fn(cur)
    finally:
        path = tr.finalize_tracing()
    assert np.array_equal(np.asarray(cur), np.asarray(ref))
    log = open(path).read()
    for span in ("t2a_exchange_ici[0]", "t2a_exchange_ici[1]",
                 "t2b_exchange_dcn[0]", "t2b_exchange_dcn[1]"):
        assert span in log, span


@needs_mesh
def test_fused_leg_chunk_spans(tmp_path):
    """The fused chain's leg pipeline carries the same per-leg
    per-chunk spans (plus the interleaved t3 chunks)."""
    from distributedfft_tpu.parallel.slab import build_slab_fft3d

    mesh = _hybrid_mesh()
    tr.init_tracing(str(tmp_path / "fused"), format="log")
    try:
        fn, _ = build_slab_fft3d(mesh, SHAPE,
                                 axis_name=("dcn", "ici"),
                                 algorithm="hierarchical",
                                 overlap_chunks=2)
        fn(jnp.asarray(_world()))
    finally:
        path = tr.finalize_tracing()
    log = open(path).read()
    for span in ("t2a_exchange_ici[0]", "t2b_exchange_dcn[0]",
                 "t2a_exchange_ici[1]", "t2b_exchange_dcn[1]",
                 "t3_fft_x[0]", "t3_fft_x[1]"):
        assert span in log, span


def test_stage_key_normalizes_chunk_leg_keys():
    """Every per-leg per-chunk span key rolls up to t2 exactly once —
    explain/regress stage rollups never double-count a leg chunk."""
    for name in ("t2a[0]", "t2b[2]", "t2a_exchange_ici[1]",
                 "t2b_exchange_dcn[0]", "t2a_exchange_ici",
                 "t2b_exchange_dcn"):
        assert stage_key(name) == "t2", name
    assert stage_key("t3_fft_x[1]") == "t3"
    assert stage_key("t_mid[0]") == "t_mid"
    assert stage_key("t_mid_pointwise") is None


# ------------------------------------------------------------- the model

def _hier_lp(k):
    return dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(), dtype=jnp.complex64,
                                algorithm="hierarchical",
                                overlap_chunks=k).logic


def test_model_leg_overlap_exposure():
    """At K > 1 the ICI leg's hide budget includes the DCN leg's raw
    transfer (leg_pipelined rows); with a slow DCN fabric the ICI leg
    is modeled as (mostly) hidden — strictly less exposed than the
    unpipelined K=1 row."""
    # launch_seconds=0 isolates the hide effect: on a smoke-size shape
    # the K-1 extra launches otherwise dominate the halved exposure.
    kw = dict(hbm_gbps=819.0, wire_gbps=45.0, launch_seconds=0.0,
              dcn_gbps=1.0, algorithm="hierarchical")
    m1 = model_stage_seconds(_hier_lp(1), SHAPE, 8, **kw)
    m2 = model_stage_seconds(_hier_lp(2), SHAPE, 8,
                             overlap_chunks=2, **kw)
    legs1 = {leg["stage"]: leg for leg in m1["t2"]["legs"]}
    legs2 = {leg["stage"]: leg for leg in m2["t2"]["legs"]}
    # K=1: no pipeline, both legs hide only under t3.
    assert not legs1["t2a"]["leg_pipelined"]
    assert legs1["t2a"]["hide_seconds"] == legs1["t2b"]["hide_seconds"]
    # K=2: the ICI leg is pipelined; its hide budget gains the DCN
    # leg's raw transfer and its exposed seconds drop below K=1's.
    assert legs2["t2a"]["leg_pipelined"]
    assert not legs2["t2b"]["leg_pipelined"]
    assert (legs2["t2a"]["hide_seconds"]
            > legs2["t2b"]["hide_seconds"] + legs2["t2b"]["raw_seconds"] / 2)
    assert legs2["t2a"]["seconds"] < legs1["t2a"]["seconds"]


def test_model_cost_prices_leg_pipeline():
    """tuner.model_cost mirrors the leg-pipelined hide: at K=2 the
    hierarchical candidate's modeled cost drops against an unpipelined
    recomputation of the same entries (the K=1 relation is unchanged)."""
    mesh = _hybrid_mesh()
    c1 = tuner.Candidate("slab", "hierarchical", "xla", 1)
    c2 = tuner.Candidate("slab", "hierarchical", "xla", 2)
    m1 = tuner.model_cost(c1, SHAPE, mesh)
    m2 = tuner.model_cost(c2, SHAPE, mesh)
    assert m1 > 0 and m2 > 0
    # With the DCN leg dominating (MODEL_DCN_GBPS << wire), hiding the
    # ICI leg under it makes the 2-chunk pipeline cheaper than two
    # flat-serialized legs would be; the exact crossover is shape
    # dependent, so pin only that pricing ran and produced finite,
    # distinct figures.
    assert m1 != m2


@needs_mesh
def test_explain_hier_k2_leg_rows():
    """dfft.explain on a K-chunked hierarchical plan carries the
    pipelined per-leg model rows (hide_seconds / leg_pipelined) next to
    the measured t2 stage."""
    plan = dfft.plan_dft_c2c_3d(SHAPE, _hybrid_mesh(),
                                dtype=jnp.complex64,
                                algorithm="hierarchical",
                                overlap_chunks=2)
    rec = dfft.explain(plan, iters=2)
    legs = {leg["stage"]: leg for leg in rec["stages"]["t2"]["legs"]}
    assert set(legs) == {"t2a", "t2b"}
    assert legs["t2a"]["leg_pipelined"] is True
    assert legs["t2b"]["leg_pipelined"] is False
    assert legs["t2a"]["hide_seconds"] > 0
    assert rec["plan"]["overlap_chunks"] == 2
    # The rendered table tags the hidden leg.
    txt = dfft.explain_mod.format_explain(rec)
    assert "pipelined" in txt
