"""Multi-tenant QoS subsystem (PR 15, docs/SERVING_QOS.md).

Contracts pinned here:

1. **Default pin** — with no policy configured the queue's flush
   behavior, span names, and metrics are identical to the anonymous
   tier (``tenant=`` is a label-only no-op), and the policy-free drain
   order is the documented FIFO: oldest formed group first, by the
   explicit formation stamp — NOT dict-iteration order (regression:
   a reshuffled pending dict still drains oldest-first).
2. **Admission** — token-bucket quotas: over-quota submits shed with
   ``QuotaExceeded`` under ``admission="raise"`` and park under
   ``"block"`` (bounded by the request's deadline); a realtime tenant
   overdraws one extra burst before either applies, so realtime never
   sheds before batch under equal configs. Retries and degraded
   rebuilds are charged to the owning tenant's bucket.
3. **Weighted-fair drain** — under saturation a 3:1 weight ratio
   drains as a 3:1 transform share (within 15%), strict class order
   across classes, and the starvation clock promotes aged batch groups
   ahead of everything (zero starvation past the promotion age). Every
   request still completes bit-correct, including under multi-threaded
   submit contention (2 tenants x 2 classes).
4. **Concurrent-wave placement** — drain order = schedule order
   (higher classes take the earliest waves) and a realtime group never
   rides a cohort containing batch groups; ``concurrent_groups="auto"``
   picks the width from ``model_concurrent_seconds`` (1..4).
5. **Accounting** — ``serving_tenant_*`` metrics, ``tenant=`` span
   attributes, the SLO ledger (p50/p99 vs declared target), and the
   ``report qos`` subcommand (``--ledger``/history/``--json``/
   ``--gate``).

NOTE on the filename: must collect BEFORE ``test_alltoallv.py``
(alphabetical clean-backend tier; see ``tests/conftest.py``).
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import qos, report, serving
from distributedfft_tpu.qos import QosPolicy, QuotaExceeded, Tenant
from distributedfft_tpu.utils import metrics as m
from distributedfft_tpu.utils import trace as tr

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (8, 8, 8)
CDT = jnp.complex128


def _world(seed=0, shape=SHAPE):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape) + 1j * rng.standard_normal(shape)


@pytest.fixture
def metrics_on():
    dfft.enable_metrics()
    m.metrics_reset()
    yield
    m.metrics_reset()
    dfft.enable_metrics(False)


def _queue(policy=None, **kw):
    kw.setdefault("dtype", CDT)
    kw.setdefault("max_batch", 64)
    return dfft.CoalescingQueue(None, policy=policy, **kw)


def _two_class_policy(**kw):
    return QosPolicy([
        Tenant("rt", "realtime", weight=1.0),
        Tenant("it", "interactive", weight=1.0),
        Tenant("bt", "batch", weight=1.0),
    ], **kw)


# ------------------------------------------------------------ spec/units

def test_parse_qos_grammar():
    ts = qos.parse_qos("acme:class=realtime,weight=3,rate=100,burst=20,"
                       "slo=0.05;bulk:class=batch,rate=10")
    assert [t.name for t in ts] == ["acme", "bulk"]
    a, b = ts
    assert a.klass == "realtime" and a.weight == 3.0 and a.rate == 100.0
    assert a.burst == 20.0 and a.slo_wait_s == 0.05
    assert b.klass == "batch" and b.rate == 10.0 and b.burst is None
    assert b.bucket_burst == 10.0  # default max(rate, 1)
    assert qos.parse_qos("") == [] and qos.parse_qos("  ;  ") == []


@pytest.mark.parametrize("bad", [
    "noclause", "x:class=warp", "x:weight=-1", "x:rate=0",
    "x:unknown=1", "x:weight", "x:burst=5",  # burst without rate
])
def test_parse_qos_rejects_malformed(bad):
    with pytest.raises(ValueError):
        QosPolicy(qos.parse_qos(bad))


def test_tenant_validation():
    with pytest.raises(ValueError, match="class"):
        Tenant("x", "urgent")
    with pytest.raises(ValueError, match="weight"):
        Tenant("x", weight=0)
    with pytest.raises(ValueError, match="name"):
        Tenant("")


def test_policy_resolve_and_unknown_tenant():
    pol = QosPolicy([Tenant("a")])
    assert pol.resolve("a").name == "a"
    assert pol.resolve(None).name == "default"
    assert pol.resolve(None).klass == "interactive"
    with pytest.raises(ValueError, match="unknown tenant"):
        pol.resolve("ghost")
    q = _queue(policy=pol)
    with pytest.raises(ValueError, match="unknown tenant"):
        q.submit(jnp.asarray(_world(1)), tenant="ghost")


def test_queue_policy_validation():
    with pytest.raises(ValueError, match="policy"):
        dfft.CoalescingQueue(None, policy=42)
    with pytest.raises(ValueError, match="concurrent_groups"):
        dfft.CoalescingQueue(None, concurrent_groups="fast")
    q = dfft.CoalescingQueue(None)
    with pytest.raises(ValueError, match="limit"):
        q.flush(limit=0)
    with pytest.raises(ValueError, match="tenant"):
        q.submit(jnp.zeros(SHAPE, CDT), tenant=7)


def test_dfft_qos_env_arms_policy(monkeypatch):
    monkeypatch.setenv("DFFT_QOS", "acme:class=realtime,weight=2")
    q = dfft.CoalescingQueue(None, dtype=CDT)
    assert q.policy is not None
    assert q.policy.tenant("acme").klass == "realtime"
    # policy="off" forces the anonymous tier even with the env set.
    q2 = dfft.CoalescingQueue(None, dtype=CDT, policy="off")
    assert q2.policy is None
    monkeypatch.setenv("DFFT_QOS", "")
    assert dfft.CoalescingQueue(None, dtype=CDT).policy is None


def test_starve_factor_env(monkeypatch):
    monkeypatch.setenv("DFFT_QOS_STARVE_FACTOR", "2.5")
    pol = QosPolicy([])
    assert pol.starvation_factor == 2.5
    assert pol.starvation_s(0.2) == pytest.approx(0.5)
    assert pol.starvation_s(None) == pytest.approx(
        2.5 * qos.DEFAULT_STARVE_WAIT_S)


# ----------------------------------------------------------- default pin

def test_no_policy_is_byte_identical_to_anonymous_tier():
    """Acceptance pin: with no policy, tenant-less traffic produces the
    exact pre-QoS observable surface — no tenant metrics, no tenant
    span suffixes, 3-tuple group keys, identical results."""
    assert not tr.tracing_enabled()
    m.enable_metrics(False)
    m.metrics_reset()
    q = _queue()
    assert q.policy is None
    xs = [_world(s) for s in (1, 2)]
    hs = [q.submit(jnp.asarray(v)) for v in xs]
    (key,) = set(h._key for h in hs)
    assert len(key) == 3  # no tenant element
    assert q.flush() == 2
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    for v, h in zip(xs, hs):
        assert np.array_equal(np.asarray(h.result()),
                              np.asarray(ref(jnp.asarray(v))))
    assert dfft.metrics_snapshot()["counters"] == {}
    assert q._pending == {} and q._formed == {}


def test_no_policy_span_names_unchanged(tmp_path):
    """The exact pre-QoS span names (the PR 7 contract) survive."""
    tr.init_tracing(str(tmp_path / "pin"), format="chrome")
    try:
        q = _queue()
        hs = [q.submit(jnp.asarray(_world(s))) for s in (3, 4)]
        q.flush()
        for h in hs:
            h.result()
    finally:
        path = tr.finalize_tracing()
    names = [e["name"] for e in report.load_events(path)]
    assert "serve_flush[c2c:b2:manual]" in names
    assert not any("tenant" in n for n in names)


def test_tenant_label_without_policy_is_accounting_only(metrics_on):
    """tenant= on a policy-free queue: metrics + span label only, no
    behavior change (3-tuple keys, no admission)."""
    q = _queue()
    h = q.submit(jnp.asarray(_world(5)), tenant="acme")
    assert len(h._key) == 3
    q.flush()
    h.result()
    snap = dfft.metrics_snapshot()
    assert snap["counters"]["serving_tenant_submits"][
        "kind=c2c,tenant=acme"] == 1.0


def test_policy_free_fifo_drain_order_is_formation_order():
    """Satellite: the policy-free drain order is the EXPLICIT formation
    FIFO. Regression shape: reshuffling the pending dict (the order a
    dict rebuild could produce) must not change the drain order —
    oldest formed group still drains first."""
    q = _queue()
    q.submit(jnp.asarray(_world(6)))                       # group A
    q.submit(jnp.asarray(_world(7, (4, 4, 4))))            # group B
    q.submit(jnp.asarray(_world(8)), direction=dfft.BACKWARD)  # group C
    formed = sorted(q._pending, key=lambda k: q._formed[k][0])
    # Adversarially rebuild the dict in reversed iteration order.
    with q._lock:
        items = list(q._pending.items())[::-1]
        q._pending.clear()
        q._pending.update(items)
    assert list(q._pending) != formed  # the shuffle took
    executed = []
    real = q._execute_group

    def spy(key, group, **kw):
        executed.append(key)
        return real(key, group, **kw)

    q._execute_group = spy
    assert q.flush() == 3
    assert executed == formed  # FIFO by formation stamp, not dict order


def test_flush_limit_splits_group_and_preserves_remainder():
    q = _queue()
    xs = [_world(s) for s in range(10, 15)]
    hs = [q.submit(jnp.asarray(v)) for v in xs]
    assert q.flush(limit=2) == 2
    assert q.pending() == 3
    assert q.flush(limit=2) == 2
    assert q.flush() == 1
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    for v, h in zip(xs, hs):
        assert np.array_equal(np.asarray(h.result()),
                              np.asarray(ref(jnp.asarray(v))))


# ------------------------------------------------------------- admission

def test_quota_shed_raises_quota_exceeded(metrics_on):
    pol = QosPolicy([Tenant("bulk", "batch", rate=1000.0, burst=2.0)])
    q = _queue(policy=pol, admission="raise")
    clock = {"t": 0.0}
    pol._clock = lambda: clock["t"]  # frozen bucket clock
    q.submit(jnp.asarray(_world(20)), tenant="bulk")
    q.submit(jnp.asarray(_world(21)), tenant="bulk")
    with pytest.raises(QuotaExceeded) as ei:
        q.submit(jnp.asarray(_world(22)), tenant="bulk")
    assert ei.value.tenant == "bulk" and ei.value.retry_after_s > 0
    snap = dfft.metrics_snapshot()
    assert snap["counters"]["serving_tenant_quota_shed"][
        "kind=c2c,tenant=bulk"] == 1.0
    rep = pol.slo_report()["tenants"]["bulk"]
    assert rep["quota_shed"] == 1 and rep["submits"] == 3
    q.flush()


def test_quota_park_blocks_until_refill():
    pol = QosPolicy([Tenant("bulk", "batch", rate=50.0, burst=1.0)])
    q = _queue(policy=pol)  # admission="block"
    q.submit(jnp.asarray(_world(23)), tenant="bulk")
    t0 = time.perf_counter()
    h = q.submit(jnp.asarray(_world(24)), tenant="bulk")  # parks ~20ms
    assert time.perf_counter() - t0 >= 0.015
    q.flush()
    h.result(timeout=30)


def test_quota_park_honors_deadline():
    pol = QosPolicy([Tenant("bulk", "batch", rate=0.5, burst=1.0)])
    q = _queue(policy=pol)
    q.submit(jnp.asarray(_world(25)), tenant="bulk")
    with pytest.raises(dfft.DeadlineExceeded) as ei:
        q.submit(jnp.asarray(_world(26)), tenant="bulk", deadline_s=0.05)
    assert ei.value.stage == "admission"
    assert pol.slo_report()["tenants"]["bulk"]["deadline_misses"] == 1
    q.flush()


def test_realtime_never_sheds_before_batch():
    """Equal rate/burst, equal traffic: the batch tenant sheds first —
    the realtime tenant still admits on overdraft at the point batch is
    already over quota."""
    pol = QosPolicy([
        Tenant("rt", "realtime", rate=1000.0, burst=2.0),
        Tenant("bt", "batch", rate=1000.0, burst=2.0),
    ])
    clock = {"t": 0.0}
    pol._clock = lambda: clock["t"]
    q = _queue(policy=pol, admission="raise")
    for i in range(2):  # both burn their burst
        q.submit(jnp.asarray(_world(30 + i)), tenant="rt")
        q.submit(jnp.asarray(_world(40 + i)), tenant="bt")
    with pytest.raises(QuotaExceeded):
        q.submit(jnp.asarray(_world(50)), tenant="bt")
    # Same instant, same config: realtime still admits (overdraft).
    h = q.submit(jnp.asarray(_world(51)), tenant="rt")
    # The overdraft is bounded: one extra burst, then realtime sheds too.
    q.submit(jnp.asarray(_world(52)), tenant="rt")
    with pytest.raises(QuotaExceeded):
        q.submit(jnp.asarray(_world(53)), tenant="rt")
    q.flush()
    h.result(timeout=30)


def test_retry_and_degraded_are_charged_to_tenant_bucket():
    """Robustness composition: a transient fault's retry re-execution
    is charged to the owning tenant's bucket (recovery work is
    traffic)."""
    from distributedfft_tpu import faults

    pol = QosPolicy([Tenant("acme", "interactive", rate=1000.0,
                            burst=100.0)])
    clock = {"t": 0.0}
    pol._clock = lambda: clock["t"]
    q = _queue(policy=pol, retry_max=2, retry_backoff_s=0.0)
    h = q.submit(jnp.asarray(_world(60)), tenant="acme")
    faults.reset()
    try:
        with faults.injected("execute", once=True, kind="transient"):
            q.flush()
    finally:
        faults.reset()
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    assert np.array_equal(np.asarray(h.result(timeout=30)),
                          np.asarray(ref(jnp.asarray(_world(60)))))
    # 1 admission token + 1 retry charge.
    assert pol._buckets["acme"].tokens == pytest.approx(98.0)


# ------------------------------------------------------ drain order / WFQ

def test_order_groups_strict_class_then_promotion():
    pol = _two_class_policy(starvation_factor=4.0)
    infos = [
        {"key": "b", "tenant": "bt", "n": 1, "age_s": 0.0},
        {"key": "i", "tenant": "it", "n": 1, "age_s": 0.0},
        {"key": "r", "tenant": "rt", "n": 1, "age_s": 0.0},
    ]
    ordered = [i["key"] for i in pol.order_groups(infos, max_wait_s=1.0)]
    assert ordered == ["r", "i", "b"]  # strict class rank
    # Starvation: an aged batch group is promoted past everything.
    infos[0]["age_s"] = 100.0
    ordered = [i["key"] for i in pol.order_groups(infos, max_wait_s=1.0)]
    assert ordered == ["b", "r", "i"]


def test_weighted_fair_drain_shares_3_to_1():
    """Acceptance: 3:1 weights drain as a 3:1 transform share (within
    15%) over the contention window, and every request completes
    bit-correct."""
    pol = QosPolicy([
        Tenant("heavy", "interactive", weight=3.0),
        Tenant("light", "interactive", weight=1.0),
    ])
    q = _queue(policy=pol)
    n = 48
    xs = {t: [_world(hash((t, i)) % 2**31, SHAPE) for i in range(n)]
          for t in ("heavy", "light")}
    hs = {t: [q.submit(jnp.asarray(v), tenant=t) for v in xs[t]]
          for t in ("heavy", "light")}
    drained = []  # (tenant, n) per flush quantum
    while q.pending():
        before = {k: len(g) for k, g in q._pending.items()}
        q.flush(limit=4)
        after = {k: len(g) for k, g in q._pending.items()}
        for k, was in before.items():
            took = was - after.get(k, 0)
            if took:
                drained.append((k[3], took))
    # Contention window: the prefix before either tenant runs dry.
    heavy = light = 0
    totals = {"heavy": 0, "light": 0}
    for t, took in drained:
        totals[t] += took
        if totals["heavy"] >= n or totals["light"] >= n:
            break
        heavy, light = totals["heavy"], totals["light"]
    assert light > 0
    ratio = heavy / light
    assert abs(ratio - 3.0) <= 0.15 * 3.0, (ratio, drained)
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    for t in ("heavy", "light"):
        for v, h in zip(xs[t], hs[t]):
            assert np.array_equal(np.asarray(h.result(timeout=60)),
                                  np.asarray(ref(jnp.asarray(v))))


def test_starvation_clock_promotes_batch_under_realtime_flood():
    """Zero batch starvation past the promotion clock: with realtime
    traffic saturating every drain quantum, an aged batch group is
    promoted and drains."""
    pol = _two_class_policy(starvation_factor=0.05)  # promote at ~50ms
    q = _queue(policy=pol)
    hb = q.submit(jnp.asarray(_world(70)), tenant="bt")
    bt_key = hb._key
    time.sleep(0.08)  # age the batch group past starvation_s(None)=50ms
    for i in range(6):
        q.submit(jnp.asarray(_world(71 + i)), tenant="rt")
    executed = []
    real = q._execute_group

    def spy(key, group, **kw):
        executed.append(key)
        return real(key, group, **kw)

    q._execute_group = spy
    q.flush(limit=1)
    assert executed == [bt_key]  # promoted past the realtime backlog
    q.flush()
    hb.result(timeout=30)


def test_multithreaded_contention_stress():
    """Satellite: 2 tenants x 2 classes submitting from threads;
    weighted shares hold within tolerance for the same-class pair, the
    batch tenant never starves past the promotion clock, and outputs
    are bit-identical to the sequential reference."""
    pol = QosPolicy([
        Tenant("rt-a", "realtime", weight=3.0),
        Tenant("rt-b", "realtime", weight=1.0),
        Tenant("bt-a", "batch", weight=1.0),
        Tenant("bt-b", "batch", weight=1.0),
    ], starvation_factor=0.2)
    q = _queue(policy=pol)
    n = 24
    results: dict = {}
    errs: list = []

    def submitter(tenant):
        try:
            hs = []
            for i in range(n):
                v = _world(hash((tenant, i)) % 2**31)
                hs.append((v, q.submit(jnp.asarray(v), tenant=tenant)))
            results[tenant] = hs
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in ("rt-a", "rt-b", "bt-a", "bt-b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs
    drained = []
    t_start = time.perf_counter()
    while q.pending():
        before = {k: len(g) for k, g in q._pending.items()}
        q.flush(limit=4)
        after = {k: len(g) for k, g in q._pending.items()}
        for k, was in before.items():
            took = was - after.get(k, 0)
            if took:
                drained.append((k[3], took))
        assert time.perf_counter() - t_start < 120
    # Bit-correct under contention.
    ref = dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)
    for tenant, hs in results.items():
        for v, h in hs:
            assert np.array_equal(np.asarray(h.result(timeout=60)),
                                  np.asarray(ref(jnp.asarray(v))))
    # Weighted share within the realtime class over its contention
    # window (prefix before either realtime tenant runs dry).
    totals = {"rt-a": 0, "rt-b": 0}
    a = b = 0
    for t, took in drained:
        if t in totals:
            totals[t] += took
            if totals["rt-a"] >= n or totals["rt-b"] >= n:
                break
            a, b = totals["rt-a"], totals["rt-b"]
    assert b > 0 and abs(a / b - 3.0) <= 0.45 * 3.0, (a, b)
    # Zero batch starvation: both batch tenants fully drained.
    assert all(h.done() for _, h in results["bt-a"])
    assert all(h.done() for _, h in results["bt-b"])


# ------------------------------------------- concurrent-wave placement

def test_concurrent_chunks_realtime_never_rides_batch():
    pol = _two_class_policy()
    infos = [{"key": k, "tenant": t, "n": 1}
             for k, t in (("r1", "rt"), ("r2", "rt"), ("i1", "it"),
                          ("b1", "bt"), ("b2", "bt"))]
    chunks = pol.concurrent_chunks(infos, 4)
    keysets = [[i["key"] for i in c] for c in chunks]
    # Realtime + interactive may cohort; the batch groups split off.
    assert keysets == [["r1", "r2", "i1"], ["b1", "b2"]]
    # Width cap still applies; interactive may cohort with batch (only
    # the realtime/batch pairing is forbidden).
    chunks = pol.concurrent_chunks(infos, 2)
    assert [[i["key"] for i in c] for c in chunks] == [
        ["r1", "r2"], ["i1", "b1"], ["b2"]]
    for c in pol.concurrent_chunks(infos, 3):
        klasses = {pol.tenant(i["tenant"]).klass for i in c}
        assert not ({"realtime", "batch"} <= klasses)


@needs_mesh
def test_concurrent_flush_splits_realtime_from_batch_cohort(metrics_on):
    """Mesh tier: a flush draining one realtime and one batch group
    under concurrent_groups=2 dispatches them SEPARATELY (no concurrent
    merge), while two same-class groups do merge — and results stay
    bit-correct either way."""
    mesh = dfft.make_mesh(8)
    pol = _two_class_policy()
    q = dfft.CoalescingQueue(mesh, dtype=CDT, max_batch=64,
                             concurrent_groups=2, policy=pol)
    a = _world(80, (16, 8, 8))
    b = _world(81, (8, 16, 8))
    ha = q.submit(jnp.asarray(a), tenant="rt")
    hb = q.submit(jnp.asarray(b), tenant="bt")
    q.flush()
    assert m.counter_total("serving_concurrent_dispatches") == 0
    ra = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT)
    rb = dfft.plan_dft_c2c_3d((8, 16, 8), mesh, dtype=CDT)
    assert np.array_equal(np.asarray(ha.result(timeout=60)),
                          np.asarray(ra(jnp.asarray(a))))
    assert np.array_equal(np.asarray(hb.result(timeout=60)),
                          np.asarray(rb(jnp.asarray(b))))
    # Same class: the merge happens (and realtime leads the waves).
    h2a = q.submit(jnp.asarray(a), tenant="rt")
    h2b = q.submit(jnp.asarray(b), tenant="it")
    q.flush()
    assert m.counter_total("serving_concurrent_dispatches") == 1.0
    assert np.array_equal(np.asarray(h2a.result(timeout=60)),
                          np.asarray(ra(jnp.asarray(a))))
    assert np.array_equal(np.asarray(h2b.result(timeout=60)),
                          np.asarray(rb(jnp.asarray(b))))


@needs_mesh
def test_concurrent_auto_width_model_driven(metrics_on):
    """concurrent_groups='auto' (the PR 14 remainder): the width comes
    from model_concurrent_seconds over 1..4 — on a mesh whose exchange
    hides under peer compute the model picks >= 2, the flush merges,
    and results are bit-correct."""
    mesh = dfft.make_mesh(8)
    q = dfft.CoalescingQueue(mesh, dtype=CDT, max_batch=64,
                             concurrent_groups="auto")
    a = _world(82, (16, 8, 8))
    b = _world(83, (8, 16, 8))
    ha = q.submit(jnp.asarray(a))
    hb = q.submit(jnp.asarray(b))
    with q._lock:
        groups = [(k, g) for k, g in q._pending.items()]
        w = q._concurrent_width(groups)
    assert 1 <= w <= 4
    q.flush()
    ra = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT)
    rb = dfft.plan_dft_c2c_3d((8, 16, 8), mesh, dtype=CDT)
    assert np.array_equal(np.asarray(ha.result(timeout=60)),
                          np.asarray(ra(jnp.asarray(a))))
    assert np.array_equal(np.asarray(hb.result(timeout=60)),
                          np.asarray(rb(jnp.asarray(b))))
    if w >= 2:
        assert m.counter_total("serving_concurrent_dispatches") == 1.0
    # The width memo holds for the steady-state flush pattern.
    with q._lock:
        assert q._concurrent_width(groups) == w


def test_concurrent_auto_falls_back_below_ir_tier():
    """Single-device plans carry no stage graph: 'auto' degrades to
    sequential flushes (width 1), never an error."""
    q = _queue(concurrent_groups="auto")
    ha = q.submit(jnp.asarray(_world(84)))
    hb = q.submit(jnp.asarray(_world(85, (4, 4, 4))))
    with q._lock:
        assert q._concurrent_width(list(q._pending.items())) == 1
    q.flush()
    ha.result(timeout=30), hb.result(timeout=30)


def test_env_concurrent_auto(monkeypatch):
    monkeypatch.setenv("DFFT_CONCURRENT_GROUPS", "auto")
    q = dfft.CoalescingQueue(None, dtype=CDT)
    assert q.concurrent_groups == "auto"


# --------------------------------------------------- accounting / ledger

def test_tenant_metrics_and_span_attributes(tmp_path, metrics_on):
    pol = QosPolicy([Tenant("acme", "realtime", slo_wait_s=10.0)])
    tr.init_tracing(str(tmp_path / "qos"), format="chrome")
    try:
        q = _queue(policy=pol)
        h = q.submit(jnp.asarray(_world(90)), tenant="acme")
        q.flush()
        h.result(timeout=30)
    finally:
        path = tr.finalize_tracing()
    names = [e["name"] for e in report.load_events(path)]
    assert any(n.startswith("serve_submit[") and n.endswith(
        ":tenant=acme]") for n in names)
    assert "serve_flush[c2c:b1:manual:tenant=acme]" in names
    snap = dfft.metrics_snapshot()
    assert snap["counters"]["serving_tenant_submits"][
        "kind=c2c,tenant=acme"] == 1.0
    assert snap["counters"]["serving_tenant_transforms"][
        "kind=c2c,tenant=acme"] == 1.0
    assert snap["histograms"]["serving_tenant_wait_seconds"][
        "kind=c2c,tenant=acme"]["count"] == 1


def test_deadline_miss_lands_in_tenant_ledger(metrics_on):
    pol = QosPolicy([Tenant("acme", slo_wait_s=10.0)])
    q = _queue(policy=pol)
    doomed = q.submit(jnp.asarray(_world(91)), tenant="acme",
                      deadline_s=0.05)
    end = time.time() + 10
    while not doomed.done() and time.time() < end:
        time.sleep(0.02)
    with pytest.raises(dfft.DeadlineExceeded):
        doomed.result(timeout=10)
    rep = pol.slo_report()["tenants"]["acme"]
    assert rep["deadline_misses"] == 1
    assert rep["slo_ok"] is False  # misses count against the SLO
    snap = dfft.metrics_snapshot()
    assert snap["counters"]["serving_tenant_deadline_misses"][
        "kind=c2c,tenant=acme"] == 1.0


def test_slo_ledger_quantiles_and_verdict():
    pol = QosPolicy([Tenant("a", slo_wait_s=1.0), Tenant("b")])
    for w in (0.01, 0.02, 0.03, 0.5):
        pol.note_wait("a", w)
    pol.account_drain("a", 4)
    rep = pol.slo_report()["tenants"]["a"]
    assert rep["transforms"] == 4
    assert rep["wait_p50_s"] == pytest.approx(0.03)
    assert rep["wait_p99_s"] == pytest.approx(0.5)
    assert rep["slo_ok"] is True
    pol.note_wait("a", 5.0)  # p99 now busts the 1s target
    assert pol.slo_report()["tenants"]["a"]["slo_ok"] is False
    # No declared target -> no verdict key.
    assert "slo_ok" not in pol.slo_report()["tenants"]["b"]


def test_report_qos_cli_ledger_table_json_gate(tmp_path, capsys):
    pol = QosPolicy([Tenant("acme", "realtime", weight=3.0, rate=100.0,
                            slo_wait_s=1.0),
                     Tenant("bulk", "batch")])
    pol.note_wait("acme", 0.01)
    pol.account_drain("acme", 1)
    pol.note_submit("acme")
    path = str(tmp_path / "ledger.json")
    qos.write_ledger(pol, path)
    assert report.main(["qos", "--ledger", path]) == 0
    out = capsys.readouterr().out
    assert "acme" in out and "realtime" in out and "ok" in out
    assert "bulk" in out
    # --json round-trips the document.
    assert report.main(["qos", "--ledger", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["tenants"]["acme"]["transforms"] == 1
    # --gate trips when a declared SLO is missed.
    pol.note_wait("acme", 9.0)
    qos.write_ledger(pol, path)
    assert report.main(["qos", "--ledger", path, "--gate"]) == 1
    assert "MISSED" in capsys.readouterr().out


def test_report_qos_reads_history_record(tmp_path, capsys):
    from distributedfft_tpu import regress

    pol = QosPolicy([Tenant("acme", slo_wait_s=1.0)])
    pol.note_wait("acme", 0.02)
    pol.account_drain("acme", 1)
    rec = regress.make_run_record(
        metric="serving_qos_smoke", value=1.0, backend="cpu",
        qos=pol.slo_report())
    hist = str(tmp_path / "history.jsonl")
    regress.append_records([rec], hist)
    assert report.main(["qos", "--history", hist]) == 0
    assert "acme" in capsys.readouterr().out
    # No qos block anywhere -> exit 2.
    hist2 = str(tmp_path / "empty.jsonl")
    regress.append_records([regress.make_run_record(
        metric="x", value=1.0, backend="cpu")], hist2)
    assert report.main(["qos", "--history", hist2]) == 2


def test_qos_knobs_not_plan_cache_keyed():
    """DFFT_QOS* never changes what a plan compiles to, so it must NOT
    fragment the plan cache."""
    from distributedfft_tpu import api

    assert "DFFT_QOS" not in api._PLAN_ENV_KNOBS
    assert "DFFT_QOS_STARVE_FACTOR" not in api._PLAN_ENV_KNOBS
