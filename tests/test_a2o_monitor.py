"""Live-monitor acceptance on the virtual 8-device mesh (PR 16,
docs/OBSERVABILITY.md "Live monitoring & health").

Contracts pinned here:

1. **Monitored concurrent multi-tenant serving** — a ``DFFT_MONITOR``-
   armed :class:`CoalescingQueue` under ``concurrent_groups=2``
   two-tenant load streams a JSONL series whose Prometheus rendering
   exposes queue depth, per-tenant SLO misses, and the stall count;
   results stay bit-correct, ``report live --prom`` serves the newest
   sample, and ``report health --gate`` exits 0 on the healthy run.
2. **Fault-injected SLO burn trips the gate** — with
   ``DFFT_FAULT_INJECT`` keeping the drain stuck in transient-retry
   backoff, a deadlined request expires while queued; the tenant
   ledger goes out of SLO and ``report health --gate`` exits 1.
3. **Measured overlap attribution** — ``explain(..., concurrent=2)``
   and an overlap-K (K=2) leg-pipelined plan both carry
   ``overlap.measured_hide_ratio`` (the dispatch-span join) next to
   the model's hide budget; a plain plan carries ``overlap: None``
   (the disarmed pin) and malformed cohorts raise.

NOTE on the filename: must collect BEFORE ``test_alltoallv.py``
(alphabetical clean-backend tier; see ``tests/conftest.py``).
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import report
from distributedfft_tpu.monitor import (
    Monitor,
    dispatch_spans,
    load_series,
    overlap_from_events,
    prometheus_from_sample,
)
from distributedfft_tpu.qos import QosPolicy, Tenant
from distributedfft_tpu.utils import metrics as m

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (8, 8, 8)
CDT = jnp.complex128


def _world(seed=0, shape=SHAPE):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape) + 1j * rng.standard_normal(shape)


@pytest.fixture
def metrics_on():
    dfft.enable_metrics()
    m.metrics_reset()
    yield
    m.metrics_reset()
    dfft.enable_metrics(False)


def _wait_for(pred, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------ monitored serving acceptance

@needs_mesh
def test_monitored_concurrent_multitenant_acceptance(
        tmp_path, monkeypatch, metrics_on, capsys):
    """Acceptance: DFFT_MONITOR-armed queue, concurrent_groups=2, two
    tenants -> JSONL series; its Prometheus rendering exposes queue
    depth, tenant SLO misses, and the stall count; the healthy run
    passes ``report health --gate``."""
    series = str(tmp_path / "live.jsonl")
    monkeypatch.setenv("DFFT_MONITOR", f"0.05,{series}")
    mesh = dfft.make_mesh(8)
    pol = QosPolicy([
        Tenant("acme", "interactive", weight=2.0, slo_wait_s=30.0),
        Tenant("bulk", "batch", slo_wait_s=60.0),
    ])
    q = dfft.CoalescingQueue(mesh, dtype=CDT, max_batch=64,
                             concurrent_groups=2, policy=pol)
    try:
        mon = q._monitor
        assert mon is not None and mon._thread.is_alive()
        a = _world(1, (16, 8, 8))
        b = _world(2, (8, 16, 8))
        ha = q.submit(jnp.asarray(a), tenant="acme")
        hb = q.submit(jnp.asarray(b), tenant="bulk")
        pending = mon.sample()  # deterministic mid-load sample
        assert pending["queue"]["depth"] == 2
        q.flush()
        # interactive+bulk may cohort: ONE concurrent dispatch.
        assert m.counter_total("serving_concurrent_dispatches") == 1.0
        ra = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT)
        rb = dfft.plan_dft_c2c_3d((8, 16, 8), mesh, dtype=CDT)
        assert np.array_equal(np.asarray(ha.result(timeout=60)),
                              np.asarray(ra(jnp.asarray(a))))
        assert np.array_equal(np.asarray(hb.result(timeout=60)),
                              np.asarray(rb(jnp.asarray(b))))
        drained = mon.sample()
        sampler = mon._thread
    finally:
        q.close()
    assert not sampler.is_alive()  # close tears the sampler down

    # The series carries both manual samples (plus any daemon ones).
    docs = load_series(series)
    assert len(docs) >= 2
    # Prometheus rendering of the mid-load sample: depth, SLO standing,
    # stall count — the three acceptance series.
    prom = prometheus_from_sample(pending)
    assert 'dfft_queue_depth{kind="c2c"} 2' in prom
    assert 'dfft_queue_stalls_total{kind="c2c"} 0' in prom
    assert 'dfft_tenant_submits_total{tenant="acme"} 1' in prom
    after = prometheus_from_sample(drained)
    assert 'dfft_queue_depth{kind="c2c"} 0' in after
    assert 'dfft_tenant_slo_misses_total{tenant="acme"} 0' in after
    assert 'dfft_tenant_slo_misses_total{tenant="bulk"} 0' in after
    assert 'dfft_tenant_slo_ok{tenant="acme"} 1' in after
    # report live --prom serves the newest sample of the series.
    assert report.main(["live", "--series", series, "--prom"]) == 0
    out = capsys.readouterr().out
    assert "dfft_queue_depth" in out
    assert "dfft_tenant_slo_misses_total" in out
    assert "dfft_queue_stalls_total" in out
    # Healthy load: the gate passes.
    assert report.main(["health", "--series", series, "--gate"]) == 0
    assert "status: ok" in capsys.readouterr().out


def test_health_gate_trips_on_fault_injected_slo_burn(
        tmp_path, chaos, metrics_on, capsys):
    """Acceptance: DFFT_FAULT_INJECT keeps the drain stuck in
    transient-retry backoff; a deadlined request expires while queued,
    the tenant ledger goes out of SLO, and ``report health --gate``
    exits 1 on the streamed series."""
    pol = QosPolicy([Tenant("acme", "interactive", slo_wait_s=5.0)])
    q = dfft.CoalescingQueue(None, dtype=CDT, max_batch=64, policy=pol,
                             retry_max=2, retry_backoff_s=0.2)
    series = str(tmp_path / "burn.jsonl")
    mon = Monitor(q, path=series)
    ha = q.submit(jnp.asarray(_world(1)), tenant="acme")
    mon.sample()  # healthy baseline sample
    chaos("execute:every=1,kind=transient")
    # The drain sticks in fail->backoff->fail: ~0.6s per flush attempt.
    drain = threading.Thread(target=q.flush)
    drain.start()
    try:
        # Once the stuck flush owns group A, a deadlined request lands
        # in the queue with nobody left to drain it.
        assert _wait_for(lambda: not q.pending())
        hb = q.submit(jnp.asarray(_world(2)), tenant="acme",
                      deadline_s=0.2)
        # No result() here before expiry — an await would trigger the
        # reason="result" rescue flush. The deadline timer owns hb.
        assert _wait_for(hb.done)
        with pytest.raises(dfft.DeadlineExceeded):
            hb.result(timeout=10)
    finally:
        drain.join(60)
    assert not drain.is_alive()
    with pytest.raises(Exception):
        ha.result(timeout=30)  # retries exhausted under every=1
    rep = pol.slo_report()["tenants"]["acme"]
    assert rep["deadline_misses"] == 1 and rep["slo_ok"] is False
    assert m.counter_total("serving_expired") == 1.0
    mon.sample()  # the incident sample
    verdict = mon.health()
    assert verdict["status"] == "alert"
    assert any(a["name"] == "slo_burn" and a["tenant"] == "acme"
               for a in verdict["alerts"])
    assert report.main(["health", "--series", series, "--gate"]) == 1
    err = capsys.readouterr().err
    assert "slo_burn" in err


# ------------------------------------------ measured overlap attribution

@needs_mesh
def test_dispatch_spans_interleave_on_mesh():
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT)
    spans = dispatch_spans([plan, plan])
    names = [n for n, _, _ in spans]
    assert any(n.startswith("cc0:") for n in names)
    assert any(n.startswith("cc1:") for n in names)
    cc = overlap_from_events(spans)["concurrent"]
    assert cc["groups"] == 2
    # schedule_concurrent interleaves the two stage DAGs: the realized
    # dispatch overlap is strictly positive (and < 1 by construction).
    assert 0.0 < cc["hide_ratio"] < 1.0
    with pytest.raises(ValueError):
        dispatch_spans([dfft.plan_dft_c2c_3d(SHAPE, None, dtype=CDT)])


@needs_mesh
def test_explain_measured_overlap_concurrent(metrics_on):
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT)
    rec = dfft.explain(plan, measure=False, concurrent=2)
    ov = rec["overlap"]
    assert ov is not None and ov["kind"] == "concurrent"
    assert ov["cohort"] == 2 and ov["groups"] == 2
    assert 0.0 < ov["measured_hide_ratio"] < 1.0
    assert len(ov["measured_samples"]) >= 1
    assert isinstance(ov["model_hide_ratio"], float)
    assert "model_speedup" in ov and "divergence" in ov


@needs_mesh
def test_explain_measured_overlap_leg_pipeline():
    mesh = dfft.make_mesh(8)
    p2 = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT,
                              overlap_chunks=2)
    rec = dfft.explain(p2, measure=False)
    ov = rec["overlap"]
    assert ov is not None and ov["kind"] == "overlap_k"
    assert ov["cohort"] == 1 and ov["groups"] == 2
    # The per-chunk [k] spans joined; the dispatch-level ratio is
    # honest (0.0 for back-to-back chunk issue), never negative.
    assert 0.0 <= ov["measured_hide_ratio"] <= 1.0
    # Model side: min(1, sum leg hides / raw t2) — clamped nonnegative.
    assert 0.0 <= ov["model_hide_ratio"] <= 1.0


@needs_mesh
def test_explain_overlap_disarmed_pin_and_validation():
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d((16, 8, 8), mesh, dtype=CDT)
    # No concurrent cohort, K=1: no overlap block at all (the record
    # shape every pre-PR-16 consumer saw).
    assert dfft.explain(plan, measure=False)["overlap"] is None
    with pytest.raises(ValueError):
        dfft.explain(plan, measure=False, concurrent=True)
    with pytest.raises(ValueError):
        dfft.explain(plan, measure=False, concurrent=1)
