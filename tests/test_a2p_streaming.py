"""Streaming wave scheduler (PR 18, docs/SERVING_QOS.md "Streaming
scheduler & wave preemption").

Contracts pinned here:

1. **Wave preemption** (``qos.QosPolicy.preempt_wave``) — a
   realtime-class group past a saturated wave's cutoff is admitted into
   THIS wave, displacing the youngest lower-class window members; the
   bumped transforms are charged to the preempting tenant (the ledger's
   ``preemptions`` row) and the bumped groups are returned for
   re-queueing, never dropped. Without a realtime group past the
   cutoff, plain truncation: no bumps, no charges.
2. **Streaming drain loop** (``serve()``/``stop()``) — bit-parity with
   the direct plan, clean shutdown with in-flight waves (every handle
   resolved, loop thread dead, nothing pending), idempotent
   re-arm/re-stop, and the ``DFFT_SERVE_STREAMING`` constructor knob.
3. **Width tournament** (``tuner.tune_concurrent_width``) — budget
   grammar (``DFFT_WIDTH_TOURNAMENT``), and determinism under fixed
   wisdom: the first call measures and persists a winner, every later
   call replays it without re-measuring.
4. **Fault isolation** — an injected execute fault mid-wave fails that
   wave's handles but never wedges the loop: later waves still drain
   and the queue stays usable.
5. **(slow) Occupancy win** — on one fixed arrival trace, the
   streaming scheduler's measured inter-wave device-idle fraction is
   strictly lower than the discrete flush cadence's, and the realtime
   class's p99 admit-to-dispatch latency stays within a wave duration.

NOTE on the filename: must collect BEFORE ``test_alltoallv.py``
(alphabetical clean-backend tier; see ``tests/conftest.py``).
"""

import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import serving, tuner
from distributedfft_tpu.qos import QosPolicy, Tenant
from distributedfft_tpu.serving import CoalescingQueue

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (8, 8, 8)
CDT = jnp.complex64


def _x(seed=0, shape=SHAPE):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64))


def _rt_policy():
    pol = QosPolicy()
    pol.register(Tenant("rt", klass="realtime"))
    pol.register(Tenant("bulk", klass="batch"))
    return pol


# ------------------------------------------------- 1. wave preemption


def test_preempt_wave_admits_realtime_and_charges():
    pol = _rt_policy()
    infos = [
        {"key": "b1", "tenant": "bulk", "n": 3},
        {"key": "b2", "tenant": "bulk", "n": 2},
        {"key": "r1", "tenant": "rt", "n": 1},
    ]
    admit, bumped, charges = pol.preempt_wave(infos, 2)
    assert [i["key"] for i in admit] == ["b1", "r1"]
    assert [i["key"] for i in bumped] == ["b2"]
    # The bumped transforms are charged to the preempting realtime
    # tenant and land in its ledger's preemption row.
    assert charges == {"rt": 2}
    row = pol.slo_report()["tenants"]["rt"]
    assert row["preemptions"] == 2


def test_preempt_wave_all_realtime_guaranteed():
    pol = _rt_policy()
    infos = [{"key": f"b{i}", "tenant": "bulk", "n": 1} for i in range(3)]
    infos += [{"key": f"r{i}", "tenant": "rt", "n": 1} for i in range(2)]
    admit, bumped, _ = pol.preempt_wave(infos, 2)
    # Width 2, two realtime groups past the cutoff: BOTH get slots —
    # a realtime arrival never waits out a saturated wave.
    assert [i["key"] for i in admit] == ["r0", "r1"]
    assert [i["key"] for i in bumped] == ["b0", "b1"]


def test_preempt_wave_without_realtime_truncates():
    pol = _rt_policy()
    infos = [{"key": f"b{i}", "tenant": "bulk", "n": 1} for i in range(4)]
    admit, bumped, charges = pol.preempt_wave(infos, 2)
    assert [i["key"] for i in admit] == ["b0", "b1"]
    assert bumped == [] and charges == {}
    assert pol.slo_report()["tenants"]["rt"]["preemptions"] == 0


def test_preempt_wave_order_preserved_under_width():
    pol = _rt_policy()
    infos = [
        {"key": "b1", "tenant": "bulk", "n": 1},
        {"key": "r1", "tenant": "rt", "n": 1},
        {"key": "b2", "tenant": "bulk", "n": 1},
    ]
    admit, bumped, charges = pol.preempt_wave(infos, 3)
    # Unsaturated width: everything dispatches, relative order intact.
    assert [i["key"] for i in admit] == ["b1", "r1", "b2"]
    assert bumped == [] and charges == {}


# ------------------------------------- 2. streaming drain loop


@needs_mesh
def test_streaming_parity_and_clean_shutdown():
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, direction=dfft.FORWARD,
                                dtype=CDT)
    xs = [_x(i) for i in range(10)]
    want = [plan(x) for x in xs]
    q = CoalescingQueue(mesh, max_batch=4, dtype=CDT, streaming=True)
    try:
        assert q._streaming and q._serve_thread is not None
        handles = [q.submit(x) for x in xs]
        q.stop(drain=True)
        # Clean shutdown with in-flight waves: every admitted request
        # resolved, nothing pending, the loop thread exited.
        for h, w in zip(handles, want):
            np.testing.assert_array_equal(np.asarray(h.result(timeout=60)),
                                          np.asarray(w))
        assert q.pending() == 0
        assert q._serve_thread is None
        # Idempotent: stop again, re-arm, stop again.
        q.stop()
        q.serve()
        assert q._serve_thread is not None
        h = q.submit(xs[0])
        q.stop(drain=True)
        np.testing.assert_array_equal(np.asarray(h.result(timeout=60)),
                                      np.asarray(want[0]))
    finally:
        q.close()


@needs_mesh
def test_streaming_records_wave_occupancy():
    mesh = dfft.make_mesh(8)
    q = CoalescingQueue(mesh, max_batch=4, dtype=CDT, streaming=True)
    try:
        hs = [q.submit(_x(i)) for i in range(8)]
        q.stop(drain=True)
        for h in hs:
            h.result(timeout=60)
        snap = q._wave_stats.snapshot()
        assert snap["waves"] >= 1
        assert snap["busy_s"] > 0.0
        assert snap["width_max"] >= 1
        # Admit-to-dispatch reservoirs exist for the anonymous class.
        assert sum(v["n"] for v in snap["admit_wait"].values()) > 0
    finally:
        q.close()


def test_env_knob_arms_streaming(monkeypatch):
    monkeypatch.setenv("DFFT_SERVE_STREAMING", "1")
    q = CoalescingQueue(max_batch=2, dtype=CDT)
    try:
        assert q._streaming and q._serve_thread.is_alive()
        h = q.submit(_x(3))
        q.stop(drain=True)
        h.result(timeout=60)
    finally:
        q.close()
    monkeypatch.setenv("DFFT_SERVE_STREAMING", "0")
    q2 = CoalescingQueue(max_batch=2, dtype=CDT)
    try:
        assert not q2._streaming and q2._serve_thread is None
    finally:
        q2.close()


@needs_mesh
def test_streaming_realtime_admitted_under_saturation():
    mesh = dfft.make_mesh(8)
    pol = _rt_policy()
    q = CoalescingQueue(mesh, max_batch=2, dtype=CDT, policy=pol,
                        streaming=True)
    try:
        hs = [q.submit(_x(i), tenant="bulk") for i in range(8)]
        hs += [q.submit(_x(100 + i), tenant="rt") for i in range(3)]
        q.stop(drain=True)
        for h in hs:
            h.result(timeout=120)  # nobody starved, nothing dropped
        led = pol.slo_report()["tenants"]
        assert led["rt"]["transforms"] == 3
        assert led["bulk"]["transforms"] == 8
    finally:
        q.close()


# ---------------------------------------- 3. width tournament


def test_width_budget_grammar(monkeypatch):
    monkeypatch.delenv("DFFT_WIDTH_TOURNAMENT", raising=False)
    assert tuner.width_budget() is None
    for off in ("0", "off", ""):
        monkeypatch.setenv("DFFT_WIDTH_TOURNAMENT", off)
        assert tuner.width_budget() is None
    monkeypatch.setenv("DFFT_WIDTH_TOURNAMENT", "3")
    assert tuner.width_budget() == (3, 2)
    monkeypatch.setenv("DFFT_WIDTH_TOURNAMENT", "4x5")
    assert tuner.width_budget() == (4, 5)
    monkeypatch.setenv("DFFT_WIDTH_TOURNAMENT", "junk")
    with pytest.raises(ValueError):
        tuner.width_budget()


@needs_mesh
def test_width_tournament_deterministic_under_wisdom(
        monkeypatch, tmp_path):
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, direction=dfft.FORWARD,
                                dtype=CDT)
    plans, counts = [plan, plan, plan], [1, 1, 1]
    path = str(tmp_path / "wisdom.jsonl")

    monkeypatch.delenv("DFFT_WIDTH_TOURNAMENT", raising=False)
    assert tuner.tune_concurrent_width(plans, counts, path=path) is None

    monkeypatch.setenv("DFFT_WIDTH_TOURNAMENT", "2x1")
    w1 = tuner.tune_concurrent_width(plans, counts, path=path)
    assert isinstance(w1, int) and 1 <= w1 <= 3
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 1
    entry = lines[0]
    assert entry["winner"]["width"] == w1
    assert entry["key"]["kind"] == "concurrent"
    assert entry["waves_per_s"] > 0
    # Fixed wisdom => deterministic replay: same width, no re-measure
    # (the store would have grown a second line).
    for _ in range(3):
        assert tuner.tune_concurrent_width(plans, counts, path=path) == w1
    assert sum(1 for _ in open(path)) == 1


@needs_mesh
def test_queue_auto_width_uses_tournament(monkeypatch, tmp_path):
    path = str(tmp_path / "wisdom.jsonl")
    monkeypatch.setenv("DFFT_WISDOM", path)
    monkeypatch.setenv("DFFT_WIDTH_TOURNAMENT", "1x1")
    mesh = dfft.make_mesh(8)
    # max_batch above the per-group submit count so neither group
    # auto-flushes "full" before flush() sees BOTH pending (the
    # concurrent path needs >= 2 groups in one drain).
    q = CoalescingQueue(mesh, max_batch=4, dtype=CDT,
                        concurrent_groups="auto")
    try:
        hs = [q.submit(_x(i, (8, 8, 8))) for i in range(2)]
        hs += [q.submit(_x(9 + i, (16, 8, 4))) for i in range(2)]
        q.flush()
        for h in hs:
            h.result(timeout=120)
        # The measured tournament persisted its winner for the live
        # plan tuple (model-only auto never writes wisdom).
        entries = [json.loads(ln) for ln in open(path)]
        assert any(e.get("key", {}).get("kind") == "concurrent"
                   for e in entries)
    finally:
        q.close()


# ------------------------------------------- 4. fault isolation


@needs_mesh
def test_fault_mid_wave_does_not_wedge_loop(chaos):
    mesh = dfft.make_mesh(8)
    q = CoalescingQueue(mesh, max_batch=2, dtype=CDT, streaming=True)
    try:
        chaos("execute:every=2,kind=deterministic")
        hs = [q.submit(_x(i)) for i in range(8)]
        q.stop(drain=True)
        # Every handle resolved — success or a carried error, never a
        # hang — and the loop exited cleanly.
        outcomes = []
        for h in hs:
            try:
                h.result(timeout=60)
                outcomes.append("ok")
            except Exception:  # noqa: BLE001 — injected
                outcomes.append("err")
        assert q._serve_thread is None and q.pending() == 0
        # Disarmed, the queue keeps serving (the loop never wedged).
        os.environ.pop("DFFT_FAULT_INJECT", None)
        from distributedfft_tpu import faults
        faults.reset()
        q.serve()
        h = q.submit(_x(42))
        q.stop(drain=True)
        assert np.asarray(h.result(timeout=60)).shape == SHAPE
    finally:
        q.close()


# ------------------------------------ 5. (slow) occupancy win


@pytest.mark.slow
@needs_mesh
def test_streaming_idle_fraction_beats_flush_cadence():
    """On one fixed arrival trace, the streaming loop's inter-wave
    device-idle fraction must undercut the discrete flush cadence's
    (which parks arrivals until the next tick), and the realtime
    class's p99 admit-to-dispatch wait must stay within a wave
    duration (plus CPU scheduling noise)."""
    mesh = dfft.make_mesh(8)
    shape = (16, 16, 8)
    pol_kw = dict(max_batch=4, dtype=CDT)
    # Seeded SATURATED trace: arrival gaps (mean ~0.5 ms) below the
    # per-wave service time even with every compile cache warm, so work
    # is pending across waves in both modes. That is the scenario the
    # scheduler exists for — the flush cadence parks the backlog until
    # the next tick (device idle between ticks), the streaming loop
    # dispatches wave k+1 the moment wave k's admission point opens.
    # (An arrival-LIMITED trace proves nothing: with gaps above the
    # service time both schedulers just wait for traffic.)
    import random
    rng = random.Random(7)
    trace = [(rng.uniform(0.0, 0.001), "rt" if i % 5 == 0 else "bulk")
             for i in range(150)]
    cadence = 0.02

    def drive(streaming: bool) -> dict:
        q = CoalescingQueue(mesh, policy=_rt_policy(),
                            streaming=streaming, **pol_kw)
        if q._wave_stats is None:
            q._wave_stats = serving._WaveStats(q.kind)
        try:
            hs = []
            next_flush = time.perf_counter() + cadence
            for i, (gap, tenant) in enumerate(trace):
                time.sleep(gap)
                if not streaming and time.perf_counter() >= next_flush:
                    q.flush(reason="manual")
                    next_flush = time.perf_counter() + cadence
                hs.append(q.submit(_x(i, shape), tenant=tenant))
            if streaming:
                q.stop(drain=True)
            else:
                q.flush(reason="manual")
            for h in hs:
                h.result(timeout=120)
            return q._wave_stats.snapshot()
        finally:
            q.close()

    drive(True)  # warm: compiles land in the plan/compile caches
    stream_snap = drive(True)
    flush_snap = drive(False)
    s_idle = stream_snap["idle_fraction"]
    f_idle = flush_snap["idle_fraction"]
    assert s_idle is not None and f_idle is not None
    assert s_idle < f_idle, (
        f"streaming idle {s_idle:.3f} not below flush-cadence idle "
        f"{f_idle:.3f}")
    rt = stream_snap["admit_wait"].get("realtime")
    assert rt and rt["n"] > 0
    dur_max = stream_snap["wave_duration_max_s"] or 0.0
    assert rt["p99_s"] <= dur_max + 0.05, (
        f"realtime p99 admit wait {rt['p99_s']:.4f}s exceeds one wave "
        f"duration ({dur_max:.4f}s) beyond scheduling noise")
