"""Pallas fusion executor tier + split-exponent wire codec (PR 19).

Contracts pinned on the 8-way CPU mesh:

1. **The split codec is a first-class registry member** — int16
   mantissas with a shared power-of-two exponent sidecar: half the c64
   wire bytes at ~100x better accuracy than bf16, exact idempotence,
   and the full transport x decomposition accuracy matrix (usable with
   no Pallas anywhere in the plan).
2. **Fusion is a label, parity is exact** — ``fuse=True`` composes the
   ``pallas:fuse`` executor label; fused plans produce outputs
   IDENTICAL to their unfused twins across slab/pencil x the three flat
   transports x K in {1,2} x batch in {None, 3} (on the CPU shard_map
   interpreter the fused sites run the pure-JAX mirrors, bit-identical
   to the unfused chain; on TPU the kernels quantize with the same
   pow2-step math).
3. **Unfused defaults are untouched** — a default plan's lowered HLO is
   byte-identical to an explicit ``fuse=False`` build (the tier is
   invisible until asked for), and ``DFFT_FUSE`` is plan-cache keyed.
4. **Gates are explained, fallbacks are counted** — ineligible graphs
   gate off with machine-readable reasons (``overlap_k`` /
   ``no_wire_codec``) in ``graph.meta["fusion"]`` and the explain
   record; ineligible kernel sites fall back to the mirrors, counted in
   the ``fusion_fallback`` series — never an error.
5. **The kernels themselves are interpret-exercised** — outside
   shard_map the Pallas bodies run in interpret mode: decode+FFT is
   bit-identical to the unfused chain, FFT+encode agrees within each
   codec's measured error (the CI smoke).
6. **Tuner/wisdom discipline** — fused candidates enter the tournament
   only where the fusion pass can activate (real codec, K=1), model
   cheaper than their unfused twins, admit under the one roundtrip-err
   budget, and replay from wisdom with zero timing executions; a budget
   rejection strips the fuse flag with the codec.

NOTE on the filename: this module must collect BEFORE
``test_alltoallv.py`` (alphabetical collection) — the XLA:CPU fft-thunk
poisoning rule; see ``tests/test_a2g_wire.py``.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distributedfft_tpu as dfft
from distributedfft_tpu import regress, tuner
from distributedfft_tpu.ops import pallas_fft, pallas_fuse
from distributedfft_tpu.ops.executors import (
    FUSE_BASES,
    executor_roundtrip_error,
    fused_name,
    split_executor,
    split_fuse,
)
from distributedfft_tpu.parallel.exchange import (
    FLAT_ALGORITHMS,
    WIRE_CODECS,
    WIRE_DTYPES,
    wire_codec,
    wire_itemsize,
    wire_roundtrip_error,
)
from distributedfft_tpu.plan_logic import (
    PlanOptions,
    exchange_payloads,
    fused_model_stages,
    resolve_fuse,
)
from distributedfft_tpu.utils import metrics as m

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 8)
CDT = jnp.complex64
SPLIT_ERR = 1e-4  # split acceptance bound for c64 unit-scale data


def _world(shape=SHAPE, seed=11):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


@pytest.fixture
def wisdom_path(tmp_path, monkeypatch):
    monkeypatch.setenv("DFFT_WISDOM", str(tmp_path / "wisdom.jsonl"))
    monkeypatch.setenv("DFFT_COMPILE_CACHE", str(tmp_path / "xla_cache"))
    return str(tmp_path / "wisdom.jsonl")


# ------------------------------------------------------ the split codec

def test_split_in_registry_menu():
    assert "split" in WIRE_DTYPES and "split" in WIRE_CODECS
    assert WIRE_CODECS["split"].sidecar
    assert wire_itemsize(8, "split") == 4    # c64 -> int16 pair: half
    assert wire_itemsize(16, "split") == 4   # c128 -> int16 pair: quarter


def test_split_roundtrip_bounded_and_idempotent():
    codec = wire_codec("split")
    x = jnp.asarray(_world((8, 12, 5)))
    q, scales = codec.encode(x, tile_axis=1, tiles=4)
    assert q.dtype == jnp.int16 and q.shape == x.shape + (2,)
    # One f32 power-of-two step per (peer tile, component plane).
    assert scales.dtype == jnp.float32
    assert scales.shape == (1, 4, 1, 2)
    s = np.asarray(scales)
    assert np.all(np.exp2(np.round(np.log2(s))) == s)
    y = codec.decode((q, scales), x.dtype, tile_axis=1, tiles=4)
    err = float(np.max(np.abs(np.asarray(y) - np.asarray(x)))
                / np.max(np.abs(np.asarray(x))))
    assert err <= SPLIT_ERR
    # Exact idempotence (power-of-two steps): the staged per-leg
    # decode/re-encode boundary is bit-identical to one cast pair.
    q2, s2 = codec.encode(y, tile_axis=1, tiles=4)
    assert np.array_equal(np.asarray(q2), np.asarray(q))
    assert np.array_equal(np.asarray(s2), np.asarray(scales))


def test_split_beats_bf16_by_orders_of_magnitude():
    e_split = wire_roundtrip_error(np.complex64, "split")
    e_bf16 = wire_roundtrip_error(np.complex64, "bf16")
    assert 0.0 < e_split <= SPLIT_ERR
    assert e_split * 10 < e_bf16  # the headline: finer AND half the bytes


def test_split_payload_wire_factor():
    lp = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT,
                              wire_dtype="split").logic
    entries = exchange_payloads(lp, SHAPE, 8)
    assert entries and all(e["wire_factor"] == 0.5 for e in entries)


@needs_mesh
@pytest.mark.parametrize("alg", FLAT_ALGORITHMS)
@pytest.mark.parametrize("mesh_shape", [8, (2, 4)])
def test_split_accuracy_through_plans(alg, mesh_shape):
    """The standalone-codec acceptance: split works on every transport x
    decomposition with no Pallas anywhere in the plan (executor xla)."""
    mesh = dfft.make_mesh(mesh_shape)
    exact = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, algorithm=alg)
    comp = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, algorithm=alg,
                                wire_dtype="split")
    x = jnp.asarray(_world())
    ref = np.asarray(exact(x))
    err = float(np.max(np.abs(np.asarray(comp(x)) - ref))
                / np.max(np.abs(ref)))
    # x20 slack: two exchanges on the pencil mesh + FFT accumulation.
    assert err <= 20 * SPLIT_ERR, (alg, mesh_shape, err)


# --------------------------------------------- the fuse label algebra

def test_fuse_label_algebra():
    assert split_fuse("pallas:fuse") == ("pallas", True)
    assert split_fuse("pallas:bf16:fuse") == ("pallas:bf16", True)
    assert split_fuse("pallas") == ("pallas", False)
    assert fused_name("pallas", True) == "pallas:fuse"
    assert fused_name("pallas:fuse") == "pallas:fuse"  # idempotent
    with pytest.raises(ValueError, match="fuse"):
        fused_name("pallas:fuse", False)
    with pytest.raises(ValueError, match="fuse"):
        fused_name("xla", True)
    with pytest.raises(ValueError, match="fuse"):
        split_fuse("xla:fuse")
    # The fuse flag is orthogonal to the matmul tier in split_executor.
    assert split_executor("pallas:fuse")[0] == "pallas"
    assert split_executor("pallas:fuse")[1] is None
    assert executor_roundtrip_error("pallas:fuse", np.complex64) == 0.0
    assert "pallas" in FUSE_BASES


def test_fuse_kwarg_composes_label():
    plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, executor="pallas",
                                wire_dtype="split", fuse=True)
    assert plan.executor == "pallas:fuse"
    assert plan.options.fuse is True
    with pytest.raises(ValueError, match="fuse"):
        dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, executor="xla",
                             fuse=True)


def test_resolve_fuse_env(monkeypatch):
    monkeypatch.delenv("DFFT_FUSE", raising=False)
    assert resolve_fuse(None) is False
    assert resolve_fuse(True) is True
    monkeypatch.setenv("DFFT_FUSE", "1")
    assert resolve_fuse(None) is True
    monkeypatch.setenv("DFFT_FUSE", "0")
    assert resolve_fuse(None) is False


# ------------------------------------------- fused-vs-unfused parity

@needs_mesh
@pytest.mark.parametrize("alg,mesh_shape,k,batch", [
    # Covering set over the full product (transport x slab/pencil x
    # K in {1,2} x batch in {None,3}) — every axis value appears on
    # both meshes and both the active (K=1) and gated (K=2) paths,
    # without paying for all 24 combos in tier-1 wall clock.
    ("alltoall", 8, 1, None),
    ("alltoallv", 8, 1, None),
    ("ppermute", 8, 1, None),
    ("alltoall", (2, 4), 1, 3),
    ("ppermute", (2, 4), 1, None),
    ("alltoallv", (2, 4), 2, None),
    ("alltoall", 8, 2, 3),
])
def test_fused_parity_matrix(alg, mesh_shape, k, batch):
    """The acceptance matrix: a fused plan's output is IDENTICAL to its
    unfused twin's on slab/pencil x all three flat transports x K in
    {1,2} x batch in {None, 3}. At K=1 the fusion pass is active (the
    CPU shard_map interpreter runs the bit-identical mirrors); at K=2
    it gates off (``overlap_k``) and the programs coincide."""
    mesh = dfft.make_mesh(mesh_shape)
    kw = dict(dtype=CDT, algorithm=alg, overlap_chunks=k, batch=batch,
              executor="pallas", wire_dtype="split")
    unfused = dfft.plan_dft_c2c_3d(SHAPE, mesh, **kw)
    fused = dfft.plan_dft_c2c_3d(SHAPE, mesh, fuse=True, **kw)
    assert ":fuse" in fused.executor and ":fuse" not in unfused.executor
    shape = ((batch,) + SHAPE) if batch else SHAPE
    x = jnp.asarray(_world(shape))
    assert np.array_equal(np.asarray(fused(x)), np.asarray(unfused(x)))


@needs_mesh
def test_fusion_active_metadata_and_sites():
    mesh = dfft.make_mesh((2, 4))
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, executor="pallas",
                                wire_dtype="split", fuse=True)
    fu = plan.graph.meta.get("fusion")
    assert fu["requested"] and fu["active"] and not fu["reasons"]
    plan(jnp.asarray(_world()))  # sites record at trace time
    fu = plan.graph.meta["fusion"]
    assert fu["sites"], "an active fused plan must record its sites"
    for site in fu["sites"].values():
        assert "sender" in site and "receiver" in site


@needs_mesh
@pytest.mark.parametrize("kw,reason", [
    (dict(wire_dtype="split", overlap_chunks=2), "overlap_k"),
    (dict(), "no_wire_codec"),
])
def test_fusion_gates_reasoned_never_error(kw, reason):
    """Ineligible graphs gate off with a machine-readable reason — the
    plan builds and runs; requesting fusion is never an error."""
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, executor="pallas",
                                fuse=True, **kw)
    fu = plan.graph.meta.get("fusion")
    assert fu["requested"] and not fu["active"]
    assert reason in fu["reasons"]
    x = jnp.asarray(_world())
    ref = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, executor="pallas",
                               **kw)(x)
    assert np.array_equal(np.asarray(plan(x)), np.asarray(ref))


@needs_mesh
def test_explain_surfaces_fusion(monkeypatch):
    monkeypatch.setenv("DFFT_COMPILE_CACHE", "")
    from distributedfft_tpu.explain import format_explain

    mesh = dfft.make_mesh((2, 4))
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT, executor="pallas",
                                wire_dtype="split", fuse=True)
    rec = dfft.explain(plan, iters=2)
    fu = rec["fusion"]
    assert fu["requested"] and fu["active"] and fu["sites"]
    assert "fusion: active" in format_explain(rec)
    gated = dfft.plan_dft_c2c_3d(SHAPE, mesh, dtype=CDT,
                                 executor="pallas", wire_dtype="split",
                                 overlap_chunks=2, fuse=True)
    rec2 = dfft.explain(gated, iters=2)
    assert rec2["fusion"]["requested"] and not rec2["fusion"]["active"]
    assert "overlap_k" in rec2["fusion"]["reasons"]
    assert "fusion: requested but gated off" in format_explain(rec2)


# --------------------------------------------- default-unfused HLO pin

@needs_mesh
@pytest.mark.parametrize("executor", ["xla", "pallas"])
def test_default_hlo_unchanged_by_fusion_tier(monkeypatch, executor):
    """The tier is invisible until asked for: a default plan's lowered
    HLO is byte-identical to an explicit ``fuse=False`` build."""
    monkeypatch.delenv("DFFT_FUSE", raising=False)
    mesh = dfft.make_mesh(8)
    kw = dict(dtype=CDT, executor=executor, wire_dtype="split")
    base = dfft.plan_dft_c2c_3d(SHAPE, mesh, **kw)
    pinned = dfft.plan_dft_c2c_3d(SHAPE, mesh, fuse=False, **kw)
    t_base = base.fn.lower(
        jax.ShapeDtypeStruct(base.in_shape, base.in_dtype)).as_text()
    t_pin = pinned.fn.lower(
        jax.ShapeDtypeStruct(pinned.in_shape, pinned.in_dtype)).as_text()
    assert t_base == t_pin


# ----------------------------- the kernels (interpret-mode CI smoke)

_ENC_BOUNDS = {"bf16": 8e-3, "int8": 2e-2, "split": 2e-4}


@pytest.mark.parametrize("codec_name", pallas_fuse.FUSABLE_CODECS)
@pytest.mark.parametrize("forward", [True, False])
def test_kernel_encode_matches_mirror(codec_name, forward):
    """FFT+encode mega-kernel vs the unfused chain, outside shard_map
    (the Pallas bodies run in interpret mode on CPU): decoded outputs
    agree within the codec's error; the pow2-step sidecars coincide
    exactly."""
    codec = wire_codec(codec_name)
    x = jnp.asarray(_world((8, 64)))
    assert pallas_fuse.kernel_ineligible(
        x.shape, 1, 1, 4, x.dtype, codec_name) is None
    parts = pallas_fuse.fused_fft_encode(
        x, fft_axis=1, forward=forward, tile_axis=1, tiles=4,
        wire_dtype=codec_name)
    y_fft = pallas_fft.fft_along_axis(x, 1, forward=forward)
    ref_parts = codec.encode(y_fft, tile_axis=1, tiles=4)
    got = np.asarray(codec.decode(parts, x.dtype, tile_axis=1, tiles=4))
    ref = np.asarray(codec.decode(ref_parts, x.dtype, tile_axis=1,
                                  tiles=4))
    scale = float(np.max(np.abs(np.asarray(y_fft))))
    assert float(np.max(np.abs(got - ref))) / scale \
        <= _ENC_BOUNDS[codec_name]
    if codec_name != "bf16":
        assert np.array_equal(np.asarray(parts[1]).ravel(),
                              np.asarray(ref_parts[1]).ravel())


@pytest.mark.parametrize("codec_name", pallas_fuse.FUSABLE_CODECS)
@pytest.mark.parametrize("forward", [True, False])
def test_kernel_decode_matches_mirror(codec_name, forward):
    """Decode+FFT mega-kernel vs the unfused chain: the unpack is exact
    (a cast / mantissa * pow2 product), so outputs agree to f32
    roundoff of the identical four-step transform."""
    codec = wire_codec(codec_name)
    y = jnp.asarray(_world((8, 64)))
    parts = codec.encode(y, tile_axis=1, tiles=4)
    got = pallas_fuse.fused_decode_fft(
        parts, y.dtype, fft_axis=1, forward=forward, tile_axis=1,
        tiles=4, wire_dtype=codec_name)
    ref = pallas_fft.fft_along_axis(
        codec.decode(parts, y.dtype, tile_axis=1, tiles=4), 1,
        forward=forward)
    scale = float(np.max(np.abs(np.asarray(ref))))
    assert float(np.max(np.abs(np.asarray(got) - np.asarray(ref)))) \
        / scale <= 1e-5, codec_name


def test_kernel_ineligibility_taxonomy():
    ok = ((8, 64), 1, 1, 4, jnp.complex64, "split")
    assert pallas_fuse.kernel_ineligible(*ok) is None
    cases = [
        (((8, 64), 1, 1, 4, jnp.complex64, "nope"), "codec"),
        (((8, 64), 1, 1, 4, jnp.complex128, "split"), "dtype"),
        (((8, 0), 1, 1, 4, jnp.complex64, "split"), "empty"),
        (((8, 64), 1, 0, 4, jnp.complex64, "split"), "tile_axis"),
        (((8, 24), 1, 1, 4, jnp.complex64, "split"), "length"),
        (((8, 64), 1, 1, 5, jnp.complex64, "split"), "uneven_tiles"),
    ]
    for args, why in cases:
        assert pallas_fuse.kernel_ineligible(*args) == why, args


def test_kernel_fallback_counted_never_error():
    """An ineligible site falls back to the mirror AND counts itself in
    the ``fusion_fallback`` series with site+reason labels."""
    m.metrics_reset()
    m.enable_metrics()
    try:
        x = jnp.asarray(_world((4, 10)).astype(np.complex128))
        parts = pallas_fuse.fused_fft_encode(
            x, fft_axis=1, forward=True, tile_axis=1, tiles=2,
            wire_dtype="split", site="t0")
        codec = wire_codec("split")
        ref = codec.encode(pallas_fft.fft_along_axis(x, 1, forward=True),
                           tile_axis=1, tiles=2)
        assert np.array_equal(np.asarray(parts[0]), np.asarray(ref[0]))
        assert m.counter_total("fusion_fallback") == 1.0
        snap = m.metrics_snapshot()["counters"]["fusion_fallback"]
        assert "reason=dtype" in next(iter(snap))
        assert "site=t0" in next(iter(snap))
    finally:
        m.enable_metrics(False)
        m.metrics_reset()


def test_pallas_fallback_counter_labels():
    """The satellite counter: pallas_fft.record_fallback feeds the
    ``pallas_fallback`` series with axis+reason labels."""
    m.metrics_reset()
    m.enable_metrics()
    try:
        pallas_fft.record_fallback(2, "length")
        assert m.counter_total("pallas_fallback") == 1.0
        snap = m.metrics_snapshot()["counters"]["pallas_fallback"]
        key = next(iter(snap))
        assert "axis=2" in key and "reason=length" in key
    finally:
        m.enable_metrics(False)
        m.metrics_reset()


# ---------------------------------------------------- model pricing

def test_fused_model_moves_fewer_hbm_bytes():
    """The pricing contract: fused stage pairs drop the intermediate
    f32 stream — a fused plan's modeled stage seconds are strictly
    below its unfused twin's wherever fusion is active."""
    from distributedfft_tpu.plan_logic import logic_plan3d, \
        model_stage_seconds

    opts = PlanOptions(decomposition="pencil", algorithm="alltoall",
                       executor="pallas:fuse", wire_dtype="split")
    lp = logic_plan3d((64, 64, 64), 8, opts)
    fused = fused_model_stages(lp, (64, 64, 64), 8)
    assert set(fused) == {"t0", "t1", "t3"}
    kw = dict(hbm_gbps=800.0, wire_gbps=50.0, launch_seconds=2e-6)
    base = model_stage_seconds(lp, (64, 64, 64), 8, **kw)
    disc = model_stage_seconds(lp, (64, 64, 64), 8, fused=fused, **kw)
    for st in fused:
        assert disc[st]["hbm_bytes"] < base[st]["hbm_bytes"], st
        assert disc[st]["seconds"] <= base[st]["seconds"], st
        assert disc[st]["fused"] is True


def test_fused_model_stages_gating():
    from distributedfft_tpu.plan_logic import logic_plan3d

    # No wire codec -> nothing to fuse into the stage kernels.
    lp = logic_plan3d((64, 64, 64), 8, PlanOptions(
        decomposition="pencil", executor="pallas:fuse"))
    assert fused_model_stages(lp, (64, 64, 64), 8) == ()
    # K=2 pipelines through chunked exchanges -> gated.
    lp = logic_plan3d((64, 64, 64), 8, PlanOptions(
        decomposition="pencil", executor="pallas:fuse",
        wire_dtype="split", overlap_chunks=2))
    assert fused_model_stages(lp, (64, 64, 64), 8) == ()
    # An unfused executor never prices the discount.
    lp = logic_plan3d((64, 64, 64), 8, PlanOptions(
        decomposition="pencil", executor="pallas", wire_dtype="split"))
    assert fused_model_stages(lp, (64, 64, 64), 8) == ()


# ------------------------------------------------- tuner integration

def test_enumerate_fused_candidates_only_where_activatable():
    cands = tuner.enumerate_candidates(
        SHAPE, 8, executors=("xla", "pallas"), wire_dtypes=WIRE_DTYPES)
    fused = [c for c in cands if ":fuse" in c.executor]
    assert fused, "fused variants must enter the tournament"
    assert all(c.executor == "pallas:fuse" for c in fused)
    assert all(c.wire_dtype is not None for c in fused)
    assert all(c.overlap_chunks == 1 for c in fused)
    lbl = next(c for c in fused if c.wire_dtype == "split").label
    assert "pallas:fuse" in lbl and lbl.endswith("+wsplit")


def test_fused_candidate_error_is_wire_error():
    cand = tuner.Candidate("slab", "alltoall", "pallas:fuse", 1, "split")
    assert tuner.candidate_roundtrip_error(cand, np.complex64) == \
        wire_roundtrip_error(np.complex64, "split")


def test_fused_candidate_models_cheaper():
    kw = dict(itemsize=8, batch=None, corrected=False)
    for wd in ("bf16", "int8", "split"):
        a = tuner.Candidate("pencil", "alltoall", "pallas", 1, wd)
        b = tuner.Candidate("pencil", "alltoall", "pallas:fuse", 1, wd)
        assert (tuner.model_cost(b, (64, 64, 64), 8, **kw)
                < tuner.model_cost(a, (64, 64, 64), 8, **kw)), wd


def test_prune_budget_governs_fused_candidates():
    cands = tuner.enumerate_candidates(
        SHAPE, 8, executors=("pallas",), wire_dtypes=WIRE_DTYPES)
    tight = tuner.prune_candidates(cands, SHAPE, 8, limit=64,
                                   max_err=1e-9, dtype=np.complex64)
    assert tight and all(":fuse" not in c.executor for c in tight)
    e_split = wire_roundtrip_error(np.complex64, "split")
    loose = tuner.prune_candidates(cands, SHAPE, 8, limit=64,
                                   max_err=e_split * 2,
                                   dtype=np.complex64)
    kept = [c for c in loose if ":fuse" in c.executor]
    assert kept and all(c.wire_dtype == "split" for c in kept)


def _fused_wisdom_entry(wisdom_path, err_budget, compression_err):
    key = tuner.wisdom_key(kind="c2c", shape=SHAPE, dtype=np.complex64,
                           direction=dfft.FORWARD, ndev=8,
                           mesh_dims=None, err_budget=err_budget)
    entry = {
        "schema": tuner.WISDOM_SCHEMA,
        "recorded_at": "2026-08-01T00:00:00", "key": key,
        "winner": {"decomposition": "slab", "algorithm": "alltoall",
                   "executor": "pallas:fuse", "overlap_chunks": 1,
                   "wire_dtype": "split"},
        "seconds": 0.001, "compression_err": compression_err,
    }
    with open(wisdom_path, "a") as f:
        f.write(json.dumps(entry) + "\n")


@needs_mesh
def test_fused_winner_replays_with_zero_timing(wisdom_path):
    dfft.clear_plan_cache()
    m.metrics_reset()
    m.enable_metrics()
    try:
        _fused_wisdom_entry(wisdom_path, err_budget=1e-3,
                            compression_err=3e-5)
        plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, tune="wisdom",
                                    max_roundtrip_err=1e-3)
        assert plan.executor == "pallas:fuse"
        assert plan.options.wire_dtype == "split"
        assert m.counter_total("tune_timing_executions") == 0
    finally:
        m.enable_metrics(False)
        m.metrics_reset()
        dfft.clear_plan_cache()


@needs_mesh
def test_fused_winner_rejected_strips_fuse_with_codec(wisdom_path):
    """Over budget, the codec goes — and the fuse flag with it (an
    exact-wire fused label could only gate off as no_wire_codec)."""
    dfft.clear_plan_cache()
    try:
        _fused_wisdom_entry(wisdom_path, err_budget=1e-9,
                            compression_err=3e-5)
        plan = dfft.plan_dft_c2c_3d(SHAPE, 8, dtype=CDT, tune="wisdom",
                                    max_roundtrip_err=1e-9)
        assert plan.options.wire_dtype is None
        assert plan.executor == "pallas"
    finally:
        dfft.clear_plan_cache()


# --------------------------------------------------- driver / regress tier

def test_regress_fusion_baseline_group():
    base = {"metric": "fft3d_c2c_512_forward_gflops", "value": 100.0,
            "dtype": "complex64", "devices": 8, "decomposition": "slab",
            "backend": "tpu", "device_kind": "TPU v5 lite",
            "wire_dtype": "split"}
    r0 = regress.normalize_bench_line(dict(base), source="test")
    rf = regress.normalize_bench_line(dict(base, fusion=True),
                                      source="test")
    assert rf["config"]["fusion"] is True
    assert regress.group_key(r0) != regress.group_key(rf)


def test_bench_emit_stamps_fusion(capsys):
    import os
    import sys
    TESTS = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(TESTS))
    import bench

    out = bench._emit(16, 1e-4, 1e-7, "pallas:fuse", 8, "slab",
                      {"pallas:fuse": 1e-4}, wire_dtype="split",
                      fusion=True)
    capsys.readouterr()
    assert out["fusion"] is True
    # Unfused rows keep the old schema — no key at all.
    out = bench._emit(16, 1e-4, 1e-7, "pallas", 8, "slab",
                      {"pallas": 1e-4})
    capsys.readouterr()
    assert "fusion" not in out


def test_speed3d_fuse_label():
    import os
    import sys
    TESTS = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(os.path.dirname(TESTS), "benchmarks"))
    from speed3d import _algorithm_label

    assert _algorithm_label("alltoall", 1, wire="split",
                            fuse=True) == "alltoall+wsplit+pfuse"
    assert _algorithm_label("alltoall", 1) == "alltoall"


def test_calibrate_profile_has_fuse_field():
    """The hwprofile schema carries the fused-tier throughput ratio
    (None off-TPU: interpret-mode timing would measure the
    interpreter, not the kernels)."""
    from distributedfft_tpu import calibrate

    prof = {"schema": calibrate.PROFILE_SCHEMA, "fuse_speedup": None}
    txt = calibrate.format_profile(prof)
    assert "fuse speedup" in txt
