"""Numerics observability plane acceptance (docs/OBSERVABILITY.md
"Numerics plane") on the virtual 8-device mesh.

Contracts pinned here:

1. **Disarmed pin** — ``DFFT_SHADOW_RATE`` unset leaves the queue's
   plane ``None`` and the served outputs bit-identical to an armed
   run's primary path (the audit observes, never perturbs).
2. **Shadow-sampled accuracy audit** — an armed queue re-executes
   sampled requests through the memoized exact reference plan;
   realized error lands in per-(plan, tenant) reservoirs against the
   admitted budget. An exact plan audits to realized 0; an int8-wire
   plan fed one hot co-batched request drifts past the slack (the
   shared per-tile pow2 scales zero the cohort's wire data).
3. **Non-finite sentinels with quarantine** — a finite input whose
   transform overflows raises :class:`dfft.NonFiniteResult` on ITS
   handle only, through the retry -> exact-rebuild -> bisect chain;
   cohort members complete bit-correct. A non-finite *input* is
   reported, delivered, never retried.
4. **Adversarial dynamic-range parity** — the block-scaled codecs'
   seeded roundtrip figures are optimistic on heavy-tailed batches:
   int8 realized L2 error lands >10x its seeded figure, and split —
   despite its 15-bit mantissa levels — degrades even further
   *relative to its tiny seeded figure* (shared-exponent physics: the
   absolute contamination error is level-count invariant, so the
   finer codec's headroom is an illusion under contamination). Only
   the elementwise bf16 cast stays within ~2x.
5. **Surfacing** — monitor samples stamp the schema-4 ``numerics``
   block; ``health_from_samples`` fires ``accuracy_drift``/
   ``nonfinite``; fleet merge pools reservoir tails by rank (never
   averaged); mixed schema 2/3/4 fleets merge; ``report numerics
   --gate`` and the regress fold gate on drift.

NOTE on the filename: must collect BEFORE ``test_alltoallv.py``
(alphabetical clean-backend tier; see ``tests/conftest.py``).
"""

import json
import os

import numpy as np
import pytest

import distributedfft_tpu as dfft
from distributedfft_tpu import numerics

DATA = os.path.join(os.path.dirname(__file__), "data", "fleet_skew")


@pytest.fixture(autouse=True)
def _clean_ledger(monkeypatch):
    """Every test starts dark: no env arming, empty ledger, and the
    process-lifetime armed flag restored afterwards so this file
    leaves no trace in later-collected suites."""
    monkeypatch.delenv("DFFT_SHADOW_RATE", raising=False)
    monkeypatch.delenv("DFFT_WIRE_DTYPE", raising=False)
    numerics.reset_numerics()
    armed = numerics._ARMED
    yield
    numerics.reset_numerics()
    numerics._ARMED = armed


def _mk(rng, shape=(8, 8, 8)):
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


# ------------------------------------------------------------- parsing


def test_parse_shadow_rate_forms():
    assert numerics.parse_shadow_rate(None) is None
    assert numerics.parse_shadow_rate("") is None
    assert numerics.parse_shadow_rate("  ") is None
    assert numerics.parse_shadow_rate("0.25") == (0.25, 0)
    assert numerics.parse_shadow_rate("0.1,7") == (0.1, 7)
    assert numerics.parse_shadow_rate("1") == (1.0, 0)
    # Clamped, not rejected: a fat-fingered 1.5 audits everything.
    assert numerics.parse_shadow_rate("1.5") == (1.0, 0)
    assert numerics.parse_shadow_rate("-0.5,3") == (0.0, 3)
    # Malformed raises — a typo must not silently disarm the audit.
    with pytest.raises(ValueError):
        numerics.parse_shadow_rate("lots")
    with pytest.raises(ValueError):
        numerics.parse_shadow_rate("0.5,many")


def test_sampler_deterministic_and_rate_zero_arms_sentinels():
    a = numerics.NumericsPlane(0.5, seed=7)
    b = numerics.NumericsPlane(0.5, seed=7)
    assert [a.pick() for _ in range(64)] == [b.pick() for _ in range(64)]
    c = numerics.NumericsPlane(0.5, seed=8)
    assert ([numerics.NumericsPlane(0.5, seed=7).pick()
             for _ in range(64)]
            != [c.pick() for _ in range(64)])
    # Rate 0 never samples but still arms the plane (sentinels +
    # monitor block).
    z = numerics.NumericsPlane(0.0, seed=0)
    assert not any(z.pick() for _ in range(32))
    assert numerics.numerics_snapshot() is not None


def test_reservoir_bounded_deterministic_tail():
    r = numerics.Reservoir(cap=16, seed=3)
    for i in range(1000):
        r.add(float(i))
    assert r.n == 1000 and len(r.values) == 16
    r2 = numerics.Reservoir(cap=16, seed=3)
    for i in range(1000):
        r2.add(float(i))
    assert r.values == r2.values
    assert r.tail(4) == sorted(r.values)[-4:]
    assert r.quantile(0.5) <= r.quantile(0.99)


def test_judge_bucket_verdict_rules():
    errs = [0.1] * 10
    # Over budget x slack with enough samples -> drifting.
    doc = numerics.judge_bucket(errs, 10, admitted=0.001, floor=1e-6,
                                slack=8.0)
    assert doc["drifting"] and doc["drift_ratio"] > 8.0
    # Same errors, too few samples -> never fires.
    doc = numerics.judge_bucket(errs[:3], 3, admitted=0.001, floor=1e-6,
                                slack=8.0)
    assert not doc["drifting"]
    # Within slack -> quiet.
    doc = numerics.judge_bucket([0.002] * 10, 10, admitted=0.001,
                                floor=1e-6, slack=8.0)
    assert not doc["drifting"]
    # Exact plan (admitted 0): the floor keeps fp wiggle from reading
    # as infinite drift.
    doc = numerics.judge_bucket([1e-7] * 10, 10, admitted=0.0,
                                floor=1.19e-5, slack=8.0)
    assert not doc["drifting"]


def test_realized_error_and_nonfinite_kind():
    y = np.ones(8, np.complex64)
    assert numerics.realized_error(y, y) == 0.0
    assert numerics.realized_error(2 * y, y) == pytest.approx(1.0)
    assert numerics.realized_error(np.full(8, np.nan, np.complex64),
                                   y) == float("inf")
    assert numerics.nonfinite_kind(y) is None
    bad = y.copy()
    bad[0] = np.nan
    assert numerics.nonfinite_kind(bad) == "nan"
    inf = y.copy()
    inf[0] = np.inf
    assert numerics.nonfinite_kind(inf) == "inf"
    assert numerics.nonfinite_kind(np.arange(4)) is None  # ints: clean


# ------------------------------------------------- serving: the audit


def test_disarmed_pin_and_armed_bit_identical(monkeypatch):
    """Unset -> plane None; arming changes nothing about the primary
    outputs (the audit is an observer)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    xs = [_mk(rng) for _ in range(4)]

    q0 = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                              policy="off")
    assert q0._numerics is None
    hs = [q0.submit(jnp.asarray(x)) for x in xs]
    q0.flush()
    base = [np.asarray(h.result(timeout=60)) for h in hs]
    q0.close()
    assert numerics.numerics_snapshot() is None  # plane never armed

    monkeypatch.setenv("DFFT_SHADOW_RATE", "1,3")
    q1 = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                              policy="off")
    assert q1._numerics is not None and q1._numerics.rate == 1.0
    hs = [q1.submit(jnp.asarray(x)) for x in xs]
    q1.flush()
    armed = [np.asarray(h.result(timeout=60)) for h in hs]
    q1.close()
    for a, b in zip(armed, base):
        assert np.array_equal(a, b)

    snap = numerics.numerics_snapshot()
    assert snap is not None
    assert snap["sampled"] == 4 and snap["audited"] == 4
    assert snap["audit_failures"] == 0
    (key, bucket), = snap["plans"].items()
    # Exact plan: wire "exact" in the label, zero realized error.
    assert key.endswith(":exact@-")
    assert bucket["realized_p99"] == 0.0 and not bucket["drifting"]
    assert bucket["n"] == 4


def test_shadow_audit_int8_contamination_drifts(monkeypatch):
    """One hot co-batched request poisons the cohort's shared pow2
    wire scales; the audit realizes O(1) L2 error against an admitted
    budget of ~5e-3 and the bucket judges drifting."""
    import jax.numpy as jnp

    monkeypatch.setenv("DFFT_SHADOW_RATE", "1,3")
    rng = np.random.default_rng(0)
    hot = _mk(rng)
    hot[:4, :4, :4] *= 1e4

    q = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                             policy="off", max_batch=8,
                             wire_dtype="int8")
    hs = [q.submit(jnp.asarray(_mk(rng))) for _ in range(5)]
    hs.append(q.submit(jnp.asarray(hot)))
    q.flush()
    for h in hs:
        h.result(timeout=60)
    q.close()

    snap = numerics.numerics_snapshot()
    assert snap["audited"] == 6
    (key, bucket), = snap["plans"].items()
    assert ":int8@" in key
    assert bucket["n"] >= numerics.MIN_DRIFT_SAMPLES
    assert bucket["admitted_err"] > 0.0
    assert bucket["drifting"]
    assert bucket["drift_ratio"] > numerics.DEFAULT_SLACK
    # The contaminated cohort members read O(1) relative error.
    assert bucket["realized_p99"] > 0.1


def test_shadow_audit_charges_owning_tenant(monkeypatch):
    """Shadow work is charged traffic: each audited request deducts
    one extra transform from its tenant's quota bucket — the
    recovery-work charge discipline (docs/SERVING_QOS.md)."""
    import jax.numpy as jnp

    from distributedfft_tpu.qos import QosPolicy, Tenant

    monkeypatch.setenv("DFFT_SHADOW_RATE", "1,3")
    rng = np.random.default_rng(0)
    # Frozen clock: no refill, so the bucket balance is pure
    # arithmetic.
    pol = QosPolicy([Tenant("acme", rate=1000.0, burst=1000.0)],
                    clock=lambda: 0.0)
    q = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                             policy=pol)
    hs = [q.submit(jnp.asarray(_mk(rng)), tenant="acme")
          for _ in range(3)]
    q.flush()
    for h in hs:
        h.result(timeout=60)
    tokens = pol._buckets["acme"].tokens
    q.close()
    snap = numerics.numerics_snapshot()
    assert snap["audited"] == 3
    (key, bucket), = snap["plans"].items()
    assert key.endswith("@acme") and bucket["tenant"] == "acme"
    # 3 primary admissions + 3 shadow re-execution charges.
    assert tokens == pytest.approx(1000.0 - 6.0)


# --------------------------------------- serving: non-finite sentinels


def test_quarantine_poisoned_request_fails_alone(monkeypatch):
    """Finite input whose FFT overflows: the poisoned handle gets
    NonFiniteResult via the bisect chain; the cohort completes
    bit-correct; output-site sentinel counters advance."""
    import jax.numpy as jnp

    monkeypatch.setenv("DFFT_SHADOW_RATE", "0")  # sentinels only
    rng = np.random.default_rng(1)
    clean = [_mk(rng) for _ in range(3)]
    poison = np.full((8, 8, 8), 3e38 + 0j, np.complex64)
    assert np.all(np.isfinite(poison))

    q0 = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                              policy="off", retry_max=0)
    hs0 = [q0.submit(jnp.asarray(c)) for c in clean]
    q0.flush()
    base = [np.asarray(h.result(timeout=60)) for h in hs0]
    q0.close()
    numerics.reset_numerics()

    q = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                             policy="off", retry_max=0)
    hs = [q.submit(jnp.asarray(c)) for c in clean]
    hp = q.submit(jnp.asarray(poison))
    q.flush()
    outs = [np.asarray(h.result(timeout=60)) for h in hs]
    with pytest.raises(dfft.NonFiniteResult) as ei:
        hp.result(timeout=60)
    q.close()
    assert ei.value.site == "output" and ei.value.kind in ("nan", "inf")
    # Cohort members match the no-poison baseline bit for bit (the
    # bisect chain re-ran them solo, same plan, same math).
    for a, b in zip(outs, base):
        assert np.array_equal(a, b)
    nf = numerics.numerics_snapshot()["nonfinite"]
    # At least one output-site count; the chain re-detects per attempt
    # (attempt -> degraded rebuild -> bisect), so never pin an exact
    # total.
    assert sum(v for k, v in nf.items()
               if k.startswith("output:")) >= 1


def test_nonfinite_input_delivered_never_retried(monkeypatch):
    """A caller's NaN is the caller's: reported at the input site,
    result delivered as-is, no error, no retry chain."""
    import jax.numpy as jnp

    monkeypatch.setenv("DFFT_SHADOW_RATE", "0")
    rng = np.random.default_rng(2)
    bad = _mk(rng)
    bad[0, 0, 0] = np.nan
    q = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                             policy="off", retry_max=0)
    h = q.submit(jnp.asarray(bad))
    q.flush()
    y = np.asarray(h.result(timeout=60))  # no raise
    q.close()
    assert not np.all(np.isfinite(y))
    nf = numerics.numerics_snapshot()["nonfinite"]
    assert nf.get("input:nan", 0) >= 1
    assert not any(k.startswith("output:") for k in nf)


def test_quarantine_through_concurrent_dispatch(monkeypatch):
    """The concurrent fast path routes a poisoned chunk back to the
    per-group chain; the poisoned handle alone fails."""
    import jax.numpy as jnp

    monkeypatch.setenv("DFFT_SHADOW_RATE", "0")
    rng = np.random.default_rng(3)
    q = dfft.CoalescingQueue(dfft.make_mesh(8), dtype=jnp.complex64,
                             policy="off", retry_max=0,
                             concurrent_groups=2)
    hs, shapes = [], [(8, 8, 8), (16, 8, 8)]
    for sh in shapes:
        for j in range(3):
            x = _mk(rng, sh)
            if sh == (8, 8, 8) and j == 1:
                x = np.full(sh, 3e38 + 0j, np.complex64)
            hs.append(q.submit(jnp.asarray(x)))
    q.flush()
    failures = 0
    for h in hs:
        try:
            y = h.result(timeout=60)
            assert bool(np.all(np.isfinite(np.asarray(y))))
        except dfft.NonFiniteResult:
            failures += 1
    q.close()
    assert failures == 1


# ------------------------------------- adversarial dynamic-range parity


def test_adversarial_range_parity_seeded_vs_realized():
    """The seeded roundtrip figures are OPTIMISTIC for the block-scaled
    codecs on heavy-tailed batches. Physics, not tuning: one pow2
    scale per (tile, plane) is shared across the batch axis, so a hot
    request re-scales its cohort's tiles and the absolute
    contamination error is *level-count invariant* — int8 (127 levels)
    and split (32767 levels) land in the same absolute place, which
    reads as a far LARGER multiple of split's much smaller seeded
    figure. The elementwise bf16 cast has no shared state and stays
    within ~2x. (The ISSUE's prior of split staying ~2x is what this
    test falsifies — measured here at >1000x.)"""
    import jax.numpy as jnp

    from distributedfft_tpu.parallel import exchange as ex

    rng = np.random.default_rng(0)
    normals = [_mk(rng) for _ in range(4)]
    hot = _mk(rng)
    hot[:4, :4, :4] *= 1e4
    batch = np.stack(normals + [hot])

    ratios = {}
    for wd in ("bf16", "int8", "split"):
        codec = ex.wire_codec(wd)
        parts = codec.encode(jnp.asarray(batch), tile_axis=1, tiles=8)
        y = np.asarray(codec.decode(parts, np.complex64,
                                    tile_axis=1, tiles=8))
        seeded = ex.wire_roundtrip_error(np.complex64, wd)
        worst = max(
            float(np.linalg.norm(y[i] - batch[i])
                  / np.linalg.norm(batch[i]))
            for i in range(len(normals)))
        ratios[wd] = worst / seeded
    assert ratios["int8"] > 10.0
    assert ratios["bf16"] <= 2.0
    assert ratios["split"] > 10.0  # measured ~1e4x; see docstring


def test_roundtrip_error_sample_kwarg_digest_cache():
    from distributedfft_tpu.ops.executors import executor_roundtrip_error
    from distributedfft_tpu.parallel import exchange as ex

    rng = np.random.default_rng(0)
    x = (rng.standard_normal(4096)
         + 1j * rng.standard_normal(4096)).astype(np.complex64)
    seeded = ex.wire_roundtrip_error(np.complex64, "int8")
    on_x = ex.wire_roundtrip_error(np.complex64, "int8", sample=x)
    # Content-addressed: same bytes -> cache hit -> identical float.
    assert ex.wire_roundtrip_error(np.complex64, "int8",
                                   sample=x.copy()) == on_x
    # A heavy-tailed sample measures worse than the seeded Gaussian.
    hot = x.copy()
    hot[:512] *= 1e4
    on_hot = ex.wire_roundtrip_error(np.complex64, "int8", sample=hot)
    assert on_hot > seeded
    assert on_hot != on_x
    # Executor figures accept samples the same way.
    e = executor_roundtrip_error("matmul", np.complex64,
                                 sample=x[:2048])
    assert e >= 0.0
    assert executor_roundtrip_error(
        "matmul", np.complex64, sample=x[:2048].copy()) == e


# ------------------------------------------------------------ surfacing


def _sample(ts, numerics_block, seq=0):
    return {"schema": 4, "ts": ts, "mono": ts - 950.0, "host": "h",
            "pid": 1, "process_index": 0, "seq": seq,
            "metrics": {"counters": {}},
            "queue": {"kind": "c2c", "depth": 0, "groups": 0,
                      "oldest_pending_age_s": 0.0, "flush_seq": seq,
                      "stalls_total": 0},
            "numerics": numerics_block}


def _block(**kw):
    base = {"schema": 1, "sampled": 10, "audited": 10,
            "audit_failures": 0, "slack": 8.0, "nonfinite": {},
            "plans": {}}
    base.update(kw)
    return base


def test_monitor_sample_stamps_numerics_block(monkeypatch):
    from distributedfft_tpu import monitor as mon

    assert mon.MONITOR_SCHEMA == 4
    m = mon.Monitor(interval_s=60.0)
    doc = m.sample()
    assert doc["schema"] == 4
    assert "numerics" not in doc  # plane dark
    numerics.NumericsPlane(0.0)  # arm
    doc = m.sample()
    assert doc["numerics"]["schema"] == numerics.NUMERICS_SCHEMA
    assert doc["numerics"]["sampled"] == 0


def test_health_from_samples_numerics_verdicts():
    from distributedfft_tpu.monitor import health_from_samples

    drifting_bucket = {
        "plan": "c2c:8x8x8:complex64:fwd:xla:int8", "tenant": None,
        "n": 20, "admitted_err": 0.005, "floor": 1e-5,
        "realized_p50": 0.5, "realized_p99": 0.7, "drift_ratio": 140.0,
        "drifting": True, "errors": [0.5, 0.7]}
    samples = [
        _sample(1000.0, _block(), seq=0),
        _sample(1001.0, _block(
            nonfinite={"output:nan": 2, "input:nan": 1},
            plans={"c2c:8x8x8:complex64:fwd:xla:int8@-":
                   drifting_bucket}), seq=1),
    ]
    h = health_from_samples(samples)
    names = {a["name"]: a for a in h["alerts"]}
    assert h["status"] == "alert"
    assert names["accuracy_drift"]["severity"] == "alert"
    assert names["accuracy_drift"]["drift_ratio"] == 140.0
    assert names["nonfinite"]["severity"] == "alert"
    assert names["nonfinite_input"]["severity"] == "warn"
    assert h["totals"]["shadow_audited"] == 10.0
    assert h["totals"]["nonfinite"] == 3.0
    # Healthy armed ledger: no numerics alerts.
    h0 = health_from_samples([_sample(1000.0, _block(), seq=0)])
    assert not any(a["name"].startswith("nonfinite")
                   or a["name"] == "accuracy_drift"
                   for a in h0["alerts"])


def test_prometheus_rows_for_numerics():
    from distributedfft_tpu.monitor import prometheus_from_sample

    bucket = {"plan": "p", "tenant": "acme", "n": 6,
              "admitted_err": 0.005, "floor": 1e-5,
              "realized_p50": 0.001, "realized_p99": 0.002,
              "drift_ratio": 0.4, "drifting": False,
              "errors": [0.001, 0.002]}
    text = prometheus_from_sample(_sample(1000.0, _block(
        sampled=4, audited=3,
        nonfinite={"output:inf": 1},
        plans={"p@acme": bucket})))
    assert 'dfft_numerics_shadow_sampled_total 4' in text
    assert 'dfft_numerics_shadow_audited_total 3' in text
    assert ('dfft_numerics_nonfinite_total'
            '{site="output",kind="inf"} 1') in text
    assert ('dfft_numerics_drift_ratio'
            '{plan="p",tenant="acme"} 0.4') in text
    assert ('dfft_numerics_realized_err'
            '{plan="p",tenant="acme",quantile="0.99"} 0.002') in text
    # Dark plane: no numerics families at all.
    dark = dict(_sample(1000.0, _block()))
    dark.pop("numerics")
    assert "dfft_numerics" not in prometheus_from_sample(dark)


def test_fleet_merge_numerics_rank_not_average():
    from distributedfft_tpu.fleet import _merge_numerics

    b1 = _block(sampled=5, audited=5,
                nonfinite={"output:nan": 1},
                plans={"p@-": {"plan": "p", "tenant": None, "n": 5,
                               "admitted_err": 0.004, "floor": 1e-5,
                               "realized_p50": 0.001,
                               "realized_p99": 0.001,
                               "drift_ratio": 0.25, "drifting": False,
                               "errors": [0.001] * 5}})
    b2 = _block(sampled=7, audited=7,
                nonfinite={"output:nan": 2, "input:inf": 1},
                plans={"p@-": {"plan": "p", "tenant": None, "n": 7,
                               "admitted_err": 0.005, "floor": 1e-5,
                               "realized_p50": 0.9, "realized_p99": 0.9,
                               "drift_ratio": 180.0, "drifting": True,
                               "errors": [0.9] * 7}})
    merged = _merge_numerics([b1, None, b2, "garbage"])
    assert merged["sampled"] == 12 and merged["audited"] == 12
    assert merged["nonfinite"] == {"output:nan": 3, "input:inf": 1}
    b = merged["plans"]["p@-"]
    assert b["n"] == 12
    assert b["admitted_err"] == 0.005  # max, not sum
    # Rank over the concatenated tails: p99 is an observed 0.9, not an
    # averaged percentile.
    assert b["realized_p99"] == 0.9
    assert b["drifting"]
    assert _merge_numerics([None, "x"]) is None


def test_mixed_schema_fleet_merge_regression():
    """A rolling-restart fleet (schema 2 + 3 + 4 members) merges; the
    numerics block pools from the v4 member alone and the merged doc
    keeps its own schema stamp."""
    from distributedfft_tpu.fleet import (fleet_health, load_fleet,
                                          merge_streams)

    streams = load_fleet(os.path.join(DATA, "mixed_schema"))
    assert {sid.split(":")[1].split("#")[0] for sid in streams} \
        == {"201", "104", "105"}
    merged = merge_streams(streams)
    assert merged and merged[-1]["schema"] == 2
    n = merged[-1]["numerics"]
    assert n["sampled"] == 32
    assert "c2c:8x8x8:complex64:fwd:xla:int8@acme" in n["plans"]
    assert n["nonfinite"] == {"input:nan": 1}
    # Pre-v4 members carry no block; earlier buckets where only they
    # reported still merge (no numerics key or a None is tolerated).
    h = fleet_health(streams)
    assert h["status"] in ("ok", "warn")  # input-site is warn at most


def test_report_numerics_cli(tmp_path, capsys):
    from distributedfft_tpu import report

    # Live ledger path: dark plane -> exit 2 with a hint.
    assert report.main(["numerics"]) == 2
    capsys.readouterr()

    numerics.NumericsPlane(0.0)
    numerics.record_audit("p", "acme", 0.9, 0.005, 1e-5)
    for _ in range(5):
        numerics.record_audit("p", "acme", 0.9, 0.005, 1e-5)
    assert report.main(["numerics"]) == 0
    out = capsys.readouterr().out
    assert "p@acme" in out and "DRIFTING" in out
    # --json emits the raw block; --gate exits 1 while drifting.
    assert report.main(["numerics", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["plans"]["p@acme"]["drifting"]
    assert report.main(["numerics", "--gate"]) == 1
    capsys.readouterr()

    # --dir: merged fleet ledger (the mixed-schema fixture is
    # healthy -> gate 0).
    assert report.main(["numerics", "--dir",
                        os.path.join(DATA, "mixed_schema"),
                        "--gate"]) == 0
    out = capsys.readouterr().out
    assert "int8@acme" in out


def test_bench_and_regress_numerics_fold():
    from distributedfft_tpu.regress import (compare_record,
                                            make_run_record,
                                            regressed_metrics)

    numerics.NumericsPlane(0.0)
    for _ in range(6):
        numerics.record_audit("p", None, 0.9, 0.005, 1e-5)
    numerics.record_nonfinite("output", "nan")
    rec = make_run_record(
        metric="gflops", value=100.0, unit="GF/s",
        config={"shape": "8x8x8"}, device_kind="cpu",
        numerics=numerics.numerics_snapshot())
    assert rec["numerics"]["plans"]["p@-"]["drifting"]
    res = compare_record(rec, [])
    assert res["verdict"] == "no-baseline"
    regressed = regressed_metrics(res)
    assert "numerics:drift:p@-" in regressed
    assert "numerics:nonfinite" in regressed
    # A clean ledger folds nothing.
    clean = make_run_record(metric="gflops", value=100.0, unit="GF/s",
                            config={"shape": "8x8x8"},
                            device_kind="cpu")
    assert regressed_metrics(compare_record(clean, [])) == []


def test_loadgen_spawn_forwards_hot_tail_and_mesh(monkeypatch,
                                                  tmp_path):
    """The parent forwards --hot-tail/--mesh to every worker argv (a
    drill where only the parent knew the flags would silently run
    healthy traffic)."""
    import types

    from distributedfft_tpu import loadgen

    calls = {}

    def fake_popen(argv, **kw):
        calls["argv"] = argv
        return "proc"

    monkeypatch.setattr(loadgen.subprocess, "Popen", fake_popen)
    ns = types.SimpleNamespace(
        seed=1, duration=1.0, rate=10.0, mix="-", shapes="8x8x8",
        dtypes="complex64", ops="fft", max_batch=8, max_wait=0.0,
        flush_every=0.05, hot_tail=0.3, mesh=8, linger=0.0,
        streaming=False, qos="", fault_rank=0, interval=0.25)
    assert loadgen._spawn(ns, 1, str(tmp_path)) == "proc"
    argv = calls["argv"]
    assert argv[argv.index("--hot-tail") + 1] == "0.3"
    assert argv[argv.index("--mesh") + 1] == "8"


def test_loadgen_worker_hot_tail_reports_drift(tmp_path, monkeypatch,
                                               capsys):
    """One in-process worker with the shadow plane armed, int8 wire,
    and --hot-tail: its stats line carries shadow_sampled and a
    drift_ratio past the slack (the CI drift drill's physics)."""
    from distributedfft_tpu import loadgen

    monkeypatch.setenv("DFFT_MONITOR_DIR", str(tmp_path))
    monkeypatch.setenv("DFFT_MONITOR", "0.05")
    monkeypatch.setenv("DFFT_SHADOW_RATE", "1,7")
    monkeypatch.setenv("DFFT_WIRE_DTYPE", "int8")
    monkeypatch.delenv("DFFT_QOS", raising=False)
    monkeypatch.delenv("DFFT_FAULT_INJECT", raising=False)
    rc = loadgen.main(["--worker", "--rank", "0", "--seed", "3",
                       "--duration", "1", "--rate", "80",
                       "--shapes", "8x8x8", "--ops", "fft",
                       "--flush-every", "0.2", "--mesh", "8",
                       "--hot-tail", "0.4"])
    assert rc == 0
    stats = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["shadow_sampled"] > 0
    assert stats["drift_ratio"] > numerics.DEFAULT_SLACK
