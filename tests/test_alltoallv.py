"""Masked/uneven exchange (the MPI_Alltoallv analog) and payload accounting.

The reference moves uneven payloads with exact per-peer count tables
(``TransInfo``, ``fft_mpi_3d_api.cpp:84-133``; heFFTe
``reshape3d_alltoallv``, ``src/heffte_reshape3d.cpp:375``). The TPU path
ships true split-axis slices via ``lax.ragged_all_to_all`` ("alltoallv");
on the CPU test backend the op is unimplemented and the exchange mirrors
through the bit-identical ceil-padded dense path, so these tests pin
plan-level correctness and the payload arithmetic.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import distributedfft_tpu as dfft
from distributedfft_tpu import native
from distributedfft_tpu.plan_logic import exchange_payloads

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

CDT = jnp.complex128


def _world(shape, seed=5):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape) + 1j * rng.standard_normal(shape)


@pytest.mark.parametrize("shape", [(16, 16, 16), (10, 9, 7), (8, 15, 5)])
def test_alltoallv_slab_matches_reference(shape):
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, algorithm="alltoallv")
    x = _world(shape)
    ref = np.fft.fftn(x)
    y = np.asarray(plan(jnp.asarray(x)))
    assert np.max(np.abs(y - ref)) / np.max(np.abs(ref)) < 1e-11


@pytest.mark.parametrize("shape", [(16, 12, 20), (10, 9, 7)])
def test_alltoallv_pencil_roundtrip(shape):
    mesh = dfft.make_mesh((2, 4))
    fwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, algorithm="alltoallv")
    bwd = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, algorithm="alltoallv",
                               direction=dfft.BACKWARD)
    x = _world(shape)
    ref = np.fft.fftn(x)
    y = fwd(jnp.asarray(x))
    assert np.max(np.abs(np.asarray(y) - ref)) / np.max(np.abs(ref)) < 1e-11
    r = np.asarray(bwd(y))
    assert np.max(np.abs(r - x)) / np.max(np.abs(x)) < 1e-11


def test_alltoallv_r2c_uneven():
    shape = (10, 9, 12)
    mesh = dfft.make_mesh(8)
    fwd = dfft.plan_dft_r2c_3d(shape, mesh, dtype=CDT, algorithm="alltoallv")
    x = np.random.default_rng(8).standard_normal(shape)
    y = np.asarray(fwd(jnp.asarray(x)))
    ref = np.fft.rfftn(x)
    assert np.max(np.abs(y - ref)) / np.max(np.abs(ref)) < 1e-11


def test_alltoallv_absorbed_layout():
    """The masked exchange composes with reshape-minimized chains."""
    shape = (10, 9, 7)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(
        shape, mesh, dtype=CDT, algorithm="alltoallv",
        in_spec=P(None, "slab", None),
    )
    assert plan.logic.slab_axes == (1, 0)
    x = _world(shape)
    ref = np.fft.fftn(x)
    y = np.asarray(plan(jnp.asarray(x)))
    assert np.max(np.abs(y - ref)) / np.max(np.abs(ref)) < 1e-11


# ------------------------------------------------------ payload accounting

def test_payload_accounting_slab_uneven():
    """512^3-style arithmetic at test scale: the 7-way uneven case the
    VERDICT asked to quantify. true <= alltoallv <= alltoall, with
    alltoallv exactly stripping the split-axis padding."""
    shape = (10, 9, 7)
    plan = dfft.plan_dft_c2c_3d(shape, 7, dtype=CDT,
                                options=None, decomposition="slab")
    lp = plan.logic
    p = lp.mesh.devices.size
    [e] = exchange_payloads(lp, shape, 16)
    assert e["true_bytes"] <= e["alltoallv_bytes"] <= e["alltoall_bytes"]
    a_in, a_out = lp.slab_axes
    pad = lambda n: p * (-(-n // p))
    f = (p - 1) / p
    assert e["alltoallv_bytes"] == int(
        pad(shape[a_in]) * shape[a_out] * shape[3 - a_in - a_out] * f * 16
    )
    assert e["alltoall_bytes"] == int(
        pad(shape[a_in]) * pad(shape[a_out]) * shape[3 - a_in - a_out] * f * 16
    )
    # Consistency with the exact native count tables: total true elements
    # sent by all ranks == world volume (minus nothing; factor applies to
    # off-diagonal only, so compare the full-table sum).
    total = sum(
        sum(native.exchange_table(shape[a_in], shape[a_out],
                                  shape[3 - a_in - a_out], p, r)[0])
        for r in range(p)
    )
    assert total == shape[0] * shape[1] * shape[2]


def test_payload_accounting_in_plan_info():
    shape = (10, 9, 7)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT, algorithm="alltoallv")
    info = dfft.plan_info(plan)
    assert "alltoallv" in info and "true" in info
    assert "exchange counts[rank0]" in info
    # Pencil plans report both exchanges.
    pp = dfft.plan_dft_c2c_3d(shape, dfft.make_mesh((2, 4)), dtype=CDT)
    pinfo = dfft.plan_info(pp)
    assert "exchange t2a" in pinfo and "exchange t2b" in pinfo


def test_payload_accounting_even_no_overhead():
    shape = (16, 16, 16)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(shape, mesh, dtype=CDT)
    [e] = exchange_payloads(plan.logic, shape, 16)
    assert e["true_bytes"] == e["alltoallv_bytes"] == e["alltoall_bytes"]


def test_a2av_table_footprint_sublinear():
    """The a2av index-map operands are RLE (z-runs), so their per-device
    bytes scale with the overlap CROSS-SECTION, not the brick volume —
    the bound that makes campaign-size brick plans constructible
    (heFFTe ships O(P) count/offset tables, src/heffte_reshape3d.cpp:375;
    the per-element alternative here would be 4 bytes per brick element).
    Volume grows 8x between the two worlds; the tables may grow ~4x
    (cross-section) but must stay far below the volume factor."""
    from distributedfft_tpu.geometry import Box3, split_world
    from distributedfft_tpu.parallel.bricks import (
        _a2av_tables, pad_shape_for)

    def table_bytes(n):
        world = Box3((0, 0, 0), (n, n, n))
        in_boxes = split_world(world, (2, 2, 2))   # grid bricks
        out_boxes = split_world(world, (8, 1, 1))  # slab bricks
        t = _a2av_tables(in_boxes, out_boxes, pad_shape_for(in_boxes),
                         pad_shape_for(out_boxes))
        # element maps this replaces: ~4 bytes per send+recv element
        elem_bytes = 8 * max(t.send_cap, t.recv_cap)
        return t.table_bytes_per_device, elem_bytes

    small, small_elem = table_bytes(32)
    big, big_elem = table_bytes(64)
    assert big <= 5 * small, (small, big)          # ~cross-section growth
    assert big * 10 <= big_elem, (big, big_elem)   # far below element maps


def test_a2av_table_bytes_in_plan_info():
    from distributedfft_tpu.geometry import Box3, split_world

    shape = (16, 12, 10)
    mesh = dfft.make_mesh(8)
    world = Box3((0, 0, 0), shape)
    boxes = split_world(world, (2, 2, 2))
    plan = dfft.plan_brick_dft_c2c_3d(shape, mesh, boxes, boxes,
                                      algorithm="alltoallv", dtype=CDT)
    info = dfft.plan_info(plan)
    assert "index tables" in info and "RLE" in info
