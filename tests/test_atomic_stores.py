"""Concurrent-writer safety of the append-only stores (satellite).

The wisdom JSONL, hwprofile JSON, and ``history.jsonl`` writes route
through ``utils/atomicio.py`` (one ``O_APPEND`` ``os.write`` per append;
temp+rename for whole-document replace). The multi-process test below
proves the contract the discipline exists for: N processes hammering
one file concurrently produce exactly N*M parseable lines — no torn or
interleaved lines for the lenient loaders to drop.

No jax anywhere: the worker loads ``atomicio.py`` by file path (the
module is stdlib-only by design — the same loadable-without-the-package
rule as ``regress.py``).
"""

import json
import os
import subprocess
import sys

TESTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS)
AIO = os.path.join(REPO, "distributedfft_tpu", "utils", "atomicio.py")

_WORKER = """
import importlib.util, json, sys
spec = importlib.util.spec_from_file_location("aio", sys.argv[1])
aio = importlib.util.module_from_spec(spec)
spec.loader.exec_module(aio)
path, wid, n = sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
# ~300-byte lines: long enough that a buffered writer WOULD split them
# across stdio flushes, so interleaving would be visible if it existed.
for i in range(n):
    aio.append_line(path, json.dumps(
        {"writer": wid, "i": i, "pad": "x" * 256}))
"""


def _load_aio():
    import importlib.util

    spec = importlib.util.spec_from_file_location("_aio_test", AIO)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_multiprocess_appends_never_tear_or_interleave(tmp_path):
    """4 concurrent processes x 250 lines each: every line parses,
    every (writer, i) pair arrives exactly once, and each writer's own
    lines appear in order (O_APPEND preserves per-writer ordering)."""
    path = str(tmp_path / "store.jsonl")
    nproc, nlines = 4, 250
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, AIO, path, str(w), str(nlines)],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        for w in range(nproc)
    ]
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == nproc * nlines
    seen: dict[int, list[int]] = {w: [] for w in range(nproc)}
    for ln in lines:
        obj = json.loads(ln)  # a torn line would fail to parse
        assert obj["pad"] == "x" * 256  # and a spliced one to validate
        seen[obj["writer"]].append(obj["i"])
    for w, idxs in seen.items():
        assert idxs == sorted(idxs), f"writer {w} lines out of order"
        assert idxs == list(range(nlines))


def test_append_lines_batches_and_adds_newlines(tmp_path):
    aio = _load_aio()
    path = str(tmp_path / "x.jsonl")
    aio.append_lines(path, ["a", "b\n"])
    aio.append_line(path, "c")
    aio.append_lines(path, [])  # no-op, no file touch needed
    with open(path) as f:
        assert f.read() == "a\nb\nc\n"


def test_replace_file_is_atomic_and_total(tmp_path):
    aio = _load_aio()
    path = str(tmp_path / "doc.json")
    aio.replace_file(path, "{\"v\": 1}\n")
    aio.replace_file(path, "{\"v\": 2}\n")
    with open(path) as f:
        assert json.load(f) == {"v": 2}
    # No temp litter left behind.
    assert os.listdir(tmp_path) == ["doc.json"]


def test_wisdom_and_history_routes_go_through_one_write(tmp_path):
    """The stores' own writers produce whole lines through the helper:
    record_wisdom and append_records each yield parseable JSONL that
    load_wisdom/load_history read back with zero drops."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_regress_test", os.path.join(REPO, "distributedfft_tpu",
                                      "regress.py"))
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)
    hist = str(tmp_path / "history.jsonl")
    recs = [regress.make_run_record(metric="m", value=float(i),
                                    config={"devices": 8})
            for i in range(5)]
    regress.append_records(recs, hist)
    loaded, dropped = regress.load_history(hist)
    assert dropped == 0 and len(loaded) == 5
