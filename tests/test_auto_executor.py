"""executor="auto" plan-time autotuning — the setFFTPlans plan-and-pick
discipline (the reference builds hipfft/rocfft/templateFFT plans side by
side and selects one, ``fft_mpi_3d_api.cpp:318-429``)."""

import os

import numpy as np
import pytest

import jax

import distributedfft_tpu as dfft
from distributedfft_tpu import testing as tu

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)


def test_auto_picks_a_candidate_and_is_correct():
    shape = (16, 12, 8)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(shape, mesh, executor="auto",
                                dtype=np.complex64)
    assert plan.executor in ("xla", "xla_minor", "pallas", "matmul")
    x = tu.make_world_data(shape, dtype=np.complex64)
    got = np.asarray(plan(x))
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 5e-4


def test_auto_respects_env_candidates(monkeypatch):
    monkeypatch.setenv("DFFT_AUTO_EXECUTORS", "matmul")
    plan = dfft.plan_dft_c2c_3d((8, 8, 8), dfft.make_mesh(8),
                                executor="auto", dtype=np.complex64)
    assert plan.executor == "matmul"


def test_auto_r2c():
    shape = (8, 8, 16)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_r2c_3d(shape, mesh, executor="auto")
    x = tu.make_world_data(shape, dtype=np.float64)
    got = np.asarray(plan(x))
    want = np.fft.rfftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 1e-10


def test_auto_with_donation_rebuilds_winner():
    shape = (8, 8, 8)
    mesh = dfft.make_mesh(8)
    plan = dfft.plan_dft_c2c_3d(shape, mesh, executor="auto", donate=True,
                                dtype=np.complex64)
    assert plan.options.donate is True
    x = dfft.alloc_local(plan, fill=tu.make_world_data(shape,
                                                       dtype=np.complex64))
    y = plan(x)  # consumes x
    assert y.shape == shape


def test_plan_compile_chains():
    plan = dfft.plan_dft_c2c_3d((8, 8, 8), dfft.make_mesh(8),
                                dtype=np.complex64)
    assert plan.compile() is plan
    x = tu.make_world_data((8, 8, 8), dtype=np.complex64)
    got = np.asarray(plan(x))
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.abs(want).max() < 5e-4


def test_auto_rejects_recursive_candidate(monkeypatch):
    """'auto' in the candidate list cannot recurse into nested tournaments."""
    monkeypatch.setenv("DFFT_AUTO_EXECUTORS", "auto, xla")
    plan = dfft.plan_dft_c2c_3d((8, 8, 8), dfft.make_mesh(8),
                                executor="auto", dtype=np.complex64)
    assert plan.executor == "xla"
