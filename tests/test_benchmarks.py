"""Benchmark-CLI smoke tests (the role of heFFTe's benchmark builds in CI:
the harness itself must keep working, ``.jenkins:22-35``). Runs the CLIs
in-process with tiny problems on the test fixture's CPU mesh."""

import os
import re
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import batch_bench  # noqa: E402
import speed3d  # noqa: E402


def test_speed3d_c2c_slab(capsys, tmp_path):
    csv = str(tmp_path / "s.csv")
    speed3d.main(["c2c", "double", "16", "16", "16",
                  "-ndev", "4", "-slabs", "-iters", "1", "-csv", csv])
    out = capsys.readouterr().out
    assert "size: 16 16 16, ranks: 4" in out
    assert "gflops:" in out
    assert len(open(csv).read().splitlines()) == 2


def test_speed3d_r2c_pencil_ppermute(capsys):
    speed3d.main(["r2c", "double", "16", "16", "16",
                  "-ndev", "8", "-pencils", "-p2p_pl", "-iters", "1"])
    out = capsys.readouterr().out
    assert "decomposition: pencil" in out
    assert "algorithm: ppermute" in out


def test_speed3d_staged(capsys):
    speed3d.main(["c2c", "double", "16", "16", "16",
                  "-ndev", "4", "-slabs", "-staged", "-iters", "1"])
    out = capsys.readouterr().out
    assert "t0_fft_yz" in out and "t2_all_to_all" in out and "t3_fft_x" in out


@pytest.mark.slow
def test_speed3d_staged_pencil(capsys):
    # Slow tier: the pencil staged builder is covered directly in
    # test_staged.py; the CLI -staged glue by test_speed3d_staged.
    speed3d.main(["c2c", "double", "16", "16", "16",
                  "-ndev", "8", "-pencils", "-staged", "-iters", "1"])
    out = capsys.readouterr().out
    assert "t2a_exchange_col" in out and "t2b_exchange_row" in out


@pytest.mark.slow
def test_speed3d_staged_r2c(capsys):
    # Slow tier: the r2c staged builder is covered directly in
    # test_staged.py; the CLI -staged glue by test_speed3d_staged.
    speed3d.main(["r2c", "double", "16", "16", "16",
                  "-ndev", "8", "-slabs", "-staged", "-iters", "1"])
    out = capsys.readouterr().out
    assert "t0_r2c_zy" in out and "t2_exchange" in out and "t3_fft_x" in out


@pytest.mark.slow
def test_speed3d_dd_tier(capsys, tmp_path):
    """The dd precision tier through the speed3d CLI: slab mesh, result
    block with a double-tier roundtrip error, CSV row. Slow tier: the
    CLI glue is thin over plan_dd_dft_c2c_3d (whose surfaces the default
    gate executes) and the dd compile dominates suite wall time."""
    csv = str(tmp_path / "dd.csv")
    speed3d.main(["c2c", "dd", "16", "16", "16",
                  "-ndev", "4", "-iters", "1", "-csv", csv])
    out = capsys.readouterr().out
    assert "precision: dd" in out and "decomposition: slab" in out
    assert "max error:" in out
    err = float(out.split("max error:")[1].split()[0])
    assert err < 1e-11
    rows = open(csv).read().splitlines()
    assert rows[1].startswith("c2c,dd,16")


def test_speed3d_r2c_axis_flag(capsys):
    """-r2c_axis routes heFFTe's r2c_direction through the CLI; the
    roundtrip verify is axis-agnostic."""
    speed3d.main(["r2c", "double", "8", "16", "8",
                  "-ndev", "8", "-slabs", "-iters", "1", "-r2c_axis", "1"])
    out = capsys.readouterr().out
    assert "(16, 8, 8)" not in out  # caller convention preserved
    assert "-> (8, 9, 8)" in out and "max error:" in out
    err = float(out.split("max error:")[1].split()[0])
    assert err < 1e-11


def test_speed3d_r2c_axis_rejects_c2c_and_dd():
    with pytest.raises(SystemExit, match="r2c path only"):
        speed3d.main(["c2c", "double", "8", "8", "8", "-ndev", "4",
                      "-iters", "1", "-r2c_axis", "0"])
    with pytest.raises(SystemExit, match="r2c path only"):
        speed3d.main(["r2c", "dd", "8", "8", "8", "-ndev", "4",
                      "-iters", "1", "-r2c_axis", "0"])


def test_speed3d_dd_rejects_r2c():
    with pytest.raises(SystemExit, match="c2c only"):
        speed3d.main(["r2c", "dd", "16", "16", "16", "-ndev", "4",
                      "-iters", "1"])


def test_speed3d_a2av(capsys):
    speed3d.main(["c2c", "double", "10", "9", "7",
                  "-ndev", "8", "-slabs", "-a2av", "-iters", "1"])
    out = capsys.readouterr().out
    assert "algorithm: alltoallv" in out


def test_batch_bench_1d(capsys, tmp_path):
    csv = str(tmp_path / "b.csv")
    batch_bench.main(["1d", "-radix", "5", "-total", "1000",
                      "-iters", "1", "-csv", csv])
    out = capsys.readouterr().out
    assert "1D n=" in out
    rows = open(csv).read().splitlines()
    assert rows[0].startswith("n0,")
    assert len(rows) >= 3  # 5, 25, 125, 625


def test_batch_bench_2d(capsys, tmp_path):
    csv = str(tmp_path / "b2.csv")
    batch_bench.main(["2d", "-sizes", "8", "16", "-batch", "2",
                      "-iters", "1", "-csv", csv])
    out = capsys.readouterr().out
    assert "2D 8x8" in out and "2D 16x16" in out


def test_bench_executor_menu(tmp_path):
    """bench.py's candidate runner: plans, verifies, and times one executor
    (tiny shape); a broken executor name raises instead of silently passing."""
    sys.path.insert(0, REPO)
    import bench
    import jax.numpy as jnp

    import distributedfft_tpu as dfft

    mesh = dfft.make_mesh(4)
    secs, err, plan = bench.bench_executor((16, 16, 16), mesh,
                                           jnp.complex64, "xla")
    assert secs > 0 and err < 1e-3 and plan.decomposition == "slab"
    with pytest.raises(ValueError):
        bench.bench_executor((16, 16, 16), mesh, jnp.complex64, "nope")
    # Precision-suffixed candidates now plan the TIERED executor label
    # (plan-scoped precision — ops/executors.py tier grammar; the lax
    # spelling canonicalizes) and never touch the env knobs.
    before = os.environ.get("DFFT_MM_PRECISION")
    secs, err, plan = bench.bench_executor((16, 16, 16), mesh,
                                           jnp.complex64, "matmul:high")
    assert secs > 0 and err < 1e-3 and plan.executor == "matmul:f32"
    assert plan.options.mm_precision == "f32"
    assert os.environ.get("DFFT_MM_PRECISION") == before
    # Multi-suffix candidates (tier + complex-product mode) compose;
    # the env knobs stay untouched (no mutation to restore).
    before_cm = os.environ.get("DFFT_MM_COMPLEX")
    secs, err, plan = bench.bench_executor((16, 16, 16), mesh,
                                           jnp.complex64,
                                           "matmul:high:gauss")
    assert secs > 0 and err < 1e-3 and plan.executor == "matmul:f32:gauss"
    assert plan.options.mm_complex == "gauss"
    assert os.environ.get("DFFT_MM_PRECISION") == before
    assert os.environ.get("DFFT_MM_COMPLEX") == before_cm
    with pytest.raises(ValueError, match="suffix"):
        bench.bench_executor((16, 16, 16), mesh, jnp.complex64,
                             "matmul:fast")


@pytest.mark.parametrize("script", [
    "bench.py", "speed3d.py", "batch_bench.py", "tune_pallas.py",
    "record_baseline.py", "hw_smoke.py", "diag_r2c.py",
    "hw_campaign.sh", "hw_campaign2.sh", "campaign2_loop.sh",
])
def test_campaign_scripts_importable(script):
    """Every script the hardware campaign invokes must at least import /
    parse — an import-time error discovered on a live tunnel burns that
    step's slice of a rare window. Shell scripts get bash -n; Python
    scripts get an import (none runs main at import: __main__-guarded)."""
    import subprocess

    d = REPO if script == "bench.py" else os.path.join(REPO, "benchmarks")
    path = os.path.join(d, script)
    if script.endswith(".sh"):
        rc = subprocess.run(["bash", "-n", path],
                            capture_output=True, text=True, timeout=30)
        assert rc.returncode == 0, rc.stderr
        return
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    rc = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {os.path.dirname(path)!r}); "
         f"import {script[:-3]}"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert rc.returncode == 0, rc.stderr[-800:]


def test_bench_last_recorded_tpu_line():
    """The CPU-insurance line's interpretability metadata: the newest
    committed backend:"tpu" bench line from an earlier campaign window,
    clearly labeled as recorded (never measured by this run)."""
    sys.path.insert(0, REPO)
    import bench

    rec = bench._last_recorded_tpu_line()
    # The repo ships at least one recorded window
    # (benchmarks/results/hw_bench_campaign.json, 2026-07-31). The filter
    # accepts ANY *bench*.json artifact, so pruning the campaign file
    # would still surface bench_tpu_v5e1_*.json provenance.
    assert rec is not None
    assert "NOT measured" in rec["note"]
    assert rec["source"].startswith("benchmarks/results/")
    assert "bench" in rec["source"] and rec["source"].endswith(".json")
    assert rec["value"] > 0 and rec["unit"] == "GFlops/s"


def test_hw_smoke_step_orchestration(tmp_path):
    """hw_smoke's per-step parent: each step runs in its own process
    group (one poisoned compile cannot cascade, as it did in the first
    r5 window), an unknown --step is rejected, and rows land in the
    per-backend CSV (redirected here — the repo copies are hardware
    evidence and must never see test rows)."""
    import subprocess

    script = os.path.join(REPO, "benchmarks", "hw_smoke.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               DFFT_SMOKE_CSV_DIR=str(tmp_path))
    rc = subprocess.run(
        [sys.executable, script, "--step", "nope", "--timeout", "60"],
        env=env, capture_output=True, text=True, timeout=90,
    )
    assert rc.returncode == 2 and "unknown step" in rc.stderr

    rc = subprocess.run(
        [sys.executable, script, "--step", "step_brick_orders",
         "--timeout", "240"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert rc.returncode == 0, rc.stderr[-800:]
    # devices depends on ambient XLA_FLAGS (1 bare, 8 under the suite's
    # virtual mesh) -> p1 or p2
    assert re.search(r"brick_orders_p[12]: ok", rc.stdout)
    rows = (tmp_path / "hw_smoke_cpu.csv").read_text()
    assert re.search(r"brick_orders_p[12],cpu,ok", rows)


def test_bench_donated_chain():
    """Donated-execution timing chains x <- plan(x) (c2c is
    shape-preserving), so the consumed buffer is never reused."""
    sys.path.insert(0, REPO)
    import bench
    import jax.numpy as jnp

    import distributedfft_tpu as dfft

    mesh = dfft.make_mesh(4)
    secs = bench.bench_donated((16, 16, 16), mesh, jnp.complex64, "xla")
    assert secs > 0
    # The winner's donation pass must also work for suffixed candidates
    # (trace under the scoped env, donated ping-pong after).
    secs = bench.bench_donated((16, 16, 16), mesh, jnp.complex64,
                               "matmul:high:gauss")
    assert secs > 0


def test_speed3d_profile_flag(tmp_path):
    d = str(tmp_path / "prof")
    speed3d.main(["c2c", "double", "16", "16", "16",
                  "-ndev", "4", "-slabs", "-iters", "1", "-profile", d])
    assert os.path.isdir(d) and os.listdir(d)


def test_record_baseline_quick(tmp_path):
    """The BASELINE.json sweep recorder (manuscript-CSV parity artifact,
    templateFFT/csv/*.csv role) runs end-to-end and records ok rows."""
    import subprocess
    import sys

    out = tmp_path / "sweep.csv"
    hist = tmp_path / "history.jsonl"
    proc = subprocess.run(
        [sys.executable, "benchmarks/record_baseline.py", "--quick",
         "--sizes", "16", "--out", str(out), "--executors", "xla"],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        # History redirected: the repo store is hardware evidence and
        # must never see test rows.
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": "",
             "DFFT_BENCH_HISTORY": str(hist)},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = out.read_text().strip().splitlines()
    assert rows[0].startswith("run,nx,ny,nz,kind")
    assert len(rows) >= 3  # header + c2c + r2c
    assert all(r.endswith(",ok") for r in rows[1:]), rows
    # Every ok row also appended a run record to the history store.
    import json

    recs = [json.loads(ln) for ln in
            hist.read_text().strip().splitlines()]
    assert len(recs) == len(rows) - 1
    assert all(r["source"] == "record_baseline.py" for r in recs)
    assert all(r["metric"].startswith("speed3d_") for r in recs)
    assert all(r["config"]["executor"] == "xla" for r in recs)


def test_speed3d_bricks(capsys, tmp_path):
    csv = str(tmp_path / "b.csv")
    # nz=12 over 8 devices: uneven ceil-split bricks, so the pad-masking
    # init and the uneven ring path are genuinely exercised.
    speed3d.main(["c2c", "single", "24", "16", "12",
                  "-bricks", "-ndev", "8", "-iters", "1", "-csv", csv])
    out = capsys.readouterr().out
    assert "brick edge in->chain" in out
    # The CLI-side pad-masking init must not corrupt the roundtrip: parse
    # the printed error and gate it numerically.
    err = float([ln for ln in out.splitlines()
                 if ln.startswith("max error")][0].split(":")[1])
    assert err < 1e-3
    row = open(csv).read().splitlines()[1]
    assert ",bricks-" in row


def test_speed3d_ingrid_outgrid(capsys):
    """heFFTe -ingrid/-outgrid parity: user processor grids become plan
    in/out layouts and roundtrip correctly."""
    speed3d.main(["c2c", "single", "16", "16", "16",
                  "-ingrid", "1", "4", "2", "-outgrid", "4", "2", "1",
                  "-iters", "1"])
    out = capsys.readouterr().out
    assert "in sharding:  PartitionSpec(None, 'row', 'col')" in out
    assert "out sharding: PartitionSpec('row', 'col', None)" in out
    err = float([ln for ln in out.splitlines()
                 if ln.startswith("max error")][0].split(":")[1])
    assert err < 1e-3
