"""Brick-in/brick-out plans: arbitrary mesh-expressible input/output
layouts around the canonical pipeline (heFFTe's arbitrary-box capability,
``heffte_fft3d.h:105-115``; the planner prepends/appends reshapes the way
``plan_pencil_reshapes`` does for non-pencil input)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import distributedfft_tpu as dfft

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)

SHAPE = (16, 16, 16)


def _world():
    rng = np.random.default_rng(31)
    return rng.standard_normal(SHAPE) + 1j * rng.standard_normal(SHAPE)


def _check(plan, x, ref):
    y = np.asarray(plan(jnp.asarray(x)))
    assert np.max(np.abs(y - ref)) / np.max(np.abs(ref)) < 1e-11


def test_brick_in_pencil_mesh():
    mesh = dfft.make_mesh((2, 4))
    x = _world()
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, in_spec=P(None, "row", "col"))
    assert plan.in_sharding.spec == P(None, "row", "col")
    _check(plan, x, np.fft.fftn(x))


def test_brick_out_slab_mesh():
    mesh = dfft.make_mesh(8)
    x = _world()
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, out_spec=P(None, None, "slab"))
    y = plan(jnp.asarray(x))
    assert y.sharding.is_equivalent_to(
        NamedSharding(mesh, P(None, None, "slab")), y.ndim
    )
    ref = np.fft.fftn(x)
    assert np.max(np.abs(np.asarray(y) - ref)) / np.max(np.abs(ref)) < 1e-11


def test_brick_both_and_roundtrip():
    mesh = dfft.make_mesh((2, 4))
    x = _world()
    spec_in = P("row", None, "col")   # brick over axes 0 and 2
    spec_out = P("col", "row", None)  # different brick on output
    fwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, in_spec=spec_in, out_spec=spec_out)
    bwd = dfft.plan_dft_c2c_3d(SHAPE, mesh, direction=dfft.BACKWARD,
                               in_spec=spec_out, out_spec=spec_in)
    _check(fwd, x, np.fft.fftn(x))
    r = np.asarray(bwd(fwd(jnp.asarray(x))))
    assert np.max(np.abs(r - x)) / np.max(np.abs(x)) < 1e-11


def test_layout_boxes_cover_world():
    mesh = dfft.make_mesh((2, 4))
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, in_spec=P("row", None, "col"))
    from distributedfft_tpu import geometry as geo

    world = geo.world_box(SHAPE)
    assert geo.world_complete(plan.in_boxes, world)
    assert len(plan.in_boxes) == 8


def test_brick_io_r2c_roundtrip():
    mesh = dfft.make_mesh((2, 4))
    rng = np.random.default_rng(33)
    x = rng.standard_normal(SHAPE)
    spec_in = P("row", None, "col")
    fwd = dfft.plan_dft_r2c_3d(SHAPE, mesh, in_spec=spec_in)
    bwd = dfft.plan_dft_c2r_3d(SHAPE, mesh, out_spec=spec_in)
    y = fwd(jnp.asarray(x))
    assert y.shape == (16, 16, 9)
    ref = np.fft.rfftn(x)
    assert np.max(np.abs(np.asarray(y) - ref)) / np.max(np.abs(ref)) < 1e-11
    r = np.asarray(bwd(y))
    assert np.max(np.abs(r - x)) < 1e-11
    # The half-spectrum boxes cover the shrunk world.
    from distributedfft_tpu import geometry as geo

    assert geo.world_complete(fwd.in_boxes, geo.world_box(SHAPE))


def test_layout_boxes_follow_mesh_device_order():
    """Boxes are indexed by mesh.devices.flat position, also when the spec
    names mesh axes out of mesh-axis order."""
    mesh = dfft.make_mesh((2, 4))  # axes ('row', 'col')
    plan = dfft.plan_dft_c2c_3d(SHAPE, mesh, out_spec=P("col", "row", None))
    # device flat index 1 = (row 0, col 1): dim0 block = col = 1 of 4,
    # dim1 block = row = 0 of 2.
    b = plan.out_boxes[1]
    assert b.low == (4, 0, 0) and b.high == (8, 8, 16)
    # device flat index 4 = (row 1, col 0): dim0 block 0, dim1 block 1.
    b = plan.out_boxes[4]
    assert b.low == (0, 8, 0) and b.high == (4, 16, 16)


def test_overlong_spec_rejected():
    mesh = dfft.make_mesh(8)
    with pytest.raises(ValueError):
        dfft.plan_dft_c2c_3d(SHAPE, mesh,
                             in_spec=P(None, None, None, "slab"))


def test_spec_without_mesh_rejected():
    with pytest.raises(ValueError):
        dfft.plan_dft_c2c_3d(SHAPE, None, in_spec=P(None, None, None))


def test_misspelled_axis_rejected_clearly():
    mesh = dfft.make_mesh((2, 4))
    with pytest.raises(ValueError, match="unknown mesh axis"):
        dfft.plan_dft_c2c_3d(SHAPE, mesh, in_spec=P("rwo", None, None))
