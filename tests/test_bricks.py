"""Arbitrary-box reshape engine (overlap maps + ppermute ring).

Pattern follows heFFTe's ``test_reshape3d.cpp``: seeded world array,
scatter into the input decomposition, reshape on device, gather, compare
against the world — for box lists a PartitionSpec cannot express (uneven
slabs, non-grid split trees, axis-swapped pencils).
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import distributedfft_tpu as dfft
from distributedfft_tpu.geometry import (
    Box3, ceil_splits, make_pencils, make_slabs, split_world, world_box,
)
from distributedfft_tpu.parallel.bricks import (
    gather_bricks, plan_brick_reshape, scatter_bricks,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh"
)


def _mesh() -> Mesh:
    return dfft.make_mesh(8)


def _roundtrip(world_shape, in_boxes, out_boxes, dtype=np.complex64):
    mesh = _mesh()
    rng = np.random.default_rng(7)
    x = rng.standard_normal(world_shape).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        x = x + 1j * rng.standard_normal(world_shape).astype(dtype)
    fn, spec = plan_brick_reshape(mesh, in_boxes, out_boxes)
    stack = scatter_bricks(x, in_boxes, spec.in_pad, mesh=mesh)
    got = gather_bricks(fn(stack), out_boxes)
    np.testing.assert_array_equal(got, x)
    return spec


def test_slabs_to_pencils_even():
    w = world_box((16, 16, 16))
    _roundtrip((16, 16, 16), make_slabs(w, 8), make_pencils(w, (2, 4), 2))


def test_uneven_slabs_to_uneven_slabs_other_axis():
    # 13 not divisible by 8: ceil-split tails, including an empty brick.
    w = world_box((13, 16, 12))
    ins = make_slabs(w, 8, axis=0, rule=ceil_splits)
    outs = make_slabs(w, 8, axis=1)
    _roundtrip((13, 16, 12), ins, outs)


def test_pencils_axis_swap():
    w = world_box((8, 12, 16))
    ins = make_pencils(w, (4, 2), 0)
    outs = make_pencils(w, (2, 4), 2)
    _roundtrip((8, 12, 16), ins, outs)


def test_non_grid_split_tree():
    """A decomposition no PartitionSpec can express: recursive unequal
    bisection (the general brick case of heFFTe's C API)."""
    w = world_box((12, 10, 8))

    def bisect(box, depth):
        if depth == 0:
            return [box]
        ax = max(range(3), key=lambda d: box.shape[d])
        lo, hi = box.low[ax], box.high[ax]
        cut = lo + max(1, (hi - lo) * 2 // 5)  # deliberately unequal
        la = list(box.low), list(box.high)
        la[1][ax] = cut
        lb = list(box.low), list(box.high)
        lb[0][ax] = cut
        a = Box3(tuple(la[0]), tuple(la[1]))
        b = Box3(tuple(lb[0]), tuple(lb[1]))
        return bisect(a, depth - 1) + bisect(b, depth - 1)

    ins = bisect(w, 3)
    outs = make_slabs(w, 8, rule=ceil_splits)
    assert len(ins) == 8
    spec = _roundtrip((12, 10, 8), ins, outs)
    # The wire ships padded blocks; the true payload is what the exact
    # overlap tables say. Both accountings must be populated.
    assert 0 < spec.payload_elems <= spec.wire_elems


def test_real_dtype():
    w = world_box((8, 8, 8))
    _roundtrip((8, 8, 8), make_slabs(w, 8), make_pencils(w, (4, 2), 1),
               dtype=np.float32)


def test_identity_no_steps():
    """in == out: only the shift-0 local copy survives the overlap scan."""
    w = world_box((8, 8, 8))
    boxes = make_slabs(w, 8)
    mesh = _mesh()
    fn, spec = plan_brick_reshape(mesh, boxes, boxes)
    assert [st.shift for st in spec.steps] == [0]
    assert spec.payload_elems == 0
    rng = np.random.default_rng(3)
    x = rng.standard_normal((8, 8, 8)).astype(np.float32)
    got = gather_bricks(fn(scatter_bricks(x, boxes, mesh=mesh)), boxes)
    np.testing.assert_array_equal(got, x)


def test_incomplete_boxes_rejected():
    w = world_box((8, 8, 8))
    boxes = make_slabs(w, 8)
    bad = list(boxes)
    bad[3] = Box3((3, 0, 0), (3, 8, 8))  # empty: world not covered
    with pytest.raises(ValueError, match="partition the world"):
        plan_brick_reshape(_mesh(), bad, boxes)


def test_wrong_count_rejected():
    w = world_box((8, 8, 8))
    with pytest.raises(ValueError, match="one in/out box per device"):
        plan_brick_reshape(_mesh(), make_slabs(w, 4), make_slabs(w, 4))


# --------------------------------------------------- brick-I/O FFT plans

def _brick_plan_roundtrip(shape, mesh, in_boxes, out_boxes, **kw):
    """plan_brick_dft_c2c_3d forward vs np.fft.fftn, through scatter/gather."""
    rng = np.random.default_rng(11)
    x = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex64)
    plan = dfft.plan_brick_dft_c2c_3d(
        shape, mesh, in_boxes, out_boxes, dtype=np.complex64, **kw)
    stack = scatter_bricks(x, in_boxes, plan.in_shape[1:], mesh=mesh)
    got = gather_bricks(plan(stack), out_boxes)
    want = np.fft.fftn(x)
    np.testing.assert_allclose(got, want, rtol=0, atol=2e-3 * np.abs(want).max())
    return plan


def test_brick_plan_slab_mesh():
    shape = (16, 16, 16)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    ins = make_pencils(w, (4, 2), 2)       # z-pencils in
    outs = make_slabs(w, 8, axis=1)        # Y-slabs out
    plan = _brick_plan_roundtrip(shape, mesh, ins, outs)
    assert plan.decomposition == "slab"
    assert plan.in_shape == (8, 4, 8, 16)


def test_brick_plan_pencil_mesh_nongrid_boxes():
    shape = (16, 12, 8)
    mesh = dfft.make_mesh((2, 4))
    w = world_box(shape)

    # an uneven, non-grid partition: unequal X cut, then Y quarters
    ins = []
    for x0, x1 in ((0, 6), (6, 16)):
        for y0, y1 in ((0, 3), (3, 6), (6, 9), (9, 12)):
            ins.append(Box3((x0, y0, 0), (x1, y1, 8)))
    outs = make_slabs(w, 8, axis=0, rule=ceil_splits)
    plan = _brick_plan_roundtrip(shape, mesh, ins, outs)
    assert plan.decomposition == "pencil"


def test_brick_plan_backward_roundtrip():
    shape = (8, 8, 8)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    ins = make_slabs(w, 8, axis=2)
    outs = make_slabs(w, 8, axis=2)
    fwd = dfft.plan_brick_dft_c2c_3d(shape, mesh, ins, outs,
                                     dtype=np.complex64)
    bwd = dfft.plan_brick_dft_c2c_3d(shape, mesh, outs, ins,
                                     direction=dfft.BACKWARD,
                                     dtype=np.complex64)
    rng = np.random.default_rng(5)
    x = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex64)
    stack = scatter_bricks(x, ins, mesh=mesh)
    back = gather_bricks(bwd(fwd(stack)), ins)
    np.testing.assert_allclose(back, x, atol=1e-4)


def test_brick_plan_info_accounting():
    """plan_info surfaces the overlap-ring payload/wire accounting for both
    brick edges (the outputPlanInfo/TransInfo table role)."""
    from distributedfft_tpu.utils.trace import plan_info

    shape = (16, 16, 16)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    plan = dfft.plan_brick_dft_c2c_3d(
        shape, mesh, make_pencils(w, (4, 2), 2), make_slabs(w, 8, axis=1),
        dtype=np.complex64)
    info = plan_info(plan)
    assert "brick edge in->chain" in info
    assert "brick edge chain->out" in info
    assert "payload" in info and "wire" in info


@pytest.mark.parametrize(
    "seed",
    [0, pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow),
     pytest.param(3, marks=pytest.mark.slow)])
def test_random_partition_fuzz(seed):
    """Property test: ANY pair of random non-grid box partitions round-trips
    exactly through the overlap-map ring (heFFTe's shuffled-boxes testing
    idea, test_fft3d.h:155-167, applied to the reshape engine)."""
    rng = np.random.default_rng(100 + seed)

    def random_partition(world, parts):
        boxes = [world]
        while len(boxes) < parts:
            # split the largest-volume box on a random axis at a random cut
            i = max(range(len(boxes)), key=lambda k: boxes[k].size)
            b = boxes.pop(i)
            axes = [d for d in range(3) if b.shape[d] >= 2]
            ax = int(rng.choice(axes))
            lo, hi = b.low[ax], b.high[ax]
            cut = int(rng.integers(lo + 1, hi))
            la, ha = list(b.low), list(b.high)
            lb, hb = list(b.low), list(b.high)
            ha[ax], lb[ax] = cut, cut
            boxes += [Box3(tuple(la), tuple(ha)), Box3(tuple(lb), tuple(hb))]
        return boxes

    shape = tuple(int(v) for v in rng.integers(6, 14, size=3))
    w = world_box(shape)
    ins = random_partition(w, 8)
    outs = random_partition(w, 8)
    _roundtrip(shape, ins, outs)


def test_wire_ratio_bounded_realistic_uneven():
    """The padded ring's wire/payload blowup stays under a documented
    bound for realistic uneven decompositions (ceil-split tails, axis
    swaps) — the perf-parity risk vs heFFTe's exact alltoallv counts
    (``src/heffte_reshape3d.cpp:375``). The bound here is 8 = P: the
    ring's inherent uniform-block factor; the shape-skew component on
    top of it is eliminated by the step splitter."""
    from distributedfft_tpu.parallel.bricks import plan_brick_reshape

    mesh = _mesh()
    cases = []
    w = world_box((13, 16, 12))  # ceil-split tails incl. an empty brick
    cases.append((make_slabs(w, 8, axis=0, rule=ceil_splits),
                  make_slabs(w, 8, axis=1)))
    w2 = world_box((12, 10, 8))
    cases.append((make_pencils(w2, (4, 2), 0), make_pencils(w2, (2, 4), 2)))
    w3 = world_box((16, 16, 16))
    cases.append((make_slabs(w3, 8), make_pencils(w3, (2, 4), 2)))
    for ins, outs in cases:
        _, spec = plan_brick_reshape(mesh, ins, outs)
        assert spec.wire_ratio <= len(ins), (
            f"wire/payload {spec.wire_ratio:.2f} exceeds P for {ins[0]}...")


def test_shape_skew_step_split():
    """A shift pairing orthogonally-shaped overlaps — (thin-z) vs (thin-y)
    slabs against x-slabs — would inflate the joint block to the product
    of per-dim maxes; the splitter must (a) ship strictly less than the
    unsplit ring would and (b) keep the reshape exact."""
    from distributedfft_tpu.parallel.bricks import plan_brick_reshape

    n = 16
    w = world_box((n, n, n))
    ins = make_slabs(w, 8, axis=0)  # (2, 16, 16) x-slabs
    # Out: two thin plates (z and y) + the bulk split into 6 — overlap
    # shapes against the x-slabs are (2,16,1), (2,1,15), (2,~5,15): skewed.
    outs = [
        Box3((0, 0, 0), (n, n, 1)),      # thin-z plate
        Box3((0, 0, 1), (n, 1, n)),      # thin-y plate
    ]
    rest = Box3((0, 1, 1), (n, n, n))
    for b in make_slabs(rest, 6, axis=1, rule=ceil_splits):
        outs.append(b)
    fn, spec = plan_brick_reshape(mesh := _mesh(), ins, outs)

    # (a) the splitter engaged: some shift appears in >1 step, and the
    # shipped wire is below the naive per-shift joint-block accounting.
    shifts = [st.shift for st in spec.steps if st.shift]
    assert len(shifts) > len(set(shifts)), "expected split ring steps"
    naive = {}
    for st in spec.steps:
        if not st.shift:
            continue
        joint = naive.setdefault(st.shift, np.zeros(3, np.int64))
        np.maximum(joint, st.true_size.max(axis=0), out=joint)
    naive_wire = sum(int(np.prod(j)) * 8 for j in naive.values())
    assert spec.wire_elems < naive_wire

    # (b) exactness through the split ring.
    rng = np.random.default_rng(31)
    x = rng.standard_normal((n, n, n)).astype(np.float32)
    stack = scatter_bricks(x, ins, spec.in_pad, mesh=mesh)
    got = gather_bricks(fn(stack), outs)
    np.testing.assert_array_equal(got, x)


@pytest.mark.parametrize("case", ["slabs", "uneven", "nongrid"])
def test_a2av_exact_transport(case):
    """The exact-count (ragged alltoallv) brick transport reproduces the
    ring's results bit-for-bit on even, uneven, and non-grid partitions,
    with wire == payload (the heFFTe alltoallv discipline the padded
    ring can only approximate)."""
    from distributedfft_tpu.parallel.bricks import plan_brick_reshape

    mesh = _mesh()
    if case == "slabs":
        w = world_box((16, 16, 16))
        ins, outs = make_slabs(w, 8), make_pencils(w, (2, 4), 2)
    elif case == "uneven":
        w = world_box((13, 16, 12))
        ins = make_slabs(w, 8, axis=0, rule=ceil_splits)
        outs = make_slabs(w, 8, axis=1)
    else:
        w = world_box((12, 10, 8))
        ins = make_pencils(w, (4, 2), 0)
        outs = make_slabs(w, 8, rule=ceil_splits)

    rng = np.random.default_rng(83)
    shape = w.shape
    x = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    fn, spec = plan_brick_reshape(mesh, ins, outs, algorithm="a2av")
    assert spec.algorithm == "a2av"
    assert spec.wire_ratio == 1.0  # exact counts: wire == payload
    stack = scatter_bricks(x, ins, spec.in_pad, mesh=mesh)
    got = gather_bricks(fn(stack), outs)
    np.testing.assert_array_equal(got, x)


def test_brick_plan_a2av_edges():
    """algorithm='alltoallv' on a brick-I/O plan routes both edges over
    the exact-count transport (wire == payload in plan_info terms)."""
    shape = (16, 16, 16)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    ins = make_pencils(w, (4, 2), 2)
    outs = make_slabs(w, 8, axis=1)
    plan = dfft.plan_brick_dft_c2c_3d(
        shape, mesh, ins, outs, dtype=np.complex64, algorithm="alltoallv")
    for bs in plan.brick_edges:
        assert bs.algorithm == "a2av" and bs.wire_ratio == 1.0
    rng = np.random.default_rng(89)
    x = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    stack = scatter_bricks(x, ins, plan.in_shape[1:], mesh=mesh)
    got = gather_bricks(plan(stack), outs)
    want = np.fft.fftn(x)
    np.testing.assert_allclose(got, want, rtol=0,
                               atol=2e-3 * np.abs(want).max())


def test_a2av_bad_algorithm_rejected():
    from distributedfft_tpu.parallel.bricks import plan_brick_reshape

    w = world_box((8, 8, 8))
    boxes = make_slabs(w, 8)
    with pytest.raises(ValueError, match="ring|a2av"):
        plan_brick_reshape(_mesh(), boxes, boxes, algorithm="nope")


def test_brick_r2c_roundtrip_matches_numpy():
    """Brick-I/O r2c: real bricks in, shrunk-world complex bricks out
    (heFFTe fft3d_r2c brick tier), inverse back to the real bricks."""
    shape = (16, 12, 16)
    cshape = (16, 12, 9)
    mesh = dfft.make_mesh(8)
    w, cw = world_box(shape), world_box(cshape)
    ins = make_slabs(w, 8, axis=1, rule=ceil_splits)
    outs = make_slabs(cw, 8, axis=0)
    fwd = dfft.plan_brick_dft_r2c_3d(shape, mesh, ins, outs)
    bwd = dfft.plan_brick_dft_c2r_3d(shape, mesh, outs, ins)
    assert fwd.real and fwd.in_shape[0] == 8

    rng = np.random.default_rng(17)
    x = rng.standard_normal(shape)
    stack = scatter_bricks(x.astype(fwd.in_dtype), ins, fwd.in_shape[1:],
                           mesh=mesh)
    got = gather_bricks(fwd(stack), outs)
    want = np.fft.rfftn(x)
    np.testing.assert_allclose(got, want, atol=1e-9 * np.abs(want).max())
    back = gather_bricks(bwd(fwd(stack)), ins)
    np.testing.assert_allclose(back, x, atol=1e-11)


def test_brick_r2c_world_mismatch_rejected():
    shape = (16, 12, 16)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    ins = make_slabs(w, 8, axis=1)
    with pytest.raises(ValueError, match="world"):
        # out boxes must partition the SHRUNK complex world, not the real one
        dfft.plan_brick_dft_r2c_3d(shape, mesh, ins, make_slabs(w, 8, axis=0))


def test_brick_plan_scale_and_donate():
    """Scale enum applies to brick-stack outputs (pads stay zero), and
    donated brick plans consume their input stack."""
    from distributedfft_tpu.ops.executors import Scale

    shape = (8, 8, 8)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    ins = make_slabs(w, 8, axis=0)
    outs = make_slabs(w, 8, axis=2)
    fwd = dfft.plan_brick_dft_c2c_3d(shape, mesh, ins, outs,
                                     dtype=np.complex64)
    rng = np.random.default_rng(23)
    x = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
        np.complex64)
    stack = scatter_bricks(x, ins, fwd.in_shape[1:], mesh=mesh)
    y_full = gather_bricks(fwd(stack, scale=Scale.FULL), outs)
    np.testing.assert_allclose(y_full, np.fft.fftn(x) / x.size, atol=1e-5)

    dplan = dfft.plan_brick_dft_c2c_3d(shape, mesh, ins, outs,
                                       dtype=np.complex64, donate=True)
    stack2 = scatter_bricks(x, ins, dplan.in_shape[1:], mesh=mesh)
    y = dplan(stack2)
    np.testing.assert_allclose(gather_bricks(y, outs), np.fft.fftn(x),
                               atol=1e-3)
    assert stack2.is_deleted()  # donation consumed the input stack


# ------------------------------------------------- per-box storage order

def test_box3_order_field():
    b = Box3((0, 0, 0), (4, 6, 8), (2, 0, 1))
    assert b.storage_shape == (8, 4, 6)
    assert b.r2c(2).order == (2, 0, 1)
    # equality ignores order, like heffte box3d::operator==
    assert b == Box3((0, 0, 0), (4, 6, 8))
    with pytest.raises(ValueError):
        Box3((0, 0, 0), (4, 4, 4), (0, 0, 2))


def test_scatter_gather_bricks_with_orders():
    shape = (8, 6, 4)
    w = world_box(shape)
    boxes = [b.with_order(o) for b, o in zip(
        make_slabs(w, 4, axis=0),
        [(0, 1, 2), (2, 1, 0), (1, 2, 0), (0, 2, 1)])]
    rng = np.random.default_rng(7)
    x = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    stack = scatter_bricks(x, boxes)
    # each brick travels transposed by its order
    b1 = boxes[1]
    s1 = b1.storage_shape
    np.testing.assert_array_equal(
        stack[1, :s1[0], :s1[1], :s1[2]],
        x[b1.slices()].transpose(b1.order))
    np.testing.assert_array_equal(gather_bricks(stack, boxes), x)


@pytest.mark.parametrize("algorithm", ["alltoall", "alltoallv"])
def test_brick_plan_shuffled_orders(algorithm):
    """heFFTe's shuffled-order fft3d test (test_fft3d.h:155-167 with
    box3d::order variations): per-rank bricks whose buffers are stored in
    non-canonical axis orders, different on input and output."""
    shape = (16, 12, 8)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    in_orders = [(0, 1, 2), (2, 1, 0), (1, 0, 2), (2, 0, 1),
                 (0, 2, 1), (1, 2, 0), (0, 1, 2), (2, 1, 0)]
    out_orders = list(reversed(in_orders))
    ins = [b.with_order(o) for b, o in zip(
        make_pencils(w, (4, 2), 2), in_orders)]
    outs = [b.with_order(o) for b, o in zip(
        make_slabs(w, 8, axis=1, rule=ceil_splits), out_orders)]
    rng = np.random.default_rng(11)
    x = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    plan = dfft.plan_brick_dft_c2c_3d(
        shape, mesh, ins, outs, dtype=np.complex64, algorithm=algorithm)
    assert plan.in_shape == (8,) + tuple(
        max(b.storage_shape[d] for b in ins) for d in range(3))
    stack = scatter_bricks(x, ins, mesh=mesh)
    got = gather_bricks(plan(stack), outs)
    ref = np.fft.fftn(x)
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-3


def test_brick_r2c_shuffled_orders_roundtrip():
    shape = (8, 12, 16)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    cw = world_box((8, 12, 16 // 2 + 1))
    ins = [b.with_order((1, 2, 0)) for b in make_slabs(w, 8, axis=0)]
    outs = [b.with_order((2, 0, 1)) for b in
            make_slabs(cw, 8, axis=0, rule=ceil_splits)]
    rng = np.random.default_rng(13)
    x = rng.standard_normal(shape).astype(np.float32)
    fwd = dfft.plan_brick_dft_r2c_3d(shape, mesh, ins, outs,
                                     dtype=np.complex64)
    bwd = dfft.plan_brick_dft_c2r_3d(shape, mesh, outs, ins,
                                     dtype=np.complex64)
    stack = scatter_bricks(x, ins, mesh=mesh)
    y = fwd(stack)
    ref = np.fft.rfftn(x)
    got = gather_bricks(y, outs)
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-3
    back = gather_bricks(bwd(y), ins)
    np.testing.assert_allclose(back, x, atol=1e-4)


@pytest.mark.parametrize("axis", [0, 1])
def test_brick_r2c_axis_choice(axis):
    """Brick r2c with a non-default halved axis (heFFTe r2c_direction
    through the brick tier), plus storage orders on the complex side."""
    shape = (8, 12, 16)
    mesh = dfft.make_mesh(8)
    w = world_box(shape)
    half = list(shape)
    half[axis] = shape[axis] // 2 + 1
    cw = world_box(tuple(half))
    ins = make_slabs(w, 8, axis=2, rule=ceil_splits)
    outs = [b.with_order((1, 0, 2)) for b in
            make_slabs(cw, 8, axis=2, rule=ceil_splits)]
    rng = np.random.default_rng(19)
    x = rng.standard_normal(shape).astype(np.float32)
    fwd = dfft.plan_brick_dft_r2c_3d(shape, mesh, ins, outs,
                                     r2c_axis=axis, dtype=np.complex64)
    assert fwd.r2c_axis == axis
    bwd = dfft.plan_brick_dft_c2r_3d(shape, mesh, outs, ins,
                                     r2c_axis=axis, dtype=np.complex64)
    stack = scatter_bricks(x, ins, mesh=mesh)
    y = fwd(stack)
    got = gather_bricks(y, outs)
    ref = np.fft.rfftn(x.astype(np.float64), axes=(
        [a for a in range(3) if a != axis] + [axis]))
    # numpy rfftn halves the LAST axis of `axes`; transform order of the
    # other two axes does not change the result
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-3
    back = gather_bricks(bwd(y), ins)
    np.testing.assert_allclose(back, x, atol=1e-4)


def test_brick_bad_algorithm_rejected_dd_tier():
    """dd brick planners validate algorithm like the c64 tier."""
    shape = (8, 8, 8)
    mesh = dfft.make_mesh(4)
    w = world_box(shape)
    ins = make_slabs(w, 4, axis=0)
    outs = make_slabs(w, 4, axis=2)
    with pytest.raises(ValueError, match="unknown algorithm"):
        dfft.plan_dd_brick_dft_c2c_3d(shape, mesh, ins, outs,
                                      algorithm="a2av")


# ------------------------------------------------ single-device degenerate

def test_brick_plan_single_device_orders():
    """heFFTe brick plans run on one rank (self communicator): the world is
    one (possibly order-permuted) brick per side; no collectives. Same
    ``[1, *pad]`` stack convention as the distributed tier."""
    shape = (12, 10, 8)
    w = world_box(shape)
    ins = [w.with_order((2, 0, 1))]
    outs = [w.with_order((1, 2, 0))]
    rng = np.random.default_rng(23)
    x = (rng.standard_normal(shape)
         + 1j * rng.standard_normal(shape)).astype(np.complex64)
    plan = dfft.plan_brick_dft_c2c_3d(shape, None, ins, outs,
                                      dtype=np.complex64)
    assert plan.mesh is None
    assert plan.in_shape == (1,) + ins[0].storage_shape
    stack = scatter_bricks(x, ins)
    got = gather_bricks(plan(stack), outs)
    ref = np.fft.fftn(x)
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-3
    bwd = dfft.plan_brick_dft_c2c_3d(shape, None, outs, ins,
                                     direction=dfft.BACKWARD,
                                     dtype=np.complex64)
    back = gather_bricks(bwd(plan(stack)), ins)
    np.testing.assert_allclose(back, x, atol=1e-4)


def test_brick_plan_single_device_r2c():
    shape = (8, 12, 10)
    w = world_box(shape)
    cw = world_box((8, 12, 6))  # N//2+1 along axis 2
    ins = [w.with_order((1, 0, 2))]
    outs = [cw.with_order((2, 1, 0))]
    rng = np.random.default_rng(29)
    x = rng.standard_normal(shape).astype(np.float32)
    fwd = dfft.plan_brick_dft_r2c_3d(shape, None, ins, outs,
                                     dtype=np.complex64)
    got = gather_bricks(fwd(scatter_bricks(x, ins)), outs)
    ref = np.fft.rfftn(x.astype(np.float64))
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-3


def test_brick_plan_single_device_dd():
    from distributedfft_tpu.ops import ddfft

    shape = (8, 8, 8)
    w = world_box(shape)
    ins = [w.with_order((2, 1, 0))]
    outs = [w]
    rng = np.random.default_rng(31)
    x = rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
    plan = dfft.plan_dd_brick_dft_c2c_3d(shape, None, ins, outs)
    assert plan.mesh is None
    hi, lo = ddfft.dd_from_host(x)
    sh = scatter_bricks(np.asarray(hi), ins)
    sl = scatter_bricks(np.asarray(lo), ins)
    yh, yl = plan(sh, sl)
    got = gather_bricks(np.asarray(ddfft.dd_to_host(yh, yl)), outs)
    ref = np.fft.fftn(x)
    assert np.max(np.abs(got - ref)) / np.max(np.abs(ref)) < 1e-11


def test_brick_plan_single_device_multiple_boxes_rejected():
    shape = (8, 8, 8)
    w = world_box(shape)
    ins = make_slabs(w, 2, axis=0)
    with pytest.raises(ValueError, match="one box per side"):
        dfft.plan_brick_dft_c2c_3d(shape, None, ins, [w],
                                   dtype=np.complex64)


# --------------------------------------------------- batched brick edges

def _batched_edges_case():
    """Uneven slabs (ragged overlap maps) — the geometry that exercises
    clamps, masks, and shape-skew grouping."""
    w = world_box((13, 16, 12))
    return w, make_slabs(w, 8, axis=0, rule=ceil_splits)


def _batched_parity(w, boxes, algorithm, B=2):
    from jax.sharding import PartitionSpec as P

    from distributedfft_tpu.parallel.bricks import (
        plan_bricks_to_spec, plan_spec_to_bricks,
    )

    mesh = _mesh()
    spec = P(None, "slab")
    rng = np.random.default_rng(11)
    xs = [(rng.standard_normal(w.shape)
           + 1j * rng.standard_normal(w.shape)).astype(np.complex64)
          for _ in range(B)]
    stacks = np.stack([np.asarray(scatter_bricks(x, boxes))
                       for x in xs])
    fwd, _ = plan_bricks_to_spec(mesh, boxes, spec, algorithm=algorithm,
                                 batch=B, jit=True)
    fwd1, _ = plan_bricks_to_spec(mesh, boxes, spec, algorithm=algorithm,
                                  jit=True)
    y = np.asarray(fwd(jax.numpy.asarray(stacks)))
    for b in range(B):
        ref = np.asarray(fwd1(jax.numpy.asarray(stacks[b])))
        np.testing.assert_array_equal(y[b], ref)
        np.testing.assert_array_equal(ref, xs[b])
    inv, _ = plan_spec_to_bricks(mesh, spec, boxes, algorithm=algorithm,
                                 batch=B, jit=True)
    z = np.asarray(inv(jax.numpy.asarray(np.stack(xs))))
    for b in range(B):
        np.testing.assert_array_equal(gather_bricks(z[b], boxes), xs[b])


@pytest.mark.parametrize("algorithm", ["ring", "a2av"])
def test_bricks_to_spec_batched_parity(algorithm):
    """batch=B through plan_bricks_to_spec/plan_spec_to_bricks (the
    PR 6 leading-axis pattern): B independent reshapes bit-match B
    unbatched executions, both directions (even slabs)."""
    w = world_box((16, 8, 8))
    _batched_parity(w, make_slabs(w, 8, axis=0), algorithm)


@pytest.mark.parametrize("algorithm", ["ring", "a2av"])
def test_bricks_to_spec_batched_parity_uneven(algorithm):
    """The uneven/ragged duplicate: ceil-split tails, shape-skew step
    groups, an empty brick — the clamp/mask paths under batch."""
    w, boxes = _batched_edges_case()
    _batched_parity(w, boxes, algorithm, B=3)


@pytest.mark.parametrize("algorithm", ["ring", "a2av"])
def test_bricks_batch1_hlo_byte_identical(algorithm):
    """batch=1 normalizes to the unbatched plan — byte-identical
    lowered text (the PR 6 pin), both edges."""
    from jax.sharding import PartitionSpec as P

    from distributedfft_tpu.parallel.bricks import (
        plan_bricks_to_spec, plan_spec_to_bricks,
    )

    mesh = _mesh()
    w, boxes = _batched_edges_case()
    spec = P(None, "slab")
    rng = np.random.default_rng(12)
    x = (rng.standard_normal(w.shape)
         + 1j * rng.standard_normal(w.shape)).astype(np.complex64)
    stack = jax.numpy.asarray(np.asarray(scatter_bricks(x, boxes)))
    f0, _ = plan_bricks_to_spec(mesh, boxes, spec, algorithm=algorithm)
    f1, _ = plan_bricks_to_spec(mesh, boxes, spec, algorithm=algorithm,
                                batch=1)
    assert (jax.jit(f0).lower(stack).as_text()
            == jax.jit(f1).lower(stack).as_text())
    g0, _ = plan_spec_to_bricks(mesh, spec, boxes, algorithm=algorithm)
    g1, _ = plan_spec_to_bricks(mesh, spec, boxes, algorithm=algorithm,
                                batch=1)
    xg = jax.numpy.asarray(x)
    assert (jax.jit(g0).lower(xg).as_text()
            == jax.jit(g1).lower(xg).as_text())


def test_bricks_batched_share_collectives():
    """The batch rides each ring step as a bystander dim: the batched
    program issues exactly as many collective-permutes (and, on the
    a2av edge, gathers) as the unbatched one — B transforms, one
    collective latency per step."""
    from jax.sharding import PartitionSpec as P

    from distributedfft_tpu.parallel.bricks import plan_bricks_to_spec

    mesh = _mesh()
    w, boxes = _batched_edges_case()
    spec = P(None, "slab")
    rng = np.random.default_rng(13)
    x = (rng.standard_normal(w.shape)
         + 1j * rng.standard_normal(w.shape)).astype(np.complex64)
    stack = np.asarray(scatter_bricks(x, boxes))
    for algorithm, op in (("ring", "collective_permute"),
                          ("a2av", "all_gather")):
        f1, _ = plan_bricks_to_spec(mesh, boxes, spec,
                                    algorithm=algorithm)
        fB, _ = plan_bricks_to_spec(mesh, boxes, spec,
                                    algorithm=algorithm, batch=4)
        t1 = jax.jit(f1).lower(jax.numpy.asarray(stack)).as_text()
        tB = jax.jit(fB).lower(
            jax.numpy.asarray(np.stack([stack] * 4))).as_text()
        n1, nB = t1.count(op), tB.count(op)
        assert n1 >= 1 and nB == n1, (algorithm, op, n1, nB)


def test_bricks_batch_validation():
    from jax.sharding import PartitionSpec as P

    from distributedfft_tpu.parallel.bricks import plan_bricks_to_spec

    mesh = _mesh()
    w, boxes = _batched_edges_case()
    with pytest.raises(ValueError, match="batch"):
        plan_bricks_to_spec(mesh, boxes, P(None, "slab"), batch=0)
    with pytest.raises(ValueError, match="batch"):
        plan_bricks_to_spec(mesh, boxes, P(None, "slab"), batch=True)
