"""Transform-time C API: the heffte_c parity surface (heffte_c.h:52-179,
test/test_c.c) — C-ABI plan/execute/destroy over the JAX runtime via the
native bridge, including a roundtrip driven entirely from compiled C."""

import ctypes

import numpy as np
import pytest

import distributedfft_tpu as dfft
from distributedfft_tpu import capi, native


pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native toolchain unavailable")


@pytest.fixture(scope="module", autouse=True)
def _bridge():
    assert capi.install_c_api(mesh=None)
    assert capi.c_api_installed()


def test_c_selftest_roundtrip_from_c():
    """dfft_c_selftest allocates, plans, executes fwd+bwd, and checks the
    roundtrip entirely in C — the proof a C caller owns the lifecycle
    (the test_c.c role)."""
    err = capi.c_selftest((8, 6, 5))
    assert 0 <= err < 5e-4, err


def test_c_abi_calls_from_ctypes_match_numpy():
    """Drive the raw C entry points (as any C code would) and compare the
    forward transform against numpy."""
    lib = native._load()
    lib.dfft_plan_c2c_3d.restype = ctypes.c_longlong
    lib.dfft_plan_c2c_3d.argtypes = [ctypes.c_longlong] * 3 + [ctypes.c_int]
    lib.dfft_execute_c2c.restype = ctypes.c_int
    fp = ctypes.POINTER(ctypes.c_float)
    lib.dfft_execute_c2c.argtypes = [ctypes.c_longlong, fp, fp]

    shape = (4, 6, 5)
    n = int(np.prod(shape))
    rng = np.random.default_rng(4242)
    x = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
         ).astype(np.complex64)
    xin = np.ascontiguousarray(x.view(np.float32).reshape(-1))
    out = np.zeros(2 * n, np.float32)

    pid = lib.dfft_plan_c2c_3d(*shape, -1)
    assert pid >= 0
    rc = lib.dfft_execute_c2c(pid, xin.ctypes.data_as(fp),
                              out.ctypes.data_as(fp))
    assert rc == 0
    got = out.view(np.complex64).reshape(shape)
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.max(np.abs(want)) < 5e-4
    lib.dfft_destroy_plan_c(pid)
    # Executing a destroyed plan fails cleanly, never crashes.
    assert lib.dfft_execute_c2c(pid, xin.ctypes.data_as(fp),
                                out.ctypes.data_as(fp)) != 0


def test_c_plan_bad_size_reports_failure():
    lib = native._load()
    lib.dfft_plan_c2c_3d.restype = ctypes.c_longlong
    lib.dfft_plan_c2c_3d.argtypes = [ctypes.c_longlong] * 3 + [ctypes.c_int]
    assert lib.dfft_plan_c2c_3d(0, 6, 5, -1) == -1


def test_c_api_on_mesh():
    """The bridge carries distributed plans too: a C caller sees the full
    world while the transform runs slab-decomposed on the mesh."""
    assert capi.install_c_api(mesh=dfft.make_mesh(8))
    try:
        err = capi.c_selftest((16, 8, 8))
        assert 0 <= err < 5e-4, err
    finally:
        capi.install_c_api(mesh=None)
