"""Transform-time C API: the heffte_c parity surface (heffte_c.h:52-179,
test/test_c.c) — C-ABI plan/execute/destroy over the JAX runtime via the
native bridge, including a roundtrip driven entirely from compiled C."""

import ctypes

import numpy as np
import pytest

import distributedfft_tpu as dfft
from distributedfft_tpu import capi, native


pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native toolchain unavailable")


@pytest.fixture(scope="module", autouse=True)
def _bridge():
    assert capi.install_c_api(mesh=None)
    assert capi.c_api_installed()


def test_c_selftest_roundtrip_from_c():
    """dfft_c_selftest allocates, plans, executes fwd+bwd, and checks the
    roundtrip entirely in C — the proof a C caller owns the lifecycle
    (the test_c.c role)."""
    err = capi.c_selftest((8, 6, 5))
    assert 0 <= err < 5e-4, err


def test_c_abi_calls_from_ctypes_match_numpy():
    """Drive the raw C entry points (as any C code would) and compare the
    forward transform against numpy."""
    lib = native._load()
    lib.dfft_plan_c2c_3d.restype = ctypes.c_longlong
    lib.dfft_plan_c2c_3d.argtypes = [ctypes.c_longlong] * 3 + [ctypes.c_int]
    lib.dfft_execute_c2c.restype = ctypes.c_int
    fp = ctypes.POINTER(ctypes.c_float)
    lib.dfft_execute_c2c.argtypes = [ctypes.c_longlong, fp, fp]

    shape = (4, 6, 5)
    n = int(np.prod(shape))
    rng = np.random.default_rng(4242)
    x = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)
         ).astype(np.complex64)
    xin = np.ascontiguousarray(x.view(np.float32).reshape(-1))
    out = np.zeros(2 * n, np.float32)

    pid = lib.dfft_plan_c2c_3d(*shape, -1)
    assert pid >= 0
    rc = lib.dfft_execute_c2c(pid, xin.ctypes.data_as(fp),
                              out.ctypes.data_as(fp))
    assert rc == 0
    got = out.view(np.complex64).reshape(shape)
    want = np.fft.fftn(x)
    assert np.max(np.abs(got - want)) / np.max(np.abs(want)) < 5e-4
    lib.dfft_destroy_plan_c(pid)
    # Executing a destroyed plan fails cleanly, never crashes.
    assert lib.dfft_execute_c2c(pid, xin.ctypes.data_as(fp),
                                out.ctypes.data_as(fp)) != 0


def test_c_plan_bad_size_reports_failure():
    lib = native._load()
    lib.dfft_plan_c2c_3d.restype = ctypes.c_longlong
    lib.dfft_plan_c2c_3d.argtypes = [ctypes.c_longlong] * 3 + [ctypes.c_int]
    assert lib.dfft_plan_c2c_3d(0, 6, 5, -1) == -1


def test_c_api_on_mesh():
    """The bridge carries distributed plans too: a C caller sees the full
    world while the transform runs slab-decomposed on the mesh."""
    assert capi.install_c_api(mesh=dfft.make_mesh(8))
    try:
        err = capi.c_selftest((16, 8, 8))
        assert 0 <= err < 5e-4, err
    finally:
        capi.install_c_api(mesh=None)


def test_c_selftest_r2c_from_c():
    """Typed surface: r2c/c2r float roundtrip driven from compiled C
    (heffte_plan_create_r2c parity, heffte_c.h:63)."""
    for axis in (2, 0):
        err = capi.c_selftest_r2c((8, 6, 10), r2c_axis=axis)
        assert 0 <= err < 5e-4, (axis, err)


def test_c_selftest_z2z_double_gate_from_c():
    """Typed surface: DOUBLE z2z roundtrip via the dd tier, meeting the
    reference's 1e-11 double tolerance from compiled C
    (heffte_c.h:141-179 typed double entries; test_common.h:138)."""
    err = capi.c_selftest_z2z((8, 6, 5))
    assert 0 <= err < 1e-11, err


def test_c_selftest_resident_from_c():
    """Plan-resident buffers: upload once, repeat-execute device-side,
    download once — the reference driver's warm+timed-loop pattern
    without per-call host round-trips."""
    err = capi.c_selftest_resident((8, 6, 5), repeats=4)
    assert 0 <= err < 5e-4, err


def test_c_abi_d2z_from_ctypes():
    """Drive the raw typed entries for double r2c (d2z/z2d) as C would."""
    lib = native._load()
    lib.dfft_plan_d2z_3d.restype = ctypes.c_longlong
    lib.dfft_plan_d2z_3d.argtypes = [ctypes.c_longlong] * 3 + [
        ctypes.c_int, ctypes.c_int]
    dp = ctypes.POINTER(ctypes.c_double)
    lib.dfft_execute_d2z.restype = ctypes.c_int
    lib.dfft_execute_d2z.argtypes = [ctypes.c_longlong, dp, dp]
    lib.dfft_execute_z2d.restype = ctypes.c_int
    lib.dfft_execute_z2d.argtypes = [ctypes.c_longlong, dp, dp]

    shape = (8, 4, 6)
    hshape = (8, 4, 4)
    rng = np.random.default_rng(7)
    x = rng.standard_normal(shape)
    out = np.zeros(2 * int(np.prod(hshape)), np.float64)

    fwd = lib.dfft_plan_d2z_3d(*shape, -1, 2)
    bwd = lib.dfft_plan_d2z_3d(*shape, +1, 2)
    assert fwd >= 0 and bwd >= 0
    assert lib.dfft_execute_d2z(fwd, x.ctypes.data_as(dp),
                                out.ctypes.data_as(dp)) == 0
    got = out.view(np.complex128).reshape(hshape)
    want = np.fft.rfftn(x)
    assert np.max(np.abs(got - want)) / np.max(np.abs(want)) < 1e-11
    back = np.zeros(int(np.prod(shape)), np.float64)
    assert lib.dfft_execute_z2d(bwd, out.ctypes.data_as(dp),
                                back.ctypes.data_as(dp)) == 0
    np.testing.assert_allclose(back.reshape(shape), x, atol=1e-11)
    lib.dfft_destroy_plan_c(fwd)
    lib.dfft_destroy_plan_c(bwd)


def test_c_typed_on_mesh():
    """Typed plans are distributed too when the bridge holds a mesh."""
    assert capi.install_c_api(mesh=dfft.make_mesh(4))
    try:
        assert 0 <= capi.c_selftest_r2c((16, 8, 8)) < 5e-4
        assert 0 <= capi.c_selftest_z2z((8, 8, 8)) < 1e-11
        assert 0 <= capi.c_selftest_resident((16, 8, 8)) < 5e-4
    finally:
        capi.install_c_api(mesh=None)


def test_resident_download_before_execute_errors():
    """A fresh upload invalidates the previous output: downloading before
    the next execute returns error code 5, never stale data."""
    lib = native._load()
    lib.dfft_plan_c2c_3d.restype = ctypes.c_longlong
    lib.dfft_plan_c2c_3d.argtypes = [ctypes.c_longlong] * 3 + [ctypes.c_int]
    vp = ctypes.c_void_p
    for fn, args in (("dfft_upload", [ctypes.c_longlong, vp]),
                     ("dfft_execute_resident", [ctypes.c_longlong]),
                     ("dfft_download", [ctypes.c_longlong, vp])):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = args

    shape = (4, 4, 4)
    n = int(np.prod(shape))
    x = np.arange(2 * n, dtype=np.float32)
    out = np.zeros(2 * n, np.float32)
    pid = lib.dfft_plan_c2c_3d(*shape, -1)
    assert pid >= 0
    assert lib.dfft_upload(pid, x.ctypes.data_as(vp)) == 0
    assert lib.dfft_download(pid, out.ctypes.data_as(vp)) == 5
    assert lib.dfft_execute_resident(pid) == 0
    assert lib.dfft_download(pid, out.ctypes.data_as(vp)) == 0
    # second upload invalidates the first run's output again
    assert lib.dfft_upload(pid, x.ctypes.data_as(vp)) == 0
    assert lib.dfft_download(pid, out.ctypes.data_as(vp)) == 5
    lib.dfft_destroy_plan_c(pid)


def test_c_api_on_pencil_mesh():
    """The bridge carries 2D-mesh (pencil) plans for every tier."""
    assert capi.install_c_api(mesh=dfft.make_mesh((2, 4)))
    try:
        assert 0 <= capi.c_selftest((16, 8, 8)) < 5e-4
        assert 0 <= capi.c_selftest_r2c((16, 8, 8)) < 5e-4
        assert 0 <= capi.c_selftest_z2z((8, 8, 8)) < 1e-11
        assert 0 <= capi.c_selftest_resident((16, 8, 8), repeats=2) < 5e-4
    finally:
        capi.install_c_api(mesh=None)
